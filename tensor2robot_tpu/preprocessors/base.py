"""Preprocessor contract: 4 spec getters + a pure transform.

Capability-equivalent of the reference's ``AbstractPreprocessor``
(``/root/reference/preprocessors/abstract_preprocessor.py:34-223``) with one
TPU-first change: ``_preprocess_fn`` must be a *pure jax-traceable function*,
because the trainer invokes it **inside the jitted train step** — crops and
distortions then run on-device fused with the model instead of burning host
CPU in a ``dataset.map``. Randomness is explicit: a ``jax.random`` key is
threaded in (no hidden op-level seeds).

The spec contract is unchanged:

* ``get_in_*_specification(mode)``: what arrives from the data layer;
* ``get_out_*_specification(mode)``: what the model consumes;
* ``preprocess`` = validate+pack(in) → ``_preprocess_fn`` →
  validate+pack(out).
"""

from __future__ import annotations

import abc
from typing import Callable, Optional, Tuple

from tensor2robot_tpu.specs import SpecStruct, algebra

SpecGetter = Callable[[str], SpecStruct]


class AbstractPreprocessor(abc.ABC):
  """Base preprocessor; subclasses define specs and the pure transform."""

  def __init__(self,
               model_feature_specification_fn: Optional[SpecGetter] = None,
               model_label_specification_fn: Optional[SpecGetter] = None):
    self._model_feature_specification_fn = model_feature_specification_fn
    self._model_label_specification_fn = model_label_specification_fn

  # ------------------------------------------------------------ model specs

  def model_feature_specification(self, mode: str) -> Optional[SpecStruct]:
    if self._model_feature_specification_fn is None:
      return None
    return algebra.flatten_spec_structure(
        self._model_feature_specification_fn(mode))

  def model_label_specification(self, mode: str) -> Optional[SpecStruct]:
    if self._model_label_specification_fn is None:
      return None
    spec = self._model_label_specification_fn(mode)
    return None if spec is None else algebra.flatten_spec_structure(spec)

  # ------------------------------------------------------------- 4 getters

  @abc.abstractmethod
  def get_in_feature_specification(self, mode: str) -> SpecStruct:
    ...

  @abc.abstractmethod
  def get_in_label_specification(self, mode: str) -> Optional[SpecStruct]:
    ...

  @abc.abstractmethod
  def get_out_feature_specification(self, mode: str) -> SpecStruct:
    ...

  @abc.abstractmethod
  def get_out_label_specification(self, mode: str) -> Optional[SpecStruct]:
    ...

  # ------------------------------------------------------------- transform

  def _preprocess_fn(self, features: SpecStruct,
                     labels: Optional[SpecStruct], mode: str,
                     rng) -> Tuple[SpecStruct, Optional[SpecStruct]]:
    """Pure jax transform; default is identity."""
    del mode, rng
    return features, labels

  def preprocess(self,
                 features,
                 labels,
                 mode: str,
                 rng=None) -> Tuple[SpecStruct, Optional[SpecStruct]]:
    """Validated preprocess; safe to call under jit (validation is static)."""
    features = algebra.validate_and_pack(
        self.get_in_feature_specification(mode), features, ignore_batch=True)
    in_label_spec = self.get_in_label_specification(mode)
    if labels is not None and in_label_spec is not None:
      labels = algebra.validate_and_pack(
          in_label_spec, labels, ignore_batch=True)
    elif in_label_spec is None:
      labels = None
    features, labels = self._preprocess_fn(features, labels, mode, rng)
    features = algebra.validate_and_pack(
        self.get_out_feature_specification(mode), features,
        ignore_batch=True)
    out_label_spec = self.get_out_label_specification(mode)
    if labels is not None and out_label_spec is not None:
      labels = algebra.validate_and_pack(
          out_label_spec, labels, ignore_batch=True)
    return features, labels

  # Preprocessors are callable for ergonomic use inside jitted steps.
  __call__ = preprocess


class NoOpPreprocessor(AbstractPreprocessor):
  """Identity: in specs == out specs == model specs.

  Reference: ``preprocessors/noop_preprocessor.py:32-130``.
  """

  def get_in_feature_specification(self, mode):
    return self.model_feature_specification(mode)

  def get_in_label_specification(self, mode):
    return self.model_label_specification(mode)

  def get_out_feature_specification(self, mode):
    return self.model_feature_specification(mode)

  def get_out_label_specification(self, mode):
    return self.model_label_specification(mode)


class SpecTransformationPreprocessor(NoOpPreprocessor):
  """Convenience base: mutate copies of the model specs per direction.

  Override ``_transform_in_feature_specification`` (etc.) to derive the data
  contract from the model contract — e.g. declare that a float32 image the
  model wants arrives as a uint8-encoded JPEG on disk. Reference:
  ``preprocessors/spec_transformation_preprocessor.py:31-200``.
  """

  def update_spec(self, spec_struct: SpecStruct, key: str,
                  **overrides) -> None:
    """In-place override of one spec in a (copied) struct."""
    from tensor2robot_tpu.specs import TensorSpec

    spec_struct[key] = TensorSpec.from_spec(spec_struct[key], **overrides)

  def _transform_in_feature_specification(
      self, spec: SpecStruct, mode: str) -> SpecStruct:
    del mode
    return spec

  def _transform_in_label_specification(
      self, spec: Optional[SpecStruct], mode: str) -> Optional[SpecStruct]:
    del mode
    return spec

  def _transform_out_feature_specification(
      self, spec: SpecStruct, mode: str) -> SpecStruct:
    del mode
    return spec

  def _transform_out_label_specification(
      self, spec: Optional[SpecStruct], mode: str) -> Optional[SpecStruct]:
    del mode
    return spec

  def get_in_feature_specification(self, mode):
    return self._transform_in_feature_specification(
        self.model_feature_specification(mode).copy(), mode)

  def get_in_label_specification(self, mode):
    spec = self.model_label_specification(mode)
    return self._transform_in_label_specification(
        None if spec is None else spec.copy(), mode)

  def get_out_feature_specification(self, mode):
    return self._transform_out_feature_specification(
        self.model_feature_specification(mode).copy(), mode)

  def get_out_label_specification(self, mode):
    spec = self.model_label_specification(mode)
    return self._transform_out_label_specification(
        None if spec is None else spec.copy(), mode)
