"""Preprocessors: spec-driven, device-side (jit-traceable) transforms."""

from tensor2robot_tpu.preprocessors.base import (
    AbstractPreprocessor,
    NoOpPreprocessor,
    SpecTransformationPreprocessor,
)
from tensor2robot_tpu.preprocessors.dtype_policy import DtypePolicyPreprocessor
