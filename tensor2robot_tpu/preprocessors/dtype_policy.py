"""Device dtype-policy preprocessor: the host/device bfloat16 boundary.

Capability-equivalent of the reference's ``TPUPreprocessorWrapper``
(``/root/reference/preprocessors/tpu_preprocessor_wrapper.py:37-160``), which
pairs with ``TPUT2RModelWrapper``: on the way in, specs the device wants in
bfloat16 are declared float32 to the host pipeline; on the way out, optional
specs are stripped (dense-only batches for the device) and float32 tensors
are cast to bfloat16.

In the TPU-native design this runs *inside the jitted step*, so the
float32→bfloat16 cast compiles into the input of the first matmul/conv and
is effectively free on the MXU.
"""

from __future__ import annotations

from typing import Optional, Tuple

from tensor2robot_tpu.preprocessors.base import AbstractPreprocessor
from tensor2robot_tpu.specs import (SpecStruct, algebra, dtypes)


class DtypePolicyPreprocessor(AbstractPreprocessor):
  """Wraps a base preprocessor with the TPU bfloat16 in/out policy."""

  def __init__(self, preprocessor: AbstractPreprocessor):
    super().__init__()
    self._preprocessor = preprocessor

  @property
  def wrapped(self) -> AbstractPreprocessor:
    return self._preprocessor

  # In specs (host side): bfloat16 → float32, the host never sees bfloat16.
  def get_in_feature_specification(self, mode):
    return dtypes.cast_bfloat16_to_float32(
        self._preprocessor.get_in_feature_specification(mode))

  def get_in_label_specification(self, mode):
    spec = self._preprocessor.get_in_label_specification(mode)
    return None if spec is None else dtypes.cast_bfloat16_to_float32(spec)

  # Out specs (device side): strip optionals, float32 → bfloat16.
  def get_out_feature_specification(self, mode):
    return dtypes.cast_float32_to_bfloat16(
        algebra.filter_required_flat_tensor_spec(
            algebra.flatten_spec_structure(
                self._preprocessor.get_out_feature_specification(mode))))

  def get_out_label_specification(self, mode):
    spec = self._preprocessor.get_out_label_specification(mode)
    if spec is None:
      return None
    return dtypes.cast_float32_to_bfloat16(
        algebra.filter_required_flat_tensor_spec(
            algebra.flatten_spec_structure(spec)))

  def _preprocess_fn(self, features, labels, mode,
                     rng) -> Tuple[SpecStruct, Optional[SpecStruct]]:
    features, labels = self._preprocessor._preprocess_fn(  # pylint: disable=protected-access
        features, labels, mode, rng)

    def apply_policy(tensors, out_spec):
      if tensors is None or out_spec is None:
        return None if out_spec is None else tensors
      flat = algebra.flatten_spec_structure(tensors)
      kept = SpecStruct(
          (k, v) for k, v in flat.items() if k in out_spec)
      return dtypes.cast_arrays_to_spec_dtypes(out_spec, kept)

    return (apply_policy(features, self.get_out_feature_specification(mode)),
            apply_policy(labels, self.get_out_label_specification(mode)))
