"""Device-side image transformations (pure jax, jit/vmap-friendly).

Capability-equivalent of the reference's
``preprocessors/image_transformations.py`` (RandomCropImages:31,
CenterCropImages:68, CustomCropImages:110,
ApplyPhotometricImageDistortions:181-272, ApplyDepthImageDistortions:275-332)
— re-designed to run on-TPU inside the jitted step: static crop sizes (XLA
dynamic_slice with traced offsets), explicit ``jax.random`` keys, and
vectorized color math instead of per-image TF ops.

All functions take images as float arrays in [0, 1] with shape
``[batch, H, W, C]`` (crops also accept uint8) and are batch-vectorized.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def _check_crop(input_shape, target_shape) -> None:
  if len(target_shape) != 2:
    raise ValueError(f'target_shape must be (h, w), got {target_shape}')
  if (target_shape[0] > input_shape[-3] or target_shape[1] > input_shape[-2]):
    raise ValueError(
        f'Crop {target_shape} larger than image {input_shape[-3:-1]}')


def random_crop_images(rng: jax.Array, images: jax.Array,
                       target_shape: Sequence[int]) -> jax.Array:
  """Random spatial crop with ONE offset shared across the batch.

  Reference semantics (``RandomCropImages``,
  ``preprocessors/image_transformations.py:55-65``): scalar
  ``offset_y/offset_x`` applied to the whole [B, h, w, c] tensor. The
  shared offset is also the fast form — a single ``dynamic_slice``;
  per-image offsets lower to a length-B while-loop of
  dynamic-update-slices on TPU, which profiled at 600 ms/step on the
  WTL episode batch (32×40 frames).
  """
  _check_crop(images.shape, target_shape)
  th, tw = int(target_shape[0]), int(target_shape[1])
  batch = images.shape[0]
  h, w = images.shape[-3], images.shape[-2]
  rng_h, rng_w = jax.random.split(rng)
  oh = jax.random.randint(rng_h, (), 0, h - th + 1)
  ow = jax.random.randint(rng_w, (), 0, w - tw + 1)
  zero = jnp.zeros((), oh.dtype)
  return jax.lax.dynamic_slice(
      images, (zero, oh, ow, zero), (batch, th, tw, images.shape[-1]))


def center_crop_images(images: jax.Array,
                       target_shape: Sequence[int]) -> jax.Array:
  """Deterministic center crop (eval-time counterpart of random crop)."""
  _check_crop(images.shape, target_shape)
  th, tw = int(target_shape[0]), int(target_shape[1])
  h, w = images.shape[-3], images.shape[-2]
  oh, ow = (h - th) // 2, (w - tw) // 2
  return images[..., oh:oh + th, ow:ow + tw, :]


def crop_resize_images(offset_y, offset_x, images: jax.Array,
                       crop_shape: Sequence[int],
                       target_shape: Sequence[int],
                       method: str = 'bilinear') -> jax.Array:
  """``resize(crop(images, offset, crop_shape), target_shape)`` with the
  crop FOLDED INTO the resize weight matrices — no materialized crop.

  The [B, H, W, C] crop intermediate (215 MB on the WTL episode batch)
  and its TPU layout copy are the only reasons the two-step form touches
  HBM twice; since resize is linear and separable, the same result is
  two dots with per-axis weight matrices shifted by the crop offset:

    out = (roll(pad(A_h), oy) @ img) @ roll(pad(A_w), ox)^T

  ``A_h [target_h, crop_h]`` comes from resizing an identity matrix, so
  edge renormalization and antialiasing match ``jax.image.resize``
  exactly; zero-padding to the full image width and rolling by the
  (traced) offset reproduces the crop — extra columns multiply by zero.
  ``offset_y``/``offset_x`` may be traced scalars (the random-crop
  draw). Input may be uint8; the output is float32 in the INPUT's
  units (divide by 255 afterwards — scaling commutes with the linear
  resample and the small output is the cheaper place to do it).
  """
  th, tw = int(target_shape[0]), int(target_shape[1])
  ch, cw = int(crop_shape[0]), int(crop_shape[1])
  h, w = images.shape[-3], images.shape[-2]
  _check_crop(images.shape, crop_shape)
  eye_h = jnp.eye(ch, dtype=jnp.float32)
  eye_w = jnp.eye(cw, dtype=jnp.float32)
  a_h = jax.image.resize(eye_h, (th, ch), method)  # [th, ch], constant
  a_w = jax.image.resize(eye_w, (tw, cw), method)  # [tw, cw], constant
  a_h = jnp.roll(jnp.pad(a_h, ((0, 0), (0, h - ch))), offset_y, axis=1)
  a_w = jnp.roll(jnp.pad(a_w, ((0, 0), (0, w - cw))), offset_x, axis=1)
  x = images.astype(jnp.float32)
  # H-pass first, then W: measured 29.6 ms/step on the WTL episode
  # batch vs 31.6 for W-first (the W-first contraction both keeps the
  # input layout copies AND slows them to 1.8x their HBM bound).
  x = jnp.einsum('iy,byxc->bixc', a_h, x)
  return jnp.einsum('jx,bixc->bijc', a_w, x)


def custom_crop_images(images: jax.Array,
                       crop_box: Sequence[int]) -> jax.Array:
  """Fixed crop at (y, x) with size (h, w) — crop_box = [y, x, h, w]."""
  y, x, h, w = (int(v) for v in crop_box)
  if y + h > images.shape[-3] or x + w > images.shape[-2]:
    raise ValueError(f'crop_box {crop_box} exceeds image {images.shape}')
  return images[..., y:y + h, x:x + w, :]


# ------------------------------------------------------------- color space


def rgb_to_hsv(rgb: jax.Array) -> jax.Array:
  """Vectorized RGB->HSV on [..., 3] arrays in [0, 1]."""
  r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
  max_c = jnp.max(rgb, axis=-1)
  min_c = jnp.min(rgb, axis=-1)
  delta = max_c - min_c
  safe = jnp.where(delta == 0, 1.0, delta)
  hue = jnp.where(
      max_c == r, (g - b) / safe % 6.0,
      jnp.where(max_c == g, (b - r) / safe + 2.0, (r - g) / safe + 4.0))
  hue = jnp.where(delta == 0, 0.0, hue / 6.0)
  sat = jnp.where(max_c == 0, 0.0, delta / jnp.where(max_c == 0, 1.0, max_c))
  return jnp.stack([hue, sat, max_c], axis=-1)


def hsv_to_rgb(hsv: jax.Array) -> jax.Array:
  """Vectorized HSV->RGB on [..., 3] arrays in [0, 1]."""
  h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
  h6 = h * 6.0
  k = jnp.stack([(5.0 + h6) % 6.0, (3.0 + h6) % 6.0, (1.0 + h6) % 6.0],
                axis=-1)
  t = jnp.minimum(k, jnp.minimum(4.0 - k, 1.0))
  t = jnp.clip(t, 0.0, 1.0)
  return v[..., None] * (1.0 - s[..., None] * t)


# ------------------------------------------------------ photometric chain


def adjust_brightness(images, delta):
  return images + delta


def adjust_saturation(images, factor):
  hsv = rgb_to_hsv(jnp.clip(images, 0.0, 1.0))
  hsv = hsv.at[..., 1].multiply(factor)
  return hsv_to_rgb(jnp.clip(hsv, 0.0, 1.0))


def adjust_hue(images, delta):
  hsv = rgb_to_hsv(jnp.clip(images, 0.0, 1.0))
  hsv = hsv.at[..., 0].set((hsv[..., 0] + delta) % 1.0)
  return hsv_to_rgb(hsv)


def adjust_contrast(images, factor):
  mean = jnp.mean(images, axis=(-3, -2), keepdims=True)
  return (images - mean) * factor + mean


def apply_photometric_image_distortions(
    rng: jax.Array,
    images: jax.Array,
    random_brightness: bool = False,
    max_delta_brightness: float = 0.125,
    random_saturation: bool = False,
    lower_saturation: float = 0.5,
    upper_saturation: float = 1.5,
    random_hue: bool = False,
    max_delta_hue: float = 0.2,
    random_contrast: bool = False,
    lower_contrast: float = 0.5,
    upper_contrast: float = 1.5,
    random_noise_level: float = 0.0,
    random_noise_apply_probability: float = 0.5,
    use_fused_kernel: bool = False,
) -> jax.Array:
  """Per-image random photometric distortion chain.

  Each enabled distortion draws independent per-image parameters, mirroring
  the reference's per-image loop (image_transformations.py:181-272) but
  vectorized over the batch.

  ``use_fused_kernel`` routes the brightness+contrast-only case to the
  Pallas kernel in :mod:`tensor2robot_tpu.ops.photometric`. It is OFF by
  default: trace-based measurement on this chip shows XLA's own fusion of
  the chain is faster (0.28 vs 0.43 ms on [32,472,472,3] — Pallas DMA
  throughput trails XLA loop fusions here; see PERF_NOTES.md). The kernel
  remains the numerics-tested Pallas reference for fusion-hostile
  elementwise+reduction chains.
  """
  batch = images.shape[0]
  if (use_fused_kernel and random_brightness and random_contrast and
      not random_saturation and not random_hue and not random_noise_level and
      jax.default_backend() == 'tpu'):
    from tensor2robot_tpu.ops import photometric

    return photometric.random_brightness_contrast(
        rng, images,
        max_delta_brightness=max_delta_brightness,
        lower_contrast=lower_contrast,
        upper_contrast=upper_contrast)
  keys = jax.random.split(rng, 6)
  if random_brightness:
    delta = jax.random.uniform(
        keys[0], (batch, 1, 1, 1),
        minval=-max_delta_brightness, maxval=max_delta_brightness)
    images = adjust_brightness(images, delta)
  if random_saturation:
    factor = jax.random.uniform(
        keys[1], (batch, 1, 1), minval=lower_saturation,
        maxval=upper_saturation)
    images = adjust_saturation(images, factor)
  if random_hue:
    delta = jax.random.uniform(
        keys[2], (batch, 1, 1), minval=-max_delta_hue, maxval=max_delta_hue)
    images = adjust_hue(images, delta)
  if random_contrast:
    factor = jax.random.uniform(
        keys[3], (batch, 1, 1, 1), minval=lower_contrast,
        maxval=upper_contrast)
    images = adjust_contrast(images, factor)
  if random_noise_level:
    noise = jax.random.normal(keys[4], images.shape) * random_noise_level
    apply = (jax.random.uniform(keys[5], (batch, 1, 1, 1)) <
             random_noise_apply_probability)
    images = jnp.where(apply, images + noise, images)
  return jnp.clip(images, 0.0, 1.0)


def apply_depth_image_distortions(
    rng: jax.Array,
    depth_images: jax.Array,
    random_noise_level: float = 0.05,
    random_noise_apply_probability: float = 0.5,
    scale_noise_by_depth: bool = True) -> jax.Array:
  """Gamma/gaussian noise on depth maps, optionally scaled by depth.

  Reference: ApplyDepthImageDistortions (image_transformations.py:275-332).
  """
  batch = depth_images.shape[0]
  k_noise, k_apply = jax.random.split(rng)
  noise = jax.random.normal(k_noise, depth_images.shape) * random_noise_level
  if scale_noise_by_depth:
    noise = noise * depth_images
  apply = (jax.random.uniform(k_apply, (batch,) + (1,) *
                              (depth_images.ndim - 1)) <
           random_noise_apply_probability)
  return jnp.where(apply, depth_images + noise, depth_images)
