"""SNAIL meta-learner blocks: dilated causal convs + causal attention.

Reference: ``/root/reference/layers/snail.py:35-152`` (Mishra et al. '17).
Flax modules with the same shape contracts. The causal mask is applied as
an additive ``-inf`` upper triangle before one fused softmax — the TPU-
friendly form XLA pattern-matches — instead of the reference's band-part
decomposition.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class CausalConv(nn.Module):
  """Causal dilated 1-D conv over [B, T, C] (snail.py:35-58)."""

  filters: int
  dilation_rate: int = 1
  kernel_size: int = 2

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    pad = (self.kernel_size - 1) * self.dilation_rate
    x = jnp.pad(x, ((0, 0), (pad, 0), (0, 0)))
    return nn.Conv(
        features=self.filters,
        kernel_size=(self.kernel_size,),
        kernel_dilation=(self.dilation_rate,),
        padding='VALID')(x)


class DenseBlock(nn.Module):
  """Gated activation, concatenated to the input (snail.py:60-76)."""

  filters: int
  dilation_rate: int = 1

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    xf = CausalConv(self.filters, self.dilation_rate, name='xf')(x)
    xg = CausalConv(self.filters, self.dilation_rate, name='xg')(x)
    activations = jnp.tanh(xf) * nn.sigmoid(xg)
    return jnp.concatenate([x, activations], axis=2)


class TCBlock(nn.Module):
  """DenseBlocks with dilations 2^1..2^ceil(log2(T)) (snail.py:78-93)."""

  sequence_length: int
  filters: int

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    num_blocks = int(np.ceil(np.log2(self.sequence_length)))
    for i in range(1, num_blocks + 1):
      x = DenseBlock(self.filters, 2**i, name=f'DenseBlock_{i}')(x)
    return x


def causally_masked_softmax(logits: jnp.ndarray) -> jnp.ndarray:
  """Softmax over the last dim with positions j > i masked out.

  Same contract as snail.py:95-117 for [B, T, T] logits.
  """
  t = logits.shape[-1]
  mask = jnp.tril(jnp.ones((t, t), dtype=bool))
  logits = jnp.where(mask, logits, -jnp.inf)
  return nn.softmax(logits, axis=-1)


class AttentionBlock(nn.Module):
  """Causal single-head attention, read concatenated (snail.py:119-152).

  Returns ([B, T, C + value_size], {'attn_prob': [B, T, T]}).
  """

  key_size: int
  value_size: int

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, dict]:
    key = nn.Dense(self.key_size)(x)
    query = nn.Dense(self.key_size)(x)
    logits = jnp.einsum('btk,bsk->bts', query, key)
    probs = causally_masked_softmax(logits / np.sqrt(self.key_size))
    values = nn.Dense(self.value_size)(x)
    read = jnp.einsum('bts,bsv->btv', probs, values)
    return jnp.concatenate([x, read], axis=2), {'attn_prob': probs}
