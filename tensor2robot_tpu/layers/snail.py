"""SNAIL meta-learner blocks: dilated causal convs + causal attention.

Reference: ``/root/reference/layers/snail.py:35-152`` (Mishra et al. '17).
Flax modules with the same shape contracts. The causal mask is applied as
an additive ``-inf`` upper triangle before one fused softmax — the TPU-
friendly form XLA pattern-matches — instead of the reference's band-part
decomposition.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class CausalConv(nn.Module):
  """Causal dilated 1-D conv over [B, T, C] (snail.py:35-58)."""

  filters: int
  dilation_rate: int = 1
  kernel_size: int = 2

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    pad = (self.kernel_size - 1) * self.dilation_rate
    x = jnp.pad(x, ((0, 0), (pad, 0), (0, 0)))
    return nn.Conv(
        features=self.filters,
        kernel_size=(self.kernel_size,),
        kernel_dilation=(self.dilation_rate,),
        padding='VALID')(x)


class DenseBlock(nn.Module):
  """Gated activation, concatenated to the input (snail.py:60-76)."""

  filters: int
  dilation_rate: int = 1

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    xf = CausalConv(self.filters, self.dilation_rate, name='xf')(x)
    xg = CausalConv(self.filters, self.dilation_rate, name='xg')(x)
    activations = jnp.tanh(xf) * nn.sigmoid(xg)
    return jnp.concatenate([x, activations], axis=2)


class TCBlock(nn.Module):
  """DenseBlocks with dilations 2^1..2^ceil(log2(T)) (snail.py:78-93)."""

  sequence_length: int
  filters: int

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    num_blocks = int(np.ceil(np.log2(self.sequence_length)))
    for i in range(1, num_blocks + 1):
      x = DenseBlock(self.filters, 2**i, name=f'DenseBlock_{i}')(x)
    return x


def causally_masked_softmax(logits: jnp.ndarray) -> jnp.ndarray:
  """Softmax over the last dim with positions j > i masked out.

  Same contract as snail.py:95-117 for [B, T, T] logits.
  """
  t = logits.shape[-1]
  mask = jnp.tril(jnp.ones((t, t), dtype=bool))
  logits = jnp.where(mask, logits, -jnp.inf)
  return nn.softmax(logits, axis=-1)


def _flash_pad_dim(key_size: int, value_size: int) -> int:
  """Shared head dim for the flash kernels: max(dk, dv) rounded up to 8."""
  d = max(key_size, value_size)
  return -(-d // 8) * 8


def flash_supported(t: int, key_size: int, value_size: int,
                    itemsize: int = 2) -> bool:
  """Whether the flash path can serve an AttentionBlock problem."""
  from tensor2robot_tpu.ops import flash_attention as fa

  return fa.is_supported(t, _flash_pad_dim(key_size, value_size),
                         itemsize=itemsize)


def _flash_auto_ok() -> bool:
  """Auto-dispatch gate: real TPU only — interpret-mode Pallas loses to
  the dense einsum off-TPU, and Mosaic custom calls don't lower for CPU
  serving platforms. Tests monkeypatch this to exercise the flash path
  in interpret mode."""
  return jax.default_backend() == 'tpu'


def _flash_causal_read(query: jnp.ndarray, key: jnp.ndarray,
                       values: jnp.ndarray) -> jnp.ndarray:
  """Causal attention read via the Pallas flash kernels, O(T·D) memory.

  q/k ([B, T, dk]) and v ([B, T, dv]) are zero-padded to one 8-aligned
  head dim (zero pads contribute nothing to q·kᵀ or the read), and q is
  pre-scaled so the kernel's 1/√d_pad matches the SNAIL 1/√dk logits.
  """
  from tensor2robot_tpu.ops import flash_attention as fa

  dk, dv = query.shape[-1], values.shape[-1]
  d = _flash_pad_dim(dk, dv)
  query = query * np.sqrt(d / dk)

  def pad(x):
    need = d - x.shape[-1]
    if need:
      x = jnp.pad(x, ((0, 0), (0, 0), (0, need)))
    return x[:, :, None, :]  # single head: [B, T, 1, d]

  out = fa.flash_attention(pad(query), pad(key), pad(values), causal=True)
  return out[:, :, 0, :dv]


class AttentionBlock(nn.Module):
  """Causal single-head attention, read concatenated (snail.py:119-152).

  Returns ``([B, T, C + value_size], end_points)``. By default the block
  dispatches to the Pallas flash-attention kernels whenever the problem
  is supported (:func:`flash_supported`) AND the backend is a real TPU —
  O(T·D) memory, no [B, T, T] materialization — and ``end_points`` is
  empty. Off-TPU the auto default stays dense: interpret-mode Pallas
  would be slower than the einsum it replaces, and a serving export
  traced with a Mosaic custom call cannot lower for CPU robot hosts
  (models additionally force the dense path in PREDICT mode for that
  reason). Setting ``return_prob=True`` requests the
  ``{'attn_prob': [B, T, T]}`` tensor, which forces the dense O(T²) path
  (that tensor IS the quadratic cost). ``use_flash`` overrides the auto
  dispatch either way.
  """

  key_size: int
  value_size: int
  return_prob: bool = False
  use_flash: Optional[bool] = None

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, dict]:
    key = nn.Dense(self.key_size)(x)
    query = nn.Dense(self.key_size)(x)
    values = nn.Dense(self.value_size)(x)
    t = x.shape[1]
    use_flash = self.use_flash
    if use_flash is None:
      use_flash = (not self.return_prob and _flash_auto_ok() and
                   flash_supported(t, self.key_size, self.value_size,
                                   itemsize=query.dtype.itemsize))
    if use_flash:
      if self.return_prob:
        raise ValueError(
            'return_prob=True requires the dense path (the [B, T, T] '
            'probability tensor is what flash attention avoids); do not '
            'combine it with use_flash=True.')
      read = _flash_causal_read(query, key, values)
      return jnp.concatenate([x, read], axis=2), {}
    logits = jnp.einsum('btk,bsk->bts', query, key)
    probs = causally_masked_softmax(logits / np.sqrt(self.key_size))
    read = jnp.einsum('bts,bsv->btv', probs, values)
    end_points = {'attn_prob': probs} if self.return_prob else {}
    return jnp.concatenate([x, read], axis=2), end_points


class MultiHeadAttentionBlock(nn.Module):
  """Causal multi-head SNAIL attention for long-horizon sequences.

  The scaling generalization of :class:`AttentionBlock`: H heads of size
  D let the read be computed by the Pallas flash kernels AND sharded over
  a ``seq`` mesh axis — ``attention_fn`` (a
  ``sequence_parallel.make_ring_attention`` /
  ``make_ulysses_attention`` product built for the trainer's mesh, causal
  pre-bound) takes precedence; otherwise flash when supported; otherwise
  the dense oracle. Returns ``([B, T, C + H·D], {})`` — the read is the
  concatenated heads, matching the single-head block's read-concat form.
  """

  num_heads: int
  head_size: int
  attention_fn: Optional[Callable] = None
  use_flash: Optional[bool] = None  # None = auto (TPU + supported)

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, dict]:
    b, t = x.shape[:2]
    h, d = self.num_heads, self.head_size

    def heads(name):
      return nn.Dense(h * d, name=name)(x).reshape(b, t, h, d)

    query, key, values = heads('query'), heads('key'), heads('value')
    if self.attention_fn is not None:
      out = self.attention_fn(query, key, values)
    else:
      from tensor2robot_tpu.ops import flash_attention as fa

      use_flash = self.use_flash
      if use_flash is None:
        use_flash = _flash_auto_ok() and fa.is_supported(
            t, d, itemsize=query.dtype.itemsize)
      if use_flash:
        out = fa.flash_attention(query, key, values, causal=True)
      else:
        from tensor2robot_tpu.parallel.sequence_parallel import (
            reference_attention)

        out = reference_attention(query, key, values, causal=True)
    read = out.reshape(b, t, h * d)
    return jnp.concatenate([x, read], axis=2), {}
