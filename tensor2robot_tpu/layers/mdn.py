"""Mixture density network head: mixture of isotropic gaussians.

Reference: ``/root/reference/layers/mdn.py:34-168`` (tfp-based). Rebuilt in
pure jnp (no tfp in this environment): a lightweight
:class:`GaussianMixture` pytree provides exactly the operations the
framework uses — ``log_prob``, ``mode of the most probable component``,
and ``sample`` — with logsumexp-stable math that jits cleanly.

Layout contract is identical: params vector =
``[alphas (K) | mus (K*D) | raw_sigmas (K*D)]``, ``sigma = softplus(raw)``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp
import numpy as np


@flax.struct.dataclass
class GaussianMixture:
  """Mixture of K isotropic gaussians over D dims; batched arbitrarily."""

  logits: jnp.ndarray      # [..., K]
  mus: jnp.ndarray         # [..., K, D]
  sigmas: jnp.ndarray      # [..., K, D] (already softplus'd, > 0)

  @property
  def num_components(self) -> int:
    return self.logits.shape[-1]

  def component_log_prob(self, value: jnp.ndarray) -> jnp.ndarray:
    """log N(value | mu_k, sigma_k) for each component k → [..., K]."""
    value = value[..., None, :]  # broadcast over K
    var = jnp.square(self.sigmas)
    log_det = jnp.sum(jnp.log(var), axis=-1)
    d = self.mus.shape[-1]
    quad = jnp.sum(jnp.square(value - self.mus) / var, axis=-1)
    return -0.5 * (quad + log_det + d * jnp.log(2.0 * jnp.pi))

  def log_prob(self, value: jnp.ndarray) -> jnp.ndarray:
    log_alphas = jax.nn.log_softmax(self.logits, axis=-1)
    return jax.scipy.special.logsumexp(
        log_alphas + self.component_log_prob(value), axis=-1)

  def mean(self) -> jnp.ndarray:
    alphas = jax.nn.softmax(self.logits, axis=-1)
    return jnp.sum(alphas[..., None] * self.mus, axis=-2)

  def approximate_mode(self) -> jnp.ndarray:
    """Mean of the most probable component (mdn.py:118-125)."""
    top = jnp.argmax(self.logits, axis=-1)
    return jnp.take_along_axis(
        self.mus, top[..., None, None], axis=-2).squeeze(-2)

  def sample(self, rng: jax.Array) -> jnp.ndarray:
    comp_rng, noise_rng = jax.random.split(rng)
    idx = jax.random.categorical(comp_rng, self.logits, axis=-1)
    mus = jnp.take_along_axis(self.mus, idx[..., None, None], axis=-2)
    sigmas = jnp.take_along_axis(self.sigmas, idx[..., None, None], axis=-2)
    noise = jax.random.normal(noise_rng, mus.shape, dtype=mus.dtype)
    return (mus + sigmas * noise).squeeze(-2)


def get_mixture_distribution(params: jnp.ndarray,
                             num_alphas: int,
                             sample_size: int,
                             output_mean: Optional[jnp.ndarray] = None
                             ) -> GaussianMixture:
  """Param vector → mixture (mdn.py:34-73); same packing layout."""
  num_mus = num_alphas * sample_size
  if params.shape[-1] != num_alphas + 2 * num_mus:
    raise ValueError(
        f'params last dim {params.shape[-1]} != '
        f'{num_alphas + 2 * num_mus} (K + 2*K*D)')
  batch_shape = params.shape[:-1]
  alphas = params[..., :num_alphas]
  mus = params[..., num_alphas:num_alphas + num_mus].reshape(
      batch_shape + (num_alphas, sample_size))
  raw_sigmas = params[..., num_alphas + num_mus:].reshape(
      batch_shape + (num_alphas, sample_size))
  if output_mean is not None:
    mus = mus + output_mean[..., None, :]
  return GaussianMixture(
      logits=alphas, mus=mus, sigmas=jax.nn.softplus(raw_sigmas))


gaussian_mixture_approximate_mode = GaussianMixture.approximate_mode


class MDNParams(nn.Module):
  """Dense head emitting mixture params (predict_mdn_params, mdn.py:76-115).

  With ``condition_sigmas=False`` the sigmas are free variables initialized
  so ``softplus(sigma) = 1``.
  """

  num_alphas: int
  sample_size: int
  condition_sigmas: bool = False

  @nn.compact
  def __call__(self, inputs: jnp.ndarray) -> jnp.ndarray:
    num_mus = self.num_alphas * self.sample_size
    num_out = self.num_alphas + num_mus
    if self.condition_sigmas:
      num_out += num_mus
    params = nn.Dense(num_out, name='mdn_params')(inputs)
    if not self.condition_sigmas:
      sigmas = self.param(
          'mdn_stddev_inputs',
          nn.initializers.constant(np.log(np.e - 1.0)),
          (num_mus,), jnp.float32)
      tiled = jnp.broadcast_to(
          sigmas, params.shape[:-1] + (num_mus,)).astype(params.dtype)
      params = jnp.concatenate([params, tiled], axis=-1)
    return params


class MDNDecoder(nn.Module):
  """Action decoder head (mdn.py:128-168), stateless JAX version.

  ``__call__(params_features, output_size)`` returns
  ``(action, GaussianMixture)`` — the mixture is returned instead of being
  stashed on the object (the statefulness the reference's TODO warns about).
  Use :func:`mdn_nll_loss` with the returned mixture.
  """

  num_mixture_components: int = 1

  @nn.compact
  def __call__(self, params: jnp.ndarray,
               output_size: int) -> Tuple[jnp.ndarray, GaussianMixture]:
    dist_params = MDNParams(
        num_alphas=self.num_mixture_components,
        sample_size=output_size,
        condition_sigmas=False)(params)
    gm = get_mixture_distribution(
        dist_params.astype(jnp.float32), self.num_mixture_components,
        output_size)
    action = gm.approximate_mode()
    return action, gm


def mdn_nll_loss(gm: GaussianMixture, target: jnp.ndarray) -> jnp.ndarray:
  """Mean negative log likelihood over batch/sequence dims."""
  return -jnp.mean(gm.log_prob(target.astype(jnp.float32)))
