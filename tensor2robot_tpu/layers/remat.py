"""Activation-rematerialization policies for the conv towers.

Sublinear activation checkpointing (Chen et al. 2016) as a declarative
knob: a model picks a *policy name* and the layers apply ``jax.checkpoint``
(via flax's lifted ``nn.remat``) around their tower blocks. Activation
memory then trades against recompute on the MXU — the lever that moves
the HBM batch ceiling (PERF_NOTES: the qtopt batch curve collapses 8.6×
at batch 96 from HBM pressure while the MXU sits at ~22% utilization).

Policies (``REMAT_POLICIES``):

* ``none`` — status quo: XLA keeps every activation the backward needs.
* ``conv_towers`` — each tower block is a checkpoint region; inside a
  region only results of *weight-stationary* dots (no batch dimensions —
  cheap, e.g. FiLM projections) are saved, so the big [B, H, W, C]
  conv/BN activations are recomputed from the block boundary during the
  backward pass. Activation memory drops from O(depth) blocks to
  O(1) block + boundaries; recompute adds roughly one extra forward of
  MXU work, which the measured ~22% MFU ceiling has headroom for.
* ``full`` — like ``conv_towers`` but nothing inside a region is saved
  (``nothing_saveable``): maximum memory savings, maximum recompute.

Wrapping happens with flax lifted transforms, so parameter/collection
trees are IDENTICAL with and without remat (checkpoints interchange;
pinned by tests/test_memory_scaling.py), and the forward/backward values
are exactly equal — remat changes scheduling, not math.
"""

from __future__ import annotations

from typing import Optional

REMAT_NONE = 'none'
REMAT_CONV_TOWERS = 'conv_towers'
REMAT_FULL = 'full'
REMAT_POLICIES = (REMAT_NONE, REMAT_CONV_TOWERS, REMAT_FULL)


def validate_remat_policy(policy: Optional[str]) -> str:
  """Normalizes/validates a policy name (None → 'none')."""
  policy = REMAT_NONE if policy is None else str(policy)
  if policy not in REMAT_POLICIES:
    raise ValueError(
        f'Unknown remat_policy {policy!r}; expected one of {REMAT_POLICIES}.')
  return policy


def checkpoint_policy(policy: Optional[str]):
  """The ``jax.checkpoint`` policy for a name (None when remat is off)."""
  import jax

  policy = validate_remat_policy(policy)
  if policy == REMAT_NONE:
    return None
  if policy == REMAT_CONV_TOWERS:
    return jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
  return jax.checkpoint_policies.nothing_saveable


def remat_module(module_cls, policy: Optional[str], static_argnums=()):
  """Wraps a flax Module class in ``nn.remat`` per the named policy.

  ``static_argnums`` index into ``__call__``'s arguments with ``self`` at
  0 (flax's convention) — pass the indices of python-control-flow args
  like ``train``. Returns ``module_cls`` untouched for policy 'none', so
  call sites can apply it unconditionally.
  """
  policy = validate_remat_policy(policy)
  if policy == REMAT_NONE:
    return module_cls
  import flax.linen as nn

  return nn.remat(
      module_cls, policy=checkpoint_policy(policy),
      static_argnums=tuple(static_argnums))


def remat_method(fn, policy: Optional[str], static_argnums=()):
  """``nn.remat`` over an UNBOUND Module method (call as ``fn(self, ...)``).

  For towers whose blocks are built inline in a ``@nn.compact``
  ``__call__`` (e.g. ``vision_layers.ImagesToFeaturesModel``), wrapping a
  helper method keeps the parameter tree byte-identical to the unwrapped
  module — the lifted transform shares the caller's scope.
  """
  policy = validate_remat_policy(policy)
  if policy == REMAT_NONE:
    return fn
  import flax.linen as nn

  return nn.remat(
      fn, policy=checkpoint_policy(policy),
      static_argnums=tuple(static_argnums))
