"""Vision layers: conv towers with spatial-softmax heads + pose MLPs.

Reference: ``/root/reference/layers/vision_layers.py`` ("Berkeley-Net"
family used by pose_env / vrgripper). Flax modules with identical shape
and conditioning contracts:

* :class:`ImagesToFeaturesModel` — VALID-padded conv stack with optional
  per-block FiLM ``(1+γ)·x + β`` conditioning, 1×1 projection, spatial
  softmax head (vision_layers.py:33-151).
* :class:`FILMParams` — linear layer emitting concatenated γ/β
  (vision_layers.py:154-174).
* :class:`ImagesToFeaturesModelHighRes` — multi-resolution PI-GPS variant
  summing upsampled block outputs (vision_layers.py:177-266).
* :class:`ImageFeaturesToPoseModel` — MLP with MAML-style bias transform
  (vision_layers.py:269-343).
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu.layers.remat import remat_method
from tensor2robot_tpu.layers.spatial_softmax import spatial_softmax
from tensor2robot_tpu.ops import _pallas_dispatch as pallas_dispatch
from tensor2robot_tpu.ops import pool as pool_ops

_NUM_CHANNELS_PER_BLOCK = 32


def film_modulation(net: jnp.ndarray, gamma: jnp.ndarray,
                    beta: jnp.ndarray) -> jnp.ndarray:
  """FiLM with the zero-centered-gamma convention: (1 + γ)·x + β."""
  gamma = gamma[:, None, None, :]
  beta = beta[:, None, None, :]
  return (1.0 + gamma) * net + beta


def film_params_size(num_blocks: int,
                     channels: int = _NUM_CHANNELS_PER_BLOCK) -> int:
  return 2 * num_blocks * channels


class ImagesToFeaturesModel(nn.Module):
  """Conv tower → spatial softmax (vision_layers.py:33-151).

  ``__call__(images, film_output_params=None, train=False)`` returns
  ``(expected_feature_points [B, 2*num_output_maps], {'softmax': maps})``.
  FiLM params, when given, are ``[B, 2*num_blocks*32]`` laid out as all
  gammas then all betas (block-major).
  """

  filter_size: int = 3
  num_blocks: int = 5
  num_output_maps: int = 32
  use_batch_norm: bool = False  # reference default: layer norm
  # Activation remat per conv block (layers/remat.py): recompute block
  # activations during backward instead of keeping them live. Parameter
  # tree and numerics are unchanged ('none' = historical behavior).
  remat_policy: str = 'none'

  def _conv_block(self, net, gamma, beta, i, train):
    """One conv→norm→FiLM→relu block (the remat unit)."""
    stride = 2 if i in (0, 1) else 1
    net = nn.Conv(
        features=_NUM_CHANNELS_PER_BLOCK,
        kernel_size=(self.filter_size, self.filter_size),
        strides=(stride, stride),
        padding='VALID',
        kernel_init=nn.initializers.xavier_uniform(),
        bias_init=nn.initializers.constant(0.01),
        name=f'conv{i + 2}')(net)
    net = self._normalize(net, train, scale=False, name=f'norm{i + 2}')
    if gamma is not None:
      net = film_modulation(net, gamma, beta)
    return nn.relu(net)

  @nn.compact
  def __call__(self,
               images: jnp.ndarray,
               film_output_params: Optional[jnp.ndarray] = None,
               train: bool = False) -> Tuple[jnp.ndarray, dict]:
    channels = _NUM_CHANNELS_PER_BLOCK
    gammas = betas = None
    if film_output_params is not None:
      expected = film_params_size(self.num_blocks, channels)
      if film_output_params.ndim != 2 or (
          film_output_params.shape[-1] != expected):
        raise ValueError(
            f'FiLM params must be [B, {expected}], got '
            f'{film_output_params.shape}')
      split = jnp.split(film_output_params, 2 * self.num_blocks, axis=-1)
      gammas, betas = split[:self.num_blocks], split[self.num_blocks:]

    # Method-form remat keeps the blocks' inline parameter names
    # (conv{i}/norm{i} at this module's top level) byte-identical to the
    # unwrapped tower. `i` (4) names modules and `train` (5) is python
    # control flow — both static under jax.checkpoint.
    block = remat_method(
        ImagesToFeaturesModel._conv_block, self.remat_policy,
        static_argnums=(4, 5))

    net = images
    for i in range(self.num_blocks):
      net = block(self, net,
                  None if gammas is None else gammas[i],
                  None if betas is None else betas[i], i, train)

    net = nn.Conv(
        features=self.num_output_maps,
        kernel_size=(1, 1),
        padding='VALID',
        kernel_init=nn.initializers.xavier_uniform(),
        bias_init=nn.initializers.constant(0.01),
        name='final_conv_1x1')(net)
    net = self._normalize(net, train, scale=True, name='final_norm')
    points, softmax = spatial_softmax(net)
    return points, {'softmax': softmax}

  def _normalize(self, net, train, scale, name):
    if self.use_batch_norm:
      return nn.BatchNorm(
          use_running_average=not train, momentum=0.99, epsilon=1e-4,
          use_scale=scale, name=name)(net)
    return nn.LayerNorm(use_scale=scale, name=name)(net)


class FILMParams(nn.Module):
  """Linear γ/β generator from an embedding (vision_layers.py:154-174)."""

  film_output_size: int = film_params_size(5)

  @nn.compact
  def __call__(self, embedding: jnp.ndarray) -> jnp.ndarray:
    return nn.Dense(self.film_output_size, name='film')(embedding)


class ImagesToFeaturesModelHighRes(nn.Module):
  """Multi-res conv tower (PI-GPS variant, vision_layers.py:177-266).

  Block outputs at different resolutions are nearest-neighbor upsampled to
  the first block's resolution and summed before the spatial softmax.
  """

  filter_size: int = 3
  num_blocks: int = 5
  num_output_maps: int = 32
  # Pallas kernel routing (ops/_pallas_dispatch.py): the per-block 2×2
  # max pools go through the argmax-emitting fused kernel; size-gated,
  # stock fallback off-TPU, bitwise-identical either way.
  kernel_policy: str = 'none'

  @nn.compact
  def __call__(self, images: jnp.ndarray,
               train: bool = False) -> Tuple[jnp.ndarray, dict]:
    # use_bias=False: every conv here feeds a BatchNorm, whose mean
    # subtraction cancels a conv bias exactly (dead param + a wasted
    # full-tensor gradient reduction; same rationale as qtopt networks).
    conv_kwargs = dict(
        padding='VALID',
        use_bias=False,
        kernel_init=nn.initializers.truncated_normal(stddev=0.1))

    def norm(net, scale, name):
      return nn.BatchNorm(
          use_running_average=not train, momentum=0.99, epsilon=1e-4,
          use_scale=scale, name=name)(net)

    block_outs = []
    net = nn.avg_pool(images, (2, 2), strides=(2, 2), padding='VALID')
    net = nn.Conv(16, (self.filter_size, self.filter_size), strides=(2, 2),
                  name='conv1', **conv_kwargs)(net)
    net = nn.relu(norm(net, False, 'norm1'))
    net = nn.Conv(32, (self.filter_size, self.filter_size), name='conv2',
                  **conv_kwargs)(net)
    net = nn.relu(norm(net, False, 'norm2'))
    out = nn.Conv(32, (1, 1), name='conv2_1x1', **conv_kwargs)(net)
    block_outs.append(nn.relu(norm(out, False, 'norm2_1x1')))
    max_pool = (pool_ops.max_pool
                if pallas_dispatch.policy_enables_pool(self.kernel_policy)
                else nn.max_pool)
    for i in range(1, self.num_blocks):
      net = max_pool(net, (2, 2), strides=(2, 2), padding='VALID')
      net = nn.Conv(32, (self.filter_size, self.filter_size),
                    name=f'conv{i + 2}', **conv_kwargs)(net)
      net = nn.relu(norm(net, False, f'norm{i + 2}'))
      out = nn.Conv(32, (1, 1), name=f'conv{i + 2}_1x1', **conv_kwargs)(net)
      block_outs.append(nn.relu(norm(out, False, f'norm{i + 2}_1x1')))

    target_hw = block_outs[0].shape[1:3]

    def resize(layer):
      return jax.image.resize(
          layer, layer.shape[:1] + target_hw + layer.shape[3:],
          method='nearest')

    net = sum(resize(layer) for layer in block_outs)
    net = nn.Conv(self.num_output_maps, (1, 1), name='final_conv_1x1',
                  **conv_kwargs)(net)
    net = norm(net, True, 'final_norm')
    points, softmax = spatial_softmax(net)
    return points, {'softmax': softmax}


class ImageFeaturesToPoseModel(nn.Module):
  """Feature points (+aux) → pose MLP (vision_layers.py:269-343).

  The bias transform — a learned vector concatenated to the input — gives
  MAML's inner loop a direct knob on the MLP input distribution.
  """

  num_outputs: Optional[int]
  aux_output_dim: int = 0
  hidden_dim: int = 100
  num_layers: int = 2
  bias_transform_size: int = 10

  @nn.compact
  def __call__(self,
               expected_feature_points: jnp.ndarray,
               aux_input: Optional[jnp.ndarray] = None
               ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    dense_kwargs = dict(
        kernel_init=nn.initializers.truncated_normal(stddev=0.01),
        bias_init=nn.initializers.constant(0.01))
    if aux_input is not None:
      net = jnp.concatenate([expected_feature_points, aux_input], axis=1)
    else:
      net = expected_feature_points
    if self.bias_transform_size > 0:
      bias_transform = self.param(
          'bias_transform', nn.initializers.constant(0.01),
          (self.bias_transform_size,), jnp.float32)
      tiled = jnp.broadcast_to(
          bias_transform,
          (net.shape[0], self.bias_transform_size)).astype(net.dtype)
      net = jnp.concatenate([net, tiled], axis=1)
    for layer_index in range(self.num_layers):
      net = nn.Dense(self.hidden_dim, name=f'pose_fc{layer_index}',
                     **dense_kwargs)(net)
      net = nn.LayerNorm()(net)
      net = nn.relu(net)
    if self.num_outputs:
      net = nn.Dense(self.num_outputs, name=f'pose_fc{self.num_layers}',
                     **dense_kwargs)(net)
    aux_output = None
    if self.aux_output_dim > 0:
      aux_output = nn.Dense(self.aux_output_dim, name='pose_fc_aux',
                            **dense_kwargs)(expected_feature_points)
    return net, aux_output


# Reference-name aliases.
BuildImagesToFeaturesModel = ImagesToFeaturesModel
BuildFILMParams = FILMParams
BuildImagesToFeaturesModelHighRes = ImagesToFeaturesModelHighRes
BuildImageFeaturesToPoseModel = ImageFeaturesToPoseModel
