"""Spatial softmax: expected 2-D feature coordinates (soft arg-max).

Reference: ``/root/reference/layers/spatial_softmax.py:33-93``. Same output
contract — coordinates in [-1, 1], inner dim ordered
``[x1..xN, y1..yN]`` — as pure jnp: one softmax over flattened pixels and
one matmul against the coordinate grid (fuses into a couple of XLA ops; no
per-pixel Python loops).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _coordinate_grid(num_rows: int, num_cols: int, dtype) -> jnp.ndarray:
  """[num_rows*num_cols, 2] grid of (x, y) in [-1, 1]."""
  ys = jnp.linspace(-1.0, 1.0, num_rows, dtype=dtype)
  xs = jnp.linspace(-1.0, 1.0, num_cols, dtype=dtype)
  grid_y, grid_x = jnp.meshgrid(ys, xs, indexing='ij')
  return jnp.stack([grid_x.ravel(), grid_y.ravel()], axis=-1)


def spatial_softmax(features: jnp.ndarray,
                    temperature: float = 1.0,
                    spatial_gumbel_softmax: bool = False,
                    rng: Optional[jax.Array] = None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
  """Expected feature coordinates of [B, H, W, C] feature maps.

  Returns:
    (expected_feature_points [B, 2*C] ordered [x1..xC, y1..yC],
     softmax [B, H, W, C]).
  """
  batch, num_rows, num_cols, num_features = features.shape
  compute_dtype = jnp.promote_types(features.dtype, jnp.float32)
  # [B, C, H*W]: merge batch & channel for one batched softmax.
  logits = jnp.transpose(features, (0, 3, 1, 2)).reshape(
      batch, num_features, num_rows * num_cols).astype(compute_dtype)
  logits = logits / temperature
  if spatial_gumbel_softmax:
    if rng is None:
      raise ValueError('spatial_gumbel_softmax requires an rng key.')
    # Relaxed one-hot categorical sample (Gumbel-softmax, temperature 1.0).
    gumbel = jax.random.gumbel(rng, logits.shape, dtype=compute_dtype)
    attention = jax.nn.softmax(logits + gumbel, axis=-1)
  else:
    attention = jax.nn.softmax(logits, axis=-1)
  grid = _coordinate_grid(num_rows, num_cols, compute_dtype)  # [HW, 2]
  # [B, C, 2]: expectation = attention @ grid (rides the MXU).
  expected_xy = attention @ grid
  # Reorder to [x1..xC, y1..yC].
  expected_feature_points = jnp.concatenate(
      [expected_xy[..., 0], expected_xy[..., 1]], axis=-1)
  softmax_maps = jnp.transpose(
      attention.reshape(batch, num_features, num_rows, num_cols),
      (0, 2, 3, 1))
  return (expected_feature_points.astype(features.dtype),
          softmax_maps.astype(features.dtype))


# Reference-name alias.
BuildSpatialSoftmax = spatial_softmax
