"""Task-embedding (TEC) layers: episode → embedding + contrastive loss.

Reference: ``/root/reference/layers/tec.py:30-172`` (Task-Embedded Control
Networks). Flax modules with the same contracts: full-state/image episode
encoders, temporal reduction via 1-D convs + MLP, and the contrastive
embedding loss over (inference, condition) episode embeddings.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from tensor2robot_tpu.layers.vision_layers import ImagesToFeaturesModel


class EmbedFullstate(nn.Module):
  """MLP embedding of non-image state [N, F] → [N, embed_size] (tec.py:30)."""

  embed_size: int
  fc_layers: Sequence[int] = (100,)

  @nn.compact
  def __call__(self, fullstate: jnp.ndarray) -> jnp.ndarray:
    net = fullstate
    for i, width in enumerate(self.fc_layers):
      net = nn.Dense(width, name=f'fc{i}')(net)
      net = nn.LayerNorm()(net)
      net = nn.relu(net)
    return nn.Dense(self.embed_size, name='embed')(net)


class EmbedConditionImages(nn.Module):
  """Per-image embedding via the vision tower (tec.py:53-88)."""

  fc_layers: Optional[Sequence[int]] = None

  @nn.compact
  def __call__(self, condition_image: jnp.ndarray,
               train: bool = False) -> jnp.ndarray:
    if condition_image.ndim != 4:
      raise ValueError(
          f'Image has unexpected shape {condition_image.shape}.')
    embedding, _ = ImagesToFeaturesModel()(condition_image, train=train)
    if self.fc_layers is not None:
      for i, width in enumerate(self.fc_layers[:-1]):
        embedding = nn.Dense(width, name=f'fc{i}')(embedding)
        embedding = nn.LayerNorm()(embedding)
        embedding = nn.relu(embedding)
      embedding = nn.Dense(self.fc_layers[-1], name='fc_out')(embedding)
    return embedding


class ReduceTemporalEmbeddings(nn.Module):
  """[N, T, F] → [N, output_size] via 1-D convs + MLP (tec.py:90-133).

  For sequences shorter than ``kernel_size`` the conv kernel is clipped to
  T (a VALID conv would otherwise produce an empty time axis). Parameter
  shapes therefore depend on the episode length the module is first built
  with — one module instance serves ONE episode length, which is also the
  reference's contract (fixed ``episode_length`` per model).
  """

  output_size: int
  conv1d_layers: Optional[Sequence[int]] = (64,)
  fc_hidden_layers: Sequence[int] = (100,)
  kernel_size: int = 10

  @nn.compact
  def __call__(self, temporal_embedding: jnp.ndarray) -> jnp.ndarray:
    if temporal_embedding.ndim != 3:
      raise ValueError(
          f'Temporal embedding has unexpected shape '
          f'{temporal_embedding.shape}.')
    net = temporal_embedding
    if self.conv1d_layers is not None:
      for i, num_filters in enumerate(self.conv1d_layers):
        # Clip the kernel to the (possibly short) sequence so VALID conv
        # never produces an empty time axis (short test episodes).
        kernel = min(self.kernel_size, net.shape[1])
        net = nn.Conv(
            num_filters, (kernel,), padding='VALID',
            use_bias=False, name=f'conv1d_{i}')(net)
        net = nn.relu(net)
        net = nn.LayerNorm()(net)
    net = net.reshape((net.shape[0], -1))
    for i, width in enumerate(self.fc_hidden_layers):
      net = nn.Dense(width, name=f'fc{i}')(net)
      net = nn.LayerNorm()(net)
      net = nn.relu(net)
    return nn.Dense(self.output_size, name='out')(net)


def contrastive_loss(labels: jnp.ndarray,
                     anchor: jnp.ndarray,
                     embeddings: jnp.ndarray,
                     margin: float = 1.0) -> jnp.ndarray:
  """Standard contrastive loss between one anchor and N embeddings.

  ``labels[i]`` marks embedding i as a positive for the anchor. Positives
  pull (squared distance), negatives push below ``margin``.
  """
  distances = jnp.sqrt(
      jnp.sum(jnp.square(anchor - embeddings), axis=-1) + 1e-12)
  labels = labels.astype(jnp.float32)
  positive_term = labels * jnp.square(distances)
  negative_term = (1.0 - labels) * jnp.square(
      jnp.maximum(margin - distances, 0.0))
  return jnp.mean(positive_term + negative_term)


def compute_embedding_contrastive_loss(
    inf_embedding: jnp.ndarray,
    con_embedding: jnp.ndarray,
    positives: Optional[jnp.ndarray] = None) -> jnp.ndarray:
  """Anchor = task-0 inference embedding vs all condition embeddings.

  Mirrors tec.py:136-172: embeddings [num_tasks, num_episodes, K] are
  averaged over episodes; task 0 is the positive unless ``positives``
  marks otherwise. Embeddings are expected L2-normalized.
  """
  if inf_embedding.ndim != 3:
    raise ValueError(
        f'Unexpected inf_embedding shape: {inf_embedding.shape}.')
  if con_embedding.ndim != 3:
    raise ValueError(
        f'Unexpected con_embedding shape: {con_embedding.shape}.')
  avg_inf_embedding = jnp.mean(inf_embedding, axis=1)
  avg_con_embedding = jnp.mean(con_embedding, axis=1)
  anchor = avg_inf_embedding[0:1]
  if positives is not None:
    labels = positives
  else:
    labels = jnp.arange(avg_con_embedding.shape[0]) == 0
  return contrastive_loss(labels, anchor, avg_con_embedding)


# Reference-name aliases.
embed_fullstate = EmbedFullstate
embed_condition_images = EmbedConditionImages
reduce_temporal_embeddings = ReduceTemporalEmbeddings
