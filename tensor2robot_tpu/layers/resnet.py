"""ResNet v1/v2 (18–200) with per-block FiLM conditioning, in Flax.

Reference: ``/root/reference/layers/film_resnet_model.py`` (TF official
ResNet extended with FiLM, ``:113-124`` ``_apply_film``) and
``/root/reference/layers/resnet.py`` (size table ``:37-68``, builder
``:152-218``, ``linear_film_generator`` ``:103-149``,
``resnet_endpoints`` ``:86-100``).

TPU-first notes: NHWC layout (XLA's native conv layout on TPU), bfloat16-
friendly (compute dtype follows the input), no channels_first switch, and
endpoints returned as a dict instead of graph-name scraping.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from tensor2robot_tpu.layers.remat import remat_module
from tensor2robot_tpu.ops import _pallas_dispatch as pallas_dispatch
from tensor2robot_tpu.ops import pool as pool_ops

BLOCK_SIZES = {
    18: [2, 2, 2, 2],
    34: [3, 4, 6, 3],
    50: [3, 4, 6, 3],
    101: [3, 4, 23, 3],
    152: [3, 8, 36, 3],
    200: [3, 24, 36, 3],
}

# v1/v2 bottleneck cutoff: sizes < 50 use basic blocks (resnet.py:172-187).
_BOTTLENECK_MIN_SIZE = 50


def apply_film(inputs: jnp.ndarray,
               film_gamma_beta: Optional[jnp.ndarray]) -> jnp.ndarray:
  """(1+γ)·x + β with γ/β split from [B, 2C] (film_resnet_model.py:113-124)."""
  if film_gamma_beta is None:
    return inputs
  gamma, beta = jnp.split(film_gamma_beta, 2, axis=-1)
  gamma = (1.0 + gamma)[:, None, None, :].astype(inputs.dtype)
  beta = beta[:, None, None, :].astype(inputs.dtype)
  return gamma * inputs + beta


class _BatchNorm(nn.Module):
  """BN with the TF official model's hyperparams (momentum .997, eps 1e-5)."""

  @nn.compact
  def __call__(self, x, train: bool):
    return nn.BatchNorm(
        use_running_average=not train, momentum=0.997, epsilon=1e-5,
        dtype=x.dtype)(x)


def _conv_fixed_padding(x, filters, kernel_size, strides, name=None,
                        dtype=None):
  """Strided convs use explicit symmetric padding (resnet fixed_padding)."""
  if strides > 1:
    pad_total = kernel_size - 1
    pad_beg = pad_total // 2
    pad_end = pad_total - pad_beg
    x = jnp.pad(x, ((0, 0), (pad_beg, pad_end), (pad_beg, pad_end), (0, 0)))
    padding = 'VALID'
  else:
    padding = 'SAME'
  return nn.Conv(
      features=filters,
      kernel_size=(kernel_size, kernel_size),
      strides=(strides, strides),
      padding=padding,
      use_bias=False,
      dtype=dtype,
      kernel_init=nn.initializers.variance_scaling(
          2.0, 'fan_out', 'truncated_normal'),
      name=name)(x)


class _Block(nn.Module):
  """One residual block, v1 or v2, basic or bottleneck, FiLM-aware."""

  filters: int
  strides: int
  bottleneck: bool
  version: int
  project_shortcut: bool
  # Activation/compute dtype (bfloat16 on TPU); params stay float32 via
  # flax's param_dtype default, and _BatchNorm statistics are computed in
  # float32 internally by flax regardless of this dtype.
  dtype: Optional[Any] = None

  @nn.compact
  def __call__(self, x, film_gamma_beta, train: bool):
    shortcut = x
    out_filters = self.filters * (4 if self.bottleneck else 1)
    conv = functools.partial(_conv_fixed_padding, dtype=self.dtype)

    if self.version == 2:
      # v2: pre-activation; projection taken from the pre-activated input.
      pre = _BatchNorm()(x, train)
      pre = nn.relu(pre)
      if self.project_shortcut:
        shortcut = conv(pre, out_filters, 1, self.strides, name='proj')
      net = pre
      if self.bottleneck:
        net = conv(net, self.filters, 1, 1, name='conv1')
        net = nn.relu(_BatchNorm()(net, train))
        net = conv(net, self.filters, 3, self.strides, name='conv2')
        net = nn.relu(_BatchNorm()(net, train))
        net = conv(net, out_filters, 1, 1, name='conv3')
      else:
        net = conv(net, self.filters, 3, self.strides, name='conv1')
        net = nn.relu(_BatchNorm()(net, train))
        net = conv(net, out_filters, 3, 1, name='conv2')
      # FiLM on the block output before the residual add
      # (film_resnet_model.py:219-222, applied pre-shortcut in v2).
      net = apply_film(net, film_gamma_beta)
      return net + shortcut

    # v1: post-activation.
    if self.project_shortcut:
      shortcut = conv(x, out_filters, 1, self.strides, name='proj')
      shortcut = _BatchNorm()(shortcut, train)
    net = x
    if self.bottleneck:
      net = conv(net, self.filters, 1, 1, name='conv1')
      net = nn.relu(_BatchNorm()(net, train))
      net = conv(net, self.filters, 3, self.strides, name='conv2')
      net = nn.relu(_BatchNorm()(net, train))
      net = conv(net, out_filters, 1, 1, name='conv3')
      net = _BatchNorm()(net, train)
    else:
      net = conv(net, self.filters, 3, self.strides, name='conv1')
      net = nn.relu(_BatchNorm()(net, train))
      net = conv(net, out_filters, 3, 1, name='conv2')
      net = _BatchNorm()(net, train)
    # FiLM before the final ReLU (film_resnet_model.py:166-173).
    net = apply_film(net, film_gamma_beta)
    return nn.relu(net + shortcut)


class ResNet(nn.Module):
  """ResNet v1/v2 with optional FiLM conditioning per block.

  ``__call__(images, film_gamma_betas=None, train=False)`` returns
  ``(logits_or_features, endpoints)`` where endpoints mirrors
  ``resnet_endpoints`` (resnet.py:86-100): ``initial_conv``,
  ``initial_max_pool``, ``block_layer{1..4}``, ``pre_final_pool``,
  ``final_reduce_mean``, ``final_dense``.

  ``film_gamma_betas[i][j]`` conditions block j of block-layer i with a
  [B, 2*C_out] tensor (or None) — the `linear_film_generator` layout.
  """

  resnet_size: int = 50
  num_classes: Optional[int] = None  # None → return pooled features
  num_filters: int = 64
  version: int = 2
  first_pool: bool = True
  include_initial_layers: bool = True
  # Activation/compute dtype: bfloat16 on TPU keeps the convs on the MXU's
  # native input dtype (params stay float32; flax BatchNorm computes its
  # statistics in float32 internally). None → follow input/param promotion
  # (float32 params ⇒ float32 compute).
  dtype: Optional[Any] = None
  # Activation remat around each residual block (layers/remat.py):
  # 'conv_towers' / 'full' recompute block activations in the backward
  # pass instead of keeping all of them live — same params, same values,
  # less HBM. 'none' is the historical behavior.
  remat_policy: str = 'none'
  # Pallas kernel routing (ops/_pallas_dispatch.py): 'pool'/'pool_conv'
  # send the initial 3×3/s2 max pool — the grasp2vec roofline's 2.7–3.0×
  # select-and-scatter backward rows — through the argmax-emitting fused
  # kernel (ops/pool.py). Size-gated, stock-XLA fallback off-TPU,
  # bitwise-identical values and gradients either way.
  kernel_policy: str = 'none'

  @nn.compact
  def __call__(self,
               images: jnp.ndarray,
               film_gamma_betas: Optional[Sequence[Sequence[Any]]] = None,
               train: bool = False) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    block_sizes = BLOCK_SIZES[self.resnet_size]
    bottleneck = self.resnet_size >= _BOTTLENECK_MIN_SIZE
    if film_gamma_betas is None:
      film_gamma_betas = [[None] * n for n in block_sizes]
    # `train` (arg 3, counting self) drives python control flow inside
    # the block, so it must stay static under jax.checkpoint.
    block_cls = remat_module(_Block, self.remat_policy, static_argnums=(3,))
    endpoints: Dict[str, Any] = {}

    net = images if self.dtype is None else images.astype(self.dtype)
    if self.include_initial_layers:
      net = _conv_fixed_padding(net, self.num_filters, 7, 2,
                                name='initial_conv', dtype=self.dtype)
      if self.version == 1:
        net = nn.relu(_BatchNorm()(net, train))
      endpoints['initial_conv'] = net
      if self.first_pool:
        # Symmetric (1, 1) pool padding as EXPLICIT reduce_window
        # padding, not a materialized -inf jnp.pad: identical numerics
        # (reduce_window's init value is -inf, and post-conv activations
        # never tie with it), but the padded copy of the largest
        # activation in the network never exists — on a v5e the pad
        # fusion alone was 1.38 ms/step of grasp2vec (460 MB at
        # [48, 236, 236, 64]). kernel_policy routes the same pool
        # through the Pallas argmax kernel (overlapping 3×3/s2 windows;
        # the backward accumulates in XLA's window order — bitwise).
        pool_fn = (pool_ops.max_pool if pallas_dispatch.policy_enables_pool(
            self.kernel_policy) else nn.max_pool)
        net = pool_fn(net, (3, 3), strides=(2, 2),
                      padding=((1, 1), (1, 1)))
      endpoints['initial_max_pool'] = net

    for i, num_blocks in enumerate(block_sizes):
      filters = self.num_filters * (2**i)
      strides = 1 if i == 0 else 2
      for j in range(num_blocks):
        net = block_cls(
            filters=filters,
            strides=strides if j == 0 else 1,
            bottleneck=bottleneck,
            version=self.version,
            project_shortcut=(j == 0),
            dtype=self.dtype,
            name=f'block_layer{i + 1}_block{j}')(
                net, film_gamma_betas[i][j], train)
      endpoints[f'block_layer{i + 1}'] = net

    if self.version == 2:
      net = nn.relu(_BatchNorm()(net, train))
    endpoints['pre_final_pool'] = net
    net = jnp.mean(net, axis=(1, 2))
    endpoints['final_reduce_mean'] = net
    if self.num_classes is not None:
      net = nn.Dense(self.num_classes, dtype=self.dtype, name='final_dense')(
          net)
      endpoints['final_dense'] = net
    return net, endpoints

  @property
  def block_sizes(self) -> List[int]:
    return BLOCK_SIZES[self.resnet_size]

  @property
  def filter_sizes(self) -> List[int]:
    mult = 4 if self.resnet_size >= _BOTTLENECK_MIN_SIZE else 1
    return [self.num_filters * (2**i) * mult for i in range(4)]


class LinearFilmGenerator(nn.Module):
  """Linear FiLM γ/β generator for every enabled block (resnet.py:103-149).

  Produces ``film_gamma_betas[i][j]`` of shape [B, 2*C_out_i].
  """

  block_sizes: Sequence[int]
  filter_sizes: Sequence[int]
  enabled_block_layers: Optional[Sequence[bool]] = None

  @nn.compact
  def __call__(self, embedding: jnp.ndarray) -> List[List[Any]]:
    if self.enabled_block_layers and (
        len(self.enabled_block_layers) != len(self.block_sizes)):
      raise ValueError(
          f'Got {len(self.enabled_block_layers)} bools for '
          f'enabled_block_layers, expected {len(self.block_sizes)}')
    film_gamma_betas: List[List[Any]] = []
    for i, num_blocks in enumerate(self.block_sizes):
      if self.enabled_block_layers and not self.enabled_block_layers[i]:
        film_gamma_betas.append([None] * num_blocks)
        continue
      film_output_size = num_blocks * self.filter_sizes[i] * 2
      flat = nn.Dense(film_output_size, name=f'film{i}')(embedding)
      film_gamma_betas.append(list(jnp.split(flat, num_blocks, axis=-1)))
    return film_gamma_betas


class FilmResNet(nn.Module):
  """ResNet whose blocks are conditioned on an embedding via FiLM.

  The capability of ``resnet_model(..., film_generator_fn=...)``
  (resnet.py:152-218): embedding → linear γ/β per block → conditioned
  ResNet forward.
  """

  resnet_size: int = 50
  num_classes: Optional[int] = None
  version: int = 2
  enabled_block_layers: Optional[Sequence[bool]] = None
  dtype: Optional[Any] = None
  remat_policy: str = 'none'
  kernel_policy: str = 'none'

  @nn.compact
  def __call__(self, images, embedding=None, train: bool = False):
    resnet = ResNet(
        resnet_size=self.resnet_size,
        num_classes=self.num_classes,
        version=self.version,
        dtype=self.dtype,
        remat_policy=self.remat_policy,
        kernel_policy=self.kernel_policy,
        name='resnet')
    film_gamma_betas = None
    if embedding is not None:
      film_gamma_betas = LinearFilmGenerator(
          block_sizes=tuple(BLOCK_SIZES[self.resnet_size]),
          filter_sizes=tuple(resnet.filter_sizes),
          enabled_block_layers=self.enabled_block_layers,
          name='film_generator')(embedding)
    return resnet(images, film_gamma_betas, train=train)


def resnet_model(images,
                 is_training: bool,
                 num_classes: Optional[int] = None,
                 resnet_size: int = 50,
                 **unused_kwargs):
  """Functional alias mirroring the reference builder's call shape."""
  del unused_kwargs
  model = ResNet(resnet_size=resnet_size, num_classes=num_classes)
  return model, model  # module; apply via .init/.apply in JAX style
