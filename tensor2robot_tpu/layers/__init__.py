"""NN layers: Flax re-designs of the reference layer library."""

from tensor2robot_tpu.layers.mdn import (
    GaussianMixture,
    MDNDecoder,
    MDNParams,
    gaussian_mixture_approximate_mode,
    get_mixture_distribution,
    mdn_nll_loss,
)
from tensor2robot_tpu.layers.remat import (
    REMAT_CONV_TOWERS,
    REMAT_FULL,
    REMAT_NONE,
    REMAT_POLICIES,
    checkpoint_policy,
    remat_method,
    remat_module,
    validate_remat_policy,
)
from tensor2robot_tpu.layers.resnet import (
    BLOCK_SIZES,
    FilmResNet,
    LinearFilmGenerator,
    ResNet,
    apply_film,
)
from tensor2robot_tpu.layers.snail import (
    AttentionBlock,
    CausalConv,
    DenseBlock,
    MultiHeadAttentionBlock,
    TCBlock,
    causally_masked_softmax,
)
from tensor2robot_tpu.layers.spatial_softmax import (
    BuildSpatialSoftmax,
    spatial_softmax,
)
from tensor2robot_tpu.layers.tec import (
    EmbedConditionImages,
    EmbedFullstate,
    ReduceTemporalEmbeddings,
    compute_embedding_contrastive_loss,
    contrastive_loss,
)
from tensor2robot_tpu.layers.vision_layers import (
    FILMParams,
    ImageFeaturesToPoseModel,
    ImagesToFeaturesModel,
    ImagesToFeaturesModelHighRes,
    film_modulation,
    film_params_size,
)
