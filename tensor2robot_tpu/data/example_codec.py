"""Spec-driven tf.Example / SequenceExample codec.

TPU-native re-design of the reference's auto-generated parser
(``/root/reference/utils/tfdata.py:254-524`` and
``utils/tensorspec_utils.py:1553-1624``): from a spec structure alone we
generate (a) the tf.io feature map, (b) a batched parse function, and (c) the
inverse encoder used by replay writers and tests.

This module is the only place TensorFlow tensors touch specs — it runs on
host CPUs inside tf.data; devices only ever see the resulting numpy batches.

Parsing semantics preserved from the reference:

* features are addressed by spec *name* on disk and re-keyed to spec *paths*
  in the output (the same parsed tensor may serve several paths);
* bfloat16-declared specs are parsed as float32 and cast back after parsing;
* specs with ``data_format`` JPEG/PNG are parsed as strings then decoded,
  with empty strings decoded as all-zero images, including fixed-length lists
  of images (leading shape dims) and batched decode;
* specs with ``varlen_default_value`` parse as VarLen, densify with that
  default, then pad-or-clip dim 0 to the spec shape;
* ``is_sequence`` specs parse from SequenceExamples and emit a ``<key>_length``
  int64 tensor alongside;
* multi-dataset parsing: each spec's ``dataset_key`` routes it to one of the
  zipped serialized-example streams.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from tensor2robot_tpu.specs import (SpecStruct, TensorSpec, algebra, bfloat16)


def _tf():
  import tensorflow as tf  # local import: host-only dependency
  return tf


SUPPORTED_PIXEL_DTYPES = (np.uint8, np.uint16)
# tf.Example can natively hold only these (reference tfdata.py:328-331).
_PARSEABLE_DTYPES = ('float32', 'int64', 'string', 'bfloat16')


def is_encoded_image_spec(spec: TensorSpec) -> bool:
  return spec.is_encoded_image


def _parse_dtype(spec: TensorSpec):
  """The dtype handed to the tf parser for a given spec."""
  tf = _tf()
  if spec.is_encoded_image:
    return tf.string
  if spec.dtype == bfloat16:
    return tf.float32
  name = spec.dtype.name
  if name not in _PARSEABLE_DTYPES:
    raise ValueError(
        f'Feature spec dtype {name!r} cannot be parsed from tf.Example; '
        f'supported: {_PARSEABLE_DTYPES} (spec: {spec})')
  return tf.dtypes.as_dtype(name)


def spec_to_tf_feature(spec: TensorSpec, decode_images: bool = True):
  """tf.io.*Feature for one spec (reference _get_feature semantics)."""
  tf = _tf()
  dtype = _parse_dtype(spec)
  if spec.is_sequence:
    if spec.is_encoded_image and decode_images:
      return tf.io.FixedLenSequenceFeature((), tf.string)
    return tf.io.FixedLenSequenceFeature(spec.shape, dtype)
  if spec.varlen_default_value is not None:
    return tf.io.VarLenFeature(
        tf.string if (spec.is_encoded_image and decode_images) else dtype)
  if spec.is_encoded_image and decode_images:
    if len(spec.shape) > 3:
      # A fixed-length list of encoded images.
      return tf.io.FixedLenFeature((spec.shape[0],), tf.string)
    return tf.io.FixedLenFeature((), tf.string)
  return tf.io.FixedLenFeature(spec.shape, dtype)


def spec_struct_to_feature_maps(
    spec_struct, decode_images: bool = True
) -> Tuple[dict, dict, 'collections.OrderedDict[str, TensorSpec]']:
  """Builds (context_features, sequence_features, name->spec) maps."""
  by_name = algebra.spec_names(spec_struct)
  context, sequence = {}, {}
  for name, spec in by_name.items():
    feature = spec_to_tf_feature(spec, decode_images)
    if spec.is_sequence:
      sequence[name] = feature
    else:
      context[name] = feature
  return context, sequence, by_name


def _decode_image_tensor(raw_bytes, spec: TensorSpec):
  """Decodes (possibly nested-batched) JPEG/PNG strings to spec shape."""
  tf = _tf()
  if len(spec.shape) < 3:
    raise ValueError(
        f'Encoded-image spec must be at least (h, w, c), got {spec}')
  if spec.dtype.name not in ('uint8', 'uint16'):
    raise ValueError(
        f'Encoded-image spec must be uint8 or uint16, got {spec}')
  single_dims = tuple(spec.shape[-3:])
  channels = single_dims[2]
  if channels not in (1, 3):
    raise ValueError(f'Image channels must be 1 or 3, got {spec}')
  dtype = tf.dtypes.as_dtype(spec.dtype.name)

  batch_dims = tf.shape(raw_bytes)
  flat = tf.reshape(raw_bytes, [-1])

  def decode_one(image_bytes):
    image = tf.cond(
        tf.equal(image_bytes, ''),
        lambda: tf.zeros(single_dims, dtype=dtype),
        lambda: tf.io.decode_image(image_bytes, channels=channels,
                                   dtype=dtype))
    image.set_shape(single_dims)
    return image

  images = tf.map_fn(decode_one, flat, fn_output_signature=dtype)
  return tf.reshape(images, tf.concat([batch_dims, single_dims], axis=0))


def make_parse_fn(feature_spec,
                  label_spec=None,
                  decode_images: bool = True):
  """Builds a batched parse fn: serialized examples -> (features[, labels]).

  The returned callable accepts either a string tensor of serialized examples
  or a dict ``{dataset_key: string tensor}`` for multi-dataset pipelines, and
  returns SpecStructs of tf tensors keyed by spec *paths*, validated and
  packed against the declared specs.
  """
  tf = _tf()

  flat_feature_spec = SpecStruct(
      sorted(algebra.flatten_spec_structure(feature_spec).items()))
  flat_label_spec = None
  if label_spec is not None:
    flat_label_spec = SpecStruct(
        sorted(algebra.flatten_spec_structure(label_spec).items()))

  def parse_single_dataset(serialized, dataset_key):
    """Parses one serialized stream; returns name-keyed tensors + specs."""
    specs_for_dataset = SpecStruct()
    for flat in (flat_feature_spec, flat_label_spec):
      if flat is None:
        continue
      for key, spec in algebra.filter_spec_structure_by_dataset(
          flat, dataset_key).items():
        if spec.name is None:
          # Resolve the on-disk name from the original path *before*
          # prefixing, so unnamed specs keep their natural feature key.
          spec = TensorSpec.from_spec(spec, name=key.split('/')[-1])
        specs_for_dataset[('l_' if flat is flat_label_spec else 'f_') +
                          key] = spec
    context, sequence, by_name = spec_struct_to_feature_maps(
        specs_for_dataset, decode_images)

    if sequence:
      parsed_context, parsed_sequence, lengths = tf.io.parse_sequence_example(
          serialized, context_features=context, sequence_features=sequence)
      parsed = dict(parsed_context)
      parsed.update(parsed_sequence)
      for name, length in lengths.items():
        parsed[name + '_length'] = length
        by_name[name + '_length'] = TensorSpec(
            (), np.int64, name=name + '_length')
    else:
      parsed = tf.io.parse_example(serialized, context)

    # Densify VarLen features (images default to '', data to the declared
    # default) and pad/clip dim 1 (dim 0 is the batch) to the spec shape.
    for name, spec in by_name.items():
      if spec.varlen_default_value is None or name not in parsed:
        continue
      value = parsed[name]
      if isinstance(value, tf.sparse.SparseTensor):
        default = ('' if spec.is_encoded_image else tf.cast(
            tf.constant(spec.varlen_default_value),
            _parse_dtype(spec)))
        value = tf.sparse.to_dense(value, default_value=default)
      parsed[name] = value

    # Decode images.
    if decode_images:
      for name, spec in by_name.items():
        if spec.is_encoded_image and name in parsed:
          parsed[name] = _decode_image_tensor(parsed[name], spec)

    # Pad/clip varlen features along the per-example dim.
    for name, spec in by_name.items():
      if spec.varlen_default_value is None or name not in parsed:
        continue
      target = spec.shape[0]
      if target is None:
        continue
      value = parsed[name]
      trailing_dims = [int(d) for d in spec.shape[1:]]
      if trailing_dims and not spec.is_encoded_image:
        # VarLen parses as [batch, total_values]; restore trailing dims.
        value = tf.reshape(
            value, tf.concat([[tf.shape(value)[0], -1],
                              tf.constant(trailing_dims, tf.int32)], axis=0))
      length = tf.shape(value)[1]
      pad_value = tf.constant(
          0 if spec.is_encoded_image else spec.varlen_default_value,
          dtype=value.dtype)
      trailing = trailing_dims
      padding_shape = tf.concat(
          [[tf.shape(value)[0], tf.maximum(target - length, 0)],
           tf.constant(trailing, dtype=tf.int32)], axis=0)
      padded = tf.concat(
          [value[:, :target], tf.fill(padding_shape, pad_value)], axis=1)
      padded.set_shape([None, target] + trailing)
      parsed[name] = padded

    # bfloat16-declared features were parsed as float32; cast back so the
    # batch conforms to the declared spec (device transfer is then free).
    for name, spec in by_name.items():
      if spec.dtype == bfloat16 and name in parsed:
        parsed[name] = tf.cast(parsed[name], tf.bfloat16)
    return parsed

  def parse_fn(serialized):
    if isinstance(serialized, dict):
      streams = serialized
    else:
      streams = {'': serialized}
    parsed_by_name = {}
    for dataset_key, stream in streams.items():
      for name, value in parse_single_dataset(stream, dataset_key).items():
        parsed_by_name[dataset_key + name] = value

    def pack(flat_spec):
      with_lengths = algebra.add_sequence_length_specs(flat_spec)
      tensors = SpecStruct()
      for key, spec in with_lengths.items():
        name = spec.dataset_key + (spec.name or key.split('/')[-1])
        if name in parsed_by_name:
          tensors[key] = parsed_by_name[name]
        elif not spec.is_optional and spec.name is not None and (
            not key.endswith('_length')):
          raise ValueError(f'Parsed data is missing required {key!r} '
                           f'({spec}).')
      return algebra.pack_flat_sequence_to_spec_structure(
          with_lengths, tensors)

    features = pack(flat_feature_spec)
    if flat_label_spec is not None:
      return features, pack(flat_label_spec)
    return features

  return parse_fn


# ----------------------------------------------------------------- encoding


def _encode_image_bytes(array: np.ndarray, data_format: str) -> bytes:
  import io

  from PIL import Image

  array = np.asarray(array)
  if array.ndim == 3 and array.shape[2] == 1:
    array = array[:, :, 0]
  image = Image.fromarray(array)
  buf = io.BytesIO()
  image.save(buf, format=data_format)
  return buf.getvalue()


def _feature_for_value(spec: TensorSpec, value: np.ndarray):
  """One tf.train.Feature for a single (non-sequence-step) value."""
  tf = _tf()
  if spec.is_encoded_image:
    arrays = np.asarray(value)
    if arrays.ndim == len(spec.shape):  # single image or list of images
      if len(spec.shape) > 3:
        images = [arrays[i] for i in range(arrays.shape[0])]
      else:
        images = [arrays]
    else:
      images = [arrays]
    encoded = [_encode_image_bytes(img, spec.data_format) for img in images]
    return tf.train.Feature(bytes_list=tf.train.BytesList(value=encoded))
  flat = np.asarray(value).reshape(-1)
  if spec.dtype.name in ('float32', 'float64', 'bfloat16'):
    return tf.train.Feature(
        float_list=tf.train.FloatList(value=flat.astype(np.float32)))
  if np.issubdtype(spec.dtype, np.integer) or spec.dtype == np.bool_:
    return tf.train.Feature(
        int64_list=tf.train.Int64List(value=flat.astype(np.int64)))
  if spec.dtype.name in ('object', 'str', 'bytes') or flat.dtype.kind in 'SU':
    return tf.train.Feature(bytes_list=tf.train.BytesList(
        value=[v.encode() if isinstance(v, str) else bytes(v) for v in flat]))
  raise ValueError(f'Cannot encode dtype {spec.dtype} for {spec}')


def encode_example(spec_struct, numpy_struct) -> bytes:
  """Encodes ONE example (no batch dim) to a serialized tf.(Sequence)Example.

  Sequence specs (is_sequence=True) expect a leading time dim in the value and
  are written as SequenceExample feature lists; everything else goes into
  context features.
  """
  tf = _tf()
  flat_spec = algebra.flatten_spec_structure(spec_struct)
  flat_np = algebra.flatten_spec_structure(numpy_struct)
  context = {}
  feature_lists = {}
  for key, raw_spec in flat_spec.items():
    spec = TensorSpec.to_spec(raw_spec)
    if key not in flat_np:
      if spec.is_optional:
        continue
      raise ValueError(f'Missing value for required spec {key!r}.')
    name = spec.name or key.split('/')[-1]
    value = np.asarray(flat_np[key])
    if spec.is_sequence:
      steps = [
          _feature_for_value(TensorSpec.from_spec(spec, is_sequence=False),
                             value[t]) for t in range(value.shape[0])
      ]
      feature_lists[name] = tf.train.FeatureList(feature=steps)
    else:
      context[name] = _feature_for_value(spec, value)
  if feature_lists:
    example = tf.train.SequenceExample(
        context=tf.train.Features(feature=context),
        feature_lists=tf.train.FeatureLists(feature_list=feature_lists))
  else:
    example = tf.train.Example(features=tf.train.Features(feature=context))
  return example.SerializeToString()
