"""Record-file handling: formats, file patterns, and writers.

Capability-equivalent of the reference's format registry / pattern utilities
(``/root/reference/utils/tfdata.py:34-191``) plus the replay writer
(``utils/writer.py:31-70``). TFRecord is the default interchange format; the
registry is open so new formats can be plugged in.
"""

from __future__ import annotations

import glob as glob_lib
import os
from typing import (Callable, Dict, Iterator, List, Optional, Sequence,
                    Tuple, Union)


def _tf():
  import tensorflow as tf
  return tf


def _tfrecord_dataset(filenames):
  return _tf().data.TFRecordDataset(filenames)


DATA_FORMATS = {
    'tfrecord': _tfrecord_dataset,
}


def register_data_format(name: str, dataset_factory: Callable) -> None:
  DATA_FORMATS[name] = dataset_factory


def infer_data_format(file_patterns: str) -> str:
  """Infers the data format from a 'format:pattern' or bare pattern string."""
  if ':' in file_patterns:
    prefix = file_patterns.split(':', 1)[0]
    if prefix in DATA_FORMATS:
      return prefix
  for data_format in DATA_FORMATS:
    if data_format in os.path.basename(file_patterns):
      return data_format
  raise ValueError(
      f'Cannot infer data format from {file_patterns!r}; known formats: '
      f'{sorted(DATA_FORMATS)}. Prefix the pattern with "<format>:".')


def get_data_format_and_filenames(
    file_patterns: Union[str, Sequence[str]]) -> Tuple[str, List[str]]:
  """Resolves comma-separated glob patterns to (format, filenames)."""
  if isinstance(file_patterns, str):
    patterns = [p for p in file_patterns.split(',') if p]
  else:
    patterns = list(file_patterns)
  data_format = None
  filenames: List[str] = []
  for pattern in patterns:
    if ':' in pattern and pattern.split(':', 1)[0] in DATA_FORMATS:
      fmt, pattern = pattern.split(':', 1)
    else:
      fmt = infer_data_format(pattern)
    if data_format is None:
      data_format = fmt
    elif data_format != fmt:
      raise ValueError(
          f'Mixed data formats in patterns: {data_format} vs {fmt}')
    matches = sorted(glob_lib.glob(pattern))
    filenames.extend(matches if matches else [pattern])
  if data_format is None:
    raise ValueError(f'No file patterns provided: {file_patterns!r}')
  return data_format, filenames


def verify_tfrecord_file(path: str) -> bool:
  """Whether every record of a TFRecord file reads back intact.

  The budget-attribution probe for parse paths whose corruption errors
  do not name the failing file (tf.data's ``DataLossError`` says only
  "corrupted record at <offset>"): walking the CRC32C framing locates
  the rotten shard. Prefers the native reader (GB/s, no TF); falls back
  to ``TFRecordDataset``. Missing/unopenable files count as corrupt.
  """
  from tensor2robot_tpu.data import native_io

  if '://' not in path and native_io.available():
    try:
      with native_io.NativeRecordReader(path) as reader:
        for _ in reader:
          pass
      return True
    except (IOError, OSError, ValueError):
      return False
  tf = _tf()
  try:
    for _ in _tfrecord_dataset([path]):
      pass
    return True
  except tf.errors.OpError:
    return False


def open_at(path: str, record_ordinal: int,
            index: Optional['shard_index.ShardIndex'] = None,
            verify_crc: bool = True) -> Iterator[bytes]:
  """Sequential records of ``path`` starting at ``record_ordinal``.

  The O(1) deep-position entry point: the shard index sidecar
  (``data/shard_index.py``) maps the ordinal to a byte offset and the
  reader seeks there — no records before the position are read. Prefers
  the native reader; falls back to the pure-Python framing walker.
  ``index`` (optional) skips re-loading the sidecar; without it the
  sidecar is loaded AND validated against the shard (raises
  ``shard_index.StaleIndexError`` on mismatch — callers fall back to the
  O(position) replay path, never a wrong stream).
  """
  from tensor2robot_tpu.data import native_io, shard_index

  if index is None:
    index = shard_index.load_index(path)
  if record_ordinal == index.record_count:
    return iter(())
  offset = index.offset_of(record_ordinal)
  if '://' not in path and native_io.available():
    return native_io.iter_records_from(path, offset, verify_crc)
  return shard_index.iter_records_from(path, offset, verify_crc)


def read_records_at(path: str, ordinals: Sequence[int],
                    index: Optional['shard_index.ShardIndex'] = None
                    ) -> Dict[int, bytes]:
  """Indexed point reads: ``{ordinal: payload}`` via one open + seeks.

  The shuffle-buffer refill primitive for constant-time resume
  (``data/seek_resume.plan_resume``): ≤ buffer_size records fetched by
  offset, independent of their depth in the shard.
  """
  from tensor2robot_tpu.data import native_io, shard_index

  if index is None:
    index = shard_index.load_index(path)
  out: Dict[int, bytes] = {}
  if '://' not in path and native_io.available():
    with native_io.NativeRecordReader(path) as reader:
      for ordinal in sorted(set(ordinals)):
        reader.seek(index.offset_of(ordinal))
        record = reader.read_next()
        if record is None:
          raise IOError(
              f'{path}: unexpected EOF at indexed record {ordinal}')
        out[ordinal] = record
    return out
  for ordinal in sorted(set(ordinals)):
    record = next(
        shard_index.iter_records_from(path, index.offset_of(ordinal)),
        None)
    if record is None:
      raise IOError(f'{path}: unexpected EOF at indexed record {ordinal}')
    out[ordinal] = record
  return out


class RecordWriter:
  """Sharded TFRecord writer for serialized examples (replay/test data).

  Prefers the native C++ writer (``data/native_io.py`` — same wire
  format, no TF dependency); falls back to ``tf.io.TFRecordWriter`` when
  the native library can't be built.
  """

  def __init__(self, path: str, shard: Optional[int] = None,
               num_shards: Optional[int] = None):
    if shard is not None and num_shards:
      path = f'{path}-{shard:05d}-of-{num_shards:05d}'
    self._path = path
    from tensor2robot_tpu.data import native_io
    # The native writer is plain-fs only; remote filesystem schemes
    # (gs://, s3://, hdfs://, cns paths, …) go through TF's filesystem
    # layer.
    local = '://' not in path
    if local:
      os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    if local and native_io.available():
      self._writer = native_io.NativeRecordWriter(path)
    else:
      self._writer = _tf().io.TFRecordWriter(path)

  @property
  def path(self) -> str:
    return self._path

  def write(self, serialized: bytes) -> None:
    self._writer.write(serialized)

  def flush(self) -> None:
    self._writer.flush()

  def close(self) -> None:
    self._writer.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


def write_examples(path: str, serialized_examples: Sequence[bytes]) -> str:
  """Writes serialized examples to one tfrecord file; returns the path."""
  with RecordWriter(path) as writer:
    for example in serialized_examples:
      writer.write(example)
  return path
