"""Seekable shard index: per-record byte offsets for TFRecord shards.

The checkpointable-iterator restore used to be a fast-forward replay that
is O(position) — a job preempted 100k records into an epoch re-read all
100k records before its first step (ROADMAP direction 5). The TFRecord
wire format already fixes every record's byte offset (each record
occupies ``12 + payload + 4`` bytes), so a compact sidecar turns
deep-position resume into a seek:

    <shard>.idx = magic | record_count | offsets[count] | footer

All integers little-endian. The footer pins the SHARD the index
describes — size plus CRC32 samples of the shard's head and tail — so a
rewritten, truncated, or appended shard makes its index STALE and
resume degrades loudly to the legacy replay path instead of serving a
wrong stream. Validation is O(1) in the shard size (one stat + two
bounded reads), which is what keeps deep-position restore constant-time.

Stdlib-only by design (``tools/index_shards.py`` builds/verifies
sidecars offline on machines with no numpy/jax/TF), same dependency
discipline as ``tools/inspect_checkpoint.py``. The observability
registry (itself stdlib-only) is the one internal import.
"""

from __future__ import annotations

import logging
import os
import struct
import zlib
from typing import BinaryIO, Iterator, List, Optional, Sequence, Tuple

from tensor2robot_tpu.observability import metrics as metrics_lib

INDEX_SUFFIX = '.idx'
_MAGIC = b'T2RIDX01'
_FOOTER = struct.Struct('<QII')  # shard_size, head_crc, tail_crc
_COUNT = struct.Struct('<Q')
_INDEX_CRC = struct.Struct('<I')
# Head/tail CRC sample size: big enough that an in-place rewrite is
# caught with near certainty, small enough that validation stays O(1).
_CRC_SAMPLE_BYTES = 65536

_HEADER_BYTES = 12  # u64 length + u32 masked_crc(length)
_FOOTER_BYTES = 4   # u32 masked_crc(payload)


class IndexError_(Exception):
  """Raised for malformed shards/indexes (name avoids builtins clash)."""


class StaleIndexError(IndexError_):
  """The shard changed since its index was written."""


class ShardIndex:
  """Parsed sidecar: per-record byte offsets plus the shard fingerprint."""

  __slots__ = ('path', 'offsets', 'shard_size', 'head_crc', 'tail_crc')

  def __init__(self, path: str, offsets: Sequence[int], shard_size: int,
               head_crc: int, tail_crc: int):
    self.path = path
    self.offsets = list(offsets)
    self.shard_size = int(shard_size)
    self.head_crc = int(head_crc)
    self.tail_crc = int(tail_crc)

  @property
  def record_count(self) -> int:
    return len(self.offsets)

  def offset_of(self, ordinal: int) -> int:
    """Byte offset of record ``ordinal``'s header start."""
    if not 0 <= ordinal < len(self.offsets):
      raise IndexError_(
          f'record ordinal {ordinal} out of range for {self.path!r} '
          f'({len(self.offsets)} records)')
    return self.offsets[ordinal]


def index_path_for(shard_path: str) -> str:
  return shard_path + INDEX_SUFFIX


def _sample_crcs(f: BinaryIO, size: int) -> Tuple[int, int]:
  """CRC32 of the shard's first and last ``_CRC_SAMPLE_BYTES`` bytes."""
  n = min(size, _CRC_SAMPLE_BYTES)
  f.seek(0)
  head = zlib.crc32(f.read(n))
  f.seek(max(0, size - n))
  tail = zlib.crc32(f.read(n))
  return head & 0xffffffff, tail & 0xffffffff


def scan_record_offsets(shard_path: str) -> Tuple[List[int], int]:
  """Walks the TFRecord framing, returning (offsets, shard_size).

  Header-only walk: reads each 12-byte length header and SEEKS over the
  payload, so building an index costs one small read per record, not one
  pass over the bytes. Raises :class:`IndexError_` on truncation or an
  implausible length (the CRC fields are not verified here —
  ``tools/index_shards.py --verify`` and the readers do that).
  """
  offsets: List[int] = []
  with open(shard_path, 'rb') as f:
    size = os.fstat(f.fileno()).st_size
    pos = 0
    while pos < size:
      header = f.read(_HEADER_BYTES)
      if not header:
        break
      if len(header) != _HEADER_BYTES:
        raise IndexError_(
            f'{shard_path}: truncated record header at offset {pos}')
      (length,) = struct.unpack('<Q', header[:8])
      if length > (1 << 30):
        raise IndexError_(
            f'{shard_path}: implausible record length {length} at '
            f'offset {pos} (corrupt framing?)')
      end = pos + _HEADER_BYTES + length + _FOOTER_BYTES
      if end > size:
        raise IndexError_(
            f'{shard_path}: truncated record payload/footer at offset '
            f'{pos} (record ends at {end}, shard is {size} bytes)')
      offsets.append(pos)
      f.seek(end)
      pos = end
  return offsets, size


def build_index(shard_path: str) -> ShardIndex:
  """Scans a shard and returns its in-memory index (no sidecar write)."""
  offsets, size = scan_record_offsets(shard_path)
  with open(shard_path, 'rb') as f:
    head_crc, tail_crc = _sample_crcs(f, size)
  return ShardIndex(shard_path, offsets, size, head_crc, tail_crc)


def serialize_index(index: ShardIndex) -> bytes:
  body = b''.join([
      _MAGIC,
      _COUNT.pack(index.record_count),
      struct.pack(f'<{index.record_count}Q', *index.offsets),
      _FOOTER.pack(index.shard_size, index.head_crc, index.tail_crc),
  ])
  return body + _INDEX_CRC.pack(zlib.crc32(body) & 0xffffffff)


def parse_index(shard_path: str, blob: bytes) -> ShardIndex:
  """Parses a sidecar blob; raises :class:`IndexError_` when malformed."""
  min_len = len(_MAGIC) + _COUNT.size + _FOOTER.size + _INDEX_CRC.size
  if len(blob) < min_len or not blob.startswith(_MAGIC):
    raise IndexError_(f'{index_path_for(shard_path)}: not a shard index')
  body, (crc,) = blob[:-_INDEX_CRC.size], _INDEX_CRC.unpack(
      blob[-_INDEX_CRC.size:])
  if zlib.crc32(body) & 0xffffffff != crc:
    raise IndexError_(
        f'{index_path_for(shard_path)}: index checksum mismatch '
        f'(truncated or corrupt sidecar)')
  (count,) = _COUNT.unpack_from(body, len(_MAGIC))
  offsets_off = len(_MAGIC) + _COUNT.size
  expect = offsets_off + 8 * count + _FOOTER.size
  if len(body) != expect:
    raise IndexError_(
        f'{index_path_for(shard_path)}: index length {len(body)} does '
        f'not match record count {count}')
  offsets = list(struct.unpack_from(f'<{count}Q', body, offsets_off))
  shard_size, head_crc, tail_crc = _FOOTER.unpack_from(
      body, offsets_off + 8 * count)
  return ShardIndex(shard_path, offsets, shard_size, head_crc, tail_crc)


def write_index(shard_path: str, index: Optional[ShardIndex] = None,
                index_path: Optional[str] = None) -> str:
  """Builds (if needed) and atomically writes the sidecar; returns path."""
  index = index or build_index(shard_path)
  index_path = index_path or index_path_for(shard_path)
  tmp = index_path + f'.tmp{os.getpid()}'
  with open(tmp, 'wb') as f:
    f.write(serialize_index(index))
  os.replace(tmp, index_path)  # atomic: readers never see partials
  return index_path


def validate_index(index: ShardIndex, shard_path: str) -> None:
  """Raises :class:`StaleIndexError` unless the shard still matches.

  O(1) in the shard size: one stat plus two bounded sample reads. The
  staleness rule — size, head-CRC, and tail-CRC must all match — catches
  truncation, appends, and rewrites; it is deliberately NOT a full-file
  CRC, which would make deep resume O(file) again (the offline
  ``tools/index_shards.py --verify`` does the full framing walk).
  """
  try:
    size = os.path.getsize(shard_path)
  except OSError as e:
    raise StaleIndexError(f'{shard_path}: unreadable ({e})') from e
  if size != index.shard_size:
    raise StaleIndexError(
        f'{shard_path}: size {size} != indexed {index.shard_size} '
        f'(shard truncated/appended since indexing)')
  with open(shard_path, 'rb') as f:
    head_crc, tail_crc = _sample_crcs(f, size)
  if (head_crc, tail_crc) != (index.head_crc, index.tail_crc):
    raise StaleIndexError(
        f'{shard_path}: head/tail checksum mismatch (shard rewritten '
        f'since indexing)')


def load_index(shard_path: str, validate: bool = True) -> ShardIndex:
  """Loads + validates the sidecar. Raises ``FileNotFoundError`` when the
  sidecar is missing, :class:`IndexError_` when unparseable,
  :class:`StaleIndexError` when the shard changed."""
  with open(index_path_for(shard_path), 'rb') as f:
    blob = f.read()
  index = parse_index(shard_path, blob)
  if validate:
    validate_index(index, shard_path)
  return index


def ensure_index(shard_path: str) -> ShardIndex:
  """Loads a valid sidecar or (re)builds it, writing best-effort.

  The opportunistic path: called when a resumable stream is created, so
  the first run over a corpus leaves sidecars behind and every later
  restore seeks. A read-only data directory only costs the write — the
  in-memory index still serves this process.
  """
  try:
    return load_index(shard_path)
  except FileNotFoundError:
    metrics_lib.counter('data/index/missing').inc()
  except StaleIndexError:
    metrics_lib.counter('data/index/stale').inc()
    logging.warning('Shard index for %r is stale; rebuilding.', shard_path)
  except IndexError_:
    metrics_lib.counter('data/index/corrupt').inc()
    logging.warning('Shard index for %r is corrupt; rebuilding.',
                    shard_path)
  index = build_index(shard_path)
  metrics_lib.counter('data/index/built').inc()
  try:
    write_index(shard_path, index)
  except OSError as e:
    logging.warning(
        'Could not write shard index sidecar for %r (%s); keeping the '
        'in-memory index for this process only.', shard_path, e)
  return index


def iter_records_from(shard_path: str, offset: int = 0,
                      verify_crc: bool = False) -> Iterator[bytes]:
  """Pure-Python TFRecord reader from a byte offset (native-lib-free).

  The fallback route for ``records.open_at`` when the C++ runtime is
  unavailable, and the reader ``tools/index_shards.py --verify`` uses.
  ``verify_crc`` checks the payload CRC32C via :func:`masked_crc32c`.
  """
  with open(shard_path, 'rb') as f:
    f.seek(offset)
    pos = offset
    while True:
      header = f.read(_HEADER_BYTES)
      if not header:
        return
      if len(header) != _HEADER_BYTES:
        raise IndexError_(
            f'{shard_path}: truncated record header at offset {pos}')
      (length,) = struct.unpack('<Q', header[:8])
      if length > (1 << 30):
        raise IndexError_(
            f'{shard_path}: implausible record length at offset {pos}')
      payload = f.read(length)
      footer = f.read(_FOOTER_BYTES)
      if len(payload) != length or len(footer) != _FOOTER_BYTES:
        raise IndexError_(
            f'{shard_path}: truncated record at offset {pos}')
      if verify_crc:
        (want,) = struct.unpack('<I', footer)
        if masked_crc32c(payload) != want:
          raise IndexError_(
              f'{shard_path}: payload crc mismatch at offset {pos}')
      pos += _HEADER_BYTES + length + _FOOTER_BYTES
      yield payload


# Pure-Python CRC32C (Castagnoli), table-driven — only used by the
# stdlib-only verify path; the hot readers verify in C++.
_CRC32C_TABLE: List[int] = []


def _crc32c_table() -> List[int]:
  if not _CRC32C_TABLE:
    poly = 0x82f63b78
    for i in range(256):
      crc = i
      for _ in range(8):
        crc = (crc >> 1) ^ (poly if crc & 1 else 0)
      _CRC32C_TABLE.append(crc)
  return _CRC32C_TABLE


def masked_crc32c(data: bytes) -> int:
  """TFRecord's masked CRC32C, matching ``native_io.masked_crc32c``."""
  table = _crc32c_table()
  crc = 0xffffffff
  for b in data:
    crc = (crc >> 8) ^ table[(crc ^ b) & 0xff]
  crc ^= 0xffffffff
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8 & 0xffffffff
