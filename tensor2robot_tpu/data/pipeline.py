"""Host-side input pipeline: files -> parsed, batched numpy SpecStructs.

TPU-native re-design of the reference's Estimator ``input_fn`` template
(``/root/reference/utils/tfdata.py:527-606``). Same stages — list_files →
parallel interleave → shuffle/repeat → batch(drop_remainder=True) → zip
multi-datasets → parse → prefetch — but the sink is a numpy iterator feeding
``jax.device_put`` instead of an in-graph Estimator: preprocessing that the
reference ran in ``dataset.map`` happens *on device inside the jitted step*
(see preprocessors/), so host CPUs only parse and decode.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, Optional, Union


from tensor2robot_tpu import modes
from tensor2robot_tpu.data import example_codec, records
from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.observability import tracing
from tensor2robot_tpu.specs import SpecStruct


def _tf():
  import tensorflow as tf
  return tf


def match_filename_in_error(exc: BaseException, filenames) -> Optional[str]:
  """The filename (from a KNOWN file set) an error message names, or None.

  Budget-source attribution for parse paths whose errors carry the
  failing file only in prose (tf.data's DataLossError does): a full-path
  substring match wins; a unique basename match covers messages that
  abbreviate paths. Ambiguity returns None — the budget's generic
  path-regex fallback is better than a wrong attribution.
  """
  import os as os_lib

  text = str(exc)
  if not text:
    return None
  for name in filenames:
    if name and name in text:
      return name
  by_base = [name for name in filenames
             if os_lib.path.basename(name) and
             os_lib.path.basename(name) in text]
  if len(by_base) == 1:
    return by_base[0]
  return None


def shard_filenames_for_process(filenames):
  """Per-host file sharding: each jax process reads a distinct slice.

  The multi-host feeding contract (reference: TPUEstimator's per-host
  ``input_fn``): with fewer files than processes the caller falls back to
  element-level sharding. No-op in single-process runs.
  """
  import jax

  process_count = jax.process_count()
  if process_count <= 1 or len(filenames) < process_count:
    return filenames, False
  return list(filenames)[jax.process_index()::process_count], True


def make_serialized_dataset(file_patterns: Union[str, Dict[str, str]],
                            batch_size: int,
                            is_training: bool,
                            shuffle_buffer_size: int = 1000,
                            parallel_shards: int = 10,
                            repeat: bool = True,
                            seed: Optional[int] = None,
                            shard_by_process: bool = True):
  """Batched serialized-example dataset; dict patterns -> zipped dict."""
  tf = _tf()
  if isinstance(file_patterns, dict):
    patterns_map = file_patterns
  else:
    patterns_map = {'': file_patterns}
  datasets = {}
  for dataset_key, patterns in patterns_map.items():
    data_format, filenames = records.get_data_format_and_filenames(patterns)
    sharded_by_file = False
    if shard_by_process:
      filenames, sharded_by_file = shard_filenames_for_process(filenames)
    element_shard = False
    if shard_by_process and not sharded_by_file:
      import jax

      element_shard = jax.process_count() > 1
    if element_shard:
      # Fewer files than processes: shard at the example level. The shard
      # must partition an IDENTICALLY-ORDERED stream on every host, so
      # read files sequentially and deterministically (shuffling AFTER
      # the shard restores randomness).
      files = tf.data.Dataset.from_tensor_slices(sorted(filenames))
      dataset = files.interleave(
          records.DATA_FORMATS[data_format],
          cycle_length=1,
          deterministic=True)
      dataset = dataset.shard(jax.process_count(), jax.process_index())
    else:
      files = tf.data.Dataset.list_files(
          filenames, shuffle=is_training, seed=seed)
      cycle_length = (
          min(parallel_shards, len(filenames)) if is_training else 1)
      dataset = files.interleave(
          records.DATA_FORMATS[data_format],
          cycle_length=cycle_length,
          num_parallel_calls=tf.data.AUTOTUNE,
          deterministic=not is_training)
    if is_training:
      dataset = dataset.shuffle(shuffle_buffer_size, seed=seed)
    if repeat:
      dataset = dataset.repeat()
    dataset = dataset.batch(batch_size, drop_remainder=True)
    datasets[dataset_key] = dataset
  if list(datasets) == ['']:
    return datasets['']
  return tf.data.Dataset.zip(datasets)


def make_dataset(file_patterns,
                 feature_spec,
                 label_spec=None,
                 mode: str = modes.ModeKeys.TRAIN,
                 batch_size: int = 32,
                 preprocess_fn: Optional[Callable] = None,
                 shuffle_buffer_size: int = 1000,
                 parallel_shards: int = 10,
                 num_parallel_calls: Optional[int] = None,
                 repeat: bool = True,
                 seed: Optional[int] = None):
  """Full parsed tf.data.Dataset of (features[, labels]) SpecStructs.

  ``preprocess_fn`` here is a *host-side* (tf) transform; device-side
  preprocessing belongs in the jitted step. Most models need none.
  """
  tf = _tf()
  dataset = make_serialized_dataset(
      file_patterns, batch_size,
      is_training=modes.is_training(mode),
      shuffle_buffer_size=shuffle_buffer_size,
      parallel_shards=parallel_shards,
      repeat=repeat,
      seed=seed)
  parse_fn = example_codec.make_parse_fn(feature_spec, label_spec)

  def parse(serialized):
    parsed = parse_fn(serialized)
    # tf.data needs plain dict structures; convert SpecStructs to flat dicts.
    if label_spec is not None:
      features, labels = parsed
      return dict(features.items()), dict(labels.items())
    return dict(parsed.items())

  dataset = dataset.map(
      parse, num_parallel_calls=num_parallel_calls or tf.data.AUTOTUNE)
  if preprocess_fn is not None:
    dataset = dataset.map(preprocess_fn, num_parallel_calls=tf.data.AUTOTUNE)
  return dataset.prefetch(tf.data.AUTOTUNE)


def make_task_grouped_dataset(file_patterns: str,
                              feature_spec,
                              label_spec=None,
                              mode: str = modes.ModeKeys.TRAIN,
                              task_batch_size: int = 4,
                              num_train_samples_per_task: int = 4,
                              num_val_samples_per_task: int = 4,
                              shuffle_buffer_size: int = 50,
                              interleave_cycle_length: Optional[int] = None,
                              shuffle_filenames: bool = True,
                              seed: Optional[int] = None):
  """Per-task file interleave emitting [task_batch, samples, ...] batches.

  Capability-equivalent of the reference's task-grouped ``parallel_read``
  (``meta_learning/meta_tfdata.py:37-132``): each FILE holds one task's
  examples; every element dequeues ``num_train + num_val`` examples from
  ONE task (so meta-learning sees per-task sample groups), tasks are
  interleaved block_length=1, and ``task_batch_size`` tasks form the meta
  batch.
  """
  tf = _tf()
  import jax

  data_format, filenames = records.get_data_format_and_filenames(
      file_patterns)
  # Multi-host: each process owns a distinct slice of task files. With
  # fewer task files than processes, fall back to sharding the stream of
  # task GROUPS below (mirrors make_serialized_dataset's element shard)
  # so hosts never silently feed duplicate data.
  filenames, sharded_by_file = shard_filenames_for_process(filenames)
  group_shard = not sharded_by_file and jax.process_count() > 1
  num_tasks = len(filenames)
  samples = num_train_samples_per_task + num_val_samples_per_task
  is_training = modes.is_training(mode)

  if group_shard:
    filenames = sorted(filenames)
  files = tf.data.Dataset.from_tensor_slices(filenames)
  if shuffle_filenames and is_training:
    shuffle_seed = seed
    if group_shard and shuffle_seed is None:
      # The positional shard below only partitions the task stream if
      # every host walks it in the same order.
      shuffle_seed = 0
    files = files.shuffle(buffer_size=num_tasks, seed=shuffle_seed).repeat()
  else:
    files = files.repeat()

  # Enumerate file visits: every per_task invocation builds FRESH shuffle
  # ops, so a constant user seed would make each visit to a task (and
  # every host, under the group shard) draw the identical sample group
  # forever. Mixing the visit index in keeps runs reproducible while
  # varying the draw per visit.
  files = files.enumerate()

  def per_task(visit, filename):
    task = records.DATA_FORMATS[data_format](filename)
    if is_training:
      # ONE sample-group per file visit: an infinite (repeat'd) inner
      # dataset would permanently starve tasks beyond the first
      # interleave cycle (tf.data only advances the cycle when an inner
      # iterator exhausts). The filenames stream repeats, so every task
      # recurs across visits.
      visit_seed = None if seed is None else seed + visit
      task = task.shuffle(
          buffer_size=max(shuffle_buffer_size, samples), seed=visit_seed)
      return task.repeat().batch(samples, drop_remainder=True).take(1)
    # Eval: drain the file's groups once per filename epoch.
    return task.batch(samples, drop_remainder=True)

  # Sequential interleave (no num_parallel_calls) is deterministic, which
  # the positional group shard below relies on.
  dataset = files.interleave(
      per_task,
      cycle_length=interleave_cycle_length or num_tasks,
      block_length=1)
  if group_shard:
    if is_training and not shuffle_filenames:
      # Unshuffled round-robin + stride-P keeps host h on tasks
      # ≡ h (mod gcd(P, num_tasks)) forever. The GLOBAL batch stays
      # complete and balanced (the classes partition the tasks), but
      # host and task become correlated; filename shuffling (the
      # default) breaks the alias.
      import logging

      logging.warning(
          'Task-group shard with shuffle_filenames=False: host/task '
          'aliasing (gcd(%d, %d) classes); enable filename shuffling '
          'for host-decorrelated task draws.', jax.process_count(),
          num_tasks)
    dataset = dataset.shard(jax.process_count(), jax.process_index())

  parse_fn = example_codec.make_parse_fn(feature_spec, label_spec)

  def parse(serialized):
    parsed = parse_fn(serialized)
    if label_spec is not None:
      features, labels = parsed
      return dict(features.items()), dict(labels.items())
    return dict(parsed.items())

  dataset = dataset.map(parse, num_parallel_calls=tf.data.AUTOTUNE)
  dataset = dataset.batch(task_batch_size, drop_remainder=True)
  return dataset.prefetch(tf.data.AUTOTUNE)


def pack_numpy_element(element, has_labels: bool = True):
  """One parsed dataset element -> the (features, labels-or-None) Batch
  shape the trainer consumes — the ONE convention shared by the plain
  and the checkpointable input-generator iterators."""
  if has_labels:
    features, labels = element
    return SpecStruct(features), SpecStruct(labels)
  return SpecStruct(element), None


def as_numpy_iterator(dataset, has_labels: bool = True) -> Iterator:
  """Yields SpecStruct numpy batches from a parsed tf.data.Dataset.

  Legacy convenience shape: BARE features when ``has_labels=False``
  (``numpy_batches`` callers rely on it); input generators use
  :func:`pack_numpy_element` for the trainer's Batch shape instead.
  """
  batches = metrics_lib.counter('data/tf_batches')
  it = iter(dataset.as_numpy_iterator())
  while True:
    with tracing.span('data/tf_next', annotate=False):
      try:
        element = next(it)
      except StopIteration:
        return
    batches.inc()
    if has_labels:
      yield pack_numpy_element(element, has_labels=True)
    else:
      features, _ = pack_numpy_element(element, has_labels=False)
      yield features


class CheckpointableNumpyIterator:
  """Packed-numpy-batch iterator whose STREAM POSITION checkpoints.

  Beyond the reference: its estimator input_fns restart the data stream
  from scratch on every job restart, silently re-feeding early examples.
  tf.data iterator checkpointing round-trips the full pipeline state —
  file-shuffle order, reader offsets, the shuffle BUFFER contents, and
  rng — so a restored trainer continues exactly where the stream left
  off. ``save``/``restore`` take a path prefix (a tf Checkpoint write);
  the restoring process must build the iterator from the same dataset
  definition (same patterns/seed/batch size), which
  ``DefaultRecordInputGenerator.create_checkpointable_iterator``
  guarantees by construction.
  """

  def __init__(self, dataset, has_labels: bool = True):
    import threading

    tf = _tf()
    self._iterator = iter(dataset)  # GUARDED_BY(self._lock)
    self._checkpoint = tf.train.Checkpoint(iterator=self._iterator)  # GUARDED_BY(self._lock)
    self._has_labels = has_labels
    # save/restore vs a concurrent next() (the trainer's prefetch worker
    # advances this iterator from its own thread) is undefined in
    # tf.data — a torn mid-advance serialization would corrupt the
    # resumed stream. One lock makes position capture atomic.
    self._lock = threading.Lock()

  def __iter__(self):
    return self

  def __next__(self):
    # data/tf_next_ms: host time to surface one parsed batch from the
    # tf.data pipeline (parse/decode runs inside tf.data's own threads;
    # this measures what the TRAIN LOOP pays — the input-bound signal).
    with tracing.span('data/tf_next', annotate=False):
      with self._lock:
        element = next(self._iterator)
      element = _tf().nest.map_structure(lambda t: t.numpy(), element)
    metrics_lib.counter('data/tf_batches').inc()
    return pack_numpy_element(element, has_labels=self._has_labels)

  def save(self, path_prefix: str) -> str:
    with self._lock:
      return self._checkpoint.write(path_prefix)

  def restore(self, path_prefix: str) -> None:
    # assert_consumed: a silently-unmatched restore would restart the
    # stream from zero — the failure mode this class exists to prevent.
    import time

    t0 = time.perf_counter()
    with self._lock:
      self._checkpoint.read(path_prefix).assert_consumed()
    # Same resume gauges the native path publishes: the tf.data blob
    # round-trips the FULL pipeline state (reader offsets + shuffle
    # buffer), so nothing is replayed and restore is position-flat.
    metrics_lib.gauge('data/resume_ms').set(
        (time.perf_counter() - t0) * 1e3)
    metrics_lib.gauge('data/resume_seek_mode').set(1)
    metrics_lib.gauge('data/resume_replayed_records').set(0)


def numpy_batches(file_patterns,
                  feature_spec,
                  label_spec=None,
                  mode: str = modes.ModeKeys.TRAIN,
                  batch_size: int = 32,
                  **kwargs) -> Iterator:
  """One-call convenience: files -> iterator of packed numpy batches."""
  dataset = make_dataset(file_patterns, feature_spec, label_spec, mode,
                         batch_size, **kwargs)
  return as_numpy_iterator(dataset, has_labels=label_spec is not None)
