"""Follow mode: tail a growing shard directory into a sampling window.

The train half of the collect→train→export→collect loop: actors
(``collect/actor.py``) keep committing episode shards into a directory;
a :class:`FollowStream` tails it, ingests ONLY commit-marked shards, and
serves records out of a bounded replay-buffer-style sampling window, so
the trainer's input engine consumes a live, changing corpus with the
same ``Iterator[bytes]`` contract a static interleave has.

Robustness contracts, drilled by ``tests/test_collect_loop.py``:

* **Torn shards are invisible.** A shard file without its
  ``<shard>.commit`` marker (a killed actor, a suppressed marker) is
  never opened — only counted (``data/follow/torn_pending``). Commit
  markers are published atomically AFTER the shard bytes are durable,
  so a marker implies a complete shard.
* **Corrupt/stale shards skip loudly.** A committed shard that fails
  its CRC-verified read charges the stream's
  :class:`~tensor2robot_tpu.utils.retry.ErrorBudget` (per-source
  accounting, ``resilience/data_errors/...``) and is skipped; the
  budget's exhaustion raises, never silently shrinking the corpus.
* **Bounded-wait backpressure, both directions.** When the trainer
  outruns collection the sampler BLOCKS on a condition (no busy-spin)
  until the window holds ``min_window_records``, bounded by
  ``starve_timeout_secs`` — exhaustion raises a loud
  :class:`FollowStarvedError`, never a silent hang. When collection
  outruns the trainer the bounded window evicts oldest records
  (``data/follow/evicted_records``) — memory is fixed, staleness
  shrinks.
* **Off-policy staleness is measurable.** Every record carries the
  policy version (export global step) that collected it (the
  ``collect/`` stamp manifest riding the commit marker);
  ``data/follow/staleness_steps`` gauges sampled-record age against the
  newest version seen, next to ``data/follow/{shards_seen,
  window_records}``.

Each commit marker also carries its episodes' rollout-span manifest
(trace/span ids + timings); ingest records the actor's rollout span and
a child ``data/follow/ingest`` span into this process's span index, so
``tools/assemble_trace.py --request <episode>`` resolves a training
record back through the trainer to the actor and export generation that
produced it.
"""

from __future__ import annotations

import dataclasses
import glob as glob_lib
import hashlib
import json
import logging
import os
import threading
import time
from typing import List, Optional, Set, Tuple

from tensor2robot_tpu.observability import flight
from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.utils import retry as retry_lib

COMMIT_SUFFIX = '.commit'


class FollowStarvedError(RuntimeError):
  """The sampling window stayed under its minimum past the bounded wait.

  Collection has stalled (actors dead? filesystem wedged?) — raising is
  the honest move; a trainer silently spinning on an empty window would
  look like a hang.
  """


@dataclasses.dataclass
class FollowConfig:
  """Follow-mode knobs (see module docstring for the semantics)."""

  directory: str
  pattern: str = '*.tfrecord'
  poll_interval_secs: float = 0.25
  # Bounded sampling window (records). Collection past it evicts oldest.
  window_records: int = 4096
  # Sampling blocks until the window holds this many records (None =
  # one training batch, resolved by the input generator).
  min_window_records: Optional[int] = None
  # Bounded wait for the window minimum; exceeded → FollowStarvedError.
  starve_timeout_secs: float = 120.0
  # Tolerated unreadable committed shards (ErrorBudget; the raise path).
  error_budget: int = 10
  seed: Optional[int] = None
  # Ingest the commit markers' episode manifests into the span index
  # (the assemble_trace --request join).
  record_trace_spans: bool = True
  # Drill accounting: keep sha1 digests of every sampled record on the
  # stream (bounded by window uniqueness) so tests can assert the
  # trainer stream is byte-clean against the committed shard set.
  trace_samples: bool = False


class FollowStream:
  """``Iterator[bytes]`` over a live shard directory (see module doc).

  One background follower thread ingests committed shards into the
  window; any number of consumer threads sample (the input engine uses
  exactly one issuer). ``close()`` stops the follower and makes
  ``next()`` raise ``StopIteration`` — the engine then drains normally.
  """

  def __init__(self, config: FollowConfig, batch_size: int = 1):
    import numpy as np

    if config.window_records < 1:
      raise ValueError(
          f'window_records must be >= 1, got {config.window_records}')
    self._config = config
    self._min_records = (config.min_window_records
                         if config.min_window_records is not None
                         else max(1, int(batch_size)))
    if self._min_records > config.window_records:
      raise ValueError(
          f'min_window_records={self._min_records} exceeds the window '
          f'capacity {config.window_records}: sampling could never start')
    self._rng = np.random.RandomState(config.seed)
    self._lock = threading.Lock()
    self._cond = threading.Condition(self._lock)
    # The sampling window: (record_bytes, policy_version) pairs, evicted
    # FIFO past window_records.
    self._window: List[Tuple[bytes, int]] = []  # GUARDED_BY(self._lock)
    self._ingested_shards: Set[str] = set()  # GUARDED_BY(self._lock)
    self._latest_version = -1  # GUARDED_BY(self._lock)
    self._closed = False  # GUARDED_BY(self._lock)
    self._shards_seen = 0  # GUARDED_BY(self._lock)
    self.sampled_hashes: Set[bytes] = set()  # GUARDED_BY(self._lock)
    self._budget = retry_lib.ErrorBudget(
        config.error_budget, name='follow stream')
    self._budget_error: Optional[BaseException] = None  # GUARDED_BY(self._lock)
    # Registry series (static names: the cardinality gate).
    self._g_shards = metrics_lib.gauge('data/follow/shards_seen')
    self._g_window = metrics_lib.gauge('data/follow/window_records')
    self._g_staleness = metrics_lib.gauge('data/follow/staleness_steps')
    # High-water mark (monotonic per stream): the drill-assertable proof
    # that off-policy data was actually served at some point, which the
    # instantaneous gauge can't retain.
    self._g_max_staleness = metrics_lib.gauge(
        'data/follow/max_staleness_steps')
    self._max_staleness = 0  # GUARDED_BY(self._lock)
    self._g_torn = metrics_lib.gauge('data/follow/torn_pending')
    self._c_records = metrics_lib.counter('data/follow/records_ingested')
    self._c_evicted = metrics_lib.counter('data/follow/evicted_records')
    self._c_samples = metrics_lib.counter('data/follow/samples')
    self._c_waits = metrics_lib.counter('data/follow/sample_waits')
    self._h_wait_ms = metrics_lib.histogram('data/follow/sample_wait_ms')
    self._c_skipped = metrics_lib.counter('data/follow/skipped_shards')
    self._follower = threading.Thread(
        target=self._follow_loop, name='follow-ingest', daemon=True)
    self._follower.start()

  # ------------------------------------------------------------- ingestion

  def _committed_shards(self) -> Tuple[List[str], int]:
    """Shards whose commit marker exists, plus the torn-pending count.

    A marker names a COMPLETE shard (the writer publishes it last), so
    marker presence is the only visibility authority. Deterministic
    order: markers sorted by (mtime, name) — commit order, name-tied.
    """
    directory = self._config.directory
    shards = glob_lib.glob(os.path.join(directory, self._config.pattern))
    committed, torn = [], 0
    for shard in shards:
      if os.path.exists(shard + COMMIT_SUFFIX):
        committed.append(shard)
      else:
        torn += 1

    def order(path):
      try:
        mtime = os.path.getmtime(path + COMMIT_SUFFIX)
      except OSError:
        mtime = 0.0
      return (mtime, path)

    return sorted(committed, key=order), torn

  def _follow_loop(self) -> None:
    while True:
      with self._lock:
        if self._closed:
          return
        seen = set(self._ingested_shards)
      try:
        committed, torn = self._committed_shards()
        self._g_torn.set(torn)
        for shard in committed:
          if shard in seen:
            continue
          self._ingest_shard(shard)
          with self._lock:
            if self._closed:
              return
      except retry_lib.DataErrorBudgetExceededError as e:
        # Surface on the consumer thread: the sampler re-raises it so
        # the trainer dies loudly instead of starving quietly.
        with self._cond:
          self._budget_error = e
          self._cond.notify_all()
        return
      except Exception as e:  # pylint: disable=broad-except
        # Directory scans must survive transient filesystem errors; the
        # budget machinery above is the bounded-failure authority.
        logging.warning('Follow scan of %r failed (%r); retrying.',
                        self._config.directory, e)
      with self._cond:
        if self._closed:
          return
        self._cond.wait(timeout=self._config.poll_interval_secs)

  def _read_shard(self, shard: str) -> List[bytes]:
    """All records of a committed shard, CRC-verified."""
    from tensor2robot_tpu.data import native_io, shard_index

    if native_io.available() and '://' not in shard:
      with native_io.NativeRecordReader(shard) as reader:
        return list(reader)
    return list(shard_index.iter_records_from(shard, 0))

  def _episode_versions(self, shard: str,
                        record_count: int) -> Tuple[List[int], dict]:
    """Per-record policy versions from the commit-marker manifest."""
    marker: dict = {}
    try:
      with open(shard + COMMIT_SUFFIX) as f:
        marker = json.load(f)
    except (OSError, ValueError):
      pass
    versions: List[int] = []
    for episode in marker.get('episodes', []):
      versions.extend([int(episode.get('policy_version', -1))] *
                      int(episode.get('records', 0)))
    if len(versions) < record_count:
      versions.extend([-1] * (record_count - len(versions)))
    return versions[:record_count], marker

  def _record_ingest_spans(self, marker: dict, t0: float, t1: float) -> None:
    """Actor rollout spans (riding the marker) + this process's ingest
    child spans → the span index, one batched call per shard."""
    from tensor2robot_tpu.observability import tracing

    span_dicts = []
    for episode in marker.get('episodes', []):
      trace_id = episode.get('trace_id')
      span_id = episode.get('span_id')
      if not trace_id or not span_id:
        continue
      request_id = episode.get('request_id', '')
      span_dicts.append({
          'trace_id': trace_id, 'span_id': span_id, 'parent_id': '',
          'name': 'collect/rollout', 'kind': 'collect',
          'start': float(episode.get('start', t0)),
          'end': float(episode.get('end', t0)),
          'request_id': request_id,
          'detail': (f"actor={marker.get('actor_id')} "
                     f"version={episode.get('policy_version')} "
                     f"reward={episode.get('reward')}"),
          'service': episode.get('service',
                                 f"actor{marker.get('actor_id')}"),
      })
      span_dicts.append({
          'trace_id': trace_id, 'span_id': tracing.mint_span_id(),
          'parent_id': span_id, 'name': 'data/follow/ingest',
          'kind': 'collect', 'start': t0, 'end': t1,
          'request_id': request_id,
          'detail': f"version={episode.get('policy_version')}",
      })
    if span_dicts:
      tracing.record_spans(span_dicts)

  def _ingest_shard(self, shard: str) -> None:
    t0 = time.time()
    try:
      records = self._read_shard(shard)
    except (IOError, OSError, ValueError) as e:
      # A COMMITTED shard that cannot be read: stale replication, bitrot,
      # or an injected tear. Budget-charged per source, skipped loudly.
      self._c_skipped.inc()
      flight.event('collect', 'data/follow/shard_skipped',
                   f'shard={os.path.basename(shard)} error='
                   f'{type(e).__name__}')
      with self._lock:
        self._ingested_shards.add(shard)  # never retried: skip is final
      self._budget.record(e, source=shard)
      return
    versions, marker = self._episode_versions(shard, len(records))
    t1 = time.time()
    evicted = 0
    with self._cond:
      self._ingested_shards.add(shard)
      self._shards_seen += 1
      for record, version in zip(records, versions):
        self._window.append((record, version))
        if version > self._latest_version:
          self._latest_version = version
      overflow = len(self._window) - self._config.window_records
      if overflow > 0:
        del self._window[:overflow]
        evicted = overflow
      window_size = len(self._window)
      shards_seen = self._shards_seen
      self._cond.notify_all()
    self._c_records.inc(len(records))
    if evicted:
      self._c_evicted.inc(evicted)
    self._g_shards.set(shards_seen)
    self._g_window.set(window_size)
    flight.event(
        'collect', 'data/follow/shard_ingested',
        f'shard={os.path.basename(shard)} records={len(records)} '
        f'window={window_size} evicted={evicted}')
    if self._config.record_trace_spans and marker:
      self._record_ingest_spans(marker, t0, t1)

  # -------------------------------------------------------------- sampling

  def __iter__(self):
    return self

  def __next__(self) -> bytes:
    deadline = time.monotonic() + self._config.starve_timeout_secs
    waited = False
    t_wait0 = time.monotonic()
    with self._cond:
      while True:
        if self._budget_error is not None:
          raise self._budget_error
        if self._closed:
          raise StopIteration
        if len(self._window) >= self._min_records:
          break
        remaining = deadline - time.monotonic()
        if remaining <= 0:
          raise FollowStarvedError(
              f'follow stream starved: window holds {len(self._window)} '
              f'record(s) < minimum {self._min_records} after '
              f'{self._config.starve_timeout_secs:.1f}s '
              f'({self._shards_seen} shard(s) ingested from '
              f'{self._config.directory!r}); collection has stalled')
        if not waited:
          waited = True
          self._c_waits.inc()
        self._cond.wait(timeout=remaining)
      index = int(self._rng.randint(len(self._window)))
      record, version = self._window[index]
      staleness = (self._latest_version - version
                   if version >= 0 and self._latest_version >= 0 else 0)
      staleness = max(0, staleness)
      if staleness > self._max_staleness:
        self._max_staleness = staleness
      max_staleness = self._max_staleness
      if self._config.trace_samples:
        self.sampled_hashes.add(hashlib.sha1(record).digest())
    if waited:
      self._h_wait_ms.observe((time.monotonic() - t_wait0) * 1e3)
    self._c_samples.inc()
    self._g_staleness.set(staleness)
    self._g_max_staleness.set(max_staleness)
    return record

  # ------------------------------------------------------------- lifecycle

  @property
  def latest_version(self) -> int:
    with self._lock:
      return self._latest_version

  @property
  def window_size(self) -> int:
    with self._lock:
      return len(self._window)

  @property
  def shards_seen(self) -> int:
    with self._lock:
      return self._shards_seen

  def ingested_shards(self) -> Set[str]:
    with self._lock:
      return set(self._ingested_shards)

  def close(self) -> None:
    with self._cond:
      self._closed = True
      self._cond.notify_all()
    self._follower.join(timeout=5.0)
