"""Parallel host input engine: stage-overlapped, deterministic batching.

The native record path (``NativeRecordInputGenerator``) historically ran
read → parse → decode → batch as ONE serial chain per batch behind the
trainer's single prefetch thread: on multi-core hosts, decode of batch
N+1 never overlapped parse of N+2, and the host side capped record-fed
training far below the device floor on shallow-step workloads (PERF_NOTES
"Record-fed training"). The reference hid this problem inside tf.data's
C++ multi-threaded runtime; this module is the JAX-native equivalent for
the TF-free path.

Stages (each its own thread(s), connected by bounded queues):

  ticket issuer   ONE thread walks the interleaved/shuffled record
                  stream in its deterministic order and slices it into
                  numbered batch tickets ``(seq, [records])``. All
                  ordering authority lives here.
  workers (N)     each pulls a ticket and runs parse + image decode for
                  its WHOLE batch (the expensive, GIL-releasing work),
                  concurrently across DIFFERENT batches.
  reorder         delivers parsed batches strictly in ticket order, so
                  the output stream is byte-identical to the serial path
                  for ANY worker count — and errors surface at exactly
                  the batch index where the serial path would have
                  raised them.

Because delivery order equals ticket order equals the serial record
order, the engine's stream position is well-defined (delivered batch
count), which is what makes the native path's mid-epoch resumable input
state possible (``NativeRecordInputGenerator.create_checkpointable_
iterator``).

Backpressure: at most ``ring_depth`` tickets are outstanding (issued but
not yet delivered/released), bounding memory to a ring of batch buffers.
With ``reuse_buffers=True`` the ring is literal: each slot owns
preallocated contiguous per-feature image buffers (``parse_fn.
make_image_buffers``) that workers decode straight into — no per-batch
allocation, no ``np.stack`` copy — and a slot recycles only after the
consumer calls :meth:`release` (delivered arrays are VIEWS of slot
buffers; release declares them dead). Default ``False`` allocates fresh
buffers per ticket, so delivered batches are plainly owned by the caller
— the right mode for the trainer, whose prefetch queue holds batches
with no release point.

Sizing is core-aware and self-tuning: :func:`autotune` generalizes the
trainer's ``prefetch auto`` heuristic — it reads the AVAILABLE core
count (affinity/cgroup-aware) plus the PR-2 observability signals
(``trainer/input_bound_fraction``, prefetch starvation counters) when a
measured window exists, and collapses to the serial path on single-core
hosts, where PERF_NOTES measured extra pipeline threads as a net loss
(they contend with dispatch instead of overlapping it).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import queue as queue_lib
import threading
import time
from typing import Any, Callable, Iterable, Iterator, List, Optional

from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.observability import tracing

# Autotune defaults. Workers beyond ~4 stop paying off for JPEG-decode
# batches (the native decoder already fans one batch across cores); the
# input-bound escalation may go to 8 when the breakdown proves the run
# is starved anyway.
_DEFAULT_MAX_WORKERS = 4
_INPUT_BOUND_MAX_WORKERS = 8
# A tuning window is trusted only after this many measured dispatches.
_MIN_DISPATCHES_FOR_SIGNALS = 32
# input_bound_fraction thresholds: below the floor the run is compute-
# bound and pipeline threads would only contend; above the ceiling the
# host is the bottleneck and deserves every core.
_COMPUTE_BOUND_FRACTION = 0.05
_INPUT_BOUND_FRACTION = 0.5


def available_cpus() -> int:
  """CPUs AVAILABLE to this process (affinity/cgroup-aware):
  ``os.cpu_count`` lies under taskset/containers."""
  try:
    return len(os.sched_getaffinity(0))
  except (AttributeError, OSError):
    return os.cpu_count() or 1


@dataclasses.dataclass(frozen=True)
class EngineDecision:
  """One autotune outcome — recorded beside bench metrics (bench.py)."""

  num_workers: int
  ring_depth: int
  prefetch_depth: int
  cpus: int
  reason: str

  @property
  def serial(self) -> bool:
    return self.num_workers == 0

  def as_dict(self) -> dict:
    return dataclasses.asdict(self)


_LAST_DECISION: Optional[EngineDecision] = None


def last_decision() -> Optional[EngineDecision]:
  """The most recent :func:`autotune` outcome in this process."""
  return _LAST_DECISION


def _signal_window():
  """(input_bound_fraction, starvation, dispatches) from the registry,
  or None when no trustworthy measured window exists yet."""
  dispatches = metrics_lib.counter('trainer/dispatches').value
  if dispatches < _MIN_DISPATCHES_FOR_SIGNALS:
    return None
  if 'trainer/input_bound_fraction' not in metrics_lib.registry.names(
      'trainer/input_bound_fraction'):
    return None
  return (metrics_lib.gauge('trainer/input_bound_fraction').value,
          metrics_lib.counter('trainer/prefetch/starvation').value,
          dispatches)


def autotune(num_workers: Optional[int] = None,
             ring_depth: Optional[int] = None,
             cpus: Optional[int] = None) -> EngineDecision:
  """Core-aware worker/ring sizing; explicit arguments always win.

  ``num_workers=None`` asks for the heuristic: 0 (serial) on single-core
  hosts; else ``min(cpus - 1, 4)``, refined by the step-time breakdown's
  signals when a prior window measured this process as compute-bound
  (shrink to 1) or input-bound (grow toward ``cpus - 1``). The decision
  is published as ``data/engine/*`` gauges and kept for
  :func:`last_decision`.
  """
  global _LAST_DECISION
  cpus = available_cpus() if cpus is None else int(cpus)
  if num_workers is not None:
    workers = max(0, int(num_workers))
    reason = f'explicit num_workers={workers}'
  elif cpus <= 1:
    workers = 0
    reason = ('single-core host: serial path (pipeline threads contend '
              'with dispatch instead of overlapping it)')
  else:
    workers = min(cpus - 1, _DEFAULT_MAX_WORKERS)
    reason = f'{cpus} cpus: default min(cpus-1, {_DEFAULT_MAX_WORKERS})'
    signals = _signal_window()
    if signals is not None:
      input_bound, starvation, dispatches = signals
      if input_bound < _COMPUTE_BOUND_FRACTION and starvation == 0:
        workers = min(workers, 1)
        reason = (f'measured compute-bound (input_bound_fraction='
                  f'{input_bound:.3f} over {dispatches} dispatches): '
                  f'1 worker suffices')
      elif input_bound >= _INPUT_BOUND_FRACTION or starvation > 0:
        workers = min(cpus - 1, _INPUT_BOUND_MAX_WORKERS)
        reason = (f'measured input-bound (input_bound_fraction='
                  f'{input_bound:.3f}, starvation={starvation}): '
                  f'all spare cores')
  if ring_depth is None:
    ring_depth = 2 * workers if workers else 0
  ring_depth = max(ring_depth, workers + 1) if workers else 0
  prefetch_depth = 0 if cpus <= 1 else 2
  decision = EngineDecision(
      num_workers=workers, ring_depth=ring_depth,
      prefetch_depth=prefetch_depth, cpus=cpus, reason=reason)
  scope = metrics_lib.scope('data/engine')
  scope.gauge('workers').set(decision.num_workers)
  scope.gauge('ring_depth').set(decision.ring_depth)
  _LAST_DECISION = decision
  return decision


def autotune_prefetch(cpus: Optional[int] = None) -> int:
  """The trainer's ``prefetch auto`` depth — same core heuristic."""
  cpus = available_cpus() if cpus is None else int(cpus)
  return 0 if cpus <= 1 else 2


class _Failure:
  """A ticket whose production raised: delivered in order, then raised."""

  __slots__ = ('exc',)

  def __init__(self, exc: BaseException):
    self.exc = exc


class ParallelBatchEngine:
  """Ticket-ordered parallel read→parse→decode over a record stream.

  ``records``: the raw serialized-record iterator (the generator's
  interleaved + shuffled stream) — consumed by ONE issuer thread, so its
  deterministic order is preserved exactly. ``parse_fn(records) ->
  batch`` runs in the workers (it must be thread-safe across DIFFERENT
  record lists, which the native parser and decode pools are).
  ``num_workers == 0`` degrades to a fully serial inline loop (no
  threads at all) — the reference stream every parallel configuration is
  byte-compared against.

  Iteration yields exactly what the serial loop would: one parsed batch
  per ``batch_size`` records, final short batch dropped
  (``drop_remainder`` parity). ``delivered`` counts yielded batches —
  the engine's checkpointable stream position.

  ``reautotune=True`` re-evaluates the worker count MID-RUN: at every
  trainer log-window crossing (``trainer/breakdown_windows``) the engine
  re-reads the live breakdown signals — ``trainer/input_bound_fraction``
  and the prefetch-starvation delta over the window — and grows/shrinks
  its worker pool, at most one change per window. The stream stays
  byte-identical through any resize (ticket order is the only ordering
  authority); bounds are [1, ring_depth - 1], so a running pipeline
  never collapses to the thread-less serial path nor outgrows its ring.
  Decision history is published as ``data/engine/reautotune/*`` and kept
  on :attr:`decision_history`.
  """

  _DONE = object()
  _RETIRE = object()  # poison pill retiring exactly one worker (resize)

  def __init__(self,
               records: Iterable[bytes],
               parse_fn: Callable[[List[bytes]], Any],
               batch_size: int,
               num_workers: int,
               ring_depth: Optional[int] = None,
               reuse_buffers: bool = False,
               reautotune: bool = False,
               cpus: Optional[int] = None,
               lease_timeout: float = 5.0,
               start_delivered: int = 0):
    if batch_size <= 0:
      raise ValueError(f'batch_size must be positive, got {batch_size}')
    self._records = iter(records)
    self._parse_fn = parse_fn
    self._batch_size = int(batch_size)
    # Serial-vs-pipeline is a MODE, fixed at construction; the mutable
    # worker-pool size below never crosses it (re-autotune bounds are
    # [1, ring_depth-1]), so mode checks need no lock.
    self._serial = max(0, int(num_workers)) == 0
    self._num_workers = max(0, int(num_workers))  # GUARDED_BY(self._workers_lock)
    # ``start_delivered``: a resumed pipeline's record iterator begins
    # mid-stream (seek or replay restore), so ``delivered`` — the
    # engine's checkpointable stream position — continues from the
    # restored batch count instead of restarting at 0.
    self.delivered = int(start_delivered)
    self._workers_lock = threading.Lock()
    self._closed = False  # GUARDED_BY(self._workers_lock)
    self._metrics = metrics_lib.scope('data/engine')
    self._m_tickets = self._metrics.counter('tickets')
    self._m_batches = self._metrics.counter('batches')
    self._m_reorder_depth = self._metrics.gauge('reorder_depth')
    self._m_wait = self._metrics.histogram('reorder_wait_ms')
    if self._serial:
      self._pending: List[bytes] = []
      return

    if ring_depth is None:
      ring_depth = 2 * self._num_workers
    self._ring_depth = max(int(ring_depth), self._num_workers + 1)
    # Mid-run re-autotune state: one evaluation per closed breakdown
    # window, keyed off the trainer's window counter; starvation is read
    # as a per-window delta (the counter is cumulative — an incident an
    # hour ago must not pin the pool grown forever).
    self._cpus = cpus
    self._reautotune_enabled = bool(reautotune)
    self._max_workers = self._ring_depth - 1
    self._worker_seq = self._num_workers  # GUARDED_BY(self._workers_lock)
    self._lease_lock = threading.Lock()
    self._lease_cond = threading.Condition(self._lease_lock)
    self._lease_timeout = float(lease_timeout)
    self._m_windows = metrics_lib.counter('trainer/breakdown_windows')
    self._last_window = self._m_windows.value
    self._starve_counter = metrics_lib.counter('trainer/prefetch/starvation')
    self._last_starvation = self._starve_counter.value
    self._m_workers = self._metrics.gauge('workers')
    self._m_reauto_windows = self._metrics.counter('reautotune/windows')
    self._m_reauto_changes = self._metrics.counter('reautotune/changes')
    self._m_reauto_target = self._metrics.gauge('reautotune/target_workers')
    self.decision_history: List[dict] = []  # GUARDED_BY(self._workers_lock)
    # Outstanding-ticket bound: acquired per issued ticket, released when
    # the consumer is done with the batch (delivery, or — in ring mode —
    # the explicit release that frees the slot for reuse).
    self._sem = threading.Semaphore(self._ring_depth)
    self._ticket_q: 'queue_lib.Queue' = queue_lib.Queue()
    self._cond = threading.Condition()
    self._results: dict = {}  # seq -> batch | _Failure  # GUARDED_BY(self._cond)
    self._next_seq = 0  # GUARDED_BY(self._cond)
    self._end_seq: Optional[int] = None  # first seq never produced  # GUARDED_BY(self._cond)
    self._stop = threading.Event()

    self._reuse = bool(reuse_buffers)
    self._free_slots: 'queue_lib.Queue' = queue_lib.Queue()
    self._slot_of: dict = {}  # seq -> slot id (ring mode)  # GUARDED_BY(self._cond)
    self._lease_order: List[int] = []  # delivered-not-released slots, FIFO  # GUARDED_BY(self._lease_cond)
    if self._reuse:
      make_buffers = getattr(parse_fn, 'make_image_buffers', None)
      if make_buffers is None:
        logging.warning(
            'reuse_buffers=True but parse_fn has no make_image_buffers; '
            'falling back to per-ticket allocation.')
        self._reuse = False
      else:
        self._slots = [make_buffers(self._batch_size)
                       for _ in range(self._ring_depth)]
        for i in range(self._ring_depth):
          self._free_slots.put(i)

    self._threads = [  # GUARDED_BY(self._workers_lock)
        threading.Thread(target=self._issue_tickets, daemon=True,
                         name='t2r-engine-tickets')
    ]
    for i in range(self._num_workers):
      self._threads.append(
          threading.Thread(target=self._worker, daemon=True,
                           name=f't2r-engine-worker-{i}'))
    for t in self._threads:
      t.start()

  # ------------------------------------------------------------- threads

  def _issue_tickets(self) -> None:
    """The ordering authority: slices the record stream into numbered
    tickets. A stream error occupies the seq at which the serial path
    would have raised it, so error position is order-preserved too."""
    seq = 0
    try:
      pending: List[bytes] = []
      for record in self._records:
        pending.append(record)
        if len(pending) < self._batch_size:
          continue
        while not self._sem.acquire(timeout=0.1):
          if self._stop.is_set():
            return
        if self._stop.is_set():
          return
        self._m_tickets.inc()
        self._ticket_q.put((seq, pending))
        seq += 1
        pending = []
      # Final short batch dropped: drop_remainder parity with the
      # serial loop and the tf.data path.
    except BaseException as e:  # delivered, in order, at seq
      with self._cond:
        self._results[seq] = _Failure(e)
        self._end_seq = seq + 1
        self._cond.notify_all()
    else:
      with self._cond:
        self._end_seq = seq
        self._cond.notify_all()
    finally:
      # One sentinel; workers re-put it as they exit (the pool may have
      # been resized since these tickets were issued).
      self._ticket_q.put(self._DONE)

  def _worker(self) -> None:
    while True:
      item = self._ticket_q.get()
      if item is self._RETIRE:
        return  # mid-run shrink: exactly one worker exits
      if item is self._DONE:
        # Propagate end-of-stream to sibling workers: the issuer puts
        # ONE sentinel, so shutdown is correct for any worker count the
        # pool was resized to since tickets started.
        self._ticket_q.put(self._DONE)
        return
      if self._stop.is_set():
        return
      seq, records = item
      slot = None
      if self._reuse:
        slot = self._free_slots.get()  # never blocks long: slots ≥ the
        # outstanding-ticket bound, and a ticket only exists with its
        # semaphore permit held.
      try:
        with tracing.span('data/engine/parse_decode', annotate=False):
          if slot is None:
            batch = self._parse_fn(records)
          else:
            batch = self._parse_fn(records, image_out=self._slots[slot])
      except BaseException as e:  # surfaced at this seq, in order
        if slot is not None:
          self._free_slots.put(slot)
          slot = None
        batch = _Failure(e)
      with self._cond:
        self._results[seq] = batch
        if slot is not None:
          self._slot_of[seq] = slot
        self._m_reorder_depth.set(len(self._results))
        self._cond.notify_all()

  # ----------------------------------------------------- mid-run autotune

  def _maybe_reautotune(self) -> None:
    """One worker-count re-evaluation per closed breakdown window."""
    if not self._reautotune_enabled:
      return
    windows = self._m_windows.value
    if windows == self._last_window:
      return
    self._last_window = windows
    self._m_reauto_windows.inc()
    starvation = self._starve_counter.value
    starve_delta = starvation - self._last_starvation
    self._last_starvation = starvation
    if (metrics_lib.counter('trainer/dispatches').value <
        _MIN_DISPATCHES_FOR_SIGNALS):
      return
    input_bound = metrics_lib.gauge('trainer/input_bound_fraction').value
    cpus = available_cpus() if self._cpus is None else int(self._cpus)
    with self._workers_lock:
      current = self._num_workers
    if input_bound < _COMPUTE_BOUND_FRACTION and starve_delta == 0:
      target = 1  # compute-bound: extra pipeline threads only contend
    elif input_bound >= _INPUT_BOUND_FRACTION or starve_delta > 0:
      target = min(max(cpus - 1, 1), _INPUT_BOUND_MAX_WORKERS)
    else:
      target = current
    target = max(1, min(target, self._max_workers))
    self._m_reauto_target.set(target)
    if target != current:
      self._set_num_workers(target, input_bound, starve_delta)

  def _set_num_workers(self, target: int, input_bound: float,
                       starvation: int) -> None:
    """Grows (spawn) or shrinks (retire pills) the worker pool in place.

    Safe mid-stream: tickets/reorder carry all ordering state, so the
    delivered stream is byte-identical across any resize. Retire pills
    queue FIFO behind outstanding tickets — a shrinking pool finishes
    the work it already accepted.
    """
    with self._workers_lock:
      if self._closed:
        return  # close() already snapshotted the pool: no new threads
      old = self._num_workers
      if target == old:
        return
      if target > old:
        for _ in range(target - old):
          t = threading.Thread(target=self._worker, daemon=True,
                               name=f't2r-engine-worker-{self._worker_seq}')
          self._worker_seq += 1
          self._threads.append(t)
          t.start()
      else:
        for _ in range(old - target):
          self._ticket_q.put(self._RETIRE)
      self._num_workers = target
      decision = {'window': self._last_window, 'from': old, 'to': target,
                  'input_bound_fraction': round(float(input_bound), 4),
                  'starvation': int(starvation)}
      self.decision_history.append(decision)
    self._m_workers.set(target)
    self._m_reauto_changes.inc()
    logging.info('Input engine re-autotune: %s', decision)

  # ------------------------------------------------------------ consumer

  def __iter__(self) -> Iterator[Any]:
    return self

  def __next__(self) -> Any:
    if self._serial:
      return self._serial_next()
    self._maybe_reautotune()
    if self._reuse:
      # A full ring is TRANSIENT when someone releases asynchronously
      # (the trainer's placement stage frees each lease at transfer
      # completion, from its own thread) — wait briefly for that. Only a
      # ring nobody will ever release (a consumer ignoring the lease
      # contract) stays full: fail loudly then, deadlocking never.
      deadline = time.monotonic() + self._lease_timeout
      with self._lease_cond:
        while len(self._lease_order) >= self._ring_depth:
          remaining = deadline - time.monotonic()
          if remaining <= 0:
            raise RuntimeError(
                f'all {self._ring_depth} ring slots are leased (no '
                f'release() for {self._lease_timeout:.1f}s); call '
                f'release() once per consumed batch before requesting '
                f'the next one')
          self._lease_cond.wait(timeout=remaining)
    t0 = time.perf_counter()
    with self._cond:
      while (self._next_seq not in self._results and
             (self._end_seq is None or self._next_seq < self._end_seq)):
        self._cond.wait()
      if self._next_seq not in self._results:
        raise StopIteration
      seq = self._next_seq
      self._next_seq += 1
      result = self._results.pop(seq)
      self._m_reorder_depth.set(len(self._results))
      slot = self._slot_of.pop(seq, None)
    self._m_wait.observe((time.perf_counter() - t0) * 1e3)
    if isinstance(result, _Failure):
      self.close()
      raise result.exc
    if slot is not None:
      # Ring mode: the permit (and the slot) stay held until release().
      with self._lease_lock:
        self._lease_order.append(slot)
    else:
      self._sem.release()
    self.delivered += 1
    self._m_batches.inc()
    return result

  def _serial_next(self) -> Any:
    """The reference path: one batch, produced inline, no threads."""
    pending = self._pending
    self._pending = []
    for record in self._records:
      pending.append(record)
      if len(pending) >= self._batch_size:
        with tracing.span('data/engine/parse_decode', annotate=False):
          batch = self._parse_fn(pending)
        self.delivered += 1
        self._m_batches.inc()
        return batch
    raise StopIteration  # final short batch dropped (drop_remainder)

  def release(self) -> None:
    """Ring mode: declares the OLDEST still-leased batch's arrays dead.

    Delivered batches are views of ring-slot buffers; releasing returns
    the slot to the worker pool (and its backpressure permit), after
    which those arrays WILL be overwritten. Call once per consumed batch,
    after its contents are copied/placed. No-op without
    ``reuse_buffers``. Thread-safe: the trainer's placement stage
    releases from its own thread while the fetch stage consumes.
    """
    if self._serial or not self._reuse:
      return
    with self._lease_cond:
      if not self._lease_order:
        return
      slot = self._lease_order.pop(0)
      self._lease_cond.notify_all()
    self._free_slots.put(slot)
    self._sem.release()

  # ------------------------------------------------------------ lifecycle

  def close(self, timeout: float = 5.0) -> None:
    """Stops the pipeline threads (idempotent)."""
    with self._workers_lock:
      if self._serial or self._closed:
        self._closed = True
        return
      self._closed = True
      # Snapshot pool state under the lock: a concurrent mid-run grow
      # (_set_num_workers, driven from the consumer thread) appends to
      # _threads while this method would otherwise iterate it — a
      # RuntimeError plus unjoined workers (found by the lock-discipline
      # checker, PR 8). After _closed flips, _set_num_workers is a
      # no-op, so the snapshot is complete.
      threads = list(self._threads)
      workers = self._num_workers
    self._stop.set()
    with self._cond:
      # A next() after close must observe end-of-stream, not block
      # forever waiting for a ticket no worker will ever produce.
      if self._end_seq is None:
        self._end_seq = self._next_seq
      self._cond.notify_all()
    # Unblock workers waiting on tickets/slots and the issuer waiting on
    # the semaphore (it polls with a timeout).
    for _ in range(workers):
      self._ticket_q.put(self._DONE)
    if self._reuse:
      for _ in range(workers):
        self._free_slots.put(0)
    deadline = time.monotonic() + timeout
    for t in threads:
      t.join(max(0.0, deadline - time.monotonic()))
      if t.is_alive():
        logging.warning(
            'Engine thread %s did not exit within %.1fs (record stream '
            'blocked?); abandoning the daemon thread.', t.name, timeout)

  def __enter__(self) -> 'ParallelBatchEngine':
    return self

  def __exit__(self, *exc) -> None:
    self.close()

  def __del__(self):
    try:
      self.close(timeout=0.1)
    except Exception:  # interpreter shutdown
      pass
