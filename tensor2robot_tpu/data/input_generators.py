"""Input generators: the spec-driven sources of training batches.

Capability-equivalent of the reference's ``input_generators/`` package
(``abstract_input_generator.py:38-211``, ``default_input_generator.py``).
A generator owns the *in* specs (what is on disk / in memory), which it pulls
from a model's preprocessor via :meth:`set_specification_from_model`, and
yields packed numpy (features, labels) SpecStruct batches ready for
``jax.device_put``.
"""

from __future__ import annotations

import abc
import json
import logging
import os
from typing import Callable, Dict, Iterator, Optional, Tuple, Union

import numpy as np

from tensor2robot_tpu import modes
from tensor2robot_tpu.data import pipeline
from tensor2robot_tpu.specs import (SpecStruct, algebra, numpy_gen)

Batch = Tuple[SpecStruct, Optional[SpecStruct]]


class AbstractInputGenerator(abc.ABC):
  """Holds in-specs and produces an iterator of packed numpy batches.

  ``error_budget`` (None disables, the default) bounds tolerated batch
  production failures: a failed ``next()`` on the underlying iterator
  (transient IO, a corrupt record surfacing as a parse error) is
  charged, logged, and the stream is rebuilt — training continues on
  the surviving data until the budget is spent, at which point
  ``utils.retry.DataErrorBudgetExceededError`` raises with full
  accounting. Rebuilding restarts the stream definition, so budget data
  sources should shuffle or repeat.
  """

  def __init__(self, batch_size: int = 32,
               error_budget: Optional[int] = None):
    self._batch_size = batch_size
    self._error_budget = error_budget
    self._feature_spec: Optional[SpecStruct] = None
    self._label_spec: Optional[SpecStruct] = None

  @property
  def batch_size(self) -> int:
    return self._batch_size

  @batch_size.setter
  def batch_size(self, value: int) -> None:
    self._batch_size = int(value)

  @property
  def feature_spec(self) -> Optional[SpecStruct]:
    return self._feature_spec

  @property
  def label_spec(self) -> Optional[SpecStruct]:
    return self._label_spec

  def set_specification(self, feature_spec: SpecStruct,
                        label_spec: Optional[SpecStruct]) -> None:
    self._feature_spec = algebra.flatten_spec_structure(feature_spec)
    self._label_spec = (None if label_spec is None else
                        algebra.flatten_spec_structure(label_spec))

  def set_specification_from_model(self, model, mode: str) -> None:
    """Pulls the preprocessor *in* specs — the on-disk data contract."""
    preprocessor = model.preprocessor
    self.set_specification(
        preprocessor.get_in_feature_specification(mode),
        preprocessor.get_in_label_specification(mode))

  def create_iterator(self, mode: str,
                      batch_size: Optional[int] = None) -> Iterator[Batch]:
    if self._feature_spec is None:
      raise ValueError(
          'Input generator has no specs; call set_specification(_from_model) '
          'first.')
    batch_size = batch_size or self._batch_size
    if self._error_budget is None:
      return self._create_iterator(mode, batch_size)
    from tensor2robot_tpu.utils import retry as retry_lib

    return retry_lib.ResilientIterator(
        lambda: self._create_iterator(mode, batch_size),
        budget=retry_lib.ErrorBudget(
            self._error_budget, name=f'{type(self).__name__} batch'),
        retry_on=self._budget_retry_on(),
        source_fn=self._budget_source)

  def _budget_retry_on(self):
    """Exception types the error budget absorbs (subclasses extend)."""
    from tensor2robot_tpu.utils import retry as retry_lib

    return retry_lib.DEFAULT_RETRYABLE

  def _budget_source(self, exc: BaseException) -> Optional[str]:
    """Maps a caught data error to a source label (None = let the
    budget's path-in-message fallback attribute it)."""
    del exc
    return None

  @abc.abstractmethod
  def _create_iterator(self, mode: str, batch_size: int) -> Iterator[Batch]:
    ...


class DefaultRecordInputGenerator(AbstractInputGenerator):
  """Record-backed input: file patterns or a {dataset_key: patterns} map.

  Reference: ``default_input_generator.py:54-115``.
  """

  def __init__(self,
               file_patterns: Union[str, Dict[str, str], None] = None,
               dataset_map: Optional[Dict[str, str]] = None,
               batch_size: int = 32,
               shuffle_buffer_size: int = 1000,
               parallel_shards: int = 10,
               seed: Optional[int] = None,
               error_budget: Optional[int] = None):
    super().__init__(batch_size, error_budget=error_budget)
    if not file_patterns and not dataset_map:
      raise ValueError('Provide file_patterns or dataset_map.')
    if file_patterns and dataset_map:
      raise ValueError('file_patterns and dataset_map are mutually '
                       'exclusive.')
    self._file_patterns = dataset_map or file_patterns
    self._shuffle_buffer_size = shuffle_buffer_size
    self._parallel_shards = parallel_shards
    self._seed = seed
    # Lazy (filenames, format) cache + per-file probe results for
    # _budget_source; resolved on the first budget charge, not in the
    # constructor (subclasses may still be rewriting _file_patterns).
    self._budget_filenames: Optional[tuple] = None
    self._budget_file_ok: dict = {}

  def _budget_retry_on(self):
    """tf.data surfaces corrupt records/files as ``tf.errors.OpError``
    subclasses (DataLossError et al.), which are NOT OSErrors — without
    this, the tf-codec route's error budget never engaged at all."""
    base = super()._budget_retry_on()
    try:
      import tensorflow as tf  # the parse path imports it anyway

      return base + (tf.errors.OpError,)
    except ImportError:
      return base

  def _budget_source(self, exc: BaseException) -> Optional[str]:
    """Per-file budget attribution for the tf-codec parse path, matching
    what the native reader does by construction.

    Two mechanisms, cheapest first: (a) match the failing file out of
    the error text when tf names it (open/NotFound errors do); (b) for
    record-corruption errors that only say "corrupted record at
    <offset>" (``DataLossError``), probe this generator's tfrecord files
    once each with a framing/CRC walk (``records.verify_tfrecord_file``)
    and charge the rotten shard. Probe results are cached per generator
    — each file is scanned at most once, and repeat errors re-charge the
    known-corrupt shards without re-reading anything.
    """
    filenames, fmt = self._resolved_budget_filenames()
    match = pipeline.match_filename_in_error(exc, filenames)
    if match is not None:
      return match
    if fmt != 'tfrecord' or not self._is_corruption_error(exc):
      return None
    from tensor2robot_tpu.data import records

    for path in filenames:
      if path in self._budget_file_ok:
        continue
      if '://' in path:  # remote probe cost is an operator decision
        self._budget_file_ok[path] = True
        continue
      self._budget_file_ok[path] = records.verify_tfrecord_file(path)
    corrupt = [p for p in filenames if not self._budget_file_ok.get(p, True)]
    return corrupt[0] if corrupt else None

  def _resolved_budget_filenames(self):
    if self._budget_filenames is None:
      from tensor2robot_tpu.data import records

      filenames, fmt = [], None
      patterns = self._file_patterns
      for pattern in (patterns.values() if isinstance(patterns, dict)
                      else [patterns]):
        try:
          fmt, resolved = records.get_data_format_and_filenames(pattern)
          filenames.extend(resolved)
        except ValueError:
          pass
      self._budget_filenames = (filenames, fmt)
      self._budget_file_ok = {}
    return self._budget_filenames

  @staticmethod
  def _is_corruption_error(exc: BaseException) -> bool:
    try:
      import tensorflow as tf

      if isinstance(exc, tf.errors.DataLossError):
        return True
    except ImportError:
      pass
    text = str(exc).lower()
    return 'corrupt' in text or 'truncated' in text

  def _make_dataset(self, mode, batch_size):
    """The ONE dataset definition both iterator flavors build from."""
    return pipeline.make_dataset(
        self._file_patterns,
        self._feature_spec,
        self._label_spec,
        mode=mode,
        batch_size=batch_size,
        shuffle_buffer_size=self._shuffle_buffer_size,
        parallel_shards=self._parallel_shards,
        seed=self._seed)

  def _create_iterator(self, mode, batch_size):
    from tensor2robot_tpu.observability import metrics as metrics_lib
    from tensor2robot_tpu.observability import tracing

    dataset = self._make_dataset(mode, batch_size)
    has_labels = self._label_spec is not None

    def iterate():
      batches = metrics_lib.counter('data/tf_batches')
      it = iter(dataset.as_numpy_iterator())
      while True:
        # The train loop's cost of surfacing one tf.data batch (the
        # pipeline's own parse/decode threads run behind this call).
        with tracing.span('data/tf_next', annotate=False):
          try:
            element = next(it)
          except StopIteration:
            return
        batches.inc()
        yield pipeline.pack_numpy_element(element, has_labels)

    return iterate()

  def create_checkpointable_iterator(
      self, mode: str, batch_size: Optional[int] = None
  ) -> 'pipeline.CheckpointableNumpyIterator':
    """Like ``create_iterator`` but with a checkpointable stream position.

    Pair with :class:`~tensor2robot_tpu.train.input_state.
    InputStateCallback` so a restored trainer resumes the data stream
    mid-epoch (shuffle buffer and reader offsets included) instead of
    restarting it.
    """
    if self._feature_spec is None:
      raise ValueError(
          'Input generator has no specs; call set_specification(_from_model) '
          'first.')
    return pipeline.CheckpointableNumpyIterator(
        self._make_dataset(mode, batch_size or self._batch_size),
        has_labels=self._label_spec is not None)


class FractionalRecordInputGenerator(DefaultRecordInputGenerator):
  """Data-ablation input: only the first ``file_fraction`` of files.

  Reference: ``default_input_generator.py:118-137``.
  """

  def __init__(self, file_fraction: float = 1.0, **kwargs):
    super().__init__(**kwargs)
    if not 0.0 < file_fraction <= 1.0:
      raise ValueError(f'file_fraction must be in (0, 1], got {file_fraction}')
    if isinstance(self._file_patterns, str):
      from tensor2robot_tpu.data import records

      data_format, filenames = records.get_data_format_and_filenames(
          self._file_patterns)
      n = max(1, int(file_fraction * len(filenames)))
      # Keep the explicit format prefix: resolved filenames may not carry
      # the format in their basename.
      self._file_patterns = ','.join(
          f'{data_format}:{f}' for f in filenames[:n])


class MultiEvalRecordInputGenerator(DefaultRecordInputGenerator):
  """Eval dataset selected by name from a dataset map.

  The reference reads ``multi_eval_name`` from the TF_CONFIG env var
  (``default_input_generator.py:141-153``); we accept it directly or from the
  ``T2R_MULTI_EVAL_NAME`` env var.
  """

  def __init__(self, eval_dataset_map: Dict[str, str],
               multi_eval_name: Optional[str] = None, **kwargs):
    multi_eval_name = multi_eval_name or os.environ.get(
        'T2R_MULTI_EVAL_NAME')
    if not multi_eval_name:
      # Match the reference's TF_CONFIG fallback for drop-in parity.
      tf_config = json.loads(os.environ.get('TF_CONFIG', '{}'))
      multi_eval_name = tf_config.get('multi_eval_name')
    if not multi_eval_name:
      raise ValueError('MultiEvalRecordInputGenerator needs multi_eval_name.')
    if multi_eval_name not in eval_dataset_map:
      raise ValueError(
          f'Unknown eval dataset {multi_eval_name!r}; available: '
          f'{sorted(eval_dataset_map)}')
    super().__init__(
        file_patterns=eval_dataset_map[multi_eval_name], **kwargs)
    self.multi_eval_name = multi_eval_name


class NativeRecordInputGenerator(AbstractInputGenerator):
  """TF-free record input on the native C++ runtime.

  Reads TFRecord files with the native interleaved prefetch reader
  (``native/record_io.cpp``), parses tf.Examples with the native
  wire-format parser, and decodes images with PIL — no TensorFlow in the
  loop (the robot/serving-host story: a predictor plus this generator
  needs only numpy + PIL + a C++ toolchain). Restricted to the
  context-feature subset the native parser supports
  (``native_io.NativeExampleParser.supports``); use
  :class:`DefaultRecordInputGenerator` for SequenceExample or
  multi-dataset specs.

  Batches are produced by the parallel host input engine
  (``data/engine.py``): ``engine_workers`` pipeline workers run
  parse+decode for DIFFERENT batches concurrently, with a reorder stage
  guaranteeing the delivered stream is byte-identical to the serial
  path for any worker count. ``engine_workers=None`` autotunes
  (core-aware; collapses to the serial inline path on 1-core hosts),
  ``0`` forces serial.

  The stream is fully deterministic (strict round-robin interleave +
  seeded shuffle), so — with a seed — its position is well-defined and
  :meth:`create_checkpointable_iterator` supports mid-epoch resume:
  restore replays the record stream to the saved batch count (read-only
  fast-forward, no parse/decode) and continues bit-exactly.

  **Follow mode** (``follow=`` a ``data/follow.FollowConfig`` or a
  directory path) replaces the static interleave with a live tail of a
  GROWING shard directory (``data/follow.py``): only commit-marked
  shards are ingested, records are sampled from a bounded
  replay-buffer-style window (the window IS the shuffle; the static
  shuffle buffer is bypassed), and off-policy staleness is gauged under
  ``data/follow/*``. Torn/unreadable shards skip loudly through the
  follow stream's own error budget (``FollowConfig.error_budget``), so
  the generator-level ``error_budget`` must stay None in follow mode.
  The stream has no checkpointable position — a restarted trainer
  re-enters the live window — so
  :meth:`create_checkpointable_iterator` refuses.
  """

  def __init__(self,
               file_patterns: str,
               batch_size: int = 32,
               shuffle_buffer_size: int = 1000,
               cycle_length: int = 16,
               queue_capacity: int = 64,
               decode_workers: int = 8,
               seed: Optional[int] = None,
               error_budget: Optional[int] = None,
               open_retries: int = 3,
               engine_workers: Optional[int] = None,
               engine_ring_depth: Optional[int] = None,
               reuse_batch_buffers: bool = False,
               engine_reautotune: Optional[bool] = None,
               follow=None):
    super().__init__(batch_size, error_budget=error_budget)
    if not file_patterns:
      raise ValueError('Provide file_patterns.')
    if follow is not None:
      from tensor2robot_tpu.data import follow as follow_lib

      if isinstance(follow, str):
        follow = follow_lib.FollowConfig(directory=follow)
      if error_budget is not None:
        raise ValueError(
            'follow mode owns its error budget (FollowConfig.error_budget); '
            'pass error_budget=None on the generator.')
    self._follow = follow
    # The live follow stream behind the most recent iterator (follow
    # mode only): exposes close() and the drill accounting surface.
    self.follow_stream = None
    self._file_patterns = file_patterns
    self._shuffle_buffer_size = shuffle_buffer_size
    self._cycle_length = cycle_length
    self._queue_capacity = queue_capacity
    self._decode_workers = decode_workers
    self._seed = seed
    self._open_retries = open_retries
    self._engine_workers = engine_workers
    self._engine_ring_depth = engine_ring_depth
    # Ring-slot reuse: delivered image arrays are views of recycled
    # buffers and the CONSUMER must call engine.release() per batch —
    # the Trainer does this automatically at H2D transfer completion
    # (its placement stage / inline place path detects the release hook);
    # other callers must honor the contract themselves (data/engine.py).
    self._reuse_batch_buffers = reuse_batch_buffers
    # Mid-run re-autotune (data/engine.py): re-evaluate the worker count
    # at trainer log-window crossings from the live breakdown signals.
    # None = on exactly when the worker count itself was autotuned — an
    # explicit engine_workers is an operator decision the engine honors.
    self._engine_reautotune = (engine_workers is None
                               if engine_reautotune is None
                               else bool(engine_reautotune))

  def _resolved_filenames(self):
    """This process's shard list IN STREAM ORDER plus the shard flavor:
    ``(filenames, element_shard)`` — the one resolution both the live
    stream and the seek-resume position math must agree on."""
    from tensor2robot_tpu.data import records

    data_format, filenames = records.get_data_format_and_filenames(
        self._file_patterns)
    if data_format != 'tfrecord':
      raise ValueError(f'Native reader supports tfrecord, got {data_format}')
    filenames, sharded = pipeline.shard_filenames_for_process(filenames)
    import jax

    element_shard = not sharded and jax.process_count() > 1
    if element_shard:
      filenames = sorted(filenames)
    return filenames, element_shard

  def _records(self, mode: str, resume=None):
    """Yields raw serialized examples forever (train) or one epoch.

    With ``error_budget`` set, a RECORD-level ``ErrorBudget`` is shared
    across reader reopens: a corrupt record ends the current interleave
    pass (framing cannot resync) and the train loop's reopen continues
    on the surviving bytes, bounded by the budget; reader OPENS are
    additionally retried with jittered backoff (transient filesystem
    errors should not kill a multi-day run).

    ``resume`` (a ``seek_resume.ResumePlan``) starts the stream
    mid-epoch: the PARTIAL epoch runs through per-slot readers seeked
    via the shard index (byte-identical order to the native interleave,
    no prefetch threads — it lasts at most one epoch), after which full
    epochs go back through the native prefetching interleave reader.
    """
    from tensor2robot_tpu.data import native_io, records, seek_resume
    from tensor2robot_tpu.utils import retry as retry_lib

    filenames, element_shard = self._resolved_filenames()
    import jax

    process_count = jax.process_count()
    process_index = jax.process_index()
    training = modes.is_training(mode)
    read_budget = None
    if self._error_budget is not None:
      read_budget = retry_lib.ErrorBudget(
          self._error_budget, name=f'{type(self).__name__} record stream')
    open_policy = retry_lib.RetryPolicy(max_attempts=max(1,
                                                         self._open_retries))
    if resume is not None:
      if not training and resume.epoch > 0:
        return  # single-pass stream already exhausted at the position
      indexes = resume.indexes or {}

      def open_reader(path, ordinal):
        return records.open_at(path, ordinal, index=indexes.get(path))

      for within, record in seek_resume.iter_epoch_from(
          resume.layout, resume.files, resume.within_epoch, open_reader):
        if element_shard and within % process_count != process_index:
          continue
        yield record
      if not training:
        return
    while True:
      reader = retry_lib.retry_call(
          native_io.NativeInterleaveReader,
          filenames,
          cycle_length=self._cycle_length,
          queue_capacity=self._queue_capacity,
          error_budget=read_budget,
          policy=open_policy,
          describe='native interleave open')
      with reader:
        for i, record in enumerate(reader):
          if element_shard and i % process_count != process_index:
            continue
          yield record
      if not training:
        return

  def _create_iterator(self, mode, batch_size):
    return self._build_batches(mode, batch_size)

  def _build_batches(self, mode, batch_size, skip_batches: int = 0,
                     resume=None, start_delivered: Optional[int] = None):
    """The ONE batch pipeline both iterator flavors build from:
    interleaved read → seeded shuffle → engine (ticket-parallel
    parse/decode, order-preserving). ``skip_batches`` fast-forwards the
    deterministic stream by consuming (without parsing) the records the
    first N batches would have used — the O(position) replay restore.
    ``resume`` (a ``seek_resume.ResumePlan``) is the O(1) restore: the
    shuffle buffer arrives pre-filled by indexed reads, the rng already
    advanced, and the raw stream starts at a seeked mid-epoch position —
    the delivered stream is byte-identical to the replay path."""
    import itertools

    from tensor2robot_tpu.data import engine as engine_lib
    from tensor2robot_tpu.data import native_io

    parse_fn = native_io.make_native_parse_fn(
        self._feature_spec, self._label_spec,
        decode_workers=self._decode_workers)
    if parse_fn is None:
      raise ValueError(
          'Specs not natively parseable (sequence/multi-dataset/'
          'multi-image features, or no C++ toolchain); use '
          'DefaultRecordInputGenerator.')
    if self._follow is not None:
      if skip_batches or resume is not None:
        raise ValueError(
            'follow-mode streams have no checkpointable position; '
            'a restarted trainer re-enters the live window.')
      from tensor2robot_tpu.data import follow as follow_lib

      self.follow_stream = follow_lib.FollowStream(
          self._follow, batch_size=batch_size)
      decision = engine_lib.autotune(self._engine_workers,
                                     self._engine_ring_depth)
      return engine_lib.ParallelBatchEngine(
          iter(self.follow_stream), parse_fn, batch_size,
          num_workers=decision.num_workers,
          ring_depth=decision.ring_depth,
          reuse_buffers=self._reuse_batch_buffers,
          reautotune=self._engine_reautotune)
    training = modes.is_training(mode)
    shuffling = training and self._shuffle_buffer_size > 1
    if start_delivered is None:
      start_delivered = skip_batches

    def stream():
      if not shuffling:
        yield from self._records(mode, resume=resume)
        return
      if resume is None:
        rng = np.random.RandomState(self._seed)
        buf = []
      else:
        # The buffer and rng resume EXACTLY where the saved position
        # left them, so the refill loop below continues the same
        # deterministic emission sequence.
        rng = resume.rng
        buf = list(resume.buffer)
      for record in self._records(mode, resume=resume):
        if len(buf) < self._shuffle_buffer_size:
          buf.append(record)
          continue
        i = rng.randint(len(buf))
        yield buf[i]
        buf[i] = record
      while buf:  # unreachable for train (infinite), kept for safety
        yield buf.pop(rng.randint(len(buf)))

    records = stream()
    if skip_batches:
      # Post-shuffle skip: exactly the records batches [0, N) consumed,
      # so the next delivered batch is bit-identical to batch N of an
      # uninterrupted run. Read + shuffle replay only — no parse/decode.
      records = itertools.islice(records, skip_batches * batch_size, None)
    decision = engine_lib.autotune(self._engine_workers,
                                   self._engine_ring_depth)
    return engine_lib.ParallelBatchEngine(
        records, parse_fn, batch_size,
        num_workers=decision.num_workers,
        ring_depth=decision.ring_depth,
        reuse_buffers=self._reuse_batch_buffers,
        reautotune=self._engine_reautotune,
        start_delivered=start_delivered)

  def create_checkpointable_iterator(
      self, mode: str, batch_size: Optional[int] = None
  ) -> '_CheckpointableEngineIterator':
    """Engine-fed iterator whose STREAM POSITION checkpoints.

    The native stream is a deterministic function of (files, seed,
    batch size), so its position is the delivered-batch count. Restore
    is CONSTANT-TIME at any depth when shard-index sidecars are valid
    (``data/shard_index.py``: per-record byte offsets, built
    opportunistically here on first use): the shuffle buffer and rng
    are reconstructed by closed-form position math plus ≤ buffer_size
    indexed reads, and each reader seeks straight to its record
    boundary. A missing/stale index degrades LOUDLY
    (``data/resume_fallbacks`` counter + warning) to the legacy
    O(position) replay — identical bytes either way, never a wrong
    stream. Requires a ``seed`` when shuffling. Same prefetch caveat as
    the tf.data flavor (``train/input_state.py``): run
    ``prefetch_batches=0`` when bit-exact resume matters.
    """
    if self._feature_spec is None:
      raise ValueError(
          'Input generator has no specs; call set_specification(_from_model) '
          'first.')
    if self._follow is not None:
      raise ValueError(
          'follow-mode streams are not positional (a live window has no '
          'replayable position); use create_iterator — a restarted '
          'trainer re-enters the window.')
    if (modes.is_training(mode) and self._shuffle_buffer_size > 1 and
        self._seed is None):
      raise ValueError(
          'create_checkpointable_iterator needs a seed when shuffling: '
          'an unseeded shuffle cannot be replayed bit-exactly on resume.')
    return _CheckpointableEngineIterator(
        self, mode, batch_size or self._batch_size)

  def _maybe_build_indexes(self) -> Dict[str, object]:
    """Opportunistic sidecar build for this stream's shards.

    Returns ``{path: ShardIndex}`` for every shard that could be
    indexed (loaded if a valid sidecar exists, else one header-only
    framing walk + best-effort atomic write). Shards that cannot be
    indexed (remote schemes, scan errors) are simply absent — save
    then records the stream as replay-only and restore stays on the
    legacy path. ``T2R_SHARD_INDEX_DISABLE=1`` opts out entirely.
    """
    from tensor2robot_tpu.data import shard_index

    if os.environ.get('T2R_SHARD_INDEX_DISABLE'):
      return {}
    indexes: Dict[str, object] = {}
    filenames, _ = self._resolved_filenames()
    for path in filenames:
      if '://' in path:
        continue  # remote shards: offline `tools/index_shards.py` only
      try:
        indexes[path] = shard_index.ensure_index(path)
      except (OSError, shard_index.IndexError_) as e:
        logging.warning('Cannot index shard %r (%s); deep-position '
                        'resume will replay.', path, e)
    return indexes


class _SeekUnavailable(Exception):
  """Why an O(1) seek restore degraded to the O(position) replay."""


class _CheckpointableEngineIterator:
  """Resumable position tracking over the native engine pipeline.

  Same save/restore surface as ``pipeline.CheckpointableNumpyIterator``
  (``train/input_state.py`` drives both): ``save`` writes a tiny JSON
  position next to the model checkpoint; ``restore`` rebuilds the
  engine at the saved position — an O(1) index-seek when every shard's
  sidecar validates (v2 states carry the stream fingerprint: files,
  per-shard record counts, seed/shuffle/cycle config), else the legacy
  O(position) read-only replay, loudly. The lock makes position capture
  atomic against a prefetch worker's concurrent ``next()``.
  """

  def __init__(self, generator: NativeRecordInputGenerator, mode: str,
               batch_size: int):
    import threading

    self._generator = generator
    self._mode = mode
    self._batch_size = batch_size
    self._delivered = 0  # GUARDED_BY(self._lock)
    self._lock = threading.Lock()
    # Opportunistic: the first resumable stream over a corpus leaves
    # index sidecars behind, so every later restore is a seek.
    self._indexes = generator._maybe_build_indexes()  # pylint: disable=protected-access
    self._engine = generator._build_batches(mode, batch_size)  # pylint: disable=protected-access  # GUARDED_BY(self._lock)

  def __iter__(self):
    return self

  def __next__(self) -> Batch:
    with self._lock:
      batch = next(self._engine)
      self._delivered += 1
      return batch

  def release(self) -> None:
    """Ring-buffer lease release, delegated to the engine (the trainer
    detects this hook on its input iterator — see ``Trainer.train``)."""
    # ANALYSIS_OK(lock-discipline): taking the position lock here would
    # deadlock — __next__ holds it while blocked on the ring waiting for
    # THIS release (placement thread). The engine ref only changes in
    # restore(), which runs before the consuming threads start.
    self._engine.release()

  def _stream_fingerprint(self) -> dict:
    """The v2 'stream' block: everything restore needs to decide seek
    vs replay. Per-shard counts come from the sidecars and each sidecar
    is re-validated (O(1) stat + sampled CRC) at SAVE time, so a shard
    rewritten mid-run can never masquerade as seekable."""
    from tensor2robot_tpu.data import shard_index

    gen = self._generator
    filenames, element_shard = gen._resolved_filenames()  # pylint: disable=protected-access
    import jax

    counts = []
    seekable = True
    reason = None
    for path in filenames:
      index = self._indexes.get(path)
      if index is None:
        seekable, reason = False, f'no index for {path}'
        break
      try:
        shard_index.validate_index(index, path)
      except shard_index.StaleIndexError as e:
        seekable, reason = False, str(e)
        break
      counts.append(index.record_count)
    return {
        'version': 2,
        'seekable': seekable,
        'reason': reason,
        'files': filenames,
        'record_counts': counts if seekable else None,
        'seed': gen._seed,  # pylint: disable=protected-access
        'shuffle_buffer_size': gen._shuffle_buffer_size,  # pylint: disable=protected-access
        'cycle_length': gen._cycle_length,  # pylint: disable=protected-access
        'element_shard': element_shard,
        'process_count': jax.process_count(),
        'process_index': jax.process_index(),
    }

  def save(self, path_prefix: str) -> str:
    path = path_prefix + '.json'
    dirname = os.path.dirname(path)
    if dirname:
      os.makedirs(dirname, exist_ok=True)
    stream = self._stream_fingerprint()
    with self._lock:
      state = {'batches_delivered': self._delivered,
               'batch_size': self._batch_size, 'mode': self._mode,
               'stream': stream}
    with open(path, 'w') as f:
      json.dump(state, f)
    return path

  def _seek_plan(self, state):
    """Builds the O(1) resume plan, or raises with a fallback reason."""
    from tensor2robot_tpu.data import records, seek_resume, shard_index

    gen = self._generator
    stream = state.get('stream') or {}
    if not stream.get('seekable'):
      raise _SeekUnavailable(
          stream.get('reason') or 'state has no seekable stream block '
          '(saved by an older version?)')
    filenames, element_shard = gen._resolved_filenames()  # pylint: disable=protected-access
    import jax

    config = {
        'files': filenames,
        'seed': gen._seed,  # pylint: disable=protected-access
        'shuffle_buffer_size': gen._shuffle_buffer_size,  # pylint: disable=protected-access
        'cycle_length': gen._cycle_length,  # pylint: disable=protected-access
        'element_shard': element_shard,
        'process_count': jax.process_count(),
        'process_index': jax.process_index(),
    }
    for key, value in config.items():
      if stream.get(key) != value:
        raise _SeekUnavailable(
            f'stream config changed since save: {key} was '
            f'{stream.get(key)!r}, now {value!r}')
    indexes = {}
    for path, saved_count in zip(filenames, stream['record_counts']):
      try:
        index = shard_index.load_index(path)
      except FileNotFoundError as e:
        raise _SeekUnavailable(f'missing shard index: {path}') from e
      except shard_index.StaleIndexError as e:
        raise _SeekUnavailable(f'stale shard index: {e}') from e
      except (OSError, shard_index.IndexError_) as e:
        raise _SeekUnavailable(f'unreadable shard index: {e}') from e
      if index.record_count != saved_count:
        raise _SeekUnavailable(
            f'{path}: {index.record_count} records now vs {saved_count} '
            f'at save time')
      indexes[path] = index
    emitted = int(state['batches_delivered']) * self._batch_size
    shuffled = (modes.is_training(self._mode) and
                gen._shuffle_buffer_size > 1)  # pylint: disable=protected-access
    stride = (config['process_count'], config['process_index']) \
        if element_shard else (1, 0)
    plan = seek_resume.plan_resume(
        files=filenames,
        counts=stream['record_counts'],
        cycle_length=gen._cycle_length,  # pylint: disable=protected-access
        seed=gen._seed,  # pylint: disable=protected-access
        shuffle_buffer_size=gen._shuffle_buffer_size,  # pylint: disable=protected-access
        records_emitted=emitted,
        shuffled=shuffled,
        fetch=lambda path, ords: records.read_records_at(
            path, ords, index=indexes[path]),
        process_count=stride[0],
        process_index=stride[1])
    plan.indexes = indexes
    return plan

  def restore(self, path_prefix: str, allow_seek: bool = True) -> None:
    """Rebuilds the pipeline at the saved position.

    Seek path (O(1) at any depth) when the state is v2-seekable and
    every sidecar validates; otherwise the legacy O(position) replay —
    LOUDLY (``data/resume_fallbacks`` + warning), byte-identical either
    way. ``allow_seek=False`` forces the replay path (bench A/B).
    Publishes ``data/resume_ms``, ``data/resume_seek_mode`` and
    ``data/resume_replayed_records``.
    """
    import time

    from tensor2robot_tpu.observability import metrics as metrics_lib

    t0 = time.perf_counter()
    with open(path_prefix + '.json') as f:
      state = json.load(f)
    if state.get('batch_size') != self._batch_size:
      raise ValueError(
          f'Input state was saved with batch_size='
          f'{state.get("batch_size")}, but this iterator uses '
          f'{self._batch_size}; the stream positions are incompatible.')
    plan = None
    if allow_seek:
      try:
        plan = self._seek_plan(state)
      except _SeekUnavailable as e:
        metrics_lib.counter('data/resume_fallbacks').inc()
        logging.warning(
            'Deep-position seek resume unavailable (%s); falling back '
            'to the O(position) replay of %d batches.', e,
            int(state['batches_delivered']))
    delivered = int(state['batches_delivered'])
    with self._lock:
      self._engine.close()
      self._delivered = delivered
      if plan is not None:
        self._engine = self._generator._build_batches(  # pylint: disable=protected-access
            self._mode, self._batch_size, resume=plan,
            start_delivered=delivered)
        replayed = 0
      else:
        self._engine = self._generator._build_batches(  # pylint: disable=protected-access
            self._mode, self._batch_size, skip_batches=delivered)
        replayed = delivered * self._batch_size
    metrics_lib.gauge('data/resume_ms').set(
        (time.perf_counter() - t0) * 1e3)
    metrics_lib.gauge('data/resume_seek_mode').set(
        1 if plan is not None else 0)
    metrics_lib.gauge('data/resume_replayed_records').set(replayed)
    logging.info(
        'Input stream restored at batch %d via %s (%.1f ms).', delivered,
        'index seek' if plan is not None else 'replay',
        (time.perf_counter() - t0) * 1e3)

  def close(self) -> None:
    # ANALYSIS_OK(lock-discipline): same no-lock contract as release();
    # close is idempotent and the engine ref is stable once consuming.
    self._engine.close()


class TaskGroupedRecordInputGenerator(AbstractInputGenerator):
  """Per-task file interleave feeding MAML's meta-batch layout.

  Each record FILE holds one task's examples (base model specs on disk).
  Every meta batch groups ``num_train_samples_per_task`` condition +
  ``num_val_samples_per_task`` inference examples per task, for
  ``batch_size`` tasks:

  * ``condition/features/*``, ``condition/labels/*`` —
    [tasks, num_train, ...]
  * ``inference/features/*`` — [tasks, num_val, ...]
  * labels — the inference examples' labels, [tasks, num_val, ...]

  Capability-equivalent of the reference's task-grouped ``parallel_read``
  (``meta_learning/meta_tfdata.py:37-132``) feeding ``MAMLPreprocessorV2``.
  """

  def __init__(self,
               file_patterns: str,
               num_train_samples_per_task: int = 4,
               num_val_samples_per_task: int = 4,
               shuffle_buffer_size: int = 50,
               interleave_cycle_length: Optional[int] = None,
               batch_size: int = 4,
               seed: Optional[int] = None):
    super().__init__(batch_size)
    self._file_patterns = file_patterns
    self._num_train = num_train_samples_per_task
    self._num_val = num_val_samples_per_task
    self._shuffle_buffer_size = shuffle_buffer_size
    self._interleave_cycle_length = interleave_cycle_length
    self._seed = seed
    self._base_feature_spec: Optional[SpecStruct] = None
    self._base_label_spec: Optional[SpecStruct] = None

  def set_specification_from_model(self, model, mode: str) -> None:
    """Pulls BASE specs (the on-disk record contract) from the wrapped
    preprocessor; the meta layout is reassembled by this generator."""
    super().set_specification_from_model(model, mode)
    preprocessor = model.preprocessor
    # Unwrap dtype-policy and MAML wrappers down to the base preprocessor.
    while hasattr(preprocessor, 'base_preprocessor'):
      preprocessor = preprocessor.base_preprocessor
    self._base_feature_spec = algebra.flatten_spec_structure(
        preprocessor.get_in_feature_specification(mode))
    self._base_label_spec = algebra.flatten_spec_structure(
        preprocessor.get_in_label_specification(mode))

  def _create_iterator(self, mode, batch_size):
    if self._base_feature_spec is None:
      raise ValueError(
          'TaskGroupedRecordInputGenerator needs base specs; call '
          'set_specification_from_model first.')
    num_train = self._num_train

    dataset = pipeline.make_task_grouped_dataset(
        self._file_patterns,
        self._base_feature_spec,
        self._base_label_spec,
        mode=mode,
        task_batch_size=batch_size,
        num_train_samples_per_task=num_train,
        num_val_samples_per_task=self._num_val,
        shuffle_buffer_size=self._shuffle_buffer_size,
        interleave_cycle_length=self._interleave_cycle_length,
        seed=self._seed)

    def iterate():
      for features, labels in dataset.as_numpy_iterator():
        meta = SpecStruct()
        for key, value in features.items():
          meta[f'condition/features/{key}'] = value[:, :num_train]
          meta[f'inference/features/{key}'] = value[:, num_train:]
        for key, value in labels.items():
          meta[f'condition/labels/{key}'] = value[:, :num_train]
        meta_labels = SpecStruct(
            {key: value[:, num_train:] for key, value in labels.items()})
        yield meta, meta_labels

    return iterate()


class GeneratorInputGenerator(AbstractInputGenerator):
  """Batches produced by a user-supplied python generator of examples.

  The generator must yield (features, labels) tuples of spec-shaped,
  unbatched numpy structures. Reference:
  ``default_input_generator.py:156-206``.
  """

  def __init__(self,
               generator_fn: Callable[[], Iterator],
               sequence_length: Optional[int] = None,
               batch_size: int = 32):
    super().__init__(batch_size)
    self._generator_fn = generator_fn
    self._sequence_length = sequence_length

  def _create_iterator(self, mode, batch_size):
    feature_spec, label_spec = self._feature_spec, self._label_spec

    def iterate():
      source = self._generator_fn()
      while True:
        feature_batches, label_batches = [], []
        for _ in range(batch_size):
          try:
            features, labels = next(source)
          except StopIteration:
            source = self._generator_fn()
            features, labels = next(source)
          feature_batches.append(algebra.flatten_spec_structure(features))
          label_batches.append(algebra.flatten_spec_structure(labels))

        def fit_sequence(array, spec):
          """Pads/clips a sequence example's time dim to sequence_length."""
          if (self._sequence_length is None or
              not getattr(spec, 'is_sequence', False)):
            return array
          length = array.shape[0]
          if length >= self._sequence_length:
            return array[:self._sequence_length]
          padding = np.zeros(
              (self._sequence_length - length,) + array.shape[1:],
              dtype=array.dtype)
          return np.concatenate([array, padding], axis=0)

        def stack(batches, spec):
          if spec is None:
            return None
          out = SpecStruct()
          for key in batches[0]:
            out[key] = np.stack([
                fit_sequence(np.asarray(b[key]), spec.get(key))
                for b in batches
            ])
          return algebra.validate_and_pack(spec, out, ignore_batch=True)

        yield stack(feature_batches, feature_spec), stack(
            label_batches, label_spec)

    return iterate()


class _SyntheticInputGenerator(AbstractInputGenerator):
  """Base for random/constant synthetic data (tests & smoke training)."""

  def __init__(self, sequence_length: int = 3, batch_size: int = 32):
    super().__init__(batch_size)
    self._sequence_length = sequence_length

  def _make_batch(self, spec, batch_size, seed):
    raise NotImplementedError

  def _create_iterator(self, mode, batch_size):
    def iterate():
      seed = 0
      while True:
        features = self._make_batch(self._feature_spec, batch_size, seed)
        labels = (None if self._label_spec is None else
                  self._make_batch(self._label_spec, batch_size, seed + 1))
        seed += 2
        yield features, labels

    return iterate()


class DefaultRandomInputGenerator(_SyntheticInputGenerator):
  """Random spec-conformant batches. Reference: :210-223."""

  def _make_batch(self, spec, batch_size, seed):
    return algebra.validate_and_pack(
        spec,
        numpy_gen.make_random_numpy(
            spec, batch_size=batch_size,
            sequence_length=self._sequence_length, seed=seed),
        ignore_batch=True)


class DefaultConstantInputGenerator(_SyntheticInputGenerator):
  """Constant spec-conformant batches. Reference: :226-238."""

  def __init__(self, constant_value: float, **kwargs):
    super().__init__(**kwargs)
    self._constant_value = constant_value

  def _make_batch(self, spec, batch_size, seed):
    return algebra.validate_and_pack(
        spec,
        numpy_gen.make_constant_numpy(
            spec, self._constant_value, batch_size=batch_size,
            sequence_length=self._sequence_length),
        ignore_batch=True)
