"""TF-free TFRecord IO on the native C++ runtime (ctypes bindings).

Wire-format-compatible with ``tf.io.TFRecordWriter`` / ``TFRecordDataset``
(CRC32C-framed records), so files interchange freely with the TF-based
pipeline. The interleave reader overlaps disk IO with training via one
prefetch thread per file (the role tf.data's C++ runtime plays for the
reference, ``utils/tfdata.py:43-66``).

All classes raise ``RuntimeError`` if the native library is unavailable;
call ``available()`` first or use the ``records.RecordWriter`` facade,
which falls back to TF automatically.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Iterator, List, Optional, Sequence

from tensor2robot_tpu import native
from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.observability import tracing

# Per-record counters batch locally and flush every N records: one lock
# acquire per record would tax the multi-GB/s interleave reader; one per
# 256 is noise. (Registry counts lag the live stream by <N records.)
_COUNTER_FLUSH_EVERY = 256


def _charge_read_error(err: str) -> None:
  """Registry accounting for one reader-level error."""
  metrics_lib.counter('data/read_errors').inc()
  if 'crc mismatch' in err:
    metrics_lib.counter('data/crc_errors').inc()


def available() -> bool:
  return native.load_record_io() is not None


def _lib() -> ctypes.CDLL:
  lib = native.load_record_io()
  if lib is None:
    raise RuntimeError('native record_io library unavailable '
                       '(no toolchain, or T2R_NATIVE_DISABLE set)')
  return lib


def masked_crc32c(data: bytes) -> int:
  return _lib().t2r_masked_crc32c(data, len(data))


class NativeRecordWriter:
  """Appends TFRecord-framed records to a file."""

  def __init__(self, path: str, append: bool = False):
    self._lib = _lib()
    self._h = self._lib.t2r_writer_open(
        path.encode(), b'a' if append else b'w')
    if not self._h:
      raise IOError(f'cannot open {path!r} for writing')

  def write(self, serialized: bytes) -> None:
    if self._lib.t2r_writer_write(self._h, serialized, len(serialized)):
      raise IOError('short write')

  def flush(self) -> None:
    self._lib.t2r_writer_flush(self._h)

  def close(self) -> None:
    if self._h:
      self._lib.t2r_writer_close(self._h)
      self._h = None

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


class NativeRecordReader:
  """Sequential reader with CRC verification.

  ``error_budget`` (a ``utils.retry.ErrorBudget``) bounds tolerated read
  errors: a corrupt record breaks TFRecord framing irrecoverably, so a
  within-budget error is charged, logged, and the file is treated as
  truncated at that point (records before the corruption were already
  yielded) — the budget raises loudly once spent. Without a budget, read
  errors raise immediately (historical behavior).
  """

  def __init__(self, path: str, verify_crc: bool = True,
               error_budget: Optional['retry_lib.ErrorBudget'] = None,
               start_offset: int = 0):
    self._lib = _lib()
    self._path = path
    self._error_budget = error_budget
    self._h = self._lib.t2r_reader_open(path.encode(), int(verify_crc))
    if not self._h:
      raise IOError(f'cannot open {path!r}')
    if start_offset:
      self.seek(start_offset)

  def seek(self, offset: int) -> None:
    """Repositions to an absolute byte offset — a record boundary from a
    shard-index sidecar (``data/shard_index.py``); a mid-record offset
    surfaces as a framing/CRC error on the next read, never silence."""
    if self._lib.t2r_reader_seek(self._h, int(offset)):
      raise IOError(
          f'seek to offset {offset} failed in {self._path!r}: '
          f'{self._lib.t2r_reader_error(self._h).decode()}')

  def read_next(self) -> Optional[bytes]:
    """One record (or None at EOF) — the indexed-read primitive
    ``records.read_records_at`` drives between seeks."""
    buf = ctypes.POINTER(ctypes.c_uint8)()
    n = self._lib.t2r_reader_next(self._h, ctypes.byref(buf))
    if n == -1:
      return None
    if n == -2:
      err = self._lib.t2r_reader_error(self._h).decode()
      _charge_read_error(err)
      raise IOError(f'record read failed in {self._path!r}: {err}')
    metrics_lib.counter('data/records_read').inc()
    metrics_lib.counter('data/bytes_read').inc(n)
    return ctypes.string_at(buf, n)

  def __iter__(self) -> Iterator[bytes]:
    buf = ctypes.POINTER(ctypes.c_uint8)()
    m_records = metrics_lib.counter('data/records_read')
    m_bytes = metrics_lib.counter('data/bytes_read')
    pending_records = pending_bytes = 0
    try:
      while True:
        n = self._lib.t2r_reader_next(self._h, ctypes.byref(buf))
        if n == -1:
          return
        if n == -2:
          err = self._lib.t2r_reader_error(self._h).decode()
          _charge_read_error(err)
          exc = IOError(f'record read failed: {err}')
          if self._error_budget is None:
            raise exc
          # This reader KNOWS its file — charge the budget per source.
          self._error_budget.record(exc, source=self._path)
          logging.warning(
              'Treating %r as truncated after a framing-breaking read '
              'error.', self._path)
          return
        pending_records += 1
        pending_bytes += n
        if pending_records >= _COUNTER_FLUSH_EVERY:
          m_records.inc(pending_records)
          m_bytes.inc(pending_bytes)
          pending_records = pending_bytes = 0
        yield ctypes.string_at(buf, n)
    finally:
      if pending_records:
        m_records.inc(pending_records)
        m_bytes.inc(pending_bytes)

  def close(self) -> None:
    if self._h:
      self._lib.t2r_reader_close(self._h)
      self._h = None

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


class NativeInterleaveReader:
  """Round-robin (block_length=1) reader over many files.

  ``cycle_length`` native prefetch threads (slot ``s`` owns files
  ``s, s+C, s+2C, …``) keep bounded queues full, so thread count and
  queue memory stay fixed regardless of shard count and ``__iter__``
  never touches the filesystem on the consumer thread.
  """

  def __init__(self, paths: Sequence[str], cycle_length: int = 16,
               queue_capacity: int = 64, verify_crc: bool = True,
               error_budget: Optional['retry_lib.ErrorBudget'] = None):
    if not paths:
      raise ValueError('need at least one path')
    self._lib = _lib()
    self._error_budget = error_budget
    arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
    self._h = self._lib.t2r_interleave_open(
        arr, len(paths), cycle_length, queue_capacity, int(verify_crc))
    if not self._h:
      raise IOError('cannot open interleave reader')

  def __iter__(self) -> Iterator[bytes]:
    buf = ctypes.POINTER(ctypes.c_uint8)()
    m_records = metrics_lib.counter('data/records_read')
    m_bytes = metrics_lib.counter('data/bytes_read')
    pending_records = pending_bytes = 0
    try:
      while True:
        n = self._lib.t2r_interleave_next(self._h, ctypes.byref(buf))
        if n == -1:
          return
        if n == -2:
          err = self._lib.t2r_interleave_error(self._h).decode()
          _charge_read_error(err)
          exc = IOError(f'interleave read failed: {err}')
          if self._error_budget is None:
            raise exc
          # A read error poisons the whole interleave (the failing slot
          # cannot resync mid-file): charge the budget and end this pass;
          # callers that loop epochs (train) reopen and continue on the
          # surviving bytes, bounded by the shared budget. The failing
          # FILE rides in the native error text ("<path>: <reason>"), so
          # the budget's source attribution resolves it from the message.
          self._error_budget.record(exc)  # raises once the budget is spent
          logging.warning(
              'Ending interleave pass early after a read error (budget '
              'remaining: %d).', self._error_budget.remaining)
          return
        pending_records += 1
        pending_bytes += n
        if pending_records >= _COUNTER_FLUSH_EVERY:
          m_records.inc(pending_records)
          m_bytes.inc(pending_bytes)
          pending_records = pending_bytes = 0
        yield ctypes.string_at(buf, n)
    finally:
      if pending_records:
        m_records.inc(pending_records)
        m_bytes.inc(pending_bytes)

  def close(self) -> None:
    if self._h:
      self._lib.t2r_interleave_close(self._h)
      self._h = None

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


def read_records(path: str) -> List[bytes]:
  """Reads every record of one file (convenience for tools/tests)."""
  with NativeRecordReader(path) as r:
    return list(r)


def iter_records_from(path: str, offset: int = 0,
                      verify_crc: bool = True) -> Iterator[bytes]:
  """Sequential records from an absolute byte offset (a record boundary
  from a shard index) — the seeked-reader primitive behind
  ``records.open_at``. The reader closes when the generator finishes."""
  reader = NativeRecordReader(path, verify_crc=verify_crc,
                              start_offset=offset)
  try:
    yield from reader
  finally:
    reader.close()


# ------------------------------------------------------- example parsing

_KIND_FLOAT, _KIND_INT64, _KIND_BYTES = 0, 1, 2


def _is_bytes_spec(spec) -> bool:
  return (getattr(spec, 'is_encoded_image', False) or
          spec.dtype.name in ('object', 'str', 'bytes'))


class NativeExampleParser:
  """Spec-driven tf.Example batch parser on the C++ wire decoder.

  Covers the context-feature subset of the codec (fixed-shape and
  padded/clipped varlen float/int features, single encoded-image bytes
  features). Sequence (``SequenceExample``) specs and multi-image bytes
  features are unsupported — callers fall back to the TF parse path;
  ``supports(spec)`` reports coverage.

  ``parse_batch`` returns numpy arrays shaped ``[B, *spec.shape]`` (bytes
  features: a list of ``bytes`` per example, for the image decoder).
  """

  def __init__(self, named_specs):
    """named_specs: iterable of (output_key, record_name, TensorSpec)."""
    import numpy as np

    self._lib = _lib()
    self._np = np
    self._fields = []
    keys, kinds, lens, req, varlen = [], [], [], [], []
    for out_key, name, spec in named_specs:
      if not self.supports(spec):
        raise ValueError(f'spec {out_key!r} not supported natively')
      if _is_bytes_spec(spec):
        kind, flat = _KIND_BYTES, 1
      elif spec.dtype.name in ('float32', 'float64', 'bfloat16', 'float16'):
        kind, flat = _KIND_FLOAT, int(np.prod(spec.shape, dtype=np.int64))
      else:
        kind, flat = _KIND_INT64, int(np.prod(spec.shape, dtype=np.int64))
      pad = spec.varlen_default_value
      required = pad is None and not spec.is_optional
      self._fields.append((out_key, spec, kind, flat))
      keys.append(name.encode())
      kinds.append(kind)
      lens.append(flat)
      req.append(int(required))
      varlen.append(int(pad is not None))
    n = len(keys)
    self._h = self._lib.t2r_parser_create(
        (ctypes.c_char_p * n)(*keys), (ctypes.c_int * n)(*kinds),
        (ctypes.c_int64 * n)(*lens), (ctypes.c_int * n)(*req),
        (ctypes.c_int * n)(*varlen), n)

  @staticmethod
  def supports(spec) -> bool:
    import numpy as np

    if getattr(spec, 'is_sequence', False):
      return False
    if getattr(spec, 'is_encoded_image', False):
      return len(spec.shape) <= 3  # single encoded blob per example
    if spec.dtype.name in ('object', 'str', 'bytes'):
      # Plain string features pass through undecoded: one per example.
      return int(np.prod(spec.shape, dtype=np.int64)) == 1
    if spec.dtype.name in ('float32', 'float64', 'bfloat16', 'float16'):
      return True
    return np.issubdtype(spec.dtype, np.integer) or spec.dtype == np.bool_

  def parse_batch(self, records: Sequence[bytes]):
    np = self._np
    batch = len(records)
    recs = (ctypes.c_char_p * batch)(*records)
    lens = (ctypes.c_uint64 * batch)(*[len(r) for r in records])
    buffers = []
    outs = (ctypes.c_void_p * len(self._fields))()
    for i, (_, spec, kind, flat) in enumerate(self._fields):
      if kind == _KIND_BYTES:
        buf = np.full((batch, flat, 2), -1, np.int64)
      elif kind == _KIND_FLOAT:
        pad = spec.varlen_default_value or 0.0
        buf = np.full((batch, flat), pad, np.float32)
      else:
        pad = spec.varlen_default_value or 0
        buf = np.full((batch, flat), int(pad), np.int64)
      buffers.append(buf)
      outs[i] = buf.ctypes.data_as(ctypes.c_void_p)
    rc = self._lib.t2r_parser_parse_batch(self._h, recs, lens, batch, outs)
    if rc:
      raise ValueError(
          f'example parse failed: '
          f'{self._lib.t2r_parser_error(self._h).decode()}')
    out = {}
    for (key, spec, kind, flat), buf in zip(self._fields, buffers):
      if kind == _KIND_BYTES:
        vals = []
        for b in range(batch):
          off, ln = int(buf[b, 0, 0]), int(buf[b, 0, 1])
          vals.append(records[b][off:off + ln] if off >= 0 else b'')
        out[key] = vals
      else:
        out[key] = buf.reshape((batch,) + tuple(spec.shape)).astype(
            spec.dtype, copy=False)
    return out

  def close(self) -> None:
    if self._h:
      self._lib.t2r_parser_destroy(self._h)
      self._h = None

  def __del__(self):
    try:
      self.close()
    except Exception:  # interpreter shutdown
      pass


def _decode_image(raw: bytes, spec, key=None):
  """PIL image decode with the codec's empty-bytes→zeros convention."""
  import numpy as np

  shape = tuple(spec.shape[-3:])
  if not raw:
    return np.zeros(shape, spec.dtype)
  import io

  import PIL.Image

  img = PIL.Image.open(io.BytesIO(raw))
  # Channel-count reconciliation, matching the TF codec's decode
  # (example_codec forces channels from the spec): grayscale-stored
  # images under a 3-channel spec convert, and vice versa. High-bit
  # modes (16-bit PNG 'I;16'/'I', float 'F') are exempt — convert()
  # would clamp them to 8 bits; they pass through as decoded.
  high_bit = img.mode in ('I', 'I;16', 'I;16B', 'I;16L', 'F')
  if not high_bit:
    if shape[-1] == 3 and img.mode != 'RGB':
      img = img.convert('RGB')
    elif shape[-1] == 1 and img.mode != 'L':
      img = img.convert('L')
  arr = np.asarray(img)
  if arr.ndim == 2:
    arr = arr[..., None]
  if arr.shape != shape:
    # A genuine RESOLUTION mismatch must fail here, by feature name, not
    # as a np.stack shape error (or silently mis-shaped features)
    # downstream.
    raise ValueError(
        f'Decoded image for feature {key or spec.name!r} has shape '
        f'{arr.shape}, but the spec declares {shape}.')
  return arr.astype(spec.dtype)


def _native_jpeg_batch(raws, spec, workers: int, key=None, out=None):
  """Batch JPEG decode through the native C++ decoder, or ``None``.

  Decodes straight into one contiguous [N, H, W, C] uint8 array (no
  per-image numpy intermediates, no np.stack copy) — the caller's
  preallocated ``out`` (a ring-buffer slot, see ``data/engine.py``) when
  given, else a fresh allocation. Images the native decoder declines
  (non-JPEG bytes, shape mismatch, decode errors) fall back to
  :func:`_decode_image` individually — shape mismatches then raise the
  same descriptive error the PIL path raises.
  """
  import numpy as np

  from tensor2robot_tpu import native

  shape = tuple(spec.shape[-3:])
  if (np.dtype(spec.dtype) != np.uint8 or len(shape) != 3 or
      shape[-1] not in (1, 3)):
    return None
  lib = native.load_jpeg_decode()
  if lib is None:
    return None
  n = len(raws)
  h, w, c = shape
  if out is not None and (out.shape != (n, h, w, c) or
                          out.dtype != np.uint8 or
                          not out.flags['C_CONTIGUOUS']):
    raise ValueError(
        f'decode buffer for {key or spec.name!r} must be contiguous '
        f'uint8 {(n, h, w, c)}, got {out.dtype} {out.shape}')
  if out is None:
    out = np.empty((n, h, w, c), np.uint8)
  status = np.zeros(n, np.int32)
  bufs = (ctypes.c_char_p * n)(*raws)
  lens = (ctypes.c_uint64 * n)(*[len(r) for r in raws])
  try:
    cpus = len(os.sched_getaffinity(0))
  except (AttributeError, OSError):
    cpus = os.cpu_count() or 1
  lib.t2r_jpeg_decode_batch(
      bufs, lens, n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
      h, w, c, min(int(workers) or 1, cpus), status.ctypes.data_as(
          ctypes.POINTER(ctypes.c_int32)))
  declined = np.nonzero(status > 1)[0]  # 0=ok, 1=empty→zeroed
  if len(declined) > 1 and workers and workers > 1:
    # All-PNG (or similar) batches fall back wholesale — keep the PIL
    # decodes on the shared pool, as the pure-PIL path does.
    decoded = _decode_pool(workers).map(
        lambda i: _decode_image(raws[i], spec, key=key), declined)
    for i, img in zip(declined, decoded):
      out[i] = img
  else:
    for i in declined:
      out[i] = _decode_image(raws[i], spec, key=key)
  return out


def _decode_image_batch(raws, spec, workers: int, key=None, out=None):
  """Contiguous [N, H, W, C] image-batch decode, any encoding.

  The zero-copy batch-assembly discipline for EVERY decode route: the
  native JPEG fast path already fills one contiguous buffer; the PIL
  fallback now writes each decoded image straight into its row of the
  same batch buffer — the whole-batch ``np.stack`` copy is gone from
  both. ``out`` (optional) is a caller-preallocated buffer (an engine
  ring slot); without it one fresh buffer is allocated per batch.
  """
  import numpy as np

  batch = _native_jpeg_batch(raws, spec, workers, key=key, out=out)
  if batch is not None:
    return batch
  n = len(raws)
  shape = tuple(spec.shape[-3:])
  if out is None:
    out = np.empty((n,) + shape, spec.dtype)

  def decode_into(i):
    out[i] = _decode_image(raws[i], spec, key=key)

  if workers and workers > 1 and n > 1:
    # Exhausts the map so any decode error (e.g. a descriptive shape
    # mismatch) raises here, exactly like the serial loop.
    for _ in _decode_pool(workers).map(decode_into, range(n)):
      pass
  else:
    for i in range(n):
      decode_into(i)
  return out


_DECODE_POOLS: dict = {}  # max_workers → ThreadPoolExecutor  # GUARDED_BY(_DECODE_POOL_LOCK)
_DECODE_POOL_LOCK = threading.Lock()


def _decode_pool(workers: int):
  """Shared decode pools per process — parse fns are created per iterator
  (train + every eval round), so a pool per parse fn would churn threads
  for the process lifetime.

  A pool is NEVER shut down once handed out: another iterator thread
  (train vs eval generators with different ``decode_workers``) may hold a
  reference and ``.map`` on it concurrently, and an executor raises
  ``cannot schedule new futures after shutdown`` mid-training. Instead,
  pools are kept per requested size and a request is served by the
  largest existing pool that satisfies it — distinct sizes are few (one
  per generator config), so idle-thread cost stays bounded."""
  with _DECODE_POOL_LOCK:
    best = max((w for w in _DECODE_POOLS if w >= workers), default=None)
    if best is None:
      import concurrent.futures

      _DECODE_POOLS[workers] = concurrent.futures.ThreadPoolExecutor(
          max_workers=workers, thread_name_prefix='t2r-decode')
      best = workers
    return _DECODE_POOLS[best]


def make_native_parse_fn(feature_spec, label_spec=None,
                         decode_workers: int = 8):
  """Spec-driven TF-free batch parse fn, or ``None`` when not coverable.

  Returns ``parse_fn(records: Sequence[bytes]) -> (features, labels)``
  yielding packed SpecStructs (labels ``None`` when no label spec), using
  the native wire parser + PIL image decode. Returns ``None`` when the
  native library is unavailable or any spec needs the TF codec
  (sequences, multi-dataset, multi-image bytes) so callers can fall back.

  ``decode_workers``: image decodes across the batch run on a shared
  thread pool (PIL releases the GIL in its C decoder, so this scales) —
  the tf.data ``num_parallel_calls`` analog for the dominant host cost
  of image workloads. 0 decodes inline.

  The returned ``parse_fn`` is safe to call concurrently on DIFFERENT
  record batches (the engine's workers do): the native parser handle's
  only cross-call state is its error string, so each calling thread
  lazily gets its own parser. It also exposes the batch-buffer protocol
  ``data/engine.py`` ring slots use: ``parse_fn.make_image_buffers(
  batch_size)`` preallocates the contiguous per-image-feature decode
  buffers, and ``parse_fn(records, image_out=buffers)`` decodes into
  them instead of allocating.
  """
  import numpy as np

  from tensor2robot_tpu.specs import algebra

  if not available():
    return None
  flat_f = algebra.flatten_spec_structure(feature_spec)
  flat_l = (None if label_spec is None else
            algebra.flatten_spec_structure(label_spec))
  named = []
  for prefix, flat in (('f/', flat_f), ('l/', flat_l)):
    if flat is None:
      continue
    for key, spec in flat.items():
      if spec.dataset_key or not NativeExampleParser.supports(spec):
        return None
      named.append((prefix + key, spec.name or key.split('/')[-1], spec))
  parser0 = NativeExampleParser(named)  # eager: validates the specs once
  tls = threading.local()
  tls.parser = parser0

  def _thread_parser() -> NativeExampleParser:
    parser = getattr(tls, 'parser', None)
    if parser is None:
      parser = NativeExampleParser(named)
      tls.parser = parser
    return parser

  def parse_fn(records, image_out=None):
    from tensor2robot_tpu.specs import SpecStruct

    with tracing.span('data/parse'):
      parsed = _thread_parser().parse_batch(list(records))
    metrics_lib.counter('data/examples_parsed').inc(len(records))
    feats, labels = SpecStruct(), SpecStruct()
    for out_key, _, spec in named:
      value = parsed[out_key]
      if isinstance(value, list):  # bytes feature
        if getattr(spec, 'is_encoded_image', False):
          # Image decode dominates host cost on vision workloads —
          # data/decode_ms is the first histogram to read when the
          # trainer breakdown says a run is input-bound.
          with tracing.span('data/decode'):
            value = _decode_image_batch(
                value, spec, decode_workers, key=out_key[2:],
                out=None if image_out is None else image_out.get(out_key))
          if len(spec.shape) > 3:  # singleton leading image dims
            value = value.reshape(value.shape[:1] + tuple(spec.shape))
        else:  # plain string: pass through undecoded (TF-codec parity)
          value = np.asarray(value, dtype=object).reshape(
              (len(records),) + tuple(spec.shape))
      (feats if out_key.startswith('f/') else labels)[out_key[2:]] = value
    features = algebra.pack_flat_sequence_to_spec_structure(flat_f, feats)
    if flat_l is None:
      return features, None
    return features, algebra.pack_flat_sequence_to_spec_structure(
        flat_l, labels)

  def make_image_buffers(batch_size: int):
    """One ring slot: a contiguous decode buffer per image feature."""
    return {
        out_key: np.empty((batch_size,) + tuple(spec.shape[-3:]),
                          spec.dtype)
        for out_key, _, spec in named
        if getattr(spec, 'is_encoded_image', False)
    }

  parse_fn.make_image_buffers = make_image_buffers
  return parse_fn
