"""Constant-time stream-position reconstruction for the native path.

The native record stream is a pure function of (file list, cycle length,
seed, shuffle size): a strict block_length=1 round-robin interleave over
per-slot file chains, then a seeded fixed-size shuffle buffer. Both
stages are algebraically invertible once per-shard record counts are
known (the shard-index sidecars, ``data/shard_index.py``), so a resume
at ANY depth reduces to:

  1. closed-form interleave math — which (shard, ordinal) produced every
     raw-stream position, and where each reader stands after N records —
     in O(slots · log max_records), no IO;
  2. a vectorized replay of the shuffle RNG — ``RandomState.randint(k,
     size=P)`` consumes the exact variate stream P scalar draws would —
     recovering the rng state AND which raw indices currently sit in the
     buffer without touching a single record;
  3. ≤ ``shuffle_buffer_size`` indexed record reads (seeks) to refill
     the buffer, plus per-slot seeks for the partial epoch.

Everything here is host math + bounded reads: restore cost is
independent of how deep into the corpus the stream was, which is the
whole point (ROADMAP direction 5; the legacy path replays O(position)
records). ``input_generators.NativeRecordInputGenerator`` drives this
and degrades loudly to the replay path when an index is missing/stale.
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

# Vectorized shuffle replay works in bounded chunks so a billion-record
# position never materializes a billion-entry draw array.
_SHUFFLE_CHUNK = 1 << 20


class InterleaveLayout:
  """Closed-form position algebra for the C++ interleave reader's order.

  Mirrors ``native/record_io.cpp``: ``S = min(cycle_length, n_files)``
  slots; slot ``s`` owns files ``s, s+S, s+2S, …`` read sequentially;
  the consumer round-robins slots (one record per visit), skipping
  exhausted slots. Equivalently: in round ``r`` every slot with more
  than ``r`` records emits its ``r``-th record, in slot order.
  """

  def __init__(self, counts: Sequence[int], cycle_length: int):
    if not counts:
      raise ValueError('need at least one shard')
    slots = cycle_length if cycle_length > 0 else 16
    self.num_slots = min(slots, len(counts))
    self.counts = [int(c) for c in counts]
    self.slot_files: List[List[int]] = [
        list(range(s, len(counts), self.num_slots))
        for s in range(self.num_slots)
    ]
    self.slot_totals = [
        sum(self.counts[f] for f in files) for files in self.slot_files
    ]
    self.total = sum(self.slot_totals)
    # Per-slot cumulative file counts, for slot-ordinal -> (file, ordinal).
    self._slot_cum: List[List[int]] = []
    for files in self.slot_files:
      cum, acc = [], 0
      for f in files:
        acc += self.counts[f]
        cum.append(acc)
      self._slot_cum.append(cum)

  def emitted_before_round(self, r: int) -> int:
    """Records emitted in rounds < r (= C(r)): sum over min(n_s, r)."""
    return sum(min(n, r) for n in self.slot_totals)

  def _rank(self, slot: int, r: int) -> int:
    """Active slots before ``slot`` in round ``r``."""
    return sum(1 for s in range(slot) if self.slot_totals[s] > r)

  def position_of(self, slot: int, r: int) -> int:
    """Within-epoch position at which slot emits its r-th record."""
    return self.emitted_before_round(r) + self._rank(slot, r)

  def locate(self, pos: int) -> Tuple[int, int]:
    """Within-epoch position -> (slot, round) that produced it."""
    if not 0 <= pos < self.total:
      raise ValueError(f'position {pos} out of range [0, {self.total})')
    lo, hi = 0, max(self.slot_totals)  # r in [lo, hi): C(r) <= pos
    while hi - lo > 1:
      mid = (lo + hi) // 2
      if self.emitted_before_round(mid) <= pos:
        lo = mid
      else:
        hi = mid
    r = lo
    j = pos - self.emitted_before_round(r)
    for s in range(self.num_slots):
      if self.slot_totals[s] > r:
        if j == 0:
          return s, r
        j -= 1
    raise AssertionError('locate: inconsistent layout')  # pragma: no cover

  def slot_consumed_at(self, slot: int, pos: int) -> int:
    """Records slot has emitted once ``pos`` records were emitted."""
    n = self.slot_totals[slot]
    if n == 0 or pos <= 0:
      return 0
    lo, hi = 0, n  # count rounds r with position_of(slot, r) < pos
    while lo < hi:
      mid = (lo + hi) // 2
      if self.position_of(slot, mid) < pos:
        lo = mid + 1
      else:
        hi = mid
    return lo

  def slot_record(self, slot: int, ordinal: int) -> Tuple[int, int]:
    """Slot-local ordinal -> (file index, record ordinal in file)."""
    cum = self._slot_cum[slot]
    if not 0 <= ordinal < self.slot_totals[slot]:
      raise ValueError(
          f'slot {slot} ordinal {ordinal} out of range '
          f'({self.slot_totals[slot]} records)')
    i = bisect.bisect_right(cum, ordinal)
    prev = cum[i - 1] if i else 0
    return self.slot_files[slot][i], ordinal - prev

  def record_at(self, pos: int) -> Tuple[int, int]:
    """Within-epoch position -> (file index, record ordinal in file)."""
    slot, r = self.locate(pos)
    return self.slot_record(slot, r)

  def per_file_position(self, pos: int) -> List[Tuple[int, int]]:
    """Reader state once ``pos`` records were emitted: for every slot,
    (next file index, next record ordinal in that file); a fully
    consumed slot reports (-1, 0)."""
    out = []
    for s in range(self.num_slots):
      consumed = self.slot_consumed_at(s, pos)
      if consumed >= self.slot_totals[s]:
        out.append((-1, 0))
      else:
        out.append(self.slot_record(s, consumed))
    return out


def simulate_shuffle(seed: Optional[int], buffer_size: int,
                     emitted: int) -> Tuple[np.random.RandomState,
                                            np.ndarray]:
  """Replays the shuffle WITHOUT data: rng state + buffered raw indices.

  The stream's shuffle (``input_generators``) fills a ``buffer_size``
  buffer from raw records 0..bs-1, then emission ``t`` draws ``j =
  rng.randint(bs)``, emits slot ``j`` and refills it with raw record
  ``bs + t``. So after ``emitted`` emissions, slot ``j`` holds raw index
  ``bs + t_last(j)`` (its latest refill) or its initial ``j``. Both the
  final rng state and ``t_last`` come from a chunked vectorized replay —
  ``randint(bs, size=n)`` consumes the identical underlying variate
  stream as n scalar draws (pinned by test) — so this is O(emitted)
  numpy work with O(buffer) memory, ~milliseconds at 100k records.
  """
  rng = np.random.RandomState(seed)
  last = np.full(buffer_size, -1, np.int64)
  done = 0
  while done < emitted:
    n = int(min(_SHUFFLE_CHUNK, emitted - done))
    draws = rng.randint(buffer_size, size=n)
    # maximum.at keeps the LAST refill per slot (t is increasing) with
    # well-defined semantics under duplicate indices.
    np.maximum.at(last, draws, np.arange(done, done + n, dtype=np.int64))
    done += n
  buffered = np.where(last >= 0, buffer_size + last,
                      np.arange(buffer_size, dtype=np.int64))
  return rng, buffered


def local_to_global(local_index: int, process_count: int,
                    process_index: int, epoch_total: int) -> Tuple[int, int]:
  """Element-sharded local raw index -> (epoch, within-epoch position).

  The element shard filters each epoch's enumeration independently
  (``i % process_count == process_index`` with ``i`` reset per epoch),
  so a process's epoch slice has ``len(range(pi, T, pc))`` records.
  """
  per_epoch = len(range(process_index, epoch_total, process_count))
  if per_epoch == 0:
    raise ValueError(
        f'process {process_index}/{process_count} owns no records of a '
        f'{epoch_total}-record epoch')
  epoch, rank = divmod(local_index, per_epoch)
  return epoch, process_index + rank * process_count


@dataclasses.dataclass
class ResumePlan:
  """Everything ``_build_batches`` needs to continue mid-stream."""

  layout: InterleaveLayout
  files: List[str]
  buffer: Optional[List[bytes]]  # shuffle buffer contents, stream order
  rng: Optional[np.random.RandomState]  # advanced past all prior draws
  epoch: int                    # epoch holding the next raw record
  within_epoch: int             # next GLOBAL within-epoch position
  records_local: int            # local raw records already consumed
  process_count: int = 1
  process_index: int = 0
  # path -> validated ShardIndex, set by the caller so the partial-epoch
  # readers seek without re-loading sidecars.
  indexes: Optional[Dict[str, object]] = None


def plan_resume(
    files: Sequence[str],
    counts: Sequence[int],
    cycle_length: int,
    seed: Optional[int],
    shuffle_buffer_size: int,
    records_emitted: int,
    shuffled: bool,
    fetch: Callable[[str, Sequence[int]], Dict[int, bytes]],
    process_count: int = 1,
    process_index: int = 0,
) -> ResumePlan:
  """Builds the constant-time resume plan for a stream position.

  ``records_emitted`` is the POST-shuffle position (delivered batches ×
  batch size). ``fetch(path, ordinals) -> {ordinal: bytes}`` performs
  the indexed reads (``records.read_records_at``).
  """
  layout = InterleaveLayout(counts, cycle_length)
  if layout.total == 0:
    raise ValueError('cannot resume over empty shards')
  if shuffled and shuffle_buffer_size > 1:
    rng, buffered = simulate_shuffle(seed, shuffle_buffer_size,
                                     records_emitted)
    raw_local = shuffle_buffer_size + records_emitted
    # Group the ≤ buffer_size indexed reads per shard.
    wanted: Dict[str, List[int]] = {}
    located = []
    for raw in buffered.tolist():
      epoch, within = local_to_global(raw, process_count, process_index,
                                      layout.total)
      del epoch  # repeated epochs re-read the same bytes
      file_idx, ordinal = layout.record_at(within)
      located.append((files[file_idx], ordinal))
      wanted.setdefault(files[file_idx], []).append(ordinal)
    payloads = {
        path: fetch(path, sorted(set(ordinals)))
        for path, ordinals in wanted.items()
    }
    buffer = [payloads[path][ordinal] for path, ordinal in located]
  else:
    rng, buffer = None, None
    raw_local = records_emitted
  epoch, within = local_to_global(raw_local, process_count, process_index,
                                  layout.total)
  return ResumePlan(layout=layout, files=list(files), buffer=buffer,
                    rng=rng, epoch=epoch, within_epoch=within,
                    records_local=raw_local,
                    process_count=process_count,
                    process_index=process_index)


def iter_epoch_from(
    layout: InterleaveLayout,
    files: Sequence[str],
    start_pos: int,
    open_at: Callable[[str, int], Iterator[bytes]],
) -> Iterator[Tuple[int, bytes]]:
  """Yields (within-epoch position, record) from ``start_pos`` to epoch
  end, byte-identical in order to the C++ interleave reader.

  Used ONLY for the resumed partial epoch: per-slot readers are opened
  at their seek positions (``open_at(path, ordinal)``) and read
  sequentially; subsequent full epochs go back through the native
  prefetching interleave.
  """
  if start_pos >= layout.total:
    return
  start_slot, start_round = layout.locate(start_pos)
  positions = layout.per_file_position(start_pos)

  # Lazy per-slot chained readers from each slot's seek position.
  def slot_stream(slot: int) -> Iterator[bytes]:
    file_idx, ordinal = positions[slot]
    if file_idx < 0:
      return
    files_in_slot = layout.slot_files[slot]
    at = files_in_slot.index(file_idx)
    for i in range(at, len(files_in_slot)):
      f = files_in_slot[i]
      yield from open_at(files[f], ordinal if f == file_idx else 0)

  streams = [None] * layout.num_slots
  pos = start_pos
  r = start_round
  max_rounds = max(layout.slot_totals)
  while r < max_rounds:
    for s in range(layout.num_slots):
      if layout.slot_totals[s] <= r:
        continue  # slot exhausted before this round
      if r == start_round and s < start_slot:
        continue  # already emitted before the resume point
      if streams[s] is None:
        streams[s] = slot_stream(s)
      record = next(streams[s], None)
      if record is None:
        raise RuntimeError(
            f'shard set changed under a resumed stream: slot {s} ran '
            f'out of records at round {r} (index said '
            f'{layout.slot_totals[s]})')
      yield pos, record
      pos += 1
    r += 1
