"""A predictor that serves from the exported TF SavedModel.

The TF-side twin of :class:`~tensor2robot_tpu.predictors.predictors.
ExportedModelPredictor`: it polls the same versioned export root, but loads
``saved_model.pb`` with ``tf.saved_model.load`` and serves through a
SavedModel signature — exactly what a TF-Serving binary does with the same
files. Exists so the SavedModel interop path
(``export/savedmodel.py``) has a first-class in-process consumer and a
parity test surface against the jax predictors
(``/root/reference/predictors/exported_savedmodel_predictor.py:60-214``).

TF is imported lazily: jax-only robot hosts never pay the dependency unless
they instantiate this class.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Optional

import numpy as np

from tensor2robot_tpu.export import exporters as exporters_lib
from tensor2robot_tpu.export import savedmodel as savedmodel_lib
from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.predictors.predictors import (AbstractPredictor,
                                                    _expand_to_spec_rank,
                                                    poll_and_load_newest)
from tensor2robot_tpu.specs import SpecStruct, algebra
from tensor2robot_tpu.utils.concurrency import ReaderWriterLock


def _run_signature(signature, feature_spec: SpecStruct,
                   features: Dict[str, np.ndarray]) -> Dict[str, Any]:
  """The stateless-style compute core over a loaded SavedModel signature.

  The TF twin of ``StatelessServingFn.fn``: all model state rides in the
  ``signature`` handle (TF binds variables into it), so callers snapshot
  ``(signature, feature_spec)`` once and a concurrent hot reload can
  never mix generations mid-call.
  """
  import tensorflow as tf

  features = _expand_to_spec_rank(features, feature_spec)
  feeds = {}
  for key, value in features.items():
    dtype = None
    if key in feature_spec:
      dtype = tf.dtypes.as_dtype(feature_spec[key].dtype.name)
    feeds[key] = tf.constant(np.asarray(value), dtype=dtype)
  outputs = signature(**feeds)
  return {k: np.asarray(v) for k, v in outputs.items()}


def _saved_model_dirs(export_root: str):
  """Export versions that carry a loadable SavedModel.

  Commit-aware: versions without the export commit marker (torn/partial
  exports — a replication that died mid-flight) are ignored, so a
  hot-reloading robot host never loads half a model
  (``export/uncommitted_skipped``); marker-less legacy roots stay fully
  visible.
  """
  return [
      d for d in exporters_lib.committed_export_dirs(export_root)
      if os.path.exists(os.path.join(d, savedmodel_lib.SAVED_MODEL_PB))
  ]


class SavedModelPredictor(AbstractPredictor):
  """Serves the newest export version through its SavedModel signature.

  Hot-reload hardened: a new version that fails to load (torn files the
  marker could not catch, an incompatible signature) FALLS BACK to the
  last-good loaded model instead of raising mid-control-loop — a robot
  keeps acting on the previous policy while the fleet investigates —
  counted as ``predictor/load_fallbacks``. The failure only propagates
  when there is no last-good model to fall back to.
  """

  def __init__(self,
               export_dir: str,
               signature_name: str = 'serving_default',
               timeout: float = 0.0):
    self._export_root = export_dir
    self._signature_name = signature_name
    self._timeout = timeout
    self._signature = None
    self._loaded_model = None  # keep the SavedModel object alive
    self._feature_spec: Optional[SpecStruct] = None
    self._global_step = -1
    self._loaded_dir: Optional[str] = None
    # Reload vs in-flight predict exclusion (utils/concurrency.py): the
    # signature/spec/step group must swap atomically.
    self._reload_lock = ReaderWriterLock()

  def get_feature_specification(self) -> SpecStruct:
    if self._feature_spec is None:
      raise ValueError('restore() must succeed before specs are available.')
    return self._feature_spec

  def restore(self) -> bool:
    return poll_and_load_newest(
        lambda: _saved_model_dirs(self._export_root),
        self._loaded_dir, self._timeout, self._load_with_fallback)

  def _load_with_fallback(self, export_dir: str) -> bool:
    try:
      return self._load(export_dir)
    except Exception as e:  # pylint: disable=broad-except
      if not self.is_loaded:
        raise
      metrics_lib.counter('predictor/load_fallbacks').inc()
      logging.warning(
          'Hot reload of export %r failed (%r); continuing to serve the '
          'last-good model from %r (step %d).', export_dir, e,
          self._loaded_dir, self._global_step)
      return True

  def _load(self, export_dir: str) -> bool:
    import tensorflow as tf

    from tensor2robot_tpu.specs import load_specs_from_export_dir

    feature_spec, _, global_step = load_specs_from_export_dir(export_dir)
    loaded = tf.saved_model.load(export_dir)
    if self._signature_name not in loaded.signatures:
      raise ValueError(
          f'SavedModel at {export_dir!r} has no signature '
          f'{self._signature_name!r}; available: '
          f'{sorted(loaded.signatures.keys())}')
    # Publication only: tf.saved_model.load ran without blocking predicts.
    with self._reload_lock.write_locked():
      self._loaded_model = loaded
      self._signature = loaded.signatures[self._signature_name]
      self._feature_spec = algebra.filter_required_flat_tensor_spec(
          feature_spec)
      self._global_step = global_step
      self._loaded_dir = export_dir
    return True

  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, Any]:
    self.assert_is_loaded()
    with self._reload_lock.read_locked():
      return _run_signature(self._signature, self._feature_spec, features)

  def predict_example_bytes(self, serialized_examples) -> Dict[str, Any]:
    """Serialized tf.Example bytes → outputs via the ``tf_example`` sig."""
    import tensorflow as tf

    self.assert_is_loaded()
    with self._reload_lock.read_locked():
      loaded_model, loaded_dir = self._loaded_model, self._loaded_dir
    examples_sig = loaded_model.signatures.get(
        savedmodel_lib.TF_EXAMPLE_SIGNATURE)
    if examples_sig is None:
      raise ValueError(
          f'SavedModel at {loaded_dir!r} was exported without the '
          f'{savedmodel_lib.TF_EXAMPLE_SIGNATURE!r} signature.')
    arg_names = sorted(examples_sig.structured_input_signature[1])
    if len(arg_names) != 1:
      raise ValueError(
          'Multi-dataset tf_example signatures need per-dataset feeds; '
          f'call the signature directly with its named inputs {arg_names}.')
    batch = tf.constant(list(serialized_examples), dtype=tf.string)
    outputs = examples_sig(**{arg_names[0]: batch})
    return {k: np.asarray(v) for k, v in outputs.items()}

  @property
  def is_loaded(self) -> bool:
    return self._signature is not None

  @property
  def global_step(self) -> int:
    return self._global_step

  @property
  def model_path(self) -> Optional[str]:
    return self._loaded_dir

  @property
  def export_meta(self) -> Dict[str, Any]:
    self.assert_is_loaded()
    with open(os.path.join(self._loaded_dir,
                           exporters_lib.EXPORT_META_FILENAME)) as f:
      return json.load(f)
