"""Predictors: restore-and-infer objects backing policies."""

from tensor2robot_tpu.predictors.predictors import (
    AbstractPredictor,
    CheckpointPredictor,
    ExportedModelPredictor,
)
