"""Predictors: restore-and-infer objects backing policies."""

from tensor2robot_tpu.predictors.predictors import (
    AbstractPredictor,
    CheckpointPredictor,
    ExportedModelPredictor,
    StatelessServingFn,
)


def __getattr__(name):
  # Lazy: SavedModelPredictor pulls in TF; jax-only hosts shouldn't pay.
  if name == 'SavedModelPredictor':
    from tensor2robot_tpu.predictors.savedmodel_predictor import (
        SavedModelPredictor)
    return SavedModelPredictor
  raise AttributeError(f'module {__name__!r} has no attribute {name!r}')
