"""Predictors: restore-and-infer objects driving robot policies.

Capability-equivalent of ``/root/reference/predictors/``:

* :class:`AbstractPredictor` — the ABC surface policies rely on
  (``abstract_predictor.py:32-88``).
* :class:`CheckpointPredictor` — rebuilds the PREDICT path from a model
  object + polls the trainer's Orbax checkpoints
  (``checkpoint_predictor.py:39-212``).
* :class:`ExportedModelPredictor` — polls a versioned export root, loads
  the newest *valid* export (specs from ``assets.extra``), hot-reloads on
  ``restore()`` (``exported_savedmodel_predictor.py:50-274``).

Both jit the preprocess→forward→export-outputs chain once and reuse it
across calls; CEM's action-batched queries become one device call.
"""

from __future__ import annotations

import abc
import os
import time
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import numpy as np

from tensor2robot_tpu.export import exporters as exporters_lib
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.specs import SpecStruct, algebra
from tensor2robot_tpu.specs import numpy_gen
from tensor2robot_tpu.train import checkpoints as ckpt_lib
from tensor2robot_tpu.train import train_state as ts_lib
from tensor2robot_tpu.utils.concurrency import ReaderWriterLock


class StatelessServingFn(NamedTuple):
  """A predictor's compute core as a pure function over ``(params, batch)``.

  This is the seam the batched serving plane (``serving/``) builds on:
  ``fn`` is jax-traceable and closes over NO weights — all state rides in
  ``params`` — so one program serves any client count (bucketed batch
  shapes compile once per bucket) and hot model swap is a params pointer
  swap. ``AbstractPredictor.predict()`` is the single-client wrapper
  around exactly this function.
  """

  # fn(params, features) -> outputs; jax-traceable, batch-polymorphic.
  fn: Callable
  params: Any
  feature_spec: SpecStruct
  version: int  # the model version served (global step)
  # Equal keys <=> same compute PROGRAM (only weights differ), so a
  # consumer's compiled-executable cache survives a hot swap.
  program_key: Any


class AbstractPredictor(abc.ABC):
  """The predictor surface policies consume (abstract_predictor.py:32-88)."""

  @abc.abstractmethod
  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, Any]:
    ...

  @abc.abstractmethod
  def get_feature_specification(self) -> SpecStruct:
    ...

  def get_label_specification(self) -> Optional[SpecStruct]:
    return None

  @abc.abstractmethod
  def restore(self) -> bool:
    """Loads the newest available weights; returns success."""

  def init_randomly(self) -> None:
    raise NotImplementedError

  def close(self) -> None:
    ...

  def assert_is_loaded(self) -> None:
    if not self.is_loaded:
      raise ValueError('The predictor has not been restored yet.')

  def device_serving_fn(self):
    """``(traceable_fn, variables)`` for composition inside a caller's jit.

    ``traceable_fn(variables, features) -> outputs`` is the restored
    serving chain as a jax-traceable callable (NOT a numpy wrapper), so
    callers can close a whole control loop — e.g. the CEM
    sample/evaluate/update cycle — into one XLA program around it.
    Raises when this predictor flavor cannot expose one.
    """
    raise NotImplementedError(
        f'{type(self).__name__} does not expose a traceable serving fn.')

  def stateless_serving_fn(
      self, quantize: Optional[str] = None) -> StatelessServingFn:
    """The loaded model as a :class:`StatelessServingFn` snapshot.

    The serving plane's contract: the returned tuple is immutable — a
    later ``restore()`` produces a NEW snapshot rather than mutating
    this one — so a consumer can keep dispatching against it while a
    reload happens concurrently. Raises for predictor flavors whose
    compute path is not a jax function (e.g. the TF SavedModel
    signature); the serving plane then degrades to batched
    ``predict()`` calls.

    ``quantize`` ('int8' / 'fp8') returns the weight-only quantized
    twin (``tensor2robot_tpu/quantize/``): int8/fp8 param payload with
    per-output-channel scales, dequantized inline in the jitted
    program, ``program_key`` extended with the mode so executable
    caches never alias precision variants. Quantization runs on the
    host OUTSIDE the reload lock — a concurrent restore is never
    blocked behind it.
    """
    raise NotImplementedError(
        f'{type(self).__name__} does not expose a stateless serving fn.')

  @staticmethod
  def _maybe_quantize_serving(serving: StatelessServingFn,
                              quantize: Optional[str]) -> StatelessServingFn:
    """Shared quantize hook for the concrete flavors (no-op on None/'off')."""
    if quantize in (None, '', 'off'):
      return serving
    from tensor2robot_tpu.quantize import quantize_serving_fn

    return quantize_serving_fn(serving, mode=quantize)

  @property
  @abc.abstractmethod
  def is_loaded(self) -> bool:
    ...

  @property
  def model_version(self) -> int:
    return self.global_step

  @property
  @abc.abstractmethod
  def global_step(self) -> int:
    ...


class _JitForward:
  """Shared jitted PREDICT chain: preprocess → network → export outputs.

  The chain is STATELESS — ``traceable(variables, features)`` closes over
  only the model's code, never its weights — so it doubles as the
  ``StatelessServingFn.fn`` the batched serving plane compiles per batch
  bucket; ``__call__`` is the single-client numpy wrapper around it.
  """

  def __init__(self, model):
    self._model = model
    preprocessor = model.preprocessor

    def forward(variables, features):
      features_p, _ = preprocessor.preprocess(
          features, None, ModeKeys.PREDICT, None)
      outputs, _ = model.inference_network_fn(
          dict(variables), features_p, None, ModeKeys.PREDICT)
      return dict(model.create_export_outputs_fn(features_p, outputs))

    # The un-jitted chain stays available for composition INSIDE a larger
    # jitted program (the device-resident CEM loop).
    self.traceable = forward
    self._fn = jax.jit(forward)

  def __call__(self, variables, features: Dict[str, np.ndarray]):
    packed = SpecStruct(features)
    outputs = self._fn(variables, packed)
    return {k: np.asarray(v) for k, v in outputs.items()}


def _expand_to_spec_rank(features: Dict[str, np.ndarray],
                         spec: SpecStruct) -> Dict[str, np.ndarray]:
  """Adds leading batch dims the caller omitted.

  The dim-expansion contract of
  ``exported_savedmodel_predictor.py:78-102``: a single example (or single
  CEM sample) may be fed without its batch dim.
  """
  out = {}
  for key, value in features.items():
    value = np.asarray(value)
    if key in spec:
      expected_rank = len(spec[key].shape) + 1  # + batch
      while value.ndim < expected_rank:
        value = value[None]
    out[key] = value
  return out


class CheckpointPredictor(AbstractPredictor):
  """Model + trainer checkpoint dir → predictor (checkpoint_predictor.py).

  ``restore()`` polls ``<model_dir>/checkpoints`` for the newest step and
  loads it; ``init_randomly()`` supports collect-before-first-checkpoint.
  """

  def __init__(self,
               t2r_model,
               model_dir: str = '',
               restore_timeout_secs: float = 0.0):
    self._model = t2r_model
    self._model_dir = model_dir
    self._restore_timeout_secs = restore_timeout_secs
    self._forward = _JitForward(t2r_model)
    self._variables = None
    self._global_step = -1
    self._restored_step: Optional[int] = None
    # Reload vs in-flight predict exclusion: restore() swaps several
    # fields; without the lock a concurrent predict can read a torn
    # (new-step, old-params) combination (utils/concurrency.py).
    self._reload_lock = ReaderWriterLock()
    self._feature_spec = algebra.filter_required_flat_tensor_spec(
        t2r_model.preprocessor.get_in_feature_specification(ModeKeys.PREDICT))

  def get_feature_specification(self) -> SpecStruct:
    return self._feature_spec

  def _init_state(self):
    features = numpy_gen.make_random_numpy(self._feature_spec, batch_size=1)
    features_p, _ = self._model.preprocessor.preprocess(
        features, None, ModeKeys.PREDICT, None)
    optimizer = self._model.create_optimizer()
    return ts_lib.create_train_state(
        self._model, optimizer, jax.random.PRNGKey(0), features_p,
        ModeKeys.PREDICT)

  def init_randomly(self) -> None:
    state = self._init_state()
    variables = jax.device_get(dict(state.eval_variables))
    with self._reload_lock.write_locked():
      self._variables = variables
      self._global_step = 0

  def restore(self) -> bool:
    ckpt_dir = f'{self._model_dir}/checkpoints'
    deadline = time.time() + self._restore_timeout_secs
    while True:
      step = ckpt_lib.latest_checkpoint_step(ckpt_dir)
      if step is not None and step != self._restored_step:
        break
      if step is not None and step == self._restored_step:
        return True  # nothing newer; still loaded
      if time.time() >= deadline:
        return False
      time.sleep(1.0)
    state = self._init_state()
    with ckpt_lib.CheckpointManager(ckpt_dir, async_save=False) as manager:
      restored = manager.restore(state, step=step)
    if restored is None:
      return False
    variables = jax.device_get(dict(restored.eval_variables))
    # Only the publication is exclusive: checkpoint IO and D2H above ran
    # without blocking in-flight predicts.
    with self._reload_lock.write_locked():
      self._variables = variables
      self._global_step = int(restored.step)
      self._restored_step = step
    return True

  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, Any]:
    self.assert_is_loaded()
    with self._reload_lock.read_locked():
      features = _expand_to_spec_rank(features, self._feature_spec)
      return self._forward(self._variables, features)

  def device_serving_fn(self):
    self.assert_is_loaded()
    with self._reload_lock.read_locked():
      return self._forward.traceable, self._variables

  def stateless_serving_fn(
      self, quantize: Optional[str] = None) -> StatelessServingFn:
    self.assert_is_loaded()
    with self._reload_lock.read_locked():
      serving = StatelessServingFn(
          fn=self._forward.traceable, params=self._variables,
          feature_spec=self._feature_spec, version=self._global_step,
          program_key=('jit_forward', id(self._forward)))
    # Host-side quantization outside the lock: it only reads the
    # immutable snapshot, never predictor state.
    return self._maybe_quantize_serving(serving, quantize)

  @property
  def is_loaded(self) -> bool:
    return self._variables is not None

  @property
  def global_step(self) -> int:
    return self._global_step


def poll_and_load_newest(list_dirs_fn, loaded_dir, timeout,
                         load_fn) -> bool:
  """Shared restore contract of the export-root predictors.

  Busy-waits (``exported_savedmodel_predictor.py:120-202``): scan with
  ``list_dirs_fn``, load the newest version when it differs from
  ``loaded_dir``, and tolerate the trainer not having exported yet until
  ``timeout`` elapses.
  """
  deadline = time.time() + timeout
  while True:
    dirs = list_dirs_fn()
    if dirs:
      newest = dirs[-1]
      if newest != loaded_dir:
        return load_fn(newest)
      return True
    if time.time() >= deadline:
      return False
    time.sleep(1.0)


class ExportedModelPredictor(AbstractPredictor):
  """Polls a versioned export root (exported_savedmodel_predictor.py).

  ``restore()`` scans for the newest *complete* export version, reads specs
  + global_step from its assets, loads its serving variables, and obtains
  the serving fn — preferring the export's SELF-CONTAINED StableHLO
  artifact (no model class / training code needed, the SavedModel-load
  contract), falling back to rebuilding from the recorded model class. A
  busy-wait with ``timeout`` tolerates the trainer not having exported yet
  (``:120-202``).
  """

  def __init__(self,
               export_dir: str,
               t2r_model=None,
               timeout: float = 0.0,
               model_kwargs: Optional[Dict[str, Any]] = None):
    self._export_root = export_dir
    self._model = t2r_model
    self._model_kwargs = model_kwargs
    self._timeout = timeout
    self._forward: Optional[Callable] = None
    self._traceable: Optional[Callable] = None
    self._variables = None
    self._global_step = -1
    self._feature_spec: Optional[SpecStruct] = None
    self._loaded_dir: Optional[str] = None
    self._parse_fn = None
    # Two digests: _serving_raw_digest short-circuits reloads whose
    # artifact BYTES are identical; _serving_digest is the canonical
    # loc-stripped PROGRAM fingerprint (exporters.
    # serving_program_fingerprint) — stable across weights-only export
    # versions, so it keys program identity for serving-executable
    # cache reuse where the raw bytes cannot (they embed drifting MLIR
    # debug locations).
    self._serving_digest: Optional[str] = None
    self._serving_raw_digest: Optional[str] = None
    # Hot reload swaps _forward/_traceable/_variables/_feature_spec as a
    # group; the lock keeps an in-flight predict from mixing generations
    # (new serving fn + old params = shape-mismatch crash).
    self._reload_lock = ReaderWriterLock()

  def get_feature_specification(self) -> SpecStruct:
    if self._feature_spec is None:
      raise ValueError('restore() must succeed before specs are available.')
    return self._feature_spec

  def restore(self) -> bool:
    # committed_export_dirs: torn/partial versions (no commit marker)
    # are never load candidates; legacy marker-less roots stay visible.
    return poll_and_load_newest(
        lambda: exporters_lib.committed_export_dirs(self._export_root),
        self._loaded_dir, self._timeout, self._load_with_fallback)

  def _load_with_fallback(self, export_dir: str) -> bool:
    """Falls back to the last-good loaded model on a failed hot reload
    (same contract as SavedModelPredictor; ``predictor/load_fallbacks``)."""
    try:
      return self._load(export_dir)
    except Exception as e:  # pylint: disable=broad-except
      if not self.is_loaded:
        raise
      from tensor2robot_tpu.observability import metrics as metrics_lib

      metrics_lib.counter('predictor/load_fallbacks').inc()
      import logging

      logging.warning(
          'Hot reload of export %r failed (%r); continuing to serve the '
          'last-good model from %r (step %d).', export_dir, e,
          self._loaded_dir, self._global_step)
      return True

  def _load(self, export_dir: str) -> bool:
    import hashlib

    from tensor2robot_tpu.specs import load_specs_from_export_dir

    feature_spec, _, global_step = load_specs_from_export_dir(export_dir)
    serving_path = f'{export_dir}/{exporters_lib.SERVING_FN_FILENAME}'
    serving_bytes = None
    if os.path.exists(serving_path):
      with open(serving_path, 'rb') as f:
        serving_bytes = f.read()
    forward = self._forward
    traceable = self._traceable
    digest = None
    raw_digest = None
    if serving_bytes is not None:
      # Self-contained path: the serialized StableHLO fn already includes
      # preprocessing; no model object is ever constructed. Successive
      # export versions normally carry the SAME program (only weights
      # change), so reuse the deserialized fn — and its compile cache —
      # unless the PROGRAM actually differs. Raw bytes can't decide that
      # (they embed drifting MLIR debug locations), hence the canonical
      # fingerprint; identical raw bytes skip the deserialize entirely.
      raw_digest = hashlib.sha256(serving_bytes).hexdigest()
      if forward is not None and raw_digest == self._serving_raw_digest:
        digest = self._serving_digest
      else:
        from jax import export as jax_export

        exported = jax_export.deserialize(serving_bytes)
        digest = exporters_lib.serving_program_fingerprint(exported)
        if forward is None or digest != self._serving_digest:
          serving_call = exported.call

          def stablehlo_traceable(variables, features):
            return dict(serving_call(
                exporters_lib.to_plain_tree(variables), dict(features)))

          def stablehlo_forward(variables, features):
            outputs = stablehlo_traceable(variables, features)
            return {k: np.asarray(v) for k, v in outputs.items()}

          forward, traceable = stablehlo_forward, stablehlo_traceable
    else:
      # Model-class fallback: the jitted forward only depends on the model
      # object — build it once and reuse its compile cache across versions.
      if self._model is None:
        self._model = exporters_lib.load_model_from_export_dir(
            export_dir, self._model_kwargs)
      if not isinstance(forward, _JitForward):
        forward = _JitForward(self._model)
      traceable = forward.traceable
    variables = exporters_lib.load_state_from_export_dir(export_dir)
    feature_spec = algebra.filter_required_flat_tensor_spec(feature_spec)
    # Publication only — the IO, StableHLO deserialization and orbax
    # restore above all ran without blocking in-flight predicts; the
    # whole generation (fn + params + spec + step) swaps as one unit.
    with self._reload_lock.write_locked():
      self._forward = forward
      self._traceable = traceable
      self._serving_digest = digest
      self._serving_raw_digest = raw_digest
      self._variables = variables
      self._feature_spec = feature_spec
      self._global_step = global_step
      self._loaded_dir = export_dir
      self._parse_fn = None
    return True

  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, Any]:
    self.assert_is_loaded()
    with self._reload_lock.read_locked():
      features = _expand_to_spec_rank(features, self._feature_spec)
      return self._forward(self._variables, features)

  def device_serving_fn(self):
    self.assert_is_loaded()
    with self._reload_lock.read_locked():
      return self._traceable, self._variables

  def stateless_serving_fn(
      self, quantize: Optional[str] = None) -> StatelessServingFn:
    self.assert_is_loaded()
    with self._reload_lock.read_locked():
      program_key = (('stablehlo', self._serving_digest)
                     if self._serving_digest is not None
                     else ('jit_forward', id(self._forward)))
      serving = StatelessServingFn(
          fn=self._traceable, params=self._variables,
          feature_spec=self._feature_spec, version=self._global_step,
          program_key=program_key)
    return self._maybe_quantize_serving(serving, quantize)

  def predict_example_bytes(self, serialized_examples) -> Dict[str, Any]:
    """Serialized tf.Example bytes → actions (the tf_example receiver).

    The parser is generated from the export's OWN assets specs — the
    robot host needs no knowledge of the model
    (``default_export_generator.py:89-138``).
    """
    self.assert_is_loaded()
    # One flat read-lock scope covering parse + predict (the lock is not
    # reentrant — see utils/concurrency.py — so this does NOT route
    # through self.predict): the parser is generated from the loaded
    # generation's spec and must run against that generation's fn/params.
    with self._reload_lock.read_locked():
      if self._parse_fn is None:
        # Prefer the TF-free native parser (C++ wire decode + PIL images)
        # so robot hosts don't need a TF wheel; the TF codec remains the
        # fallback for sequence/multi-dataset specs.
        from tensor2robot_tpu.data import native_io

        native_fn = native_io.make_native_parse_fn(self._feature_spec)
        if native_fn is not None:
          self._parse_fn = lambda ex: native_fn(list(ex))[0]
        else:
          from tensor2robot_tpu.data import example_codec

          tf_fn = example_codec.make_parse_fn(self._feature_spec)
          self._parse_fn = lambda ex: tf_fn(np.asarray(ex, dtype=object))
      parsed = self._parse_fn(serialized_examples)
      if isinstance(parsed, tuple):
        parsed = parsed[0]
      features = {k: np.asarray(v) for k, v in parsed.items()}
      features = _expand_to_spec_rank(features, self._feature_spec)
      return self._forward(self._variables, features)

  def warmup(self) -> int:
    """Replays the export's recorded warmup requests; returns the count.

    Prefers the serialized-example records (exercising the bytes
    receiver); on a TF-free host (the parser needs the host-side TF
    wheel) falls back to the ``.npz`` numpy requests through
    ``predict`` — so jax-only robot hosts still warm up.
    """
    self.assert_is_loaded()
    path = f'{self._loaded_dir}'
    count = 0
    try:
      for record in exporters_lib.read_warmup_examples(path):
        self.predict_example_bytes([record])
        count += 1
      if count:
        return count
    except (FileNotFoundError, ImportError):
      pass
    # npz fallback: arrays are keyed '<feature_path>/<request_index>'.
    npz_path = os.path.join(
        path, 'assets.extra', exporters_lib.WARMUP_NPZ_FILENAME)
    try:
      arrays = np.load(npz_path)
    except FileNotFoundError:
      return count
    requests: Dict[str, Dict[str, np.ndarray]] = {}
    for key in arrays.files:
      feature_key, _, index = key.rpartition('/')
      requests.setdefault(index, {})[feature_key] = arrays[key]
    for request in requests.values():
      self.predict(request)
      count += 1
    return count

  @property
  def is_loaded(self) -> bool:
    return self._variables is not None

  @property
  def model_path(self) -> Optional[str]:
    """The export version dir currently being served (None before
    restore) — the hot-reload observability twin of global_step."""
    return self._loaded_dir

  @property
  def global_step(self) -> int:
    return self._global_step
