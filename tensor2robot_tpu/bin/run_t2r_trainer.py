"""Trainer binary: parse config files, call train_eval_model.

Shape-for-shape equivalent of ``/root/reference/bin/run_t2r_trainer.py:
32-39``: all wiring lives in config files; the binary parses
``--gin_configs`` / ``--gin_bindings`` and calls one function.

Usage:
  python -m tensor2robot_tpu.bin.run_t2r_trainer \
      --gin_configs path/to/experiment.gin \
      --gin_bindings 'train_eval_model.max_train_steps = 100'
"""

from __future__ import annotations

import argparse
import logging
import sys

from tensor2robot_tpu import config as t2r_config


def main(argv=None):
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument('--gin_configs', action='append', default=[],
                      help='Path to a gin config file (repeatable).')
  parser.add_argument('--gin_bindings', action='append', default=[],
                      help='Individual gin bindings (repeatable).')
  parser.add_argument(
      '--handle_preemption', action=argparse.BooleanOptionalAction,
      default=True,
      help='Convert SIGTERM/SIGINT into a forced checkpoint and a '
           'distinct resumable exit status (42).')
  args = parser.parse_args(argv)

  # Install the preemption handler BEFORE any work: a SIGTERM during
  # config parsing or state init should still exit resumable, and the
  # trainer honors the process-global handler at every dispatch boundary.
  from tensor2robot_tpu.train import resilience

  shutdown = None
  if args.handle_preemption:
    shutdown = resilience.install_graceful_shutdown()

  try:
    return _run(args, resilience)
  finally:
    # Restore signal dispositions on the way out: once training is over
    # a SIGTERM should kill normally, and embedding callers (tests, or
    # programs invoking main() directly) must not inherit a process-
    # global handler as a side effect.
    if shutdown is not None:
      shutdown.uninstall()


def _run(args, resilience):
  t2r_config.register_framework_configurables()
  t2r_config.parse_config_files_and_bindings(
      config_files=args.gin_configs, bindings=args.gin_bindings)

  # Persist the config next to the checkpoints, like the reference's
  # GinConfigSaverHook (train_eval.py:540-541): the FULL parsed config at
  # startup (so crashed/preempted runs are still reproducible from the
  # model dir), refined to the operative (actually-consumed) config on
  # successful completion.
  import os

  try:
    model_dir = t2r_config.query_parameter('train_eval_model.model_dir',
                                           resolve=True)
  except t2r_config.ConfigError:
    model_dir = None
  if not isinstance(model_dir, str):
    model_dir = None

  def save_config(text, filename):
    if not model_dir or '://' in model_dir:
      return
    os.makedirs(model_dir, exist_ok=True)
    with open(os.path.join(model_dir, filename), 'w') as f:
      f.write(text)

  # The startup snapshot is the FULL parsed config (the run may crash
  # before an operative config exists) — named distinctly so
  # operative_config-0.gin never misrepresents un-consumed bindings.
  save_config(t2r_config.config_str(), 'config-0.gin')
  train_eval_model = t2r_config.get_configurable('train_eval_model')
  try:
    result = train_eval_model()
  except resilience.PreemptedError as e:
    # The trainer already forced a checkpoint (+ input state). Exit with
    # the DISTINCT resumable status so schedulers restart rather than
    # fail the job; the restarted run restores and continues.
    logging.warning('%s; exiting with resumable status %d.', e, e.exit_code)
    sys.exit(e.exit_code)
  except Exception as e:
    # Liveness failures (train/distributed_resilience.DeadHostError and
    # kin) carry their own exit status (43): a peer process died, the
    # scheduler should restart the WHOLE job from the last committed
    # checkpoint rather than treat this worker as an ordinary crash.
    code = getattr(e, 'exit_code', None)
    if code is not None:
      logging.error('%s; exiting with status %d.', e, code)
      sys.exit(code)
    raise
  operative = t2r_config.operative_config_str()
  logging.info('Operative config:\n%s', operative)
  save_config(operative, 'operative_config-0.gin')
  return result


if __name__ == '__main__':
  logging.basicConfig(level=logging.INFO)
  main()
