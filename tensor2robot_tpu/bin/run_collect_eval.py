"""Collect/eval binary: parse config, run the robot-side loop.

Equivalent of ``/root/reference/bin/run_collect_eval.py:44-51``.
"""

from __future__ import annotations

import argparse
import logging

from tensor2robot_tpu import config as t2r_config


def main(argv=None):
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument('--gin_configs', action='append', default=[])
  parser.add_argument('--gin_bindings', action='append', default=[])
  parser.add_argument('--root_dir', default='')
  args = parser.parse_args(argv)

  t2r_config.register_framework_configurables()
  t2r_config.parse_config_files_and_bindings(
      config_files=args.gin_configs, bindings=args.gin_bindings)
  collect_eval_loop = t2r_config.get_configurable('collect_eval_loop')
  if args.root_dir:
    return collect_eval_loop(root_dir=args.root_dir)
  return collect_eval_loop()


if __name__ == '__main__':
  logging.basicConfig(level=logging.INFO)
  main()
