"""Closed-loop driver: actor fleet + follow-mode trainer + live exports.

The collect→train→export→collect cycle in ONE supervised process tree
(the reference's ``continuous_collect_eval`` split across a real
process boundary):

* this process seeds an initial export (the randomly-initialized model,
  version 0) so actors never wait on a first checkpoint, then trains on
  the live episode stream via the input engine's follow mode
  (``data/follow.py``), exporting after every checkpoint
  (``AsyncExportCallback`` → ``LatestExporter`` root);
* N actor subprocesses (``collect/actor.py``) drive sim envs with the
  newest committed export and write commit-marked episode shards into
  ``<model_dir>/episodes`` — the directory the trainer is tailing;
* an :class:`~tensor2robot_tpu.collect.actor.ActorSupervisor` restarts
  crashed actors (jittered backoff, crash budget, DEAD verdicts).

Shutdown contract (drilled by ``tests/test_collect_loop.py``): SIGTERM
to this process → the trainer finishes its in-flight dispatch, forces a
checkpoint and raises ``PreemptedError``; the driver fans SIGTERM out to
every actor (finish-or-abandon the in-flight episode, commit the shard,
exit 42), waits bounded, records everyone's exit in
``<model_dir>/loop_exit.json``, and exits 42 itself — the whole loop is
one resumable unit. A restart re-enters the live window and closes the
``trainer/sigterm_to_resumed_step_seconds`` measurement.

Usage:
  python -m tensor2robot_tpu.bin.run_collect_train \
      --model-dir /tmp/loop --num-actors 2 --max-train-steps 200
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import sys
import time
from typing import Callable, Dict, List, Optional

EXPORT_NAME = 'latest_exporter_numpy'
LOOP_EXIT_FILENAME = 'loop_exit.json'


@dataclasses.dataclass
class LoopConfig:
  """One closed-loop run's wiring."""

  model_dir: str
  num_actors: int = 2
  max_train_steps: int = 200
  batch_size: int = 16
  save_interval_steps: int = 50
  episodes_per_shard: int = 4
  window_records: int = 2048
  min_window_records: Optional[int] = None
  starve_timeout_secs: float = 120.0
  actor_reload_interval_secs: float = 1.0
  actor_episode_interval_secs: float = 0.0
  explore_stddev: float = 0.8
  seed: int = 0
  crash_budget: int = 3
  serialize_serving: bool = False
  # Dotted model factory (no-arg besides device_type); the pose-env
  # regression workload by default.
  model_class: str = ('tensor2robot_tpu.research.pose_env.pose_env_models.'
                      'PoseEnvRegressionModel')
  # Per-actor utils/faults.py specs: {actor_id: ['kill_before_commit:1']}.
  actor_faults: Optional[Dict[int, List[str]]] = None
  # Extra env vars for actor subprocesses (merged over os.environ); a
  # TPU-round bench pins actors to JAX_PLATFORMS=cpu — the robot-host
  # story — while the trainer keeps the device.
  actor_env: Optional[Dict[str, str]] = None
  # Drill accounting on the follow stream (sampled-record digests).
  trace_samples: bool = False
  # Programmatic embedders (the chaos soak harness, fleet-ops tests)
  # receive the LIVE fleet handles once everything is running:
  # called with (supervisor, generator) right before the training loop
  # enters, so an actuator engine can wire itself to the real
  # ActorSupervisor and follow stream. Not part of the JSON surface.
  on_fleet_started: Optional[Callable] = None

  @property
  def episodes_dir(self) -> str:
    return os.path.join(self.model_dir, 'episodes')

  @property
  def export_root(self) -> str:
    return os.path.join(self.model_dir, 'export', EXPORT_NAME)


@dataclasses.dataclass
class LoopResult:
  """What a programmatic run hands back to its caller (tests, bench)."""

  preempted: bool
  final_step: int
  actor_exit_codes: Dict[str, Optional[int]]
  supervisor_stats: Dict[str, dict]
  sampled_hashes: set
  ingested_shards: set
  first_export_dir: Optional[str]
  last_export_dir: Optional[str]
  train_seconds: float
  records_ingested: int


def _build_model(config: LoopConfig):
  import importlib

  module_name, _, cls = config.model_class.rpartition('.')
  model_cls = getattr(importlib.import_module(module_name), cls)
  return model_cls(device_type='cpu' if _cpu_backend() else 'tpu')


def _cpu_backend() -> bool:
  import jax

  return jax.default_backend() == 'cpu'


def ensure_initial_export(config: LoopConfig) -> str:
  """Seeds ``export_root`` with the randomly-initialized model (v0).

  Actors always find a committed export — collect-before-first-
  checkpoint needs no random-init path in the fleet — and the version's
  global step 0 anchors the improvement measurement.
  """
  import jax

  from tensor2robot_tpu.export import exporters as exporters_lib
  from tensor2robot_tpu.modes import ModeKeys
  from tensor2robot_tpu.specs import algebra, numpy_gen
  from tensor2robot_tpu.train import train_state as ts_lib

  existing = exporters_lib.committed_export_dirs(config.export_root)
  if existing:
    return existing[0]
  model = _build_model(config)
  spec = algebra.filter_required_flat_tensor_spec(
      model.preprocessor.get_in_feature_specification(ModeKeys.PREDICT))
  features = numpy_gen.make_random_numpy(spec, batch_size=1)
  features_p, _ = model.preprocessor.preprocess(
      features, None, ModeKeys.PREDICT, None)
  state = ts_lib.create_train_state(
      model, model.create_optimizer(), jax.random.PRNGKey(config.seed),
      features_p, ModeKeys.PREDICT)
  return exporters_lib.ModelExporter(
      serialize_serving=config.serialize_serving).export(
          model, state, config.export_root)


def evaluate_export_policy(export_dir: str, model=None, episodes: int = 12,
                           seed: int = 1234) -> float:
  """Mean episode reward of ONE export version on fixed-seed episodes.

  The improvement metric of the acceptance drill: evaluate the first
  export (random init) and the last (post-training) on the SAME seeded
  episode sequence; a loop that actually closed shows the gap.

  ``seed`` seeds the eval env, which pins its CAMERA: a pose-env camera
  is sampled once per env (a robot's rig is fixed), and the world-frame
  pose mapping is camera-specific — so fleet-relevant numbers evaluate
  on the FLEET's camera seeds (the actors' ``env_kwargs`` seeds), where
  a few hundred CPU train steps show an unambiguous gap (measured
  −0.37→−0.09). A held-out camera additionally measures cross-camera
  generalization, which needs far more data/steps than a CI drill has.
  """
  import numpy as np

  from tensor2robot_tpu.policies import RegressionPolicy
  from tensor2robot_tpu.predictors import ExportedModelPredictor
  from tensor2robot_tpu.research.pose_env.pose_env import PoseToyEnv

  predictor = ExportedModelPredictor(os.path.dirname(export_dir),
                                     timeout=0.0)
  # Pin to the requested version, not the newest: poll_and_load_newest
  # would jump ahead.
  predictor._load_with_fallback(export_dir)  # pylint: disable=protected-access
  if model is None:
    from tensor2robot_tpu.export import exporters as exporters_lib

    model = exporters_lib.load_model_from_export_dir(export_dir)
  policy = RegressionPolicy(t2r_model=model, predictor=predictor)
  env = PoseToyEnv(seed=seed)
  rewards = []
  for _ in range(episodes):
    obs = env.reset()
    action = policy.SelectAction(obs, None, None)
    _, reward, _, _ = env.step(np.asarray(action))
    rewards.append(reward)
    env.set_new_pose()
  return float(np.mean(rewards))


def run_collect_train(config: LoopConfig) -> LoopResult:
  """Runs the closed loop; returns the accounting a drill asserts on.

  Raises nothing on preemption — a SIGTERM mid-run yields a
  ``LoopResult(preempted=True)`` after the coordinated fan-out, and the
  CLI converts that to exit 42.
  """
  from tensor2robot_tpu.collect.actor import ActorConfig, ActorSupervisor
  from tensor2robot_tpu.data import follow as follow_lib
  from tensor2robot_tpu.data.input_generators import (
      NativeRecordInputGenerator)
  from tensor2robot_tpu.export import exporters as exporters_lib
  from tensor2robot_tpu.export.async_export import AsyncExportCallback
  from tensor2robot_tpu.modes import ModeKeys
  from tensor2robot_tpu.observability import metrics as metrics_lib
  from tensor2robot_tpu.train import Trainer, TrainerConfig, resilience

  os.makedirs(config.episodes_dir, exist_ok=True)
  first_export = ensure_initial_export(config)
  model = _build_model(config)

  actor_configs = [
      ActorConfig(
          actor_id=i,
          export_root=config.export_root,
          out_dir=config.episodes_dir,
          episodes_per_shard=config.episodes_per_shard,
          reload_interval_secs=config.actor_reload_interval_secs,
          episode_interval_secs=config.actor_episode_interval_secs,
          seed=config.seed * 1000 + i,
          env_kwargs={'seed': config.seed * 100 + i},
          explore_stddev=config.explore_stddev,
          faults=(config.actor_faults or {}).get(i),
      ) for i in range(config.num_actors)
  ]
  supervisor = ActorSupervisor.for_configs(
      actor_configs, crash_budget=config.crash_budget,
      env=(dict(os.environ, **config.actor_env)
           if config.actor_env else None))

  generator = NativeRecordInputGenerator(
      file_patterns=os.path.join(config.episodes_dir, '*.tfrecord'),
      batch_size=config.batch_size,
      follow=follow_lib.FollowConfig(
          directory=config.episodes_dir,
          window_records=config.window_records,
          min_window_records=config.min_window_records,
          starve_timeout_secs=config.starve_timeout_secs,
          seed=config.seed,
          trace_samples=config.trace_samples,
      ))
  generator.set_specification_from_model(model, ModeKeys.TRAIN)

  trainer_config = TrainerConfig(
      model_dir=config.model_dir,
      max_train_steps=config.max_train_steps,
      save_interval_steps=config.save_interval_steps,
      eval_interval_steps=0,
      log_interval_steps=0,
      seed=config.seed,
      async_checkpoints=False,
      handle_preemption=True,
  )
  # Synchronous exports: every committed checkpoint's export version is
  # on disk before the next dispatch, so actor reloads track training
  # deterministically (an async drop-behind export would be fine in
  # production, but drills assert version cadence).
  export_callback = AsyncExportCallback(
      asynchronous=False, serialize_serving=config.serialize_serving)
  trainer = Trainer(model, trainer_config, callbacks=[export_callback])

  ingest_before = metrics_lib.counter('data/follow/records_ingested').value
  supervisor.start()
  supervisor.start_monitor()
  train_iter = generator.create_iterator(ModeKeys.TRAIN)
  if config.on_fleet_started is not None:
    config.on_fleet_started(supervisor, generator)
  preempted = False
  t_train0 = time.monotonic()
  try:
    trainer.train(train_iter, None)
  except resilience.PreemptedError:
    preempted = True
  finally:
    train_seconds = time.monotonic() - t_train0
    trainer.close()
    # Orderly teardown order: stop the fleet first (actors exit 42 on
    # SIGTERM whether this is completion or preemption), then the
    # follow stream and engine.
    supervisor.request_stop()
    exit_codes = supervisor.wait(timeout_secs=60.0)
    if generator.follow_stream is not None:
      generator.follow_stream.close()
    close = getattr(train_iter, 'close', None)
    if close is not None:
      close()
    # Trainer-binary hygiene: the loop is over, so embedding callers
    # (tests driving run_collect_train directly) must not inherit the
    # process-global SIGTERM handler handle_preemption installed.
    active = resilience.active_shutdown()
    if active is not None:
      active.uninstall()

  stream = generator.follow_stream
  result = LoopResult(
      preempted=preempted,
      final_step=trainer.step,
      actor_exit_codes=exit_codes,
      supervisor_stats=supervisor.stats(),
      sampled_hashes=set(stream.sampled_hashes) if stream else set(),
      ingested_shards=stream.ingested_shards() if stream else set(),
      first_export_dir=first_export,
      last_export_dir=(exporters_lib.committed_export_dirs(
          config.export_root) or [None])[-1],
      train_seconds=train_seconds,
      records_ingested=(
          metrics_lib.counter('data/follow/records_ingested').value -
          ingest_before),
  )
  _write_loop_exit(config.model_dir, result)
  return result


def _write_loop_exit(model_dir: str, result: LoopResult) -> None:
  """Persists the coordinated-exit record (the drill's assertion feed)."""
  path = os.path.join(model_dir, LOOP_EXIT_FILENAME)
  try:
    tmp = f'{path}.tmp{os.getpid()}'
    with open(tmp, 'w') as f:
      json.dump({
          'preempted': result.preempted,
          'final_step': result.final_step,
          'actor_exit_codes': result.actor_exit_codes,
          'supervisor': result.supervisor_stats,
          'records_ingested': result.records_ingested,
          'time': time.time(),
      }, f, indent=2)
    os.replace(tmp, path)
  except OSError as e:
    logging.warning('Cannot write %r: %r', path, e)


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument('--model-dir', required=True)
  parser.add_argument('--num-actors', type=int, default=2)
  parser.add_argument('--max-train-steps', type=int, default=200)
  parser.add_argument('--batch-size', type=int, default=16)
  parser.add_argument('--save-interval-steps', type=int, default=50)
  parser.add_argument('--episodes-per-shard', type=int, default=4)
  parser.add_argument('--actor-episode-interval-secs', type=float,
                      default=0.0,
                      help='Pacing between actor episodes (a sim env '
                           'outruns any robot; 0 = flat out).')
  parser.add_argument('--window-records', type=int, default=2048)
  parser.add_argument('--starve-timeout-secs', type=float, default=120.0)
  parser.add_argument('--crash-budget', type=int, default=3)
  parser.add_argument('--seed', type=int, default=0)
  parser.add_argument(
      '--serialize-serving', action=argparse.BooleanOptionalAction,
      default=False,
      help='Write the self-contained StableHLO artifact into every '
           'export version (slower; actors fall back to the model class '
           'either way).')
  args = parser.parse_args(argv)
  logging.basicConfig(level=logging.INFO)

  from tensor2robot_tpu.train import resilience

  resilience.install_graceful_shutdown()
  config = LoopConfig(
      model_dir=args.model_dir,
      num_actors=args.num_actors,
      max_train_steps=args.max_train_steps,
      batch_size=args.batch_size,
      save_interval_steps=args.save_interval_steps,
      episodes_per_shard=args.episodes_per_shard,
      actor_episode_interval_secs=args.actor_episode_interval_secs,
      window_records=args.window_records,
      starve_timeout_secs=args.starve_timeout_secs,
      crash_budget=args.crash_budget,
      seed=args.seed,
      serialize_serving=args.serialize_serving,
  )
  result = run_collect_train(config)
  logging.info(
      'Loop %s at step %d: actors %s, %d record(s) ingested while '
      'training.', 'PREEMPTED' if result.preempted else 'completed',
      result.final_step, result.actor_exit_codes, result.records_ingested)
  return resilience.PREEMPTED_EXIT_CODE if result.preempted else 0


if __name__ == '__main__':
  sys.exit(main())
