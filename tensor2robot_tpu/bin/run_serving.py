"""Serving binary: batched multi-client action serving from export roots.

Loads the newest committed export version (waiting for the trainer's
first export when ``--restore-timeout-secs`` is set), warms every batch
bucket, and serves ``POST /v1/predict`` with dynamic cross-client
batching. Hot model swap is on by default: the reload poller follows the
export root's commit markers and swaps new versions in between dispatches
with zero dropped requests (a torn or broken export leaves the last-good
model serving).

Single model:
  python -m tensor2robot_tpu.bin.run_serving \
      --export_dir /models/m/export/latest_exporter_numpy \
      --port 8000 --max-batch 64 --batch-deadline-ms 5 \
      --metricsz-port 8001 --compilation-cache-dir /var/cache/t2r-xla \
      --quantize int8

Multi-model (ModelRouter: N export roots, one device, LRU paging under
an HBM byte budget, priority-class admission control — best-effort
sheds with 503 + Retry-After before interactive is ever refused):
  python -m tensor2robot_tpu.bin.run_serving \
      --model grasp=/models/grasp/export --model eval=/models/eval/export \
      --hbm-budget-mb 4096 --shed-queue-fraction 0.25 --port 8000

Named models serve at ``POST /v1/models/<name>/predict``; the priority
class rides the ``X-Priority`` header. Replicas of this binary go behind
``tensor2robot_tpu.bin.run_balancer``.

SIGTERM/SIGINT drain: the HTTP listener stops, queued requests complete,
then the process exits 0 — a fleet scheduler can roll the serving tier
without failing client requests.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def main(argv=None):
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument('--export_dir', default=None,
                      help='Versioned export root (the trainer exporter '
                           'output, e.g. .../export/latest_exporter_numpy). '
                           'Single-model mode; exclusive with --model.')
  parser.add_argument('--model', action='append', default=[],
                      metavar='NAME=EXPORT_DIR',
                      help='Repeatable: serve EXPORT_DIR as model NAME '
                           'behind a ModelRouter (multi-model mode). '
                           'The first --model is the default model.')
  parser.add_argument('--hbm-budget-mb', type=float, default=None,
                      help='HBM byte budget for the router: models past '
                           'the budget are paged out LRU (host params + '
                           'compiled executables kept, so page-in is a '
                           'device_put, never a recompile). Unset: all '
                           'models stay resident.')
  parser.add_argument('--shed-queue-fraction', type=float, default=0.25,
                      help='Best-effort traffic sheds (503 + Retry-After) '
                           'once a model\'s queue passes this fraction of '
                           '--max-queue; interactive is only ever refused '
                           'by the hard bound itself.')
  parser.add_argument('--retry-after-secs', type=float, default=1.0,
                      help='Retry-After hint on shed responses.')
  parser.add_argument('--port', type=int, default=8000)
  parser.add_argument('--host', default='127.0.0.1',
                      help='Bind address; loopback by default — serving '
                           'beyond the host is an operator decision.')
  parser.add_argument('--max-batch', type=int, default=64,
                      help='Largest single device dispatch (the batch-64 '
                           'CEM optimum from BENCH_r05).')
  parser.add_argument('--batch-deadline-ms', type=float, default=5.0,
                      help='Max assembly wait: a batch dispatches at '
                           'max-batch examples or this deadline, '
                           'whichever first.')
  parser.add_argument('--max-queue', type=int, default=1024,
                      help='Queued-request bound; beyond it clients get '
                           '503 (backpressure, not unbounded latency).')
  parser.add_argument('--request-timeout-secs', type=float, default=30.0)
  parser.add_argument('--reload-interval-secs', type=float, default=10.0,
                      help='Export-root poll cadence for hot swap; '
                           '<= 0 disables reloading.')
  parser.add_argument('--restore-timeout-secs', type=float, default=0.0,
                      help='How long to wait for the FIRST export to '
                           'appear before giving up.')
  parser.add_argument('--metricsz-port', type=int, default=None,
                      help='Also serve the metrics registry (incl. the '
                           'serving report section) at /metricsz.')
  parser.add_argument('--compilation-cache-dir', default=None,
                      help='Persistent XLA cache: restarted servers '
                           'deserialize bucket executables instead of '
                           'recompiling (T2R_COMPILATION_CACHE_DIR).')
  parser.add_argument('--quantize', choices=('off', 'int8', 'fp8'),
                      default='off',
                      help='Weight-only quantized serving: int8 (or fp8 '
                           'where jaxlib supports float8_e4m3fn) params '
                           'with per-output-channel scales, dequantized '
                           'inline on-chip. Parity-gated: a generation '
                           'outside the band serves full precision '
                           'instead (serving/quant_parity_rejects).')
  parser.add_argument('--quant-parity-atol', type=float, default=0.05,
                      help='Absolute term of the quantization parity '
                           'band checked on calibration batches before '
                           'a quantized generation may serve.')
  parser.add_argument('--quant-parity-rtol', type=float, default=0.05,
                      help='Relative term of the quantization parity '
                           'band (scaled by the full-precision output '
                           'magnitude).')
  parser.add_argument('--request-trace-sample', type=float, default=0.0,
                      help='Fraction of requests whose queued/assembled/'
                           'dispatched/returned lifecycle is recorded '
                           'into the flight ring (0 disables; request '
                           'IDs + latency exemplars are always on).')
  parser.add_argument('--postmortem-dir', default=None,
                      help='Directory for incident bundles: a reload '
                           'failure falling back to the last-good model '
                           'dumps flight events + metrics history here; '
                           '--slo / --anomaly-watch escalations write '
                           'LIVE bundles to the same place (render with '
                           'tools/postmortem.py).')
  parser.add_argument('--slo', action='store_true',
                      help='Run the SLO burn-rate engine over the '
                           'serving objectives (per-class availability + '
                           'interactive latency threshold): multi-window '
                           'burn alerts land in /statz, /metricsz, the '
                           'flight ring, and — with --postmortem-dir — '
                           'one rate-limited live forensics bundle.')
  parser.add_argument('--slo-latency-threshold-ms', type=float,
                      default=512.0,
                      help='Interactive latency SLO threshold (good '
                           'request = at or under this).')
  parser.add_argument('--anomaly-watch', action='store_true',
                      help='Watch serving time-series signals (request '
                           'p99, queue depth, shed rate, page-in time) '
                           'with robust median/MAD detectors; anomalies '
                           'flag flight events and escalate to live '
                           'bundles.')
  args = parser.parse_args(argv)
  logging.basicConfig(
      level=logging.INFO,
      format='%(asctime)s %(levelname)s %(name)s: %(message)s')

  from tensor2robot_tpu.observability import metricsz
  from tensor2robot_tpu.predictors import ExportedModelPredictor
  from tensor2robot_tpu.serving import ModelRouter, ServingServer

  if bool(args.export_dir) == bool(args.model):
    parser.error('pass exactly one of --export_dir or --model NAME=DIR '
                 '(repeatable)')

  def load_predictor(export_dir):
    predictor = ExportedModelPredictor(
        export_dir=export_dir, timeout=args.restore_timeout_secs)
    if not predictor.restore():
      logging.error('No committed export appeared under %r within %.1fs.',
                    export_dir, args.restore_timeout_secs)
      return None
    return predictor

  reload_interval = (args.reload_interval_secs
                     if args.reload_interval_secs > 0 else None)
  batcher_kwargs = dict(
      max_batch=args.max_batch,
      batch_deadline_ms=args.batch_deadline_ms,
      max_queue=args.max_queue,
      reload_interval_secs=reload_interval,
      quantize=args.quantize,
      quant_parity_atol=args.quant_parity_atol,
      quant_parity_rtol=args.quant_parity_rtol,
      request_trace_sample=args.request_trace_sample,
      postmortem_dir=args.postmortem_dir)
  server_kwargs = dict(
      port=args.port,
      host=args.host,
      request_timeout_secs=args.request_timeout_secs,
      compilation_cache_dir=args.compilation_cache_dir)

  if args.model:
    predictors = {}
    default_model = None
    for spec in args.model:
      name, sep, export_dir = spec.partition('=')
      if not sep or not name or not export_dir:
        parser.error(f'--model {spec!r} is not NAME=EXPORT_DIR')
      predictor = load_predictor(export_dir)
      if predictor is None:
        return 1
      predictors[name] = predictor
      default_model = default_model or name
    router = ModelRouter(
        predictors,
        hbm_budget_bytes=(None if args.hbm_budget_mb is None
                          else int(args.hbm_budget_mb * 1e6)),
        default_model=default_model,
        shed_queue_fraction=args.shed_queue_fraction,
        retry_after_secs=args.retry_after_secs,
        **batcher_kwargs)
    server = ServingServer(router=router, **server_kwargs)
  else:
    predictor = load_predictor(args.export_dir)
    if predictor is None:
      return 1
    server = ServingServer(predictor, **server_kwargs, **batcher_kwargs)

  stop = threading.Event()

  def handle_signal(signum, frame):
    del frame
    logging.info('Received signal %d; draining and shutting down.', signum)
    stop.set()

  previous = {sig: signal.signal(sig, handle_signal)
              for sig in (signal.SIGTERM, signal.SIGINT)}
  engine = None
  watch = None
  try:
    with server:
      metricsz.maybe_start(args.metricsz_port)
      if args.slo:
        from tensor2robot_tpu.observability import slo as slo_lib

        models = (server.router.models()
                  if server.router is not None else [])
        engine = slo_lib.SLOEngine(
            slo_lib.serving_objectives(
                models=models,
                latency_threshold_ms=args.slo_latency_threshold_ms),
            postmortem_dir=args.postmortem_dir).start()
      if args.anomaly_watch:
        from tensor2robot_tpu.observability import anomaly as anomaly_lib

        watch = anomaly_lib.AnomalyWatch(
            postmortem_dir=args.postmortem_dir).start()
      if server.router is not None:
        logging.info('Serving models %s at %s',
                     server.router.versions(), server.url)
      else:
        logging.info('Serving model version %d at %s',
                     server.batcher.model_version, server.url)
      stop.wait()
  finally:
    if watch is not None:
      watch.stop()
    if engine is not None:
      engine.stop()
    for sig, handler in previous.items():
      signal.signal(sig, handler)
  return 0


if __name__ == '__main__':
  sys.exit(main())
