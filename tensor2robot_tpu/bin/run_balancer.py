"""Front-door balancer binary: M serving replicas behind one address.

Proxies ``POST /v1/predict`` / ``/v1/models/<name>/predict`` to the
healthy backend replica with the fewest outstanding requests, ejecting
backends whose ``/healthz`` fails and re-admitting them when it
recovers. Transport failures and 503s fail over to the next backend, so
a rolling deploy of the replica tier (``run_serving`` drains on
SIGTERM) never drops a client request. ``X-Request-Id`` and
``X-Priority`` headers are forwarded; the request ID is echoed on every
response status.

Usage:
  python -m tensor2robot_tpu.bin.run_balancer \
      --backend 10.0.0.1:8000 --backend 10.0.0.2:8000 \
      --port 9000 --metricsz-port 9001

``GET /healthz`` answers for the balancer itself (200 iff >= 1 healthy
backend); ``GET /statz`` returns per-backend health/outstanding/traffic
plus the fleet-wide slow-request log (top-k merged live from every
healthy backend, with backend attribution); ``GET /tracez`` serves the
balancer's span index — a client ``traceparent`` header records the
proxy hop and every backend attempt under the fleet-wide trace id
(assemble with ``tools/assemble_trace.py``).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def main(argv=None):
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument('--backend', action='append', default=[],
                      metavar='HOST:PORT', required=True,
                      help='Repeatable: one serving replica.')
  parser.add_argument('--port', type=int, default=9000)
  parser.add_argument('--host', default='127.0.0.1',
                      help='Bind address; loopback by default.')
  parser.add_argument('--health-interval-secs', type=float, default=0.5,
                      help='Backend /healthz poll cadence.')
  parser.add_argument('--eject-after', type=int, default=2,
                      help='Consecutive health failures before ejection.')
  parser.add_argument('--readmit-after', type=int, default=1,
                      help='Consecutive health successes before '
                           're-admission.')
  parser.add_argument('--proxy-timeout-secs', type=float, default=30.0)
  parser.add_argument('--fleet-slow-k', type=int, default=10,
                      help='Rows in the /statz fleet-wide slow-request '
                           'merge (0 disables the backend scrape).')
  parser.add_argument('--metricsz-port', type=int, default=None,
                      help='Also serve the metrics registry (incl. the '
                           'balancer report section) at /metricsz.')
  args = parser.parse_args(argv)
  logging.basicConfig(
      level=logging.INFO,
      format='%(asctime)s %(levelname)s %(name)s: %(message)s')

  from tensor2robot_tpu.observability import metricsz
  from tensor2robot_tpu.serving import Balancer

  balancer = Balancer(
      args.backend,
      port=args.port,
      host=args.host,
      health_interval_secs=args.health_interval_secs,
      eject_after=args.eject_after,
      readmit_after=args.readmit_after,
      proxy_timeout_secs=args.proxy_timeout_secs,
      fleet_slow_k=args.fleet_slow_k)

  stop = threading.Event()

  def handle_signal(signum, frame):
    del frame
    logging.info('Received signal %d; shutting down balancer.', signum)
    stop.set()

  previous = {sig: signal.signal(sig, handle_signal)
              for sig in (signal.SIGTERM, signal.SIGINT)}
  try:
    with balancer:
      metricsz.maybe_start(args.metricsz_port)
      logging.info('Balancing %d backend(s) at %s',
                   balancer.backend_count(), balancer.url)
      stop.wait()
  finally:
    for sig, handler in previous.items():
      signal.signal(sig, handler)
  return 0


if __name__ == '__main__':
  sys.exit(main())
