"""Convert legacy pickle-based export assets to ``t2r_assets.pbtxt``.

Migration tool for exports produced by the original framework before its
proto-assets era (equivalent of
``/root/reference/utils/convert_pkl_assets_to_proto_assets.py``): reads
``<assets_dir>/input_specs.pkl`` (+ optional ``global_step.pkl``) through
a restricted legacy unpickler — no TensorFlow or original package needed
— and writes ``<assets_dir>/t2r_assets.pbtxt`` in this framework's
format.

Usage::

    python -m tensor2robot_tpu.bin.convert_pkl_assets \
        --assets_filepath /path/to/export/assets.extra
"""

from __future__ import annotations

import argparse
import os

from tensor2robot_tpu.specs import assets as assets_lib
from tensor2robot_tpu.specs import legacy_pickle


def convert(assets_filepath: str) -> str:
  """Converts one assets directory; returns the written pbtxt path."""
  input_spec_path = os.path.join(assets_filepath, 'input_specs.pkl')
  if not os.path.exists(input_spec_path):
    raise ValueError(f'No file exists for {input_spec_path}.')
  feature_spec, label_spec = legacy_pickle.load_input_spec_from_file(
      input_spec_path)

  global_step = 0
  global_step_path = os.path.join(assets_filepath, 'global_step.pkl')
  if os.path.exists(global_step_path):
    global_step = legacy_pickle.load_global_step_from_file(global_step_path)

  out_path = os.path.join(assets_filepath, assets_lib.T2R_ASSETS_FILENAME)
  assets_lib.write_t2r_assets_to_file(
      assets_lib.make_t2r_assets(feature_spec, label_spec, global_step),
      out_path)
  return out_path


def main(argv=None):
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument('--assets_filepath', required=True,
                      help='Exported-model assets directory holding '
                           'input_specs.pkl.')
  args = parser.parse_args(argv)
  path = convert(args.assets_filepath)
  print(f'Wrote {path}')


if __name__ == '__main__':
  main()
