"""Checkpointing: Orbax-backed save/restore of the TrainState.

Capability-equivalent of the reference's checkpoint machinery:
``tf.train.Saver`` registration with ``max_to_keep`` /
``keep_checkpoint_every_n_hours`` (``models/abstract_model.py:782-793``),
async checkpointing (``hooks/async_export_hook_builder.py:124-137``),
restart-from-latest Estimator semantics, and the continuous evaluator's
checkpoint BACKUP: a separate evaluator process copies the step it wants
to evaluate into its own directory first, so the trainer's retention GC
cannot delete it mid-restore (``utils/train_eval.py:590-707``).

Atomic commit protocol (the distributed-resilience extension): every
finished checkpoint step carries a ``commit.json`` marker recording the
run topology (process count, mesh shape, microbatch config) and, in
multi-process runs, an ack file from EVERY host. A checkpoint is only
*visible* — to ``restore``, :func:`latest_checkpoint_step`, the
continuous evaluator and the predictors — once the marker exists, which
happens strictly after all hosts finished writing (barriered over the
``jax.distributed`` coordination service, ``train/
distributed_resilience.py``). A step without its marker is a TORN
checkpoint (a save cut off by preemption or a dead host) and is skipped
with a ``checkpoint/torn_skipped`` count; a marker whose topology does
not match the current run fails loudly instead of silently
misinterpreting the state. Directories written before this protocol
(no markers anywhere) keep the PR-1 behavior: try newest, fall back on
parse errors.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

import jax
import orbax.checkpoint as ocp

from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.observability import tracing
from tensor2robot_tpu.train.distributed_resilience import (
    DistributedContext, TopologyMismatchError)

COMMIT_FILENAME = 'commit.json'
HOST_ACK_PREFIX = 'host_ack_'

# (directory, step) pairs already reported as torn, so polling callers
# (checkpoints_iterator scans every second) count each torn checkpoint
# once rather than once per scan.
_REPORTED_TORN: Set[Tuple[str, int]] = set()


def _step_dir(directory: str, step: int) -> str:
  return os.path.join(directory, f'ckpt_{int(step)}')


def commit_marker_path(directory: str, step: int) -> str:
  return os.path.join(_step_dir(directory, step), COMMIT_FILENAME)


def read_commit_marker(directory: str, step: int) -> Optional[Dict[str, Any]]:
  """The commit marker for ``step``, or None if absent/unreadable."""
  try:
    with open(commit_marker_path(directory, step)) as f:
      return json.load(f)
  except (OSError, ValueError):
    return None


def write_commit_marker(directory: str, step: int,
                        topology: Optional[Dict[str, Any]] = None,
                        hosts: Optional[List[int]] = None) -> str:
  """Atomically publishes the commit marker for ``step``."""
  payload = {
      'step': int(step),
      'time': time.time(),
      'hosts': sorted(hosts) if hosts is not None else [0],
  }
  if topology is not None:
    payload['topology'] = dict(topology)
  path = commit_marker_path(directory, step)
  tmp = f'{path}.tmp{os.getpid()}'
  with open(tmp, 'w') as f:
    json.dump(payload, f, indent=2)
    f.flush()
    os.fsync(f.fileno())
  os.replace(tmp, path)
  return path


def _fs_steps(directory: str) -> List[int]:
  """Step numbers present on disk (any commit status), ascending."""
  try:
    names = os.listdir(directory)
  except FileNotFoundError:
    return []
  steps = []
  for name in names:
    if not name.startswith('ckpt_') or name.endswith('.orbax-checkpoint-tmp'):
      continue
    suffix = name.rsplit('_', 1)[-1]
    if suffix.isdigit():
      steps.append(int(suffix))
  return sorted(steps)


def _report_torn(directory: str, step: int, where: str) -> None:
  key = (os.path.abspath(directory), int(step))
  if key in _REPORTED_TORN:
    return
  _REPORTED_TORN.add(key)
  metrics_lib.counter('checkpoint/torn_skipped').inc()
  logging.warning(
      'Checkpoint step %d under %r has no commit marker — a torn '
      'checkpoint (save cut off by preemption or a dead host); skipping '
      'it in %s.', step, directory, where)


def _committed_steps(directory: str, steps: List[int],
                     where: str) -> Tuple[List[int], bool]:
  """Filters ``steps`` to committed ones under the legacy rule.

  Returns ``(visible_steps, protocol_active)``: if NO step carries a
  marker the directory predates the commit protocol and every step stays
  visible (PR-1 behavior); once any marker exists, unmarked steps are
  torn and are skipped with a ``checkpoint/torn_skipped`` count.
  """
  marked = [s for s in steps
            if os.path.exists(commit_marker_path(directory, s))]
  if not marked:
    return steps, False
  for s in steps:
    if s not in marked:
      _report_torn(directory, s, where)
  return marked, True


def _check_topology(saved: Optional[Dict[str, Any]],
                    expected: Optional[Dict[str, Any]],
                    directory: str, step: int) -> None:
  """Loud, actionable error when a checkpoint's topology mismatches."""
  if not saved or not expected:
    return
  mismatches = {
      key: (saved[key], expected[key])
      for key in sorted(set(saved) & set(expected))
      if saved[key] != expected[key]
  }
  if not mismatches:
    return
  detail = '; '.join(
      f'{key}: checkpoint has {was!r}, this run has {now!r}'
      for key, (was, now) in mismatches.items())
  raise TopologyMismatchError(
      f'Checkpoint step {step} under {directory!r} was saved with a '
      f'different topology than this run: {detail}. Restoring it would '
      f'silently misinterpret the saved state. Either relaunch with the '
      f'recorded topology (e.g. the same number of processes and mesh '
      f'shape), or — if the change is intentional — disable the check '
      f'with TrainerConfig.checkpoint_topology_check=False / '
      f'CheckpointManager(topology=None).')


class CheckpointManager:
  """Orbax wrapper with an atomic (multi-host-aware) commit protocol.

  Single-process: the Orbax manager behaves as before, plus every
  finalized step gets a ``commit.json`` marker (written once the async
  write is known complete — at the next ``save`` or at
  ``wait_until_finished``), and ``restore`` prefers committed steps.

  Multi-process (``distributed`` context passed): process 0 is the
  single payload writer — its Orbax manager runs with
  ``active_processes={0}`` so Orbax's internal barriers never span the
  job — and commit requires every host:

    1. primary saves the payload (synchronously) and waits;
    2. barrier; every host writes its ``host_ack_<p>.json`` into the
       step dir (the per-host "shard" — carrying process metadata — that
       fault injection can corrupt);
    3. barrier; primary validates all acks and atomically publishes
       ``commit.json`` with the run topology;
    4. barrier; ``save`` returns True on every host.

  Any host dying mid-protocol leaves the step UNCOMMITTED (never
  restored) and surfaces as a bounded
  :class:`~tensor2robot_tpu.train.distributed_resilience.DeadHostError`
  on the survivors instead of a hang.
  """

  def __init__(self,
               directory: str,
               max_to_keep: Optional[int] = 5,
               keep_period: Optional[int] = None,
               save_interval_steps: int = 1,
               async_save: bool = True,
               topology: Optional[Dict[str, Any]] = None,
               distributed: Optional[DistributedContext] = None,
               barrier_timeout_secs: float = 600.0):
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    self._directory = directory
    self._topology = dict(topology) if topology else None
    self._ctx = distributed
    self._barrier_timeout = float(barrier_timeout_secs)
    self._save_interval = max(1, int(save_interval_steps))
    self._save_seq = 0  # barrier-id uniqueness across repeated saves
    self._pending_marker: Optional[int] = None
    self._manager: Optional[ocp.CheckpointManager] = None
    self._restore_checkpointer = None
    if self._ctx is None or self._ctx.is_primary:
      extra = {}
      if self._ctx is not None:
        # Orbax must never barrier across the job: our commit protocol
        # owns cross-host ordering (over the coordination service, with
        # bounded timeouts); Orbax's own syncs collapse to this process.
        # Multi-process commit is also barrier-synchronous — the marker
        # must only be published once the payload is durably on disk —
        # so async writes buy nothing and are disabled. Orbax refuses
        # create=True with active_processes set; the root directory was
        # created above.
        async_save = False
        extra = dict(
            create=False,
            multiprocessing_options=ocp.options.MultiprocessingOptions(
                primary_host=self._ctx.process_index,
                active_processes={self._ctx.process_index},
                barrier_sync_key_prefix=(
                    f't2r_ckpt_p{self._ctx.process_index}')))
      options = ocp.CheckpointManagerOptions(
          max_to_keep=max_to_keep,
          keep_period=keep_period,
          save_interval_steps=save_interval_steps,
          enable_async_checkpointing=async_save,
          step_prefix='ckpt',
          **extra)
      self._manager = ocp.CheckpointManager(directory, options=options)

  @property
  def directory(self) -> str:
    return self._directory

  @property
  def topology(self) -> Optional[Dict[str, Any]]:
    return self._topology

  def _flush_pending_marker(self) -> None:
    """Publishes the marker for the last async save once it finished.

    Called with the Orbax write known complete (after
    ``wait_until_finished`` or at the head of the next ``save`` — Orbax
    serializes saves, so starting a new one implies the previous write
    is durable). A crash before this point correctly leaves the step
    uncommitted: its write may be torn.
    """
    if self._pending_marker is None:
      return
    step, self._pending_marker = self._pending_marker, None
    if os.path.isdir(_step_dir(self._directory, step)):
      write_commit_marker(self._directory, step, topology=self._topology)
    else:
      # Retention GC may legitimately have collected the step already;
      # anything else (e.g. a still-unfinalized write) is a bug worth
      # hearing about — the step would read as torn forever.
      logging.warning(
          'Commit marker for checkpoint step %d skipped: step directory '
          'no longer exists under %r.', step, self._directory)

  def save(self, step: int, state, force: bool = False) -> bool:
    step = int(step)
    if self._ctx is not None:
      return self._save_distributed(step, state, force)
    # Hand Orbax the DEVICE arrays: its async path owns the device→host
    # copy (blocking only for the D2H transfer, writing to disk in the
    # background). An eager jax.device_get here would serialize a full
    # host copy into the train loop even with async_save=True, defeating
    # async checkpointing. Safe against buffer donation: Orbax completes
    # the D2H copy before save() returns.
    if step in self._manager.all_steps():
      return False  # already saved (e.g. final forced save after an in-loop one)
    # checkpoint/save_ms is what the TRAIN LOOP pays (with async_save it
    # covers only the blocking D2H copy; the disk write happens in the
    # background and is accounted by checkpoint/wait_ms at barriers).
    with tracing.span('checkpoint/save'):
      if self._pending_marker is not None:
        # The previous async write must be DURABLE before its marker is
        # published (the whole point of the marker). Orbax's save would
        # wait on it internally anyway, so this adds no stall.
        self._manager.wait_until_finished()
        self._flush_pending_marker()
      saved = self._manager.save(
          step, args=ocp.args.StandardSave(state), force=force)
    if saved:
      self._pending_marker = step
      metrics_lib.counter('checkpoint/saves').inc()
    return saved

  def _save_distributed(self, step: int, state, force: bool) -> bool:
    """The multi-host commit protocol; every host calls this at the same
    step (the trainer's boundaries guarantee it)."""
    ctx = self._ctx
    if read_commit_marker(self._directory, step) is not None:
      return False  # already committed; consistent across hosts
    if not force and step % self._save_interval:
      return False  # mirror Orbax's own interval gate, identically per host
    self._save_seq += 1
    seq = self._save_seq
    step_dir = _step_dir(self._directory, step)
    with tracing.span('checkpoint/save'):
      if self._manager is not None:
        # Single payload writer. The host copy is explicit: with a
        # per-host mesh in a multi-process runtime Orbax refuses device
        # arrays, and the commit barriers serialize on the write anyway.
        if step not in self._manager.all_steps():
          self._manager.save(
              step, args=ocp.args.StandardSave(jax.device_get(state)),
              force=True)
          self._manager.wait_until_finished()
      ctx.barrier(f'ckpt/{step}/{seq}/saved', self._barrier_timeout)
      # Every host acknowledges INTO the step dir: the commit marker is
      # only written over a complete set of acks, so a host that died
      # before finishing leaves the step uncommitted.
      ack = {
          'process_index': ctx.process_index,
          'step': step,
          'pid': os.getpid(),
          'time': time.time(),
      }
      ack_path = os.path.join(
          step_dir, f'{HOST_ACK_PREFIX}{ctx.process_index}.json')
      tmp = f'{ack_path}.tmp{os.getpid()}'
      with open(tmp, 'w') as f:
        json.dump(ack, f)
        f.flush()
        os.fsync(f.fileno())
      os.replace(tmp, ack_path)
      ctx.barrier(f'ckpt/{step}/{seq}/acked', self._barrier_timeout)
      if ctx.is_primary:
        acked = self._read_acks(step)
        missing = set(range(ctx.process_count)) - set(acked)
        if missing:
          raise RuntimeError(
              f'checkpoint step {step}: host ack(s) missing for '
              f'process(es) {sorted(missing)} AFTER the ack barrier '
              f'passed — the shared filesystem dropped or corrupted '
              f'them; refusing to commit a torn checkpoint.')
        write_commit_marker(self._directory, step, topology=self._topology,
                            hosts=sorted(acked))
      ctx.barrier(f'ckpt/{step}/{seq}/committed', self._barrier_timeout)
    metrics_lib.counter('checkpoint/saves').inc()
    return True

  def _read_acks(self, step: int) -> List[int]:
    step_dir = _step_dir(self._directory, step)
    acked = []
    try:
      names = os.listdir(step_dir)
    except FileNotFoundError:
      return acked
    for name in names:
      if not (name.startswith(HOST_ACK_PREFIX) and name.endswith('.json')):
        continue
      try:
        with open(os.path.join(step_dir, name)) as f:
          acked.append(int(json.load(f)['process_index']))
      except (OSError, ValueError, KeyError, TypeError):
        continue  # unparseable ack == no ack: the step stays uncommitted
    return acked

  def _restore_payload(self, step: int, target):
    """Reads one step's payload into ``target``'s structure."""
    if self._manager is not None:
      return self._manager.restore(
          int(step), args=ocp.args.StandardRestore(target))
    # Non-primary host: single-process read of the committed payload.
    if self._restore_checkpointer is None:
      ctx = self._ctx
      self._restore_checkpointer = ocp.Checkpointer(
          ocp.StandardCheckpointHandler(),
          multiprocessing_options=ocp.options.MultiprocessingOptions(
              primary_host=ctx.process_index,
              active_processes={ctx.process_index},
              barrier_sync_key_prefix=f't2r_restore_p{ctx.process_index}'))
    item_dir = os.path.join(_step_dir(self._directory, step), 'default')
    if not os.path.isdir(item_dir):
      item_dir = _step_dir(self._directory, step)
    return self._restore_checkpointer.restore(
        item_dir, args=ocp.args.StandardRestore(target))

  def restore(self, state, step: Optional[int] = None,
              fallback_to_older: bool = True):
    """Restores into the structure of ``state`` (an abstract/concrete tree).

    Only COMMITTED steps are candidates once the commit protocol is in
    use (any marker present); a step missing its marker is torn and is
    never restored (``checkpoint/torn_skipped``). The committed step's
    recorded topology must match this manager's (when both are known) or
    a :class:`TopologyMismatchError` explains the mismatch.

    With ``fallback_to_older`` (the default when no explicit ``step`` is
    requested), a truncated/corrupt latest checkpoint — the signature of
    a save cut off by preemption or a torn filesystem — falls back to
    the next-older step instead of killing the resume. Only when EVERY
    step fails does the last error propagate; an explicit ``step``
    restores exactly that step or raises.
    """
    if step is not None:
      step = int(step)
      _, protocol_active = _committed_steps(
          self._directory, _fs_steps(self._directory), 'restore')
      marker = read_commit_marker(self._directory, step)
      if protocol_active and marker is None:
        raise RuntimeError(
            f'checkpoint step {step} under {self._directory!r} has no '
            f'commit marker (torn/uncommitted); refusing to restore it.')
      if marker is not None:
        _check_topology(marker.get('topology'), self._topology,
                        self._directory, step)
      with tracing.span('checkpoint/restore'):
        restored = self._restore_payload(step, jax.device_get(state))
      metrics_lib.counter('checkpoint/restores').inc()
      return restored
    steps, _ = _committed_steps(
        self._directory, _fs_steps(self._directory), 'restore')
    steps = sorted(steps, reverse=True)
    if not steps:
      return None
    target = jax.device_get(state)
    last_exc: Optional[BaseException] = None
    for i, s in enumerate(steps):
      marker = read_commit_marker(self._directory, s)
      if marker is not None:
        # Topology mismatch is NOT a fallback case: every step in this
        # directory came from the same job shape, so older steps would
        # fail identically — raise the actionable error instead.
        _check_topology(marker.get('topology'), self._topology,
                        self._directory, s)
      try:
        with tracing.span('checkpoint/restore'):
          restored = self._restore_payload(s, target)
        metrics_lib.counter('checkpoint/restores').inc()
        if i > 0:
          metrics_lib.counter('checkpoint/restore_fallbacks').inc(i)
          logging.warning(
              'Restored checkpoint step %d after %d newer step(s) failed '
              'to load (latest was likely truncated by a preemption).', s, i)
        return restored
      except Exception as e:  # pylint: disable=broad-except
        last_exc = e
        if not fallback_to_older:
          raise
        logging.warning(
            'Checkpoint step %d failed to restore (%r); falling back to '
            'the next-older step.', s, e)
    raise RuntimeError(
        f'All {len(steps)} checkpoint(s) under {self._directory!r} failed '
        f'to restore; last error: {last_exc!r}') from last_exc

  def latest_step(self) -> Optional[int]:
    if self._manager is not None and self._ctx is None:
      return self._manager.latest_step()
    steps = _fs_steps(self._directory)
    return steps[-1] if steps else None

  def latest_committed_step(self) -> Optional[int]:
    """Newest step ``restore`` would actually consider."""
    steps, _ = _committed_steps(
        self._directory, _fs_steps(self._directory), 'latest_committed_step')
    return steps[-1] if steps else None

  def all_steps(self):
    if self._manager is not None and self._ctx is None:
      return sorted(self._manager.all_steps())
    return _fs_steps(self._directory)

  def wait_until_finished(self) -> None:
    # Time the train loop spends barriered on in-flight async writes.
    with tracing.span('checkpoint/wait'):
      if self._manager is not None:
        self._manager.wait_until_finished()
      self._flush_pending_marker()

  def close(self) -> None:
    if self._manager is not None:
      self._manager.wait_until_finished()
      self._flush_pending_marker()
      self._manager.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


def latest_checkpoint_step(directory: str) -> Optional[int]:
  """Latest COMMITTED step in ``directory`` without opening a manager.

  Non-numeric ``ckpt_*`` entries (stray tmp dirs, editor droppings,
  backup copies) are skipped rather than crashing the scan — this
  function gates resume decisions and continuous eval, so it must stay
  robust to whatever accumulates in a long-lived model dir.

  Commit-aware: once any step in the directory carries a commit marker,
  unmarked steps are torn (or still being written) and are not reported
  — so the continuous evaluator and the predictors never pick up a
  checkpoint mid-write. Each torn step counts ``checkpoint/torn_skipped``
  once (not once per poll). Marker-less legacy directories behave as
  before.
  """
  steps, _ = _committed_steps(directory, _fs_steps(directory),
                              'latest_checkpoint_step')
  return steps[-1] if steps else None


EVAL_BACKUP_DIRNAME = 'current_eval_checkpoint'


def create_backup_checkpoint_for_eval(ckpt_dir: str,
                                      step: int,
                                      backup_dir: str,
                                      num_retries: int = 3
                                      ) -> Optional[str]:
  """Copies checkpoint ``step`` into the evaluator's own directory.

  The guard of ``utils/train_eval.py:590-707``: the trainer's retention
  GC may delete ``step`` at any moment, so the copy is retried and
  validated — the source must still exist AFTER the copy completes
  (a vanished source means the copy may be partial). Returns the backed-up
  step directory, or None if the checkpoint was GC'd before a complete
  copy was made.
  """
  src = os.path.join(ckpt_dir, f'ckpt_{int(step)}')
  os.makedirs(backup_dir, exist_ok=True)
  final = os.path.join(backup_dir, f'ckpt_{int(step)}')
  if os.path.isdir(final):
    return final  # already backed up
  for _ in range(num_retries):
    if not os.path.isdir(src):
      return None
    tmp = os.path.join(backup_dir, f'.tmp_ckpt_{int(step)}')
    shutil.rmtree(tmp, ignore_errors=True)
    try:
      shutil.copytree(src, tmp)
    except (FileNotFoundError, shutil.Error):
      continue  # GC raced the copy; retry
    if not os.path.isdir(src):
      # Source vanished mid-copy: the copy may be truncated. Retry.
      shutil.rmtree(tmp, ignore_errors=True)
      continue
    # Keep only this step in the backup dir (one eval at a time).
    for name in os.listdir(backup_dir):
      if name.startswith('ckpt_'):
        shutil.rmtree(os.path.join(backup_dir, name), ignore_errors=True)
    os.replace(tmp, final)
    return final
  return None


def restore_from_backup(state, backup_step_dir: str):
  """Restores a TrainState from a backed-up step directory."""
  checkpointer = ocp.StandardCheckpointer()
  # The state payload lives in the 'default' item of the step dir.
  item_dir = os.path.join(os.path.abspath(backup_step_dir), 'default')
  if not os.path.isdir(item_dir):
    item_dir = os.path.abspath(backup_step_dir)
  return checkpointer.restore(item_dir, jax.device_get(state))


def checkpoints_iterator(directory: str,
                         min_interval_secs: float = 1.0,
                         timeout: Optional[float] = None,
                         stop_after_step: Optional[int] = None
                         ) -> Iterator[int]:
  """Yields new checkpoint steps as they appear (continuous evaluator).

  The filesystem-watching contract of
  ``tf.contrib.training.checkpoints_iterator`` used by the reference's
  continuous eval loop (``utils/train_eval.py:550-585``).
  """
  last_seen = None
  deadline = None if timeout is None else time.time() + timeout
  while True:
    step = latest_checkpoint_step(directory)
    if step is not None and step != last_seen:
      last_seen = step
      deadline = None if timeout is None else time.time() + timeout
      yield step
      if stop_after_step is not None and step >= stop_after_step:
        return
      continue
    if deadline is not None and time.time() > deadline:
      return
    time.sleep(min_interval_secs)
