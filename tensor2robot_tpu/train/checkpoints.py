"""Checkpointing: Orbax-backed save/restore of the TrainState.

Capability-equivalent of the reference's checkpoint machinery:
``tf.train.Saver`` registration with ``max_to_keep`` /
``keep_checkpoint_every_n_hours`` (``models/abstract_model.py:782-793``),
async checkpointing (``hooks/async_export_hook_builder.py:124-137``),
restart-from-latest Estimator semantics, and the continuous evaluator's
checkpoint BACKUP: a separate evaluator process copies the step it wants
to evaluate into its own directory first, so the trainer's retention GC
cannot delete it mid-restore (``utils/train_eval.py:590-707``).

Atomic commit protocol (the distributed-resilience extension): every
finished checkpoint step carries a ``commit.json`` marker recording the
run topology (process count, mesh shape, microbatch config) and, in
multi-process runs, an ack file from EVERY participating host. A
checkpoint is only *visible* — to ``restore``,
:func:`latest_checkpoint_step`, the continuous evaluator and the
predictors — once the marker exists, which happens strictly after all
hosts finished writing (barriered over the ``jax.distributed``
coordination service, ``train/distributed_resilience.py``). A step
without its marker is a TORN checkpoint (a save cut off by preemption or
a dead host) and is skipped with a ``checkpoint/torn_skipped`` count; a
marker whose topology does not match the current run fails loudly
instead of silently misinterpreting the state. Directories written
before this protocol (no markers anywhere) keep the PR-1 behavior: try
newest, fall back on parse errors.

Elastic topology (the pod-scale extension):

* **Sharded multi-host payloads** (``sharded=True``): instead of process
  0 writing the full state, EVERY host writes its own shards through
  Orbax's multiprocess writers (``active_processes`` = the participant
  set, barriers over the coordination service — never an XLA
  collective). States already laid out on a process-spanning mesh (true
  FSDP) save their global arrays directly; per-host replica-group states
  are re-expressed as striped global arrays first
  (:func:`~tensor2robot_tpu.parallel.mesh.build_global_save_view`). The
  commit marker/ack protocol is unchanged — a host killed mid-write
  leaves the step torn and invisible.
* **Resharding restore** (``reshape=True``): the marker's recorded
  topology becomes a restore-time PARAMETER instead of a constraint — an
  N-host checkpoint restores onto an M-host mesh by building target
  shardings from the *current* mesh
  (``parallel/mesh.state_shardings_for``) and letting Orbax reshard on
  read. :class:`TopologyMismatchError` remains only for semantic
  mismatches (microbatch config, steps-per-dispatch) whose silent
  acceptance would change training, not for host/mesh shape.
* **Async multi-host commit** (``async_commit=True``): the payload write
  starts immediately at the save point, while the ack/marker agreement
  rides subsequent dispatch boundaries (``poll_async_commit``) instead
  of blocking the loop; ``checkpoint/save_overlap_ms`` records how much
  write time was hidden. Forced saves (preemption, the final save) and
  ``wait_until_finished`` take the synchronous barriered path, so a
  shutdown never leaves a durable payload without its marker.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from tensor2robot_tpu.observability import flight
from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.observability import tracing
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.train.distributed_resilience import (
    DeadHostError, DistributedContext, TopologyMismatchError)

COMMIT_FILENAME = 'commit.json'
HOST_ACK_PREFIX = 'host_ack_'

# Payload formats recorded in the commit marker (and surfaced by
# tools/inspect_checkpoint.py).
FORMAT_SINGLE_WRITER = 'single_writer'
FORMAT_SHARDED = 'sharded'

# Topology keys that describe WHERE the state lived, not WHAT it means:
# restore(reshape=True) treats a mismatch on these as a resharding
# request. Everything else (microbatch config, steps_per_dispatch)
# changes training semantics and always fails loudly.
RESHAPE_KEYS = frozenset(
    {'process_count', 'device_count', 'mesh_shape', 'mesh_spans_processes'})

# (directory, step) pairs already reported as torn, so polling callers
# (checkpoints_iterator scans every second) count each torn checkpoint
# once rather than once per scan.
_REPORTED_TORN: Set[Tuple[str, int]] = set()

# Test-only fault-injection hook (utils/faults.install_kill_during_save):
# called on every host with the step number once the sharded payload
# write has been STARTED on this host, strictly before any ack/commit —
# the window where a SIGKILL models a host dying mid-save.
_during_save_hook: Optional[Callable[[int], None]] = None


def _step_dir(directory: str, step: int) -> str:
  return os.path.join(directory, f'ckpt_{int(step)}')


def commit_marker_path(directory: str, step: int) -> str:
  return os.path.join(_step_dir(directory, step), COMMIT_FILENAME)


def read_commit_marker(directory: str, step: int) -> Optional[Dict[str, Any]]:
  """The commit marker for ``step``, or None if absent/unreadable."""
  try:
    with open(commit_marker_path(directory, step)) as f:
      return json.load(f)
  except (OSError, ValueError):
    return None


def write_commit_marker(directory: str, step: int,
                        topology: Optional[Dict[str, Any]] = None,
                        hosts: Optional[List[int]] = None,
                        extra: Optional[Dict[str, Any]] = None) -> str:
  """Atomically publishes the commit marker for ``step``."""
  payload = {
      'step': int(step),
      'time': time.time(),
      'hosts': sorted(hosts) if hosts is not None else [0],
  }
  if topology is not None:
    payload['topology'] = dict(topology)
  if extra:
    payload.update(extra)
  path = commit_marker_path(directory, step)
  tmp = f'{path}.tmp{os.getpid()}'
  with open(tmp, 'w') as f:
    json.dump(payload, f, indent=2)
    f.flush()
    os.fsync(f.fileno())
  os.replace(tmp, path)
  # The commit point of the whole protocol — the one event a postmortem
  # must have to say "this step WAS durable before the process died".
  flight.event('checkpoint', 'checkpoint/commit',
               f'step={int(step)} hosts={payload["hosts"]}')
  return path


def _fs_steps(directory: str) -> List[int]:
  """Step numbers present on disk (any commit status), ascending."""
  try:
    names = os.listdir(directory)
  except FileNotFoundError:
    return []
  steps = []
  for name in names:
    if not name.startswith('ckpt_') or name.endswith('.orbax-checkpoint-tmp'):
      continue
    suffix = name.rsplit('_', 1)[-1]
    if suffix.isdigit():
      steps.append(int(suffix))
  return sorted(steps)


def _report_torn(directory: str, step: int, where: str) -> None:
  key = (os.path.abspath(directory), int(step))
  if key in _REPORTED_TORN:
    return
  _REPORTED_TORN.add(key)
  metrics_lib.counter('checkpoint/torn_skipped').inc()
  flight.event('checkpoint', 'checkpoint/torn_skip',
               f'step={int(step)} where={where}')
  logging.warning(
      'Checkpoint step %d under %r has no commit marker — a torn '
      'checkpoint (save cut off by preemption or a dead host); skipping '
      'it in %s.', step, directory, where)


def _committed_steps(directory: str, steps: List[int],
                     where: str) -> Tuple[List[int], bool]:
  """Filters ``steps`` to committed ones under the legacy rule.

  Returns ``(visible_steps, protocol_active)``: if NO step carries a
  marker the directory predates the commit protocol and every step stays
  visible (PR-1 behavior); once any marker exists, unmarked steps are
  torn and are skipped with a ``checkpoint/torn_skipped`` count.
  """
  marked = [s for s in steps
            if os.path.exists(commit_marker_path(directory, s))]
  if not marked:
    return steps, False
  for s in steps:
    if s not in marked:
      _report_torn(directory, s, where)
  return marked, True


def _check_topology(saved: Optional[Dict[str, Any]],
                    expected: Optional[Dict[str, Any]],
                    directory: str, step: int,
                    reshape: bool = False) -> Dict[str, Tuple[Any, Any]]:
  """Validates a checkpoint's recorded topology against this run's.

  Returns the mismatches that were DEMOTED to a resharding request
  (``reshape=True`` and every mismatched key is in :data:`RESHAPE_KEYS`)
  — empty when the topologies agree. Raises
  :class:`TopologyMismatchError` for semantic mismatches, or for any
  mismatch when ``reshape`` is off.
  """
  if not saved or not expected:
    return {}
  mismatches = {
      key: (saved[key], expected[key])
      for key in sorted(set(saved) & set(expected))
      if saved[key] != expected[key]
  }
  if not mismatches:
    return {}
  semantic = {k: v for k, v in mismatches.items() if k not in RESHAPE_KEYS}
  if reshape and not semantic:
    return mismatches
  detail = '; '.join(
      f'{key}: checkpoint has {was!r}, this run has {now!r}'
      for key, (was, now) in mismatches.items())
  hint = (
      'Either relaunch with the recorded topology (e.g. the same number '
      'of processes and mesh shape), restore elastically with '
      'reshape=True (TrainerConfig.checkpoint_reshape) if only the '
      'host/mesh layout changed, or — if the change is intentional — '
      'disable the check with TrainerConfig.checkpoint_topology_check='
      'False / CheckpointManager(topology=None).')
  if reshape and semantic:
    semantic_keys = ', '.join(sorted(semantic))
    hint = (
        f'reshape=True covers only the host/mesh layout '
        f'({", ".join(sorted(RESHAPE_KEYS))}); {semantic_keys} changes '
        f'what the saved state MEANS, so it must match (or disable the '
        f'check with TrainerConfig.checkpoint_topology_check=False).')
  raise TopologyMismatchError(
      f'Checkpoint step {step} under {directory!r} was saved with a '
      f'different topology than this run: {detail}. Restoring it would '
      f'silently misinterpret the saved state. {hint}')


def _incarnation_token() -> str:
  return f'{os.getpid()}-{time.time_ns()}'


class CheckpointManager:
  """Orbax wrapper with an atomic (multi-host-aware) commit protocol.

  Single-process: the Orbax manager behaves as before, plus every
  finalized step gets a ``commit.json`` marker (written once the async
  write is known complete — at the next ``save`` or at
  ``wait_until_finished``), and ``restore`` prefers committed steps.

  Multi-process (``distributed`` context passed), ``sharded=False``:
  process 0 is the single payload writer — its Orbax manager runs with
  ``active_processes={0}`` so Orbax's internal barriers never span the
  job — and commit requires every host:

    1. primary saves the payload and waits for durability;
    2. barrier; every host writes its ``host_ack_<p>.json`` into the
       step dir (tagged with this job incarnation, so acks left behind
       by a previous crashed attempt at the same step never count);
    3. barrier; primary validates all acks and atomically publishes
       ``commit.json`` with the run topology;
    4. barrier; ``save`` returns True on every host.

  ``sharded=True`` replaces step 1: EVERY participant writes its own
  shards through one shared Orbax multiprocess ``AsyncCheckpointer``
  (coordination-service barriers only), after re-expressing per-host
  replica-group state as striped global arrays when needed
  (``parallel/mesh.build_global_save_view``). Steps 2–4 are identical —
  the marker is the single commit point either way.

  ``async_commit=True`` moves steps 2–3 off the critical path for
  unforced saves: the payload write starts at the save point, each
  host's ack lands (from a waiter thread) once its write is durable, and
  the primary publishes the marker from ``poll_async_commit`` at a later
  dispatch boundary — no barrier blocks the loop. Forced saves and
  ``wait_until_finished`` run the barriered protocol, so shutdown never
  leaves the marker behind.

  Any host dying mid-protocol leaves the step UNCOMMITTED (never
  restored) and surfaces as a bounded
  :class:`~tensor2robot_tpu.train.distributed_resilience.DeadHostError`
  on the survivors instead of a hang.
  """

  def __init__(self,
               directory: str,
               max_to_keep: Optional[int] = 5,
               keep_period: Optional[int] = None,
               save_interval_steps: int = 1,
               async_save: bool = True,
               topology: Optional[Dict[str, Any]] = None,
               distributed: Optional[DistributedContext] = None,
               barrier_timeout_secs: float = 600.0,
               sharded: bool = False,
               async_commit: bool = False,
               reshape: bool = False,
               mesh=None,
               sharding_rules: Sequence = ()):
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    self._directory = directory
    self._topology = dict(topology) if topology else None
    self._ctx = distributed
    self._barrier_timeout = float(barrier_timeout_secs)
    self._save_interval = max(1, int(save_interval_steps))
    self._max_to_keep = max_to_keep
    self._keep_period = keep_period
    self._sharded = bool(sharded and distributed is not None)
    self._async_commit = bool(async_commit and distributed is not None)
    self._reshape = bool(reshape)
    self._mesh = mesh
    self._sharding_rules = tuple(sharding_rules or ())
    self._save_seq = 0  # barrier-id uniqueness across repeated saves
    self._pending_marker: Optional[int] = None
    self._manager: Optional[ocp.CheckpointManager] = None
    self._restore_checkpointer = None
    self._incarnation: Optional[str] = None
    # Hosts participating in saves: all processes by default; shrinks
    # when the coordinated-shutdown negotiation excludes hosts that
    # finished and said goodbye (set_participants).
    self._participants: Optional[List[int]] = (
        sorted(range(distributed.process_count))
        if distributed is not None else None)
    # Shared multiprocess payload writer (sharded mode), rebuilt when the
    # participant set changes; all hosts create/use it in lockstep so
    # Orbax's per-prefix barrier counters stay aligned.
    self._payload_writer: Optional[ocp.AsyncCheckpointer] = None
    self._payload_writer_parts: Optional[Tuple[int, ...]] = None
    # In-flight async commit (one at a time; saves are serialized).
    self._async_lock = threading.Lock()
    self._async_state: Optional[Dict[str, Any]] = None  # GUARDED_BY(self._async_lock)
    if self._ctx is None or (not self._sharded and self._ctx.is_primary):
      extra = {}
      if self._ctx is not None:
        # Orbax must never barrier across the job in single-writer mode:
        # our commit protocol owns cross-host ordering (over the
        # coordination service, with bounded timeouts); Orbax's own
        # syncs collapse to this process. The synchronous commit is also
        # barrier-synchronous — the marker must only be published once
        # the payload is durably on disk — so async writes buy nothing
        # there and are enabled only for async_commit. Orbax refuses
        # create=True with active_processes set; the root directory was
        # created above.
        async_save = self._async_commit
        extra = dict(
            create=False,
            multiprocessing_options=ocp.options.MultiprocessingOptions(
                primary_host=self._ctx.process_index,
                active_processes={self._ctx.process_index},
                barrier_sync_key_prefix=(
                    f't2r_ckpt_p{self._ctx.process_index}')))
      options = ocp.CheckpointManagerOptions(
          max_to_keep=max_to_keep,
          keep_period=keep_period,
          save_interval_steps=save_interval_steps,
          enable_async_checkpointing=async_save,
          step_prefix='ckpt',
          **extra)
      self._manager = ocp.CheckpointManager(directory, options=options)

  @property
  def directory(self) -> str:
    return self._directory

  @property
  def topology(self) -> Optional[Dict[str, Any]]:
    return self._topology

  @property
  def sharded(self) -> bool:
    return self._sharded

  @property
  def participants(self) -> Optional[List[int]]:
    return list(self._participants) if self._participants else None

  def set_participants(self, hosts: Sequence[int]) -> None:
    """Restricts the commit protocol to ``hosts`` (surviving processes).

    Installed by the trainer when the coordinated-shutdown negotiation
    excluded hosts that completed and said goodbye: subsequent saves
    barrier/ack only among the survivors, and the marker records them.
    """
    if self._ctx is None:
      return
    hosts = sorted(int(h) for h in hosts)
    if self._ctx.process_index not in hosts:
      raise ValueError(
          f'process {self._ctx.process_index} cannot save with a '
          f'participant set {hosts} that excludes itself.')
    if hosts != self._participants:
      logging.warning(
          'Checkpoint commit participants restricted to %s (of %d '
          'processes): peers that completed and said goodbye are '
          'excluded from the remaining saves.', hosts,
          self._ctx.process_count)
      self._participants = hosts
    if (not self._sharded and self._manager is None and
        self._is_commit_primary()):
      # Single-writer mode with the original primary gone: this host
      # takes over the payload-writer role for the remaining saves.
      # Orbax resolves primary-host identity against the RUNTIME process
      # index (== ctx.process_index in a real job), so key on that.
      runtime_index = jax.process_index()
      self._manager = ocp.CheckpointManager(
          self._directory,
          options=ocp.CheckpointManagerOptions(
              max_to_keep=self._max_to_keep,
              keep_period=self._keep_period,
              save_interval_steps=self._save_interval,
              enable_async_checkpointing=self._async_commit,
              step_prefix='ckpt',
              create=False,
              multiprocessing_options=ocp.options.MultiprocessingOptions(
                  primary_host=runtime_index,
                  active_processes={runtime_index},
                  barrier_sync_key_prefix=(
                      f't2r_ckpt_takeover_p{self._ctx.process_index}'))))

  # ---------------------------------------------------------- commit plumbing

  def _is_commit_primary(self) -> bool:
    return (self._ctx is not None and self._participants and
            self._ctx.process_index == self._participants[0])

  def _barrier(self, name: str, participants: Sequence[int]) -> None:
    if len(participants) <= 1:
      return  # solo survivor: nothing to wait for
    self._ctx.barrier(name, self._barrier_timeout,
                      participants=participants)

  def _get_incarnation(self) -> str:
    """A job-incarnation token shared by all hosts (first-writer-wins).

    Acks are tagged with it so a PREVIOUS incarnation's leftovers in the
    same step dir (a job that crashed mid-protocol, then the restart
    reached the same step) can never satisfy this run's ack count — the
    hazard the async commit path would otherwise race against.
    """
    if self._incarnation is not None:
      return self._incarnation
    token = _incarnation_token()
    if self._ctx is None:
      self._incarnation = token
      return token
    # Stable across processes (python's str hash is per-process salted).
    dir_digest = hashlib.sha1(self._directory.encode()).hexdigest()[:12]
    key = f'ckpt/incarnation/{dir_digest}'
    self._ctx.put(key, token)  # first writer wins across hosts
    agreed = self._ctx.get(key, self._barrier_timeout)
    self._incarnation = agreed if agreed is not None else token
    return self._incarnation

  def _write_ack(self, step: int) -> None:
    ctx = self._ctx
    step_dir = _step_dir(self._directory, step)
    ack = {
        'process_index': ctx.process_index,
        'step': int(step),
        'pid': os.getpid(),
        'time': time.time(),
        'incarnation': self._get_incarnation(),
        'format': FORMAT_SHARDED if self._sharded else FORMAT_SINGLE_WRITER,
    }
    ack_path = os.path.join(
        step_dir, f'{HOST_ACK_PREFIX}{ctx.process_index}.json')
    tmp = f'{ack_path}.tmp{os.getpid()}'
    with open(tmp, 'w') as f:
      json.dump(ack, f)
      f.flush()
      os.fsync(f.fileno())
    os.replace(tmp, ack_path)

  def _read_acks(self, step: int,
                 incarnation: Optional[str] = None) -> Dict[int, dict]:
    """Parsable acks in the step dir, filtered to ``incarnation``.

    With an expected incarnation, acks missing the tag or carrying a
    different one are STALE (a previous attempt at this step) and do not
    count — a commit must never be satisfied by a dead job's leftovers.
    """
    step_dir = _step_dir(self._directory, step)
    acked: Dict[int, dict] = {}
    try:
      names = os.listdir(step_dir)
    except FileNotFoundError:
      return acked
    for name in names:
      if not (name.startswith(HOST_ACK_PREFIX) and name.endswith('.json')):
        continue
      try:
        with open(os.path.join(step_dir, name)) as f:
          payload = json.load(f)
        host = int(payload['process_index'])
      except (OSError, ValueError, KeyError, TypeError):
        continue  # unparseable ack == no ack: the step stays uncommitted
      if (incarnation is not None and
          payload.get('incarnation') != incarnation):
        continue
      acked[host] = payload
    return acked

  def _publish_marker(self, step: int, acks: Dict[int, dict]) -> None:
    fmt = FORMAT_SHARDED if self._sharded else FORMAT_SINGLE_WRITER
    shards = {
        str(host): {'pid': ack.get('pid'), 'time': ack.get('time')}
        for host, ack in sorted(acks.items())
    }
    write_commit_marker(
        self._directory, step, topology=self._topology,
        hosts=sorted(acks),
        extra={'format': fmt, 'incarnation': self._get_incarnation(),
               'shards': shards})

  def _commit_barriered(self, step: int, seq: int,
                        participants: Sequence[int]) -> None:
    """Steps 2–4 of the protocol: acks, validation, marker, release."""
    self._barrier(f'ckpt/{step}/{seq}/saved', participants)
    self._write_ack(step)
    self._barrier(f'ckpt/{step}/{seq}/acked', participants)
    if self._ctx.process_index == participants[0]:
      if read_commit_marker(self._directory, step) is None:
        self._validate_and_publish(step, participants)
    self._barrier(f'ckpt/{step}/{seq}/committed', participants)

  def _validate_and_publish(self, step: int,
                            participants: Sequence[int]) -> None:
    acks = self._read_acks(step, incarnation=self._get_incarnation())
    missing = set(participants) - set(acks)
    if missing:
      raise RuntimeError(
          f'checkpoint step {step}: host ack(s) missing for '
          f'process(es) {sorted(missing)} AFTER the ack barrier '
          f'passed — the shared filesystem dropped or corrupted '
          f'them; refusing to commit a torn checkpoint.')
    self._publish_marker(step, acks)

  def _gc_old_steps(self) -> None:
    """Retention for the sharded path (no Orbax manager owns the dir).

    Deletes COMMITTED steps beyond ``max_to_keep`` (keeping
    ``keep_period`` multiples), never torn ones — a torn step may be an
    in-flight async write. Primary-of-participants only.
    """
    if self._max_to_keep is None or not self._is_commit_primary():
      return
    committed, _ = _committed_steps(
        self._directory, _fs_steps(self._directory), 'retention')
    excess = committed[:-self._max_to_keep] if self._max_to_keep else []
    for step in excess:
      if self._keep_period and step % self._keep_period == 0:
        continue
      shutil.rmtree(_step_dir(self._directory, step), ignore_errors=True)

  # ------------------------------------------------------------ payload write

  def _payload_checkpointer(self, participants: Sequence[int]
                            ) -> ocp.AsyncCheckpointer:
    parts = tuple(participants)
    if self._payload_writer is not None and (
        self._payload_writer_parts == parts):
      return self._payload_writer
    if self._payload_writer is not None:
      self._payload_writer.close()
    prefix = 't2r_shard_p' + '_'.join(str(p) for p in parts)
    self._payload_writer = ocp.AsyncCheckpointer(
        ocp.StandardCheckpointHandler(),
        timeout_secs=max(1, int(self._barrier_timeout)),
        multiprocessing_options=ocp.options.MultiprocessingOptions(
            primary_host=parts[0],
            active_processes=set(parts),
            barrier_sync_key_prefix=prefix))
    self._payload_writer_parts = parts
    return self._payload_writer

  def _sharded_save_view(self, state, participants: Sequence[int]):
    """The global-array view of ``state`` each participant writes from."""
    leaves = jax.tree_util.tree_leaves(state)
    if leaves and all(
        isinstance(x, jax.Array) and not x.is_fully_addressable
        for x in leaves if isinstance(x, jax.Array)) and any(
            isinstance(x, jax.Array) and not x.is_fully_addressable
            for x in leaves):
      # Already global (process-spanning mesh, true FSDP): Orbax writes
      # each process's addressable shards as-is.
      return state
    save_mesh = mesh_lib.global_save_mesh(participants)
    return mesh_lib.build_global_save_view(jax.device_get(state), save_mesh)

  def _start_sharded_payload(self, step: int, state,
                             participants: Sequence[int]) -> None:
    step_dir = _step_dir(self._directory, step)
    if self._ctx.process_index == participants[0]:
      os.makedirs(step_dir, exist_ok=True)
    view = self._sharded_save_view(state, participants)
    ckptr = self._payload_checkpointer(participants)
    ckptr.save(os.path.join(step_dir, 'default'),
               args=ocp.args.StandardSave(view), force=True)

  # ------------------------------------------------------------------- saves

  def save(self, step: int, state, force: bool = False,
           sync: Optional[bool] = None) -> bool:
    """Saves ``state`` at ``step``; True when a save actually happened.

    ``force`` bypasses the interval gate (identically on every host).
    ``sync`` controls the commit style in ``async_commit`` mode: None
    (default) lets unforced interval saves commit asynchronously at
    later dispatch boundaries; True (what the trainer passes for
    preemption/final saves) runs the full barriered protocol so the
    marker is on disk before the call returns.
    """
    step = int(step)
    if self._ctx is not None:
      return self._save_distributed(step, state, force,
                                    sync=bool(sync) if sync is not None
                                    else not self._async_commit)
    # Hand Orbax the DEVICE arrays: its async path owns the device→host
    # copy (blocking only for the D2H transfer, writing to disk in the
    # background). An eager jax.device_get here would serialize a full
    # host copy into the train loop even with async_save=True, defeating
    # async checkpointing. Safe against buffer donation: Orbax completes
    # the D2H copy before save() returns.
    if step in self._manager.all_steps():
      return False  # already saved (e.g. final forced save after an in-loop one)
    # checkpoint/save_ms is what the TRAIN LOOP pays (with async_save it
    # covers only the blocking D2H copy; the disk write happens in the
    # background and is accounted by checkpoint/wait_ms at barriers).
    with tracing.span('checkpoint/save'):
      if self._pending_marker is not None:
        # The previous async write must be DURABLE before its marker is
        # published (the whole point of the marker). Orbax's save would
        # wait on it internally anyway, so this adds no stall.
        self._manager.wait_until_finished()
        self._flush_pending_marker()
      saved = self._manager.save(
          step, args=ocp.args.StandardSave(state), force=force)
    if saved:
      self._pending_marker = step
      metrics_lib.counter('checkpoint/saves').inc()
      flight.event('checkpoint', 'checkpoint/save',
                   f'step={step} force={int(force)}')
    return saved

  def _save_distributed(self, step: int, state, force: bool,
                        sync: bool) -> bool:
    """The multi-host commit protocol; every participating host calls
    this at the same step (the trainer's boundaries guarantee it)."""
    ctx = self._ctx
    if read_commit_marker(self._directory, step) is not None:
      return False  # already committed; consistent across hosts
    if not force and step % self._save_interval:
      return False  # mirror Orbax's own interval gate, identically per host
    # At most one async commit in flight: starting a new save (sync or
    # not) first finalizes the previous one — every host executes the
    # same save sequence, so all enter this path in lockstep.
    self._finalize_async_commit()
    self._save_seq += 1
    seq = self._save_seq
    participants = list(self._participants)
    with tracing.span('checkpoint/save'):
      if self._sharded:
        try:
          self._start_sharded_payload(step, state, participants)
        except DeadHostError:
          raise
        except Exception as e:  # pylint: disable=broad-except
          raise DeadHostError(
              f'sharded checkpoint payload write for step {step} failed '
              f'on process {ctx.process_index} (a peer likely died '
              f'mid-save; the step stays uncommitted): {e}') from e
      elif self._manager is not None:
        # Single payload writer. The host copy is explicit: with a
        # per-host mesh in a multi-process runtime Orbax refuses device
        # arrays, and the commit barriers serialize on the write anyway.
        step_dir = _step_dir(self._directory, step)
        if os.path.isdir(step_dir):
          # We only reach this point when the step has NO commit marker:
          # anything already on disk is a previous attempt's torn
          # leftover (payload fragments, stale acks), and Orbax refuses
          # to write over an existing destination — clear it first.
          shutil.rmtree(step_dir, ignore_errors=True)
        self._manager.save(
            step, args=ocp.args.StandardSave(jax.device_get(state)),
            force=True)
      hook = _during_save_hook
      if hook is not None:
        hook(step)
      flight.event(
          'checkpoint', 'checkpoint/save',
          f'step={step} force={int(force)} sync={int(sync)} '
          f'sharded={int(self._sharded)}')
      if not sync and self._async_commit:
        self._begin_async_commit(step, seq, participants)
        metrics_lib.counter('checkpoint/saves').inc()
        metrics_lib.counter('checkpoint/async_commits').inc()
        return True
      self._wait_payload(participants)
      self._commit_barriered(step, seq, participants)
    metrics_lib.counter('checkpoint/saves').inc()
    self._gc_old_steps()
    return True

  def _await_primary_ack(self, step: int, primary: int) -> None:
    """Blocks (bounded) until the primary's fresh ack for ``step``."""
    incarnation = self._get_incarnation()
    deadline = time.monotonic() + self._barrier_timeout
    while primary not in self._read_acks(step, incarnation=incarnation):
      if time.monotonic() > deadline:
        raise DeadHostError(
            f'checkpoint step {step}: the payload writer (process '
            f'{primary}) never acked within {self._barrier_timeout:.0f}s '
            f'(likely died mid-save); the step stays uncommitted.')
      time.sleep(0.02)

  def _wait_payload(self, participants: Sequence[int]) -> None:
    """Blocks until this host's payload contribution is durable."""
    ctx = self._ctx
    try:
      if self._sharded:
        self._payload_checkpointer(participants).wait_until_finished()
      elif self._manager is not None:
        self._manager.wait_until_finished()
    except DeadHostError:
      raise
    except Exception as e:  # pylint: disable=broad-except
      raise DeadHostError(
          f'checkpoint payload wait failed on process '
          f'{ctx.process_index} (a peer likely died mid-save; the step '
          f'stays uncommitted): {e}') from e

  # ----------------------------------------------------------- async commit

  def _begin_async_commit(self, step: int, seq: int,
                          participants: Sequence[int]) -> None:
    """Starts the off-loop half of an async save: a waiter thread acks
    once this host's write is durable; the marker rides a later
    ``poll_async_commit`` (primary) or the next forced sync."""
    pending = {
        'step': step,
        'seq': seq,
        'participants': list(participants),
        'started_at': time.perf_counter(),
        'error': None,
        'done': threading.Event(),
    }

    def waiter():
      try:
        self._wait_payload(participants)
        if (not self._sharded and
            self._ctx.process_index != participants[0]):
          # Single-writer causality: a non-primary ack must imply the
          # primary's payload is durable AND from THIS incarnation (the
          # primary may first clear a previous attempt's torn step dir —
          # acking the bare directory would race that cleanup). The
          # primary's own ack, written strictly after its payload wait,
          # carries both facts.
          self._await_primary_ack(step, participants[0])
        self._write_ack(step)
      except BaseException as e:  # pylint: disable=broad-except
        pending['error'] = e
        logging.warning(
            'Async checkpoint commit for step %d: payload wait/ack '
            'failed (%r); the step stays uncommitted until the forced '
            'sync surfaces the error.', step, e)
      finally:
        pending['done'].set()

    thread = threading.Thread(target=waiter, daemon=True,
                              name=f't2r-ckpt-async-{step}')
    pending['thread'] = thread
    with self._async_lock:
      self._async_state = pending
    thread.start()

  def poll_async_commit(self) -> bool:
    """One dispatch boundary's async-commit progress check (non-blocking).

    The commit primary publishes the marker once every participant's ack
    (for this incarnation) is on disk — each ack is written strictly
    after that host's payload is durable, so the marker never covers a
    torn write. Returns True when the pending step is now committed.
    Non-primary hosts have nothing to do here (their waiter thread wrote
    the ack); the pending record itself is cleared by the next save or
    ``wait_until_finished`` so the barriered finalize stays symmetric
    across hosts.
    """
    with self._async_lock:
      pending = self._async_state
    if pending is None:
      return False
    step = pending['step']
    if read_commit_marker(self._directory, step) is not None:
      return True
    participants = pending['participants']
    if self._ctx.process_index != participants[0]:
      return False
    acks = self._read_acks(step, incarnation=self._get_incarnation())
    if set(participants) - set(acks):
      return False
    self._publish_marker(step, acks)
    overlap_ms = (time.perf_counter() - pending['started_at']) * 1e3
    metrics_lib.histogram('checkpoint/save_overlap_ms').observe(overlap_ms)
    logging.info(
        'Async checkpoint commit: step %d marker published %.0f ms after '
        'the save point (write overlapped training).', step, overlap_ms)
    self._gc_old_steps()
    return True

  def _finalize_async_commit(self) -> None:
    """The forced-sync path: joins the waiter, runs the barriered
    ack/marker round, and surfaces any write error. Every host calls it
    at the same protocol points (next save / wait_until_finished /
    close), so the barriers always pair up."""
    with self._async_lock:
      pending, self._async_state = self._async_state, None
    if pending is None:
      return
    step, seq = pending['step'], pending['seq']
    participants = pending['participants']
    if not pending['done'].wait(self._barrier_timeout):
      raise DeadHostError(
          f'async checkpoint commit for step {step}: payload writer '
          f'still not durable after {self._barrier_timeout:.0f}s; '
          f'refusing to publish the marker.')
    if pending['error'] is not None:
      raise pending['error']
    self._barrier(f'ckpt/{step}/{seq}/async_sync', participants)
    if self._ctx.process_index == participants[0]:
      if read_commit_marker(self._directory, step) is None:
        self._validate_and_publish(step, participants)
        overlap_ms = (time.perf_counter() - pending['started_at']) * 1e3
        metrics_lib.histogram('checkpoint/save_overlap_ms').observe(
            overlap_ms)
    self._barrier(f'ckpt/{step}/{seq}/async_committed', participants)
    self._gc_old_steps()

  # ----------------------------------------------------------------- restore

  def _restore_payload(self, step: int, target):
    """Reads one step's payload into ``target``'s structure."""
    if self._manager is not None and self._ctx is None:
      return self._manager.restore(
          int(step), args=ocp.args.StandardRestore(target))
    # Multi-process (or sharded): every host reads independently — the
    # payload is one logical tree regardless of how many writers striped
    # it, and concurrent reads are safe.
    if self._restore_checkpointer is None:
      extra = {}
      if self._ctx is not None:
        extra = dict(
            multiprocessing_options=ocp.options.MultiprocessingOptions(
                primary_host=self._ctx.process_index,
                active_processes={self._ctx.process_index},
                barrier_sync_key_prefix=(
                    f't2r_restore_p{self._ctx.process_index}')))
      self._restore_checkpointer = ocp.Checkpointer(
          ocp.StandardCheckpointHandler(), **extra)
    item_dir = os.path.join(_step_dir(self._directory, step), 'default')
    if not os.path.isdir(item_dir):
      item_dir = _step_dir(self._directory, step)
    return self._restore_checkpointer.restore(
        item_dir, args=ocp.args.StandardRestore(target))

  def _host_target(self, state):
    """A host-memory restore target (Orbax rejects numpy SCALARS)."""

    def conv(x):
      if x is None or isinstance(x, (jax.ShapeDtypeStruct, int, float)):
        return x
      return np.asarray(x)

    return jax.tree_util.tree_map(conv, state)

  def _resharded_target(self, state):
    """Abstract target with shardings rebuilt from the CURRENT mesh —
    Orbax reads exactly the index ranges each device needs, so an N-host
    payload lands directly on an M-host layout with no full-state
    gather."""
    shardings = mesh_lib.state_shardings_for(
        self._mesh, state, rules=self._sharding_rules)

    def abstract(x, s):
      if x is None or isinstance(x, (int, float)):
        return x
      if isinstance(x, jax.ShapeDtypeStruct):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)
      arr_dtype = getattr(x, 'dtype', None)
      if arr_dtype is None:
        return x
      return jax.ShapeDtypeStruct(np.shape(x), arr_dtype, sharding=s)

    return jax.tree_util.tree_map(abstract, state, shardings)

  def _target_for(self, state, demoted: Dict[str, Any]):
    if demoted and self._mesh is not None:
      metrics_lib.counter('checkpoint/reshaped_restores').inc()
      logging.warning(
          'Resharding restore: checkpoint topology differs on %s; '
          'rebuilding target shardings from the current mesh and letting '
          'Orbax reshard on read.', sorted(demoted))
      return self._resharded_target(state)
    if demoted:
      metrics_lib.counter('checkpoint/reshaped_restores').inc()
    return self._host_target(state)

  def restore(self, state, step: Optional[int] = None,
              fallback_to_older: bool = True,
              reshape: Optional[bool] = None):
    """Restores into the structure of ``state`` (an abstract/concrete tree).

    Only COMMITTED steps are candidates once the commit protocol is in
    use (any marker present); a step missing its marker is torn and is
    never restored (``checkpoint/torn_skipped``). The committed step's
    recorded topology must match this manager's (when both are known) or
    a :class:`TopologyMismatchError` explains the mismatch — except
    under ``reshape`` (defaulting to the manager's ``reshape=`` flag),
    where host/mesh-layout differences become a resharding restore: the
    payload is read onto target shardings built from the CURRENT mesh
    (``checkpoint/reshaped_restores`` counts them). Semantic mismatches
    (microbatch config, steps-per-dispatch) always raise.

    With ``fallback_to_older`` (the default when no explicit ``step`` is
    requested), a truncated/corrupt latest checkpoint — the signature of
    a save cut off by preemption or a torn filesystem — falls back to
    the next-older step instead of killing the resume. Only when EVERY
    step fails does the last error propagate; an explicit ``step``
    restores exactly that step or raises.
    """
    reshape = self._reshape if reshape is None else bool(reshape)
    if step is not None:
      step = int(step)
      _, protocol_active = _committed_steps(
          self._directory, _fs_steps(self._directory), 'restore')
      marker = read_commit_marker(self._directory, step)
      if protocol_active and marker is None:
        raise RuntimeError(
            f'checkpoint step {step} under {self._directory!r} has no '
            f'commit marker (torn/uncommitted); refusing to restore it.')
      demoted = {}
      if marker is not None:
        demoted = _check_topology(marker.get('topology'), self._topology,
                                  self._directory, step, reshape=reshape)
      with tracing.span('checkpoint/restore'):
        restored = self._restore_payload(step, self._target_for(
            state, demoted))
      metrics_lib.counter('checkpoint/restores').inc()
      return restored
    steps, _ = _committed_steps(
        self._directory, _fs_steps(self._directory), 'restore')
    steps = sorted(steps, reverse=True)
    if not steps:
      return None
    last_exc: Optional[BaseException] = None
    for i, s in enumerate(steps):
      marker = read_commit_marker(self._directory, s)
      demoted = {}
      if marker is not None:
        # Topology mismatch is NOT a fallback case: every step in this
        # directory came from the same job shape, so older steps would
        # fail identically — raise the actionable error instead (unless
        # reshape demotes it to a resharding restore).
        demoted = _check_topology(marker.get('topology'), self._topology,
                                  self._directory, s, reshape=reshape)
      try:
        with tracing.span('checkpoint/restore'):
          restored = self._restore_payload(s, self._target_for(
              state, demoted))
        metrics_lib.counter('checkpoint/restores').inc()
        if i > 0:
          metrics_lib.counter('checkpoint/restore_fallbacks').inc(i)
          logging.warning(
              'Restored checkpoint step %d after %d newer step(s) failed '
              'to load (latest was likely truncated by a preemption).', s, i)
        return restored
      except Exception as e:  # pylint: disable=broad-except
        last_exc = e
        if not fallback_to_older:
          raise
        logging.warning(
            'Checkpoint step %d failed to restore (%r); falling back to '
            'the next-older step.', s, e)
    raise RuntimeError(
        f'All {len(steps)} checkpoint(s) under {self._directory!r} failed '
        f'to restore; last error: {last_exc!r}') from last_exc

  # ------------------------------------------------------------- bookkeeping

  def _flush_pending_marker(self) -> None:
    """Publishes the marker for the last async save once it finished.

    Called with the Orbax write known complete (after
    ``wait_until_finished`` or at the head of the next ``save`` — Orbax
    serializes saves, so starting a new one implies the previous write
    is durable). A crash before this point correctly leaves the step
    uncommitted: its write may be torn.
    """
    if self._pending_marker is None:
      return
    step, self._pending_marker = self._pending_marker, None
    if os.path.isdir(_step_dir(self._directory, step)):
      write_commit_marker(self._directory, step, topology=self._topology,
                          extra={'format': FORMAT_SINGLE_WRITER})
    else:
      # Retention GC may legitimately have collected the step already;
      # anything else (e.g. a still-unfinalized write) is a bug worth
      # hearing about — the step would read as torn forever.
      logging.warning(
          'Commit marker for checkpoint step %d skipped: step directory '
          'no longer exists under %r.', step, self._directory)

  def latest_step(self) -> Optional[int]:
    if self._manager is not None and self._ctx is None:
      return self._manager.latest_step()
    steps = _fs_steps(self._directory)
    return steps[-1] if steps else None

  def latest_committed_step(self) -> Optional[int]:
    """Newest step ``restore`` would actually consider."""
    steps, _ = _committed_steps(
        self._directory, _fs_steps(self._directory), 'latest_committed_step')
    return steps[-1] if steps else None

  def all_steps(self):
    if self._manager is not None and self._ctx is None:
      return sorted(self._manager.all_steps())
    return _fs_steps(self._directory)

  def wait_until_finished(self) -> None:
    # Time the train loop spends barriered on in-flight async writes.
    with tracing.span('checkpoint/wait'):
      if self._ctx is not None:
        self._finalize_async_commit()
      if self._manager is not None:
        self._manager.wait_until_finished()
      self._flush_pending_marker()

  def close(self) -> None:
    if self._ctx is not None:
      self._finalize_async_commit()
    if self._payload_writer is not None:
      self._payload_writer.close()
      self._payload_writer = None
    if self._manager is not None:
      self._manager.wait_until_finished()
      self._flush_pending_marker()
      self._manager.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


def latest_checkpoint_step(directory: str) -> Optional[int]:
  """Latest COMMITTED step in ``directory`` without opening a manager.

  Non-numeric ``ckpt_*`` entries (stray tmp dirs, editor droppings,
  backup copies) are skipped rather than crashing the scan — this
  function gates resume decisions and continuous eval, so it must stay
  robust to whatever accumulates in a long-lived model dir.

  Commit-aware: once any step in the directory carries a commit marker,
  unmarked steps are torn (or still being written) and are not reported
  — so the continuous evaluator and the predictors never pick up a
  checkpoint mid-write. Each torn step counts ``checkpoint/torn_skipped``
  once (not once per poll). Sharded and single-writer step dirs mix
  freely (the marker rule is format-agnostic); marker-less legacy
  directories behave as before.
  """
  steps, _ = _committed_steps(directory, _fs_steps(directory),
                              'latest_checkpoint_step')
  return steps[-1] if steps else None


EVAL_BACKUP_DIRNAME = 'current_eval_checkpoint'


def create_backup_checkpoint_for_eval(ckpt_dir: str,
                                      step: int,
                                      backup_dir: str,
                                      num_retries: int = 3
                                      ) -> Optional[str]:
  """Copies checkpoint ``step`` into the evaluator's own directory.

  The guard of ``utils/train_eval.py:590-707``: the trainer's retention
  GC may delete ``step`` at any moment, so the copy is retried and
  validated — the source must still exist AFTER the copy completes
  (a vanished source means the copy may be partial). Returns the backed-up
  step directory, or None if the checkpoint was GC'd before a complete
  copy was made.
  """
  src = os.path.join(ckpt_dir, f'ckpt_{int(step)}')
  os.makedirs(backup_dir, exist_ok=True)
  final = os.path.join(backup_dir, f'ckpt_{int(step)}')
  if os.path.isdir(final):
    return final  # already backed up
  for _ in range(num_retries):
    if not os.path.isdir(src):
      return None
    tmp = os.path.join(backup_dir, f'.tmp_ckpt_{int(step)}')
    shutil.rmtree(tmp, ignore_errors=True)
    try:
      shutil.copytree(src, tmp)
    except (FileNotFoundError, shutil.Error):
      continue  # GC raced the copy; retry
    if not os.path.isdir(src):
      # Source vanished mid-copy: the copy may be truncated. Retry.
      shutil.rmtree(tmp, ignore_errors=True)
      continue
    # Keep only this step in the backup dir (one eval at a time).
    for name in os.listdir(backup_dir):
      if name.startswith('ckpt_'):
        shutil.rmtree(os.path.join(backup_dir, name), ignore_errors=True)
    os.replace(tmp, final)
    return final
  return None


def restore_from_backup(state, backup_step_dir: str):
  """Restores a TrainState from a backed-up step directory."""
  checkpointer = ocp.StandardCheckpointer()
  # The state payload lives in the 'default' item of the step dir.
  item_dir = os.path.join(os.path.abspath(backup_step_dir), 'default')
  if not os.path.isdir(item_dir):
    item_dir = os.path.abspath(backup_step_dir)
  return checkpointer.restore(item_dir, jax.device_get(state))


def checkpoints_iterator(directory: str,
                         min_interval_secs: float = 1.0,
                         timeout: Optional[float] = None,
                         stop_after_step: Optional[int] = None
                         ) -> Iterator[int]:
  """Yields new checkpoint steps as they appear (continuous evaluator).

  The filesystem-watching contract of
  ``tf.contrib.training.checkpoints_iterator`` used by the reference's
  continuous eval loop (``utils/train_eval.py:550-585``).
  """
  last_seen = None
  deadline = None if timeout is None else time.time() + timeout
  while True:
    step = latest_checkpoint_step(directory)
    if step is not None and step != last_seen:
      last_seen = step
      deadline = None if timeout is None else time.time() + timeout
      yield step
      if stop_after_step is not None and step >= stop_after_step:
        return
      continue
    if deadline is not None and time.time() > deadline:
      return
    time.sleep(min_interval_secs)
