"""Checkpointing: Orbax-backed save/restore of the TrainState.

Capability-equivalent of the reference's checkpoint machinery:
``tf.train.Saver`` registration with ``max_to_keep`` /
``keep_checkpoint_every_n_hours`` (``models/abstract_model.py:782-793``),
async checkpointing (``hooks/async_export_hook_builder.py:124-137``),
restart-from-latest Estimator semantics, and the continuous evaluator's
checkpoint BACKUP: a separate evaluator process copies the step it wants
to evaluate into its own directory first, so the trainer's retention GC
cannot delete it mid-restore (``utils/train_eval.py:590-707``).
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Any, Iterator, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.observability import tracing


class CheckpointManager:
  """Thin wrapper over ``orbax.checkpoint.CheckpointManager``."""

  def __init__(self,
               directory: str,
               max_to_keep: Optional[int] = 5,
               keep_period: Optional[int] = None,
               save_interval_steps: int = 1,
               async_save: bool = True):
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    options = ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep,
        keep_period=keep_period,
        save_interval_steps=save_interval_steps,
        enable_async_checkpointing=async_save,
        step_prefix='ckpt')
    self._manager = ocp.CheckpointManager(directory, options=options)
    self._directory = directory

  @property
  def directory(self) -> str:
    return self._directory

  def save(self, step: int, state, force: bool = False) -> bool:
    # Hand Orbax the DEVICE arrays: its async path owns the device→host
    # copy (blocking only for the D2H transfer, writing to disk in the
    # background). An eager jax.device_get here would serialize a full
    # host copy into the train loop even with async_save=True, defeating
    # async checkpointing. Safe against buffer donation: Orbax completes
    # the D2H copy before save() returns.
    step = int(step)
    if step in self._manager.all_steps():
      return False  # already saved (e.g. final forced save after an in-loop one)
    # checkpoint/save_ms is what the TRAIN LOOP pays (with async_save it
    # covers only the blocking D2H copy; the disk write happens in the
    # background and is accounted by checkpoint/wait_ms at barriers).
    with tracing.span('checkpoint/save'):
      saved = self._manager.save(
          step, args=ocp.args.StandardSave(state), force=force)
    if saved:
      metrics_lib.counter('checkpoint/saves').inc()
    return saved

  def restore(self, state, step: Optional[int] = None,
              fallback_to_older: bool = True):
    """Restores into the structure of ``state`` (an abstract/concrete tree).

    With ``fallback_to_older`` (the default when no explicit ``step`` is
    requested), a truncated/corrupt latest checkpoint — the signature of
    a save cut off by preemption or a torn filesystem — falls back to
    the next-older step instead of killing the resume. Only when EVERY
    step fails does the last error propagate; an explicit ``step``
    restores exactly that step or raises.
    """
    if step is not None:
      with tracing.span('checkpoint/restore'):
        restored = self._manager.restore(
            int(step), args=ocp.args.StandardRestore(jax.device_get(state)))
      metrics_lib.counter('checkpoint/restores').inc()
      return restored
    steps = sorted(self._manager.all_steps(), reverse=True)
    if not steps:
      return None
    target = jax.device_get(state)
    last_exc: Optional[BaseException] = None
    for i, s in enumerate(steps):
      try:
        with tracing.span('checkpoint/restore'):
          restored = self._manager.restore(
              int(s), args=ocp.args.StandardRestore(target))
        metrics_lib.counter('checkpoint/restores').inc()
        if i > 0:
          metrics_lib.counter('checkpoint/restore_fallbacks').inc(i)
          logging.warning(
              'Restored checkpoint step %d after %d newer step(s) failed '
              'to load (latest was likely truncated by a preemption).', s, i)
        return restored
      except Exception as e:  # pylint: disable=broad-except
        last_exc = e
        if not fallback_to_older:
          raise
        logging.warning(
            'Checkpoint step %d failed to restore (%r); falling back to '
            'the next-older step.', s, e)
    raise RuntimeError(
        f'All {len(steps)} checkpoint(s) under {self._directory!r} failed '
        f'to restore; last error: {last_exc!r}') from last_exc

  def latest_step(self) -> Optional[int]:
    return self._manager.latest_step()

  def all_steps(self):
    return sorted(self._manager.all_steps())

  def wait_until_finished(self) -> None:
    # Time the train loop spends barriered on in-flight async writes.
    with tracing.span('checkpoint/wait'):
      self._manager.wait_until_finished()

  def close(self) -> None:
    self._manager.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


def latest_checkpoint_step(directory: str) -> Optional[int]:
  """Latest finalized step in ``directory`` without opening a manager.

  Non-numeric ``ckpt_*`` entries (stray tmp dirs, editor droppings,
  backup copies) are skipped rather than crashing the scan — this
  function gates resume decisions and continuous eval, so it must stay
  robust to whatever accumulates in a long-lived model dir.
  """
  try:
    names = os.listdir(directory)
  except FileNotFoundError:
    return None
  steps = []
  for name in names:
    if not name.startswith('ckpt_') or name.endswith('.orbax-checkpoint-tmp'):
      continue
    suffix = name.rsplit('_', 1)[-1]
    if suffix.isdigit():
      steps.append(int(suffix))
  return max(steps) if steps else None


EVAL_BACKUP_DIRNAME = 'current_eval_checkpoint'


def create_backup_checkpoint_for_eval(ckpt_dir: str,
                                      step: int,
                                      backup_dir: str,
                                      num_retries: int = 3
                                      ) -> Optional[str]:
  """Copies checkpoint ``step`` into the evaluator's own directory.

  The guard of ``utils/train_eval.py:590-707``: the trainer's retention
  GC may delete ``step`` at any moment, so the copy is retried and
  validated — the source must still exist AFTER the copy completes
  (a vanished source means the copy may be partial). Returns the backed-up
  step directory, or None if the checkpoint was GC'd before a complete
  copy was made.
  """
  src = os.path.join(ckpt_dir, f'ckpt_{int(step)}')
  os.makedirs(backup_dir, exist_ok=True)
  final = os.path.join(backup_dir, f'ckpt_{int(step)}')
  if os.path.isdir(final):
    return final  # already backed up
  for _ in range(num_retries):
    if not os.path.isdir(src):
      return None
    tmp = os.path.join(backup_dir, f'.tmp_ckpt_{int(step)}')
    shutil.rmtree(tmp, ignore_errors=True)
    try:
      shutil.copytree(src, tmp)
    except (FileNotFoundError, shutil.Error):
      continue  # GC raced the copy; retry
    if not os.path.isdir(src):
      # Source vanished mid-copy: the copy may be truncated. Retry.
      shutil.rmtree(tmp, ignore_errors=True)
      continue
    # Keep only this step in the backup dir (one eval at a time).
    for name in os.listdir(backup_dir):
      if name.startswith('ckpt_'):
        shutil.rmtree(os.path.join(backup_dir, name), ignore_errors=True)
    os.replace(tmp, final)
    return final
  return None


def restore_from_backup(state, backup_step_dir: str):
  """Restores a TrainState from a backed-up step directory."""
  checkpointer = ocp.StandardCheckpointer()
  # The state payload lives in the 'default' item of the step dir.
  item_dir = os.path.join(os.path.abspath(backup_step_dir), 'default')
  if not os.path.isdir(item_dir):
    item_dir = os.path.abspath(backup_step_dir)
  return checkpointer.restore(item_dir, jax.device_get(state))


def checkpoints_iterator(directory: str,
                         min_interval_secs: float = 1.0,
                         timeout: Optional[float] = None,
                         stop_after_step: Optional[int] = None
                         ) -> Iterator[int]:
  """Yields new checkpoint steps as they appear (continuous evaluator).

  The filesystem-watching contract of
  ``tf.contrib.training.checkpoints_iterator`` used by the reference's
  continuous eval loop (``utils/train_eval.py:550-585``).
  """
  last_seen = None
  deadline = None if timeout is None else time.time() + timeout
  while True:
    step = latest_checkpoint_step(directory)
    if step is not None and step != last_seen:
      last_seen = step
      deadline = None if timeout is None else time.time() + timeout
      yield step
      if stop_after_step is not None and step >= stop_after_step:
        return
      continue
    if deadline is not None and time.time() > deadline:
      return
    time.sleep(min_interval_secs)
