"""Distributed fault tolerance: the control plane for multi-process runs.

PR 1's resilience layer is single-process: ``GracefulShutdown`` only
checkpoints the host that caught the signal, and nothing detects a peer
that died mid-collective. On a real pod preemption hits ONE worker first
— an uncoordinated checkpoint is a torn checkpoint. This module adds the
host-level coordination that makes every prior subsystem survive a pod:

* :class:`DistributedContext` — a thin, timeout-bounded wrapper over the
  ``jax.distributed`` coordination service (gRPC key-value store +
  barriers). Everything here is deliberately **control plane**: no
  device collectives, so coordination works on any backend, keeps
  working while the data plane is wedged, and every wait has an
  enforceable deadline (a hung XLA collective does not).
* :class:`CoordinatedShutdown` — any process's SIGTERM propagates to all
  processes: the first observer proposes a stop, every host publishes
  its current dispatch boundary, and all agree on ``max`` — so every
  host forces a checkpoint at the SAME step and exits resumable
  (``PREEMPTED_EXIT_CODE``) together.
* :class:`HeartbeatService` — each host publishes a heartbeat file
  (last-completed step + a registry snapshot) into the shared model dir;
  a monitor thread flags stragglers and declares a host DEAD after a
  timeout, exiting with :data:`LIVENESS_EXIT_CODE` and a loud error
  instead of hanging forever in a collective or barrier.
* :func:`aggregate_snapshots` — process-0 merges the per-host registry
  snapshots riding the heartbeats (counters summed, gauges labeled per
  host), so train scalars, ``/metricsz`` and the end-of-run report
  reflect the whole job instead of one process (closes the PR-2 ROADMAP
  follow-up).

The atomic multi-host checkpoint commit protocol built on these
primitives lives in ``train/checkpoints.py``.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

from tensor2robot_tpu.observability import flight
from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.train import resilience

# A host that declared a PEER dead exits with this status: distinct from
# PREEMPTED_EXIT_CODE (42, resumable as-is) — the scheduler should
# restart the WHOLE job, not just this worker.
LIVENESS_EXIT_CODE = 43

HEARTBEAT_DIRNAME = 'heartbeats'


class DeadHostError(RuntimeError):
  """A peer process stopped participating (barrier timeout / stale
  heartbeat). Raised instead of hanging forever; carries the exit status
  long-running binaries should use."""

  exit_code = LIVENESS_EXIT_CODE


class TopologyMismatchError(RuntimeError):
  """A checkpoint's recorded topology does not match the current run."""


def _coordination_client():
  """The process's jax.distributed coordination-service client, or None."""
  try:
    from jax._src import distributed  # pylint: disable=g-import-not-at-top

    return distributed.global_state.client
  except Exception:  # pylint: disable=broad-except
    return None


class DistributedContext:
  """Host-level coordination fabric: gRPC KV store + bounded barriers.

  All keys/barrier ids are namespaced, every blocking call takes a
  timeout, and a barrier timeout surfaces as :class:`DeadHostError`
  (naming the barrier) rather than the raw gRPC DEADLINE_EXCEEDED.
  """

  def __init__(self, client, process_index: int, process_count: int,
               namespace: str = 't2r'):
    self._client = client
    self.process_index = int(process_index)
    self.process_count = int(process_count)
    self._ns = namespace.rstrip('/')

  @classmethod
  def create(cls, namespace: str = 't2r') -> Optional['DistributedContext']:
    """The context for this process, or None outside a multi-process job."""
    import jax  # pylint: disable=g-import-not-at-top

    if jax.process_count() <= 1:
      return None
    client = _coordination_client()
    if client is None:
      logging.warning(
          'Multi-process run (%d processes) without a jax.distributed '
          'coordination client; distributed resilience is DISABLED — '
          'preemption and checkpoints will be uncoordinated.',
          jax.process_count())
      return None
    return cls(client, jax.process_index(), jax.process_count(),
               namespace=namespace)

  @property
  def is_primary(self) -> bool:
    return self.process_index == 0

  def _key(self, key: str) -> str:
    return f'{self._ns}/{key}'

  def put(self, key: str, value: str) -> bool:
    """First-writer-wins set; False if another process set it first."""
    try:
      self._client.key_value_set(self._key(key), str(value))
      return True
    except Exception as e:  # pylint: disable=broad-except
      if 'ALREADY_EXISTS' in str(e):
        return False
      raise

  def get_dir(self, prefix: str) -> Dict[str, str]:
    """Non-blocking: all (key, value) pairs under ``prefix``, unprefixed."""
    full = self._key(prefix)
    out = {}
    for key, value in self._client.key_value_dir_get(full):
      out[key[len(self._key('')):]] = value
    return out

  def get(self, key: str, timeout_secs: float) -> Optional[str]:
    """Blocking get; None if the key never appears within the timeout."""
    try:
      return self._client.blocking_key_value_get(
          self._key(key), int(timeout_secs * 1000))
    except Exception:  # pylint: disable=broad-except
      return None

  def barrier(self, name: str, timeout_secs: float,
              participants: Optional[Sequence[int]] = None) -> None:
    """Processes wait at ``name``; DeadHostError on timeout.

    Barrier ids are one-shot in the coordination service — callers must
    make ``name`` unique per use (embed the step / a sequence number).
    ``participants`` restricts the barrier to a subset of processes
    (surviving hosts after peers completed and said goodbye); the subset
    is embedded in the barrier id, so two hosts with DIFFERENT views of
    who participates time out bounded instead of pairing up wrongly.
    """
    process_ids = None
    if participants is not None:
      process_ids = sorted(int(p) for p in participants)
      name = f'{name}/p{"_".join(str(p) for p in process_ids)}'
      if process_ids == list(range(self.process_count)):
        process_ids = None  # full set: the plain all-process barrier
    try:
      with tracing_span('distributed/barrier'):
        self._client.wait_at_barrier(self._key(name),
                                     int(timeout_secs * 1000),
                                     process_ids)
    except Exception as e:  # pylint: disable=broad-except
      metrics_lib.counter('distributed/barrier_timeouts').inc()
      raise DeadHostError(
          f'process {self.process_index}/{self.process_count} timed out '
          f'after {timeout_secs:.0f}s at barrier {name!r}: one or more '
          f'peer processes stopped participating (preempted, crashed, or '
          f'wedged). The job should be restarted as a whole; resuming '
          f'will restore the last COMMITTED checkpoint. Underlying '
          f'error: {e}') from e


def tracing_span(name: str):
  """Lazy import of the tracing span (observability stays optional)."""
  from tensor2robot_tpu.observability import tracing  # pylint: disable=g-import-not-at-top

  return tracing.span(name, annotate=False)


class CoordinatedShutdown:
  """Cross-host preemption agreement over the coordination KV store.

  Polled at every dispatch boundary (the same place the single-process
  loop checks ``GracefulShutdown.requested``), plus once after the loop:

  1. A host whose LOCAL shutdown flag is set proposes a stop (a
     KV entry under ``shutdown/proposal/``).
  2. Every host that observes a proposal publishes its own current
     boundary step — all of them within one dispatch, since all poll
     every boundary; a host that already COMPLETED training publishes
     its final step from the trainer's post-loop poll.
  3. Every host spin-polls the KV store (deadline-bounded — never a
     hang) until all ``process_count`` steps are published, then
     computes the SAME target ``max(published steps)`` and keeps
     training until it reaches it, so the forced checkpoint lands on
     one common step on every host.

  Deliberately BARRIER-FREE: a gRPC barrier would deadlock against the
  checkpoint-commit barriers when one host finishes training before the
  proposal lands. With KV polling that skew resolves instead: the
  completed host's published (final) step wins the max, every other
  host trains to it, and the aligned final save commits normally.

  Two defenses close the completed-host vs late-proposal race (a host
  that finished its loop while a peer's SIGTERM was still in flight):

  * a COMPLETING host publishes its final boundary unconditionally
    (:meth:`publish_boundary`) before entering its final-save barriers,
    so a late proposer finds the boundary in the KV store even though
    the completed host will never poll again — the negotiation converges
    on the completed host's final step and the aligned save commits with
    every host;
  * if a missing host is GONE entirely (its goodbye heartbeat says
    ``done`` and no boundary ever landed — it exited before the
    proposal), the negotiation RETRIES ONCE against the surviving hosts:
    the target becomes the survivors' max, :attr:`participants` records
    who remains, and the subsequent forced save commits among them. Only
    when a missing host is neither published nor done does the bounded
    :class:`DeadHostError` escalate.

  ``poll`` returns the agreed target step (or None). The trainer
  checkpoints at the first boundary >= target and raises
  :class:`~tensor2robot_tpu.train.resilience.PreemptedError`.
  """

  def __init__(self,
               context: DistributedContext,
               local: Optional[resilience.GracefulShutdown],
               negotiate_timeout_secs: float = 120.0,
               poll_interval_secs: float = 0.05,
               peer_heartbeats: Optional[
                   Callable[[], Dict[int, Dict[str, Any]]]] = None):
    self._ctx = context
    self._local = local
    self._timeout = float(negotiate_timeout_secs)
    self._poll_interval = float(poll_interval_secs)
    self._peer_heartbeats = peer_heartbeats
    self._proposed = False
    self._published = False
    self._target: Optional[int] = None
    self.participants: Optional[List[int]] = None
    self._m_stops = metrics_lib.counter('distributed/coordinated_stops')
    self._m_target = metrics_lib.gauge('distributed/coordinated_stop_step')
    self._m_retries = metrics_lib.counter('distributed/negotiation_retries')

  @property
  def target_step(self) -> Optional[int]:
    return self._target

  def request(self) -> None:
    """Programmatic local shutdown request (tests, cluster agents)."""
    if self._local is not None:
      self._local.request()

  def publish_boundary(self, step: int) -> None:
    """Publishes this host's boundary unconditionally (idempotent).

    Called by the trainer when its loop COMPLETES, before the final-save
    barriers: a peer whose SIGTERM lands after this moment still finds
    our final step in the KV store, so its negotiation converges instead
    of timing out against a host that will never poll again.
    """
    if self._published:
      return
    self._published = True
    self._ctx.put(f'shutdown/step/{self._ctx.process_index}',
                  str(int(step)))

  def _done_peers(self) -> Dict[int, int]:
    """Hosts whose goodbye heartbeat marks an orderly, completed exit."""
    if self._peer_heartbeats is None:
      return {}
    out: Dict[int, int] = {}
    try:
      for host, payload in self._peer_heartbeats().items():
        if payload.get('done'):
          out[int(host)] = int(payload.get('step', 0))
    except Exception:  # pylint: disable=broad-except
      logging.exception('peer heartbeat read failed (non-fatal).')
    return out

  def poll(self, step: int) -> Optional[int]:
    """One boundary's coordination round; returns the agreed stop step."""
    if self._target is not None:
      return self._target
    if (not self._proposed and self._local is not None
        and self._local.requested):
      self._proposed = True
      # Directory-style key: the coordination service's dir_get only
      # lists keys UNDER a prefix, so the poll below can see it.
      self._ctx.put(f'shutdown/proposal/{self._ctx.process_index}',
                    str(int(step)))
      flight.event('shutdown', 'distributed/stop_proposed',
                   f'host={self._ctx.process_index} step={step}')
      logging.warning(
          'Process %d observed a local shutdown signal at step %d; '
          'proposing a coordinated stop to all %d processes.',
          self._ctx.process_index, step, self._ctx.process_count)
    if not self._ctx.get_dir('shutdown/proposal/'):
      return None
    # A proposal exists (ours or a peer's): publish this host's boundary
    # once — we then PAUSE here (the published step must stay our true
    # position) until every host has published, bounded by the deadline.
    if not self._published:
      self._published = True
      self._ctx.put(f'shutdown/step/{self._ctx.process_index}',
                    str(int(step)))
    deadline = time.monotonic() + self._timeout
    retried = False
    expected = set(range(self._ctx.process_count))
    while True:
      published = self._ctx.get_dir('shutdown/step/')
      # Keys come back namespace-stripped but path-full:
      # 'shutdown/step/<p>'.
      steps = {int(key.rsplit('/', 1)[-1]): int(value)
               for key, value in published.items()}
      if expected <= set(steps):
        break
      missing = expected - set(steps)
      if not retried and missing:
        done = self._done_peers()
        if missing <= set(done):
          # Every missing host completed and said goodbye before the
          # proposal landed: retry the negotiation once against the
          # surviving hosts. The survivors' max is the target; the done
          # hosts' final states are already committed by their own final
          # saves, and they are excluded from the remaining commits.
          retried = True
          expected = expected - missing
          self._m_retries.inc()
          logging.warning(
              'Coordinated stop: host(s) %s completed and exited before '
              'the proposal; retrying the negotiation against surviving '
              'host(s) %s.', sorted(missing), sorted(expected))
          continue
      if time.monotonic() > deadline:
        metrics_lib.counter('distributed/barrier_timeouts').inc()
        raise DeadHostError(
            f'coordinated shutdown negotiation: only '
            f'{len(set(steps) & expected)} of {len(expected)} expected '
            f'processes published a stop boundary within '
            f'{self._timeout:.0f}s — one or more peers died '
            f'mid-negotiation. Restart the job; it will resume from '
            f'the last committed checkpoint.')
      time.sleep(self._poll_interval)
    steps = {h: s for h, s in steps.items() if h in expected}
    self._target = max(steps.values())
    self.participants = sorted(expected)
    self._m_stops.inc()
    self._m_target.set(self._target)
    flight.event('shutdown', 'distributed/stop_agreed',
                 f'target={self._target} participants={self.participants}')
    logging.warning(
        'Coordinated stop agreed: %d process(es) %s checkpoint at step '
        '%d (published boundaries: %s).', len(expected),
        sorted(expected), self._target,
        {f'host{h}': s for h, s in sorted(steps.items())})
    return self._target


# ------------------------------------------------ heartbeats + aggregation


def aggregate_snapshots(snapshots: Dict[int, Dict[str, Any]]
                        ) -> Dict[str, Any]:
  """Merges per-host registry snapshots into one job-level view.

  * counters (int values) are SUMMED under their original name;
  * gauges (float values) are labeled per host — ``name/host<p>`` — a
    gauge has no meaningful cross-host sum;
  * histograms (dict values) merge count/sum (mean recomputed);
    min/max/percentiles are per-host artifacts and are dropped.
  """
  merged: Dict[str, Any] = {}
  for host in sorted(snapshots):
    for name, value in snapshots[host].items():
      if isinstance(value, bool):
        continue
      if isinstance(value, int):
        merged[name] = merged.get(name, 0) + value
      elif isinstance(value, float):
        merged[f'{name}/host{host}'] = value
      elif isinstance(value, dict):
        agg = merged.setdefault(name, {'count': 0, 'sum': 0.0})
        if 'count' in agg:  # guard against a counter/hist name collision
          agg['count'] += int(value.get('count', 0))
          agg['sum'] += float(value.get('sum', 0.0))
          agg['mean'] = agg['sum'] / agg['count'] if agg['count'] else 0.0
  return merged


class HeartbeatService:
  """Per-host liveness publisher + peer monitor over the shared model dir.

  Each host atomically rewrites ``<directory>/host_<p>.json`` every
  ``interval_secs``: wall time, last-completed step, pid, and a registry
  snapshot (the payload process-0 aggregates). The same thread monitors
  every peer's file:

  * age > ``straggler_after_secs`` → flagged (gauge + counter + log);
  * age > ``dead_after_secs`` → the peer is DEAD. ``action='exit'``
    (what the trainer installs) logs a loud liveness error and calls
    ``os._exit(LIVENESS_EXIT_CODE)`` — the only way out when the main
    thread is wedged inside a collective; ``action='flag'`` records the
    dead set for the owner to act on (tests, embedders).

  The shared directory is the same filesystem the checkpoints already
  require (GCS/NFS on a real pod), so heartbeats need no extra
  infrastructure and remain observable post-mortem.
  """

  def __init__(self,
               directory: str,
               process_index: int,
               process_count: int,
               interval_secs: float = 5.0,
               straggler_after_secs: float = 15.0,
               dead_after_secs: float = 60.0,
               action: str = 'exit',
               include_metrics: bool = True,
               on_dead: Optional[Callable[[Set[int]], None]] = None):
    if action not in ('exit', 'flag'):
      raise ValueError(f"action must be 'exit' or 'flag', got {action!r}")
    self._dir = directory
    self.process_index = int(process_index)
    self.process_count = int(process_count)
    self._interval = float(interval_secs)
    self._straggler_after = float(straggler_after_secs)
    self._dead_after = float(dead_after_secs)
    self._action = action
    self._include_metrics = include_metrics
    self._on_dead = on_dead
    self._step = 0
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None
    self._started_at = time.time()
    self.dead_hosts: Set[int] = set()
    self._flagged_stragglers: Set[int] = set()
    hb = metrics_lib.scope('distributed/heartbeat')
    self._m_beats = hb.counter('beats')
    self._m_stragglers = hb.counter('stragglers_flagged')
    self._m_last_step = hb.gauge('last_completed_step')
    self._hb_scope = hb

  # ------------------------------------------------------------- publishing

  def set_step(self, step: int) -> None:
    """Called by the train loop at each dispatch boundary."""
    self._step = int(step)

  def _path(self, host: int) -> str:
    return os.path.join(self._dir, f'host_{host}.json')

  def beat(self, done: bool = False) -> None:
    """Publishes one heartbeat (atomic tmp+rename, crash-safe)."""
    os.makedirs(self._dir, exist_ok=True)
    payload = {
        'time': time.time(),
        'step': self._step,
        'pid': os.getpid(),
        'process_index': self.process_index,
        'done': bool(done),
    }
    if self._include_metrics:
      payload['metrics'] = metrics_lib.snapshot()
    path = self._path(self.process_index)
    tmp = f'{path}.tmp{os.getpid()}'
    with open(tmp, 'w') as f:
      json.dump(payload, f)
    os.replace(tmp, path)
    self._m_beats.inc()
    self._m_last_step.set(self._step)

  # ------------------------------------------------------------- monitoring

  def read_peers(self) -> Dict[int, Dict[str, Any]]:
    """All hosts' latest heartbeat payloads (including our own file)."""
    out = {}
    for host in range(self.process_count):
      try:
        with open(self._path(host)) as f:
          out[host] = json.load(f)
      except (OSError, ValueError):
        continue
    return out

  def check_peers(self) -> Dict[int, float]:
    """One monitoring pass; returns peer → heartbeat age in seconds."""
    now = time.time()
    peers = self.read_peers()
    ages: Dict[int, float] = {}
    newly_dead: Set[int] = set()
    for host in range(self.process_count):
      if host == self.process_index:
        continue
      payload = peers.get(host)
      # A peer that never beat ages from our start (startup grace).
      age = now - (payload['time'] if payload else self._started_at)
      ages[host] = age
      if payload is not None and payload.get('done'):
        # The peer finished its run and said goodbye: a growing age is
        # not death, and declaring it dead would needlessly kill THIS
        # still-training host.
        self._hb_scope.gauge(f'host{host}/age_sec').set(age)
        continue
      self._hb_scope.gauge(f'host{host}/age_sec').set(age)
      if payload is not None:
        self._hb_scope.gauge(f'host{host}/step').set(payload.get('step', 0))
      if age > self._dead_after:
        if host not in self.dead_hosts:
          newly_dead.add(host)
        self.dead_hosts.add(host)
      elif age > self._straggler_after:
        if host not in self._flagged_stragglers:
          self._flagged_stragglers.add(host)
          self._m_stragglers.inc()
          flight.event('liveness', 'distributed/straggler',
                       f'host={host} age_sec={age:.1f}')
          logging.warning(
              'Host %d is straggling: last heartbeat %.1fs ago (straggler '
              'threshold %.1fs, declared dead at %.1fs).', host, age,
              self._straggler_after, self._dead_after)
      else:
        self._flagged_stragglers.discard(host)
    self._hb_scope.gauge('dead_hosts').set(len(self.dead_hosts))
    if newly_dead:
      self._handle_dead(newly_dead, ages)
    return ages

  def _handle_dead(self, newly_dead: Set[int], ages: Dict[int, float]) -> None:
    detail = ', '.join(f'host {h} (last heartbeat {ages[h]:.1f}s ago)'
                       for h in sorted(newly_dead))
    message = (
        f'LIVENESS: declaring {detail} DEAD after '
        f'{self._dead_after:.0f}s without a heartbeat. This process '
        f'(host {self.process_index}) would otherwise hang forever in the '
        f'next collective or barrier; exiting with status '
        f'{LIVENESS_EXIT_CODE} so the scheduler restarts the job from the '
        f'last committed checkpoint.')
    logging.critical(message)
    flight.event('error', 'distributed/dead_host',
                 f'dead={sorted(newly_dead)} '
                 f'ages={[round(ages[h], 1) for h in sorted(newly_dead)]}')
    if self._on_dead is not None:
      self._on_dead(set(newly_dead))
    if self._action == 'exit':
      print(message, file=sys.stderr, flush=True)
      # Forensics before the hard exit: the bundle is a bounded atomic
      # write (postmortem.dump never raises), and this monitor thread is
      # alive precisely because the main thread may be wedged — this is
      # the only chance to record what led up to the death.
      from tensor2robot_tpu.observability import postmortem

      postmortem.dump(
          os.path.dirname(os.path.abspath(self._dir)) or None,
          'dead_host', exit_code=LIVENESS_EXIT_CODE,
          extra={'dead_hosts': sorted(newly_dead),
                 'monitor_host': self.process_index,
                 'last_step': self._step})
      # os._exit, not sys.exit: the main thread may be wedged inside a
      # collective/barrier and would never process a normal exception.
      os._exit(LIVENESS_EXIT_CODE)

  # ----------------------------------------------------------- aggregation

  def aggregate(self) -> Dict[str, Any]:
    """Job-level merged metrics (this host's LIVE registry + peers'
    heartbeat snapshots)."""
    snaps: Dict[int, Dict[str, Any]] = {}
    for host, payload in self.read_peers().items():
      if host == self.process_index:
        continue
      metrics = payload.get('metrics')
      if isinstance(metrics, dict):
        snaps[host] = metrics
    snaps[self.process_index] = metrics_lib.snapshot()
    return aggregate_snapshots(snaps)

  def aggregated_scalars(self) -> Dict[str, float]:
    """Flat ``cluster/...`` scalars for the trainer's log-crossing merge:
    summed counters plus per-host step/age gauges (full per-host gauge
    labeling stays in ``/metricsz`` and the report, where cardinality is
    free)."""
    out: Dict[str, float] = {}
    for name, value in self.aggregate().items():
      if isinstance(value, int):
        out[f'cluster/{name}'] = float(value)
    for host, payload in sorted(self.read_peers().items()):
      out[f'cluster/host{host}/step'] = float(payload.get('step', 0))
      out[f'cluster/host{host}/heartbeat_age_sec'] = (
          time.time() - float(payload.get('time', self._started_at)))
    return out

  def cluster_report(self) -> Dict[str, Any]:
    """The ``/metricsz`` + end-of-run report section (report provider)."""
    peers = self.read_peers()
    now = time.time()
    return {
        'process_index': self.process_index,
        'process_count': self.process_count,
        'dead_hosts': sorted(self.dead_hosts),
        'hosts': {
            str(host): {
                'step': payload.get('step'),
                'pid': payload.get('pid'),
                'heartbeat_age_sec': round(now - payload.get('time', now), 3),
            } for host, payload in sorted(peers.items())
        },
        'merged_metrics': self.aggregate(),
    }

  # -------------------------------------------------------------- lifecycle

  def start(self) -> 'HeartbeatService':
    if self._thread is not None:
      return self
    self._started_at = time.time()
    self._stop.clear()

    def run():
      while not self._stop.is_set():
        try:
          self.beat()
          self.check_peers()
        except Exception:  # pylint: disable=broad-except
          logging.exception('Heartbeat pass failed (non-fatal).')
        self._stop.wait(self._interval)

    self._thread = threading.Thread(target=run, daemon=True,
                                    name='t2r-heartbeat')
    self._thread.start()
    if self.process_index == 0:
      metrics_lib.register_report_provider('cluster', self.cluster_report)
    return self

  def stop(self) -> None:
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=5.0)
      self._thread = None
    # The 'cluster' report provider stays REGISTERED: the heartbeat
    # files it reads persist, so the end-of-run report / a post-training
    # /metricsz scrape still shows the whole job's merged view (a later
    # service in the same process replaces the registration).
    # Final beat says goodbye (done=True): post-mortem tooling sees the
    # last completed step, and peers still training do not declare this
    # orderly exit a death. Never raise during shutdown.
    try:
      self.beat(done=True)
    except OSError:
      pass

  def __enter__(self) -> 'HeartbeatService':
    return self.start()

  def __exit__(self, *exc) -> None:
    self.stop()
