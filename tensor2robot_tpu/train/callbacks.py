"""Trainer callbacks: logging, profiling, variable stats.

The callback protocol (``trainer.TrainerCallback``) replaces the
reference's SessionRunHook/HookBuilder machinery (``hooks/hook_builder.py``,
``hooks/variable_logger_hook.py``); these are the stock implementations.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

import jax
import numpy as np

from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.observability import tracing
from tensor2robot_tpu.train.trainer import TrainerCallback


class VariableLoggerCallback(TrainerCallback):
  """Logs mean/std (optionally values) of all params.

  Capability-equivalent of ``hooks/variable_logger_hook.py:33-68``.
  """

  def __init__(self, log_interval_steps: int = 100,
               log_values: bool = False):
    self._log_interval_steps = log_interval_steps
    self._log_values = log_values

  def after_step(self, trainer, step: int, scalars) -> None:
    if not trainer.crossed(self._log_interval_steps, step):
      return
    flat = jax.tree_util.tree_leaves_with_path(trainer.state.params)
    for path, value in flat:
      value = np.asarray(value)
      name = jax.tree_util.keystr(path)
      logging.info('var %s mean=%.6f std=%.6f', name, value.mean(),
                   value.std())
      if self._log_values:
        logging.info('var %s value=%s', name, value)


class MetricsLoggerCallback(TrainerCallback):
  """Appends train/eval scalars as JSON lines under the model dir."""

  def __init__(self, filename: str = 'metrics.jsonl'):
    self._filename = filename

  def _write(self, trainer, record: dict) -> None:
    if not trainer.config.model_dir:
      return
    os.makedirs(trainer.config.model_dir, exist_ok=True)
    path = os.path.join(trainer.config.model_dir, self._filename)
    with open(path, 'a') as f:
      f.write(json.dumps(record) + '\n')

  def after_step(self, trainer, step: int, scalars) -> None:
    if not scalars or not trainer.crossed(trainer.config.log_interval_steps,
                                          step):
      return
    record = {'kind': 'train', 'step': int(step)}
    record.update({k: float(v) for k, v in scalars.items()})
    self._write(trainer, record)

  def after_eval(self, trainer, step: int, metrics) -> None:
    record = {'kind': 'eval', 'step': int(step)}
    record.update({k: float(v) for k, v in metrics.items()})
    self._write(trainer, record)


class ResilienceLoggerCallback(TrainerCallback):
  """Surfaces fault-tolerance counters in the normal log stream.

  At each crossed log interval, reports the non-finite guard's skipped-
  update totals and the data error budget charges absorbed so far, so a
  run quietly absorbing faults is VISIBLY absorbing them — silent
  resilience ages into silent data loss. Reads the observability
  registry (``resilience/*`` deltas against its ``begin()`` snapshot)
  rather than poking trainer or iterator internals: every layer's
  budget — reader-level, batch-level, per SOURCE file — flows through
  the same counters, whichever object absorbed the fault.
  """

  def __init__(self, log_interval_steps: Optional[int] = None,
               iterator=None):
    self._log_interval_steps = log_interval_steps
    # Legacy parameter: budgets now reach this callback through the
    # registry, so the iterator handle is only kept as a fallback for
    # budget metadata (max_errors) in the absorbed-errors line.
    self._iterator = iterator
    self._start = {}

  def begin(self, trainer) -> None:
    self._start = metrics_lib.snapshot('resilience/')

  def _deltas(self):
    return metrics_lib.delta(self._start, 'resilience/')

  def after_step(self, trainer, step: int, scalars) -> None:
    interval = (self._log_interval_steps
                if self._log_interval_steps is not None
                else trainer.config.log_interval_steps)
    if not trainer.crossed(interval, step):
      return
    deltas = self._deltas()
    skipped = deltas.get('resilience/nonfinite_skipped_steps', 0)
    if skipped:
      policy = trainer.nonfinite_policy
      logging.info(
          'resilience: %d non-finite update(s) skipped so far '
          '(%d consecutive bad dispatch(es)%s).', skipped,
          int(deltas.get('resilience/consecutive_bad_dispatches', 0)),
          f', mode={policy.mode}' if policy is not None else '')
    errors = deltas.get('resilience/data_errors', 0)
    if errors:
      by_source = ', '.join(
          f'{name[len("resilience/data_errors/"):]}: {count}'
          for name, count in sorted(deltas.items())
          if name.startswith('resilience/data_errors/') and count)
      budget = getattr(self._iterator, 'budget', None)
      limit = (f'/{budget.max_errors}' if budget is not None else '')
      logging.info('resilience: %d%s data error(s) absorbed (%s).',
                   errors, limit, by_source or 'unattributed')

  def end(self, trainer) -> None:
    skipped = self._deltas().get('resilience/nonfinite_skipped_steps', 0)
    if skipped:
      logging.warning(
          'resilience: run finished with %d non-finite update(s) skipped.',
          skipped)


class ProfilerCallback(TrainerCallback):
  """Captures a ``jax.profiler`` trace over a step window.

  The tracing capability the reference delegates to TF summaries /
  TensorBoard (SURVEY §5); traces are viewable in TensorBoard or Perfetto.
  """

  def __init__(self,
               start_step: int = 10,
               num_steps: int = 5,
               logdir: Optional[str] = None):
    self._start_step = start_step
    self._stop_step = start_step + num_steps
    self._logdir = logdir
    self._active = False
    self._done = False
    self._step_annotation = None

  def _close_step_annotation(self) -> None:
    if self._step_annotation is not None:
      self._step_annotation.__exit__(None, None, None)
      self._step_annotation = None

  def after_step(self, trainer, step: int, scalars) -> None:
    # >= not ==: with steps_per_dispatch > 1 the loop reports only
    # dispatch-boundary steps; the trace starts at the first boundary
    # at-or-after start_step and stops at the first at-or-after
    # stop_step (covering at least one dispatch even when the window is
    # narrower than the dispatch stride). A run that resumes already
    # past the window (checkpoint restore at step >> stop_step) must
    # never start — a spurious one-dispatch trace on every restart is
    # worse than no trace — so a dispatch that BEGAN at-or-after
    # stop_step retires the window instead of opening it.
    if (not self._done and not self._active and
        trainer.dispatch_start_step >= self._stop_step):
      self._done = True
      return
    if step >= self._start_step and not self._active and not self._done:
      logdir = self._logdir or os.path.join(
          trainer.config.model_dir or '/tmp', 'profile')
      os.makedirs(logdir, exist_ok=True)
      jax.profiler.start_trace(logdir)
      self._active = True
    elif step >= self._stop_step and self._active:
      self._close_step_annotation()
      jax.profiler.stop_trace()
      self._active = False
      self._done = True
    if self._active:
      # Step markers: while the trace runs, bracket everything from this
      # dispatch boundary to the next (host feed + the next dispatch)
      # in a StepTraceAnnotation, so captured traces carry per-step
      # boundaries and TensorBoard/Perfetto can compute a step-time
      # breakdown instead of one undifferentiated span.
      self._close_step_annotation()
      self._step_annotation = tracing.step_annotation(step)
      self._step_annotation.__enter__()

  def end(self, trainer) -> None:
    self._close_step_annotation()
    if self._active:
      jax.profiler.stop_trace()
      self._active = False


class TensorBoardCallback(TrainerCallback):
  """Writes train/eval scalars as TensorBoard event files.

  The reference's primary observability surface (``tf.summary`` via
  ``models/abstract_model.py:350-370`` + summary hooks); uses the host-side
  TF for writing only — nothing touches the device path. Event files land
  under ``<model_dir>/events/{train,eval}``.
  """

  def __init__(self, logdir: Optional[str] = None):
    self._logdir = logdir
    self._writers = {}

  def _writer(self, trainer, kind: str):
    if kind not in self._writers:
      import tensorflow as tf

      logdir = self._logdir or os.path.join(
          trainer.config.model_dir or '/tmp', 'events')
      self._writers[kind] = tf.summary.create_file_writer(
          os.path.join(logdir, kind))
    return self._writers[kind]

  def _write(self, trainer, kind: str, step: int, scalars) -> None:
    import tensorflow as tf

    writer = self._writer(trainer, kind)
    with writer.as_default(step=int(step)):
      for key, value in scalars.items():
        tf.summary.scalar(key, float(value))
    writer.flush()

  def after_step(self, trainer, step: int, scalars) -> None:
    if not scalars or not trainer.crossed(trainer.config.log_interval_steps,
                                          step):
      return
    self._write(trainer, 'train', step, scalars)

  def after_eval(self, trainer, step: int, metrics) -> None:
    if metrics:
      self._write(trainer, 'eval', step, metrics)

  def end(self, trainer) -> None:
    for writer in self._writers.values():
      writer.close()
    self._writers.clear()
