"""Preemption-safe shutdown and non-finite-update policy for the trainer.

The *survival* half of fault tolerance for the train layer (checkpoints
and resumable input streams are the recovery half):

* :class:`GracefulShutdown` — a SIGTERM/SIGINT handler for preemptible
  fleets. The signal only sets a flag; ``Trainer.train`` checks it at
  each dispatch boundary, finishes the in-flight dispatch, forces a
  checkpoint (+ input-state save via the normal ``after_checkpoint``
  callbacks), and raises :class:`PreemptedError`, which the trainer
  binary converts to the distinct resumable exit status
  ``PREEMPTED_EXIT_CODE``. A second signal falls through to the previous
  handler (the handlers are restored after the first), so an operator
  can still hard-kill a stuck save.

* :class:`NonFinitePolicy` — the host-side decision for the device-side
  ``all_finite(loss, grads)`` flag the jitted train step folds into its
  scalars. The step itself always guards the update (``where(ok, new,
  old)``), so params are never corrupted by a NaN/Inf batch; the policy
  decides what the HOST does about it: raise immediately, or skip and
  count, halting after N consecutive bad dispatches. The flag is
  evaluated one dispatch behind (the trainer checks the previous
  dispatch's flag after queueing the next), so policy enforcement adds
  no device sync to the pipeline — the lag is safe precisely because the
  update was already guarded on device.

Everything here is SINGLE-process. In a multi-process job a signal
lands on one worker first; ``train/distributed_resilience.py`` layers
the cross-host half on top: ``CoordinatedShutdown`` propagates the flag
so every host checkpoints the SAME step and exits
``PREEMPTED_EXIT_CODE`` together, heartbeats declare dead hosts instead
of hanging, and the checkpoint that gets forced goes through the atomic
multi-host commit protocol in ``train/checkpoints.py``.
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Optional, Tuple

from tensor2robot_tpu.observability import flight
from tensor2robot_tpu.observability import metrics as metrics_lib

# The distinct, resumable exit status the trainer binary uses for
# preemption: schedulers/wrappers restart the job, and the restarted run
# restores the forced checkpoint + input state.
PREEMPTED_EXIT_CODE = 42


class PreemptedError(RuntimeError):
  """Training stopped by a preemption signal AFTER a forced checkpoint.

  Resumable: rerunning the same job restores the checkpoint this error
  acknowledges. ``exit_code`` is the status long-running binaries should
  exit with so the scheduler distinguishes preemption from failure.
  """

  exit_code = PREEMPTED_EXIT_CODE

  def __init__(self, step: int):
    super().__init__(
        f'training preempted at step {step}; checkpoint saved, resumable')
    self.step = int(step)


class NonFiniteError(RuntimeError):
  """The non-finite policy halted training (params are still finite)."""


class GracefulShutdown:
  """Converts SIGTERM/SIGINT into a flag checked at dispatch boundaries.

  ``install()`` registers handlers (main thread only — callers on other
  threads should use :meth:`request`); the first signal sets the flag
  and restores the previous handlers, so a second signal behaves as if
  this class were never there. Usable as a context manager.
  """

  def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                 signal.SIGINT)):
    self._signals = tuple(signals)
    self._event = threading.Event()
    self._prev = {}
    self._installed = False
    # Which signal tripped the flag (None for programmatic requests);
    # read by the trainer's boundary poll for the flight-ring record.
    self._signal_observed: Optional[int] = None
    # Wall-clock receipt of the request/signal: the start mark of the
    # whole-loop restart number (trainer/sigterm_to_resumed_step_seconds
    # — SIGTERM receipt → first post-restore completed dispatch, so the
    # measurement charges signal→checkpoint drain to the restart too).
    self._signal_time: Optional[float] = None

  @property
  def requested(self) -> bool:
    return self._event.is_set()

  @property
  def signal_time(self) -> Optional[float]:
    """Epoch seconds the shutdown was requested (None before)."""
    return self._signal_time

  def request(self) -> None:
    """Programmatic preemption (tests, cluster agents without signals)."""
    if not self._event.is_set():
      self._signal_time = time.time()
      flight.event('shutdown', 'resilience/shutdown_requested',
                   'source=programmatic')
    self._event.set()

  def _handler(self, signum, frame) -> None:
    del frame
    logging.warning(
        'Received signal %d: finishing the in-flight dispatch, then '
        'checkpointing and exiting resumable (next signal kills).', signum)
    # No flight.event here: a signal handler must not take the ring lock
    # (the interrupted main thread may hold it). The signal is recorded
    # when the trainer OBSERVES the flag at the next dispatch boundary.
    # time.time() is async-signal-safe (one syscall, no locks).
    self._signal_time = time.time()
    self._signal_observed = signum
    self._event.set()
    self.uninstall()

  def install(self) -> 'GracefulShutdown':
    if not self._installed:
      for s in self._signals:
        self._prev[s] = signal.signal(s, self._handler)
      self._installed = True
    return self

  def uninstall(self) -> None:
    if self._installed:
      for s, prev in self._prev.items():
        signal.signal(s, prev)
      self._prev.clear()
      self._installed = False

  def __enter__(self) -> 'GracefulShutdown':
    return self.install()

  def __exit__(self, *exc) -> None:
    self.uninstall()


_GLOBAL_SHUTDOWN: Optional[GracefulShutdown] = None


def install_graceful_shutdown() -> GracefulShutdown:
  """Installs (idempotently) the process-wide shutdown handler.

  Long-running binaries call this once at startup; any Trainer in the
  process then honors it via :func:`active_shutdown` without plumbing.
  """
  global _GLOBAL_SHUTDOWN
  if _GLOBAL_SHUTDOWN is None:
    _GLOBAL_SHUTDOWN = GracefulShutdown()
  # install() is idempotent, and re-installing matters: a caller that
  # uninstalled the singleton (e.g. the trainer binary restoring signal
  # dispositions on exit) can bring it back for a later run.
  return _GLOBAL_SHUTDOWN.install()


def active_shutdown() -> Optional[GracefulShutdown]:
  return _GLOBAL_SHUTDOWN


class NonFinitePolicy:
  """Host-side accounting/decision for device-guarded non-finite steps.

  ``mode``:
    * ``'off'``   — no guard compiled into the step (bitwise status quo).
    * ``'skip_update'`` — bad steps leave params/opt-state/``state.step``
      untouched (the rng stream therefore replays the slot, exactly as
      if the bad batch had never been drawn); skips are counted and a
      run of ``halt_after`` consecutive bad dispatches raises
      :class:`NonFiniteError` so an all-NaN stream cannot spin forever.
    * ``'raise'`` — first bad dispatch raises. Enforcement lags one
      dispatch (see module docstring) but the lagged dispatch ran on
      clean params, so nothing is ever corrupted.
  """

  MODES = ('off', 'skip_update', 'raise')

  def __init__(self, mode: str = 'skip_update', halt_after: int = 10):
    if mode not in self.MODES:
      raise ValueError(f'nonfinite mode must be one of {self.MODES}, '
                       f'got {mode!r}')
    self.mode = mode
    self.halt_after = int(halt_after)
    self.bad_steps = 0        # total non-finite steps skipped on device
    self.consecutive_bad = 0  # consecutive dispatches containing any
    # Registry mirror (observability/): the trainer merges these into
    # the train scalars at log intervals and ResilienceLoggerCallback
    # reads them — created here (even if never incremented) so the
    # series exists from step one whenever the guard is on.
    self._m_bad_steps = metrics_lib.counter(
        'resilience/nonfinite_skipped_steps')
    self._m_consecutive = metrics_lib.gauge(
        'resilience/consecutive_bad_dispatches')

  @property
  def enabled(self) -> bool:
    return self.mode != 'off'

  def observe(self, nonfinite_count: int, step: int) -> None:
    """Accounts one dispatch's device-computed non-finite step count."""
    if not self.enabled:
      return
    count = int(nonfinite_count)
    if count == 0:
      self.consecutive_bad = 0
      self._m_consecutive.set(0)
      return
    self.bad_steps += count
    self.consecutive_bad += 1
    # Mirror to the registry BEFORE any raise below: a halting run's
    # final scalars/report must carry the full skip accounting.
    self._m_bad_steps.inc(count)
    self._m_consecutive.set(self.consecutive_bad)
    flight.event(
        'nonfinite', 'resilience/nonfinite_skip',
        f'count={count} step={step} consecutive={self.consecutive_bad} '
        f'mode={self.mode}')
    if self.mode == 'raise':
      raise NonFiniteError(
          f'non-finite loss/grads at dispatch ending step {step} '
          f'(policy=raise); update was skipped on device, params remain '
          f'finite ({self.bad_steps} bad step(s) total)')
    logging.warning(
        'Non-finite loss/grads: skipped %d update(s) at dispatch ending '
        'step %d (%d total, %d consecutive bad dispatch(es), halt at %d).',
        count, step, self.bad_steps, self.consecutive_bad, self.halt_after)
    if self.halt_after and self.consecutive_bad >= self.halt_after:
      raise NonFiniteError(
          f'{self.consecutive_bad} consecutive dispatches with non-finite '
          f'loss/grads (>= halt_after={self.halt_after}) at step {step}; '
          f'{self.bad_steps} update(s) skipped in total — halting, the '
          f'input stream looks systematically broken')
