"""Train/eval driver: one generic jitted step over a device mesh.

TPU-native replacement of ``utils/train_eval.py:394-587``. The reference
drives ``tf.estimator.train_and_evaluate`` with Estimator/TPUEstimator,
wrapper models, and SessionRunHooks. Here a single SPMD program owns the
step: host-side input generators yield numpy batches; the jitted step runs
preprocess → forward → loss → grad → update entirely on device, sharded
over a ``jax.sharding.Mesh`` (data/fsdp axes shard the batch, XLA inserts
the gradient all-reduce the reference got from ``CrossShardOptimizer``).

Composition mirrors ``abstract_model.py:683-821``:

  preprocess (device, bf16 cast) → inference_network_fn → model_train_fn
  → optax update [→ EMA update]           (TRAIN, donated state)
  preprocess → inference_network_fn → model_eval_fn      (EVAL, averaged)

Checkpoints are Orbax (``train/checkpoints.py``); export and hooks attach
through the callback protocol (the reference's HookBuilder surface).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import os
import threading
import time
import warnings
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.observability import flight
from tensor2robot_tpu.observability import memory as memory_lib
from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.observability import postmortem as postmortem_lib
from tensor2robot_tpu.observability import programs as programs_lib
from tensor2robot_tpu.observability import tracing
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.specs import SpecStruct
from tensor2robot_tpu.train import checkpoints as ckpt_lib
from tensor2robot_tpu.train import distributed_resilience as dist_lib
from tensor2robot_tpu.train import resilience
from tensor2robot_tpu.train.train_state import (TrainState,
                                                accumulate_grads, apply_ema,
                                                create_train_state,
                                                finalize_accumulated_grads,
                                                init_grad_accumulators)

Batch = Tuple[Any, Any]
# What the train loop's place() emits and the prefetch queue carries:
# (placed (features, labels), use_auto_layout_executable).
PlacedBatch = Tuple[Batch, bool]
MetricDict = Dict[str, float]


def _process_start_time() -> float:
  """Epoch seconds this PROCESS started (not this module's import).

  /proc-derived on Linux so the restart-goodput gauge charges python
  startup + imports to the restart, which is what an operator's restart
  budget pays; falls back to this module's import time elsewhere.
  """
  try:
    with open('/proc/self/stat') as f:
      stat = f.read()
    # Fields after the parenthesized comm (which may contain spaces):
    # index 19 is starttime, in clock ticks since boot.
    ticks = float(stat[stat.rindex(')') + 2:].split()[19])
    with open('/proc/uptime') as f:
      uptime = float(f.read().split()[0])
    return time.time() - (uptime - ticks / os.sysconf('SC_CLK_TCK'))
  except Exception:  # pylint: disable=broad-except
    return time.time()


_PROCESS_START_TIME = _process_start_time()
# restart_to_first_step_seconds is a per-PROCESS number: only the first
# completed dispatch after a (re)start is a restart measurement.
_restart_recorded = False


def _record_restart_to_first_step() -> None:
  global _restart_recorded
  if _restart_recorded:
    return
  _restart_recorded = True
  elapsed = time.time() - _PROCESS_START_TIME
  metrics_lib.gauge('trainer/restart_to_first_step_seconds').set(elapsed)
  from tensor2robot_tpu.utils import compilation_cache as cache_lib

  logging.info(
      'First train step completed %.2fs after process start '
      '(compilation cache: %s).', elapsed, cache_lib.enabled_dir() or 'off')


# Whole-loop restart accounting (ROADMAP direction 5): the preemption
# branch persists the SIGTERM receipt time beside the checkpoints, and
# the restarted process's first completed dispatch turns it into the
# `trainer/sigterm_to_resumed_step_seconds` gauge — signal receipt →
# in-flight dispatch drain → forced checkpoint → scheduler restart →
# python/jax startup → restore → first post-restore dispatch, the number
# an operator's preemption budget actually pays. Measured across a REAL
# subprocess restart by tests/test_collect_loop.py; `loop_restart.json`
# persists the measurement for bench.py's `loop_restart_seconds` line.
PREEMPT_STATE_FILENAME = 'preempt_state.json'
LOOP_RESTART_FILENAME = 'loop_restart.json'


def _write_preempt_state(model_dir: str, shutdown, step: int) -> None:
  """Persists the SIGTERM receipt mark (atomic, never raises)."""
  if not model_dir:
    return
  import json

  sigterm_time = getattr(shutdown, 'signal_time', None) if shutdown else None
  path = os.path.join(model_dir, PREEMPT_STATE_FILENAME)
  try:
    tmp = f'{path}.tmp{os.getpid()}'
    with open(tmp, 'w') as f:
      json.dump({'sigterm_time': float(sigterm_time or time.time()),
                 'step': int(step), 'pid': os.getpid()}, f)
    os.replace(tmp, path)
  except OSError as e:
    logging.warning('Cannot persist preempt state under %r: %r',
                    model_dir, e)


def _record_sigterm_to_resumed(model_dir: str, step: int) -> None:
  """First-post-restore-dispatch mark: closes the restart measurement.

  A no-op unless a preemption left its receipt mark; the mark is
  CONSUMED (one measurement per preemption) and the result persisted to
  ``loop_restart.json`` for bench/test readers.
  """
  if not model_dir:
    return
  import json

  path = os.path.join(model_dir, PREEMPT_STATE_FILENAME)
  try:
    with open(path) as f:
      state = json.load(f)
    sigterm_time = float(state['sigterm_time'])
  except (OSError, ValueError, KeyError, TypeError):
    return
  elapsed = time.time() - sigterm_time
  metrics_lib.gauge('trainer/sigterm_to_resumed_step_seconds').set(elapsed)
  flight.event('shutdown', 'trainer/sigterm_to_resumed',
               f'seconds={elapsed:.3f} step={step}')
  logging.info(
      'Whole-loop restart: %.2fs from SIGTERM receipt (pre-restart step '
      '%s) to the first post-restore completed dispatch (step %d).',
      elapsed, state.get('step'), step)
  try:
    os.remove(path)
    out = os.path.join(model_dir, LOOP_RESTART_FILENAME)
    tmp = f'{out}.tmp{os.getpid()}'
    with open(tmp, 'w') as f:
      json.dump({'sigterm_to_resumed_step_seconds': elapsed,
                 'resumed_step': int(step),
                 'preempted_step': state.get('step')}, f)
    os.replace(tmp, out)
  except OSError as e:
    logging.warning('Cannot persist loop-restart measurement: %r', e)


def _place_releasing(place: Callable[[Batch], 'PlacedBatch'],
                     release: Callable[[], None],
                     batch: Batch) -> 'PlacedBatch':
  """Places ``batch`` and returns its ring-buffer lease (data/engine.py).

  The release point depends on what placement actually does with the
  host bytes:

  * Accelerator backends: ``device_put`` COPIES to device memory, so
    place, block on the placed leaves (transfer completion only — never
    compute), then release. This is the ROADMAP PR-3 follow-up's
    transfer-completion release point.
  * XLA-CPU: ``device_put`` may ZERO-COPY alias the host numpy buffer —
    "transfer completion" never copies, and releasing would let the
    engine overwrite the live batch under the step (observed as
    corrupted training). Take an explicit host copy of the ring views,
    release, then place the copy — exactly the copy ``np.stack`` paid
    before ring buffers existed.
  """
  if jax.default_backend() == 'cpu':
    batch = jax.tree_util.tree_map(lambda x: np.array(x, copy=True), batch)
    release()
    return place(batch)
  placed = place(batch)
  jax.block_until_ready(placed[0])
  release()
  return placed


def crossed_interval(interval: int, step_before: int, step_after: int) -> bool:
  """Did the step counter cross a multiple of ``interval``?

  The ONE interval test for the trainer loop and every logging callback
  (via ``Trainer.crossed``), so the cadence can't drift. ``interval == 0``
  disables. With ``steps_per_dispatch > 1`` the counter advances K at a
  time and may jump over exact multiples; an interval fires at the first
  dispatch boundary on or after each multiple. For K == 1 this reduces
  exactly to ``step_after % interval == 0``.
  """
  return bool(interval) and (step_after // interval) > (step_before // interval)


class TrainerCallback:
  """Hook surface, replacing SessionRunHooks/HookBuilders (hooks/*.py)."""

  def begin(self, trainer: 'Trainer') -> None:
    ...

  def after_step(self, trainer: 'Trainer', step: int,
                 scalars: MetricDict) -> None:
    ...

  def after_checkpoint(self, trainer: 'Trainer', step: int) -> None:
    ...

  def after_eval(self, trainer: 'Trainer', step: int,
                 metrics: MetricDict) -> None:
    ...

  def end(self, trainer: 'Trainer') -> None:
    ...


@dataclasses.dataclass
class TrainerConfig:
  """Run configuration (the reference's RunConfig + TrainSpec/EvalSpec)."""

  model_dir: str = ''
  max_train_steps: int = 1000
  eval_steps: int = 10          # batches per eval pass
  eval_interval_steps: int = 500  # train steps between eval passes
  save_interval_steps: int = 500
  max_checkpoints_to_keep: Optional[int] = 5
  keep_checkpoint_period: Optional[int] = None
  log_interval_steps: int = 100
  seed: int = 0
  async_checkpoints: bool = True
  # Bounded device prefetch: a background thread pulls batches from the
  # input iterator and stages them on device (shard_batch) up to this
  # many ahead, overlapping host parse/decode + h2d with the device step
  # (the role tf.data prefetch + infeed play for the reference's
  # TPUEstimator). 0 disables (batches fetched inline). Batch order is
  # preserved, so training is bit-identical either way. None = auto:
  # 2 on multi-core hosts, 0 on single-core ones — profiled on a 1-CPU
  # host, the worker thread CONTENDS with dispatch instead of
  # overlapping it (record-fed grasp2vec: 297 → 663 ms/step median).
  prefetch_batches: Optional[int] = None
  # Compiler-chosen input layouts for the BATCH arguments: the train
  # step is additionally lowered with AUTO layouts and batches are
  # placed in the layout the executable prefers, so XLA never inserts
  # a re-layout copy at the parameter boundary (the WTL episode batch
  # paid 2×0.9 ms/step re-laying 507 MB of uint8 input). None = auto:
  # on for TPU backends, off elsewhere and for multi-host feeding
  # (the process-local assembly path has no layout control).
  auto_input_layouts: Optional[bool] = None
  # Non-finite update guard (train/resilience.py). 'off' compiles the
  # historical step (bitwise status quo). 'skip_update' / 'raise' fold a
  # device-side all_finite(loss, grads) check into the jitted step and
  # guard the whole state update with where(ok, new, old): a NaN/Inf
  # batch can never corrupt params, opt state, EMA, or the rng stream
  # (state.step only advances on applied updates, so the skipped slot's
  # fold_in key is reused — training equals a run that never drew the
  # bad batch). The host evaluates the flag one dispatch behind (no
  # added sync) and either raises immediately ('raise') or counts skips
  # and halts after nonfinite_halt_after consecutive bad dispatches.
  nonfinite_mode: str = 'off'
  nonfinite_halt_after: int = 10
  # Honor SIGTERM/SIGINT at dispatch boundaries: finish the in-flight
  # dispatch, force a checkpoint (+ input-state save via the normal
  # after_checkpoint callbacks), and raise resilience.PreemptedError —
  # the preemptible-fleet contract. False leaves signal handling alone
  # (library embedders own their signals); an already-installed global
  # handler (resilience.install_graceful_shutdown) is honored either way.
  handle_preemption: bool = False
  # Train steps folded into ONE device dispatch (TPUEstimator's
  # iterations_per_loop, tpu_config.py in the reference's stack): the
  # loop stacks K host batches and a lax.scan runs K optimizer steps
  # per XLA program, so per-dispatch host overhead (RPC latency on
  # remote/tunneled devices, python dispatch otherwise) amortizes K×.
  # Training math is IDENTICAL to K single dispatches (same rng stream:
  # the per-step fold_in keys off state.step). Logging, checkpointing
  # and eval quantize to dispatch boundaries — intervals fire at the
  # first boundary ON OR AFTER each multiple, exactly like
  # iterations_per_loop; callbacks see only boundary steps.
  steps_per_dispatch: int = 1
  # Device-resident multi-step feeding: with steps_per_dispatch=K, the
  # K-batch step-group moves to device as ONE ``jax.device_put`` of the
  # whole (features, labels) pytree — one H2D burst per dispatch instead
  # of one per leaf — into a double-buffered input ring (prefetch depth
  # >= 2, so the burst for superbatch N+1 overlaps the scanned compute
  # of N), and on accelerator backends the batch arguments are DONATED
  # to the K-step executable, letting XLA reuse the superbatch's device
  # buffers as scratch. The grouping path assembles batches in place
  # into preallocated contiguous superbatch buffers (no np.stack copy;
  # see _SuperbatchAssembler). Training math is bitwise identical to
  # the default feed (same executable on CPU; pinned by
  # tests/test_device_feed.py). Ignored (off) when the mesh spans
  # processes — multi-host feeding assembles per-process shards, which
  # has no single-put form. Default OFF until BENCH_r06 measures it,
  # per the round-2 honesty rule.
  device_feed: bool = False
  # Fused optimizer/EMA/guard update (ops/fused_update.py): run the
  # entire Adam/SGD + EMA + nonfinite-select chain as ONE elementwise
  # Pallas pass over flattened parameter blocks — each param leaf read
  # once, written once, instead of XLA's multi-pass op soup. Takes
  # effect only when the kernel-dispatch gate is on (TPU, or the test
  # force) AND the model's optimizer is a tagged factory from
  # models/optimizers.py with a recognized opt-state structure; in
  # every other case the stock optax path runs, bit for bit. The fused
  # pass itself is accepted by a documented parity band vs optax
  # (tests/test_device_feed.py). Default OFF until BENCH_r06.
  fused_update: bool = False
  # Microbatch gradient accumulation (GPipe-style): the jitted step runs
  # a lax.scan over M slices of the host batch — [B, ...] reshaped to
  # [M, B/M, ...] — accumulating gradients in donated float32 carries,
  # then applies ONE optimizer update on the microbatch-mean gradient.
  # Peak activation memory follows the MICRObatch (B/M), so effective
  # batches past the HBM cliff train at near-optimal per-example
  # throughput (the qtopt curve collapses 8.6× at batch 96; M=2×64 keeps
  # batch-64 activations). For mean-reduced losses the update equals the
  # full-batch step exactly (f32 accumulators; pinned by
  # tests/test_memory_scaling.py), with one caveat: batch-coupled ops
  # (BatchNorm batch statistics, batch-shaped dropout masks) see the
  # microbatch — "ghost batch norm" semantics, B/M-sized stats.
  # Preprocessing runs ONCE over the full host batch (same rng draws as
  # the unsliced step); the per-step rng fold_in, EMA update, and the
  # non-finite guard (evaluated over the ACCUMULATED gradients) all
  # advance once per effective batch. Composes with steps_per_dispatch:
  # K host batches × M microbatches nest as one XLA program. B % M == 0.
  grad_accum_microbatches: int = 1
  # Dense/Conv contraction precision for the training step
  # (quantize/fp8_training.py). None leaves the model's own
  # ``matmul_precision`` untouched; 'bf16' forces the historical
  # program; 'fp8' routes every Dense/Conv contraction through
  # delayed-amax-scaled float8_e4m3fn quantize-dequantize — the chip's
  # 2×-bf16 MXU path, the only lever on the 22% MFU ceiling itself.
  # Master weights stay float32 in the optimizer state (params are
  # never cast); per-op gradients leave the injected ops unscaled in
  # full precision before any accumulation; amax histories ride the
  # 'fp8_stats' collection through model_state like BatchNorm
  # statistics. Gated on quantization.fp8_supported(); accepted by a
  # parity band vs. the bf16 run (tests/test_kernels.py), the same
  # certificate discipline as the grasp2vec bf16 soak.
  matmul_precision: Optional[str] = None
  # Per-dispatch step-time breakdown (observability/): decomposes each
  # dispatch's wall time into host wait-for-batch, H2D placement,
  # dispatch/enqueue, device step, and callback overhead, and merges
  # examples_per_sec / input_bound_fraction / goodput into the scalars
  # dict at log intervals (so MetricsLogger/TensorBoard publish them
  # with zero API change). The device-step measurement blocks on the
  # PREVIOUS dispatch's outputs only after enqueueing the current one —
  # one dispatch behind, so the device pipeline never drains and no
  # sync is added to the in-flight dispatch (host run-ahead caps at one
  # dispatch, which the bounded prefetch queue effectively imposed
  # already). Costs a handful of perf_counter reads + registry updates
  # per dispatch; False restores the uninstrumented loop exactly.
  step_breakdown: bool = True
  # Compiled-program ledger (observability/programs.py): record the
  # train step's executable — cost_analysis FLOPs/bytes, memory
  # analysis, fingerprint, donation map — once at compile time, derive
  # live train/mfu + train/hbm_gbps + train/roofline_fraction at log
  # crossings from the breakdown's device time, and watch the jit cache
  # for steady-state recompiles (flagged as 'program' flight events
  # within the dispatch that paid them). Per-dispatch cost is one C++
  # cache-size probe + an int compare; the one-off AOT harvest of the
  # jitted step runs on a daemon thread (a disk read when the
  # persistent compilation cache is enabled).
  program_ledger: bool = True
  # When the auto-layout build did not already record 'train/step', the
  # AOT harvest of the jitted step is a REAL second backend compile
  # whose tracing contends (GIL) with the dispatch loop. Deferring it
  # keeps short runs and benchmarks unpolluted — the timer is cancelled
  # if the loop ends first (a post-run harvest serves no live gauge),
  # and on any run longer than the delay the MFU gauges appear from the
  # next log window on. 0 harvests immediately after the first dispatch.
  program_harvest_delay_seconds: float = 5.0
  # Live metrics endpoint (observability/metricsz.py): serve
  # ``registry.report()`` JSON at http://127.0.0.1:<port>/metricsz from a
  # stdlib http.server daemon thread, for fleet scraping without touching
  # the training process. None = off (the default; the T2R_METRICSZ_PORT
  # env var also opts in); 0 = an ephemeral port (logged, and readable
  # from ``observability.metricsz.global_server().port``).
  metricsz_port: Optional[int] = None
  # Metrics time-series history (observability/timeseries.py): snapshot
  # the whole registry every this-many seconds into a bounded ring,
  # served at /metricsz?history=1 and embedded in postmortem bundles so
  # an incident shows how every series MOVED over the final minutes,
  # not just where it ended. 0 disables; the process-global recorder is
  # started once (first cadence wins).
  timeseries_interval_secs: float = 10.0
  # Persistent XLA compilation cache (utils/compilation_cache.py): a
  # restarted process deserializes prior executables instead of
  # re-lowering the K×M train program, so restart-to-first-step time
  # (the `trainer/restart_to_first_step_seconds` gauge, recorded per
  # bench round) drops to checkpoint-restore + cache-read. None also
  # consults the T2R_COMPILATION_CACHE_DIR env var; still-None keeps
  # jax's in-memory cache only.
  compilation_cache_dir: Optional[str] = None
  # Distributed resilience (train/distributed_resilience.py), the
  # multi-process extension of handle_preemption: coordinated preemption
  # (any host's SIGTERM → ALL hosts checkpoint the same step and exit
  # resumable together), the atomic multi-host checkpoint commit
  # protocol, per-host heartbeats with a liveness monitor, and process-0
  # metric aggregation. None = auto: on iff jax.process_count() > 1 and
  # the jax.distributed coordination service is available; False forces
  # it off (each process then behaves like PR 1's single-process layer —
  # NOT safe on real pods).
  distributed_coordination: Optional[bool] = None
  # Heartbeat cadence and liveness thresholds (multi-process only). A
  # host whose heartbeat is older than straggler_after is flagged; older
  # than liveness_timeout is DEAD: with liveness_action='exit' the
  # monitor logs a loud liveness error and exits
  # distributed_resilience.LIVENESS_EXIT_CODE instead of letting the
  # survivors hang forever in a collective/barrier ('flag' only records
  # it — embedders that own their own death handling).
  heartbeat_interval_secs: float = 5.0
  heartbeat_straggler_secs: float = 15.0
  liveness_timeout_secs: float = 60.0
  liveness_action: str = 'exit'
  # Validate a checkpoint's recorded topology (process count, mesh
  # shape, microbatch config) against this run on restore; a mismatch is
  # a loud TopologyMismatchError instead of silently misread state.
  checkpoint_topology_check: bool = True
  # Elastic topology resume: with reshape on (the default), a mismatch
  # on the PURE-LAYOUT topology keys (process_count, device_count, mesh
  # shape) becomes a resharding restore — target shardings are rebuilt
  # from the CURRENT mesh and Orbax reshards the payload on read — so a
  # preempted 2-host job resumes on 1 host (or 4) instead of dying on
  # TopologyMismatchError. Semantic keys (grad_accum_microbatches,
  # steps_per_dispatch) still fail loudly: they change what the state
  # MEANS, not where it lives. False restores the strict PR-5 behavior.
  checkpoint_reshape: bool = True
  # Sharded multi-host checkpoint payloads: 'auto' shards whenever the
  # state's arrays span processes (a true FSDP/pod mesh — each host then
  # writes exactly the shards it owns); 'on' additionally stripes
  # per-host replica-group state across hosts (requires replicas in
  # lockstep, which deterministic same-stream training guarantees — the
  # 2-process drills run this); 'off' keeps the single-writer path
  # (process 0 writes everything).
  checkpoint_sharded_payloads: str = 'auto'
  # Async multi-host commit: unforced interval saves start their payload
  # write at the save point but run the ack/marker agreement at LATER
  # dispatch boundaries instead of blocking the loop on commit barriers
  # (checkpoint/save_overlap_ms records the hidden write time). Forced
  # saves — preemption, the final save — always commit synchronously, so
  # shutdown never leaves a durable payload without its marker.
  checkpoint_async_commit: bool = False
  # Deadline for every cross-host wait in the commit protocol; a peer
  # that misses it surfaces as a bounded DeadHostError, never a hang.
  checkpoint_barrier_timeout_secs: float = 600.0

  def resolved_distributed_coordination(self) -> bool:
    if self.distributed_coordination is not None:
      return self.distributed_coordination
    return jax.process_count() > 1

  def resolved_sharded_payloads(self, mesh) -> bool:
    if self.checkpoint_sharded_payloads == 'on':
      return True
    if self.checkpoint_sharded_payloads == 'off':
      return False
    if self.checkpoint_sharded_payloads != 'auto':
      raise ValueError(
          f"checkpoint_sharded_payloads must be 'auto', 'on' or 'off'; "
          f'got {self.checkpoint_sharded_payloads!r}')
    return mesh is not None and mesh_lib.mesh_spans_processes(mesh)

  def resolved_auto_input_layouts(self) -> bool:
    if jax.process_count() > 1:
      return False
    if self.auto_input_layouts is not None:
      return self.auto_input_layouts
    return jax.default_backend() == 'tpu'

  def resolved_prefetch_batches(self) -> int:
    if self.prefetch_batches is not None:
      return self.prefetch_batches
    # The data layer's autotuner owns the core heuristic (it also sizes
    # the input engine's workers off the same affinity-aware count and
    # breakdown signals): 2 on multi-core hosts, 0 on single-core ones,
    # where the worker thread CONTENDS with dispatch instead of
    # overlapping it (record-fed grasp2vec: 297 → 663 ms/step median).
    from tensor2robot_tpu.data import engine as engine_lib

    return engine_lib.autotune_prefetch()


class _DevicePrefetcher:
  """Background pipeline staging upcoming batches ahead of the step.

  Pulls ``(features, labels)`` from ``it`` and keeps up to ``depth``
  staged batches in a bounded queue, so host parse/decode overlaps the
  device step instead of serializing with it. Two shapes, by backend:

  * Real TPU backends run a THREE-stage pipeline: a fetch worker pulls
    host batches from ``it`` (with the parallel input engine upstream
    this is mostly dequeueing — the engine's own workers do the decode),
    and a DEDICATED placement worker applies ``place`` (the auto-layout
    H2D shard_batch), so the decode of batch N+2, the placement of N+1
    and the device step of N all overlap across batches instead of
    serializing behind one thread.
  * On the forced-host CPU platform placement happens on the consumer
    thread and a single fetch worker is the only stage — XLA CPU runs an
    N-device mesh's collectives as N in-process threads, and a
    concurrent device_put can starve one participant into a rendezvous
    deadlock (observed as an all-reduce termination timeout → SIGABRT).

  FIFO through every stage: batch order — and therefore training — is
  unchanged in either shape.
  """

  _DONE = object()

  def __init__(self, it: Iterator[Batch],
               place: Callable[[Batch], 'PlacedBatch'], depth: int,
               place_stage: Optional[bool] = None,
               release: Optional[Callable[[], None]] = None):
    import queue
    import threading

    self._q: 'queue.Queue' = queue.Queue(maxsize=depth)
    self._host_q: Optional['queue.Queue'] = None
    self._err: Optional[BaseException] = None
    self._stop = threading.Event()
    # Ring-buffer lease release (data/engine.py reuse_buffers): called
    # once per batch AFTER its H2D transfer completes, so the engine may
    # recycle the host buffers the batch's arrays were views of. The
    # placement stage is the transfer-completion point this closes the
    # ROADMAP PR-3 follow-up with: place() → block on the placed leaves
    # → release() — all on the place/consumer thread, off the dispatch
    # critical path.
    self._release = release
    # Queue telemetry: a depth gauge pinned near 0 plus a climbing
    # starvation counter is the registry's signature of an input-bound
    # run (the breakdown's host_wait_ms says the same from the loop
    # side); starved_wait_ms is how long each starvation stalled.
    prefetch_metrics = metrics_lib.scope('trainer/prefetch')
    self._m_depth = prefetch_metrics.gauge('queue_depth')
    self._m_starved = prefetch_metrics.counter('starvation')
    self._m_starve_ms = prefetch_metrics.histogram('starved_wait_ms')
    self._m_batches = prefetch_metrics.counter('batches')
    if place_stage is None:
      place_stage = jax.default_backend() == 'tpu'
    self._consumer_place = None if place_stage else place
    self._threads = []

    if place_stage:
      host_q: 'queue.Queue' = queue.Queue(maxsize=depth)
      self._host_q = host_q
      m_host_depth = prefetch_metrics.gauge('host_queue_depth')

      def fetch():
        try:
          for batch in it:
            if self._stop.is_set():
              return
            host_q.put(batch)
            m_host_depth.set(host_q.qsize())
        except BaseException as e:  # surfaced on the consumer side
          self._err = e
        finally:
          host_q.put(self._DONE)

      def placer():
        try:
          while not self._stop.is_set():
            item = host_q.get()
            if item is self._DONE:
              return
            # Placement overlaps the device step and the upstream
            # decode; its time shows up as placement_overlapped_ms in
            # the breakdown (off the dispatch critical path).
            with tracing.span('trainer/place_stage', annotate=False):
              if self._release is not None:
                placed = _place_releasing(place, self._release, item)
              else:
                placed = place(item)
            self._q.put(placed)
        except BaseException as e:
          if self._err is None:
            self._err = e
        finally:
          self._q.put(self._DONE)

      self._threads = [
          threading.Thread(target=fetch, daemon=True,
                           name='t2r-prefetch-fetch'),
          threading.Thread(target=placer, daemon=True,
                           name='t2r-prefetch-place'),
      ]
    else:
      def worker():
        try:
          for batch in it:
            if self._stop.is_set():
              return
            self._q.put(batch)
        except BaseException as e:  # surfaced on the consumer side
          self._err = e
        finally:
          self._q.put(self._DONE)

      self._threads = [
          threading.Thread(target=worker, daemon=True, name='t2r-prefetch')
      ]
    for thread in self._threads:
      thread.start()

  def __iter__(self):
    return self

  def __next__(self) -> 'PlacedBatch':
    import queue

    if self._err is not None:
      # Deliver worker failures PROMPTLY: staged batches behind the
      # sentinel are not drained first — a dead pipeline must not feed
      # up to `depth` more steps before the loop learns about it.
      raise self._err
    try:
      item = self._q.get_nowait()
    except queue.Empty:
      # Starvation: the consumer outran the staging worker.
      self._m_starved.inc()
      t0 = time.perf_counter()
      item = self._q.get()
      self._m_starve_ms.observe((time.perf_counter() - t0) * 1e3)
    self._m_depth.set(self._q.qsize())
    if item is self._DONE:
      if self._err is not None:
        raise self._err
      raise StopIteration
    if self._consumer_place is not None:
      if self._release is not None:
        item = _place_releasing(self._consumer_place, self._release, item)
      else:
        item = self._consumer_place(item)
    self._m_batches.inc()
    return item

  def close(self, timeout: float = 10.0) -> None:
    import queue
    import time

    self._stop.set()
    # Keep draining until the workers exit: a single drain is not enough
    # (a worker's blocked put() refills the slot, and its final
    # put(_DONE) could block forever on a depth-1 queue). Both queues
    # drain — the fetch stage can be blocked on the host queue just as
    # the placement stage can be on the placed queue. Bounded: a worker
    # stuck inside the input iterator's next() (stalled producer) can
    # never observe the stop event — abandon the daemon thread rather
    # than hang end-of-training shutdown.
    deadline = time.monotonic() + timeout
    while any(t.is_alive() for t in self._threads):
      if time.monotonic() > deadline:
        logging.warning(
            'Prefetch worker did not exit within %.1fs (input iterator '
            'blocked?); abandoning the daemon thread(s).', timeout)
        break
      for q in (self._q, self._host_q):
        if q is None:
          continue
        try:
          q.get(timeout=0.025)
        except queue.Empty:
          pass
    for q in (self._q, self._host_q):
      if q is None:
        continue
      try:
        while True:
          q.get_nowait()
      except queue.Empty:
        pass


class _SuperbatchAssembler:
  """Assembles K host batches into contiguous ``[K, batch, ...]`` groups.

  Replaces the PR-4 ``np.stack`` grouping copy: each source batch is
  copied exactly once, directly into its slice of a preallocated
  contiguous superbatch buffer, and its source ring lease
  (``data/engine.py`` ``release()``) is returned the moment its bytes
  are copied in — per batch, instead of per group.

  Two buffer modes:

  * ``reuse=False`` (default, and the CPU path): every group gets fresh
    buffers and :meth:`release` is a no-op. Required wherever a
    zero-copy ``device_put`` may alias the host buffer for the
    dispatch's lifetime (XLA-CPU — see ``_place_releasing``).
  * ``reuse=True`` (device feed on accelerators): ``slots``
    preallocated buffer sets are recycled as a ring, mirroring the
    input engine's lease contract — the consumer calls
    :meth:`release` once per delivered superbatch when its H2D
    transfer completes, freeing the OLDEST outstanding slot (FIFO,
    exactly like engine ``release()``). Two slots double-buffer: the
    assembly of group N+1 proceeds while group N's burst is in flight,
    and assembly blocks only when both slots are outstanding.

  Grouping semantics are unchanged from the old ``_grouped_batches``:
  groups clip so the train loop never overshoots ``max_steps``; a batch
  whose leaf shapes differ from the open group's closes that group
  early (the odd batch starts its own group); short/ragged groups get
  fresh buffers (never ring slots — their shapes differ) and just
  retrace the scan executable. Emitted steps are tracked here so
  grouping stays correct when a prefetcher pulls groups ahead.
  """

  def __init__(self, it: Iterator[Batch], k: int, start_step: int,
               max_steps: int,
               release: Optional[Callable[[], None]] = None,
               reuse: bool = False, slots: int = 2):
    import collections
    import queue

    self._it = iter(it)
    self._k = max(1, int(k))
    self._max_steps = max_steps
    self._emitted = start_step
    self._release_source = release
    self._reuse = bool(reuse)
    self._slots = max(1, int(slots))
    self._free: Optional['queue.Queue'] = queue.Queue() if reuse else None
    self._ring: List[Batch] = []
    self._ring_sig = None
    # FIFO of outstanding superbatch leases: ring slot index, or None
    # for fresh buffers (whose release is a no-op entry).
    self._leases = collections.deque()
    self._lease_lock = threading.Lock()
    self._gen = self._generate()

  def release(self) -> None:
    """Frees the OLDEST outstanding superbatch lease (engine contract).

    Called by the placement stage once a superbatch's H2D transfer has
    completed; returns its ring slot (if any) for reuse.
    """
    with self._lease_lock:
      if not self._leases:
        raise RuntimeError('release() without an outstanding superbatch')
      slot = self._leases.popleft()
    if slot is not None:
      self._free.put(slot)

  @staticmethod
  def _leaf_shapes(batch):
    return [np.shape(x) for x in jax.tree_util.tree_leaves(batch)]

  @staticmethod
  def _alloc(batch: Batch, k: int) -> Batch:
    return jax.tree_util.tree_map(
        lambda x: np.empty((k,) + np.shape(x),
                           dtype=np.asarray(x).dtype), batch)

  def _assemble(self, group) -> Batch:
    k = len(group)
    slot = None
    if self._reuse and k == self._k:
      sig = (k, self._leaf_shapes(group[0]),
             [np.asarray(x).dtype
              for x in jax.tree_util.tree_leaves(group[0])])
      if self._ring_sig is None:
        self._ring_sig = sig
        for i in range(self._slots):
          self._ring.append(self._alloc(group[0], k))
          self._free.put(i)
      if sig == self._ring_sig:
        # Blocks until the consumer releases a slot: bounds assembly
        # run-ahead to the ring depth (the double buffer).
        slot = self._free.get()
    buffers = self._ring[slot] if slot is not None else self._alloc(
        group[0], k)
    dst_leaves = jax.tree_util.tree_leaves(buffers)
    for i, batch in enumerate(group):
      for dst, src in zip(dst_leaves, jax.tree_util.tree_leaves(batch)):
        np.copyto(dst[i], src)
      if self._release_source is not None:
        # This batch's bytes now live in the superbatch buffer; its
        # source ring slot can be recycled immediately.
        self._release_source()
    with self._lease_lock:
      self._leases.append(slot)
    return buffers

  def _generate(self):
    group: List[Batch] = []
    for batch in self._it:
      if group and self._leaf_shapes(batch) != self._leaf_shapes(group[0]):
        yield self._assemble(group)
        self._emitted += len(group)
        group = []
        if self._emitted >= self._max_steps:
          return
      group.append(batch)
      if len(group) >= min(self._k, self._max_steps - self._emitted):
        yield self._assemble(group)
        self._emitted += len(group)
        group = []
        if self._emitted >= self._max_steps:
          return
    if group:
      yield self._assemble(group)

  def __iter__(self):
    return self

  def __next__(self) -> Batch:
    return next(self._gen)


def _grouped_batches(it: Iterator[Batch], k: int, start_step: int,
                     max_steps: int,
                     release: Optional[Callable[[], None]] = None
                     ) -> Iterator[Batch]:
  """K-batch ``[K, batch, ...]`` step-groups (fresh-buffer assembly).

  Compatibility wrapper over :class:`_SuperbatchAssembler` in its
  fresh-allocation mode — the historical grouping semantics, minus the
  intermediate ``np.stack`` list-of-views copy.
  """
  return _SuperbatchAssembler(it, k, start_step, max_steps, release=release)


def _layout_api():
  """Adapters across jax's Layout→Format API rename.

  Returns ``(make_auto, compiled_input_formats, leaf_format)``:
  jax >= 0.5 spells compiler-chosen layouts ``Format(Layout.AUTO, s)``
  with ``compiled.input_formats`` / ``array.format``; jax 0.4.x spells
  them ``Layout(DeviceLocalLayout.AUTO, s)`` with
  ``compiled.input_layouts`` / ``array.layout``. Everything downstream
  (device_put placement, equality checks) is API-compatible.
  """
  try:
    from jax.experimental.layout import Format, Layout

    return (lambda s: Format(Layout.AUTO, s),
            lambda c: c.input_formats,
            lambda a: getattr(a, 'format', None))
  except ImportError:
    from jax.experimental.layout import DeviceLocalLayout, Layout

    return (lambda s: Layout(DeviceLocalLayout.AUTO, s),
            lambda c: c.input_layouts,
            lambda a: getattr(a, 'layout', None))


def _mean_metrics(metric_batches: List[MetricDict]) -> MetricDict:
  if not metric_batches:
    return {}
  keys = metric_batches[0].keys()
  return {
      k: float(np.mean([float(m[k]) for m in metric_batches])) for k in keys
  }


class _DispatchBreakdown:
  """Per-dispatch wall-time decomposition for the train loop.

  A *boundary* is the instant right after a dispatch's one-behind
  device block. ``wall(i) = boundary(i) - boundary(i-1)`` then
  decomposes EXACTLY (no untracked residue — every interval between
  the five timestamps is attributed):

    callback_ms   boundary(i-1) → start of wait: callbacks, logging,
                  checkpoint saves, interleaved eval — everything the
                  host does between dispatches besides feeding.
    host_wait_ms  blocked in ``next(batches)`` (minus consumer-thread
                  placement, carved out below) — input-bound time.
    placement_ms  ``shard_batch`` H2D placement on the LOOP thread
                  (worker-thread placement overlaps the device step and
                  is recorded separately as placement_overlapped_ms).
    dispatch_ms   the async ``step_fn`` enqueue call.
    device_step_ms  blocked on the PREVIOUS dispatch's outputs after
                  enqueueing this one: the device compute not hidden by
                  host work. Compute-bound runs see the true step time
                  here; input-bound runs see ~0 — which is the answer.

  The first dispatch is excluded from windows (it pays jit compile).
  ``window_scalars`` drains the accumulation into the scalar dict the
  existing logging callbacks already publish.
  """

  _WINDOW_KEYS = ('callback', 'wait', 'place', 'dispatch', 'device')

  def __init__(self, enabled: bool):
    self.enabled = enabled
    # Written by place() when it runs on the loop thread; drained by
    # record(). A plain list cell: single producer+consumer (the loop).
    self.place_ms = [0.0]
    self._boundary: Optional[float] = None
    self._dispatches = metrics_lib.counter('trainer/dispatches')
    self._steps = metrics_lib.counter('trainer/steps')
    self._examples = metrics_lib.counter('trainer/examples')
    self._wall_hist = metrics_lib.histogram('trainer/step_wall_ms')
    self._place_hist = metrics_lib.histogram('trainer/placement_ms')
    self._callback_hist = metrics_lib.histogram('trainer/callback_ms')
    # Closed log windows: the input engine's mid-run re-autotune keys off
    # this counter (one re-evaluation per window, data/engine.py).
    self._windows = metrics_lib.counter('trainer/breakdown_windows')
    self._skipped_counter = metrics_lib.counter(
        'resilience/nonfinite_skipped_steps')
    self._reset_window()

  def _reset_window(self) -> None:
    self._win = {k: 0.0 for k in self._WINDOW_KEYS}
    self._win_wall = 0.0
    self._win_dispatches = 0
    self._win_steps = 0
    self._win_examples = 0
    self._win_skipped0 = self._skipped_counter.value

  def record(self, t_wait0: float, t_wait1: float, t_disp: float,
             t_boundary: float, steps: int, examples: int) -> None:
    """Closes one dispatch given its four loop timestamps: start-of-wait,
    batch-in-hand, dispatch-enqueued, after-device-block."""
    self._dispatches.inc()
    self._steps.inc(steps)
    self._examples.inc(examples)
    place_ms, self.place_ms[0] = self.place_ms[0], 0.0
    prev_boundary, self._boundary = self._boundary, t_boundary
    if not self.enabled:
      return  # counters only: without the device block the timestamps
              # measure dispatch enqueues, not where the time went
    self._place_hist.observe(place_ms)
    if prev_boundary is None:
      return  # first dispatch: jit compile dominates; not a steady-state sample
    callback_ms = (t_wait0 - prev_boundary) * 1e3
    wall_ms = (t_boundary - prev_boundary) * 1e3
    self._callback_hist.observe(callback_ms)
    self._wall_hist.observe(wall_ms)
    self._win['callback'] += callback_ms
    self._win['wait'] += max(0.0, (t_wait1 - t_wait0) * 1e3 - place_ms)
    self._win['place'] += place_ms
    self._win['dispatch'] += (t_disp - t_wait1) * 1e3
    self._win['device'] += (t_boundary - t_disp) * 1e3
    self._win_wall += wall_ms
    self._win_dispatches += 1
    self._win_steps += steps
    self._win_examples += examples

  def window_scalars(self, utilization_fn=None) -> MetricDict:
    """Drains the current log window into publishable scalars.

    ``goodput_examples_per_sec`` discounts examples whose updates the
    non-finite guard skipped on device — throughput that moved bytes
    but trained nothing. ``utilization_fn(n_steps, device_seconds)``
    (the program ledger's MFU/HBM derivation) is handed the window's
    STEP count — not dispatches; the ledger normalizes the K-step
    executable per step — and device time before the drain, and its
    scalars ride the same merge; it publishes its own gauges, so it
    runs after the ``trainer/``-prefixed gauge loop.
    """
    if not self.enabled or self._win_dispatches == 0:
      return {}
    n = self._win_dispatches
    wall_ms = self._win_wall
    wall_s = wall_ms / 1e3
    skipped = self._skipped_counter.value - self._win_skipped0
    eps = self._win_examples / wall_s if wall_s > 0 else 0.0
    out = {
        'examples_per_sec': eps,
        'input_bound_fraction':
            (self._win['wait'] + self._win['place']) / wall_ms
            if wall_ms > 0 else 0.0,
        'goodput_examples_per_sec':
            eps * max(0.0, 1.0 - skipped / max(1, self._win_steps)),
        'breakdown/wall_ms': wall_ms / n,
        'breakdown/host_wait_ms': self._win['wait'] / n,
        'breakdown/placement_ms': self._win['place'] / n,
        'breakdown/dispatch_ms': self._win['dispatch'] / n,
        'breakdown/device_step_ms': self._win['device'] / n,
        'breakdown/callback_ms': self._win['callback'] / n,
    }
    for key, value in out.items():
      metrics_lib.gauge(f'trainer/{key}').set(value)
    if utilization_fn is not None:
      try:
        out.update(
            utilization_fn(self._win_steps, self._win['device'] / 1e3) or {})
      except Exception:  # pylint: disable=broad-except
        pass  # telemetry derivation must never stall a log crossing
    self._windows.inc()
    # Postmortem retention: the last K closed windows ride every
    # incident bundle (bounded ring in observability/postmortem.py).
    postmortem_lib.note_breakdown_window(out)
    self._reset_window()
    return out


def _resilience_scalars(start_snapshot, policy) -> MetricDict:
  """Train-scalar view of the resilience registry counters.

  Deltas against the run-start snapshot (the registry is process-global;
  a second trainer in the same process must not inherit the first one's
  counts). Zero-valued entries are elided except the two non-finite
  counters, which stay in the schema whenever the guard is on so their
  TensorBoard series exist from step one.
  """
  always = ()
  if policy is not None:
    always = ('resilience/nonfinite_skipped_steps',
              'resilience/consecutive_bad_dispatches')
  out: MetricDict = {}
  for name, value in metrics_lib.delta(start_snapshot, 'resilience/').items():
    if isinstance(value, dict):  # histogram: not a publishable scalar
      continue
    if value or name in always:
      out[name] = float(value)
  return out


class Trainer:
  """Owns the jitted step functions, state, and checkpoint manager."""

  def __init__(self,
               model,
               config: TrainerConfig,
               mesh: Optional[jax.sharding.Mesh] = None,
               callbacks: Sequence[TrainerCallback] = (),
               shutdown: Optional[resilience.GracefulShutdown] = None):
    self._model = model
    self._config = config
    if config.matmul_precision is not None:
      # Before any module build: modules bake the precision in at
      # construction (the Dense/Conv injection classes).
      if hasattr(model, 'set_matmul_precision'):
        model.set_matmul_precision(config.matmul_precision)
      else:
        from tensor2robot_tpu.quantize import fp8_training as fp8_lib

        fp8_lib.require_fp8_support(config.matmul_precision)
    self._nonfinite_policy = (
        resilience.NonFinitePolicy(config.nonfinite_mode,
                                   config.nonfinite_halt_after)
        if config.nonfinite_mode != 'off' else None)
    if shutdown is None and config.handle_preemption:
      shutdown = resilience.install_graceful_shutdown()
    self._shutdown = shutdown
    self._mesh = mesh if mesh is not None else mesh_lib.single_device_mesh()
    if hasattr(model, 'set_mesh'):
      # Mesh-aware models (e.g. sequence-parallel attention layouts) get
      # the mesh the jitted step will run over before any module build.
      model.set_mesh(self._mesh)
    self._callbacks = list(callbacks)
    self._preprocessor = model.preprocessor
    self._optimizer = model.create_optimizer()
    self._loop_k = max(1, int(config.steps_per_dispatch))
    self._accum_m = max(1, int(config.grad_accum_microbatches))
    # Device-resident feeding (one device_put + one dispatch per K
    # steps). Off when the mesh spans processes: multi-host placement
    # assembles per-process shards leaf by leaf, which has no
    # single-put form. Batch-argument donation rides only accelerator
    # backends — on XLA-CPU device_put may zero-copy alias host numpy,
    # and donating an aliased buffer would let XLA scribble on the host
    # batch (it also keeps the CPU executable identical to the
    # default-feed one, the bitwise on/off equivalence tests pin).
    self._feed_enabled = (bool(config.device_feed) and
                          not mesh_lib.mesh_spans_processes(self._mesh))
    self._feed_donate_batch = (self._feed_enabled and
                               jax.default_backend() != 'cpu')
    self._state: Optional[TrainState] = None
    self._train_step_fn = None
    self._eval_step_fn = None
    # Auto (compiler-chosen) input-layout executable; built lazily from
    # the first host batch's avals (see _maybe_build_auto_step).
    self._auto_step = None  # GUARDED_BY(self._auto_build_lock)
    self._batch_formats = None  # GUARDED_BY(self._auto_build_lock)
    self._auto_batch_avals = None  # GUARDED_BY(self._auto_build_lock)
    self._auto_disabled = not config.resolved_auto_input_layouts()  # GUARDED_BY(self._auto_build_lock)
    self._auto_build_lock = threading.Lock()
    # Whether 'train/step' landed in the program ledger (set by the
    # auto-step build or the off-thread jitted-step harvest, whichever
    # compiles the dispatched program). Plain bool, single-writer-ish:
    # a racing reader at worst harvests a duplicate record of the SAME
    # program, which the ledger de-duplicates by fingerprint.
    self._program_recorded = False
    # Step the current dispatch started from; callbacks use crossed() so
    # their interval semantics survive steps_per_dispatch > 1.
    self._dispatch_start_step = 0
    # Distributed control plane (multi-process runs only): coordinated
    # preemption, the multi-host checkpoint commit protocol, heartbeats.
    self._dist_ctx: Optional[dist_lib.DistributedContext] = None
    if config.resolved_distributed_coordination():
      self._dist_ctx = dist_lib.DistributedContext.create()
    self._heartbeat: Optional[dist_lib.HeartbeatService] = None
    topology = None
    if config.checkpoint_topology_check:
      topology = mesh_lib.describe_topology(
          self._mesh,
          grad_accum_microbatches=self._accum_m,
          steps_per_dispatch=self._loop_k)
    self._manager: Optional[ckpt_lib.CheckpointManager] = None
    if config.model_dir:
      sharding_rules = ()
      if hasattr(model, 'param_sharding_rules'):
        sharding_rules = tuple(
            model.param_sharding_rules(self._mesh) or ())
      self._manager = ckpt_lib.CheckpointManager(
          os.path.join(config.model_dir, 'checkpoints'),
          max_to_keep=config.max_checkpoints_to_keep,
          keep_period=config.keep_checkpoint_period,
          save_interval_steps=config.save_interval_steps,
          async_save=config.async_checkpoints,
          topology=topology,
          distributed=self._dist_ctx,
          barrier_timeout_secs=config.checkpoint_barrier_timeout_secs,
          sharded=config.resolved_sharded_payloads(self._mesh),
          async_commit=config.checkpoint_async_commit,
          reshape=config.checkpoint_reshape,
          mesh=self._mesh,
          sharding_rules=sharding_rules)
    # Opt-in live metrics endpoint (config port or T2R_METRICSZ_PORT
    # env); process-global and idempotent, so a second Trainer in the
    # same process reuses the running server.
    from tensor2robot_tpu.observability import metricsz, timeseries

    metricsz.maybe_start(config.metricsz_port)
    # Metrics history ring: feeds /metricsz?history=1 and the postmortem
    # bundle's time-series window (idempotent, first cadence wins).
    timeseries.maybe_start(config.timeseries_interval_secs or None)
    # Before the first lowering: the restart-goodput slice — executables
    # compiled by a previous incarnation load from disk instead of
    # recompiling (measured by restart_to_first_step_seconds below).
    from tensor2robot_tpu.utils.compilation_cache import (
        install_compile_counters, maybe_enable_compilation_cache)

    # Cache-hit/miss + backend-compile-seconds counters ride jax's
    # monitoring events whether or not the persistent cache is on: the
    # restart-goodput gauge gets its cause line either way.
    install_compile_counters()
    maybe_enable_compilation_cache(config.compilation_cache_dir)

  # ------------------------------------------------------------- properties

  @property
  def model(self):
    return self._model

  @property
  def config(self) -> TrainerConfig:
    return self._config

  @property
  def mesh(self) -> jax.sharding.Mesh:
    return self._mesh

  @property
  def state(self) -> Optional[TrainState]:
    return self._state

  @property
  def step(self) -> int:
    return 0 if self._state is None else int(self._state.step)

  @property
  def checkpoint_manager(self) -> Optional[ckpt_lib.CheckpointManager]:
    return self._manager

  @property
  def dispatch_start_step(self) -> int:
    """The step the dispatch that just reported began from (callbacks)."""
    return self._dispatch_start_step

  @property
  def nonfinite_policy(self) -> Optional['resilience.NonFinitePolicy']:
    """Host-side non-finite accounting (None when the guard is off)."""
    return self._nonfinite_policy

  @property
  def distributed_context(self) -> Optional['dist_lib.DistributedContext']:
    """The multi-process control plane (None in single-process runs)."""
    return self._dist_ctx

  @property
  def is_primary_process(self) -> bool:
    """Whether this process owns job-wide side effects (exports,
    checkpoint payloads, aggregation). True in single-process runs."""
    return self._dist_ctx is None or self._dist_ctx.is_primary

  def crossed(self, interval: int, step: int) -> bool:
    """Whether the dispatch that just reported ``step`` crossed a multiple
    of ``interval`` — the interval test callbacks must use instead of
    ``step % interval == 0``, which boundary steps (multiples of
    ``steps_per_dispatch``) rarely satisfy."""
    return crossed_interval(interval, self._dispatch_start_step, step)

  # ------------------------------------------------------------ step builds

  def _train_step_body(self):
    model = self._model
    preprocessor = self._preprocessor
    optimizer = self._optimizer
    decay = model.avg_model_params_decay
    guard_nonfinite = self._config.nonfinite_mode != 'off'
    accum_m = self._accum_m
    # Fused optimizer/EMA/guard update (ops/fused_update.py): decided
    # at BUILD time — the kernel gate and the optimizer tag are python
    # facts, so the branch bakes into the traced program. None keeps
    # the stock optax path bit for bit.
    fused_plan = None
    fused_lib = None
    if self._config.fused_update:
      from tensor2robot_tpu.ops import fused_update as fused_lib

      # plan_for logs the fallback reason itself when it returns None
      # (kernel gate off, untagged optimizer, unrecognized opt state).
      fused_plan = fused_lib.plan_for(
          optimizer, ema_decay=decay,
          opt_state=None if self._state is None else self._state.opt_state)

    def all_finite(loss, grads):
      # Device-side guard flag: ok == all_finite(loss, grads). With
      # grad_accum_microbatches > 1, `grads` is the ACCUMULATED
      # (microbatch-mean) tree — one bad microbatch poisons the whole
      # effective batch's update, which is the correct granularity: the
      # optimizer only ever sees the accumulated gradient.
      checks = [jnp.all(jnp.isfinite(loss))]
      for g in jax.tree_util.tree_leaves(grads):
        if jnp.issubdtype(jnp.asarray(g).dtype, jnp.inexact):
          checks.append(jnp.all(jnp.isfinite(g)))
      return jnp.stack(checks).all()

    def train_step(state: TrainState, features, labels):
      step_rng = jax.random.fold_in(state.rng, state.step)
      pre_rng, net_rng = jax.random.split(step_rng)
      # Preprocessing covers the FULL host batch in one call — with
      # microbatching this keeps every rng draw (crop offsets,
      # photometric distortions) identical to the unsliced step; only
      # the network forward/backward is sliced.
      features_p, labels_p = preprocessor.preprocess(
          features, labels, ModeKeys.TRAIN, pre_rng)

      def loss_fn(params, model_state, f, l):
        variables = dict(model_state)
        variables['params'] = params
        outputs, new_variables = model.inference_network_fn(
            variables, f, l, ModeKeys.TRAIN, net_rng)
        loss, scalars = model.model_train_fn(f, l, outputs, ModeKeys.TRAIN)
        new_model_state = {
            k: v for k, v in dict(new_variables).items() if k != 'params'
        }
        return loss, (scalars, new_model_state)

      grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
      if accum_m == 1:
        (loss, (scalars, new_model_state)), grads = grad_fn(
            state.params, state.model_state, features_p, labels_p)
      else:
        # Microbatch accumulation: scan over [M, B/M, ...] slices with
        # f32 accumulators in the (donated) carry; ONE update per
        # effective batch. model_state threads through the scan, so
        # BatchNorm running averages advance per microbatch (their
        # values never feed the TRAIN-mode forward, so loss/grads are
        # unaffected by the threading order).
        micro_f = mesh_lib.microbatch_split(features_p, accum_m)
        micro_l = (None if labels_p is None else
                   mesh_lib.microbatch_split(labels_p, accum_m))

        def micro_body(carry, mb):
          model_state, grad_acc, loss_acc = carry
          f, l = mb
          (mb_loss, (mb_scalars, new_ms)), mb_grads = grad_fn(
              state.params, model_state, f, l)
          carry = (new_ms, accumulate_grads(grad_acc, mb_grads),
                   loss_acc + mb_loss.astype(jnp.float32))
          return carry, mb_scalars

        (new_model_state, grad_acc, loss_acc), scalars_m = jax.lax.scan(
            micro_body,
            (state.model_state, init_grad_accumulators(state.params),
             jnp.zeros((), jnp.float32)),
            (micro_f, micro_l))
        grads = finalize_accumulated_grads(grad_acc, state.params, accum_m)
        loss = loss_acc / accum_m
        # Mean-reduced scalars: the microbatch mean IS the full-batch
        # value; reduced in f32 for the same reason the accumulators are.
        scalars = jax.tree_util.tree_map(
            lambda s: jnp.mean(jnp.asarray(s).astype(jnp.float32), axis=0),
            scalars_m)
      if fused_plan is not None:
        # One elementwise Pallas pass over every parameter leaf runs
        # moments + update + apply + EMA + the guard's old-vs-new
        # select; opt-state counts select outside (scalars). The
        # remaining replaced leaves (step, model_state) select below;
        # rng is kept by reference, exactly like the stock path.
        ok = all_finite(loss, grads) if guard_nonfinite else None
        new_params, new_opt_state, new_ema = fused_lib.apply_update(
            fused_plan, state.params, grads, state.opt_state,
            state.ema_params, ok=ok)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            model_state=new_model_state,
            opt_state=new_opt_state,
            ema_params=new_ema)
        scalars = dict(scalars)
        scalars['loss'] = loss
        if guard_nonfinite:
          new_state = new_state.replace(
              step=jnp.where(ok, new_state.step, state.step),
              model_state=jax.tree_util.tree_map(
                  lambda n, o: jnp.where(ok, n, o),
                  new_model_state, state.model_state))
          scalars['nonfinite_count'] = jnp.where(ok, 0, 1).astype(jnp.int32)
        return new_state, scalars
      updates, new_opt_state = optimizer.update(
          grads, state.opt_state, state.params)
      new_params = optax.apply_updates(state.params, updates)
      new_state = state.replace(
          step=state.step + 1,
          params=new_params,
          model_state=new_model_state,
          opt_state=new_opt_state,
          ema_params=apply_ema(state, new_params, decay))
      scalars = dict(scalars)
      scalars['loss'] = loss
      if guard_nonfinite:
        # The ENTIRE
        # state transition is selected through where(ok, new, old), so a
        # non-finite batch leaves params/opt-state/EMA/step untouched —
        # no host sync, no extra dispatch; the host policy reads the
        # count from the scalars one dispatch behind. Leaves the replace
        # kept by reference (rng) skip the select via identity.
        ok = all_finite(loss, grads)
        new_state = jax.tree_util.tree_map(
            lambda n, o: n if n is o else jnp.where(ok, n, o),
            new_state, state)
        scalars['nonfinite_count'] = jnp.where(ok, 0, 1).astype(jnp.int32)
      return new_state, scalars

    return train_step

  def _multi_step_body(self):
    """K optimizer steps per XLA program over ``[K, batch, ...]`` groups.

    A ``lax.scan`` of the single-step body: same math and the same rng
    stream as K separate dispatches (the per-step ``fold_in`` keys off
    ``state.step``, which the scan carry advances). Returns the LAST
    step's scalars — the value per-step logging would have reported at
    the dispatch boundary.
    """
    step = self._train_step_body()

    def multi_step(state: TrainState, features_k, labels_k):
      def body(carry, batch):
        return step(carry, batch[0], batch[1])

      state, scalars_k = jax.lax.scan(body, state, (features_k, labels_k))
      out = jax.tree_util.tree_map(lambda x: x[-1], scalars_k)
      if 'nonfinite_count' in out:
        # The guard flag aggregates over the WHOLE group (a bad step in
        # the middle must not be masked by a clean last step).
        out['nonfinite_count'] = jnp.sum(scalars_k['nonfinite_count'])
      return state, out

    return multi_step

  def _loop_step_body(self):
    """The body the train loop dispatches (single- or K-step)."""
    return (self._multi_step_body() if self._loop_k > 1
            else self._train_step_body())

  def _loop_batch_sharding(self):
    return (mesh_lib.stacked_batch_sharding(self._mesh)
            if self._loop_k > 1 else mesh_lib.batch_sharding(self._mesh))

  def _donate_argnums(self) -> Tuple[int, ...]:
    """(state,) — plus the batch args under accelerator device feed,
    where the superbatch's device buffers become the step's scratch (the
    donated input ring; the host copy already lives in the assembler)."""
    return (0, 1, 2) if self._feed_donate_batch else (0,)

  def _build_train_step(self):
    state_sharding = self._state_sharding()
    batch_sharding = self._loop_batch_sharding()
    return jax.jit(
        self._loop_step_body(),
        in_shardings=(state_sharding, batch_sharding, batch_sharding),
        out_shardings=(state_sharding, None),
        donate_argnums=self._donate_argnums())

  def _capture_program_avals(self, cell, features, labels) -> None:
    """Fills ``cell`` with (avals, donated_leaves) for the harvest.

    Shape/dtype/sharding only — no batch buffers are retained. A
    ~tree-size-microseconds cost paid once, at the first dispatch (the
    expensive part of harvesting, the AOT compile, runs elsewhere).
    """
    try:
      def to_aval(x):
        return jax.ShapeDtypeStruct(
            np.shape(x), getattr(x, 'dtype', None) or np.result_type(x),
            sharding=getattr(x, 'sharding', None))

      avals = jax.tree_util.tree_map(
          to_aval, (self._state, features, labels))
      cell.append((avals, len(jax.tree_util.tree_leaves(self._state))))
    except Exception:  # pylint: disable=broad-except
      pass

  def _program_harvest_fn(self, cell, loop_live_fn=None):
    """The deferred ledger record of the jitted step ('train/step').

    jax's on-call executable cache is not readable from the outside, so
    harvesting cost/memory analysis for the dispatched program means
    one AOT ``lower().compile()`` of the same program — a real second
    backend compile (a disk read when the persistent compilation cache
    is on). Its tracing half holds the GIL and would contend with the
    dispatch loop, so the loop runs this DEFERRED (a Timer created at
    loop setup, outside any measured dispatch) by
    ``program_harvest_delay_seconds``, or on an immediate daemon thread
    at delay 0. Bails when the loop already ended (``loop_live_fn``),
    when another path recorded the program (the auto-layout build), or
    when the first dispatch never filled ``cell``.
    """
    step_fn = self._train_step_fn

    def harvest():
      if loop_live_fn is not None and not loop_live_fn():
        return  # the run already ended: no live gauge to feed
      if self._program_recorded or not cell:
        return
      avals, donated_params = cell[0]
      if programs_lib.record_jitted(
          'train/step', step_fn, avals,
          donate_argnums=self._donate_argnums(),
          donated_params=donated_params, source='trainer/jit_step',
          steps_per_execution=self._loop_k):
        self._program_recorded = True

    return harvest

  def _program_utilization(self, n_steps: int,
                           device_seconds: float) -> MetricDict:
    """train/mfu + train/hbm_gbps + train/roofline_fraction for one
    closed log window (empty until 'train/step' is recorded).

    ``n_steps`` counts STEPS, not dispatches: the ledger records the
    K-step executable with ``steps_per_execution=K`` and normalizes its
    FLOPs/bytes per step, so MFU stays honest (and ragged-tail exact)
    when one dispatch trains K steps. Identical to the historical
    dispatch math for K == 1.
    """
    return programs_lib.utilization_scalars(
        'train/step', n_steps, device_seconds, scope='train')

  def _maybe_build_auto_step(self, features, labels) -> bool:
    """Compiles the train step with compiler-chosen (AUTO) batch layouts.

    ``features``/``labels`` are a HOST batch (avals only). On success
    the train loop dispatches ``self._auto_step`` and ``place`` uses
    ``self._batch_formats``; any failure (backend without layout
    support, exotic batch leaves) permanently falls back to the default
    jitted step. Thread-safe: the prefetcher's worker may be the first
    caller.
    """
    # Double-checked fast path: both fields are written exactly once,
    # under the build lock; a racing reader that sees a stale None just
    # falls through to the locked re-check below.
    if self._auto_step is not None:  # ANALYSIS_OK(lock-discipline): published-once ref; locked re-check follows
      return True
    if self._auto_disabled or self._state is None:  # ANALYSIS_OK(lock-discipline): same double-checked fast path
      return False
    with self._auto_build_lock:
      if self._auto_step is not None:
        return True
      if self._auto_disabled:
        return False
      try:
        make_auto, input_formats_of, leaf_format = _layout_api()

        state_sharding = self._state_sharding()
        auto = make_auto(self._loop_batch_sharding())
        jitted = jax.jit(
            self._loop_step_body(),
            in_shardings=(state_sharding, auto, auto),
            out_shardings=(state_sharding, None),
            donate_argnums=self._donate_argnums())
        t_compile0 = time.perf_counter()
        with warnings.catch_warnings(record=True) as caught:
          warnings.simplefilter('always')
          lowered = jitted.lower(self._state, features, labels)
          compiled = lowered.compile()
        compile_seconds = time.perf_counter() - t_compile0
        (state_fmt, feat_fmt, label_fmt), _ = input_formats_of(compiled)
        leaves, treedef = jax.tree_util.tree_flatten((features, labels))
        self._auto_batch_avals = (
            treedef, [(tuple(np.shape(x)), np.result_type(x))
                      for x in leaves])
        # The executable's expected STATE layouts must match how the
        # state is actually placed (state keeps its concrete sharding;
        # only batches are AUTO) — a mismatch would error mid-train, so
        # verify statically and fall back instead.
        placed = [leaf_format(leaf)
                  for leaf in jax.tree_util.tree_leaves(self._state)]
        expected = list(jax.tree_util.tree_leaves(state_fmt))
        if len(placed) != len(expected) or any(
            p is not None and p != e for p, e in zip(placed, expected)):
          raise ValueError('state layout mismatch vs compiled step')
        self._batch_formats = (feat_fmt, label_fmt)
        self._auto_step = compiled
        if self._config.program_ledger:
          # This executable IS the program driving steady-state
          # dispatches, so it owns the 'train/step' ledger entry (the
          # off-thread jitted-step harvest is skipped — see the
          # _program_recorded check in _train_loop).
          self._program_recorded = True
          programs_lib.record_compiled(
              'train/step', compiled, lowered=lowered,
              compile_seconds=compile_seconds,
              donate_argnums=self._donate_argnums(),
              donated_params=len(jax.tree_util.tree_leaves(self._state)),
              captured_warnings=[
                  str(w.message) for w in caught
                  if 'donat' in str(w.message).lower()],
              source='trainer/auto_step',
              steps_per_execution=self._loop_k)
        return True
      except Exception as e:  # pylint: disable=broad-except
        logging.info(
            'Auto input layouts unavailable (%s); using default layouts.',
            e)
        self._auto_disabled = True
        return False

  def _batch_matches_auto(self, batch: Batch) -> bool:
    """Whether a batch has the avals the AOT auto-layout step expects.

    The compiled executable is shape-specialized; an off-shape batch
    (e.g. a ragged final batch from an external iterator) must fall
    back to the jitted step, which retraces transparently.
    """
    # ANALYSIS_OK(lock-discipline): immutable tuple once published under
    # the build lock; a stale None here means "fall back to jitted".
    if self._auto_batch_avals is None:
      return False
    treedef, avals = self._auto_batch_avals  # ANALYSIS_OK(lock-discipline): published-once immutable tuple
    leaves, td = jax.tree_util.tree_flatten(batch)
    return td == treedef and all(
        tuple(np.shape(x)) == shape and np.result_type(x) == dtype
        for x, (shape, dtype) in zip(leaves, avals))

  def _build_eval_step(self):
    model = self._model
    preprocessor = self._preprocessor

    def eval_step(state: TrainState, features, labels):
      features_p, labels_p = preprocessor.preprocess(
          features, labels, ModeKeys.EVAL, None)
      outputs, _ = model.inference_network_fn(
          dict(state.eval_variables), features_p, labels_p, ModeKeys.EVAL)
      return model.model_eval_fn(features_p, labels_p, outputs)

    state_sharding = self._state_sharding()
    batch_sharding = mesh_lib.batch_sharding(self._mesh)
    return jax.jit(
        eval_step,
        in_shardings=(state_sharding, batch_sharding, batch_sharding))

  def _state_sharding(self):
    if self._state is None:
      raise ValueError('State must be initialized before building steps.')
    rules = ()
    if hasattr(self._model, 'param_sharding_rules'):
      rules = tuple(self._model.param_sharding_rules(self._mesh) or ())
    return mesh_lib.state_shardings_for(self._mesh, self._state,
                                        rules=rules)

  # ------------------------------------------------------- state lifecycle

  def initialize(self, features, labels=None) -> TrainState:
    """Creates (or restores) the train state from spec-shaped features."""
    del labels
    rng = jax.random.PRNGKey(self._config.seed)
    pre_rng, init_rng = jax.random.split(rng)
    # Initialize from *preprocessed* features: the device-side contract.
    features_p, _ = self._preprocessor.preprocess(
        features, None, ModeKeys.TRAIN, pre_rng)
    self._state = create_train_state(
        self._model, self._optimizer, init_rng, features_p, ModeKeys.TRAIN)
    if self._manager is not None and self._manager.latest_step() is not None:
      restored = self._manager.restore(self._state)
      if restored is not None:
        self._state = restored
    # Place the state according to mesh rules (replicated or fsdp-sharded).
    sharding = self._state_sharding()
    self._state = jax.tree_util.tree_map(
        lambda x, s: x if x is None else jax.device_put(x, s),
        self._state, sharding, is_leaf=lambda x: x is None)
    self._train_step_fn = self._build_train_step()
    self._eval_step_fn = self._build_eval_step()
    return self._state

  def save_checkpoint(self, force: bool = False,
                      sync: Optional[bool] = None) -> None:
    """Saves the current state; ``sync=True`` (preemption/final saves)
    forces the barriered commit even under checkpoint_async_commit."""
    if self._manager is None or self._state is None:
      return
    if self._manager.save(self.step, self._state, force=force, sync=sync):
      for cb in self._callbacks:
        cb.after_checkpoint(self, self.step)

  # ------------------------------------------------------------------ loops

  def train(self,
            train_iter: Iterator[Batch],
            eval_iter_fn: Optional[Callable[[], Iterator[Batch]]] = None
            ) -> MetricDict:
    """Interleaved train/eval loop (train_and_evaluate semantics).

    Every abnormal exit — preemption (:class:`~tensor2robot_tpu.train.
    resilience.PreemptedError`, 42), a liveness/barrier failure
    (``DeadHostError``, 43), a non-finite raise, or any uncaught
    exception — writes a postmortem bundle into
    ``<model_dir>/postmortem/`` (flight-ring events, metrics report,
    time-series window, breakdown windows, topology) before the error
    propagates; render it with ``tools/postmortem.py``.
    """
    try:
      return self._train_loop(train_iter, eval_iter_fn)
    except BaseException as e:
      self._note_abnormal_exit(e)
      raise

  def _note_abnormal_exit(self, error: BaseException) -> None:
    """Classifies a terminal error and dumps the postmortem bundle.

    Bounded and non-raising (postmortem.dump's contract): runs between
    the terminal error and its propagation to the exit path.
    """
    if isinstance(error, (GeneratorExit, StopIteration)):
      return
    if isinstance(error, resilience.PreemptedError):
      reason = 'preempted'
    elif isinstance(error, resilience.NonFiniteError):
      reason = 'nonfinite'
    elif isinstance(error, dist_lib.DeadHostError):
      reason = 'dead_host'
    elif isinstance(error, KeyboardInterrupt):
      reason = 'keyboard_interrupt'
    else:
      reason = 'trainer_exception'
    flight.event('error', f'trainer/{reason}',
                 f'{type(error).__name__}: {str(error)[:180]}')
    exit_code = getattr(error, 'exit_code', None)
    try:
      topology = mesh_lib.describe_topology(
          self._mesh,
          grad_accum_microbatches=self._accum_m,
          steps_per_dispatch=self._loop_k)
    except Exception:  # pylint: disable=broad-except
      topology = None
    postmortem_lib.dump(self._config.model_dir, reason,
                        exit_code=exit_code, error=error,
                        topology=topology,
                        extra={'step': self.step})

  def _train_loop(self,
                  train_iter: Iterator[Batch],
                  eval_iter_fn: Optional[Callable[[], Iterator[Batch]]] = None
                  ) -> MetricDict:
    config = self._config
    # Ring-buffer lease hook (data/engine.py reuse_buffers): present on
    # engine-backed iterators; None otherwise. Called once per consumed
    # batch at the point its bytes stop being needed (H2D transfer
    # completion, or the np.stack copy in the K>1 grouping path).
    release_fn = getattr(train_iter, 'release', None)
    if self._state is None:
      resuming = (self._manager is not None and
                  self._manager.latest_step() is not None)
      features, labels = next(train_iter)
      self.initialize(features)
      # On resume the pulled batch served only as the shape probe: the
      # restored run must not train on it — an InputStateCallback's
      # begin() rewinds the stream UNDER it, and without one the
      # restarted stream repeats examples anyway, so dropping it is
      # never a loss.
      first_batch: Optional[Batch] = None if resuming else (features, labels)
      if resuming and release_fn is not None:
        # The dropped probe batch still holds its ring lease; block
        # until initialization consumed its values (async dispatches
        # may still be reading the slot buffers) before releasing.
        jax.block_until_ready(self._state)
        release_fn()
    else:
      first_batch = None

    for cb in self._callbacks:
      cb.begin(self)

    scalars: MetricDict = {}
    eval_metrics: MetricDict = {}
    last_log = time.time()
    # Host-side step mirror: reading self.step would force a device sync
    # (int(state.step)) after every dispatch, serializing the pipeline.
    step = self.step
    last_log_step = step
    breakdown = _DispatchBreakdown(config.step_breakdown)
    # Compiled-program plane (observability/programs.py): one ledger
    # harvest after the first dispatch, a cache-size probe per dispatch
    # (the steady-state recompile sentinel), and MFU/HBM gauges derived
    # at log crossings from the breakdown's device time.
    programs_on = config.program_ledger and programs_lib.enabled()
    program_harvest_pending = programs_on
    program_harvest_timer = None
    program_aval_cell: list = []  # filled at the first dispatch
    program_loop_live = [True]  # flipped by teardown; read at timer fire
    program_harvest_delay = max(
        0.0, float(config.program_harvest_delay_seconds))
    if programs_on and program_harvest_delay > 0:
      # Created HERE, at loop setup: Timer/thread creation costs ~1 ms,
      # which inside the loop would land in one measured dispatch wall
      # (visible on the zero-overhead pin for short runs).
      program_harvest_timer = threading.Timer(
          program_harvest_delay,
          self._program_harvest_fn(
              program_aval_cell, loop_live_fn=lambda: program_loop_live[0]))
      program_harvest_timer.daemon = True
      program_harvest_timer.start()
    recompile_probe = (
        programs_lib.dispatch_probe(self._train_step_fn, 'train/step')
        if programs_on else None)
    # Resilience counters are published as deltas against this run's
    # starting point (the registry is process-global).
    resilience_snap = metrics_lib.snapshot('resilience/')
    loop_ident = threading.get_ident()
    overlap_place_hist = metrics_lib.histogram(
        'trainer/placement_overlapped_ms')
    device_feed = self._feed_enabled
    feed_sharding = self._loop_batch_sharding() if device_feed else None
    # One increment per device-feed placement call: with the dispatch
    # counter, the registry pins "exactly ONE device_put and ONE
    # dispatch per K steps" (tests/test_device_feed.py; bench.py's
    # h2d_dispatches_per_step line).
    h2d_puts = metrics_lib.counter('trainer/h2d/device_puts')

    def place(batch: Batch):
      # First placement builds the auto-layout executable from this
      # batch's avals, so every batch (including this one) lands in the
      # layout the step prefers — no re-layout copy inside the step.
      # Off-shape batches (ragged tails) place default and the loop
      # dispatches the jitted step for them. The auto decision travels
      # WITH the placed batch: dispatching a default-layout batch into
      # the layout-specialized executable would be a runtime error, so
      # the choice is made exactly once, here.
      t0 = time.perf_counter()
      use_auto = (self._maybe_build_auto_step(batch[0], batch[1]) and
                  self._batch_matches_auto(batch))
      # ANALYSIS_OK(lock-discipline): use_auto=True implies the build
      # lock published _batch_formats before _maybe_build_auto_step
      # returned (happens-before via the lock release).
      formats = self._batch_formats if use_auto else None
      if device_feed:
        # Device feed: the whole (features, labels) group moves in ONE
        # device_put call — one H2D burst per dispatch — instead of
        # shard_batch's per-leaf puts. The target is the executable's
        # preferred format tree when the auto build landed, else the
        # loop sharding replicated over the batch's structure.
        target = (formats if formats is not None else
                  jax.tree_util.tree_map(lambda _: feed_sharding, batch))
        placed = jax.device_put(batch, target)
        h2d_puts.inc()
      else:
        placed = mesh_lib.shard_batch(
            batch, self._mesh, formats, stacked=self._loop_k > 1)
      place_ms = (time.perf_counter() - t0) * 1e3
      if threading.get_ident() == loop_ident:
        # Critical-path placement: carved out of host_wait in the
        # breakdown (the no-prefetch path and the CPU consumer-place
        # path both run here, inside the loop's next(batches)).
        breakdown.place_ms[0] += place_ms
      else:
        # Prefetch-worker placement overlaps the device step: real H2D
        # cost, but not on the dispatch critical path.
        overlap_place_hist.observe(place_ms)
      return placed, use_auto

    if first_batch is not None:
      train_iter = itertools.chain([first_batch], train_iter)
    host_iter: Iterator[Batch] = train_iter
    place_release = release_fn
    if self._loop_k > 1:
      # Group assembly copies batches out of their SOURCE ring slots
      # into the superbatch buffers, so source leases are released
      # there; downstream stages see only the assembled buffers. Under
      # accelerator device feed the superbatch buffers are themselves a
      # two-slot ring: the assembler leases a slot per group and the
      # placement stage frees it once the H2D burst completes
      # (``_place_releasing`` blocks on the placed arrays, then calls
      # ``assembler.release``) — the host half of the double-buffered
      # donated input ring. On CPU ``device_put`` aliases host memory
      # (zero copy), so reusing buffers would corrupt in-flight
      # batches: keep fresh allocations there.
      feed_reuse = device_feed and jax.default_backend() != 'cpu'
      assembler = _SuperbatchAssembler(
          train_iter, self._loop_k, step, config.max_train_steps,
          release=release_fn, reuse=feed_reuse)
      host_iter = assembler
      place_release = assembler.release if feed_reuse else None

    prefetcher: Optional[_DevicePrefetcher] = None
    prefetch_depth = config.resolved_prefetch_batches()
    if device_feed and prefetch_depth > 0:
      # Double-buffered device input ring: keep at least two placed
      # superbatches in flight so the H2D burst for group N+1 overlaps
      # the scanned compute of group N.
      prefetch_depth = max(2, prefetch_depth)
    if prefetch_depth > 0:
      prefetcher = _DevicePrefetcher(host_iter, place, prefetch_depth,
                                     release=place_release)
      batches: Iterator[PlacedBatch] = iter(prefetcher)
    elif place_release is not None:
      batches = (_place_releasing(place, place_release, b)
                 for b in host_iter)
    else:
      batches = (place(b) for b in host_iter)
    # Previous dispatch's device-side non-finite count, evaluated one
    # dispatch behind so policy enforcement adds no sync (the update was
    # already guarded on device; the lagged dispatch ran on clean state).
    pending_nonfinite: Optional[Tuple[Any, int]] = None
    # The previous dispatch's device outputs: the one-behind readiness
    # probe the breakdown blocks on AFTER enqueueing the next dispatch.
    prev_out: Optional[MetricDict] = None
    shutdown = (self._shutdown if self._shutdown is not None
                else resilience.active_shutdown())
    # Multi-process control plane: coordinated preemption agreement and
    # the per-host heartbeat/liveness monitor (model_dir is the shared
    # medium — without one, liveness degrades to barrier timeouts only).
    coordinated: Optional[dist_lib.CoordinatedShutdown] = None
    if self._dist_ctx is not None:
      if config.model_dir:
        self._heartbeat = dist_lib.HeartbeatService(
            os.path.join(config.model_dir,
                         dist_lib.HEARTBEAT_DIRNAME),
            process_index=self._dist_ctx.process_index,
            process_count=self._dist_ctx.process_count,
            interval_secs=config.heartbeat_interval_secs,
            straggler_after_secs=config.heartbeat_straggler_secs,
            dead_after_secs=config.liveness_timeout_secs,
            action=config.liveness_action)
        self._heartbeat.set_step(step)
        self._heartbeat.start()
      # Goodbye heartbeats let the negotiation retry against surviving
      # hosts when a peer completed and exited before a late proposal.
      coordinated = dist_lib.CoordinatedShutdown(
          self._dist_ctx, shutdown,
          peer_heartbeats=(self._heartbeat.read_peers
                           if self._heartbeat is not None else None))
    # The step ALL processes agreed to stop at (or this process's own
    # boundary for a single-process shutdown). The loop keeps training
    # until it reaches it, so every host's forced checkpoint lands on
    # one common step.
    stop_step: Optional[int] = None
    try:
      while step < config.max_train_steps:
        if stop_step is None:
          if coordinated is not None:
            # One boundary's coordination round: propagates any host's
            # local SIGTERM to every process and agrees on the common
            # stop step (max of all published boundaries).
            stop_step = coordinated.poll(step)
            if (stop_step is not None and self._manager is not None and
                coordinated.participants is not None):
              # Hosts that completed and said goodbye before the
              # proposal are excluded from the remaining commits.
              self._manager.set_participants(coordinated.participants)
          elif shutdown is not None and shutdown.requested:
            stop_step = step
            # First boundary that OBSERVES the flag: safe (non-signal)
            # context for the flight record the handler could not take.
            signum = getattr(shutdown, '_signal_observed', None)
            flight.event(
                'shutdown', 'resilience/shutdown_observed',
                f'step={step} ' + (f'signum={signum}' if signum is not None
                                   else 'source=programmatic'))
        if stop_step is not None and step >= stop_step:
          # Preemption: the in-flight dispatch finished (we are at a
          # boundary); force a checkpoint + input-state save and exit
          # with the distinct resumable status. In a multi-process run
          # every host takes this branch at the SAME step and the save
          # below runs the atomic commit protocol.
          logging.warning(
              'Graceful shutdown requested; checkpointing step %d and '
              'raising PreemptedError (resumable).', self.step)
          self.save_checkpoint(force=True, sync=True)
          if self._manager is not None:
            self._manager.wait_until_finished()
          if getattr(self, 'is_primary_process', True):
            # Start mark of the whole-loop restart number: the restarted
            # process's first post-restore dispatch consumes it into
            # trainer/sigterm_to_resumed_step_seconds.
            _write_preempt_state(config.model_dir, shutdown, step)
          for cb in self._callbacks:
            cb.end(self)
          raise resilience.PreemptedError(self.step)
        t_wait0 = time.perf_counter()
        with tracing.span('trainer/wait_batch'):
          (features, labels), use_auto = next(batches)
        t_wait1 = time.perf_counter()
        # ANALYSIS_OK(lock-discipline): published-once executable; the
        # use_auto flag travelled with the batch from under the lock.
        step_fn = (self._auto_step if use_auto and self._auto_step is not None
                   else self._train_step_fn)
        with tracing.span('trainer/dispatch'):
          self._state, scalars = step_fn(self._state, features, labels)
        t_disp = time.perf_counter()
        if breakdown.enabled and prev_out is not None:
          # One dispatch behind: the current dispatch is already on
          # device, so this block never drains the pipeline — it
          # measures the device compute not hidden by host work.
          with tracing.span('trainer/device_wait'):
            jax.block_until_ready(prev_out)
        prev_out = scalars
        t_boundary = time.perf_counter()
        if not _restart_recorded:
          # Restart-goodput mark: the first dispatch's outputs becoming
          # ready means compile + restore + warmup are all paid. The
          # one-off block adds no steady-state sync (first dispatch is
          # excluded from the breakdown as compile anyway).
          jax.block_until_ready(scalars)
          _record_restart_to_first_step()
          _record_sigterm_to_resumed(config.model_dir, step)
        before = step
        self._dispatch_start_step = before
        batch_leaves = jax.tree_util.tree_leaves(features)
        if self._loop_k > 1:
          # Group size travels as the leading (scan) dim; the final
          # group may be short (max_train_steps or an exhausted input).
          step += batch_leaves[0].shape[0]
        else:
          step += 1
        breakdown.record(
            t_wait0, t_wait1, t_disp, t_boundary, steps=step - before,
            examples=int(np.prod(batch_leaves[0].shape[:2]))
            if self._loop_k > 1 and batch_leaves
            else (batch_leaves[0].shape[0] if batch_leaves else 0))
        if program_harvest_pending:
          # First dispatch done: the program (and its avals) are final.
          # If the auto-layout build already recorded 'train/step', the
          # AOT harvest of the jitted twin would be a duplicate.
          program_harvest_pending = False
          if not self._program_recorded:
            self._capture_program_avals(
                program_aval_cell, features, labels)
            if program_harvest_delay <= 0:
              threading.Thread(
                  target=self._program_harvest_fn(program_aval_cell),
                  name='t2r-program-ledger', daemon=True).start()
        if recompile_probe is not None:
          # One C++ cache-size read + int compare per dispatch: growth
          # after warmup means steady state just paid a trace+compile.
          recompile_probe()
        if flight.enabled():
          # One flight event per dispatch boundary: the incident ring's
          # backbone timeline (~1 µs; the ring is bounded, so even
          # sub-ms steps only shorten the window it covers).
          flight.event(
              'dispatch', 'trainer/boundary',
              f'step={step} wall_ms={(t_boundary - t_wait0) * 1e3:.3f}')
        if self._heartbeat is not None:
          # Liveness payload: peers (and post-mortem tooling) see the
          # last COMPLETED dispatch boundary, not a wall-clock guess.
          self._heartbeat.set_step(step)
        if self._manager is not None and self._dist_ctx is not None:
          # Async-commit progress (checkpoint_async_commit): the commit
          # primary publishes the marker for an in-flight save once every
          # participant's payload is durable — no barrier on the loop.
          self._manager.poll_async_commit()
        if self._nonfinite_policy is not None:
          prev, pending_nonfinite = pending_nonfinite, (
              scalars.get('nonfinite_count'), step)
          if prev is not None and prev[0] is not None:
            self._nonfinite_policy.observe(prev[0], prev[1])
        if crossed_interval(config.log_interval_steps, before, step):
          scalars = {k: float(v) for k, v in scalars.items()}
          dt = time.time() - last_log
          last_log = time.time()
          scalars['steps_per_sec'] = (step - last_log_step) / max(dt, 1e-9)
          last_log_step = step
          # Step-time breakdown + resilience counters ride the normal
          # scalars dict, so MetricsLogger/TensorBoard publish them with
          # zero call-site changes.
          scalars.update(breakdown.window_scalars(
              utilization_fn=(self._program_utilization
                              if programs_on else None)))
          # HBM gauges (peak/live bytes) ride the same scalar merge, so
          # TensorBoard shows memory beside throughput; no-op (empty) on
          # backends without allocator stats (CPU).
          scalars.update(memory_lib.memory_scalars())
          scalars.update(
              _resilience_scalars(resilience_snap, self._nonfinite_policy))
          if (self._heartbeat is not None and self._dist_ctx is not None
              and self._dist_ctx.is_primary):
            # Whole-job view (PR-2 follow-up): process 0 merges every
            # host's registry snapshot riding the heartbeats — counters
            # summed, per-host step/age gauges — into the same scalars
            # dict TensorBoard already publishes.
            scalars.update(self._heartbeat.aggregated_scalars())
        for cb in self._callbacks:
          cb.after_step(self, step, scalars)
        if (self._manager is not None and
            crossed_interval(config.save_interval_steps, before, step)):
          # K > 1 boundary steps are rarely exact interval multiples;
          # the crossing above is the interval authority, so force past
          # orbax's own multiple-of-interval should_save.
          self.save_checkpoint(force=self._loop_k > 1)
        if (eval_iter_fn is not None and config.eval_interval_steps and
            (crossed_interval(config.eval_interval_steps, before, step) or
             step >= config.max_train_steps)):
          eval_metrics = self.evaluate(eval_iter_fn())
    finally:
      # A still-pending deferred harvest serves no live gauge once the
      # loop ends — cancel it (and tell an already-fired one to bail)
      # so short runs and benchmarks never pay the AOT compile.
      program_loop_live[0] = False
      if program_harvest_timer is not None:
        program_harvest_timer.cancel()
      if prefetcher is not None:
        prefetcher.close()
      if self._heartbeat is not None:
        self._heartbeat.stop()
        self._heartbeat = None
    if (self._nonfinite_policy is not None and
        pending_nonfinite is not None and pending_nonfinite[0] is not None):
      # Flush the final dispatch's flag before declaring success.
      self._nonfinite_policy.observe(*pending_nonfinite)
    if coordinated is not None and stop_step is None:
      # Completion: publish this host's final boundary UNCONDITIONALLY —
      # a peer whose SIGTERM lands after this moment (the completed-host
      # vs late-proposal race) finds it in the KV store and converges on
      # it, even though this host will never poll again. Then join any
      # already-in-flight negotiation so the peer is not stranded: the
      # agreed target includes this host's completed boundary in its
      # max, so completion proceeds normally and the final save's commit
      # barriers align across hosts (every host saves the same final
      # step).
      coordinated.publish_boundary(step)
      coordinated.poll(step)
      if (self._manager is not None and
          coordinated.participants is not None):
        self._manager.set_participants(coordinated.participants)
    self.save_checkpoint(force=True, sync=True)
    if self._manager is not None:
      self._manager.wait_until_finished()
    if eval_iter_fn is not None and not eval_metrics:
      eval_metrics = self.evaluate(eval_iter_fn())
    for cb in self._callbacks:
      cb.end(self)
    return eval_metrics or scalars

  def evaluate(self, eval_iter: Iterator[Batch]) -> MetricDict:
    config = self._config
    if self._state is None:
      features, labels = next(eval_iter)
      self.initialize(features)
      batches: List[Batch] = [(features, labels)]
    else:
      batches = []
    metric_batches: List[MetricDict] = []
    for _ in range(config.eval_steps):
      if batches:
        features, labels = batches.pop()
      else:
        try:
          features, labels = next(eval_iter)
        except StopIteration:
          break
      features = mesh_lib.shard_batch(features, self._mesh)
      labels = mesh_lib.shard_batch(labels, self._mesh)
      # Keep per-batch metrics on device; a float() here would force a
      # device sync every eval step. One sync happens in _mean_metrics.
      metric_batches.append(self._eval_step_fn(self._state, features, labels))
    metrics = _mean_metrics(jax.device_get(metric_batches))
    for cb in self._callbacks:
      cb.after_eval(self, self.step, metrics)
    return metrics

  def predict(self, features) -> SpecStruct:
    """Single PREDICT-mode forward pass on numpy features."""
    if self._state is None:
      self.initialize(features)
    features_p, _ = self._preprocessor.preprocess(
        features, None, ModeKeys.PREDICT, None)
    outputs, _ = self._model.inference_network_fn(
        dict(self._state.eval_variables), features_p, None, ModeKeys.PREDICT)
    return self._model.create_export_outputs_fn(features_p, outputs)

  def close(self) -> None:
    if self._manager is not None:
      self._manager.wait_until_finished()
      self._manager.close()


# ------------------------------------------------------------ driver entry


EVAL_STATE_FILENAME = 'eval_state.json'


def _read_continuous_eval_state(model_dir: str) -> Optional[int]:
  """Last step the continuous evaluator finished, or None."""
  if not model_dir:
    return None
  import json

  try:
    with open(os.path.join(model_dir, EVAL_STATE_FILENAME)) as f:
      return int(json.load(f)['last_evaluated_step'])
  except (OSError, ValueError, KeyError, TypeError):
    return None


def _write_continuous_eval_state(model_dir: str, step: int) -> None:
  """Atomically persists the evaluator's position (crash/preempt-safe)."""
  if not model_dir:
    return
  import json

  path = os.path.join(model_dir, EVAL_STATE_FILENAME)
  tmp = path + f'.tmp{os.getpid()}'
  with open(tmp, 'w') as f:
    json.dump({'last_evaluated_step': int(step)}, f)
  os.replace(tmp, path)


def provide_input_generator_with_model_information(input_generator, model,
                                                   mode: str):
  """Spec handshake (utils/train_eval.py:101-129)."""
  input_generator.set_specification_from_model(model, mode)
  return input_generator


def train_eval_model(model=None,
                     model_dir: str = '',
                     train_input_generator=None,
                     eval_input_generator=None,
                     max_train_steps: int = 1000,
                     eval_steps: int = 10,
                     eval_interval_steps: int = 500,
                     save_interval_steps: int = 500,
                     max_checkpoints_to_keep: Optional[int] = 5,
                     log_interval_steps: int = 100,
                     seed: int = 0,
                     mesh: Optional[jax.sharding.Mesh] = None,
                     callbacks: Sequence[TrainerCallback] = (),
                     create_exporters_fn=None,
                     use_continuous_eval: bool = False,
                     eval_timeout_secs: Optional[float] = 30.0,
                     steps_per_dispatch: int = 1,
                     checkpoint_input_state: bool = False,
                     nonfinite_mode: str = 'off',
                     nonfinite_halt_after: int = 10,
                     handle_preemption: bool = False,
                     ) -> MetricDict:
  """The reference's `train_eval_model` entry (utils/train_eval.py:394-587).

  * train + eval generators → interleaved train/eval (+ export on eval).
  * train generator only → train-only job.
  * eval generator only + ``use_continuous_eval`` → watch ``model_dir`` for
    new checkpoints, evaluate each, and run exporters.
  """
  if model is None:
    raise ValueError('train_eval_model requires a model.')
  config = TrainerConfig(
      model_dir=model_dir,
      max_train_steps=max_train_steps,
      eval_steps=eval_steps,
      eval_interval_steps=eval_interval_steps,
      save_interval_steps=save_interval_steps,
      max_checkpoints_to_keep=max_checkpoints_to_keep,
      log_interval_steps=log_interval_steps,
      seed=seed,
      steps_per_dispatch=steps_per_dispatch,
      nonfinite_mode=nonfinite_mode,
      nonfinite_halt_after=nonfinite_halt_after,
      handle_preemption=handle_preemption)
  callbacks = list(callbacks)
  exporters = []
  if create_exporters_fn is not None:
    exporters = list(create_exporters_fn(model))

  if train_input_generator is not None:
    provide_input_generator_with_model_information(
        train_input_generator, model, ModeKeys.TRAIN)
  if eval_input_generator is not None:
    provide_input_generator_with_model_information(
        eval_input_generator, model, ModeKeys.EVAL)

  train_iter = None
  if train_input_generator is not None:
    if checkpoint_input_state:
      # Resumable stream (train/input_state.py): save the pipeline
      # position with every checkpoint and restore it on resume. The
      # generator must support it (record-backed generators do); a
      # config asking for it on one that doesn't should fail loudly,
      # not silently restart streams on every preemption.
      from tensor2robot_tpu.train.input_state import InputStateCallback

      if not hasattr(train_input_generator,
                     'create_checkpointable_iterator'):
        raise ValueError(
            'checkpoint_input_state=True needs a generator with '
            'create_checkpointable_iterator (e.g. '
            'DefaultRecordInputGenerator); got '
            f'{type(train_input_generator).__name__}.')
      train_iter = train_input_generator.create_checkpointable_iterator(
          ModeKeys.TRAIN)
      callbacks.append(InputStateCallback(train_iter))
    else:
      train_iter = train_input_generator.create_iterator(ModeKeys.TRAIN)

  trainer = Trainer(model, config, mesh=mesh, callbacks=callbacks)

  # Spec dump at startup (the reference logs the full in/out spec contract
  # before training, utils/train_eval.py:65-98).
  preprocessor = model.preprocessor
  for kind, getter in (
      ('feature', preprocessor.get_in_feature_specification),
      ('label', preprocessor.get_in_label_specification)):
    spec = getter(ModeKeys.TRAIN)
    if spec is not None:
      logging.info('train %s specs:\n%s', kind,
                   '\n'.join(f'  {k}: {v}'
                             for k, v in sorted(spec.items())))

  def run_exporters(metrics: MetricDict) -> None:
    for exporter in exporters:
      exporter.export(trainer, metrics)

  try:
    if train_iter is not None:
      eval_iter_fn = None
      if eval_input_generator is not None:
        eval_iter_fn = lambda: eval_input_generator.create_iterator(
            ModeKeys.EVAL)
      metrics = trainer.train(train_iter, eval_iter_fn)
      if exporters:
        run_exporters(metrics)
      return metrics
    if eval_input_generator is None:
      raise ValueError('Need a train or eval input generator.')
    # Continuous-eval job over appearing checkpoints
    # (utils/train_eval.py:550-585). Each step is BACKED UP into the
    # evaluator's own directory before restore so the trainer's retention
    # GC cannot delete it mid-eval (utils/train_eval.py:590-707).
    #
    # Preemption-aware (PR-1 follow-up): the loop persists its last
    # evaluated step to <model_dir>/eval_state.json after every eval, and
    # a graceful-shutdown request (SIGTERM on a preemptible evaluator —
    # installed by the Trainer when handle_preemption is on) raises
    # PreemptedError BETWEEN checkpoints, which the trainer binary
    # converts to the resumable exit status 42. The restarted evaluator
    # reads the state and skips already-evaluated checkpoints instead of
    # re-running (or worse, re-exporting) them.
    metrics = {}
    ckpt_dir = os.path.join(model_dir, 'checkpoints')
    backup_dir = os.path.join(model_dir, ckpt_lib.EVAL_BACKUP_DIRNAME)
    last_evaluated: Optional[int] = None
    if use_continuous_eval:
      last_evaluated = _read_continuous_eval_state(model_dir)
      if last_evaluated is not None:
        logging.info(
            'Continuous eval resuming: checkpoints up to step %d were '
            'already evaluated.', last_evaluated)
    shutdown = (trainer._shutdown if trainer._shutdown is not None  # pylint: disable=protected-access
                else resilience.active_shutdown())
    for step in ckpt_lib.checkpoints_iterator(
        ckpt_dir,
        timeout=eval_timeout_secs,
        stop_after_step=max_train_steps if use_continuous_eval else None):
      if last_evaluated is not None and step <= last_evaluated:
        logging.info(
            'Continuous eval: skipping step %d (already evaluated before '
            'the restart).', step)
        continue
      if shutdown is not None and shutdown.requested:
        logging.warning(
            'Graceful shutdown requested; continuous eval exiting '
            'resumable after step %s.', last_evaluated)
        if use_continuous_eval and last_evaluated is not None:
          _write_continuous_eval_state(model_dir, last_evaluated)
        raise resilience.PreemptedError(last_evaluated or 0)
      backup = ckpt_lib.create_backup_checkpoint_for_eval(
          ckpt_dir, step, backup_dir)
      if backup is None:
        # GC won the race; wait for the next checkpoint. If this was the
        # final checkpoint the iterator will terminate, so say loudly
        # that the returned metrics are from an earlier step.
        logging.warning(
            'Continuous eval: checkpoint %d disappeared before it could '
            'be backed up; skipping its eval.', step)
        if use_continuous_eval and step >= max_train_steps:
          logging.warning(
              'Continuous eval: the FINAL checkpoint (step %d) was never '
              'evaluated; returning metrics from the last evaluated '
              'checkpoint%s.', step, '' if metrics else ' (none: empty)')
        continue
      eval_iter = eval_input_generator.create_iterator(ModeKeys.EVAL)
      if trainer.state is None:
        features, _ = next(eval_input_generator.create_iterator(ModeKeys.EVAL))
        trainer.initialize(features)
      restored = ckpt_lib.restore_from_backup(trainer.state, backup)
      if restored is not None:
        trainer._state = restored  # pylint: disable=protected-access
      metrics = trainer.evaluate(eval_iter)
      if exporters:
        run_exporters(metrics)
      last_evaluated = step
      if use_continuous_eval:
        _write_continuous_eval_state(model_dir, step)
      if not use_continuous_eval:
        break
    return metrics
  finally:
    trainer.close()


def predict_from_model(model=None,
                       input_generator=None,
                       model_dir: str = '',
                       mesh: Optional[jax.sharding.Mesh] = None):
  """Streams predictions batch-by-batch (utils/train_eval.py:364-391)."""
  if model is None or input_generator is None:
    raise ValueError('predict_from_model requires model and input generator.')
  config = TrainerConfig(model_dir=model_dir, async_checkpoints=False)
  trainer = Trainer(model, config, mesh=mesh)
  provide_input_generator_with_model_information(
      input_generator, model, ModeKeys.PREDICT)
  for features, _ in input_generator.create_iterator(ModeKeys.PREDICT):
    yield trainer.predict(features)
