"""Train state: one pytree holding everything a training step mutates.

Replaces the reference's implicit graph state (global_step variable, slot
variables, moving averages, batch-norm stats living in TF collections —
``models/abstract_model.py:739-799``) with a single explicit, shardable
pytree. Because it is a pytree, the whole state can be donated to the jitted
step, checkpointed by Orbax in one call, and sharded by pjit.

``ema_params`` realises the reference's ``MovingAverageOptimizer`` +
swapping-saver capability (``models/optimizers.py:140-167``): when enabled,
eval and export read the averaged weights.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
  step: jax.Array
  params: Any
  model_state: Dict[str, Any]  # non-trainable Flax collections
  opt_state: Any
  ema_params: Optional[Any] = None
  rng: Optional[jax.Array] = None

  @property
  def eval_params(self) -> Any:
    """Params eval/export should use (EMA when enabled)."""
    return self.params if self.ema_params is None else self.ema_params

  @property
  def variables(self) -> Mapping[str, Any]:
    merged = dict(self.model_state or {})
    merged['params'] = self.params
    return merged

  @property
  def eval_variables(self) -> Mapping[str, Any]:
    merged = dict(self.model_state or {})
    merged['params'] = self.eval_params
    return merged


def create_train_state(model,
                       optimizer: optax.GradientTransformation,
                       rng: jax.Array,
                       features,
                       mode: str = 'train') -> TrainState:
  """Initializes variables + optimizer state for spec-shaped ``features``."""
  init_rng, state_rng = jax.random.split(rng)
  variables = model.init_variables(init_rng, features, mode)
  variables = dict(variables)
  params = variables.pop('params')
  if model.init_from_checkpoint_fn is not None:
    params, variables = model.init_from_checkpoint_fn(params, variables)
  opt_state = optimizer.init(params)
  # EMA starts as a *copy*: sharing buffers with params would donate the
  # same buffer twice in the jitted step (donate_argnums on the state).
  ema_params = (jax.tree_util.tree_map(jnp.copy, params)
                if model.use_avg_model_params else None)
  return TrainState(
      step=jnp.zeros((), jnp.int32),
      params=params,
      model_state=variables,
      opt_state=opt_state,
      ema_params=ema_params,
      rng=state_rng)


def init_grad_accumulators(params: Any) -> Any:
  """Zeroed float32 gradient accumulators shaped like ``params``.

  Float32 regardless of param/compute dtype: summing M microbatch
  gradients in bfloat16 would lose the low bits the optimizer update
  depends on. The accumulators live only inside the jitted step's
  ``lax.scan`` carry, which XLA updates in place (donated across scan
  iterations) — they never exist M times.
  """
  return jax.tree_util.tree_map(
      lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)


def accumulate_grads(acc: Any, grads: Any) -> Any:
  """One accumulation step: ``acc += grads`` in float32."""
  return jax.tree_util.tree_map(
      lambda a, g: a + g.astype(jnp.float32), acc, grads)


def finalize_accumulated_grads(acc: Any, params: Any,
                               num_microbatches: int) -> Any:
  """Mean over microbatches, cast back to the params' gradient dtype.

  For a mean-reduced loss, the mean of M microbatch-mean gradients IS the
  full-batch gradient, so the optimizer sees exactly what the unsliced
  step would feed it (up to f32 summation order).
  """
  return jax.tree_util.tree_map(
      lambda a, p: (a / num_microbatches).astype(jnp.asarray(p).dtype),
      acc, params)


def apply_ema(state: TrainState, new_params, decay: float) -> Optional[Any]:
  """One EMA update; returns the new ema tree (or None when disabled)."""
  if state.ema_params is None:
    return None
  return jax.tree_util.tree_map(
      lambda ema, p: ema * decay + p.astype(ema.dtype) * (1.0 - decay),
      state.ema_params, new_params)
