"""Input-stream checkpointing: resume the data pipeline with the model.

Beyond the reference: its estimator jobs rebuild the input_fn from
scratch on every restart (``utils/train_eval.py`` has no input state),
so a preempted trainer silently re-feeds the examples its shuffle buffer
and readers had already advanced past. Here the input stream's position
is saved ATOMICALLY-ADJACENT to each model checkpoint and restored with
it:

    gen = DefaultRecordInputGenerator(..., seed=7)
    gen.set_specification_from_model(model, ModeKeys.TRAIN)
    it = gen.create_checkpointable_iterator(ModeKeys.TRAIN)
    trainer = Trainer(model, config,
                      callbacks=[InputStateCallback(it)])
    trainer.train(it, None)   # resumes both model AND stream state

The callback saves on ``after_checkpoint`` (one state per checkpoint
step, GC'd alongside) and restores on ``begin`` when the trainer
restored a step for which a state exists. A missing state (pre-feature
checkpoints, deleted dirs) logs and falls back to a fresh stream — the
reference's behavior, never an error.

Exactness caveat: with ``prefetch_batches=N`` the prefetcher has pulled
up to N batches past the training position when the state is saved, so
a resume SKIPS those never-trained batches (it never repeats any). Run
``prefetch_batches=0`` when bit-exact resume matters; the exactness
test pins that configuration.

Cost caveat: ``iterator.save`` synchronously serializes the FULL
pipeline state — including the shuffle buffer's contents — inside the
training loop, so the per-checkpoint stall scales with
``shuffle_buffer_size`` times the example size (hundreds of MB for
image streams with the default 1000-element buffer). Size the buffer,
the save interval, or both accordingly; async model checkpointing does
not cover this write.

Multi-host: every process saves/restores ITS OWN stream position under
``input_state/<name>/process_<i>/`` — the per-host input shards
(``pipeline.shard_filenames_for_process`` / element sharding) have
independent reader/shuffle state, so sharing one state would make every
host replay one host's shard.
"""

from __future__ import annotations

import logging
import os
import re
import shutil
from typing import Optional

from tensor2robot_tpu.train.trainer import TrainerCallback

INPUT_STATE_DIRNAME = 'input_state'
_STEP_RE = re.compile(r'^step_(\d+)$')


class InputStateCallback(TrainerCallback):
  """Saves/restores a checkpointable input iterator with the trainer."""

  def __init__(self, iterator, name: str = 'train', keep: int = 5):
    """``iterator`` must expose ``save(path_prefix)`` / ``restore(path)``
    (``pipeline.CheckpointableNumpyIterator``)."""
    self._iterator = iterator
    self._name = name
    self._keep = keep

  def _root(self, trainer) -> Optional[str]:
    if not trainer.config.model_dir:
      return None
    import jax

    return os.path.join(trainer.config.model_dir, INPUT_STATE_DIRNAME,
                        self._name, f'process_{jax.process_index()}')

  def _step_dirs(self, root):
    try:
      entries = os.listdir(root)
    except FileNotFoundError:
      return {}
    return {int(m.group(1)): os.path.join(root, e)
            for e in entries if (m := _STEP_RE.match(e))}

  def begin(self, trainer) -> None:
    root = self._root(trainer)
    step = trainer.step
    if root is None or step == 0:
      return
    path = self._step_dirs(root).get(step)
    if path is None:
      logging.warning(
          'No %r input state for restored step %d under %s; the stream '
          'restarts from the beginning (examples before the checkpoint '
          'may repeat).', self._name, step, root)
      return
    import time

    from tensor2robot_tpu.observability import metrics as metrics_lib

    t0 = time.perf_counter()
    self._iterator.restore(os.path.join(path, 'state'))
    # The goodput-facing number for ROADMAP direction 5: how long the
    # DATA side of a restart took, and whether it was an O(1) index
    # seek (data/resume_seek_mode=1) or an O(position) replay — read
    # next to trainer/restart_to_first_step_seconds.
    resume_ms = (time.perf_counter() - t0) * 1e3
    logging.info(
        'Restored %r input stream state at step %d in %.1f ms '
        '(seek_mode=%s, replayed_records=%s).', self._name, step,
        resume_ms,
        int(metrics_lib.gauge('data/resume_seek_mode').value),
        int(metrics_lib.gauge('data/resume_replayed_records').value))

  def after_checkpoint(self, trainer, step: int) -> None:
    root = self._root(trainer)
    if root is None:
      return
    final_dir = os.path.join(root, f'step_{int(step)}')
    tmp_dir = os.path.join(root, f'.tmp_{int(step)}')
    shutil.rmtree(tmp_dir, ignore_errors=True)
    os.makedirs(tmp_dir, exist_ok=True)
    self._iterator.save(os.path.join(tmp_dir, 'state'))
    shutil.rmtree(final_dir, ignore_errors=True)
    os.replace(tmp_dir, final_dir)  # atomic: restore never sees partials
    # GC keys off the checkpoint manager's OWN retention: every model
    # checkpoint that still exists keeps its stream state (deleting it
    # would turn a rollback into a silent stream restart — the failure
    # mode this feature exists to prevent). ``keep`` newest is only the
    # fallback when no manager tracks retention.
    by_step = self._step_dirs(root)
    manager = trainer.checkpoint_manager
    if manager is not None:
      retained = set(int(s) for s in manager.all_steps()) | {int(step)}
      for old in sorted(s for s in by_step if s not in retained):
        shutil.rmtree(by_step[old], ignore_errors=True)
    elif self._keep:
      for old in sorted(by_step)[:-self._keep]:
        shutil.rmtree(by_step[old], ignore_errors=True)
