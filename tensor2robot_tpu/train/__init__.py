"""Training: jitted train/eval steps, checkpointing, driver entry points."""

from tensor2robot_tpu.train.checkpoints import (
    CheckpointManager,
    checkpoints_iterator,
    latest_checkpoint_step,
)
from tensor2robot_tpu.train.distributed_resilience import (
    LIVENESS_EXIT_CODE,
    CoordinatedShutdown,
    DeadHostError,
    DistributedContext,
    HeartbeatService,
    TopologyMismatchError,
    aggregate_snapshots,
)
from tensor2robot_tpu.train.train_state import (
    TrainState,
    apply_ema,
    create_train_state,
)
from tensor2robot_tpu.train.input_state import InputStateCallback
from tensor2robot_tpu.train.resilience import (
    PREEMPTED_EXIT_CODE,
    GracefulShutdown,
    NonFiniteError,
    NonFinitePolicy,
    PreemptedError,
    active_shutdown,
    install_graceful_shutdown,
)
from tensor2robot_tpu.train.trainer import (
    Trainer,
    TrainerCallback,
    TrainerConfig,
    predict_from_model,
    provide_input_generator_with_model_information,
    train_eval_model,
)
