"""Run modes. String-valued so they are trivially gin/json/config friendly."""


class ModeKeys:
  TRAIN = 'train'
  EVAL = 'eval'
  PREDICT = 'predict'

  ALL = (TRAIN, EVAL, PREDICT)


def is_training(mode: str) -> bool:
  return mode == ModeKeys.TRAIN
