"""MetaExample construction: episode Examples → one MetaExample record.

Capability-equivalent of
``/root/reference/meta_learning/meta_example.py:34-90``: every feature of
episode i is copied under ``condition_ep<i>/...`` or ``inference_ep<i>/...``.
Operates on ``tf.train.Example`` / ``SequenceExample`` protos or their
serialized bytes.
"""

from __future__ import annotations

from typing import Sequence, Union

ExampleLike = Union[bytes, object]


def _tf():
  import tensorflow as tf

  return tf


def _to_example(example: ExampleLike):
  tf = _tf()
  if isinstance(example, bytes):
    parsed = tf.train.Example()
    parsed.ParseFromString(example)
    return parsed
  return example


def append_example(meta_example, ep_example, prefix: str) -> None:
  """Copies episode features under ``<prefix>/<key>`` (meta_example.py:54-60)."""
  context_feature_map = meta_example.features.feature
  for key, feature in ep_example.features.feature.items():
    context_feature_map[f'{prefix}/{key}'].CopyFrom(feature)


def append_sequence_example(meta_example, ep_example, prefix: str) -> None:
  """SequenceExample variant (meta_example.py:63-76)."""
  context_feature_map = meta_example.context.feature
  for key, feature in ep_example.context.feature.items():
    context_feature_map[f'{prefix}/{key}'].CopyFrom(feature)
  sequential_feature_map = meta_example.feature_lists.feature_list
  for key, feature_list in ep_example.feature_lists.feature_list.items():
    sequential_feature_map[f'{prefix}/{key}'].CopyFrom(feature_list)


def make_meta_example(condition_examples: Sequence[ExampleLike],
                      inference_examples: Sequence[ExampleLike]):
  """K condition + M inference Examples → MetaExample (meta_example.py:34-51)."""
  tf = _tf()
  condition_examples = [_to_example(e) for e in condition_examples]
  inference_examples = [_to_example(e) for e in inference_examples]
  if isinstance(condition_examples[0], tf.train.Example):
    meta_example = tf.train.Example()
    append_fn = append_example
  else:
    meta_example = tf.train.SequenceExample()
    append_fn = append_sequence_example
  for i, train_example in enumerate(condition_examples):
    append_fn(meta_example, train_example, f'condition_ep{i}')
  for i, val_example in enumerate(inference_examples):
    append_fn(meta_example, val_example, f'inference_ep{i}')
  return meta_example


def serialize_meta_example(condition_examples: Sequence[ExampleLike],
                           inference_examples: Sequence[ExampleLike]) -> bytes:
  return make_meta_example(
      condition_examples, inference_examples).SerializeToString()
