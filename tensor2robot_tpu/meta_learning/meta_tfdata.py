"""Meta-batch utilities: flatten/unflatten task×sample dims, multi-batch apply.

Capability-equivalent of ``/root/reference/meta_learning/meta_tfdata.py``:

* :func:`flatten_batch_examples` / :func:`unflatten_batch_examples` —
  merge/split the leading [num_tasks, num_samples] dims (``:179-224``).
* :func:`multi_batch_apply` — vectorize a function over multiple leading
  batch dims (``:266-286``); in JAX this is a reshape round-trip (the
  reference's approach) kept for API parity — ``jax.vmap`` is the
  idiomatic alternative and what MAML uses.
* Task-grouped record reading lives in
  :class:`MetaExampleInputGenerator` (one MetaExample record per task).
"""

from __future__ import annotations

from typing import Callable


from tensor2robot_tpu.specs import SpecStruct, algebra


def _map_leaves(fn, structure):
  if structure is None:
    return None
  flat = algebra.flatten_spec_structure(structure)
  out = SpecStruct()
  for key, value in flat.items():
    out[key] = fn(value)
  return out


def flatten_batch_examples(tensor_collection):
  """[num_tasks, num_samples, ...] → [num_tasks*num_samples, ...]."""

  def flatten(value):
    shape = value.shape
    return value.reshape((shape[0] * shape[1],) + tuple(shape[2:]))

  return _map_leaves(flatten, tensor_collection)


def unflatten_batch_examples(tensor_collection, num_samples_per_task: int):
  """[num_tasks*num_samples, ...] → [num_tasks, num_samples, ...]."""

  def unflatten(value):
    shape = value.shape
    return value.reshape(
        (-1, num_samples_per_task) + tuple(shape[1:]))

  return _map_leaves(unflatten, tensor_collection)


def multi_batch_apply(fn: Callable, num_batch_dims: int, *args, **kwargs):
  """Applies ``fn`` (expecting one batch dim) over several leading dims.

  All array leaves in ``args`` are reshaped to merge their first
  ``num_batch_dims`` dims, ``fn`` is applied, and outputs are reshaped
  back (meta_tfdata.py:266-286).
  """
  import jax

  lead_shape = None

  def merge(value):
    nonlocal lead_shape
    if hasattr(value, 'shape') and len(value.shape) >= num_batch_dims:
      lead_shape = tuple(value.shape[:num_batch_dims])
      return value.reshape((-1,) + tuple(value.shape[num_batch_dims:]))
    return value

  merged_args = jax.tree_util.tree_map(merge, list(args))
  result = fn(*merged_args, **kwargs)
  if lead_shape is None:
    return result

  def split(value):
    if hasattr(value, 'shape'):
      return value.reshape(lead_shape + tuple(value.shape[1:]))
    return value

  return jax.tree_util.tree_map(split, result)


def split_train_val(tensors, num_train_samples_per_task: int):
  """Splits the samples dim into (train, val) (meta_tfdata.py:135-156)."""

  def head(value):
    return value[:, :num_train_samples_per_task]

  def tail(value):
    return value[:, num_train_samples_per_task:]

  return _map_leaves(head, tensors), _map_leaves(tail, tensors)
