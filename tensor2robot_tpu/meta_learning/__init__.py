"""Meta-learning: MAML via grad+vmap, meta specs, MetaExample pipeline."""

from tensor2robot_tpu.meta_learning.maml_inner_loop import (
    MAMLInnerLoopGradientDescent,
    gradient_descent_step,
)
from tensor2robot_tpu.meta_learning.maml_model import MAMLModel
from tensor2robot_tpu.meta_learning.meta_example import (
    make_meta_example,
    serialize_meta_example,
)
from tensor2robot_tpu.meta_learning.meta_policies import (
    FixedLengthSequentialRegressionPolicy,
    MAMLCEMPolicy,
    MAMLRegressionPolicy,
    MetaLearningPolicy,
    ScheduledExplorationMAMLRegressionPolicy,
)
from tensor2robot_tpu.meta_learning.preprocessors import (
    FixedLenMetaExamplePreprocessor,
    MAMLPreprocessorV2,
    create_maml_feature_spec,
    create_maml_label_spec,
    create_metaexample_spec,
)
from tensor2robot_tpu.meta_learning.run_meta_env import run_meta_env
from tensor2robot_tpu.meta_learning import meta_tfdata
