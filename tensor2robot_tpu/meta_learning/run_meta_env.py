"""Meta task-loop driver: per task, adapt on demos/rollouts then evaluate.

Capability-equivalent of
``/root/reference/meta_learning/run_meta_env.py:37-262``: for each task,
(optionally) collect demonstration episodes, ``policy.adapt`` on them, run
``num_adaptations_per_task`` trial rounds re-adapting on accumulated data,
and log per-step rewards (JSON lines instead of TF summaries).
"""

from __future__ import annotations

import collections
import copy
import datetime
import json
import logging
import os
from typing import Callable, Optional

import numpy as np


def run_meta_env(env,
                 policy=None,
                 demo_policy_cls=None,
                 explore_schedule=None,
                 episode_to_transitions_fn: Optional[Callable] = None,
                 replay_writer=None,
                 root_dir: Optional[str] = None,
                 task: int = 0,
                 global_step: int = 0,
                 num_episodes=None,
                 num_tasks: int = 10,
                 num_adaptations_per_task: int = 2,
                 num_episodes_per_adaptation: int = 1,
                 num_demos: int = 1,
                 break_after_one_task: bool = False,
                 tag: str = 'collect',
                 write_summary: bool = False):
  """Runs the meta collect/eval loop; returns per-task step rewards."""
  del num_episodes

  task_step_rewards = collections.defaultdict(
      lambda: collections.defaultdict(list))
  episode_q_values = collections.defaultdict(list)

  for task_idx in range(num_tasks):
    if hasattr(policy, 'reset_task'):
      policy.reset_task()
    env.reset_task()

    record_name = None
    if root_dir and replay_writer:
      timestamp = datetime.datetime.now().strftime('%Y-%m-%d-%H-%M-%S')
      record_name = os.path.join(
          root_dir, f'gs{global_step}_t{task}_{timestamp}_{task_idx}')
      replay_writer.open(record_name)

    # Collect demonstration episodes to condition on (run_meta_env.py:
    # 126-176).
    condition_data = []
    if hasattr(env, 'get_demonstration') and hasattr(policy, 'adapt'):
      for _ in range(num_demos):
        obs = env.reset()
        demo_policy = demo_policy_cls(env)
        episode_data = []
        while True:
          action, debug = demo_policy.sample_action(obs, 0)
          if action is None:
            break
          next_obs, rew, done, debug = env.step(action)
          debug = dict(debug or {})
          debug['is_demo'] = True
          episode_data.append((obs, action, rew, next_obs, done, debug))
          obs = next_obs
        condition_data.append(episode_data)
        if replay_writer and episode_to_transitions_fn:
          replay_writer.write(
              episode_to_transitions_fn(episode_data, is_demo=True))
      policy.adapt(copy.copy(condition_data))
    elif hasattr(env, 'task_data') and hasattr(policy, 'adapt'):
      for episode_name, episode_data in env.task_data.items():
        if str(episode_name).startswith('condition_ep'):
          condition_data.append(episode_data)
      policy.adapt(copy.copy(condition_data))

    # Trial rounds with re-adaptation (run_meta_env.py:178-225).
    for step_num in range(num_adaptations_per_task):
      if step_num != 0 and hasattr(policy, 'adapt'):
        policy.adapt(copy.copy(condition_data))
      for ep in range(num_episodes_per_adaptation):
        done, env_step, episode_reward, episode_data = False, 0, 0.0, []
        policy.reset()
        obs = env.reset()
        explore_prob = (explore_schedule.value(global_step)
                        if explore_schedule else 0.0)
        while not done:
          debug = {}
          action, policy_debug = policy.sample_action(obs, explore_prob)
          if policy_debug is not None:
            debug.update(policy_debug)
          if policy_debug and 'q_predicted' in policy_debug:
            episode_q_values[env_step].append(policy_debug['q_predicted'])
          new_obs, rew, done, env_debug = env.step(action)
          debug.update(env_debug)
          env_step += 1
          episode_reward += rew
          episode_data.append((obs, action, rew, new_obs, done, debug))
          obs = new_obs
          if done:
            logging.info('Step %d episode %d reward: %f', step_num, ep,
                         episode_reward)
            task_step_rewards[task_idx][step_num].append(episode_reward)
            if replay_writer and episode_to_transitions_fn:
              replay_writer.write(episode_to_transitions_fn(episode_data))
        condition_data.append(episode_data)

    avg = float(np.mean(
        task_step_rewards[task_idx][num_adaptations_per_task - 1]))
    logging.info('Task %d avg reward: %f', task_idx, avg)
    if replay_writer and record_name:
      replay_writer.close()
    if break_after_one_task:
      break

  if root_dir and write_summary:
    summary_dir = os.path.join(root_dir, f'live_eval_{task}')
    os.makedirs(summary_dir, exist_ok=True)
    summary = {'tag': tag, 'global_step': int(global_step)}
    for step_num in range(num_adaptations_per_task):
      step_rewards = [
          float(np.mean(task_step_rewards[t][step_num]))
          for t in task_step_rewards
      ]
      summary[f'step_{step_num}_reward'] = float(np.mean(step_rewards))
      if step_num > 0:
        deltas = [
            float(np.mean(np.asarray(task_step_rewards[t][step_num]) -
                          np.asarray(task_step_rewards[t][step_num - 1])))
            for t in task_step_rewards
        ]
        summary[f'step_{step_num}_improvement'] = float(np.mean(deltas))
    with open(os.path.join(summary_dir, 'metrics.jsonl'), 'a') as f:
      f.write(json.dumps(summary) + '\n')
  return task_step_rewards
