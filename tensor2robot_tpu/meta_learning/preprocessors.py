"""Meta-learning preprocessors: condition/inference spec transforms.

Capability-equivalent of ``/root/reference/meta_learning/preprocessors.py``:

* :func:`create_maml_feature_spec` (``:39-71``) — base specs →
  ``condition.{features,labels}`` + ``inference.features`` with
  ``condition_features``/``condition_labels``/``inference_features`` name
  prefixes (the on-disk contract).
* :func:`create_maml_label_spec` (``:74-85``) — ``meta_labels`` prefix.
* :class:`MAMLPreprocessorV2` (``:88-289``) — wraps a base preprocessor
  over the flattened task×sample batch.
* :func:`create_metaexample_spec` + :class:`FixedLenMetaExamplePreprocessor`
  (``:292-451``) — parse K condition + M inference episodes from one
  MetaExample record (``<prefix>_ep<i>/<name>`` feature columns) and stack
  them into per-task tensors.
"""

from __future__ import annotations


from tensor2robot_tpu.meta_learning import meta_tfdata
from tensor2robot_tpu.preprocessors.base import AbstractPreprocessor
from tensor2robot_tpu.specs import SpecStruct, TensorSpec, algebra


def create_maml_feature_spec(feature_spec, label_spec) -> SpecStruct:
  """Base specs → meta feature spec (preprocessors.py:39-71).

  Each spec gains a dynamic leading *samples* dim: meta batches are laid
  out [num_tasks, num_samples_per_task, ...] and validation strips only
  the task (batch) dim.
  """
  meta = SpecStruct()
  for key, spec in algebra.copy_tensorspec(
      feature_spec, prefix='condition_features', batch_size=None).items():
    meta[f'condition/features/{key}'] = spec
  for key, spec in algebra.copy_tensorspec(
      label_spec, prefix='condition_labels', batch_size=None).items():
    meta[f'condition/labels/{key}'] = spec
  for key, spec in algebra.copy_tensorspec(
      feature_spec, prefix='inference_features', batch_size=None).items():
    meta[f'inference/features/{key}'] = spec
  return meta


def create_maml_label_spec(label_spec) -> SpecStruct:
  """Base label spec → meta label spec (preprocessors.py:74-85)."""
  return algebra.copy_tensorspec(
      label_spec, prefix='meta_labels', batch_size=None)


class MAMLPreprocessorV2(AbstractPreprocessor):
  """Wraps a base preprocessor over the task×sample meta batch.

  The meta batch layout is [num_tasks, num_samples_per_task, ...]; the base
  preprocessor sees the flattened [num_tasks*num_samples, ...] batch and
  its outputs are unflattened back (preprocessors.py:237-289).
  """

  def __init__(self, base_preprocessor: AbstractPreprocessor, **kwargs):
    super().__init__(**kwargs)
    self._base_preprocessor = base_preprocessor

  @property
  def base_preprocessor(self) -> AbstractPreprocessor:
    return self._base_preprocessor

  def get_in_feature_specification(self, mode):
    return create_maml_feature_spec(
        self._base_preprocessor.get_in_feature_specification(mode),
        self._base_preprocessor.get_in_label_specification(mode))

  def get_in_label_specification(self, mode):
    return create_maml_label_spec(
        self._base_preprocessor.get_in_label_specification(mode))

  def get_out_feature_specification(self, mode):
    return create_maml_feature_spec(
        self._base_preprocessor.get_out_feature_specification(mode),
        self._base_preprocessor.get_out_label_specification(mode))

  def get_out_label_specification(self, mode):
    return create_maml_label_spec(
        self._base_preprocessor.get_out_label_specification(mode))

  def _subtree(self, features, prefix: str) -> SpecStruct:
    out = SpecStruct()
    for key, value in features.items():
      if key.startswith(prefix + '/'):
        out[key[len(prefix) + 1:]] = value
    return out

  def _preprocess_fn(self, features, labels, mode, rng):
    condition_features = self._subtree(features, 'condition/features')
    condition_labels = self._subtree(features, 'condition/labels')
    inference_features = self._subtree(features, 'inference/features')

    num_condition = next(iter(condition_features.values())).shape[1]
    num_inference = next(iter(inference_features.values())).shape[1]

    flat_cond_f = meta_tfdata.flatten_batch_examples(condition_features)
    flat_cond_l = meta_tfdata.flatten_batch_examples(condition_labels)
    flat_inf_f = meta_tfdata.flatten_batch_examples(inference_features)
    flat_labels = (None if labels is None else
                   meta_tfdata.flatten_batch_examples(labels))

    flat_cond_f, flat_cond_l = self._base_preprocessor._preprocess_fn(  # pylint: disable=protected-access
        flat_cond_f, flat_cond_l, mode, rng)
    flat_inf_f, flat_labels = self._base_preprocessor._preprocess_fn(  # pylint: disable=protected-access
        flat_inf_f, flat_labels, mode, rng)

    out = SpecStruct()
    for key, value in meta_tfdata.unflatten_batch_examples(
        flat_cond_f, num_condition).items():
      out[f'condition/features/{key}'] = value
    for key, value in meta_tfdata.unflatten_batch_examples(
        flat_cond_l, num_condition).items():
      out[f'condition/labels/{key}'] = value
    for key, value in meta_tfdata.unflatten_batch_examples(
        flat_inf_f, num_inference).items():
      out[f'inference/features/{key}'] = value
    if flat_labels is not None:
      labels = meta_tfdata.unflatten_batch_examples(flat_labels,
                                                    num_inference)
    return out, labels


def create_metaexample_spec(model_spec,
                            num_samples_per_task: int,
                            prefix: str) -> SpecStruct:
  """Spec → per-episode MetaExample spec (preprocessors.py:292-318).

  Each spec ``key`` expands to ``key/i`` with on-disk name
  ``<prefix>_ep<i>/<name>``.
  """
  model_spec = algebra.flatten_spec_structure(model_spec)
  meta_example_spec = SpecStruct()
  for key in model_spec.keys():
    for i in range(num_samples_per_task):
      spec = model_spec[key]
      name = spec.name or key.split('/')[-1]
      new_name = f'{prefix}_ep{i}/{name}'
      meta_example_spec[f'{key}/{i}'] = TensorSpec.from_spec(
          spec, name=new_name)
  return meta_example_spec


def stack_intra_task_episodes(in_tensors, num_samples_per_task: int):
  """Stacks ``key/i`` episode tensors → [B, num_samples, ...] per key."""
  import jax.numpy as jnp

  out_tensors = SpecStruct()
  key_set = sorted({'/'.join(k.split('/')[:-1]) for k in in_tensors.keys()})
  for key in key_set:
    data = [in_tensors[f'{key}/{i}'] for i in range(num_samples_per_task)]
    out_tensors[key] = jnp.stack(data, axis=1)
  return out_tensors


class FixedLenMetaExamplePreprocessor(MAMLPreprocessorV2):
  """Parses K condition + M inference episodes from one MetaExample record
  (preprocessors.py:346-451)."""

  def __init__(self,
               base_preprocessor: AbstractPreprocessor,
               num_condition_samples_per_task: int = 1,
               num_inference_samples_per_task: int = 1,
               **kwargs):
    self._num_condition_samples_per_task = num_condition_samples_per_task
    self._num_inference_samples_per_task = num_inference_samples_per_task
    super().__init__(base_preprocessor, **kwargs)

  @property
  def num_condition_samples_per_task(self) -> int:
    return self._num_condition_samples_per_task

  @property
  def num_inference_samples_per_task(self) -> int:
    return self._num_inference_samples_per_task

  def get_in_feature_specification(self, mode):
    condition_spec = SpecStruct()
    for key, spec in algebra.flatten_spec_structure(
        self._base_preprocessor.get_in_feature_specification(mode)).items():
      condition_spec[f'features/{key}'] = spec
    cond_labels = self._base_preprocessor.get_in_label_specification(mode)
    if cond_labels is not None:
      for key, spec in algebra.flatten_spec_structure(cond_labels).items():
        condition_spec[f'labels/{key}'] = spec
    inference_spec = SpecStruct()
    for key, spec in algebra.flatten_spec_structure(
        self._base_preprocessor.get_in_feature_specification(mode)).items():
      inference_spec[f'features/{key}'] = spec

    feature_spec = SpecStruct()
    for key, spec in create_metaexample_spec(
        condition_spec, self._num_condition_samples_per_task,
        'condition').items():
      feature_spec[f'condition/{key}'] = spec
    for key, spec in create_metaexample_spec(
        inference_spec, self._num_inference_samples_per_task,
        'inference').items():
      feature_spec[f'inference/{key}'] = spec
    return feature_spec

  def get_in_label_specification(self, mode):
    label_spec = self._base_preprocessor.get_in_label_specification(mode)
    if label_spec is None:
      return None
    return create_metaexample_spec(
        label_spec, self._num_inference_samples_per_task, 'inference')

  def _preprocess_fn(self, features, labels, mode, rng):
    stacked = SpecStruct()
    for key, value in stack_intra_task_episodes(
        self._subtree(features, 'condition'),
        self._num_condition_samples_per_task).items():
      stacked[f'condition/{key}'] = value
    for key, value in stack_intra_task_episodes(
        self._subtree(features, 'inference'),
        self._num_inference_samples_per_task).items():
      stacked[f'inference/{key}'] = value
    out_labels = labels
    if labels is not None:
      out_labels = stack_intra_task_episodes(
          labels, self._num_inference_samples_per_task)
    return super()._preprocess_fn(stacked, out_labels, mode, rng)
