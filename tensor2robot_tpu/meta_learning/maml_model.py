"""MAMLModel: wraps any base T2R model for meta-learning.

Capability-equivalent of
``/root/reference/meta_learning/maml_model.py:76-554``. The reference maps
per-task adaptation over the task batch with ``tf.map_fn`` (after building
the base model in a throwaway graph just to infer output dtypes,
``:154-232``). Here adaptation is a pure function and tasks are mapped
with ``jax.vmap`` — no dtype inference, no graph surgery, and the task
loop vectorizes onto the MXU.

Predictions contract (``:310-359``):
``full_condition_output/output_<i>`` for every adaptation step (pre/post),
``full_inference_output`` (adapted) and
``full_inference_output_unconditioned``.
Outer loss = base ``model_train_fn`` on the flattened inference outputs
vs ``meta_labels`` (``:420-501``).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax

from tensor2robot_tpu.meta_learning import maml_inner_loop, meta_tfdata
from tensor2robot_tpu.meta_learning.preprocessors import (
    MAMLPreprocessorV2,
    create_maml_feature_spec,
    create_maml_label_spec,
)
from tensor2robot_tpu.models.base import AbstractT2RModel
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.specs import SpecStruct, algebra


class MAMLModel(AbstractT2RModel):
  """Meta-model: per-task inner adaptation + outer meta-objective."""

  def __init__(self,
               base_model: AbstractT2RModel,
               num_inner_loop_steps: int = 1,
               inner_learning_rate: float = 0.001,
               use_second_order: bool = False,
               learn_inner_lr: bool = False,
               preprocessor_cls=None,
               **kwargs):
    kwargs.setdefault('device_type', base_model.device_type)
    super().__init__(preprocessor_cls=preprocessor_cls, **kwargs)
    self._base_model = base_model
    self._num_inner_loop_steps = num_inner_loop_steps
    self._inner_loop = maml_inner_loop.MAMLInnerLoopGradientDescent(
        learning_rate=inner_learning_rate,
        use_second_order=use_second_order,
        learn_inner_lr=learn_inner_lr)

  @property
  def base_model(self) -> AbstractT2RModel:
    return self._base_model

  # ------------------------------------------------------------------ specs

  def get_feature_specification(self, mode: str) -> SpecStruct:
    return create_maml_feature_spec(
        self._base_model.get_feature_specification(mode),
        self._base_model.get_label_specification(mode))

  def get_label_specification(self, mode: str) -> SpecStruct:
    return create_maml_label_spec(
        self._base_model.get_label_specification(mode))

  @property
  def preprocessor(self):
    base_preprocessor = self._base_model.preprocessor
    if self._preprocessor_cls is not None:
      preprocessor = self._preprocessor_cls(base_preprocessor)
    else:
      preprocessor = MAMLPreprocessorV2(base_preprocessor)
    return preprocessor

  # ----------------------------------------------------------------- params

  def init_variables(self, rng, features, mode=ModeKeys.TRAIN):
    """Initializes base variables from one task's flattened sample batch."""
    cond_features = self._subtree(features, 'condition/features')
    flat = meta_tfdata.flatten_batch_examples(cond_features)
    variables = dict(self._base_model.init_variables(rng, flat, mode))
    if self._inner_loop.learn_inner_lr:
      lr_params = self._inner_loop.create_lr_params(variables['params'])
      variables['params'] = {
          'base': variables['params'],
          'inner_lrs': lr_params,
      }
    return variables

  def _split_params(self, params) -> Tuple[Any, Optional[Any]]:
    if self._inner_loop.learn_inner_lr:
      return params['base'], params['inner_lrs']
    return params, None

  def _subtree(self, struct, prefix: str) -> SpecStruct:
    out = SpecStruct()
    for key, value in struct.items():
      if key.startswith(prefix + '/'):
        out[key[len(prefix) + 1:]] = value
    return out

  # ---------------------------------------------------------------- forward

  def inference_network_fn(self, variables, features, labels, mode,
                           rng=None):
    base = self._base_model
    variables = dict(variables)
    params = variables.pop('params')
    base_params, lr_params = self._split_params(params)
    model_state = variables  # non-trainable collections, shared across tasks

    condition_features = self._subtree(features, 'condition/features')
    condition_labels = self._subtree(features, 'condition/labels')
    inference_features = self._subtree(features, 'inference/features')

    def forward(p, task_features):
      merged = dict(model_state)
      merged['params'] = p
      outputs, _ = base.inference_network_fn(
          merged, task_features, None, ModeKeys.EVAL, rng)
      return dict(outputs)

    def inner_objective(p, task_features, task_labels):
      outputs = forward(p, task_features)
      loss, _ = base.model_train_fn(
          task_features, task_labels,
          algebra.flatten_spec_structure(outputs), mode)
      return loss

    def task_learn(task_cond_f, task_cond_l, task_inf_f):
      result = self._inner_loop.inner_loop(
          base_params,
          inner_objective,
          forward,
          task_cond_f,
          task_cond_l,
          task_inf_f,
          num_steps=self._num_inner_loop_steps,
          lr_params=lr_params)
      return (result['condition_outputs'], result['conditioned_output'],
              result['unconditioned_output'])

    cond_outputs, inf_outputs, inf_unconditioned = jax.vmap(task_learn)(
        dict(condition_features), dict(condition_labels),
        dict(inference_features))

    predictions = SpecStruct()
    for i, step_output in enumerate(cond_outputs):
      for key, value in step_output.items():
        predictions[f'full_condition_output/output_{i}/{key}'] = value
    for key, value in inf_outputs.items():
      predictions[f'full_inference_output/{key}'] = value
    for key, value in inf_unconditioned.items():
      predictions[f'full_inference_output_unconditioned/{key}'] = value
    variables['params'] = params
    return predictions, variables

  # ------------------------------------------------------------------ losses

  def _base_label_view(self, labels) -> SpecStruct:
    """meta_labels/... → base label keys, flattened over tasks."""
    base_labels = SpecStruct()
    for key, value in labels.items():
      base_labels[key] = value
    return meta_tfdata.flatten_batch_examples(base_labels)

  def _base_inference_view(self, inference_outputs) -> SpecStruct:
    outputs = SpecStruct()
    for key, value in inference_outputs.items():
      prefix = 'full_inference_output/'
      if key.startswith(prefix):
        outputs[key[len(prefix):]] = value
    return meta_tfdata.flatten_batch_examples(outputs)

  def model_train_fn(self, features, labels, inference_outputs, mode):
    """Outer loss on adapted inference outputs (maml_model.py:420-501)."""
    flat_outputs = self._base_inference_view(inference_outputs)
    flat_labels = self._base_label_view(labels)
    inference_features = meta_tfdata.flatten_batch_examples(
        self._subtree(features, 'inference/features'))
    loss, scalars = self._base_model.model_train_fn(
        inference_features, flat_labels, flat_outputs, mode)
    return loss, scalars

  def model_eval_fn(self, features, labels, inference_outputs):
    flat_outputs = self._base_inference_view(inference_outputs)
    flat_labels = self._base_label_view(labels)
    inference_features = meta_tfdata.flatten_batch_examples(
        self._subtree(features, 'inference/features'))
    metrics = self._base_model.model_eval_fn(
        inference_features, flat_labels, flat_outputs)
    # Adaptation benefit: unconditioned-vs-conditioned loss delta.
    uncond = SpecStruct()
    prefix = 'full_inference_output_unconditioned/'
    for key, value in inference_outputs.items():
      if key.startswith(prefix):
        uncond[key[len(prefix):]] = value
    uncond_metrics = self._base_model.model_eval_fn(
        inference_features, flat_labels,
        meta_tfdata.flatten_batch_examples(uncond))
    metrics['loss_unconditioned'] = uncond_metrics['loss']
    return metrics

  def create_export_outputs_fn(self, features, inference_outputs):
    return inference_outputs
