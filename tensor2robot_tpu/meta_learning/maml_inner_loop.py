"""MAML inner loop: gradient-descent adaptation as a pure function.

Capability-equivalent of
``/root/reference/meta_learning/maml_inner_loop.py:33-333``. The reference
implements adaptation with a custom TF variable getter that swaps each
variable for ``var - lr*grad`` on reuse — ~300 lines of graph surgery.
In JAX the same capability is ``jax.grad`` + a tree-map update, which also
makes second-order MAML exact (gradients flow through the update unless
explicitly stopped).

Feature parity:

* K adaptation steps (``inner_loop``, reference ``:218-333``).
* Optional learned per-leaf inner learning rates (``:88-100``): scalars
  stored under ``params['inner_lrs']`` when ``learn_inner_lr``.
* ``use_second_order``: False stops gradients through inner grads
  (``:190-191``).
* Returns conditioned + unconditioned outputs for all steps.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp


InnerObjective = Callable[[Any, Any, Any], jnp.ndarray]
# (params, features, labels) -> scalar loss


def create_inner_lr_params(params: Any,
                           learning_rate: float) -> Any:
  """Per-leaf learned learning-rate scalars, initialized to ``learning_rate``."""
  return jax.tree_util.tree_map(
      lambda _: jnp.asarray(learning_rate, jnp.float32), params)


def gradient_descent_step(params: Any,
                          grads: Any,
                          learning_rate,
                          use_second_order: bool = False) -> Any:
  """One SGD adaptation step over the param tree.

  ``learning_rate`` is a scalar or a tree matching ``params`` (learned
  inner lrs).
  """
  if not use_second_order:
    grads = jax.lax.stop_gradient(grads)
  if isinstance(learning_rate, (int, float)) or (
      hasattr(learning_rate, 'ndim') and learning_rate.ndim == 0):
    return jax.tree_util.tree_map(
        lambda p, g: p - jnp.asarray(learning_rate, p.dtype) *
        g.astype(p.dtype), params, grads)
  # Tree of per-leaf learned learning rates.
  return jax.tree_util.tree_map(
      lambda p, g, lr: p - lr.astype(p.dtype) * g.astype(p.dtype),
      params, grads, learning_rate)


class MAMLInnerLoopGradientDescent:
  """K-step SGD adaptation (maml_inner_loop.py:33-333)."""

  def __init__(self,
               learning_rate: float = 0.001,
               use_second_order: bool = False,
               learn_inner_lr: bool = False):
    self._learning_rate = learning_rate
    self._use_second_order = use_second_order
    self._learn_inner_lr = learn_inner_lr

  @property
  def learn_inner_lr(self) -> bool:
    return self._learn_inner_lr

  def create_lr_params(self, params: Any) -> Optional[Any]:
    if not self._learn_inner_lr:
      return None
    return create_inner_lr_params(params, self._learning_rate)

  def adapt(self,
            params: Any,
            inner_objective: InnerObjective,
            condition_features,
            condition_labels,
            num_steps: int = 1,
            lr_params: Optional[Any] = None) -> Tuple[Any, List[jnp.ndarray]]:
    """Runs ``num_steps`` adaptation steps; returns (adapted, inner losses)."""
    losses = []
    for _ in range(num_steps):
      loss, grads = jax.value_and_grad(inner_objective)(
          params, condition_features, condition_labels)
      losses.append(loss)
      learning_rate = lr_params if lr_params is not None else (
          self._learning_rate)
      params = gradient_descent_step(
          params, grads, learning_rate, self._use_second_order)
    return params, losses

  def inner_loop(self,
                 params: Any,
                 inner_objective: InnerObjective,
                 forward_fn: Callable[[Any, Any], Any],
                 condition_features,
                 condition_labels,
                 inference_features,
                 num_steps: int = 1,
                 lr_params: Optional[Any] = None) -> Dict[str, Any]:
    """Full inner loop (maml_inner_loop.py:218-333).

    Returns per-step condition outputs plus conditioned and unconditioned
    inference outputs.
    """
    outputs: Dict[str, Any] = {}
    outputs['unconditioned_output'] = forward_fn(params, inference_features)
    outputs['condition_outputs'] = [
        forward_fn(params, condition_features)
    ]
    adapted = params
    inner_losses = []
    for step in range(num_steps):
      loss, grads = jax.value_and_grad(inner_objective)(
          adapted, condition_features, condition_labels)
      inner_losses.append(loss)
      learning_rate = lr_params if lr_params is not None else (
          self._learning_rate)
      adapted = gradient_descent_step(
          adapted, grads, learning_rate, self._use_second_order)
      outputs['condition_outputs'].append(forward_fn(adapted,
                                                     condition_features))
    outputs['conditioned_output'] = forward_fn(adapted, inference_features)
    outputs['inner_losses'] = inner_losses
    outputs['adapted_params'] = adapted
    return outputs
