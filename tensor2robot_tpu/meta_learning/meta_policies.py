"""Meta-learning policies: condition on demo episodes, then act.

Capability-equivalent of
``/root/reference/meta_learning/meta_policies.py:32-206``: policies cache
condition episodes via ``adapt(episode_data)`` and feed them alongside the
inference state; the exported MAML model performs the inner-loop
adaptation inside its forward pass.
"""

from __future__ import annotations


import numpy as np

from tensor2robot_tpu.policies import policies


class MetaLearningPolicy(policies.Policy):
  """Adds reset_task/adapt to the policy surface (meta_policies.py:32-43)."""

  def reset_task(self) -> None:
    ...

  def adapt(self, episode_data) -> None:
    ...


class MAMLCEMPolicy(MetaLearningPolicy, policies.CEMPolicy):
  """CEM + MAML adaptation (meta_policies.py:45-99)."""

  def reset_task(self):
    self._prev_episode_data = None

  def adapt(self, episode_data):
    self._prev_episode_data = episode_data

  def SelectAction(self, state, context, timestep):
    if getattr(self, '_prev_episode_data', None):
      prediction_key = 'full_inference_output/q_predicted'
    else:
      prediction_key = 'full_inference_output_unconditioned/q_predicted'

    def objective_fn(samples):
      cem_state = np.tile(
          np.expand_dims(state, 0), [samples.shape[0]] + [1] * state.ndim)
      np_inputs = self._t2r_model.pack_features(
          cem_state, self._prev_episode_data, timestep, samples)
      q_values = self._predictor.predict(np_inputs)[prediction_key]
      if not self._prev_episode_data:
        q_values = q_values * 0
      return np.asarray(q_values).reshape(-1)

    action, _ = self.get_cem_action(objective_fn)
    return action


class MAMLRegressionPolicy(MetaLearningPolicy, policies.RegressionPolicy):
  """Regression + MAML adaptation (meta_policies.py:103-139)."""

  def reset_task(self):
    self._prev_episode_data = None

  def adapt(self, episode_data):
    self._prev_episode_data = episode_data

  def sample_action(self, obs, explore_prob):
    del explore_prob
    action = self.SelectAction(obs, None, None)
    # Replay writers require the is_demo flag when forming MetaExamples.
    return action, {'is_demo': False}

  def SelectAction(self, state, context, timestep):
    np_features = self._t2r_model.pack_features(
        state, getattr(self, '_prev_episode_data', None), timestep)
    action = np.asarray(
        self._predictor.predict(np_features)['full_inference_output/'
                                             'inference_output'])
    if action.ndim == 4:
      return action[0, 0, 0]
    if action.ndim == 3:
      return action[0, 0]
    if action.ndim == 2:
      return action[0]
    raise ValueError(f'Invalid action rank: {action.ndim}')


class FixedLengthSequentialRegressionPolicy(MAMLRegressionPolicy):
  """Buffers recent observations into a fixed-length episode context
  (meta_policies.py:141-170)."""

  def reset_task(self):
    self._prev_episode_data = None

  def adapt(self, episode_data):
    self._prev_episode_data = episode_data

  def reset(self):
    self._obs_buffer = []

  def SelectAction(self, state, context, timestep):
    self._obs_buffer.append(state)
    np_features = self._t2r_model.pack_features(
        self._obs_buffer, getattr(self, '_prev_episode_data', None), timestep)
    action = np.asarray(
        self._predictor.predict(np_features)['full_inference_output/'
                                             'inference_output'])
    return action.reshape(-1, action.shape[-1])[-1]


class ScheduledExplorationMAMLRegressionPolicy(MAMLRegressionPolicy):
  """MAML regression + scheduled gaussian noise (meta_policies.py:172-206)."""

  def __init__(self,
               *args,
               action_size: int = 2,
               stddev_0: float = 0.2,
               slope: float = 0.0,
               **kwargs):
    super().__init__(*args, **kwargs)
    self._noise_action_size = action_size
    self._stddev_0 = stddev_0
    self._slope = slope

  def get_noise(self):
    stddev = max(self._stddev_0 + self.global_step * self._slope, 0.0)
    return stddev * np.random.randn(self._noise_action_size)

  def sample_action(self, obs, explore_prob):
    del explore_prob
    action = self.SelectAction(obs, None, None) + self.get_noise()
    return action, {'is_demo': False}
