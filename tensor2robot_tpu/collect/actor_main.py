"""Actor subprocess entry point.

A separate module from ``collect/actor.py`` so ``python -m`` execution
never re-runs a module the package ``__init__`` already imported (the
runpy double-import warning); the supervisor spawns
``python -m tensor2robot_tpu.collect.actor_main --config-json ...``.
"""

from __future__ import annotations

import sys

from tensor2robot_tpu.collect import actor

if __name__ == '__main__':
  sys.exit(actor.main())
