"""Distributed episode collection: actors, shard commit, supervision.

The collect half of the reference's collect→train→export→collect cycle
(``continuous_collect_eval``, dql_grasping ``run_env``): N actor
processes drive sim envs with the latest *committed* export, write
episodes as atomically-committed tfrecord shards, and an
:class:`~tensor2robot_tpu.collect.actor.ActorSupervisor` keeps the fleet
alive under crashes. The train half is the input engine's follow mode
(``data/follow.py``); ``bin/run_collect_train.py`` wires both into one
supervised loop.
"""

from tensor2robot_tpu.collect.actor import (
    ActorConfig,
    ActorSupervisor,
    EpisodeShardWriter,
    run_actor,
)
from tensor2robot_tpu.collect.episodes import (
    EpisodeStamp,
    encode_feature_map,
    pose_episode_to_transitions,
    read_stamp,
    scan_example,
    stamp_transition,
)
