"""Episode record codec: TF-free tf.Example encode/scan + provenance stamps.

Actors run on robot-class hosts (the serving-host contract: numpy + PIL
+ the native record writer, no TensorFlow wheel), yet the episodes they
write must parse through BOTH training parse paths — the native C++ wire
parser and tf.data — so this module hand-encodes the ``tf.train.Example``
wire format with the stdlib only:

* :func:`encode_feature_map` — ``{key: bytes | floats | ints}`` → one
  serialized Example (packed float/int64 lists, exactly what the TF
  serializer emits).
* :func:`scan_example` — the inverse walk, for inspection tooling
  (``tools/inspect_episodes.py``) on TF-free hosts.
* :func:`stamp_transition` — appends the collecting actor's provenance
  STAMP (actor id, policy version, trace/request ids) to an
  already-serialized transition by protobuf message-merge semantics:
  concatenating two serialized Examples merges their feature maps, so
  stamping never re-encodes the (image-heavy) transition payload.
  Training parsers ignore the stamp keys (spec-driven parse); forensics
  tooling reads them back with :func:`read_stamp`, and the ids join the
  record to the actor's flight events and trace spans
  (``tools/assemble_trace.py --request``).

The stamp keys live under ``collect/`` — reserved: models must not spec
features under that prefix.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, NamedTuple, Optional, Sequence, Tuple, Union

FeatureValue = Union[bytes, Sequence[float], Sequence[int]]

# Stamp feature keys (the ``collect/`` prefix is reserved for provenance).
STAMP_ACTOR_ID = 'collect/actor_id'
STAMP_POLICY_VERSION = 'collect/policy_version'
STAMP_EPISODE_INDEX = 'collect/episode_index'
STAMP_REQUEST_ID = 'collect/request_id'
STAMP_TRACE_ID = 'collect/trace_id'
STAMP_SPAN_ID = 'collect/span_id'
STAMP_TIME = 'collect/time'


def _varint(value: int) -> bytes:
  out = bytearray()
  while True:
    bits = value & 0x7F
    value >>= 7
    if value:
      out.append(bits | 0x80)
    else:
      out.append(bits)
      return bytes(out)


def _len_field(field_number: int, payload: bytes) -> bytes:
  return _varint((field_number << 3) | 2) + _varint(len(payload)) + payload


def _encode_feature(value: FeatureValue) -> bytes:
  """One ``Feature`` message: BytesList(1) / FloatList(2) / Int64List(3)."""
  if isinstance(value, bytes):
    return _len_field(1, _len_field(1, value))
  values = list(value)
  if all(isinstance(v, (int, bool)) and not isinstance(v, float)
         for v in values):
    packed = b''.join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF) for v in values)
    return _len_field(3, _len_field(1, packed))
  packed = struct.pack(f'<{len(values)}f', *[float(v) for v in values])
  return _len_field(2, _len_field(1, packed))


def encode_feature_map(features: Dict[str, FeatureValue]) -> bytes:
  """Serializes ``{key: value}`` as one ``tf.train.Example``.

  ``bytes`` values become a single-element BytesList; int sequences a
  packed Int64List; everything else a packed FloatList — the exact
  wire bytes ``tf.train.Example`` would serialize (pinned against TF in
  the tests), so both training parse paths accept them.
  """
  entries = []
  for key in sorted(features):
    entry = (_len_field(1, key.encode()) +
             _len_field(2, _encode_feature(features[key])))
    entries.append(_len_field(1, entry))
  return _len_field(1, b''.join(entries))


# ----------------------------------------------------------------- scanning


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
  result = shift = 0
  while True:
    byte = data[pos]
    pos += 1
    result |= (byte & 0x7F) << shift
    if not byte & 0x80:
      return result, pos
    shift += 7


def _fields(data: bytes) -> Iterator[Tuple[int, int, Union[int, bytes]]]:
  """Yields ``(field_number, wire_type, value)`` over one message."""
  pos = 0
  while pos < len(data):
    tag, pos = _read_varint(data, pos)
    field, wire = tag >> 3, tag & 7
    if wire == 0:
      value, pos = _read_varint(data, pos)
    elif wire == 2:
      length, pos = _read_varint(data, pos)
      value = data[pos:pos + length]
      pos += length
    elif wire == 5:
      value = data[pos:pos + 4]
      pos += 4
    elif wire == 1:
      value = data[pos:pos + 8]
      pos += 8
    else:
      raise ValueError(f'unsupported wire type {wire} at offset {pos}')
    yield field, wire, value


def _decode_feature(data: bytes) -> Tuple[str, list]:
  kind, values = 'empty', []
  for field, wire, payload in _fields(data):
    if field == 1 and wire == 2:  # BytesList
      kind = 'bytes'
      values.extend(v for f, w, v in _fields(payload) if f == 1 and w == 2)
    elif field == 2 and wire == 2:  # FloatList
      kind = 'float'
      for f, w, v in _fields(payload):
        if f != 1:
          continue
        if w == 2:  # packed
          values.extend(struct.unpack(f'<{len(v) // 4}f', v))
        elif w == 5:
          values.append(struct.unpack('<f', v)[0])
    elif field == 3 and wire == 2:  # Int64List
      kind = 'int64'
      for f, w, v in _fields(payload):
        if f != 1:
          continue
        if w == 2:  # packed varints
          pos = 0
          while pos < len(v):
            value, pos = _read_varint(v, pos)
            values.append(value - (1 << 64) if value >= (1 << 63) else value)
        elif w == 0:
          values.append(v - (1 << 64) if v >= (1 << 63) else v)
  return kind, values


def scan_example(serialized: bytes) -> Dict[str, Tuple[str, list]]:
  """Parses one serialized Example: ``{key: (kind, values)}``.

  Map-merge semantics match protobuf: a key appearing in several
  concatenated fragments (a stamped transition) keeps the LAST
  occurrence, exactly what a proto parser would materialize.
  """
  out: Dict[str, Tuple[str, list]] = {}
  for field, wire, features in _fields(serialized):
    if field != 1 or wire != 2:
      continue
    for f, w, entry in _fields(features):
      if f != 1 or w != 2:
        continue
      key: Optional[str] = None
      feature = b''
      for ef, ew, ev in _fields(entry):
        if ef == 1 and ew == 2:
          key = ev.decode('utf-8', 'replace')
        elif ef == 2 and ew == 2:
          feature = ev
      if key is not None:
        out[key] = _decode_feature(feature)
  return out


# ------------------------------------------------------------------ stamping


class EpisodeStamp(NamedTuple):
  """Provenance of one episode: who collected it, with which policy.

  ``request_id`` is the episode's fleet-unique id (the
  ``assemble_trace --request`` join key); ``trace_id``/``span_id`` are
  the actor's rollout trace coordinates (``observability/tracing.py``
  formats), so a bad gradient traced to a record resolves to the exact
  actor rollout — and through the export generation (``policy_version``
  is the export's global step) to the trainer state that produced it.
  """

  actor_id: int
  policy_version: int
  episode_index: int
  request_id: str
  trace_id: str
  span_id: str
  time: float

  def features(self) -> Dict[str, FeatureValue]:
    return {
        STAMP_ACTOR_ID: [self.actor_id],
        STAMP_POLICY_VERSION: [self.policy_version],
        STAMP_EPISODE_INDEX: [self.episode_index],
        STAMP_REQUEST_ID: self.request_id.encode(),
        STAMP_TRACE_ID: self.trace_id.encode(),
        STAMP_SPAN_ID: self.span_id.encode(),
        # int64 epoch milliseconds: a FloatList is float32 on the wire,
        # whose ~2^7-second granularity at epoch scale is useless.
        STAMP_TIME: [int(self.time * 1000)],
    }


def stamp_transition(serialized: bytes, stamp: EpisodeStamp) -> bytes:
  """Appends the stamp to a serialized transition (proto merge)."""
  return serialized + encode_feature_map(stamp.features())


def read_stamp(serialized: bytes) -> Optional[dict]:
  """The stamp of a record, or None for unstamped records."""
  scanned = scan_example(serialized)
  if STAMP_REQUEST_ID not in scanned:
    return None

  def _one(key, default=None):
    kind_values = scanned.get(key)
    if not kind_values or not kind_values[1]:
      return default
    value = kind_values[1][0]
    return value.decode('utf-8', 'replace') if isinstance(value, bytes) \
        else value

  return {
      'actor_id': int(_one(STAMP_ACTOR_ID, -1)),
      'policy_version': int(_one(STAMP_POLICY_VERSION, -1)),
      'episode_index': int(_one(STAMP_EPISODE_INDEX, -1)),
      'request_id': _one(STAMP_REQUEST_ID, ''),
      'trace_id': _one(STAMP_TRACE_ID, ''),
      'span_id': _one(STAMP_SPAN_ID, ''),
      'time': float(_one(STAMP_TIME, 0)) / 1000.0,
  }


# ------------------------------------------------- pose-env transitions (TF-free)


def pose_episode_to_transitions(episode_data: Sequence[Tuple]) -> List[bytes]:
  """TF-free twin of ``pose_env.episode_to_transitions_pose_toy``.

  Identical record schema (``state/image`` JPEG bytes, ``pose`` [2],
  ``reward`` [1], ``target_pose`` [2]) built with the stdlib encoder, so
  actor hosts never import TensorFlow.
  """
  import numpy as np

  from tensor2robot_tpu.utils import image as image_lib

  transitions = []
  for (obs_t, action, reward, _, _, debug) in episode_data:
    transitions.append(encode_feature_map({
        'state/image': image_lib.numpy_to_image_string(obs_t),
        'pose': [float(v) for v in np.asarray(action).flatten()],
        'reward': [float(reward)],
        'target_pose': [float(v)
                        for v in np.asarray(debug['target_pose']).flatten()],
    }))
  return transitions
