"""Actor processes: env rollouts → atomically committed episode shards.

One actor = one OS process driving a sim env with the latest *committed*
export (``ExportedModelPredictor``: torn exports invisible, failed hot
reloads fall back last-good) and writing stamped episode records as
rolling tfrecord shards under a commit protocol that makes a killed
actor harmless:

1. records append to ``.tmp-<shard>`` (never matched by readers),
2. the file is flushed + fsynced, then atomically renamed to its final
   ``ep-a<actor>-p<pid>-<n>.tfrecord`` name,
3. an ``.idx`` seek sidecar is built opportunistically,
4. the per-shard commit marker ``<shard>.commit`` is published LAST
   (tmp + fsync + rename), carrying the shard's episode manifest
   (request/trace ids, policy versions, rollout span timings).

Follow-mode readers (``data/follow.py``) ingest only marker-carrying
shards, so a SIGKILL anywhere in an actor's life can at worst strand an
invisible ``.tmp`` file or an unmarked shard — never a torn record in
the trainer's stream.

:class:`ActorSupervisor` keeps N such processes alive: crashes restart
under a jittered-backoff :class:`~tensor2robot_tpu.utils.retry.
RetryPolicy` with a per-actor crash budget; a budget-exhausted actor is
declared DEAD loudly (``collect/actors_dead`` gauge + flight event)
instead of respawning forever. Orderly exits — 0 (episode quota) and 42
(graceful preemption) — are never restarted.

Fault hooks (armed by ``utils/faults.py`` injectors inside the actor
process): ``_before_commit_hook`` fires between the shard's final write
and its rename (``KillActorMidEpisode`` SIGKILLs here),
``_suppress_marker_hook`` drops a shard's commit marker
(``TornShardInjector``), ``_hold_export_hook`` pins the reload poller to
a stale generation (``StaleExportInjector``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from tensor2robot_tpu.collect import episodes as episodes_lib
from tensor2robot_tpu.observability import flight
from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.utils import retry as retry_lib

SHARD_PREFIX = 'ep-'
COMMIT_SUFFIX = '.commit'
# Orderly actor exits: completion of an episode quota / graceful
# preemption (train/resilience.PREEMPTED_EXIT_CODE). Anything else is a
# crash the supervisor charges against the actor's budget.
ORDERLY_EXIT_CODES = (0, 42)

# Fault-injection hooks (utils/faults.py arms these IN the actor
# process; None in production). See module docstring.
_before_commit_hook: Optional[Callable[[int], None]] = None
_suppress_marker_hook: Optional[Callable[[int], bool]] = None
_hold_export_hook: Optional[Callable[[int], bool]] = None


def commit_marker_path(shard_path: str) -> str:
  return shard_path + COMMIT_SUFFIX


def _fsync_path(path: str) -> None:
  fd = os.open(path, os.O_RDONLY)
  try:
    os.fsync(fd)
  finally:
    os.close(fd)


def _fsync_dir(path: str) -> None:
  try:
    fd = os.open(path, os.O_RDONLY)
  except OSError:
    return
  try:
    os.fsync(fd)
  except OSError:
    pass  # some filesystems refuse directory fsync; rename is still atomic
  finally:
    os.close(fd)


class EpisodeShardWriter:
  """Rolling episode shards under the atomic commit protocol.

  ``add_episode`` appends one episode's (already stamped) records to the
  current ``.tmp`` shard and rolls/commits every ``episodes_per_shard``
  episodes. Shard names embed the actor id AND pid, so a restarted
  incarnation never collides with its predecessor's files. ``close()``
  commits a partial final shard if it holds at least one full episode —
  episodes are the atomicity unit; a shard never carries half of one.

  **Retention GC** (``max_shards`` / ``max_bytes``): nothing else in the
  collect loop ever deletes episode shards, which makes any long soak an
  unbounded-disk run (ROADMAP direction 1a named this the blocker).
  After every commit the writer prunes ITS OWN oldest committed shards
  past the configured budget, under one hard safety rule: a shard is
  only deletable when it is (a) commit-marked — torn/tmp files are the
  crash-recovery evidence and stay for the forensics tooling — and
  (b) strictly OLDER than the follow-mode sampling window: the newest
  shards jointly covering ``retain_window_records`` records (the
  trainer's ``FollowConfig.window_records``) are always retained, so a
  follow-mode reader restarting or refilling its window can never find
  its sampling range deleted out from under it. The commit marker is
  removed FIRST (the shard becomes invisible to any new reader exactly
  like a torn shard), then the ``.idx`` sidecar and the shard bytes.
  Deletions count ``collect/shards_gced`` (+ a flight event with the
  reclaimed bytes). Budgets are per writer — a fleet's disk budget is
  ``max_bytes × actors``.
  """

  def __init__(self, out_dir: str, actor_id: int,
               episodes_per_shard: int = 8,
               max_shards: Optional[int] = None,
               max_bytes: Optional[int] = None,
               retain_window_records: int = 4096):
    if episodes_per_shard < 1:
      raise ValueError(f'episodes_per_shard must be >= 1, got '
                       f'{episodes_per_shard}')
    if max_shards is not None and max_shards < 1:
      raise ValueError(f'max_shards must be >= 1, got {max_shards}')
    if max_bytes is not None and max_bytes < 1:
      raise ValueError(f'max_bytes must be >= 1, got {max_bytes}')
    os.makedirs(out_dir, exist_ok=True)
    self._out_dir = out_dir
    self._actor_id = int(actor_id)
    self._episodes_per_shard = int(episodes_per_shard)
    self._max_shards = max_shards
    self._max_bytes = max_bytes
    self._retain_window_records = max(0, int(retain_window_records))
    self._shard_ordinal = 0
    self._writer = None
    self._tmp_path: Optional[str] = None
    self._episode_manifest: List[dict] = []
    self._record_count = 0
    self.committed_paths: List[str] = []
    # Parallel to committed_paths: (records, bytes) per committed shard,
    # oldest first — the GC's retention arithmetic.
    self._committed_stats: List[tuple] = []
    self.gced_paths: List[str] = []

  def _shard_name(self) -> str:
    return (f'{SHARD_PREFIX}a{self._actor_id}-p{os.getpid()}-'
            f'{self._shard_ordinal:05d}.tfrecord')

  def _open(self) -> None:
    from tensor2robot_tpu.data import records as records_lib

    name = self._shard_name()
    self._tmp_path = os.path.join(self._out_dir, f'.tmp-{name}')
    self._writer = records_lib.RecordWriter(self._tmp_path)
    self._episode_manifest = []
    self._record_count = 0

  def add_episode(self, records: Sequence[bytes], meta: dict) -> None:
    """Appends one episode (all-or-nothing within the shard)."""
    if self._writer is None:
      self._open()
    for record in records:
      self._writer.write(record)
    self._record_count += len(records)
    self._episode_manifest.append(dict(meta, records=len(records)))
    if len(self._episode_manifest) >= self._episodes_per_shard:
      self._commit()

  def _commit(self) -> None:
    """Publish the current shard: fsync → rename → index → marker."""
    if self._writer is None:
      return
    ordinal = self._shard_ordinal
    final_path = os.path.join(self._out_dir, self._shard_name())
    self._writer.flush()
    self._writer.close()
    self._writer = None
    _fsync_path(self._tmp_path)
    if _before_commit_hook is not None:
      # KillActorMidEpisode fires here: the shard bytes exist only under
      # the .tmp name, so a SIGKILL at this exact point strands an
      # invisible file — the torn-write anatomy the drill asserts.
      _before_commit_hook(ordinal)
    os.replace(self._tmp_path, final_path)
    _fsync_dir(self._out_dir)
    self._tmp_path = None
    self._shard_ordinal += 1
    # Opportunistic seek sidecar (data/shard_index.py): committed shards
    # are immutable, so the index can never go stale; failure only costs
    # deep-position seeks, never correctness.
    try:
      from tensor2robot_tpu.data import shard_index

      shard_index.ensure_index(final_path)
    except Exception as e:  # pylint: disable=broad-except
      logging.warning('Cannot index episode shard %r: %r', final_path, e)
    if _suppress_marker_hook is not None and _suppress_marker_hook(ordinal):
      # TornShardInjector: the shard stays marker-less forever — follow
      # readers must never surface its records.
      flight.event('collect', 'collect/marker_suppressed',
                   f'actor={self._actor_id} shard={ordinal} (injected)')
      return
    marker = {
        'actor_id': self._actor_id,
        'pid': os.getpid(),
        'shard': ordinal,
        'records': self._record_count,
        'time': time.time(),
        'episodes': self._episode_manifest,
    }
    marker_path = commit_marker_path(final_path)
    tmp_marker = marker_path + f'.tmp{os.getpid()}'
    with open(tmp_marker, 'w') as f:
      json.dump(marker, f)
      f.flush()
      os.fsync(f.fileno())
    os.replace(tmp_marker, marker_path)
    _fsync_dir(self._out_dir)
    self.committed_paths.append(final_path)
    try:
      shard_bytes = os.path.getsize(final_path)
    except OSError:
      shard_bytes = 0
    self._committed_stats.append((self._record_count, shard_bytes))
    metrics_lib.counter('collect/shards_committed').inc()
    flight.event(
        'collect', 'collect/shard_committed',
        f'actor={self._actor_id} shard={ordinal} '
        f'records={self._record_count} '
        f'episodes={len(self._episode_manifest)}')
    self._maybe_gc()

  def _maybe_gc(self) -> None:
    """Prunes this writer's oldest committed shards past the budget;
    never touches the follow-window retention suffix (see class doc)."""
    if self._max_shards is None and self._max_bytes is None:
      return
    # Newest shards covering the sampling window are untouchable: walk
    # newest → oldest until the window's record count is covered (the
    # shard that crosses the threshold is retained too).
    protected = 0
    covered = 0
    for records, _ in reversed(self._committed_stats):
      protected += 1
      covered += records
      if covered >= self._retain_window_records:
        break
    deletable = max(0, len(self.committed_paths) - protected)
    total_bytes = sum(b for _, b in self._committed_stats)
    victims = 0
    while victims < deletable:
      over_shards = (self._max_shards is not None and
                     len(self.committed_paths) - victims > self._max_shards)
      over_bytes = (self._max_bytes is not None and
                    total_bytes > self._max_bytes)
      if not over_shards and not over_bytes:
        break
      total_bytes -= self._committed_stats[victims][1]
      victims += 1
    for _ in range(victims):
      path = self.committed_paths.pop(0)
      records, shard_bytes = self._committed_stats.pop(0)
      # Marker first: the shard drops out of every follower's committed
      # set atomically (indistinguishable from torn) before its bytes go.
      for victim in (commit_marker_path(path), path + '.idx', path):
        try:
          os.remove(victim)
        except OSError:
          pass
      self.gced_paths.append(path)
      metrics_lib.counter('collect/shards_gced').inc()
      flight.event(
          'collect', 'collect/shard_gced',
          f'actor={self._actor_id} path={os.path.basename(path)} '
          f'records={records} bytes={shard_bytes}')
    if victims:
      _fsync_dir(self._out_dir)

  def close(self) -> None:
    """Commits a non-empty partial shard; abandons an empty tmp file."""
    if self._writer is None:
      return
    if self._episode_manifest:
      self._commit()
      return
    self._writer.close()
    self._writer = None
    if self._tmp_path and os.path.exists(self._tmp_path):
      os.remove(self._tmp_path)
    self._tmp_path = None


# ------------------------------------------------------------- actor process


@dataclasses.dataclass
class ActorConfig:
  """One actor process's wiring (JSON-serializable across the spawn)."""

  actor_id: int
  export_root: str
  out_dir: str
  episodes_per_shard: int = 8
  # Shard retention GC (see EpisodeShardWriter): budgets for THIS
  # actor's committed shards; None = keep everything (the historical
  # behavior — fine for drills, unbounded disk for soaks). Only
  # commit-marked shards strictly older than the newest
  # retain_window_records records are ever deleted, so the trainer's
  # follow-mode sampling window (FollowConfig.window_records — keep
  # these two in agreement) always survives.
  max_shards: Optional[int] = None
  max_bytes: Optional[int] = None
  retain_window_records: int = 4096
  max_episodes: Optional[int] = None  # None = run until SIGTERM
  reload_interval_secs: float = 1.0
  restore_timeout_secs: float = 60.0
  seed: int = 0
  # Dotted-path env factory + kwargs; the default is the pose toy env.
  env_class: str = 'tensor2robot_tpu.research.pose_env.pose_env.PoseToyEnv'
  env_kwargs: Optional[dict] = None
  # Dotted-path episode→records fn (the TF-free pose encoder by default).
  transitions_fn: str = ('tensor2robot_tpu.collect.episodes.'
                         'pose_episode_to_transitions')
  # Gaussian exploration noise added to the policy action (clipped to
  # [-1, 1]). Wide by default: the reference bootstraps its loop from a
  # UNIFORM random collect, and narrow noise around an untrained
  # policy's near-zero output concentrates the reward-weighted loss on
  # near-origin targets (measured: σ=0.3 data plateaus at reward −0.39
  # where σ=0.8 reaches −0.24 in the same 300 steps).
  explore_stddev: float = 0.8
  # Pacing between episodes: a sim env rolls out orders of magnitude
  # faster than a robot; the throttle keeps drill fleets from burying
  # the trainer in thousands of tiny shards (0 = flat out).
  episode_interval_secs: float = 0.0
  # utils/faults.py injector specs applied INSIDE the actor process,
  # e.g. ['kill_before_commit:1', 'torn_shard:2', 'hold_export:4'].
  faults: Optional[List[str]] = None

  def to_json(self) -> str:
    return json.dumps(dataclasses.asdict(self))

  @classmethod
  def from_json(cls, text: str) -> 'ActorConfig':
    return cls(**json.loads(text))


def _import_dotted(path: str):
  import importlib

  module_name, _, attr = path.rpartition('.')
  return getattr(importlib.import_module(module_name), attr)


def run_actor(config: ActorConfig) -> int:
  """One actor's life; returns the process exit code (0 / 42).

  reload-poll → rollout episode → stamp → shard write, until the episode
  quota or a graceful-shutdown request. SIGTERM mid-episode ABANDONS the
  in-flight episode (nothing of it is written — episodes are atomic),
  commits the current shard's completed episodes, and exits 42.
  """
  import numpy as np

  from tensor2robot_tpu.observability import tracing
  from tensor2robot_tpu.train import resilience

  if config.faults:
    from tensor2robot_tpu.utils import faults as faults_lib

    for spec in config.faults:
      faults_lib.apply_actor_fault(spec, config)

  tracing.set_service(f'actor{config.actor_id}')
  shutdown = resilience.install_graceful_shutdown()
  rng = np.random.RandomState(config.seed)
  env = _import_dotted(config.env_class)(**(config.env_kwargs or {}))
  transitions_fn = _import_dotted(config.transitions_fn)

  from tensor2robot_tpu.export import exporters as exporters_lib
  from tensor2robot_tpu.policies import RegressionPolicy
  from tensor2robot_tpu.predictors import ExportedModelPredictor

  predictor = ExportedModelPredictor(
      config.export_root, timeout=config.restore_timeout_secs)
  if not predictor.restore():
    raise RuntimeError(
        f'actor {config.actor_id}: no committed export appeared under '
        f'{config.export_root!r} within {config.restore_timeout_secs}s')
  model = exporters_lib.load_model_from_export_dir(predictor.model_path)
  policy = RegressionPolicy(t2r_model=model, predictor=predictor)

  writer = EpisodeShardWriter(config.out_dir, config.actor_id,
                              config.episodes_per_shard,
                              max_shards=config.max_shards,
                              max_bytes=config.max_bytes,
                              retain_window_records=(
                                  config.retain_window_records))
  episodes_counter = metrics_lib.counter('collect/episodes')
  reward_hist = metrics_lib.histogram('collect/episode_reward')
  version_gauge = metrics_lib.gauge('collect/policy_version')
  last_reload = time.monotonic()
  episode_index = 0
  preempted = False
  logging.info('actor %d: serving export step %d from %r', config.actor_id,
               predictor.global_step, predictor.model_path)
  while config.max_episodes is None or episode_index < config.max_episodes:
    if shutdown.requested:
      preempted = True
      break
    now = time.monotonic()
    if now - last_reload >= config.reload_interval_secs:
      last_reload = now
      if _hold_export_hook is not None and _hold_export_hook(episode_index):
        metrics_lib.counter('collect/export_reloads_held').inc()
      else:
        before = predictor.global_step
        predictor.restore()  # last-good fallback + torn-skip built in
        if predictor.global_step != before:
          model = exporters_lib.load_model_from_export_dir(
              predictor.model_path)
          policy = RegressionPolicy(t2r_model=model, predictor=predictor)
          metrics_lib.counter('collect/policy_reloads').inc()
          flight.event(
              'collect', 'collect/policy_reloaded',
              f'actor={config.actor_id} version={predictor.global_step}')
    version = int(predictor.global_step)
    version_gauge.set(version)
    trace_id, span_id = tracing.mint_trace_id(), tracing.mint_span_id()
    request_id = f'ep-a{config.actor_id}-p{os.getpid()}-{episode_index}'
    t_start = time.time()
    episode_data, abandoned = _rollout(
        env, policy, rng, config.explore_stddev, shutdown)
    new_task = getattr(env, 'set_new_pose', None)
    if new_task is not None:
      new_task()  # pose env: episodes are single-step; vary the target
    if abandoned:
      # Finish-or-abandon contract: a shutdown observed mid-episode
      # abandons the incomplete rollout — no partial episode is written.
      flight.event('collect', 'collect/episode_abandoned',
                   f'actor={config.actor_id} episode={episode_index}')
      preempted = True
      break
    t_end = time.time()
    stamp = episodes_lib.EpisodeStamp(
        actor_id=config.actor_id, policy_version=version,
        episode_index=episode_index, request_id=request_id,
        trace_id=trace_id, span_id=span_id, time=t_start)
    records = [episodes_lib.stamp_transition(r, stamp)
               for r in transitions_fn(episode_data)]
    reward = float(sum(step[2] for step in episode_data))
    writer.add_episode(records, {
        'request_id': request_id,
        'trace_id': trace_id,
        'span_id': span_id,
        'policy_version': version,
        'start': t_start,
        'end': t_end,
        'reward': reward,
        'service': f'actor{config.actor_id}',
    })
    episodes_counter.inc()
    reward_hist.observe(reward)
    episode_index += 1
    if config.episode_interval_secs > 0:
      # Interruptible pacing: a SIGTERM during the sleep still exits
      # within one episode interval.
      shutdown_event = getattr(shutdown, '_event', None)
      if shutdown_event is not None:
        shutdown_event.wait(config.episode_interval_secs)
      else:
        time.sleep(config.episode_interval_secs)
  writer.close()
  predictor.close()
  env.close()
  if preempted:
    logging.warning(
        'actor %d: graceful shutdown after %d episode(s); exiting 42.',
        config.actor_id, episode_index)
    return resilience.PREEMPTED_EXIT_CODE
  logging.info('actor %d: completed %d episode(s).', config.actor_id,
               episode_index)
  return 0


def _rollout(env, policy, rng, explore_stddev: float, shutdown):
  """One episode; returns ``(episode_data, abandoned)``."""
  import numpy as np

  episode_data = []
  policy.reset()
  obs = env.reset()
  if isinstance(obs, tuple) and len(obs) == 2:
    obs = obs[0]  # gymnasium returns (obs, info)
  done = False
  while not done:
    if shutdown.requested:
      return episode_data, True
    action = np.asarray(policy.SelectAction(obs, None, None), np.float32)
    if explore_stddev:
      action = np.clip(
          action + rng.normal(0.0, explore_stddev, action.shape).astype(
              np.float32), -1.0, 1.0)
    result = env.step(action)
    if len(result) == 5:  # gymnasium
      new_obs, reward, terminated, truncated, debug = result
      done = bool(terminated or truncated)
    else:
      new_obs, reward, done, debug = result
    episode_data.append((obs, action, reward, new_obs, done, debug))
    obs = new_obs
  return episode_data, False


# --------------------------------------------------------------- supervision


class _ActorSlot:
  """One supervised actor's lifecycle state (all GUARDED_BY the
  supervisor lock)."""

  def __init__(self, name: str, argv: List[str]):
    self.name = name
    self.argv = argv
    self.proc: Optional[subprocess.Popen] = None
    self.crashes = 0
    self.restarts = 0
    self.dead = False
    self.retired = False  # retire_actor(): any exit is orderly, no respawn
    self.exit_code: Optional[int] = None  # last observed exit
    self.respawn_at: Optional[float] = None  # monotonic deadline

  @property
  def running(self) -> bool:
    return self.proc is not None and self.proc.poll() is None


class ActorSupervisor:
  """Restarts crashed actors under a backoff policy and a crash budget.

  ``commands`` maps a display name to the argv that (re)spawns the
  actor; :meth:`for_configs` builds them for :class:`ActorConfig`
  fleets. :meth:`poll` advances the state machine one tick (the monitor
  thread calls it on a cadence; tests may drive it manually):

  * orderly exit (0 / 42) → slot retires, never respawned;
  * crash → ``collect/actor_crashes``, flight event, and — within the
    per-actor ``crash_budget`` — a respawn scheduled after the
    RetryPolicy's jittered backoff (``collect/actor_restarts``);
  * budget exhausted → the actor is DEAD: ``collect/actors_dead`` rises,
    a loud flight event + log records the verdict, and the slot never
    respawns — a crash-looping actor degrades the fleet loudly instead
    of spinning forever.
  """

  def __init__(self,
               commands: Dict[str, List[str]],
               crash_budget: int = 3,
               backoff: Optional[retry_lib.RetryPolicy] = None,
               env: Optional[Dict[str, str]] = None):
    self._lock = threading.Lock()
    self._slots = {name: _ActorSlot(name, list(argv))
                   for name, argv in commands.items()}  # GUARDED_BY(self._lock)
    self._crash_budget = int(crash_budget)
    self._backoff = backoff or retry_lib.RetryPolicy(
        max_attempts=crash_budget + 1, base_delay=0.25, max_delay=10.0)
    self._env = dict(env) if env is not None else None
    self._monitor: Optional[threading.Thread] = None
    self._stop_monitor = threading.Event()
    self._stopping = False  # GUARDED_BY(self._lock)
    self._dead_gauge = metrics_lib.gauge('collect/actors_dead')
    self._alive_gauge = metrics_lib.gauge('collect/actors_alive')

  @classmethod
  def for_configs(cls, configs: Sequence[ActorConfig],
                  **kwargs) -> 'ActorSupervisor':
    commands = {
        f'actor{c.actor_id}': [
            sys.executable, '-m', 'tensor2robot_tpu.collect.actor_main',
            '--config-json', c.to_json(),
        ]
        for c in configs
    }
    return cls(commands, **kwargs)

  def start(self) -> None:
    with self._lock:
      for slot in self._slots.values():
        if slot.proc is None and not slot.dead:
          self._spawn(slot)
    self._publish()

  def _spawn(self, slot: _ActorSlot) -> None:
    """GUARDED_BY(self._lock) — callers hold the supervisor lock."""
    slot.proc = subprocess.Popen(slot.argv, env=self._env)
    slot.respawn_at = None
    flight.event('collect', 'collect/actor_spawned',
                 f'name={slot.name} pid={slot.proc.pid} '
                 f'restarts={slot.restarts}')

  def poll(self) -> None:
    """One supervision tick: reap exits, schedule/execute respawns."""
    now = time.monotonic()
    with self._lock:
      for slot in self._slots.values():
        if slot.dead:
          continue
        if slot.proc is not None:
          rc = slot.proc.poll()
          if rc is None:
            continue
          slot.proc = None
          slot.exit_code = rc
          if rc in ORDERLY_EXIT_CODES or slot.retired:
            flight.event('collect', 'collect/actor_exit',
                         f'name={slot.name} code={rc} orderly=1')
            continue
          slot.crashes += 1
          metrics_lib.counter('collect/actor_crashes').inc()
          flight.event(
              'collect', 'collect/actor_crashed',
              f'name={slot.name} code={rc} crashes={slot.crashes}/'
              f'{self._crash_budget}')
          logging.warning('Actor %s crashed (exit %s), crash %d/%d.',
                          slot.name, rc, slot.crashes, self._crash_budget)
          if slot.crashes > self._crash_budget:
            slot.dead = True
            flight.event(
                'collect', 'collect/actor_dead',
                f'name={slot.name} crashes={slot.crashes} verdict=DEAD')
            logging.error(
                'Actor %s is DEAD: %d crash(es) exceeded the budget of %d; '
                'not respawning. The fleet continues degraded.',
                slot.name, slot.crashes, self._crash_budget)
            continue
          if self._stopping:
            # Shutdown race: an actor SIGTERMed during its interpreter
            # startup (no handler installed yet) dies with a crash code.
            # Respawning it here would hand wait() a fresh process that
            # was never signaled — a guaranteed straggler.
            continue
          delay = self._backoff.delay(slot.crashes - 1)
          slot.respawn_at = now + delay
          logging.warning('Actor %s respawns in %.2fs.', slot.name, delay)
        elif slot.respawn_at is not None and now >= slot.respawn_at:
          slot.restarts += 1
          metrics_lib.counter('collect/actor_restarts').inc()
          self._spawn(slot)
    self._publish()

  def _publish(self) -> None:
    with self._lock:
      dead = sum(1 for s in self._slots.values() if s.dead)
      alive = sum(1 for s in self._slots.values() if s.running)
    self._dead_gauge.set(dead)
    self._alive_gauge.set(alive)

  def start_monitor(self, interval_secs: float = 0.25) -> None:
    """Runs :meth:`poll` on a daemon thread until :meth:`stop`."""
    if self._monitor is not None:
      return

    def loop():
      while not self._stop_monitor.wait(interval_secs):
        self.poll()

    self._stop_monitor.clear()
    self._monitor = threading.Thread(
        target=loop, name='actor-supervisor', daemon=True)
    self._monitor.start()

  def request_stop(self, sig: int = signal.SIGTERM) -> None:
    """Fans the shutdown signal out to every live actor."""
    with self._lock:
      self._stopping = True  # the monitor must not respawn from here on
      for slot in self._slots.values():
        slot.respawn_at = None  # a stopping fleet schedules no respawns
        if slot.running:
          try:
            slot.proc.send_signal(sig)
          except OSError:
            pass
    flight.event('collect', 'collect/stop_requested', f'signal={sig}')

  def wait(self, timeout_secs: float = 30.0,
           kill_after_timeout: bool = True) -> Dict[str, Optional[int]]:
    """Waits for every actor to exit; SIGKILLs stragglers past the
    deadline. Returns ``{name: exit_code}`` (None = still running)."""
    deadline = time.monotonic() + timeout_secs
    with self._lock:
      slots = list(self._slots.values())
    for slot in slots:
      with self._lock:
        proc = slot.proc
      if proc is None:
        continue
      remaining = max(0.0, deadline - time.monotonic())
      try:
        rc = proc.wait(timeout=remaining)
      except subprocess.TimeoutExpired:
        if not kill_after_timeout:
          continue
        logging.error('Actor %s ignored shutdown for %.1fs; SIGKILL.',
                      slot.name, timeout_secs)
        proc.kill()
        rc = proc.wait()
      with self._lock:
        slot.exit_code = rc
        slot.proc = None
    self.stop_monitor()
    self._publish()
    return self.exit_codes()

  def stop_monitor(self) -> None:
    if self._monitor is not None:
      self._stop_monitor.set()
      self._monitor.join(timeout=5.0)
      self._monitor = None

  def exit_codes(self) -> Dict[str, Optional[int]]:
    with self._lock:
      return {name: slot.exit_code for name, slot in self._slots.items()}

  def stats(self) -> Dict[str, dict]:
    with self._lock:
      return {
          name: {
              'running': slot.running, 'crashes': slot.crashes,
              'restarts': slot.restarts, 'dead': slot.dead,
              'exit_code': slot.exit_code,
          } for name, slot in self._slots.items()
      }

  def any_alive(self) -> bool:
    with self._lock:
      return any(s.running for s in self._slots.values())

  def any_dead(self) -> bool:
    with self._lock:
      return any(s.dead for s in self._slots.values())

  def alive_count(self) -> int:
    with self._lock:
      return sum(1 for s in self._slots.values() if s.running)

  def add_actor(self, name: str, argv: List[str]) -> bool:
    """Registers and spawns a new actor at runtime (the actor-fleet
    autoscaler's grow/replace surface). False if ``name`` is taken."""
    with self._lock:
      if name in self._slots:
        return False
      slot = _ActorSlot(name, list(argv))
      self._slots[name] = slot
      self._spawn(slot)
    metrics_lib.counter('collect/actors_added').inc()
    self._publish()
    return True

  def retire_actor(self, name: Optional[str] = None,
                   sig: int = signal.SIGTERM) -> Optional[str]:
    """Gracefully removes one actor from the fleet (scale-down).

    Picks ``name``, or the most recently added running actor when None.
    The slot is marked retired — its exit is orderly whatever the code,
    and it never respawns. Returns the retired name, or None when no
    actor was eligible.
    """
    with self._lock:
      slot = None
      if name is not None:
        candidate = self._slots.get(name)
        if candidate is not None and not candidate.dead \
            and not candidate.retired:
          slot = candidate
      else:
        running = [s for s in self._slots.values()
                   if s.running and not s.retired]
        if running:
          slot = running[-1]
      if slot is None:
        return None
      slot.retired = True
      slot.respawn_at = None
      proc = slot.proc
    if proc is not None and proc.poll() is None:
      try:
        proc.send_signal(sig)
      except OSError:
        pass
    metrics_lib.counter('collect/actors_retired').inc()
    flight.event('collect', 'collect/actor_retired',
                 f'name={slot.name} signal={sig}')
    logging.info('Actor %s retired from the fleet.', slot.name)
    self._publish()
    return slot.name


def main(argv: Optional[List[str]] = None) -> int:
  """Actor subprocess entry (spawned via ``collect/actor_main.py``)."""
  import argparse

  parser = argparse.ArgumentParser(description='episode-collecting actor')
  parser.add_argument('--config-json', required=True,
                      help='ActorConfig as a JSON object.')
  args = parser.parse_args(argv)
  logging.basicConfig(level=logging.INFO)
  return run_actor(ActorConfig.from_json(args.config_json))


if __name__ == '__main__':
  sys.exit(main())
