"""Post-training weight-only quantization (int8 / fp8) for serving."""

from tensor2robot_tpu.quantize.quantization import (FP8, INT8, MODES, OFF,
                                                    ParityReport,
                                                    QuantizedTensor,
                                                    cast_tree_bytes,
                                                    check_parity,
                                                    dequantize_array,
                                                    dequantize_params,
                                                    fp8_supported,
                                                    param_bytes,
                                                    quantize_array,
                                                    quantize_params,
                                                    quantize_serving_fn,
                                                    quantized_leaf_count,
                                                    should_quantize)

__all__ = [
    'FP8', 'INT8', 'MODES', 'OFF', 'ParityReport', 'QuantizedTensor',
    'cast_tree_bytes', 'check_parity', 'dequantize_array',
    'dequantize_params', 'fp8_supported', 'param_bytes', 'quantize_array',
    'quantize_params', 'quantize_serving_fn', 'quantized_leaf_count',
    'should_quantize',
]
