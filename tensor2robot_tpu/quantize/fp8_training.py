"""fp8 matmul training: delayed-amax-scaled quantize-dequantize injection.

PR 7's quantization machinery (quantize/quantization.py) is weight-only
and serving-side; this module extends it to TRAINING — the only lever
that moves the 22% MFU ceiling itself (ROADMAP direction 3): the chip's
low-precision MXU path runs at 2× the bf16 rate, and the matmul
contractions of Dense/Conv are where the step's FLOPs live.

Mechanism — the XLA-sanctioned Q-DQ pattern (what hardware fp8 training
stacks emit): each contraction operand is quantized to ``float8_e4m3fn``
and immediately dequantized back to the compute dtype *inside the jitted
program*. Numerically the operands now hold exactly the values an fp8
matmul would see (fp8-rounded, f32-accumulated); structurally the
``dq(q(x)) · dq(q(w))`` chain around a dot/conv is the pattern XLA's
fp8 rewriter folds into a native low-precision MXU matmul where the
hardware has one — and computes faithfully (paying the rounding, not
the speed) everywhere else, which is what makes the CPU parity drills
meaningful. Backward: the incoming cotangent is quantized to
``float8_e5m2`` (gradients need range, not mantissa) for the two grad
contractions, and the resulting gradients leave the op in the full
compute dtype — **unscaled f32/bf16 before any accumulation**, so
grad-accum carries, the nonfinite guard, and the optimizer see ordinary
gradients and the **master weights stay f32 in the optimizer state** by
construction (params are never cast).

Scaling is per-tensor DELAYED amax: each operand keeps a short amax
history window in a dedicated ``'fp8_stats'`` flax collection (riding
the same model_state plumbing as BatchNorm statistics — mutated in
train steps, frozen in eval/serving); the quantization scale for step N
comes from the window maximum over steps < N, so no same-step
host/device sync ever serializes the matmul. The gradient qdq uses the
current tensor's amax computed in the backward itself (cotangents have
no forward-time history to consult; one reduction, stateless).

Entry points: :class:`Fp8DotGeneral` drops into ``nn.Dense(
dot_general_cls=...)``, :class:`Fp8ConvGeneralDilated` into ``nn.Conv(
conv_general_dilated_cls=...)``, and :func:`conv_quantize_fn` hooks the
Pallas :class:`~tensor2robot_tpu.ops.conv_s2d.SpaceToDepthConv` so the
s2d kernel and fp8 compose. Models thread ``matmul_precision``
(``'bf16' | 'fp8'``, validated here) the same way they thread
``remat_policy``; ``TrainerConfig.matmul_precision`` overrides it at
trainer construction, gated by :func:`quantization.fp8_supported`.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu.quantize.quantization import fp8_supported

MATMUL_BF16 = 'bf16'
MATMUL_FP8 = 'fp8'
MATMUL_PRECISIONS = (MATMUL_BF16, MATMUL_FP8)

# e4m3fn / e5m2 finite maxima (ml_dtypes.finfo); casts past them land on
# NaN (e4m3fn has no inf), hence the explicit clamp in _qdq.
E4M3_MAX = 448.0
E5M2_MAX = 57344.0

# Delayed-scaling history window (steps). Short on purpose: robot-
# learning activation scales move with the data distribution; a long
# window holds stale amaxes and over-quantizes after a scale drop.
DEFAULT_HISTORY_LENGTH = 16

FP8_STATS_COLLECTION = 'fp8_stats'


def validate_matmul_precision(precision: Optional[str]) -> str:
  """Normalizes/validates a matmul-precision name (None → 'bf16')."""
  precision = MATMUL_BF16 if precision is None else str(precision)
  if precision not in MATMUL_PRECISIONS:
    raise ValueError(
        f'Unknown matmul_precision {precision!r}; expected one of '
        f'{MATMUL_PRECISIONS}.')
  return precision


def _fp8_max(dtype) -> float:
  return E5M2_MAX if dtype == jnp.float8_e5m2 else E4M3_MAX


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def quantize_dequantize(x, scale, dtype):
  """``dq(q(x))`` with a straight-through gradient.

  The value path rounds ``x`` through ``dtype`` (clamped to its finite
  range at the given per-tensor ``scale``); the cotangent passes through
  untouched — quantization error is treated as noise, the standard fp8
  recipe (rounding the rounding's gradient would double-count it).
  """
  compute_dtype = x.dtype
  bound = _fp8_max(dtype)
  scaled = x.astype(jnp.float32) / scale
  scaled = jnp.clip(scaled, -bound, bound)
  return (scaled.astype(dtype).astype(jnp.float32) * scale).astype(
      compute_dtype)


def _qdq_fwd(x, scale, dtype):
  return quantize_dequantize(x, scale, dtype), jnp.shape(scale)


def _qdq_bwd(dtype, scale_shape, g):
  del dtype
  return g, jnp.zeros(scale_shape, jnp.float32)


quantize_dequantize.defvjp(_qdq_fwd, _qdq_bwd)


def amax_scale(amax, dtype) -> jnp.ndarray:
  """amax → quantization scale mapping the tensor onto the dtype's
  finite range; an empty history (amax 0) keeps scale 1."""
  amax = jnp.asarray(amax, jnp.float32)
  return jnp.where(amax > 0.0, amax / _fp8_max(dtype), 1.0)


def qdq_current(x, dtype) -> jnp.ndarray:
  """Stateless qdq from the CURRENT tensor's amax (the cotangent path)."""
  amax = jnp.max(jnp.abs(x)).astype(jnp.float32)
  return quantize_dequantize(x, amax_scale(amax, dtype), dtype)


class _DelayedAmax(nn.Module):
  """One operand's delayed-scaling state: qdq by the history max from
  PREVIOUS steps, then roll the current amax into the window (only when
  the 'fp8_stats' collection is mutable — train steps; eval/serving and
  abstract init leave it frozen)."""

  history_length: int = DEFAULT_HISTORY_LENGTH
  dtype: Any = jnp.float8_e4m3fn

  @nn.compact
  def __call__(self, x):
    hist = self.variable(
        FP8_STATS_COLLECTION, 'amax_history',
        lambda: jnp.zeros((self.history_length,), jnp.float32))
    scale = amax_scale(jnp.max(hist.value), self.dtype)
    y = quantize_dequantize(x, scale, self.dtype)
    if not self.is_initializing() and self.is_mutable_collection(
        FP8_STATS_COLLECTION):
      current = jnp.max(jnp.abs(x)).astype(jnp.float32)
      hist.value = jnp.concatenate([hist.value[1:], current[None]])
    return y


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _fp8_dot(lhs, rhs, dimension_numbers, precision):
  return jax.lax.dot_general(lhs, rhs, dimension_numbers,
                             precision=precision)


def _fp8_dot_fwd(lhs, rhs, dimension_numbers, precision):
  out = jax.lax.dot_general(lhs, rhs, dimension_numbers,
                            precision=precision)
  return out, (lhs, rhs)


def _fp8_dot_bwd(dimension_numbers, precision, res, g):
  """Grad contractions with the cotangent qdq'd to e5m2 — the operands
  saved in residuals are ALREADY fp8-rounded (qdq'd before the dot), so
  both grad matmuls run on fp8-valued tensors; outputs stay in the
  compute dtype, unscaled, ready for f32 accumulation."""
  lhs, rhs = res
  gq = qdq_current(g, jnp.float8_e5m2)

  def forward(a, b):
    return jax.lax.dot_general(a, b, dimension_numbers,
                               precision=precision)

  _, vjp = jax.vjp(forward, lhs, rhs)
  return vjp(gq)


_fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


class Fp8DotGeneral(nn.Module):
  """``nn.Dense(dot_general_cls=Fp8DotGeneral)`` — the Dense injection.

  Signature matches what flax's Dense calls: ``(lhs, rhs,
  dimension_numbers, precision=None)``.
  """

  history_length: int = DEFAULT_HISTORY_LENGTH

  @nn.compact
  def __call__(self, lhs, rhs, dimension_numbers, precision=None,
               preferred_element_type=None):
    del preferred_element_type  # compute dtype already chosen by Dense
    lhs = _DelayedAmax(self.history_length, name='lhs')(lhs)
    rhs = _DelayedAmax(self.history_length, name='rhs')(rhs)
    return _fp8_dot(lhs, rhs, dimension_numbers, precision)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _fp8_conv(lhs, rhs, window_strides, padding, lhs_dilation,
              rhs_dilation, dimension_numbers, feature_group_count):
  return jax.lax.conv_general_dilated(
      lhs, rhs, window_strides, padding, lhs_dilation=lhs_dilation,
      rhs_dilation=rhs_dilation, dimension_numbers=dimension_numbers,
      feature_group_count=feature_group_count)


def _fp8_conv_fwd(lhs, rhs, window_strides, padding, lhs_dilation,
                  rhs_dilation, dimension_numbers, feature_group_count):
  out = _fp8_conv(lhs, rhs, window_strides, padding, lhs_dilation,
                  rhs_dilation, dimension_numbers, feature_group_count)
  return out, (lhs, rhs)


def _fp8_conv_bwd(window_strides, padding, lhs_dilation, rhs_dilation,
                  dimension_numbers, feature_group_count, res, g):
  lhs, rhs = res
  gq = qdq_current(g, jnp.float8_e5m2)

  def forward(a, b):
    return jax.lax.conv_general_dilated(
        a, b, window_strides, padding, lhs_dilation=lhs_dilation,
        rhs_dilation=rhs_dilation, dimension_numbers=dimension_numbers,
        feature_group_count=feature_group_count)

  _, vjp = jax.vjp(forward, lhs, rhs)
  return vjp(gq)


_fp8_conv.defvjp(_fp8_conv_fwd, _fp8_conv_bwd)


class Fp8ConvGeneralDilated(nn.Module):
  """``nn.Conv(conv_general_dilated_cls=Fp8ConvGeneralDilated)`` — the
  Conv injection; signature matches flax's internal call."""

  history_length: int = DEFAULT_HISTORY_LENGTH

  @nn.compact
  def __call__(self, lhs, rhs, window_strides, padding, lhs_dilation=None,
               rhs_dilation=None, dimension_numbers=None,
               feature_group_count=1, precision=None):
    del precision  # fp8 rounding supersedes the XLA precision enum
    lhs = _DelayedAmax(self.history_length, name='lhs')(lhs)
    rhs = _DelayedAmax(self.history_length, name='rhs')(rhs)
    if not isinstance(padding, str):
      # custom_vjp nondiff args must hash; flax hands pads as a list.
      padding = tuple((int(lo), int(hi)) for lo, hi in padding)
    return _fp8_conv(
        lhs, rhs, tuple(window_strides), padding,
        tuple(lhs_dilation or (1,) * (lhs.ndim - 2)),
        tuple(rhs_dilation or (1,) * (lhs.ndim - 2)),
        dimension_numbers, feature_group_count)


class _ConvOperandQdq(nn.Module):
  """(x, kernel) → qdq'd pair: the SpaceToDepthConv.quantize_fn hook."""

  history_length: int = DEFAULT_HISTORY_LENGTH

  @nn.compact
  def __call__(self, x, kernel):
    x = _DelayedAmax(self.history_length, name='lhs')(x)
    kernel = _DelayedAmax(self.history_length, name='rhs')(kernel)
    return x, kernel


def dense_kwargs(matmul_precision: Optional[str],
                 history_length: int = DEFAULT_HISTORY_LENGTH) -> dict:
  """kwargs to splat into an ``nn.Dense`` for the given precision —
  ``{}`` for bf16 so call sites apply it unconditionally."""
  if validate_matmul_precision(matmul_precision) != MATMUL_FP8:
    return {}
  return {'dot_general_cls': functools.partial(
      Fp8DotGeneral, history_length=history_length)}


def conv_kwargs(matmul_precision: Optional[str],
                history_length: int = DEFAULT_HISTORY_LENGTH) -> dict:
  """kwargs to splat into an ``nn.Conv`` for the given precision."""
  if validate_matmul_precision(matmul_precision) != MATMUL_FP8:
    return {}
  return {'conv_general_dilated_cls': functools.partial(
      Fp8ConvGeneralDilated, history_length=history_length)}


def conv_quantize_cls(matmul_precision: Optional[str],
                      history_length: int = DEFAULT_HISTORY_LENGTH):
  """``quantize_cls`` factory for :class:`ops.conv_s2d.SpaceToDepthConv`
  (None for bf16): the conv constructs it inside its own compact scope,
  the ``dot_general_cls`` idiom, so the amax state lands under the conv
  module."""
  if validate_matmul_precision(matmul_precision) != MATMUL_FP8:
    return None
  return functools.partial(_ConvOperandQdq, history_length=history_length)


def require_fp8_support(precision: Optional[str]) -> str:
  """Validates and additionally gates 'fp8' on the jaxlib's dtype
  support (the same ``fp8_supported()`` gate the serving plane uses)."""
  precision = validate_matmul_precision(precision)
  if precision == MATMUL_FP8 and not fp8_supported():
    raise ValueError(
        "matmul_precision='fp8' requested but this jaxlib/ml_dtypes "
        'build does not support float8_e4m3fn')
  return precision
