"""Post-training weight-only quantization for the serving plane.

The serving economics (PERF_NOTES r6, BENCH_r05): a batch-1 predict on
the robot-scale critics is **weight-streaming-bound** — the dispatch
cost is the bytes of parameters read from HBM, not the FLOPs — so a
batch-64 dispatch costs about what batch-1 does. Quantizing the weight
tree to int8 (or ``float8_e4m3fn``) quarters/halves the bytes streamed
per dispatch, which is exactly the serving plane's bottleneck; on v5e
the int8 MXU peak is additionally 2× bf16. Training parity is never
touched: quantization happens at serving-fn construction time, after
the checkpoint/export is loaded.

Design:

* **Weight-only, activations stay bf16/f32.** A quantized leaf is a
  :class:`QuantizedTensor` — ``(qvalue, scale)`` where ``qvalue`` is the
  int8/fp8 payload and ``scale`` the per-output-channel symmetric scale
  (last axis of the weight: flax kernels are ``(in, out)`` /
  ``(h, w, in, out)``). The serving fn is wrapped so the dequantize
  ``qvalue.astype(f32) * scale`` happens INLINE in the jitted program:
  XLA streams int8 bytes from HBM and upcasts in registers, fusing the
  multiply into the consumer matmul.
* **Skip-list for quantization-sensitive leaves.** BatchNorm statistics
  (``batch_stats`` collection), biases, norm scales and any other
  sub-2D leaf stay full precision — they are a rounding error of the
  byte budget and carry the model's calibration. Callers add model-
  specific leaves via ``skip_patterns`` (substring match on the
  ``jax.tree_util.keystr`` path).
* **Parity is a gate, not a hope.** :func:`check_parity` runs the
  quantized and full-precision serving fns on calibration batches and
  reports the worst per-output error against a declared band — the
  serving plane refuses to adopt a quantized generation outside the
  band (``serving/quant_parity_rejects``) and serves full precision
  instead, mirroring the bf16-band discipline of the training stack.

``QuantizedTensor`` is a NamedTuple, hence automatically a jax pytree
node: quantized param trees flow through ``tree_map`` / ``device_put`` /
``jit(...).lower(...)`` untouched, and the bucketed AOT executor caches
key on the wrapped ``('quant', mode, original_program_key)`` program.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Sequence, Tuple

import numpy as np

INT8 = 'int8'
FP8 = 'fp8'
OFF = 'off'
MODES = (INT8, FP8)

# int8 symmetric range; fp8 e4m3fn finite max (ml_dtypes.finfo).
_INT8_BOUND = 127.0
_FP8_BOUND = 448.0

# Path components that mark a leaf as quantization-sensitive: BN/norm
# statistics and affine terms. The ndim >= 2 rule already skips all of
# these in practice (they are per-channel vectors); the explicit list is
# belt-and-braces against models that reshape them.
DEFAULT_SKIP_COMPONENTS = frozenset(
    {'bias', 'scale', 'mean', 'var', 'batch_stats'})


class QuantizedTensor(NamedTuple):
  """A weight leaf as (payload, per-output-channel scale).

  NamedTuple => a jax pytree NODE: ``qvalue`` and ``scale`` are the
  leaves, so shape/dtype mapping, device placement and AOT lowering all
  see the int8 payload directly. ``dequantize`` is
  ``qvalue.astype(scale.dtype) * scale`` (broadcast over the kept last
  axis).
  """

  qvalue: Any  # int8 / float8_e4m3fn, original weight shape
  scale: Any  # float32, shape (1, ..., 1, out_channels)


def fp8_supported() -> bool:
  """Whether this jaxlib/ml_dtypes ships ``float8_e4m3fn``."""
  try:
    import jax.numpy as jnp

    np.asarray([0.5], dtype=jnp.float8_e4m3fn)
    return True
  except (AttributeError, TypeError):
    return False


def _require_mode(mode: str) -> str:
  if mode in (None, OFF, ''):
    raise ValueError('quantization mode is off; nothing to do')
  if mode not in MODES:
    raise ValueError(f'unknown quantization mode {mode!r}; '
                     f'expected one of {MODES + (OFF,)}')
  if mode == FP8 and not fp8_supported():
    raise ValueError(
        'fp8 quantization requested but this jaxlib/ml_dtypes build '
        'does not support float8_e4m3fn')
  return mode


def channel_scales(weight: np.ndarray, bound: float) -> np.ndarray:
  """Per-output-channel symmetric scales: amax over every axis except
  the last, mapped onto ``[-bound, bound]``. Dead channels (all-zero)
  get scale 1.0 so the dequantized weight is exactly zero."""
  axes = tuple(range(weight.ndim - 1))
  amax = np.max(np.abs(weight), axis=axes, keepdims=True)
  scales = amax.astype(np.float32) / bound
  return np.where(scales > 0.0, scales, np.float32(1.0))


def quantize_array(weight: np.ndarray, mode: str) -> QuantizedTensor:
  """One weight -> :class:`QuantizedTensor` with per-channel scales."""
  weight = np.asarray(weight)
  if mode == INT8:
    scale = channel_scales(weight, _INT8_BOUND)
    q = np.clip(np.rint(weight.astype(np.float32) / scale),
                -_INT8_BOUND, _INT8_BOUND).astype(np.int8)
  else:
    _require_mode(mode)
    import jax.numpy as jnp

    scale = channel_scales(weight, _FP8_BOUND)
    q = np.asarray(weight.astype(np.float32) / scale,
                   dtype=jnp.float8_e4m3fn)
  return QuantizedTensor(qvalue=q, scale=scale)


def dequantize_array(qt: QuantizedTensor):
  """Inverse of :func:`quantize_array`; jnp under a trace (the serving
  fn path), numpy on concrete host arrays."""
  qvalue, scale = qt.qvalue, qt.scale
  if isinstance(qvalue, np.ndarray):
    return qvalue.astype(np.float32) * np.asarray(scale)
  return qvalue.astype(scale.dtype) * scale


def _path_components(path) -> Tuple[str, ...]:
  import jax

  out = []
  for entry in path:
    if isinstance(entry, jax.tree_util.DictKey):
      out.append(str(entry.key))
    elif isinstance(entry, jax.tree_util.SequenceKey):
      out.append(str(entry.idx))
    elif isinstance(entry, jax.tree_util.GetAttrKey):
      out.append(str(entry.name))
    else:
      out.append(str(entry))
  return tuple(out)


def should_quantize(path, leaf,
                    skip_patterns: Sequence[str] = ()) -> bool:
  """The default leaf policy: floating, >= 2-D (matmul/conv weights —
  1-D bias/scale/mean/var vectors stay full precision), not under a
  skip component, not matching a caller pattern."""
  leaf = np.asarray(leaf) if not hasattr(leaf, 'ndim') else leaf
  if not np.issubdtype(np.asarray(leaf).dtype, np.floating):
    return False
  if np.ndim(leaf) < 2:
    return False
  components = _path_components(path)
  if any(c.lower() in DEFAULT_SKIP_COMPONENTS for c in components):
    return False
  path_str = '/'.join(components)
  return not any(p in path_str for p in skip_patterns)


def quantize_params(params,
                    mode: str = INT8,
                    skip_patterns: Sequence[str] = (),
                    predicate: Optional[Callable] = None):
  """Weight-only quantization of a param pytree.

  Every leaf passing ``predicate`` (default :func:`should_quantize`)
  becomes a :class:`QuantizedTensor`; skip-list leaves pass through
  UNTOUCHED (same array object where the input was already a host
  array). Structure is otherwise preserved, so the tree drops into the
  same serving fn signature after :func:`dequantize_params`.
  """
  _require_mode(mode)
  import jax

  predicate = predicate or (
      lambda path, leaf: should_quantize(path, leaf, skip_patterns))

  def convert(path, leaf):
    if not predicate(path, leaf):
      return leaf
    return quantize_array(np.asarray(leaf), mode)

  return jax.tree_util.tree_map_with_path(convert, params)


def dequantize_params(params):
  """Replaces every :class:`QuantizedTensor` node with its dequantized
  array; traceable (this IS the inline upcast in the jitted serving
  program — XLA reads the int8 payload from HBM and fuses the scale
  multiply into the consumer)."""
  import jax

  return jax.tree_util.tree_map(
      lambda leaf: dequantize_array(leaf)
      if isinstance(leaf, QuantizedTensor) else leaf,
      params,
      is_leaf=lambda x: isinstance(x, QuantizedTensor))


def param_bytes(params) -> int:
  """Total parameter bytes as streamed from HBM per dispatch (quantized
  leaves count payload + scales)."""
  import jax

  total = 0
  for leaf in jax.tree_util.tree_leaves(params):
    leaf = np.asarray(leaf)
    total += leaf.size * leaf.dtype.itemsize
  return int(total)


def cast_tree_bytes(params, dtype) -> int:
  """Bytes the tree WOULD occupy with floating leaves cast to ``dtype``
  (the bf16-serving denominator of the compression claim)."""
  import jax

  itemsize = np.dtype(dtype).itemsize
  total = 0
  for leaf in jax.tree_util.tree_leaves(params):
    leaf = np.asarray(leaf)
    size = leaf.size
    if np.issubdtype(leaf.dtype, np.floating):
      total += size * itemsize
    else:
      total += size * leaf.dtype.itemsize
  return int(total)


def quantized_leaf_count(params) -> int:
  import jax

  return sum(
      1 for leaf in jax.tree_util.tree_leaves(
          params, is_leaf=lambda x: isinstance(x, QuantizedTensor))
      if isinstance(leaf, QuantizedTensor))


def quantize_serving_fn(serving,
                        mode: str = INT8,
                        skip_patterns: Sequence[str] = ()):
  """A ``StatelessServingFn`` -> its weight-quantized twin.

  ``fn`` dequantizes inline then calls the original program (the
  wrapper is traced into ONE jitted program — there is no separate
  dequant dispatch); ``params`` is the quantized tree;
  ``program_key`` becomes ``('quant', mode, original_key)`` so
  executable caches never alias full-precision and quantized programs,
  while weights-only hot swaps under the SAME mode still hit.
  """
  _require_mode(mode)
  import jax

  from tensor2robot_tpu.predictors.predictors import StatelessServingFn

  host_params = jax.tree_util.tree_map(np.asarray, serving.params)
  qparams = quantize_params(host_params, mode=mode,
                            skip_patterns=skip_patterns)
  inner = serving.fn

  def quantized_fn(params, features):
    return inner(dequantize_params(params), features)

  return StatelessServingFn(
      fn=quantized_fn,
      params=qparams,
      feature_spec=serving.feature_spec,
      version=serving.version,
      program_key=('quant', mode, serving.program_key))


class ParityReport(NamedTuple):
  """Worst-case quantized-vs-full error over the calibration batches."""

  ok: bool
  max_abs_err: float
  max_rel_err: float
  atol: float
  rtol: float
  per_output: Dict[str, float]  # output key -> max abs err

  def describe(self) -> str:
    status = 'within' if self.ok else 'OUTSIDE'
    return (f'quantization parity {status} band: max_abs_err='
            f'{self.max_abs_err:.3e} (atol={self.atol:.1e}), '
            f'max_rel_err={self.max_rel_err:.3e} (rtol={self.rtol:.1e}), '
            f'per_output={ {k: round(v, 6) for k, v in self.per_output.items()} }')


def check_parity(full_serving,
                 quant_serving,
                 atol: float,
                 rtol: float,
                 calibration_batches: int = 2,
                 calibration_batch_size: int = 4,
                 seed: int = 0) -> ParityReport:
  """Runs both serving fns on deterministic spec-shaped calibration
  batches; the band is per output key:
  ``max|q - f| <= atol + rtol * max|f|``. This is the gate the serving
  plane applies BEFORE adopting a quantized generation."""
  import jax

  from tensor2robot_tpu.specs import numpy_gen

  full_fn = jax.jit(full_serving.fn)
  quant_fn = jax.jit(quant_serving.fn)
  max_abs = 0.0
  max_rel = 0.0
  per_output: Dict[str, float] = {}
  ok = True
  for i in range(calibration_batches):
    batch = dict(numpy_gen.make_random_numpy(
        full_serving.feature_spec, batch_size=calibration_batch_size,
        seed=seed + i))
    full_out = full_fn(full_serving.params, batch)
    quant_out = quant_fn(quant_serving.params, batch)
    for key in full_out:
      f = np.asarray(full_out[key], np.float32)
      q = np.asarray(quant_out[key], np.float32)
      abs_err = float(np.max(np.abs(q - f))) if f.size else 0.0
      scale = float(np.max(np.abs(f))) if f.size else 0.0
      per_output[key] = max(per_output.get(key, 0.0), abs_err)
      max_abs = max(max_abs, abs_err)
      if scale > 0.0:
        max_rel = max(max_rel, abs_err / scale)
      if abs_err > atol + rtol * scale:
        ok = False
  return ParityReport(ok=ok, max_abs_err=max_abs, max_rel_err=max_rel,
                      atol=atol, rtol=rtol, per_output=per_output)
