"""TensorSpec: the typed declaration of a single tensor.

TPU-native re-design of the reference's ``ExtendedTensorSpec``
(``/root/reference/utils/tensorspec_utils.py:44-282``). The reference subclasses
``tf.TensorSpec``; here the spec is a frozen, hashable, pure-Python dataclass
with a numpy dtype so the core framework has **no TensorFlow dependency** —
JAX views are produced on demand via :meth:`to_shape_dtype_struct`.

Fields beyond shape/dtype/name (same capability surface as the reference):

* ``is_optional``: the tensor may be absent from data; validation tolerates it.
* ``is_sequence``: the leading (non-batch) dimension is a runtime-varying
  sequence length (SequenceExample-style parsing).
* ``is_extracted``: marks specs derived from concrete tensors/arrays, whose
  shape already includes batch/sequence dims.
* ``data_format``: 'JPEG'/'PNG' marks an encoded-image feature that the data
  layer must decode.
* ``dataset_key``: routes the feature to a named dataset in multi-dataset
  input pipelines.
* ``varlen_default_value``: if set, the feature is parsed as a variable-length
  list padded/clipped to ``shape`` with this value.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple, Union

import numpy as np

try:  # bfloat16 as a real numpy dtype (ships with jax).
  import ml_dtypes  # pytype: disable=import-error

  bfloat16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax.
  bfloat16 = np.dtype('float32')

DTypeLike = Any
ShapeLike = Union[Sequence[Optional[int]], int, None]

_IMAGE_FORMATS = ('JPEG', 'PNG')


def as_dtype(dtype: DTypeLike) -> np.dtype:
  """Canonicalizes tf/jax/numpy/string dtypes to a numpy dtype."""
  if dtype is None:
    raise ValueError('dtype must not be None')
  # tf.DType and jnp dtypes both expose `.name`; strings & np types go
  # straight through np.dtype.
  name = getattr(dtype, 'name', None)
  if name is not None and not isinstance(dtype, np.dtype):
    if name == 'bfloat16':
      return bfloat16
    return np.dtype(name)
  if isinstance(dtype, str) and dtype == 'bfloat16':
    return bfloat16
  return np.dtype(dtype)


def dtype_name(dtype: DTypeLike) -> str:
  return as_dtype(dtype).name


def _canonical_shape(shape: ShapeLike) -> Tuple[Optional[int], ...]:
  if shape is None:
    return ()
  if isinstance(shape, (int, np.integer)):
    return (int(shape),)
  out = []
  for dim in shape:
    if dim is None:
      out.append(None)
      continue
    try:
      d = int(dim)
    except Exception:
      # Symbolic dims (jax.export shape polymorphism) behave like unknown
      # runtime dims for validation purposes.
      out.append(None)
      continue
    out.append(None if d < 0 else d)
  return tuple(out)


@dataclasses.dataclass(frozen=True)
class TensorSpec:
  """A frozen declaration of one tensor's shape, dtype and data semantics."""

  shape: Tuple[Optional[int], ...]
  dtype: np.dtype
  name: Optional[str] = None
  is_optional: bool = False
  is_sequence: bool = False
  is_extracted: bool = False
  data_format: Optional[str] = None
  dataset_key: str = ''
  varlen_default_value: Optional[float] = None

  def __init__(self,
               shape: ShapeLike,
               dtype: DTypeLike,
               name: Optional[str] = None,
               is_optional: Optional[bool] = None,
               is_sequence: Optional[bool] = None,
               is_extracted: Optional[bool] = None,
               data_format: Optional[str] = None,
               dataset_key: Optional[str] = None,
               varlen_default_value: Optional[float] = None):
    object.__setattr__(self, 'shape', _canonical_shape(shape))
    object.__setattr__(self, 'dtype', as_dtype(dtype))
    object.__setattr__(self, 'name', name)
    object.__setattr__(self, 'is_optional', bool(is_optional))
    object.__setattr__(self, 'is_sequence', bool(is_sequence))
    object.__setattr__(self, 'is_extracted', bool(is_extracted))
    if data_format is not None:
      data_format = data_format.upper()
      if data_format not in _IMAGE_FORMATS:
        raise ValueError(
            f'data_format must be one of {_IMAGE_FORMATS}, got {data_format}')
    object.__setattr__(self, 'data_format', data_format)
    object.__setattr__(self, 'dataset_key', dataset_key or '')
    if varlen_default_value is not None:
      varlen_default_value = float(varlen_default_value)
    object.__setattr__(self, 'varlen_default_value', varlen_default_value)

  # ---------------------------------------------------------------- factories

  @classmethod
  def from_spec(cls,
                spec: 'TensorSpec',
                shape: ShapeLike = None,
                dtype: DTypeLike = None,
                name: Optional[str] = None,
                batch_size: int = -1,
                **overrides) -> 'TensorSpec':
    """Copy of ``spec`` with optional overrides.

    ``batch_size`` follows the reference's placeholder convention: ``-1`` →
    leave the shape alone, ``None`` → prepend a dynamic batch dim, ``N>0`` →
    prepend a fixed batch dim.
    """
    kwargs = dict(
        shape=spec.shape if shape is None else _canonical_shape(shape),
        dtype=spec.dtype if dtype is None else as_dtype(dtype),
        name=spec.name if name is None else name,
        is_optional=spec.is_optional,
        is_sequence=spec.is_sequence,
        is_extracted=spec.is_extracted,
        data_format=spec.data_format,
        dataset_key=spec.dataset_key,
        varlen_default_value=spec.varlen_default_value,
    )
    kwargs.update(overrides)
    if batch_size is None:
      kwargs['shape'] = (None,) + tuple(kwargs['shape'])
    elif batch_size != -1:
      kwargs['shape'] = (int(batch_size),) + tuple(kwargs['shape'])
    return cls(**kwargs)

  @classmethod
  def from_array(cls,
                 array,
                 name: Optional[str] = None) -> 'TensorSpec':
    """Spec extracted from a concrete ndarray / jax.Array."""
    dtype = getattr(array, 'dtype', None)
    if dtype is None:
      dtype = np.asarray(array).dtype
    def _dim(d):
      # Symbolic dims (jax.export shape polymorphism) pass through; spec
      # validation compares them structurally like ints.
      try:
        return int(d)
      except Exception:  # symbolic dims raise their own exception type
        return d

    return cls(
        shape=tuple(_dim(d) for d in np.shape(array)),
        dtype=as_dtype(dtype),
        name=name,
        is_extracted=True)

  # Kept as an alias so call sites mirror the reference API (`from_tensor`).
  from_tensor = from_array

  @classmethod
  def to_spec(cls, instance) -> 'TensorSpec':
    """Normalizes a spec or a concrete array to a TensorSpec."""
    if isinstance(instance, TensorSpec):
      return instance
    return cls.from_array(instance)

  # ------------------------------------------------------------------- views

  def to_shape_dtype_struct(self, batch_size: Optional[int] = None):
    """A ``jax.ShapeDtypeStruct`` view for jit/eval_shape.

    Dynamic (None) dims are not representable in jit-land; they must be
    resolved before tracing, so we raise if any remain.
    """
    import jax

    shape = self.shape
    if batch_size is not None and batch_size != -1:
      shape = (batch_size,) + shape
    if any(d is None for d in shape):
      raise ValueError(
          f'Cannot build ShapeDtypeStruct with dynamic dims: {self}')
    return jax.ShapeDtypeStruct(shape, self.dtype)

  @property
  def is_encoded_image(self) -> bool:
    return self.data_format in _IMAGE_FORMATS

  # -------------------------------------------------------------- proto / io

  def to_proto(self):
    from tensor2robot_tpu.proto import t2r_pb2

    proto = t2r_pb2.ExtendedTensorSpec()
    for dim in self.shape:
      proto.shape.append(-1 if dim is None else dim)
    proto.dtype = self.dtype.name if self.dtype != bfloat16 else 'bfloat16'
    if self.name is not None:
      proto.name = self.name
    proto.is_optional = self.is_optional
    proto.is_sequence = self.is_sequence
    proto.is_extracted = self.is_extracted
    if self.data_format is not None:
      proto.data_format = self.data_format
    if self.dataset_key:
      proto.dataset_key = self.dataset_key
    if self.varlen_default_value is not None:
      proto.varlen_default_value = self.varlen_default_value
      proto.has_varlen_default_value = True
    return proto

  @classmethod
  def from_proto(cls, proto) -> 'TensorSpec':
    shape = tuple(None if d < 0 else d for d in proto.shape)
    return cls(
        shape=shape,
        dtype=proto.dtype or 'float32',
        name=proto.name or None,
        is_optional=proto.is_optional,
        is_sequence=proto.is_sequence,
        is_extracted=proto.is_extracted,
        data_format=proto.data_format or None,
        dataset_key=proto.dataset_key or None,
        varlen_default_value=(proto.varlen_default_value
                              if proto.has_varlen_default_value else None))

  def to_json_dict(self) -> dict:
    d = {
        'shape': [-1 if s is None else s for s in self.shape],
        'dtype': self.dtype.name,
    }
    if self.name is not None:
      d['name'] = self.name
    for field in ('is_optional', 'is_sequence', 'is_extracted'):
      if getattr(self, field):
        d[field] = True
    if self.data_format is not None:
      d['data_format'] = self.data_format
    if self.dataset_key:
      d['dataset_key'] = self.dataset_key
    if self.varlen_default_value is not None:
      d['varlen_default_value'] = self.varlen_default_value
    return d

  @classmethod
  def from_json_dict(cls, d: dict) -> 'TensorSpec':
    return cls(
        shape=tuple(None if s < 0 else s for s in d['shape']),
        dtype=d['dtype'],
        name=d.get('name'),
        is_optional=d.get('is_optional', False),
        is_sequence=d.get('is_sequence', False),
        is_extracted=d.get('is_extracted', False),
        data_format=d.get('data_format'),
        dataset_key=d.get('dataset_key'),
        varlen_default_value=d.get('varlen_default_value'))

  # --------------------------------------------------------------- equality

  def __eq__(self, other) -> bool:
    if not isinstance(other, TensorSpec):
      return NotImplemented
    return (self.shape == other.shape and self.dtype == other.dtype and
            self.name == other.name and
            self.is_optional == other.is_optional and
            self.is_sequence == other.is_sequence and
            self.data_format == other.data_format and
            self.dataset_key == other.dataset_key and
            self.varlen_default_value == other.varlen_default_value)

  def __hash__(self):
    return hash((self.shape, self.dtype, self.name, self.is_optional,
                 self.is_sequence, self.data_format, self.dataset_key))

  def __repr__(self):
    parts = [f'shape={self.shape}', f'dtype={self.dtype.name}']
    if self.name:
      parts.append(f'name={self.name!r}')
    for field in ('is_optional', 'is_sequence', 'is_extracted'):
      if getattr(self, field):
        parts.append(f'{field}=True')
    if self.data_format:
      parts.append(f'data_format={self.data_format!r}')
    if self.dataset_key:
      parts.append(f'dataset_key={self.dataset_key!r}')
    if self.varlen_default_value is not None:
      parts.append(f'varlen_default_value={self.varlen_default_value}')
    return f'TensorSpec({", ".join(parts)})'


# The reference name; new code should prefer the shorter `TensorSpec`.
ExtendedTensorSpec = TensorSpec
