"""Dtype policy: the bfloat16-on-TPU mechanism at the spec level.

Capability-equivalent of the reference's ``replace_dtype`` /
``cast_float32_to_bfloat16`` / ``cast_bfloat16_to_float32``
(``/root/reference/utils/tensorspec_utils.py:685-747``). In the TPU-native
design the host pipeline always produces float32/uint8 and the *device step*
casts per-spec to bfloat16 on entry — a free cast on TPU that keeps all host
code and exported artifacts in float32.
"""

from __future__ import annotations


import numpy as np

from tensor2robot_tpu.specs.algebra import flatten_spec_structure
from tensor2robot_tpu.specs.spec_struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec, as_dtype, bfloat16


def replace_dtype(spec_structure, from_dtype, to_dtype) -> SpecStruct:
  """Copy of the spec structure with from_dtype specs re-typed to to_dtype."""
  from_dtype = as_dtype(from_dtype)
  to_dtype = as_dtype(to_dtype)
  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for key, value in flat.items():
    spec = TensorSpec.to_spec(value)
    if spec.dtype == from_dtype:
      spec = TensorSpec.from_spec(spec, dtype=to_dtype)
    out[key] = spec
  return out


def cast_float32_to_bfloat16(spec_structure) -> SpecStruct:
  return replace_dtype(spec_structure, np.float32, bfloat16)


def cast_bfloat16_to_float32(spec_structure) -> SpecStruct:
  return replace_dtype(spec_structure, bfloat16, np.float32)


def cast_arrays_to_spec_dtypes(spec_structure, tensors) -> SpecStruct:
  """Casts each tensor to the dtype its spec declares (jax or numpy).

  This is the device-entry cast: called inside the jit-ed step so that a
  float32 host batch becomes bfloat16 on the MXU without any host work.
  """
  import jax.numpy as jnp

  flat_spec = flatten_spec_structure(spec_structure)
  flat_tensors = flatten_spec_structure(tensors)
  out = SpecStruct()
  for key, tensor in flat_tensors.items():
    spec = flat_spec.get(key)
    if spec is None or not isinstance(spec, TensorSpec):
      out[key] = tensor
      continue
    if hasattr(tensor, 'astype'):
      if as_dtype(tensor.dtype) != spec.dtype:
        tensor = tensor.astype(spec.dtype)
    else:
      tensor = jnp.asarray(tensor, dtype=spec.dtype)
    out[key] = tensor
  return out


def bfloat16_compute_policy(spec_structure) -> SpecStruct:
  """Device-side spec view: float32 specs become bfloat16 specs.

  Trainer entry point: the model's declared (float32) specs describe the host
  batch; this view describes what the compute actually sees on TPU.
  """
  return cast_float32_to_bfloat16(spec_structure)
