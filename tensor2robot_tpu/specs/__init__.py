"""Spec system: the typed data contract every other layer builds on."""

from tensor2robot_tpu.specs.algebra import (
    add_sequence_length_specs,
    assert_equal,
    assert_equal_spec_or_tensor,
    assert_required,
    assert_valid_spec_structure,
    copy_spec_structure,
    copy_tensorspec,
    filter_required_flat_tensor_spec,
    filter_spec_structure_by_dataset,
    flatten_spec_structure,
    is_flat_spec_or_tensors_structure,
    maybe_ignore_batch,
    pack_flat_sequence_to_spec_structure,
    pad_or_clip_to_spec_shape,
    spec_names,
    tensorspec_from_tensors,
    validate_and_flatten,
    validate_and_pack,
)
from tensor2robot_tpu.specs.assets import (
    EXTRA_ASSETS_DIRECTORY,
    T2R_ASSETS_FILENAME,
    load_specs_from_export_dir,
    load_t2r_assets_from_file,
    make_t2r_assets,
    write_assets_to_export_dir,
    write_t2r_assets_to_file,
)
from tensor2robot_tpu.specs.dtypes import (
    bfloat16_compute_policy,
    cast_arrays_to_spec_dtypes,
    cast_bfloat16_to_float32,
    cast_float32_to_bfloat16,
    replace_dtype,
)
from tensor2robot_tpu.specs.numpy_gen import (
    make_constant_numpy,
    make_placeholders,
    make_random_arrays,
    make_random_numpy,
    make_shape_dtype_structs,
    map_feed_dict,
    pack_feed_dict,
)
from tensor2robot_tpu.specs.spec_struct import SpecStruct, TensorSpecStruct
from tensor2robot_tpu.specs.tensor_spec import (
    ExtendedTensorSpec,
    TensorSpec,
    as_dtype,
    bfloat16,
    dtype_name,
)
