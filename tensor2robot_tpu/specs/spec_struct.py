"""SpecStruct: a container that is simultaneously flat and hierarchical.

TPU-native re-design of the reference's ``TensorSpecStruct``
(``/root/reference/utils/tensorspec_utils.py:306-682``). The same value can be
addressed two ways:

* **flat**: ``struct['train/images']`` — the canonical '/'-joined path used by
  parsers, feed dicts and serialization;
* **hierarchical**: ``struct.train.images`` — attribute access; intermediate
  nodes are *live views* that share storage with the root, so mutations through
  a view are visible everywhere.

Leaves may be :class:`TensorSpec`, numpy arrays, jax arrays, or ``None``
(placeholder for an absent optional tensor). Assigning a Mapping expands it
into child paths.

Unlike the reference (an OrderedDict subclass with TF ``nest`` integration),
this is a small MutableMapping over a shared ordered store — it registers as a
JAX pytree, so a SpecStruct of arrays can flow through ``jit``/``grad``
directly.
"""

from __future__ import annotations

import collections
from collections import abc as collections_abc
from typing import Any, Iterator

import numpy as np

from tensor2robot_tpu.specs.tensor_spec import TensorSpec

_SEP = '/'

# Leaf types a SpecStruct may hold. jax.Array is checked lazily to keep the
# import soft for pure-data-side users.
def _is_valid_leaf(value: Any) -> bool:
  if value is None or isinstance(value, (TensorSpec, np.ndarray, np.generic)):
    return True
  type_name = type(value).__module__ + '.' + type(value).__name__
  if type_name.startswith('jax') or 'Array' in type(value).__name__:
    return True
  # Host-side pipelines may hold tf.Tensors; accept anything tensor-like.
  if hasattr(value, 'dtype') and hasattr(value, 'shape'):
    return True
  return isinstance(value, (bytes, str, int, float))


class SpecStruct(collections_abc.MutableMapping):
  """Ordered flat path->leaf mapping with live hierarchical views."""

  __slots__ = ('_store', '_prefix')

  def __init__(self, *args, **kwargs):
    object.__setattr__(self, '_store', collections.OrderedDict())
    object.__setattr__(self, '_prefix', '')
    if args:
      if len(args) > 1:
        raise TypeError('SpecStruct accepts at most one positional argument.')
      initial = args[0]
      if isinstance(initial, collections_abc.Mapping):
        initial = initial.items()
      for key, value in initial:
        self[key] = value
    for key, value in kwargs.items():
      self[key] = value

  def __reduce__(self):
    # Pickle as (class, flat items): views materialize their subtree, and
    # reconstruction goes through __init__ (plain dict-subclass pickling
    # would bypass it and leave the slots unset).
    return (type(self), (list(self.items()),))

  # ----------------------------------------------------------------- views

  @classmethod
  def _view(cls, store: collections.OrderedDict, prefix: str) -> 'SpecStruct':
    view = cls.__new__(cls)
    object.__setattr__(view, '_store', store)
    object.__setattr__(view, '_prefix', prefix)
    return view

  def _full(self, key: str) -> str:
    if not isinstance(key, str):
      raise TypeError(f'SpecStruct keys must be str, got {type(key)}')
    key = key.strip(_SEP)
    if not key:
      raise KeyError('Empty key')
    return self._prefix + key

  def _is_subtree(self, full: str) -> bool:
    probe = full + _SEP
    return any(k.startswith(probe) for k in self._store)

  # ------------------------------------------------------------ MutableMapping

  def __getitem__(self, key: str):
    full = self._full(key)
    if full in self._store:
      return self._store[full]
    if self._is_subtree(full):
      return SpecStruct._view(self._store, full + _SEP)
    raise KeyError(key)

  def __setitem__(self, key: str, value) -> None:
    full = self._full(key)
    if isinstance(value, SpecStruct):
      value = dict(value.items())
    if isinstance(value, collections_abc.Mapping):
      if not value:
        raise ValueError(f'Cannot assign an empty mapping to {key!r}.')
      if full in self._store:
        del self._store[full]
      for sub_key, sub_value in value.items():
        self[key + _SEP + sub_key] = sub_value
      return
    if not _is_valid_leaf(value):
      raise ValueError(
          f'Invalid leaf for SpecStruct[{key!r}]: {type(value)}. Expected '
          'TensorSpec, ndarray, jax array, tensor-like, or None.')
    if self._is_subtree(full):
      raise ValueError(
          f'Cannot assign a leaf to {key!r}: it is an existing subtree.')
    # The reverse conflict: writing a child under an existing leaf would make
    # that path simultaneously a leaf and a subtree.
    parts = full.split(_SEP)
    for i in range(1, len(parts)):
      ancestor = _SEP.join(parts[:i])
      if ancestor in self._store:
        raise ValueError(
            f'Cannot assign {key!r}: ancestor {ancestor!r} is an existing '
            'leaf.')
    self._store[full] = value

  def __delitem__(self, key: str) -> None:
    full = self._full(key)
    if full in self._store:
      del self._store[full]
      return
    subtree_keys = [
        k for k in self._store if k.startswith(full + _SEP)]
    if not subtree_keys:
      raise KeyError(key)
    for k in subtree_keys:
      del self._store[k]

  def __iter__(self) -> Iterator[str]:
    if not self._prefix:
      yield from list(self._store)
      return
    n = len(self._prefix)
    for k in list(self._store):
      if k.startswith(self._prefix):
        yield k[n:]

  def __len__(self) -> int:
    return sum(1 for _ in self)

  def __contains__(self, key) -> bool:
    try:
      full = self._full(key)
    except (TypeError, KeyError):
      return False
    return full in self._store or self._is_subtree(full)

  # -------------------------------------------------------------- attributes

  def __getattr__(self, name: str):
    if name.startswith('_'):
      raise AttributeError(name)
    try:
      return self[name]
    except KeyError:
      raise AttributeError(
          f'SpecStruct has no child {name!r}; children: {list(self)[:20]}')

  def __setattr__(self, name: str, value) -> None:
    if name.startswith('_'):
      object.__setattr__(self, name, value)
    else:
      self[name] = value

  def __delattr__(self, name: str) -> None:
    if name.startswith('_'):
      object.__delattr__(self, name)
    else:
      del self[name]

  # ----------------------------------------------------------------- helpers

  def is_leaf(self, key: str) -> bool:
    return self._full(key) in self._store

  def to_dict(self) -> collections.OrderedDict:
    """Plain flat OrderedDict of path -> leaf (relative to this view)."""
    return collections.OrderedDict(self.items())

  def to_nested_dict(self) -> collections.OrderedDict:
    """Nested plain-dict rendering of the hierarchy."""
    out = collections.OrderedDict()
    for path, value in self.items():
      node = out
      parts = path.split(_SEP)
      for part in parts[:-1]:
        node = node.setdefault(part, collections.OrderedDict())
      node[parts[-1]] = value
    return out

  def copy(self) -> 'SpecStruct':
    return SpecStruct(self.items())

  def __eq__(self, other) -> bool:
    if not isinstance(other, collections_abc.Mapping):
      return NotImplemented
    if set(self.keys()) != set(other.keys()):
      return False
    for key, value in self.items():
      other_value = other[key]
      if isinstance(value, (np.ndarray, np.generic)) or isinstance(
          other_value, (np.ndarray, np.generic)):
        if not np.array_equal(np.asarray(value), np.asarray(other_value)):
          return False
      elif value != other_value:
        return False
    return True

  def __repr__(self) -> str:
    items = ', '.join(f'{k!r}: {v!r}' for k, v in self.items())
    return f'SpecStruct({{{items}}})'

  # ------------------------------------------------------------- proto / io

  def to_proto(self):
    from tensor2robot_tpu.proto import t2r_pb2

    proto = t2r_pb2.TensorSpecStruct()
    for key, value in self.items():
      if value is None:
        continue
      if not isinstance(value, TensorSpec):
        value = TensorSpec.from_array(value)
      proto.key_value[key].CopyFrom(value.to_proto())
    return proto

  @classmethod
  def from_proto(cls, proto) -> 'SpecStruct':
    items = sorted(proto.key_value.items())
    return cls([(k, TensorSpec.from_proto(v)) for k, v in items])

  def to_json_dict(self) -> dict:
    out = {}
    for key, value in self.items():
      if value is None:
        continue
      if not isinstance(value, TensorSpec):
        value = TensorSpec.from_array(value)
      out[key] = value.to_json_dict()
    return out

  @classmethod
  def from_json_dict(cls, d: dict) -> 'SpecStruct':
    return cls([(k, TensorSpec.from_json_dict(v)) for k, v in sorted(
        d.items())])


# The reference name; new code should prefer the shorter `SpecStruct`.
TensorSpecStruct = SpecStruct


def _register_pytree() -> None:
  """SpecStructs of jax arrays flow through jit/grad as pytrees."""
  import jax

  def flatten(struct: SpecStruct):
    keys = list(struct.keys())
    values = [struct[k] for k in keys]
    return values, tuple(keys)

  def flatten_with_keys(struct: SpecStruct):
    keys = list(struct.keys())
    return [(jax.tree_util.DictKey(k), struct[k]) for k in keys], tuple(keys)

  def unflatten(keys, values):
    # MUST bypass __setitem__'s leaf validation: jax internals unflatten
    # treedefs around sentinel objects (e.g. pjit's in_shardings prefix
    # matching builds a dummy tree of plain object()s), and a validating
    # unflatten breaks the pytree contract — observed as pjit's
    # "Please open a bug report!" assertion on sharded SpecStruct args.
    struct = SpecStruct.__new__(SpecStruct)
    object.__setattr__(struct, '_store',
                       collections.OrderedDict(zip(keys, values)))
    object.__setattr__(struct, '_prefix', '')
    return struct

  try:
    jax.tree_util.register_pytree_with_keys(
        SpecStruct, flatten_with_keys, unflatten, flatten)
  except ValueError:  # pragma: no cover - double registration on reload.
    pass


try:
  _register_pytree()
except ImportError:  # pragma: no cover - jax is a hard dep in practice.
  pass
