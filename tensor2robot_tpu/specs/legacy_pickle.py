"""Legacy pickle-asset migration: tensor2robot ``.pkl`` specs → our specs.

The original framework stored export specs as pickles
(``input_specs.pkl`` with ``{'in_feature_spec', 'in_label_spec'}``,
``global_step.pkl``) before moving to the ``t2r_assets.pbtxt`` proto; its
``convert_pkl_assets_to_proto_assets.py`` migrated old exports
(``/root/reference/utils/convert_pkl_assets_to_proto_assets.py:40-62``,
pickle layout ``tensorspec_utils.py:278-282,1705-1713``).

This module performs the same migration WITHOUT the original package or
TensorFlow installed: a restricted unpickler maps the legacy class paths
(``tensor2robot.utils.tensorspec_utils.ExtendedTensorSpec`` /
``TensorSpecStruct``, tf ``TensorShape``/``DType``/``TensorSpec``) onto
local reconstruction shims, and everything else is refused (defense
against arbitrary-code pickles).
"""

from __future__ import annotations

import io
import pickle
from typing import Optional, Tuple

import numpy as np

from tensor2robot_tpu.specs.spec_struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec


class _TensorShape:
  """Stand-in for tf.TensorShape: captures the dims list."""

  def __init__(self, dims=None):
    self.dims = dims

  def as_tuple(self):
    if self.dims is None:
      return ()

    def dim(d):
      v = getattr(d, 'value', d)
      return None if v is None else int(v)

    return tuple(dim(d) for d in self.dims)


class _Dim:
  """Stand-in for tf.compat.v1.Dimension."""

  def __init__(self, value=None):
    self.value = value


def _as_dtype(name) -> np.dtype:
  """Stand-in for tf's ``as_dtype`` — how real TF DTypes pickle:
  ``DType.__reduce__ → (as_dtype, (self.name,))``."""
  return _np_dtype(name)


def _np_dtype(dtype) -> np.dtype:
  if isinstance(dtype, np.dtype):
    return dtype
  name = str(dtype)
  if name == 'bfloat16':
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)
  if name in ('string', 'object', 'str', 'bytes'):  # tf.string
    return np.dtype(object)
  return np.dtype(name)


class _LegacyStruct(dict):
  """Stand-in for TensorSpecStruct (an OrderedDict subclass whose pickle
  carries instance state like ``_path_prefix``): absorb and drop it."""

  def __setstate__(self, state):
    pass


def _shape_tuple(shape) -> Tuple[Optional[int], ...]:
  if isinstance(shape, _TensorShape):
    return shape.as_tuple()
  if shape is None:
    return ()
  return tuple(None if d is None else int(d) for d in shape)


def _extended_tensor_spec(shape, dtype, name=None, is_optional=False,
                          is_sequence=False, is_extracted=False,
                          data_format=None, dataset_key=None,
                          varlen_default_value=None):
  """Reconstruction shim matching ExtendedTensorSpec.__reduce__ args."""
  del is_extracted  # derived at runtime in this framework
  return TensorSpec(
      shape=_shape_tuple(shape),
      dtype=_np_dtype(dtype),
      name=name,
      is_optional=bool(is_optional),
      is_sequence=bool(is_sequence),
      data_format=data_format,
      dataset_key=dataset_key or '',
      varlen_default_value=varlen_default_value)


def _plain_tensor_spec(shape=None, dtype=None, name=None):
  return TensorSpec(shape=_shape_tuple(shape), dtype=_np_dtype(dtype),
                    name=name)


_CLASS_MAP = {
    ('tensor2robot.utils.tensorspec_utils', 'ExtendedTensorSpec'):
        _extended_tensor_spec,
    # Reconstructed as a state-dropping dict shim (pickle bypasses
    # __init__, which SpecStruct needs, and real TensorSpecStruct pickles
    # carry instance state); load_input_spec_from_file wraps the result.
    ('tensor2robot.utils.tensorspec_utils', 'TensorSpecStruct'):
        _LegacyStruct,
    ('tensorflow.python.framework.tensor_shape', 'TensorShape'):
        _TensorShape,
    ('tensorflow.python.framework.tensor_shape', 'Dimension'): _Dim,
    ('tensorflow.python.framework.dtypes', 'as_dtype'): _as_dtype,
    ('tensorflow.python.framework.tensor_spec', 'TensorSpec'):
        _plain_tensor_spec,
    ('tensorflow.python.framework.tensor', 'TensorSpec'):
        _plain_tensor_spec,
    ('collections', 'OrderedDict'): dict,
}


class _LegacyUnpickler(pickle.Unpickler):

  def find_class(self, module, name):
    try:
      return _CLASS_MAP[(module, name)]
    except KeyError:
      raise pickle.UnpicklingError(
          f'Refusing to unpickle {module}.{name}: only legacy '
          'tensor2robot spec classes are allowed.')


def loads(data: bytes):
  return _LegacyUnpickler(io.BytesIO(data)).load()


def load_input_spec_from_file(path: str) -> Tuple[SpecStruct, SpecStruct]:
  """Reads a legacy ``input_specs.pkl`` → (feature_spec, label_spec)."""
  with open(path, 'rb') as f:
    spec_data = loads(f.read())
  return (SpecStruct(spec_data['in_feature_spec']),
          SpecStruct(spec_data['in_label_spec']))


def load_global_step_from_file(path: str) -> int:
  with open(path, 'rb') as f:
    data = loads(f.read())
  if isinstance(data, dict):
    return int(data.get('global_step', 0))
  return int(data)
