"""T2RAssets: the serialized spec contract that travels with every export.

Capability-equivalent of the reference's asset I/O
(``/root/reference/utils/tensorspec_utils.py:1680-1728``): each exported model
directory carries an ``assets.extra/t2r_assets.pbtxt`` with the feature spec,
label spec and global step, so a predictor can reconstruct the input contract
without importing the model code. A JSON twin is written alongside for
proto-free consumers.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

from google.protobuf import text_format

from tensor2robot_tpu.proto import t2r_pb2
from tensor2robot_tpu.specs.spec_struct import SpecStruct

EXTRA_ASSETS_DIRECTORY = 'assets.extra'
T2R_ASSETS_FILENAME = 't2r_assets.pbtxt'
T2R_ASSETS_JSON_FILENAME = 't2r_assets.json'


def make_t2r_assets(feature_spec: Optional[SpecStruct],
                    label_spec: Optional[SpecStruct],
                    global_step: int = 0) -> t2r_pb2.T2RAssets:
  assets = t2r_pb2.T2RAssets()
  if feature_spec is not None:
    assets.feature_spec.CopyFrom(feature_spec.to_proto())
  if label_spec is not None:
    assets.label_spec.CopyFrom(label_spec.to_proto())
  assets.global_step = int(global_step)
  return assets


def write_t2r_assets_to_file(t2r_assets: t2r_pb2.T2RAssets,
                             filename: str) -> None:
  os.makedirs(os.path.dirname(filename) or '.', exist_ok=True)
  with open(filename, 'w') as f:
    f.write(text_format.MessageToString(t2r_assets))
  json_twin = {
      'feature_spec': SpecStruct.from_proto(
          t2r_assets.feature_spec).to_json_dict(),
      'label_spec': SpecStruct.from_proto(
          t2r_assets.label_spec).to_json_dict(),
      'global_step': int(t2r_assets.global_step),
  }
  json_path = os.path.join(
      os.path.dirname(filename), T2R_ASSETS_JSON_FILENAME)
  with open(json_path, 'w') as f:
    json.dump(json_twin, f, indent=2, sort_keys=True)


def load_t2r_assets_from_file(filename: str) -> t2r_pb2.T2RAssets:
  assets = t2r_pb2.T2RAssets()
  with open(filename) as f:
    text_format.Parse(f.read(), assets)
  return assets


def write_assets_to_export_dir(export_dir: str,
                               feature_spec: SpecStruct,
                               label_spec: Optional[SpecStruct],
                               global_step: int = 0) -> str:
  """Writes assets.extra/t2r_assets.pbtxt under an export dir."""
  path = os.path.join(export_dir, EXTRA_ASSETS_DIRECTORY, T2R_ASSETS_FILENAME)
  write_t2r_assets_to_file(
      make_t2r_assets(feature_spec, label_spec, global_step), path)
  return path


def load_specs_from_export_dir(
    export_dir: str) -> Tuple[SpecStruct, SpecStruct, int]:
  """Loads (feature_spec, label_spec, global_step) from an export dir."""
  path = os.path.join(export_dir, EXTRA_ASSETS_DIRECTORY, T2R_ASSETS_FILENAME)
  assets = load_t2r_assets_from_file(path)
  return (SpecStruct.from_proto(assets.feature_spec),
          SpecStruct.from_proto(assets.label_spec), int(assets.global_step))
