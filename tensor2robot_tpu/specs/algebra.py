"""Spec algebra: flatten / pack / validate / filter / transform.

TPU-native re-design of the reference's spec-structure functions
(``/root/reference/utils/tensorspec_utils.py:685-1677``). Semantics preserved:

* flattening joins paths with '/' and drops ``None`` leaves (absent optionals)
  unless asked otherwise;
* packing matches the *flat path keys* of the expected spec (spec ``name`` is
  only used at the serialized-data/feed boundary);
* validation checks dtype and shape per-dimension with ``None`` as a wildcard,
  tolerates missing optional specs, and can ignore the leading batch dim;
* sequence specs compare against extracted tensors with the sequence dim
  stripped.
"""

from __future__ import annotations

import collections
from collections import abc as collections_abc
from typing import Any, Mapping, Union

import numpy as np

from tensor2robot_tpu.specs.spec_struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec

_SEP = '/'

SpecOrTensors = Union[SpecStruct, Mapping, tuple, list, TensorSpec, Any]


def _is_namedtuple(value) -> bool:
  return isinstance(value, tuple) and hasattr(value, '_fields')


def _is_leaf(value) -> bool:
  if value is None or isinstance(value, TensorSpec):
    return True
  if isinstance(value, SpecStruct) or isinstance(value,
                                                 collections_abc.Mapping):
    return False
  if _is_namedtuple(value) or isinstance(value, (list, tuple)):
    return False
  return True


def assert_valid_spec_structure(spec_or_tensors: SpecOrTensors) -> None:
  """Raises ValueError if any leaf is not spec/tensor-like/None."""
  for key, value in _iter_flat(spec_or_tensors, filter_none=False):
    if value is None or isinstance(value, TensorSpec):
      continue
    if hasattr(value, 'dtype') and hasattr(value, 'shape'):
      continue
    if isinstance(value, (np.ndarray, np.generic, bytes, str, int, float)):
      continue
    raise ValueError(
        f'Invalid spec structure leaf at {key!r}: {type(value)}')


def _iter_flat(structure, prefix: str = '', filter_none: bool = True):
  """Yields ('/'-joined path, leaf) pairs depth-first."""
  if isinstance(structure, SpecStruct):
    for key, value in structure.items():
      if filter_none and value is None:
        continue
      yield prefix + key, value
    return
  if _is_namedtuple(structure):
    items = zip(structure._fields, structure)
  elif isinstance(structure, collections_abc.Mapping):
    items = structure.items()
  elif isinstance(structure, (list, tuple)):
    items = ((str(i), v) for i, v in enumerate(structure))
  else:  # single leaf
    if not (filter_none and structure is None):
      yield prefix.rstrip(_SEP), structure
    return
  for key, value in items:
    if _is_leaf(value):
      if filter_none and value is None:
        continue
      yield prefix + str(key), value
    else:
      yield from _iter_flat(value, prefix + str(key) + _SEP, filter_none)


def is_flat_spec_or_tensors_structure(spec_or_tensors) -> bool:
  """True if the structure is already a flat path->leaf mapping."""
  if isinstance(spec_or_tensors, SpecStruct):
    return True
  if not isinstance(spec_or_tensors, collections_abc.Mapping):
    return False
  return all(_is_leaf(v) for v in spec_or_tensors.values())


def flatten_spec_structure(spec_or_tensors,
                           filter_none: bool = True) -> SpecStruct:
  """Flattens any supported hierarchy into a SpecStruct of joined paths."""
  assert_valid_spec_structure(spec_or_tensors)
  return SpecStruct(_iter_flat(spec_or_tensors, filter_none=filter_none))


def pack_flat_sequence_to_spec_structure(
    spec_structure, flat_sequence) -> SpecStruct:
  """Packs a flat path->tensor mapping into the expected spec hierarchy.

  Optional specs with no matching tensor are dropped; required specs with no
  matching tensor raise.
  """
  assert_valid_spec_structure(spec_structure)
  expected_flat = flatten_spec_structure(spec_structure, filter_none=False)
  if not is_flat_spec_or_tensors_structure(flat_sequence):
    flat_sequence = flatten_spec_structure(flat_sequence)
  flat = dict(flat_sequence.items())

  packed = SpecStruct()
  for key, spec in sorted(expected_flat.items()):
    if key in flat:
      packed[key] = flat[key]
      continue
    if spec is None:
      continue
    if getattr(spec, 'is_optional', False):
      continue
    raise ValueError(
        f'The required spec {key!r} ({spec}) is not available; provided keys: '
        f'{sorted(flat)}')
  return packed


def maybe_ignore_batch(spec_or_tensors, ignore_batch: bool = False):
  """Strips the leading (batch) dim from every spec/tensor's shape."""
  if not ignore_batch:
    return spec_or_tensors

  def strip(value):
    if value is None:
      return None
    spec = TensorSpec.to_spec(value)
    if not spec.shape:
      raise ValueError(f'Cannot ignore batch dim of scalar spec {spec}.')
    return TensorSpec.from_spec(spec, shape=spec.shape[1:])

  flat = flatten_spec_structure(spec_or_tensors, filter_none=False)
  return SpecStruct((k, strip(v)) for k, v in flat.to_dict().items())


def assert_equal_spec_or_tensor(expected_spec_or_tensor,
                                actual_spec_or_tensor) -> None:
  """Checks dtype and per-dim shape (None = wildcard) of a single leaf."""
  expected = TensorSpec.to_spec(expected_spec_or_tensor)
  actual = TensorSpec.to_spec(actual_spec_or_tensor)
  # A sequence spec declares per-step shape; an extracted tensor carries the
  # sequence dim in its shape, so strip one leading dim before comparing.
  if expected.is_sequence and actual.is_extracted:
    actual = TensorSpec.from_spec(actual, shape=actual.shape[1:])
  if expected.dtype != actual.dtype:
    raise ValueError(
        f'dtype mismatch: expected {expected.dtype} got {actual.dtype}\n'
        f' expected: {expected}\n actual: {actual}')
  if len(expected.shape) != len(actual.shape):
    raise ValueError(
        f'rank mismatch: expected {expected.shape} got {actual.shape}\n'
        f' expected: {expected}\n actual: {actual}')
  for expected_dim, actual_dim in zip(expected.shape, actual.shape):
    if expected_dim is None or actual_dim is None:
      continue
    if expected_dim != actual_dim:
      raise ValueError(
          f'shape mismatch: expected {expected.shape} got {actual.shape}')


def assert_equal(expected_tensors_or_spec,
                 actual_tensors_or_spec,
                 ignore_batch: bool = False) -> None:
  """Asserts both structures have identical keys, dtypes and shapes."""
  actual = maybe_ignore_batch(actual_tensors_or_spec, ignore_batch)
  expected_flat = flatten_spec_structure(expected_tensors_or_spec)
  actual_flat = flatten_spec_structure(actual)
  if set(expected_flat.keys()) != set(actual_flat.keys()):
    missing = set(expected_flat) - set(actual_flat)
    extra = set(actual_flat) - set(expected_flat)
    raise ValueError(
        f'Structure mismatch; missing: {sorted(missing)}, '
        f'unexpected: {sorted(extra)}')
  for key in expected_flat:
    assert_equal_spec_or_tensor(expected_flat[key], actual_flat[key])


def assert_required(expected_spec,
                    actual_tensors_or_spec,
                    ignore_batch: bool = False) -> None:
  """Asserts all *required* expected specs are fulfilled by the actual data."""
  flat_actual = flatten_spec_structure(actual_tensors_or_spec)
  # Packing raises if a required spec has no tensor, and drops optionals
  # without data — after it, key sets are directly comparable.
  packed = pack_flat_sequence_to_spec_structure(expected_spec, flat_actual)
  flat_packed = flatten_spec_structure(packed)
  expected_flat = flatten_spec_structure(expected_spec)
  expected_subset = SpecStruct(
      (k, v) for k, v in expected_flat.items() if k in flat_packed)
  assert_equal(expected_subset, flat_packed, ignore_batch)


def validate_and_flatten(expected_spec,
                         actual_tensors_or_spec,
                         ignore_batch: bool = False) -> SpecStruct:
  """Validates required specs then returns the *actual* data flattened."""
  assert_required(expected_spec, actual_tensors_or_spec, ignore_batch)
  return flatten_spec_structure(actual_tensors_or_spec)


def validate_and_pack(expected_spec,
                      actual_tensors_or_spec,
                      ignore_batch: bool = False) -> SpecStruct:
  """Validates required specs then packs the data into the spec hierarchy."""
  if not is_flat_spec_or_tensors_structure(actual_tensors_or_spec):
    actual_tensors_or_spec = flatten_spec_structure(actual_tensors_or_spec)
  assert_required(expected_spec, actual_tensors_or_spec, ignore_batch)
  return pack_flat_sequence_to_spec_structure(expected_spec,
                                              actual_tensors_or_spec)


def copy_spec_structure(spec_structure,
                        prefix: str = '',
                        batch_size: int = -1) -> SpecStruct:
  """Deep-copies a spec structure, optionally renaming and batching.

  ``prefix`` is prepended to every spec *name* (reference: ``copy_tensorspec``
  prefixing for meta-learning condition/inference splits). ``batch_size``
  follows :meth:`TensorSpec.from_spec` semantics.
  """
  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for key, value in flat.items():
    spec = TensorSpec.to_spec(value)
    name = spec.name or key.split(_SEP)[-1]
    if prefix:
      name = prefix + _SEP + name
    out[key] = TensorSpec.from_spec(spec, name=name, batch_size=batch_size)
  return out


# Reference-compatible alias.
copy_tensorspec = copy_spec_structure


def filter_required_flat_tensor_spec(flat_tensor_spec) -> SpecStruct:
  """Subset containing only non-optional specs."""
  if not is_flat_spec_or_tensors_structure(flat_tensor_spec):
    raise ValueError(f'Expected a flat structure, got {flat_tensor_spec!r}')
  return SpecStruct(
      (k, v) for k, v in flat_tensor_spec.items()
      if not getattr(v, 'is_optional', False))


def filter_spec_structure_by_dataset(spec_structure,
                                     dataset_key: str) -> SpecStruct:
  """Subset whose specs route to ``dataset_key`` (everything if '' / None)."""
  flat = flatten_spec_structure(spec_structure)
  return SpecStruct(
      (k, v) for k, v in flat.items()
      if not dataset_key or getattr(v, 'dataset_key', '') == dataset_key)


def add_sequence_length_specs(spec_structure) -> SpecStruct:
  """Adds '<key>_length' int64 scalar specs for every sequence spec."""
  flat = flatten_spec_structure(spec_structure)
  out = flat.copy()
  for key, value in flat.items():
    if getattr(value, 'is_sequence', False):
      out[key + '_length'] = TensorSpec(
          shape=(), dtype=np.int64,
          name=(value.name or key.split(_SEP)[-1]) + '_length',
          dataset_key=value.dataset_key)
  return out


def spec_names(spec_structure) -> 'collections.OrderedDict[str, TensorSpec]':
  """Maps unique spec *names* -> specs (the serialized-data key space).

  Mirrors the reference's guarantee (README.md:138-143): a name may be shared
  by several paths only if those specs are equal — otherwise the data<->model
  mapping would be ambiguous.
  """
  flat = flatten_spec_structure(spec_structure)
  by_name = collections.OrderedDict()
  for key, value in flat.items():
    spec = TensorSpec.to_spec(value)
    name = spec.name or key.split(_SEP)[-1]
    if name in by_name and by_name[name] != spec:
      raise ValueError(
          f'Duplicate spec name {name!r} with differing specs:\n'
          f'  {by_name[name]}\n  {spec}')
    by_name[name] = spec
  return by_name


def tensorspec_from_tensors(tensors) -> SpecStruct:
  """Extracted specs for a structure of concrete tensors."""
  flat = flatten_spec_structure(tensors)
  return SpecStruct((k, TensorSpec.from_array(v, name=k.split(_SEP)[-1]))
                    for k, v in flat.items())


def pad_or_clip_to_spec_shape(array: np.ndarray, spec: TensorSpec):
  """Pads (with varlen_default_value) or clips dim 0 to the spec's shape.

  Host-side numpy equivalent of the reference's VarLen densify step
  (``utils/tensorspec_utils.py:1626-1677``).
  """
  if spec.varlen_default_value is None:
    return array
  target = spec.shape[0]
  if target is None:
    return array
  length = array.shape[0]
  if length >= target:
    return array[:target]
  pad_value = np.asarray(spec.varlen_default_value, dtype=array.dtype)
  padding = np.full((target - length,) + array.shape[1:], pad_value,
                    dtype=array.dtype)
  return np.concatenate([array, padding], axis=0)
