"""Spec-driven numpy/jax data generation and feed-dict mapping.

Capability-equivalent of the reference's placeholder/numpy helpers
(``/root/reference/utils/tensorspec_utils.py:778-1035``). There are no TF
placeholders in a JAX program; the analogue of ``make_placeholders`` is a
structure of ``jax.ShapeDtypeStruct`` used for ``jax.eval_shape`` /
ahead-of-time lowering, and the analogue of the feed-dict is a name-keyed
numpy dict handed to a predictor.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from tensor2robot_tpu.specs.algebra import (flatten_spec_structure,
                                            pack_flat_sequence_to_spec_structure)
from tensor2robot_tpu.specs.spec_struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec

_DEFAULT_SEQUENCE_LENGTH = 3


def _concrete_shape(spec: TensorSpec,
                    batch_size: Optional[int],
                    sequence_length: int) -> tuple:
  shape = tuple(1 if d is None else d for d in spec.shape)
  if spec.is_sequence and not spec.is_extracted:
    shape = (sequence_length,) + shape
  if batch_size is not None and batch_size != -1:
    shape = (batch_size,) + shape
  return shape


def make_shape_dtype_structs(spec_structure,
                             batch_size: Optional[int] = None) -> SpecStruct:
  """SpecStruct of jax.ShapeDtypeStruct — the jit-facing 'placeholders'."""
  import jax

  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for key, value in flat.items():
    spec = TensorSpec.to_spec(value)
    shape = _concrete_shape(spec, batch_size, _DEFAULT_SEQUENCE_LENGTH)
    out[key] = jax.ShapeDtypeStruct(shape, spec.dtype)
  return out


# Reference-compatible alias: in TF land these were graph placeholders.
make_placeholders = make_shape_dtype_structs


def make_constant_numpy(spec_structure,
                        constant_value,
                        batch_size: int = 2,
                        sequence_length: int = _DEFAULT_SEQUENCE_LENGTH
                        ) -> SpecStruct:
  """Constant-filled numpy arrays shaped like the spec structure."""
  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for key, value in flat.items():
    spec = TensorSpec.to_spec(value)
    shape = _concrete_shape(spec, batch_size, sequence_length)
    out[key] = np.full(shape, constant_value, dtype=spec.dtype)
  return out


def make_random_numpy(spec_structure,
                      batch_size: int = 2,
                      sequence_length: int = _DEFAULT_SEQUENCE_LENGTH,
                      seed: Optional[int] = None) -> SpecStruct:
  """Random numpy arrays shaped like the spec structure.

  Float dtypes get uniform [0,1); int dtypes get uniform [0, 2) for bools and
  [0, 255] for uint8 images, [0, 10) otherwise.
  """
  rng = np.random.default_rng(seed)
  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for key, value in flat.items():
    spec = TensorSpec.to_spec(value)
    shape = _concrete_shape(spec, batch_size, sequence_length)
    if spec.dtype == np.bool_:
      out[key] = rng.integers(0, 2, size=shape).astype(np.bool_)
    elif np.issubdtype(spec.dtype, np.integer):
      high = 256 if spec.dtype == np.uint8 else 10
      out[key] = rng.integers(0, high, size=shape).astype(spec.dtype)
    else:
      out[key] = rng.random(size=shape).astype(spec.dtype)
  return out


def make_random_arrays(spec_structure,
                       batch_size: int = 2,
                       seed: int = 0) -> SpecStruct:
  """Random *jax* arrays shaped like the spec structure (device-side)."""
  import jax
  import jax.numpy as jnp

  key = jax.random.PRNGKey(seed)
  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for path, value in flat.items():
    spec = TensorSpec.to_spec(value)
    shape = _concrete_shape(spec, batch_size, _DEFAULT_SEQUENCE_LENGTH)
    key, sub = jax.random.split(key)
    if np.issubdtype(spec.dtype, np.integer):
      out[path] = jax.random.randint(sub, shape, 0, 10).astype(spec.dtype)
    elif spec.dtype == np.bool_:
      out[path] = jax.random.bernoulli(sub, 0.5, shape)
    else:
      out[path] = jax.random.uniform(sub, shape, dtype=jnp.float32).astype(
          spec.dtype)
  return out


def map_feed_dict(spec_structure, numpy_inputs,
                  ignore_batch: bool = False) -> dict:
  """Maps a hierarchy of numpy inputs onto the spec's *name* key space.

  This is the predictor-boundary mapping: serialized/served models address
  tensors by spec name, while in-process code addresses them by path.
  """
  from tensor2robot_tpu.specs import algebra

  flat_spec = flatten_spec_structure(spec_structure)
  flat_np = flatten_spec_structure(numpy_inputs)
  feed = {}
  for key, value in flat_spec.items():
    spec = TensorSpec.to_spec(value)
    if key not in flat_np:
      if spec.is_optional:
        continue
      raise ValueError(f'Missing required feed input for {key!r} ({spec}).')
    array = np.asarray(flat_np[key])
    algebra.assert_equal_spec_or_tensor(
        spec, algebra.maybe_ignore_batch(
            SpecStruct({key: TensorSpec.from_array(array)}),
            ignore_batch)[key])
    name = spec.name or key.split('/')[-1]
    if name in feed and not np.array_equal(feed[name], array):
      raise ValueError(
          f'Conflicting values for shared feed name {name!r}.')
    feed[name] = array
  return feed


def pack_feed_dict(spec_structure, name_keyed_inputs) -> SpecStruct:
  """Inverse of :func:`map_feed_dict`: name-keyed arrays -> packed struct."""
  flat_spec = flatten_spec_structure(spec_structure)
  by_path = {}
  for key, value in flat_spec.items():
    spec = TensorSpec.to_spec(value)
    name = spec.name or key.split('/')[-1]
    if name in name_keyed_inputs:
      by_path[key] = np.asarray(name_keyed_inputs[name])
  return pack_flat_sequence_to_spec_structure(spec_structure, by_path)
