"""Blocking-call-under-lock checker.

A thread that blocks while holding a lock is one handshake away from a
deadlock: the operation it waits on (a worker joining, a queue filling,
a device transfer draining) frequently needs that same lock — or a lock
ordered after it — to make progress. The engine-iterator release waiver
in PR 8 documents a REAL instance of the shape: ``__next__`` holding the
position lock while blocked on the ring awaiting the very ``release()``
that needs the loop to advance. This checker flags the mechanical
signature so the next one never lands:

* ``.join()`` (thread/process join: no positional args, or a single
  numeric timeout) inside a ``with <lock>:`` block — ``', '.join(parts)``
  and other string joins are excluded by their non-numeric argument;
* ``.get()`` / ``.result()`` with no positional args (queue/future
  blocking reads; ``d.get(key)`` dict lookups have arguments and are
  excluded) and their ``timeout=``/``block=`` keyword forms;
* ``jax.device_get(...)`` / ``jax.block_until_ready(...)`` — a device
  sync can stall for a full dispatch (or forever, when the data plane is
  wedged — the exact regime the distributed control plane exists for);
* ``.wait_until_finished()`` — Orbax's async-checkpoint drain, which in
  multi-host runs barriers across the job.

A ``with`` target counts as a lock when it is (a) an attribute/global
assigned from ``threading.Lock/RLock/Condition`` (or a
``ReaderWriterLock``) anywhere in the module, (b) a
``rw.read_locked()``/``rw.write_locked()`` context manager, or (c) a
name whose final component looks lock-ish (``lock``/``cond``/
``mutex``/``mu``). Waive genuinely-bounded cases inline with
``# ANALYSIS_OK(blocking-under-lock): <why the wait cannot need the
lock>``.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tensor2robot_tpu.analysis import core

RULE = 'blocking-under-lock'
CHECK = 'blocking-call-under-lock'

_LOCK_CTORS = {
    'threading.Lock', 'threading.RLock', 'threading.Condition',
    'Lock', 'RLock', 'Condition', 'ReaderWriterLock',
    'concurrency.ReaderWriterLock',
}
_RW_METHODS = {'read_locked', 'write_locked'}
_NAME_HINTS = ('lock', 'cond', 'mutex', 'mu')

# Leaf call names that always block regardless of arguments.
_ALWAYS_BLOCKING = {'device_get', 'block_until_ready',
                    'wait_until_finished'}
_BLOCK_KWARGS = {'timeout', 'block', 'timeout_secs', 'timeout_in_ms'}


def _known_locks(module: core.ModuleInfo) -> Set[str]:
  """Attr/global names assigned a lock constructor anywhere in the
  module — ``self._lock = threading.Lock()`` yields ``self._lock``."""
  locks: Set[str] = set()
  for node in ast.walk(module.tree):
    if not isinstance(node, ast.Assign):
      continue
    value = node.value
    if not isinstance(value, ast.Call):
      continue
    name = core.call_name(value)
    if name is None:
      continue
    if name in _LOCK_CTORS or name.rsplit('.', 1)[-1] in (
        'Lock', 'RLock', 'Condition', 'ReaderWriterLock'):
      for target in node.targets:
        text = core.expr_text(target)
        if text is not None:
          locks.add(text)
  return locks


def _lock_of_withitem(item: ast.withitem,
                      known: Set[str]) -> Optional[str]:
  """The lock a withitem holds, or None when it is not lock-shaped."""
  ctx = item.context_expr
  text = core.expr_text(ctx)
  if text is not None:
    leaf = text.rsplit('.', 1)[-1].lower().strip('_')
    if text in known or any(h in leaf for h in _NAME_HINTS):
      return text
    return None
  if isinstance(ctx, ast.Call):
    name = core.call_name(ctx)
    if name is not None:
      base, _, leaf = name.rpartition('.')
      if leaf in _RW_METHODS and base:
        return base
  return None


def _blocking_reason(call: ast.Call) -> Optional[str]:
  name = core.call_name(call)
  if name is None:
    return None
  leaf = name.rsplit('.', 1)[-1]
  if leaf in _ALWAYS_BLOCKING:
    return (f'{leaf}() synchronizes with the device/writer and can '
            'stall indefinitely')
  has_receiver = '.' in name
  kwargs = {kw.arg for kw in call.keywords if kw.arg}
  if leaf == 'join' and has_receiver:
    if not call.args and not (kwargs - _BLOCK_KWARGS):
      return ('join() blocks until the target thread/process exits — '
              'which may itself need this lock')
    if (len(call.args) == 1 and isinstance(call.args[0], ast.Constant)
        and isinstance(call.args[0].value, (int, float))):
      return 'join(timeout) still blocks for the full timeout'
  if leaf in ('get', 'result') and has_receiver:
    if not call.args and not (kwargs - _BLOCK_KWARGS):
      return (f'{leaf}() on a queue/future blocks until a producer runs '
              '— which may itself need this lock')
  return None


def check(module: core.ModuleInfo, program: core.Program
          ) -> List[core.Finding]:
  del program
  findings: List[core.Finding] = []
  known = _known_locks(module)

  def symbol_of(node: ast.AST) -> str:
    enclosing = module.enclosing(
        node, (ast.FunctionDef, ast.AsyncFunctionDef))
    return core.qualname(module, enclosing) if enclosing else ''

  def scan_with(with_node: ast.With, lock_text: str) -> None:
    for stmt in with_node.body:
      for node in core.walk_scope(stmt):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
          continue  # nested defs run later, not under this lock
        if isinstance(node, ast.Call):
          reason = _blocking_reason(node)
          if reason is not None:
            findings.append(core.Finding(
                rule=RULE, check=CHECK, path=module.rel_path,
                line=node.lineno, symbol=symbol_of(node),
                message=(f'blocking call while holding {lock_text!r}: '
                         f'{reason}. Snapshot under the lock, then '
                         'block outside it.')))

  for node in ast.walk(module.tree):
    if isinstance(node, ast.With):
      for item in node.items:
        lock_text = _lock_of_withitem(item, known)
        if lock_text is not None:
          scan_with(node, lock_text)
  return findings
