"""Donated-buffer-reuse checker.

``donate_argnums`` hands an argument's device buffers to XLA for in-place
reuse: after the call, the CALLER's array is invalid — reading it
returns garbage or raises a deleted-buffer error, but only on backends
that actually donate (TPU), so the bug ships silently past CPU tests.
The idiomatic pattern rebinds the result over the donated name
(``state = step(state, batch)``), which this checker recognizes. Three
finding shapes (the bug class ``train_state.py``'s donation comments
warn about):

* ``use-after-donate`` — a name passed at a donated position of a
  known-donating callable (a name bound from ``jax.jit(...,
  donate_argnums=...)`` directly or via a local factory that returns
  one) is READ later in the same scope without being rebound first.
* ``aliased-donation`` — the same name appears at a donated position
  AND anywhere else in the same call's arguments: two views of one
  buffer enter the program, one of them donated — the "sharing buffers
  would donate the same buffer twice" hazard that forces
  ``ema_params`` to start as a copy.
* ``stale-scan-carry`` — the INIT carry passed to ``lax.scan`` is read
  after the scan whose result was bound to a different name. XLA
  updates the carry in place across iterations (donated scan carry);
  outside a trace the buffer is gone, and even inside one, reading the
  pre-scan value where the result exists is almost always a stale-value
  bug (the result name was bound for a reason).

Waive intentional reads inline with ``# ANALYSIS_OK(donated-reuse):
<why the buffer is still valid / the read is pre-donation on every
backend>``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tensor2robot_tpu.analysis import core

RULE = 'donated-reuse'

_JIT_WRAPPERS = {'jax.jit', 'jit', 'jax.pjit', 'pjit'}
_SCAN_NAMES = {'lax.scan', 'jax.lax.scan'}


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
  """The donate_argnums of a jit(...) call, or None when absent."""
  if core.call_name(call) not in _JIT_WRAPPERS:
    return None
  for kw in call.keywords:
    if kw.arg not in ('donate_argnums', 'donate_argnames'):
      continue
    value = kw.value
    if isinstance(value, ast.Constant) and isinstance(value.value, int):
      return (value.value,)
    if isinstance(value, (ast.Tuple, ast.List)):
      out = []
      for elt in value.elts:
        if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
          out.append(elt.value)
      return tuple(out)
    return ()  # dynamic spec: donating, positions unknown
  return None


def _donating_names(module: core.ModuleInfo) -> Dict[str, Tuple[int, ...]]:
  """Names bound to donating jitted callables → donated positions."""
  # Local factories whose return value is a donating jit.
  factory_positions: Dict[str, Tuple[int, ...]] = {}
  for fn in core.func_defs(module.tree):
    for node in ast.walk(fn):
      if isinstance(node, ast.Return) and isinstance(node.value, ast.Call):
        positions = _donated_positions(node.value)
        if positions:
          factory_positions[fn.name] = positions
  donating: Dict[str, Tuple[int, ...]] = {}
  for node in ast.walk(module.tree):
    if not isinstance(node, ast.Assign):
      continue
    value = node.value
    positions: Optional[Tuple[int, ...]] = None
    if isinstance(value, ast.Call):
      positions = _donated_positions(value)
      if not positions:
        name = core.call_name(value)
        if name is not None:
          leaf = name.rsplit('.', 1)[-1]
          positions = factory_positions.get(name,
                                            factory_positions.get(leaf))
    if positions:
      for target in node.targets:
        text = core.expr_text(target)
        if text is not None:
          donating[text] = positions
  return donating


def _assigned_names(stmt: ast.AST) -> Set[str]:
  """Names (re)bound by the statement containing a call."""
  out: Set[str] = set()
  targets: Iterable[ast.AST] = ()
  if isinstance(stmt, ast.Assign):
    targets = stmt.targets
  elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
    targets = (stmt.target,)
  for target in targets:
    for node in ast.walk(target):
      if isinstance(node, ast.Name):
        out.add(node.id)
  return out


def _containing_stmt(module: core.ModuleInfo, node: ast.AST) -> ast.AST:
  cur, parent = node, module.parent(node)
  while parent is not None and not isinstance(parent, (
      ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
    cur, parent = parent, module.parent(parent)
  return cur


def _reads_after(scope: ast.AST, names: Set[str], after_line: int
                 ) -> Dict[str, ast.Name]:
  """First read of each watched name after ``after_line`` in ``scope``,
  with a later rebind killing the watch for lines beyond it."""
  rebinds: Dict[str, int] = {}
  for node in core.walk_scope(scope):
    if isinstance(node, ast.Name) and isinstance(
        node.ctx, (ast.Store,)) and node.id in names:
      if node.lineno > after_line:
        line = rebinds.get(node.id)
        rebinds[node.id] = min(line, node.lineno) if line else node.lineno
  first_reads: Dict[str, ast.Name] = {}
  for node in core.walk_scope(scope):
    if not (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)):
      continue
    if node.id not in names or node.lineno <= after_line:
      continue
    rebind_line = rebinds.get(node.id)
    if rebind_line is not None and node.lineno > rebind_line:
      continue  # rebound before this read
    seen = first_reads.get(node.id)
    if seen is None or node.lineno < seen.lineno:
      first_reads[node.id] = node
  return first_reads


def check(module: core.ModuleInfo, program: core.Program
          ) -> List[core.Finding]:
  del program
  findings: List[core.Finding] = []
  donating = _donating_names(module)

  def scopes():
    yield module.tree
    yield from core.func_defs(module.tree)

  for scope in scopes():
    for node in core.walk_scope(scope):
      if not isinstance(node, ast.Call):
        continue
      name = core.call_name(node)
      if name is None:
        continue
      symbol = core.qualname(module, scope) if isinstance(
          scope, (ast.FunctionDef, ast.AsyncFunctionDef)) else ''
      if name in donating:
        positions = donating[name]
        donated = {node.args[i] for i in positions if i < len(node.args)}
        donated_names = {a.id for a in donated if isinstance(a, ast.Name)}
        # aliased-donation: the same name enters the call twice with at
        # least one donated position.
        all_arg_names = [a.id for a in node.args
                         if isinstance(a, ast.Name)]
        for dup in sorted(donated_names):
          if all_arg_names.count(dup) > 1:
            findings.append(core.Finding(
                rule=RULE, check='aliased-donation', path=module.rel_path,
                line=node.lineno, symbol=symbol,
                message=(f'{dup!r} is passed to donating {name}(...) '
                         'more than once with a donated position: both '
                         'views share one buffer and XLA will reuse it '
                         'in place — pass a copy for the second view')))
        stmt = _containing_stmt(module, node)
        watch = donated_names - _assigned_names(stmt)
        for read_name, read in sorted(
            _reads_after(scope, watch, node.lineno).items()):
          findings.append(core.Finding(
              rule=RULE, check='use-after-donate', path=module.rel_path,
              line=read.lineno, symbol=symbol,
              message=(f'{read_name!r} was donated to {name}(...) at '
                       f'line {node.lineno} (donate_argnums) — its '
                       'device buffer is invalid after the call on '
                       'donating backends. Rebind the result over it, '
                       'or read before the call.')))
      elif name in _SCAN_NAMES and len(node.args) >= 2:
        init = node.args[1]
        if not isinstance(init, ast.Name):
          continue
        stmt = _containing_stmt(module, node)
        if init.id in _assigned_names(stmt):
          continue  # carry rebound over itself: the idiomatic form
        reads = _reads_after(scope, {init.id}, node.lineno)
        if init.id in reads:
          findings.append(core.Finding(
              rule=RULE, check='stale-scan-carry', path=module.rel_path,
              line=reads[init.id].lineno, symbol=symbol,
              message=(f'{init.id!r} is the initial carry of the '
                       f'lax.scan at line {node.lineno}, read again '
                       'after the scan: the carry buffer is donated '
                       'across iterations (XLA updates it in place) and '
                       'the pre-scan value is stale where the scan '
                       'result exists — use the returned carry.')))
  return findings
