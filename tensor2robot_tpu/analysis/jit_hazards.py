"""Jit tracing-hazard checker.

Finds functions reachable from ``jax.jit`` / ``pjit`` / ``lax.scan``
(also ``while_loop``/``cond``/``fori_loop``) call sites — including
factory methods whose RETURN is jitted (``jax.jit(self._step_body())``
marks ``_step_body`` and every def nested in it) — and flags host-world
operations that either silently freeze at trace time or crash under a
tracer:

* ``host-side-effect`` — registry counter/gauge/histogram calls,
  ``time.*``, ``logging.*``, ``print``/``open``, ``os``/``io``/``sys``
  calls, tracing spans. Inside a traced function these run ONCE at
  trace time (metrics silently stop counting — the PR-2 failure shape)
  or not at all on retrace.
* ``tracer-leak`` — ``float()`` / ``int()`` / ``bool()`` / ``.item()``
  / ``.tolist()`` on a tracer-typed name, and bare ``if tracer:`` tests
  (TracerBoolConversionError at trace time). "Tracer-typed" is a
  per-function taint: the traced function's parameters and anything
  assigned from them.
* ``numpy-on-tracer`` — raw ``np.*`` calls on traced values (XLA can't
  stage them; they concretize or crash). Shape/dtype queries
  (``np.shape``/``np.ndim``/``np.result_type``) are exempt.
* ``rng-key-reuse`` — the same key name passed to two ``random.*``
  consumers with no intervening ``split``/``fold_in`` rebinding
  (branches are analyzed separately; loop bodies twice, so reuse
  ACROSS iterations is caught).

Resolution is per-module and name-based (bare names and
``self._method`` only) — cross-module jit targets are out of scope by
design; the checker must stay zero-false-positive enough to gate tier-1.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tensor2robot_tpu.analysis import core

RULE = 'jit-hazard'

_TRACE_ENTRY_CALLS = {
    'jax.jit', 'jax.pjit', 'jit', 'pjit',
    'jax.lax.scan', 'lax.scan', 'jax.lax.while_loop', 'lax.while_loop',
    'jax.lax.cond', 'lax.cond', 'jax.lax.fori_loop', 'lax.fori_loop',
}
_HOST_MODULE_ROOTS = {'time', 'logging', 'os', 'io', 'sys', 'shutil',
                      'tracing', 'metrics', 'metrics_lib', 'tracing_lib'}
_HOST_BUILTINS = {'print', 'open', 'input'}
_METRIC_METHODS = {'inc', 'observe', 'set', 'add'}
_NUMPY_EXEMPT = {'shape', 'ndim', 'result_type', 'dtype'}
_RNG_NON_CONSUMERS = {'PRNGKey', 'key'}


def _leaf(name: str) -> str:
  return name.rsplit('.', 1)[-1]


class _DefIndex:
  """Name-based def lookup within one module."""

  def __init__(self, module: core.ModuleInfo):
    self.module = module
    self.defs: List[ast.FunctionDef] = list(core.func_defs(module.tree))
    self.by_name: Dict[str, List[ast.FunctionDef]] = {}
    for fn in self.defs:
      self.by_name.setdefault(fn.name, []).append(fn)

  def resolve(self, name: str, from_node: ast.AST
              ) -> Optional[ast.FunctionDef]:
    """Bare ``f`` or ``self.m`` -> a local def, nearest-scope first."""
    if name.startswith('self.'):
      name = name[5:]
    if '.' in name:
      return None
    candidates = self.by_name.get(name)
    if not candidates:
      return None
    if len(candidates) == 1:
      return candidates[0]
    # Prefer a candidate sharing an enclosing scope with the reference.
    cur = self.module.parent(from_node)
    while cur is not None:
      for cand in candidates:
        if self.module.parent(cand) is cur:
          return cand
      cur = self.module.parent(cur)
    return candidates[0]


def _jit_targets(module: core.ModuleInfo, index: _DefIndex
                 ) -> Set[ast.FunctionDef]:
  """Defs traced by jit/pjit/scan: direct args, factory returns,
  decorated defs — plus everything nested inside any of them."""
  roots: Set[ast.FunctionDef] = set()

  def mark_expr(expr: ast.AST, site: ast.AST):
    if isinstance(expr, ast.Lambda):
      return  # lambda bodies are walked by their enclosing def's pass
    text = core.expr_text(expr)
    if text is not None:
      target = index.resolve(text, site)
      if target is not None:
        roots.add(target)
      return
    if isinstance(expr, ast.Call):
      # jax.jit(self._step_body()): the FACTORY's returned closure is
      # traced — mark the factory; its nested defs follow below.
      name = core.call_name(expr)
      if name is not None:
        target = index.resolve(name, site)
        if target is not None:
          roots.add(target)

  for node in ast.walk(module.tree):
    if isinstance(node, ast.Call):
      name = core.call_name(node)
      if name in _TRACE_ENTRY_CALLS and node.args:
        mark_expr(node.args[0], node)
    elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
      for dec in node.decorator_list:
        dec_name = core.expr_text(dec)
        if dec_name in _TRACE_ENTRY_CALLS:
          roots.add(node)
        elif isinstance(dec, ast.Call):
          dname = core.call_name(dec)
          if dname in _TRACE_ENTRY_CALLS:
            roots.add(node)
          elif dname in ('functools.partial', 'partial') and dec.args:
            inner = core.expr_text(dec.args[0])
            if inner in _TRACE_ENTRY_CALLS:
              roots.add(node)

  # Reachability: local calls from traced defs + nested defs.
  reachable: Set[ast.FunctionDef] = set()
  frontier = list(roots)
  while frontier:
    fn = frontier.pop()
    if fn in reachable:
      continue
    reachable.add(fn)
    for node in ast.walk(fn):
      if (node is not fn and
          isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))):
        if node not in reachable:
          frontier.append(node)
      elif isinstance(node, ast.Call):
        name = core.call_name(node)
        if name is None:
          continue
        if name.startswith('self.') or '.' not in name:
          target = index.resolve(name, node)
          if target is not None and target not in reachable:
            frontier.append(target)
  return reachable


def _tainted_names(fn: ast.FunctionDef) -> Set[str]:
  """Params + names assigned from them (two propagation passes)."""
  args = fn.args
  tainted: Set[str] = set()
  for a in (list(args.posonlyargs) + list(args.args) +
            list(args.kwonlyargs) +
            ([args.vararg] if args.vararg else []) +
            ([args.kwarg] if args.kwarg else [])):
    if a.arg != 'self':
      tainted.add(a.arg)
  for _ in range(2):
    for node in core.walk_scope(fn):
      if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
          node is not fn):
        continue
      if isinstance(node, ast.Assign):
        rhs_names = {n.id for n in ast.walk(node.value)
                     if isinstance(n, ast.Name)}
        if rhs_names & tainted:
          for target in node.targets:
            for n in ast.walk(target):
              if isinstance(n, ast.Name):
                tainted.add(n.id)
  return tainted


def _is_tainted(expr: ast.AST, tainted: Set[str]) -> bool:
  return any(isinstance(n, ast.Name) and n.id in tainted
             for n in ast.walk(expr))


def check(module: core.ModuleInfo, program: core.Program
          ) -> List[core.Finding]:
  del program
  index = _DefIndex(module)
  reachable = _jit_targets(module, index)
  findings: List[core.Finding] = []
  for fn in sorted(reachable, key=lambda f: f.lineno):
    symbol = core.qualname(module, fn)
    tainted = _tainted_names(fn)

    def flag(check: str, node: ast.AST, message: str, symbol=symbol):
      findings.append(core.Finding(
          rule=RULE, check=check, path=module.rel_path,
          line=node.lineno, symbol=symbol, message=message))

    for node in core.walk_scope(fn):
      if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
          node is not fn):
        continue  # nested defs are themselves in `reachable`
      if isinstance(node, ast.Call):
        name = core.call_name(node)
        if name is None:
          continue
        root = name.split('.', 1)[0]
        receiver = name.rpartition('.')[0]
        leaf = _leaf(name)
        if (root in _HOST_MODULE_ROOTS or name in _HOST_BUILTINS or
            ('._m_' in f'.{name}' and leaf in _METRIC_METHODS)):
          flag('host-side-effect', node,
               f'host side effect {name}(...) inside a jit-traced '
               f'function: runs once at trace time, never per step')
        elif name in ('float', 'int', 'bool') and node.args and _is_tainted(
            node.args[0], tainted):
          flag('tracer-leak', node,
               f'{name}() on a traced value forces host concretization '
               '(TracerConversionError under jit)')
        elif (leaf in ('item', 'tolist') and
              isinstance(node.func, ast.Attribute) and
              _is_tainted(node.func.value, tainted)):
          flag('tracer-leak', node,
               f'.{leaf}() on a traced value forces host concretization')
        elif root in ('np', 'numpy') and leaf not in _NUMPY_EXEMPT and any(
            _is_tainted(a, tainted) for a in node.args):
          flag('numpy-on-tracer', node,
               f'raw numpy call {name}(...) on a traced value: XLA '
               'cannot stage it (concretizes or crashes); use jnp')
      elif isinstance(node, ast.If):
        test = node.test
        if isinstance(test, ast.Name) and test.id in tainted:
          flag('tracer-leak', node,
               f"'if {test.id}:' coerces a traced value to bool at "
               'trace time; use lax.cond / jnp.where')
    findings.extend(_rng_reuse(module, fn, symbol))
  return findings


# ------------------------------------------------------------- rng reuse


def _rng_reuse(module: core.ModuleInfo, fn: ast.FunctionDef,
               symbol: str) -> List[core.Finding]:
  findings: List[core.Finding] = []

  def run(stmts, consumed: Set[str]) -> Set[str]:
    for stmt in stmts:
      consumed = run_stmt(stmt, consumed)
    return consumed

  def note_call(node: ast.Call, consumed: Set[str]) -> Set[str]:
    name = core.call_name(node)
    if name is None:
      return consumed
    parts = name.split('.')
    is_random = 'random' in parts[:-1] or (
        len(parts) == 1 and parts[0] in ('split', 'fold_in'))
    if not is_random or parts[-1] in _RNG_NON_CONSUMERS:
      return consumed
    if not node.args:
      return consumed
    key = node.args[0]
    if isinstance(key, ast.Name):
      if key.id in consumed:
        findings.append(core.Finding(
            rule=RULE, check='rng-key-reuse', path=module.rel_path,
            line=node.lineno, symbol=symbol,
            message=(f'rng key {key.id!r} consumed again by '
                     f'{name}(...) without an intervening split/'
                     'fold_in rebinding: correlated randomness')))
      else:
        consumed = consumed | {key.id}
    return consumed

  def run_expr(node: ast.AST, consumed: Set[str]) -> Set[str]:
    for sub in ast.walk(node):
      if isinstance(sub, ast.Call):
        consumed = note_call(sub, consumed)
    return consumed

  def run_stmt(stmt: ast.stmt, consumed: Set[str]) -> Set[str]:
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
      return consumed  # nested defs analyzed on their own
    if isinstance(stmt, ast.Assign):
      consumed = run_expr(stmt.value, consumed)
      for target in stmt.targets:
        for n in ast.walk(target):
          if isinstance(n, ast.Name):
            consumed = consumed - {n.id}
      return consumed
    if isinstance(stmt, ast.If):
      consumed_test = run_expr(stmt.test, consumed)
      then = run(stmt.body, set(consumed_test))
      other = run(stmt.orelse, set(consumed_test))
      return then | other
    if isinstance(stmt, (ast.For, ast.While)):
      if isinstance(stmt, ast.For):
        consumed = run_expr(stmt.iter, consumed)
      else:
        consumed = run_expr(stmt.test, consumed)
      # Twice: catches a key consumed afresh every iteration.
      consumed = run(stmt.body, consumed)
      consumed = run(stmt.body, consumed)
      return run(stmt.orelse, consumed)
    if isinstance(stmt, (ast.With,)):
      for item in stmt.items:
        consumed = run_expr(item.context_expr, consumed)
      return run(stmt.body, consumed)
    if isinstance(stmt, ast.Try):
      consumed = run(stmt.body, consumed)
      for handler in stmt.handlers:
        consumed = run(handler.body, set(consumed))
      consumed = run(stmt.orelse, consumed)
      return run(stmt.finalbody, consumed)
    if isinstance(stmt, (ast.Return, ast.Expr)):
      value = stmt.value
      if value is not None:
        consumed = run_expr(value, consumed)
      return consumed
    for node in ast.iter_child_nodes(stmt):
      if isinstance(node, ast.expr):
        consumed = run_expr(node, consumed)
    return consumed

  run(fn.body, set())
  return findings
