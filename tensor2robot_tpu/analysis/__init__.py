"""AST-based static-analysis suite (stdlib-only, zero runtime cost).

Eight rule families gate tier-1 through ``tools/analyze.py`` and
``tests/test_static_analysis.py``:

* ``lock-discipline`` — ``# GUARDED_BY(lock)`` / ``# HOLDS(lock)``
  annotations on shared state + lock-ordering cycle detection.
* ``jit-hazard`` — host side effects / tracer leaks / raw numpy / rng
  key reuse inside jit-traced functions.
* ``recompile-hazard`` — unstable jit arguments and weak-keyed
  executor caches.
* ``dead-code`` — unused imports, locals, private globals.
* ``blocking-under-lock`` — ``.join()``/``.get()``/``device_get`` (and
  other indefinite waits) inside a ``with lock:`` block.
* ``donated-reuse`` — reads of an array after it was passed through
  ``donate_argnums`` / a donated ``lax.scan`` carry.
* ``donation-discipline`` — the ``state = step(state, ...)`` rebind
  idiom calling a jit with NO ``donate_argnums``: the input buffer is
  dead after the call, yet both copies stay resident per dispatch.
* ``metric-cardinality`` — registry metric names built from
  runtime-variable f-strings/concats outside the allowlisted scope
  pattern (unbounded label cardinality is the classic registry leak).

Waivers are inline ``# ANALYSIS_OK(<rule>): <reason>`` — the reason is
mandatory. See README "Static analysis" for the workflow.
"""

from tensor2robot_tpu.analysis.core import (  # noqa: F401
    ALL_RULES,
    Finding,
    ModuleInfo,
    Program,
    baseline_key,
    build_program,
    findings_to_baseline,
    load_baseline,
    load_module,
    load_source,
    run_checkers,
)
