"""H2D-in-loop checker: host→device transfers inside dispatch loops.

The device-feed work (``TrainerConfig.device_feed``) exists because the
``h2d_transport_gbps`` bench line showed per-step transfer overhead —
not math — taxing the step floor. The whole point of funneling every
placement through the trainer's placement stage (``place()`` /
``mesh.shard_batch``) is that the loop body itself never moves bytes:
one burst per dispatch, overlapped with compute by the prefetcher. A
``jax.device_put`` typed directly into a per-dispatch ``for``/``while``
body silently reintroduces a synchronous H2D copy on the critical path
on every iteration — it works, benchmarks never see it attributed, and
the MFU gauge just quietly sags. This rule makes that a gate failure.

Two finding shapes:

* ``device-put-in-loop`` — an explicit transfer call (``device_put``,
  ``device_put_sharded``, ``device_put_replicated``,
  ``make_array_from_process_local_data``) lexically inside a ``for`` /
  ``while`` body. Functions whose name contains ``place`` or ``shard``
  ARE the placement stage and are exempt — looping over batches is
  their job (e.g. ``Trainer.evaluate`` placing eval batches via
  ``shard_batch``, ``_place_releasing``).
* ``implicit-transfer-in-loop`` — ``jnp.asarray``/``jnp.array`` applied
  to a freshly built ``np.*`` array inside a loop body: a definite new
  host buffer crossing to device per iteration (the
  ``jnp.asarray(np.stack(batch))`` anti-idiom the superbatch assembler
  deletes). ``jnp.asarray(x)`` on an unknown name stays quiet — it is
  usually a trace-time dtype coercion of an already-placed array.

Transfers through the sanctioned placement helpers (``shard_batch``,
``place``) never fire: they are named calls, not raw ``device_put``.
Waive a deliberate in-loop transfer inline with
``# ANALYSIS_OK(h2d-in-loop): <why this copy is off the dispatch
critical path>``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tensor2robot_tpu.analysis import core

RULE = 'h2d-in-loop'

# Leaf names of the explicit-transfer family (matched on the last dotted
# component so `jax.device_put`, aliased `device_put`, and
# `jax.experimental.multihost_utils.*` spellings all resolve).
_TRANSFER_LEAVES = frozenset({
    'device_put', 'device_put_sharded', 'device_put_replicated',
    'make_array_from_process_local_data',
})
_IMPLICIT_LEAVES = frozenset({'asarray', 'array'})
_JAX_ROOTS = frozenset({'jax', 'jnp'})
_NUMPY_ROOTS = frozenset({'np', 'numpy', 'onp'})
# Substrings marking a function AS the placement stage.
_PLACEMENT_MARKERS = ('place', 'shard')


def _leaf(name: Optional[str]) -> Optional[str]:
  return None if name is None else name.rsplit('.', 1)[-1]


def _root(name: str) -> str:
  return name.split('.', 1)[0]


def _is_placement_fn(name: str) -> bool:
  lowered = name.lower()
  return any(marker in lowered for marker in _PLACEMENT_MARKERS)


def _numpy_sourced(node: ast.AST) -> bool:
  """True when the expression is a direct ``np.*``/``numpy.*`` call —
  a fresh host array by construction."""
  if not isinstance(node, ast.Call):
    return False
  name = core.call_name(node)
  return name is not None and '.' in name and _root(name) in _NUMPY_ROOTS


def _loop_bodies(scope: ast.AST):
  """Yields (loop_node, statement) for every statement lexically inside
  a for/while body within ``scope`` (orelse included: it still runs per
  loop construct, and a transfer there is the same smell). Nested defs
  and lambdas are separate scopes EXCEPT lambdas: a lambda inside a
  loop body (the ``tree_map(lambda x: device_put(x), ...)`` idiom) runs
  per iteration, so we descend into those."""
  for node in core.walk_scope(scope):
    if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
      continue
    stack = list(node.body) + list(node.orelse)
    while stack:
      stmt = stack.pop()
      if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        continue  # its own scope; analyzed when we visit that def
      yield node, stmt
      stack.extend(ast.iter_child_nodes(stmt))


def check(module: core.ModuleInfo, program: core.Program
          ) -> List[core.Finding]:
  del program
  findings: List[core.Finding] = []

  def scopes():
    yield '', module.tree
    for fn in core.func_defs(module.tree):
      yield core.qualname(module, fn), fn

  for symbol, scope in scopes():
    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)
                  ) and _is_placement_fn(scope.name):
      continue
    seen = set()
    for _loop, node in _loop_bodies(scope):
      if not isinstance(node, ast.Call) or id(node) in seen:
        continue
      seen.add(id(node))
      name = core.call_name(node)
      if name is None:
        continue
      leaf = _leaf(name)
      if leaf in _TRANSFER_LEAVES:
        findings.append(core.Finding(
            rule=RULE, check='device-put-in-loop',
            path=module.rel_path, line=node.lineno, symbol=symbol,
            message=(f'{name}(...) inside a loop body: a synchronous '
                     'H2D transfer on every iteration of the dispatch '
                     'loop. Move placement into the placement stage '
                     '(place()/shard_batch via the prefetcher) so the '
                     'burst overlaps compute — one device_put per '
                     'dispatch, not per step.')))
      elif (leaf in _IMPLICIT_LEAVES and _root(name) in _JAX_ROOTS
            and node.args and _numpy_sourced(node.args[0])):
        findings.append(core.Finding(
            rule=RULE, check='implicit-transfer-in-loop',
            path=module.rel_path, line=node.lineno, symbol=symbol,
            message=(f'{name}(<fresh numpy array>) inside a loop body '
                     'builds a host array and implicitly transfers it '
                     'to device every iteration. Assemble on host once '
                     '(superbatch buffers) and place through the '
                     'placement stage instead.')))
  return findings
