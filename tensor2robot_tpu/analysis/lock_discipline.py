"""Lock-discipline checker: GUARDED_BY / HOLDS + lock-ordering cycles.

Annotation convention (comments, so zero runtime cost):

* ``self._pending = deque()  # GUARDED_BY(self._cond)`` — every later
  read/write of ``self._pending`` in this class must happen inside
  ``with self._cond:`` (or a method annotated ``# HOLDS(self._cond)``).
  Module globals annotate the same way: ``_LIB = None  # GUARDED_BY(_LOCK)``.
* ``def _percentile_locked(self, q):  # HOLDS(self._lock)`` — documents
  (and makes checkable) a helper whose CALLERS own the lock.
* ``with lock.read_locked():`` / ``write_locked()`` both count as
  holding ``lock`` (the ReaderWriterLock surface).
* ``self._c = threading.Condition(self._l)`` aliases ``self._c`` to
  ``self._l`` automatically — holding either satisfies guards on both
  (a Condition shares its caller-supplied lock).

``__init__``/``__del__`` bodies are exempt at their own scope
(construction happens-before publication; the finalizer is
single-threaded) — but functions NESTED inside them (worker closures)
are checked: they run on other threads.

Lock ordering builds a cross-class "acquired-while-holding" graph:
``with B:`` lexically inside ``with A:`` adds edge A→B, and a call made
while holding A adds A→(every lock the callee may transitively
acquire). Locks are merged per class attribute (``module.Class._lock``),
so two instances of one class share a node — the conservative choice
for the dispatcher↔reload-poller↔RW-lock shapes in serving. Any cycle
(including a self-edge on a non-reentrant lock: a helper re-acquiring
the lock its caller holds) is reported once per cycle. RLocks are
exempt from self-edges; ``X.read_locked()``/``X.write_locked()`` model
the ReaderWriterLock as the single lock ``X`` — its internal Condition
use does not span the yield of the ``*_locked`` contextmanagers, so no
false edge leaks out of ``utils/concurrency.py``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tensor2robot_tpu.analysis import core

RULE = 'lock-discipline'

_LOCK_CTORS = {
    'Lock': 'lock', 'RLock': 'rlock', 'Condition': 'condition',
    'Semaphore': 'semaphore', 'BoundedSemaphore': 'semaphore',
    'ReaderWriterLock': 'rw',
}
_RW_METHODS = ('read_locked', 'write_locked', 'locked')


def _lock_ctor_kind(node: ast.AST) -> Optional[str]:
  """'lock'/'rlock'/'condition'/... when ``node`` constructs a lock."""
  if not isinstance(node, ast.Call):
    return None
  name = core.call_name(node)
  if name is None:
    return None
  leaf = name.rsplit('.', 1)[-1]
  return _LOCK_CTORS.get(leaf)


class _ClassModel:
  """Guards/aliases/lock kinds for one class (or the module scope)."""

  def __init__(self):
    self.guards: Dict[str, str] = {}      # attr/global name -> lock text
    self.aliases: Dict[str, str] = {}     # lock text -> lock text
    self.lock_kinds: Dict[str, str] = {}  # lock text -> kind

  def canonical(self, text: str) -> str:
    seen = set()
    while text in self.aliases and text not in seen:
      seen.add(text)
      text = self.aliases[text]
    return text


def _annotation_lines(module: core.ModuleInfo, node: ast.stmt) -> List[str]:
  """GUARDED_BY lock texts attached to this statement (any line of the
  statement, or a pure-comment line directly above — an annotation
  inlined on a PRECEDING statement never bleeds onto this one)."""
  out = []
  end = getattr(node, 'end_lineno', node.lineno) or node.lineno
  if module.is_comment_line(node.lineno - 1):
    out.extend(module.guarded_by.get(node.lineno - 1, ()))
  for line in range(node.lineno, end + 1):
    out.extend(module.guarded_by.get(line, ()))
  return out


def _build_model(module: core.ModuleInfo, scope: ast.AST,
                 class_name: Optional[str]) -> _ClassModel:
  """Scans a class (every method) or the module top level for guard
  annotations, lock constructions, and Condition aliases."""
  model = _ClassModel()
  for node in ast.walk(scope):
    if isinstance(node, ast.ClassDef) and node is not scope:
      continue  # inner classes build their own model
    if not isinstance(node, (ast.Assign, ast.AnnAssign)):
      continue
    targets = node.targets if isinstance(node, ast.Assign) else [node.target]
    value = node.value
    texts = []
    for target in targets:
      text = core.expr_text(target)
      if text is None:
        continue
      if class_name is not None and not text.startswith('self.'):
        continue
      if class_name is None and '.' in text:
        continue
      texts.append(text)
    if not texts or value is None:
      continue
    kind = _lock_ctor_kind(value)
    if kind is not None:
      for text in texts:
        model.lock_kinds[text] = kind
      if kind == 'condition' and value.args:
        backing = core.expr_text(value.args[0])
        if backing is not None:
          for text in texts:
            model.aliases[text] = backing
    for lock_text in _annotation_lines(module, node):
      for text in texts:
        attr = text[len('self.'):] if text.startswith('self.') else text
        model.guards[attr] = lock_text
  return model


def _with_lock_texts(item: ast.withitem) -> Optional[str]:
  """The lock expression a withitem holds, or None (not lock-shaped)."""
  ctx = item.context_expr
  text = core.expr_text(ctx)
  if text is not None:
    return text
  if isinstance(ctx, ast.Call):
    name = core.call_name(ctx)
    if name is not None:
      base, _, leaf = name.rpartition('.')
      if leaf in _RW_METHODS and base:
        return base
  return None


def _holds_for_def(module: core.ModuleInfo,
                   node: ast.FunctionDef) -> List[str]:
  lines = [node.lineno]
  if module.is_comment_line(node.lineno - 1):
    lines.append(node.lineno - 1)
  lines.extend(d.lineno for d in node.decorator_list)
  body_first = node.body[0].lineno if node.body else node.lineno
  lines.extend(range(node.lineno, body_first + 1))
  out = []
  for line in lines:
    out.extend(module.holds.get(line, ()))
  return out


def _local_names(fn: ast.FunctionDef) -> Set[str]:
  """Names bound locally in ``fn`` (assignments/args, minus globals)."""
  names: Set[str] = set()
  globals_decl: Set[str] = set()
  args = fn.args
  for a in (list(args.posonlyargs) + list(args.args) +
            list(args.kwonlyargs) +
            ([args.vararg] if args.vararg else []) +
            ([args.kwarg] if args.kwarg else [])):
    names.add(a.arg)
  for node in ast.walk(fn):
    if isinstance(node, (ast.Global, ast.Nonlocal)):
      globals_decl.update(node.names)
    elif isinstance(node, ast.Name) and isinstance(
        node.ctx, (ast.Store, ast.Del)):
      names.add(node.id)
  return names - globals_decl


def check(module: core.ModuleInfo, program: core.Program
          ) -> List[core.Finding]:
  """The per-module GUARDED_BY discipline pass."""
  del program
  findings: List[core.Finding] = []
  module_model = _build_model(module, module.tree, None)

  def visit_scope(fn: ast.FunctionDef, cls: Optional[ast.ClassDef],
                  class_model: Optional[_ClassModel]) -> None:
    exempt = cls is not None and fn.name in ('__init__', '__del__')
    held: Set[str] = set()
    for text in _holds_for_def(module, fn):
      model = class_model or module_model
      held.add(model.canonical(text))
    locals_ = _local_names(fn)

    def access_ok(model: _ClassModel, lock_text: str) -> bool:
      return model.canonical(lock_text) in held

    def flag(node: ast.AST, name: str, lock_text: str, write: bool):
      findings.append(core.Finding(
          rule=RULE,
          check='unguarded-write' if write else 'unguarded-read',
          path=module.rel_path, line=node.lineno,
          symbol=core.qualname(module, fn),
          message=(f"{'write to' if write else 'read of'} {name!r} "
                   f'(GUARDED_BY {lock_text}) outside '
                   f"'with {lock_text}:' and without HOLDS({lock_text})")))

    def walk(node: ast.AST):
      if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        # Nested defs run later, on whatever thread calls them: they
        # get a fresh walk with their own (empty + HOLDS) held set.
        visit_scope(node, cls, class_model)
        return
      if isinstance(node, ast.Lambda):
        return
      if isinstance(node, ast.With):
        acquired = []
        for item in node.items:
          text = _with_lock_texts(item)
          if text is not None:
            model = class_model or module_model
            acquired.append(model.canonical(text))
          if item.optional_vars is not None:
            walk(item.optional_vars)
          walk(item.context_expr)
        held.update(acquired)
        for stmt in node.body:
          walk(stmt)
        for text in acquired:
          held.discard(text)
        return
      if not exempt:
        if (isinstance(node, ast.Attribute) and
            isinstance(node.value, ast.Name) and
            node.value.id == 'self' and class_model is not None and
            node.attr in class_model.guards):
          lock_text = class_model.guards[node.attr]
          if not access_ok(class_model, lock_text):
            flag(node, node.attr, lock_text,
                 isinstance(node.ctx, (ast.Store, ast.Del)))
        elif (isinstance(node, ast.Name) and
              node.id in module_model.guards and
              node.id not in locals_):
          lock_text = module_model.guards[node.id]
          if not access_ok(module_model, lock_text):
            flag(node, node.id, lock_text,
                 isinstance(node.ctx, (ast.Store, ast.Del)))
      for child in ast.iter_child_nodes(node):
        walk(child)

    for stmt in fn.body:
      walk(stmt)

  def visit_container(container: ast.AST, cls: Optional[ast.ClassDef],
                      class_model: Optional[_ClassModel]):
    for node in container.body:  # type: ignore[attr-defined]
      if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        visit_scope(node, cls, class_model)
      elif isinstance(node, ast.ClassDef):
        visit_container(node, node, _build_model(module, node, node.name))

  visit_container(module.tree, None, None)
  return findings


# ------------------------------------------------------------ lock ordering


class _FuncModel:
  """Per-def facts for the cross-module ordering graph."""

  def __init__(self, fid: str, node: ast.FunctionDef,
               module: core.ModuleInfo, cls: Optional[ast.ClassDef]):
    self.fid = fid
    self.node = node
    self.module = module
    self.cls = cls
    self.is_contextmanager = any(
        core.expr_text(d) in ('contextlib.contextmanager',
                              'contextmanager')
        for d in node.decorator_list)
    self.acquired_direct: Set[str] = set()
    self.yield_held: Set[str] = set()
    # (held-at-call, callee text, receiver text, line)
    self.calls: List[Tuple[frozenset, str, Optional[str], int]] = []
    # (holder, acquired, line) lexical with-in-with edges
    self.edges: List[Tuple[str, str, int]] = []


class _Orderer:
  """Builds the acquired-while-holding graph over the whole program."""

  def __init__(self, program: core.Program):
    self.program = program
    self.funcs: Dict[str, _FuncModel] = {}
    self.lock_kinds: Dict[str, str] = {}
    self.class_models: Dict[str, _ClassModel] = {}
    self.imports: Dict[str, Dict[str, str]] = {}   # mod -> alias -> target
    self.attr_types: Dict[str, str] = {}  # 'mod.Class.attr' -> class qid
    for module in program.modules:
      self._scan_module(module)
    self._fixpoint = {}

  # ---------------------------------------------------------- scanning

  def _scan_module(self, module: core.ModuleInfo) -> None:
    imports: Dict[str, str] = {}
    for node in ast.walk(module.tree):
      if isinstance(node, ast.Import):
        for alias in node.names:
          name = core._module_name(alias.name.replace('.', '/') + '.py')
          imports[alias.asname or alias.name.split('.')[0]] = name
      elif isinstance(node, ast.ImportFrom) and node.module:
        src = core._module_name(node.module.replace('.', '/') + '.py')
        for alias in node.names:
          imports[alias.asname or alias.name] = f'{src}.{alias.name}'
    self.imports[module.name] = imports

    module_model = _build_model(module, module.tree, None)
    self.class_models[f'{module.name}.'] = module_model
    for text, kind in module_model.lock_kinds.items():
      self.lock_kinds[f'{module.name}.{module_model.canonical(text)}'] = kind

    def scan_defs(container, cls: Optional[ast.ClassDef]):
      for node in container.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
          self._scan_def(module, node, cls)
          for inner in ast.walk(node):
            if inner is not node and isinstance(
                inner, (ast.FunctionDef, ast.AsyncFunctionDef)):
              self._scan_def(module, inner, cls)
        elif isinstance(node, ast.ClassDef):
          model = _build_model(module, node, node.name)
          self.class_models[f'{module.name}.{node.name}'] = model
          for text, kind in model.lock_kinds.items():
            canon = self._canonical_lock(module, node, text, model)
            if canon:
              self.lock_kinds[canon] = kind
          scan_defs(node, node)

    scan_defs(module.tree, None)
    # self._x = ClassName(...) attribute types, for receiver resolution.
    for cls_node in [n for n in module.tree.body
                     if isinstance(n, ast.ClassDef)]:
      for node in ast.walk(cls_node):
        if not isinstance(node, ast.Assign):
          continue
        ctor = node.value
        if not isinstance(ctor, ast.Call):
          continue
        cls_qid = self._resolve_class(module, core.call_name(ctor))
        if cls_qid is None:
          continue
        for target in node.targets:
          text = core.expr_text(target)
          if text and text.startswith('self.'):
            key = f'{module.name}.{cls_node.name}.{text[5:]}'
            self.attr_types[key] = cls_qid

  def _resolve_class(self, module: core.ModuleInfo,
                     name: Optional[str]) -> Optional[str]:
    if name is None:
      return None
    leaf = name.rsplit('.', 1)[-1]
    imports = self.imports.get(module.name, {})
    for cand in (f'{module.name}.{name}', imports.get(name, ''),
                 f"{imports.get(name.split('.')[0], '')}."
                 f"{'.'.join(name.split('.')[1:])}" if '.' in name else '',
                 f'{module.name}.{leaf}'):
      if cand and cand in self.program.classes:
        return cand
    return None

  def _canonical_lock(self, module: core.ModuleInfo,
                      cls: Optional[ast.ClassDef], text: str,
                      model: Optional[_ClassModel] = None) -> Optional[str]:
    if model is not None:
      text = model.canonical(text)
    if text.startswith('self.'):
      if cls is None:
        return None
      return f'{module.name}.{cls.name}.{text[5:]}'
    return f'{module.name}.{text}'

  def _scan_def(self, module: core.ModuleInfo, fn: ast.FunctionDef,
                cls: Optional[ast.ClassDef]) -> None:
    fid = f'{module.name}.{core.qualname(module, fn)}'
    if fid in self.funcs:
      return
    model = _FuncModel(fid, fn, module, cls)
    self.funcs[fid] = model
    class_model = self.class_models.get(
        f'{module.name}.{cls.name}' if cls else f'{module.name}.')
    held0 = set()
    for text in _holds_for_def(module, fn):
      canon = self._canonical_lock(module, cls, text, class_model)
      if canon:
        held0.add(canon)

    def walk(node: ast.AST, held: frozenset, in_yield_scope: List[str]):
      if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)) and node is not fn:
        return  # nested defs are scanned as their own functions
      if isinstance(node, ast.With):
        acquired = []
        for item in node.items:
          walk(item.context_expr, held, in_yield_scope)
          text = _with_lock_texts(item)
          if text is None:
            continue
          canon = self._canonical_lock(module, cls, text, class_model)
          if canon is None:
            continue
          acquired.append(canon)
          model.acquired_direct.add(canon)
          for holder in held:
            model.edges.append((holder, canon, node.lineno))
        inner = frozenset(held | set(acquired))
        for stmt in node.body:
          walk(stmt, inner, in_yield_scope + acquired)
        return
      if isinstance(node, (ast.Yield, ast.YieldFrom)):
        model.yield_held.update(in_yield_scope)
      if isinstance(node, ast.Call):
        name = core.call_name(node)
        if name is not None:
          receiver = name.rpartition('.')[0] or None
          model.calls.append((held, name, receiver, node.lineno))
      for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
          continue
        walk(child, held, in_yield_scope)

    for stmt in fn.body:
      walk(stmt, frozenset(held0), [])

  # -------------------------------------------------------- resolution

  def resolve_call(self, caller: _FuncModel,
                   name: str) -> Optional[str]:
    module = caller.module
    base, _, leaf = name.rpartition('.')
    if leaf in _RW_METHODS:
      return None  # modeled as acquiring the receiver lock itself
    if not base:
      cand = f'{module.name}.{name}'
      if cand in self.funcs:
        return cand
      cls_qid = self._resolve_class(module, name)
      if cls_qid is not None:
        return f'{cls_qid}.__init__'
      return None
    if base == 'self' and caller.cls is not None:
      cand = f'{module.name}.{caller.cls.name}.{leaf}'
      return cand if cand in self.funcs else None
    imports = self.imports.get(module.name, {})
    if base in imports:
      cand = f'{imports[base]}.{leaf}'
      return cand if cand in self.funcs else None
    if base.startswith('self.') and caller.cls is not None:
      attr_key = f'{module.name}.{caller.cls.name}.{base[5:]}'
      cls_qid = self.attr_types.get(attr_key)
      if cls_qid is not None:
        cand = f'{cls_qid}.{leaf}'
        return cand if cand in self.funcs else None
    return None

  def transitive_acquires(self, fid: str,
                          stack: Optional[Set[str]] = None) -> Set[str]:
    if fid in self._fixpoint:
      return self._fixpoint[fid]
    stack = stack or set()
    if fid in stack:
      return set()
    stack.add(fid)
    model = self.funcs.get(fid)
    if model is None:
      return set()
    out = set(model.acquired_direct)
    for _, name, receiver, _ in model.calls:
      callee = self.resolve_call(model, name)
      if callee is not None:
        out |= self.transitive_acquires(callee, stack)
      elif name.rpartition('.')[2] in _RW_METHODS and receiver:
        canon = self._canonical_lock(
            model.module, model.cls, receiver,
            self.class_models.get(
                f'{model.module.name}.{model.cls.name}'
                if model.cls else f'{model.module.name}.'))
        if canon:
          out.add(canon)
    stack.discard(fid)
    self._fixpoint[fid] = out
    return out

  # ------------------------------------------------------------ edges

  def build_edges(self) -> List[Tuple[str, str, str, int]]:
    edges: List[Tuple[str, str, str, int]] = []
    for model in self.funcs.values():
      for holder, acquired, line in model.edges:
        edges.append((holder, acquired, model.module.rel_path, line))
      for held, name, _, line in model.calls:
        if not held:
          continue
        callee = self.resolve_call(model, name)
        if callee is None:
          continue
        for acquired in self.transitive_acquires(callee):
          for holder in held:
            edges.append((holder, acquired, model.module.rel_path, line))
    return edges


def check_lock_ordering(program: core.Program) -> List[core.Finding]:
  """Program-level pass: cycles (incl. self-edges) in the lock graph."""
  orderer = _Orderer(program)
  edges = orderer.build_edges()
  graph: Dict[str, Set[str]] = {}
  locations: Dict[Tuple[str, str], Tuple[str, int]] = {}
  findings: List[core.Finding] = []
  reported_self: Set[str] = set()
  for holder, acquired, path, line in edges:
    if holder == acquired:
      kind = orderer.lock_kinds.get(holder, 'lock')
      if kind != 'rlock' and holder not in reported_self:
        reported_self.add(holder)
        findings.append(core.Finding(
            rule=RULE, check='lock-ordering-cycle', path=path, line=line,
            symbol=holder,
            message=(f'non-reentrant lock {holder} may be re-acquired '
                     'while already held (self-deadlock)')))
      continue
    graph.setdefault(holder, set()).add(acquired)
    locations.setdefault((holder, acquired), (path, line))
  for cycle in _cycles(graph):
    pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
    path, line = locations.get(pairs[0], ('<program>', 0))
    order = ' -> '.join(cycle + [cycle[0]])
    findings.append(core.Finding(
        rule=RULE, check='lock-ordering-cycle', path=path, line=line,
        symbol=' / '.join(sorted(cycle)),
        message=(f'lock-ordering cycle: {order} (threads taking these '
                 'locks in different orders can deadlock)')))
  return findings


def _cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
  """Tarjan SCCs of size >= 2, each a potential deadlock."""
  index: Dict[str, int] = {}
  low: Dict[str, int] = {}
  on_stack: Set[str] = set()
  stack: List[str] = []
  sccs: List[List[str]] = []
  counter = [0]

  def strongconnect(v: str):
    index[v] = low[v] = counter[0]
    counter[0] += 1
    stack.append(v)
    on_stack.add(v)
    for w in graph.get(v, ()):
      if w not in index:
        strongconnect(w)
        low[v] = min(low[v], low[w])
      elif w in on_stack:
        low[v] = min(low[v], index[w])
    if low[v] == index[v]:
      scc = []
      while True:
        w = stack.pop()
        on_stack.discard(w)
        scc.append(w)
        if w == v:
          break
      if len(scc) > 1:
        sccs.append(sorted(scc))

  for v in sorted(set(graph) | {w for ws in graph.values() for w in ws}):
    if v not in index:
      strongconnect(v)
  return sccs
