"""Recompile-hazard checker.

Steady-state serving and training must never re-trace: one stray
retrace per dispatch erases every win the AOT bucket executors and the
K×M fused step bought. Two finding families:

* ``unstable-jit-arg`` — call sites of a KNOWN-jitted callable (a name
  bound from ``jax.jit(...)``/``pjit(...)``, a ``@jit``-decorated def,
  or a name bound from a local factory that returns a jit) whose
  arguments include Python scalar literals, dict/list/set displays or
  comprehensions: every distinct value/shape is a fresh cache entry
  (weak-typed scalars re-specialize; container literals rebuild pytree
  shapes per call). Also ``jax.jit(f)(...)`` called inline — wrapping
  per call defeats jit's cache when ``f`` is a lambda/closure — and
  ``jax.jit(lambda ...)``, which can NEVER hit the cache twice.
* ``weak-keyed-cache`` — executor/program caches keyed on identity or
  drifting fingerprints: subscript stores whose key contains ``id(...)``
  (ids are recycled after GC and drift across reloads — the shape of
  the PR-7 program-key bug that silently defeated executable-cache
  reuse), and ``functools.lru_cache`` on methods (keys on ``self``,
  pinning every instance forever and splitting the cache per instance).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tensor2robot_tpu.analysis import core

RULE = 'recompile-hazard'

_JIT_WRAPPERS = {'jax.jit', 'jit', 'jax.pjit', 'pjit'}


def _is_jit_call(node: ast.AST) -> bool:
  return (isinstance(node, ast.Call) and
          core.call_name(node) in _JIT_WRAPPERS)


def _returns_jit(fn: ast.FunctionDef) -> bool:
  """Does this local factory return a jitted callable?"""
  for node in ast.walk(fn):
    if isinstance(node, ast.Return) and node.value is not None:
      if _is_jit_call(node.value):
        return True
  return False


def _jitted_names(module: core.ModuleInfo) -> Set[str]:
  """Names/attrs bound to jitted callables within this module."""
  factories: Set[str] = set()
  for fn in core.func_defs(module.tree):
    if _returns_jit(fn):
      factories.add(fn.name)
  jitted: Set[str] = set()
  for node in ast.walk(module.tree):
    if isinstance(node, ast.Assign):
      value = node.value
      bind = False
      if _is_jit_call(value):
        bind = True
      elif isinstance(value, ast.Call):
        name = core.call_name(value)
        if name is not None and (name in factories or
                                 name.rsplit('.', 1)[-1] in factories):
          bind = True
      if bind:
        for target in node.targets:
          text = core.expr_text(target)
          if text is not None:
            jitted.add(text)
    elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
      for dec in node.decorator_list:
        dec_text = core.expr_text(dec)
        dec_call = core.call_name(dec) if isinstance(dec, ast.Call) else None
        partial_inner = None
        if isinstance(dec, ast.Call) and dec_call in (
            'functools.partial', 'partial') and dec.args:
          partial_inner = core.expr_text(dec.args[0])
        if (dec_text in _JIT_WRAPPERS or dec_call in _JIT_WRAPPERS or
            partial_inner in _JIT_WRAPPERS):
          jitted.add(node.name)
  return jitted


def _unstable_arg(arg: ast.AST) -> Optional[str]:
  if isinstance(arg, ast.Constant) and isinstance(
      arg.value, (bool, int, float)):
    return (f'Python scalar literal {arg.value!r} (weak-typed: each '
            'distinct value/dtype promotion re-specializes the trace)')
  if isinstance(arg, (ast.Dict, ast.DictComp)):
    return 'dict display (pytree structure rebuilt per call site)'
  if isinstance(arg, (ast.List, ast.ListComp, ast.Set, ast.SetComp)):
    return 'list/set display (varying length retraces per shape)'
  return None


def check(module: core.ModuleInfo, program: core.Program
          ) -> List[core.Finding]:
  del program
  findings: List[core.Finding] = []
  jitted = _jitted_names(module)

  def symbol_of(node: ast.AST) -> str:
    enclosing = module.enclosing(
        node, (ast.FunctionDef, ast.AsyncFunctionDef))
    return core.qualname(module, enclosing) if enclosing else ''

  for node in ast.walk(module.tree):
    if isinstance(node, ast.Call):
      name = core.call_name(node)
      # jax.jit(lambda ...) can never hit the trace cache twice.
      if name in _JIT_WRAPPERS and node.args and isinstance(
          node.args[0], ast.Lambda):
        findings.append(core.Finding(
            rule=RULE, check='unstable-jit-arg', path=module.rel_path,
            line=node.lineno, symbol=symbol_of(node),
            message=('jit(lambda ...): the lambda object is fresh per '
                     'evaluation, so the compiled program can never be '
                     'reused — name the function and jit it once')))
      # jax.jit(f)(args): a fresh wrapper per call.
      if _is_jit_call(node.func):
        findings.append(core.Finding(
            rule=RULE, check='unstable-jit-arg', path=module.rel_path,
            line=node.lineno, symbol=symbol_of(node),
            message=('inline jax.jit(f)(...) call: wrap once at setup '
                     'and reuse the jitted callable — per-call wrapping '
                     'defeats the trace cache for closures/lambdas')))
      if name in jitted:
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
          why = _unstable_arg(arg)
          if why is not None:
            findings.append(core.Finding(
                rule=RULE, check='unstable-jit-arg',
                path=module.rel_path, line=node.lineno,
                symbol=symbol_of(node),
                message=(f'non-static argument to jitted {name}(...): '
                         f'{why}')))
      # Identity-keyed caches: cache[id(x)] = ... or keys containing id().
      if (name == 'id' and
          isinstance(module.parent(node), ast.Subscript)):
        sub = module.parent(node)
        if isinstance(sub.ctx, ast.Store):
          findings.append(core.Finding(
              rule=RULE, check='weak-keyed-cache', path=module.rel_path,
              line=node.lineno, symbol=symbol_of(node),
              message=('cache keyed on id(...): ids are recycled after '
                       'GC and drift across reloads, so entries alias '
                       'or silently never match (the PR-7 program-key '
                       'failure shape) — key on stable content instead')))
    elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
      args = node.args.posonlyargs + node.args.args
      is_method = bool(args) and args[0].arg in ('self', 'cls')
      if not is_method:
        continue
      for dec in node.decorator_list:
        dec_name = (core.expr_text(dec) or
                    (core.call_name(dec)
                     if isinstance(dec, ast.Call) else None))
        if dec_name in ('functools.lru_cache', 'lru_cache',
                        'functools.cache', 'cache'):
          findings.append(core.Finding(
              rule=RULE, check='weak-keyed-cache', path=module.rel_path,
              line=node.lineno,
              symbol=core.qualname(module, node),
              message=('lru_cache on a method keys on self: every '
                       'instance is pinned forever and a reloaded '
                       'instance never hits the old entries — cache on '
                       'stable identity, or module level')))
  return findings
