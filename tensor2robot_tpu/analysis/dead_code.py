"""Dead-code checker: unused imports and unused bindings.

The trivial fourth checker that keeps the tree honest between PRs:

* ``unused-import`` — a name bound by ``import``/``from ... import``
  and never referenced in the module. ``__init__.py`` files follow the
  re-export convention: imports there count as intentional exports
  unless the file declares ``__all__`` (then membership decides).
* ``unused-local`` — a function-local ``name = <pure expr>`` never read
  afterwards (anywhere in the function, nested defs included). Only
  side-effect-free right-hand sides are flagged, so ``_ = fn()`` idioms
  and deliberate drains never fire; underscore-prefixed names are
  exempt by convention.
* ``unused-private-global`` — a module-level ``_NAME = <pure expr>``
  (constants only, not defs/classes) never referenced in its module.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from tensor2robot_tpu.analysis import core

RULE = 'dead-code'

_PURE_NODES = (ast.Constant, ast.Name, ast.Attribute, ast.Tuple, ast.List,
               ast.Dict, ast.Set, ast.BinOp, ast.UnaryOp, ast.Compare,
               ast.BoolOp, ast.IfExp, ast.JoinedStr, ast.FormattedValue)


def _is_pure(node: ast.AST) -> bool:
  # Non-expression helper nodes (Load/Store ctx, operators) are inert;
  # only expression kinds decide purity.
  return all(isinstance(sub, _PURE_NODES) or not isinstance(sub, ast.expr)
             for sub in ast.walk(node))


def _used_names(tree: ast.AST) -> Set[str]:
  used: Set[str] = set()
  for node in ast.walk(tree):
    if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
      used.add(node.id)
    elif isinstance(node, ast.Attribute):
      text = core.expr_text(node)
      if text:
        used.add(text.split('.', 1)[0])
  return used


def _declared_all(tree: ast.Module) -> Tuple[bool, Set[str]]:
  for node in tree.body:
    if isinstance(node, ast.Assign):
      for target in node.targets:
        if isinstance(target, ast.Name) and target.id == '__all__':
          names = set()
          if isinstance(node.value, (ast.List, ast.Tuple)):
            for elt in node.value.elts:
              if isinstance(elt, ast.Constant) and isinstance(
                  elt.value, str):
                names.add(elt.value)
          return True, names
  return False, set()


def _inside_classdef(module: core.ModuleInfo, node: ast.AST,
                     fn: ast.AST) -> bool:
  """True when ``node`` sits in a ClassDef nested inside ``fn`` —
  class attributes are API surface, not function locals."""
  cur = module.parent(node)
  while cur is not None and cur is not fn:
    if isinstance(cur, ast.ClassDef):
      return True
    cur = module.parent(cur)
  return False


def check(module: core.ModuleInfo, program: core.Program
          ) -> List[core.Finding]:
  del program
  findings: List[core.Finding] = []
  tree = module.tree
  used = _used_names(tree)
  has_all, all_names = _declared_all(tree)
  is_package_init = module.rel_path.endswith('__init__.py')

  # ---------------------------------------------------------- imports
  for node in ast.walk(tree):
    if isinstance(node, ast.Import):
      bindings = [(alias.asname or alias.name.split('.')[0],
                   alias.name) for alias in node.names]
    elif isinstance(node, ast.ImportFrom):
      if node.module == '__future__':
        continue
      bindings = [(alias.asname or alias.name, alias.name)
                  for alias in node.names if alias.name != '*']
    else:
      continue
    for bound, original in bindings:
      if bound in used or bound in all_names:
        continue
      if is_package_init and not has_all:
        continue  # re-export convention
      findings.append(core.Finding(
          rule=RULE, check='unused-import', path=module.rel_path,
          line=node.lineno, symbol=bound,
          message=f'import {original!r} (as {bound!r}) is never used'))

  # ---------------------------------------------------- private globals
  for node in tree.body:
    if not isinstance(node, ast.Assign) or node.value is None:
      continue
    if not _is_pure(node.value):
      continue
    for target in node.targets:
      if (isinstance(target, ast.Name) and target.id.startswith('_') and
          not target.id.startswith('__') and target.id not in used and
          target.id not in all_names):
        findings.append(core.Finding(
            rule=RULE, check='unused-private-global',
            path=module.rel_path, line=node.lineno, symbol=target.id,
            message=f'private module global {target.id!r} is never read'))

  # ----------------------------------------------------------- locals
  for fn in core.func_defs(tree):
    reads: Set[str] = set()
    global_decl: Set[str] = set()
    stores: Dict[str, List[ast.Assign]] = {}
    for node in ast.walk(fn):
      if isinstance(node, (ast.Global, ast.Nonlocal)):
        global_decl.update(node.names)
      elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
        reads.add(node.id)
      elif isinstance(node, ast.Assign):
        if (len(node.targets) == 1 and
            isinstance(node.targets[0], ast.Name) and
            _is_pure(node.value) and
            not _inside_classdef(module, node, fn)):
          stores.setdefault(node.targets[0].id, []).append(node)
    for name, nodes in stores.items():
      if (name in reads or name in global_decl or
          name.startswith('_') or name == 'self'):
        continue
      # Augmented or multiple-assignment names may feed later passes;
      # only a name NEVER loaded in the whole def is dead.
      for node in nodes:
        findings.append(core.Finding(
            rule=RULE, check='unused-local', path=module.rel_path,
            line=node.lineno, symbol=f'{core.qualname(module, fn)}.{name}',
            message=(f'local {name!r} is assigned a side-effect-free '
                     'value and never read')))
  return findings
