"""Shared machinery for the static-analysis suite.

The suite is the correctness-tooling analogue of ``observability/``:
pure stdlib (``ast`` + ``tokenize``), importable on any host, zero
runtime cost — it reads source, never executes it. Every checker is a
function ``check(module: ModuleInfo, program: Program) -> [Finding]``;
this module owns everything the checkers share:

* :class:`ModuleInfo` — one parsed source file: AST (parent-linked),
  per-line comment map, and the three annotation kinds extracted from
  comments (``GUARDED_BY``, ``HOLDS``, ``ANALYSIS_OK`` waivers).
* :class:`Program` — the whole analyzed file set, so cross-module
  resolution (imported classes, lock-ordering edges across files) has
  one place to look things up.
* Waiver semantics — ``# ANALYSIS_OK(<rule>): <reason>`` on the finding
  line or the line directly above. The reason is REQUIRED: a bare
  suppress is itself reported (rule ``waiver-discipline``).
* Baseline io — ``analysis_baseline.json`` records the waived findings
  (rule/check/path/symbol/reason, no line numbers so unrelated edits
  don't churn it). The gate fails on any unwaived finding and on any
  waived finding missing from the baseline, so the file can only shrink
  (fixing code) or change under review (adding a waiver edits it).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    'Finding', 'ModuleInfo', 'Program', 'load_module', 'load_source',
    'build_program', 'run_checkers', 'load_baseline', 'baseline_key',
    'findings_to_baseline', 'ALL_RULES',
]

# Rule families (each checker owns one; waivers may name the family or
# 'family:check' for a specific sub-rule).
ALL_RULES = ('lock-discipline', 'jit-hazard', 'recompile-hazard',
             'dead-code', 'blocking-under-lock', 'donated-reuse',
             'donation-discipline', 'metric-cardinality',
             'h2d-in-loop', 'waiver-discipline')

_GUARDED_BY_RE = re.compile(r'GUARDED_BY\(\s*([^)]+?)\s*\)')
_HOLDS_RE = re.compile(r'HOLDS\(\s*([^)]+?)\s*\)')
_ANALYSIS_OK_RE = re.compile(r'ANALYSIS_OK\(\s*([^)]+?)\s*\)\s*:?\s*(.*)')


@dataclasses.dataclass
class Finding:
  """One checker hit. ``waived`` findings don't fail the gate but must
  appear in the baseline (with their inline justification)."""

  rule: str            # family, e.g. 'lock-discipline'
  check: str           # sub-rule, e.g. 'unguarded-read'
  path: str            # repo-relative source path
  line: int
  message: str
  symbol: str = ''     # qualified context, e.g. 'DynamicBatcher.submit'
  waived: bool = False
  waiver_reason: str = ''

  def location(self) -> str:
    return f'{self.path}:{self.line}'

  def as_dict(self) -> dict:
    return dataclasses.asdict(self)


def baseline_key(finding: Finding) -> Tuple[str, str, str, str]:
  """Line-number-free identity used by the baseline (stable across
  unrelated edits to the same file)."""
  return (finding.rule, finding.check, finding.path, finding.symbol)


class ModuleInfo:
  """One parsed module: AST + comments + annotations."""

  def __init__(self, path: str, rel_path: str, source: str):
    self.path = path
    self.rel_path = rel_path
    self.source = source
    self.lines = source.split('\n')
    self.tree = ast.parse(source, filename=path)
    # Parent links: checkers need lexical context (enclosing class/def).
    for node in ast.walk(self.tree):
      for child in ast.iter_child_nodes(node):
        child._t2r_parent = node  # type: ignore[attr-defined]
    # Dotted module name relative to the package root, used for
    # canonical lock/function ids ('serving.batching', 'tools.analyze').
    self.name = _module_name(rel_path)
    self.comments: Dict[int, str] = {}
    for tok in _safe_tokens(source):
      if tok.type == tokenize.COMMENT:
        self.comments[tok.start[0]] = tok.string
    # line -> [(rule, reason)]
    self.waivers: Dict[int, List[Tuple[str, str]]] = {}
    # line -> [lock expression text]
    self.guarded_by: Dict[int, List[str]] = {}
    self.holds: Dict[int, List[str]] = {}
    for line, comment in self.comments.items():
      for match in _GUARDED_BY_RE.finditer(comment):
        self.guarded_by.setdefault(line, []).append(match.group(1).strip())
      for match in _HOLDS_RE.finditer(comment):
        self.holds.setdefault(line, []).append(match.group(1).strip())
      match = _ANALYSIS_OK_RE.search(comment)
      if match:
        self.waivers.setdefault(line, []).append(
            (match.group(1).strip(), match.group(2).strip()))

  def parent(self, node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, '_t2r_parent', None)

  def enclosing(self, node: ast.AST, kinds) -> Optional[ast.AST]:
    cur = self.parent(node)
    while cur is not None and not isinstance(cur, kinds):
      cur = self.parent(cur)
    return cur

  def is_comment_line(self, line: int) -> bool:
    """True when ``line`` holds ONLY a comment (no code) — the form an
    annotation may take when it won't fit inline."""
    if not 1 <= line <= len(self.lines):
      return False
    return self.lines[line - 1].lstrip().startswith('#')

  def waiver_for(self, rule: str, check: str,
                 line: int) -> Optional[Tuple[str, str]]:
    """The (rule, reason) waiver covering ``rule``/``check`` at ``line``
    — same line, or a pure-comment line directly above (both count as
    inline; an annotation attached to ANOTHER statement never bleeds)."""
    candidates = [line]
    # Walk up through a contiguous pure-comment block: a waiver wrapped
    # over several comment lines still counts as inline.
    cand = line - 1
    while self.is_comment_line(cand):
      candidates.append(cand)
      cand -= 1
    for cand in candidates:
      for waived_rule, reason in self.waivers.get(cand, ()):
        if waived_rule in (rule, f'{rule}:{check}', check, '*'):
          return waived_rule, reason
    return None


def _module_name(rel_path: str) -> str:
  name = rel_path[:-3] if rel_path.endswith('.py') else rel_path
  parts = [p for p in name.replace(os.sep, '/').split('/') if p]
  if parts and parts[0] == 'tensor2robot_tpu':
    parts = parts[1:]
  if parts and parts[-1] == '__init__':
    parts = parts[:-1] or ['__init__']
  return '.'.join(parts) or '<module>'


def _safe_tokens(source: str):
  try:
    yield from tokenize.generate_tokens(io.StringIO(source).readline)
  except (tokenize.TokenError, IndentationError):
    return


class Program:
  """The analyzed file set + cross-module lookup tables."""

  def __init__(self, modules: List[ModuleInfo]):
    self.modules = modules
    self.by_name: Dict[str, ModuleInfo] = {m.name: m for m in modules}
    self.by_rel_path: Dict[str, ModuleInfo] = {
        m.rel_path: m for m in modules}
    # 'modname.ClassName' -> ast.ClassDef, for imported-class resolution.
    self.classes: Dict[str, ast.ClassDef] = {}
    for mod in modules:
      for node in mod.tree.body:
        if isinstance(node, ast.ClassDef):
          self.classes[f'{mod.name}.{node.name}'] = node


def load_source(source: str, rel_path: str = '<memory>.py') -> ModuleInfo:
  """Builds a ModuleInfo from an in-memory snippet (fixture tests)."""
  return ModuleInfo(rel_path, rel_path, source)


def load_module(path: str, root: str) -> Optional[ModuleInfo]:
  rel = os.path.relpath(path, root)
  try:
    with open(path, encoding='utf-8') as f:
      source = f.read()
    return ModuleInfo(path, rel, source)
  except (OSError, SyntaxError, ValueError):
    return None


def iter_python_files(paths: Iterable[str], root: str) -> List[str]:
  out = []
  for p in paths:
    full = p if os.path.isabs(p) else os.path.join(root, p)
    if os.path.isdir(full):
      for dirpath, dirnames, filenames in os.walk(full):
        dirnames[:] = [d for d in dirnames if d != '__pycache__']
        for fn in sorted(filenames):
          if fn.endswith('.py'):
            out.append(os.path.join(dirpath, fn))
    elif full.endswith('.py') and os.path.exists(full):
      out.append(full)
  return sorted(set(out))


def build_program(paths: Iterable[str], root: str) -> Program:
  modules = []
  for path in iter_python_files(paths, root):
    mod = load_module(path, root)
    if mod is not None:
      modules.append(mod)
  return Program(modules)


def apply_waivers(module: ModuleInfo,
                  findings: List[Finding]) -> List[Finding]:
  """Marks findings covered by an inline ANALYSIS_OK; reports empty
  justifications as their own finding (a bare suppress never passes)."""
  out = []
  for finding in findings:
    waiver = module.waiver_for(finding.rule, finding.check, finding.line)
    if waiver is not None:
      rule, reason = waiver
      if not reason:
        out.append(Finding(
            rule='waiver-discipline', check='missing-justification',
            path=finding.path, line=finding.line,
            symbol=finding.symbol,
            message=(f'ANALYSIS_OK({rule}) has no justification; waivers '
                     'must say WHY the access is safe')))
      finding.waived = True
      finding.waiver_reason = reason
    out.append(finding)
  return out


def run_checkers(program: Program, checkers=None) -> List[Finding]:
  """Runs every checker over every module + the program-level passes."""
  from tensor2robot_tpu.analysis import blocking_under_lock
  from tensor2robot_tpu.analysis import dead_code
  from tensor2robot_tpu.analysis import donated_reuse
  from tensor2robot_tpu.analysis import donation_discipline
  from tensor2robot_tpu.analysis import h2d_in_loop
  from tensor2robot_tpu.analysis import jit_hazards
  from tensor2robot_tpu.analysis import lock_discipline
  from tensor2robot_tpu.analysis import metric_cardinality
  from tensor2robot_tpu.analysis import recompile_hazards

  if checkers is None:
    checkers = (lock_discipline.check, jit_hazards.check,
                recompile_hazards.check, dead_code.check,
                blocking_under_lock.check, donated_reuse.check,
                donation_discipline.check, metric_cardinality.check,
                h2d_in_loop.check)
  findings: List[Finding] = []
  for module in program.modules:
    for checker in checkers:
      findings.extend(apply_waivers(module, checker(module, program)))
  if checkers and any(c.__module__.endswith('lock_discipline')
                      for c in checkers):
    ordering = lock_discipline.check_lock_ordering(program)
    by_path = program.by_rel_path
    for finding in ordering:
      mod = by_path.get(finding.path)
      if mod is not None:
        findings.extend(apply_waivers(mod, [finding]))
      else:
        findings.append(finding)
  findings.sort(key=lambda f: (f.path, f.line, f.rule, f.check))
  deduped: List[Finding] = []
  seen = set()
  for f in findings:
    key = (f.rule, f.check, f.path, f.line, f.symbol, f.message)
    if key not in seen:
      seen.add(key)
      deduped.append(f)
  return deduped


# ------------------------------------------------------------------ baseline


def load_baseline(path: str) -> Dict[Tuple[str, str, str, str], dict]:
  if not os.path.exists(path):
    return {}
  with open(path, encoding='utf-8') as f:
    data = json.load(f)
  out = {}
  for entry in data.get('waived_findings', []):
    key = (entry['rule'], entry['check'], entry['path'],
           entry.get('symbol', ''))
    out[key] = entry
  return out


def findings_to_baseline(findings: List[Finding]) -> dict:
  entries = {}
  for f in findings:
    if not f.waived:
      continue
    key = baseline_key(f)
    entries[key] = {
        'rule': f.rule, 'check': f.check, 'path': f.path,
        'symbol': f.symbol, 'reason': f.waiver_reason,
    }
  return {
      'comment': (
          'Waived static-analysis findings (tools/analyze.py). Every '
          'entry has an inline ANALYSIS_OK justification at the finding '
          'site; this file may only shrink, or change under review when '
          'a new waiver is added.'),
      'waived_findings': [entries[k] for k in sorted(entries)],
  }


# ------------------------------------------------------- shared AST helpers


def expr_text(node: ast.AST) -> Optional[str]:
  """'self._lock' / '_LOCK' / 'a.b.c' for Name/Attribute chains."""
  if isinstance(node, ast.Name):
    return node.id
  if isinstance(node, ast.Attribute):
    base = expr_text(node.value)
    return None if base is None else f'{base}.{node.attr}'
  return None


def call_name(node: ast.Call) -> Optional[str]:
  return expr_text(node.func)


def walk_scope(root: ast.AST):
  """Like ``ast.walk`` but does NOT descend into nested function
  definitions or lambdas (they are separate scopes, analyzed on their
  own; ``ast.walk`` cannot prune). The nested def node itself is still
  yielded so callers can see it."""
  stack = [root]
  while stack:
    node = stack.pop()
    yield node
    for child in ast.iter_child_nodes(node):
      if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
        yield child
        continue
      stack.append(child)


def func_defs(tree: ast.AST):
  for node in ast.walk(tree):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
      yield node


def qualname(module: ModuleInfo, node: ast.AST) -> str:
  """Dotted lexical path of a def/class within its module."""
  parts = []
  cur: Optional[ast.AST] = node
  while cur is not None and not isinstance(cur, ast.Module):
    if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.ClassDef)):
      parts.append(cur.name)
    cur = module.parent(cur)
  return '.'.join(reversed(parts))
