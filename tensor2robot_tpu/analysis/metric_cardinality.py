"""Metric-cardinality checker.

The classic metrics-registry leak: a metric NAME built from
request-scoped data — ``counter(f'requests/{request_id}')``,
``histogram('latency_' + model_from_the_wire)`` — creates one registry
entry per distinct value, and the process-global registry never drops
an entry, so an unbounded label domain is an unbounded memory leak that
also floods every ``/metricsz`` scrape and time-series sample. (The
time-series ring snapshots the WHOLE registry every 10 s: registry
growth multiplies across the history ring.)

One finding family, ``dynamic-metric-name``: a registry metric-creating
call (``counter``/``gauge``/``histogram``/``scope`` — module functions
or scope methods) whose name argument is *built* from an f-string or
``+``-concatenation containing a runtime-variable part. Bare-variable
names are not flagged (passing a name through a helper is the registry
API's own shape); the checker targets construction sites, where the
cardinality decision actually lives.

A dynamic part is ALLOWED (config-scoped, not request-scoped) when it
is:

* a ``self.``/``cls.`` attribute — instance configuration, bounded by
  instance count (``f'{self._metrics_prefix}/quant'``);
* a name (or attribute) matching the **allowlisted scope pattern**
  ``(prefix|scope|name)$`` — the sanctioned scope-plumbing spelling
  (``Scope.counter(self._prefix + name)``);
* a loop variable over ``range(...)`` (per-host gauges: bounded by
  topology), a module-level constant tuple/list (``for p in
  PRIORITIES``), or ``.items()``/``.keys()`` of a local dict DISPLAY
  with constant keys (the trainer's breakdown-scalars publish loop);
* a local bound only to constants.

Deliberately capped dynamic scopes are allowlisted by their static
name prefix (:data:`ALLOWED_SCOPE_PREFIXES`): ``resilience/
data_errors/`` is the ErrorBudget's per-source accounting, capped at 32
sources in code (``utils/retry.py``) — the cap is the defense, the
allowlist records that it was reviewed.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from tensor2robot_tpu.analysis import core

RULE = 'metric-cardinality'

# Metric-creating call names (last dotted segment).
_METRIC_METHODS = {'counter', 'gauge', 'histogram'}
_SCOPE_METHODS = {'scope'}

# The allowlisted scope pattern: dynamic parts spelled as scope
# plumbing are config-time prefixes, not request data.
_ALLOWED_NAME_RE = re.compile(r'(^|_)(prefix|scope|name)$')

# Static name prefixes whose dynamic tails are a reviewed, explicitly
# CAPPED design (the code bounds the label domain itself).
ALLOWED_SCOPE_PREFIXES = (
    # ErrorBudget per-source error counters: capped at 32 sources +
    # an overflow bucket in utils/retry.py.
    'resilience/data_errors/',
)


def _is_metric_call(node: ast.Call) -> bool:
  callee = core.call_name(node)
  if callee is None:
    return False
  last = callee.rsplit('.', 1)[-1]
  if last in _METRIC_METHODS:
    return True
  # .scope(...) only as an attribute call: a bare scope() elsewhere is
  # someone else's function.
  return last in _SCOPE_METHODS and '.' in callee


def _concat_parts(node: ast.AST, out: List[ast.AST]) -> bool:
  """Flattens a +-chain; True iff the whole tree is names/constants."""
  if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
    return (_concat_parts(node.left, out) and
            _concat_parts(node.right, out))
  out.append(node)
  return True


def _built_parts(arg: ast.AST) -> Optional[List[ast.AST]]:
  """The pieces of a CONSTRUCTED name (f-string / concat), else None."""
  if isinstance(arg, ast.JoinedStr):
    return [v.value for v in arg.values
            if isinstance(v, ast.FormattedValue)]
  if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
    parts: List[ast.AST] = []
    _concat_parts(arg, parts)
    return [p for p in parts if not isinstance(p, ast.Constant)]
  return None


def _static_prefix(arg: ast.AST) -> str:
  """The leading constant text of a constructed name."""
  if isinstance(arg, ast.JoinedStr):
    out = []
    for value in arg.values:
      if isinstance(value, ast.Constant) and isinstance(value.value, str):
        out.append(value.value)
      else:
        break
    return ''.join(out)
  if isinstance(arg, ast.BinOp):
    parts: List[ast.AST] = []
    _concat_parts(arg, parts)
    out = []
    for part in parts:
      if isinstance(part, ast.Constant) and isinstance(part.value, str):
        out.append(part.value)
      else:
        break
    return ''.join(out)
  return ''


def _module_constants(module: core.ModuleInfo) -> Set[str]:
  """Module-level names bound to constant containers/values.

  Resolved to a fixpoint so ``PRIORITIES = (INTERACTIVE, BEST_EFFORT)``
  — a tuple of names that are themselves module constants — counts.
  """
  consts: Set[str] = set()
  assigns = [n for n in module.tree.body if isinstance(n, ast.Assign)]
  changed = True
  while changed:
    changed = False
    for node in assigns:
      if not _is_constant_container(node.value, consts):
        continue
      for target in node.targets:
        if isinstance(target, ast.Name) and target.id not in consts:
          consts.add(target.id)
          changed = True
  return consts


def _is_constant_element(node: ast.AST, consts: Set[str]) -> bool:
  return (isinstance(node, ast.Constant) or
          (isinstance(node, ast.Name) and node.id in consts))


def _is_constant_container(node: ast.AST,
                           consts: Optional[Set[str]] = None) -> bool:
  consts = consts or set()
  if _is_constant_element(node, consts):
    return True
  if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
    return all(_is_constant_element(e, consts) for e in node.elts)
  if isinstance(node, ast.Dict):
    return all(k is not None and _is_constant_element(k, consts)
               for k in node.keys)
  return False


def _target_names(target: ast.AST) -> Set[str]:
  if isinstance(target, ast.Name):
    return {target.id}
  if isinstance(target, (ast.Tuple, ast.List)):
    out: Set[str] = set()
    for element in target.elts:
      out |= _target_names(element)
    return out
  return set()


def _scope_nodes(module: core.ModuleInfo, fn: Optional[ast.AST]):
  if fn is not None:
    yield from core.walk_scope(fn)
  else:
    # Module level: walk everything except function/class bodies.
    yield from core.walk_scope(module.tree)


def _dict_has_constant_keys(module: core.ModuleInfo, name: str,
                            fn: Optional[ast.AST]) -> bool:
  """Is ``name`` a local bound (only) to dict displays with constant
  keys? (The ``for key in out.items():`` publish-loop idiom.)"""
  found = False
  for node in _scope_nodes(module, fn):
    if not isinstance(node, ast.Assign):
      continue
    if not any(name in _target_names(t) for t in node.targets):
      continue
    if isinstance(node.value, ast.Dict) and all(
        k is not None and isinstance(k, ast.Constant)
        for k in node.value.keys):
      found = True
    else:
      return False
  return found


def _bounded_iterable(module: core.ModuleInfo, iterable: ast.AST,
                      fn: Optional[ast.AST], consts: Set[str]) -> bool:
  if isinstance(iterable, ast.Call):
    callee = core.call_name(iterable)
    if callee in ('range', 'enumerate', 'sorted', 'reversed'):
      # range(n): values are ints bounded by config; the others wrap an
      # inner iterable — recurse on it.
      if callee == 'range':
        return True
      return bool(iterable.args) and _bounded_iterable(
          module, iterable.args[0], fn, consts)
    if (isinstance(iterable.func, ast.Attribute) and
        iterable.func.attr in ('items', 'keys', 'values')):
      base = iterable.func.value
      if isinstance(base, ast.Name):
        return (base.id in consts or
                _dict_has_constant_keys(module, base.id, fn))
    return False
  if isinstance(iterable, ast.Name):
    return iterable.id in consts
  return _is_constant_container(iterable, consts)


def _name_bounded(module: core.ModuleInfo, name: str,
                  fn: Optional[ast.AST], consts: Set[str]) -> bool:
  """Can ``name`` only hold config-bounded values in this scope?"""
  if name in consts:
    return True
  if fn is not None and isinstance(fn, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
    arg_names = {a.arg for a in (fn.args.posonlyargs + fn.args.args +
                                 fn.args.kwonlyargs)}
    if fn.args.vararg is not None:
      arg_names.add(fn.args.vararg.arg)
    if fn.args.kwarg is not None:
      arg_names.add(fn.args.kwarg.arg)
    if name in arg_names:
      return False  # caller-supplied: the classic leak shape
  bindings_seen = False
  for node in _scope_nodes(module, fn):
    if isinstance(node, ast.For):
      if name in _target_names(node.target):
        bindings_seen = True
        if not _bounded_iterable(module, node.iter, fn, consts):
          return False
    elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                           ast.GeneratorExp)):
      for comp in node.generators:
        if name in _target_names(comp.target):
          bindings_seen = True
          if not _bounded_iterable(module, comp.iter, fn, consts):
            return False
    elif isinstance(node, ast.Assign):
      if any(name in _target_names(t) for t in node.targets):
        bindings_seen = True
        if not _is_constant_container(node.value, consts):
          return False
  return bindings_seen


def _part_allowed(module: core.ModuleInfo, part: ast.AST,
                  fn: Optional[ast.AST], consts: Set[str]) -> bool:
  if isinstance(part, ast.Constant):
    return True
  text = core.expr_text(part)
  if text is not None and (text.startswith('self.') or
                           text.startswith('cls.')):
    return True  # instance configuration: bounded by instance count
  if isinstance(part, ast.Name):
    if _ALLOWED_NAME_RE.search(part.id):
      return True  # the allowlisted scope-plumbing pattern
    return _name_bounded(module, part.id, fn, consts)
  if isinstance(part, ast.Attribute):
    return bool(_ALLOWED_NAME_RE.search(part.attr))
  return False  # calls, subscripts, conditionals: runtime data


def check(module: core.ModuleInfo,
          program: core.Program) -> List[core.Finding]:
  del program
  consts = _module_constants(module)
  findings: List[core.Finding] = []
  for node in ast.walk(module.tree):
    if not isinstance(node, ast.Call) or not _is_metric_call(node):
      continue
    if not node.args:
      continue
    arg = node.args[0]
    parts = _built_parts(arg)
    if not parts:
      continue
    prefix = _static_prefix(arg)
    if any(prefix.startswith(allowed)
           for allowed in ALLOWED_SCOPE_PREFIXES):
      continue
    fn = module.enclosing(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    bad = [part for part in parts
           if not _part_allowed(module, part, fn, consts)]
    if not bad:
      continue
    rendered = ', '.join(
        filter(None, (core.expr_text(p) or type(p).__name__ for p in bad)))
    findings.append(core.Finding(
        rule=RULE, check='dynamic-metric-name',
        path=module.rel_path, line=node.lineno,
        symbol=core.qualname(module, node) or '<module>',
        message=(f'metric name built from runtime-variable part(s) '
                 f'[{rendered}]: every distinct value becomes a '
                 'permanent registry entry (unbounded label '
                 'cardinality); scope per-instance names through a '
                 'config-time prefix or cap the domain explicitly')))
  return findings
