"""Donation-discipline checker: rebind idiom without ``donate_argnums``.

The mirror image of ``donated_reuse``: that rule catches donating too
*eagerly* (reading a buffer after giving it away); this one catches not
donating at all when the call site proves donation is free. The
``state = step(state, batch)`` rebind idiom is that proof — the caller
overwrites its only reference to the argument with the result, so the
old buffer is dead the moment the call returns. A jitted step-shaped
function called this way WITHOUT ``donate_argnums`` keeps two full
copies of the train state resident (input + output) for the duration of
every dispatch: on a memory-bound TPU program that is the difference
between a batch size fitting and the 8.6× HBM-pressure cliff the batch
curve shows. The runtime twin is the program ledger's donation audit
(``observability/programs.py`` records requested vs actually-aliased
parameters per executable); this rule catches the hazard before the
program ever compiles.

One finding shape:

* ``undonated-rebind`` — a call site rebinds a result over a positional
  argument name (``x = f(x, ...)`` / ``x, aux = f(x, ...)``) of a
  callable KNOWN to be jitted without any donation spec: a name bound
  from ``jax.jit(...)`` with no ``donate_argnums``/``donate_argnames``
  (direct assign, local-factory return, or ``@jax.jit`` /
  ``@partial(jax.jit, ...)`` decorator).

Calls to donating jits are ``donated_reuse``'s jurisdiction and never
fire here. Waive intentional non-donation inline with
``# ANALYSIS_OK(donation-discipline): <why the input buffer must
survive the call — e.g. it is re-read on rollback>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tensor2robot_tpu.analysis import core

RULE = 'donation-discipline'

_JIT_WRAPPERS = {'jax.jit', 'jit', 'jax.pjit', 'pjit'}
_PARTIAL_NAMES = {'functools.partial', 'partial'}
_DONATE_KWARGS = ('donate_argnums', 'donate_argnames')


def _jit_call_donation(call: ast.Call) -> Optional[bool]:
  """None if not a jit(...) call; else True when it donates.

  ``partial(jax.jit, ...)`` counts as a jit call (the decorator idiom);
  a donate kwarg anywhere in the call counts as donating — positions
  don't matter here, only whether the author THOUGHT about donation.
  """
  name = core.call_name(call)
  if name in _PARTIAL_NAMES and call.args:
    inner = core.expr_text(call.args[0])
    if inner not in _JIT_WRAPPERS:
      return None
  elif name not in _JIT_WRAPPERS:
    return None
  return any(kw.arg in _DONATE_KWARGS for kw in call.keywords)


def _nondonating_names(module: core.ModuleInfo) -> Dict[str, int]:
  """Names bound to jitted callables with NO donation spec → def line."""
  # Local factories whose return value is a donation-less jit: the name
  # a caller binds the factory's result to inherits the hazard.
  factory_lines: Dict[str, int] = {}
  for fn in core.func_defs(module.tree):
    for node in ast.walk(fn):
      if isinstance(node, ast.Return) and isinstance(node.value, ast.Call):
        donates = _jit_call_donation(node.value)
        if donates is False:
          factory_lines[fn.name] = node.value.lineno
        elif donates:
          factory_lines.pop(fn.name, None)
  out: Dict[str, int] = {}
  for node in ast.walk(module.tree):
    if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
      donates = _jit_call_donation(node.value)
      line: Optional[int] = None
      if donates is False:
        line = node.value.lineno
      elif donates is None:
        callee = core.call_name(node.value)
        if callee is not None:
          leaf = callee.rsplit('.', 1)[-1]
          line = factory_lines.get(callee, factory_lines.get(leaf))
      if line is not None:
        for target in node.targets:
          text = core.expr_text(target)
          if text is not None:
            out[text] = line
    elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
      # @jax.jit (bare) or @jax.jit(...)/@partial(jax.jit, ...) without
      # a donate kwarg marks the function name itself.
      for dec in node.decorator_list:
        if isinstance(dec, ast.Call):
          donates = _jit_call_donation(dec)
          if donates is False:
            out[node.name] = node.lineno
          elif donates:
            out.pop(node.name, None)
        elif core.expr_text(dec) in _JIT_WRAPPERS:
          out[node.name] = node.lineno
  return out


def _target_names(stmt: ast.Assign) -> Set[str]:
  names: Set[str] = set()
  for target in stmt.targets:
    for node in ast.walk(target):
      if isinstance(node, ast.Name):
        names.add(node.id)
  return names


def check(module: core.ModuleInfo, program: core.Program
          ) -> List[core.Finding]:
  del program
  findings: List[core.Finding] = []
  nondonating = _nondonating_names(module)
  if not nondonating:
    return findings

  def scopes():
    yield module.tree
    yield from core.func_defs(module.tree)

  for scope in scopes():
    for stmt in core.walk_scope(scope):
      if not (isinstance(stmt, ast.Assign)
              and isinstance(stmt.value, ast.Call)):
        continue
      call = stmt.value
      callee = core.call_name(call)
      if callee not in nondonating:
        continue
      rebound = _target_names(stmt)
      arg_names = [a.id for a in call.args if isinstance(a, ast.Name)]
      overlap = sorted(rebound.intersection(arg_names))
      if not overlap:
        continue
      symbol = core.qualname(module, scope) if isinstance(
          scope, (ast.FunctionDef, ast.AsyncFunctionDef)) else ''
      positions = ', '.join(
          str(i) for i, a in enumerate(call.args)
          if isinstance(a, ast.Name) and a.id in overlap)
      findings.append(core.Finding(
          rule=RULE, check='undonated-rebind', path=module.rel_path,
          line=stmt.lineno, symbol=symbol,
          message=(f'{overlap[0]!r} is rebound over the result of '
                   f'{callee}(...) — the input buffer is dead after the '
                   'call, but the jit (line '
                   f'{nondonating[callee]}) has no donate_argnums: both '
                   'copies stay resident through every dispatch. Donate '
                   f'argnums ({positions}) to let XLA reuse the buffer '
                   'in place.')))
  return findings
