// Native record IO runtime: TFRecord framing, CRC32C, and a threaded
// interleaved prefetch reader.
//
// The reference framework rides on tf.data's C++ runtime for record IO
// (utils/tfdata.py); this library is the equivalent native component for
// the TPU rebuild — a TF-free data path the Python layer binds via
// ctypes (tensor2robot_tpu/data/native_io.py). Format per record
// (TFRecord wire format, interoperable with tf.io):
//
//   uint64 length (LE) | uint32 masked_crc32c(length) |
//   payload bytes      | uint32 masked_crc32c(payload)
//
// The interleave reader spawns one worker thread per file, each filling a
// bounded queue; the consumer round-robins across files (block_length=1
// semantics, deterministic order) so record parsing/decompression and
// disk latency overlap the training step.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- crc32c

uint32_t g_crc_table[8][256];

void crc32c_init() {
  const uint32_t poly = 0x82f63b78u;  // Castagnoli, reflected
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int k = 0; k < 8; k++) crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    g_crc_table[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = g_crc_table[0][i];
    for (int t = 1; t < 8; t++) {
      crc = (crc >> 8) ^ g_crc_table[0][crc & 0xff];
      g_crc_table[t][i] = crc;
    }
  }
}

struct CrcInit {
  CrcInit() { crc32c_init(); }
} g_crc_init;

uint32_t crc32c(const uint8_t* data, size_t n) {
  uint32_t crc = 0xffffffffu;
  // Slicing-by-8 over aligned middle, bytewise head/tail.
  while (n >= 8) {
    uint32_t lo;
    uint32_t hi;
    memcpy(&lo, data, 4);
    memcpy(&hi, data + 4, 4);
    lo ^= crc;
    crc = g_crc_table[7][lo & 0xff] ^ g_crc_table[6][(lo >> 8) & 0xff] ^
          g_crc_table[5][(lo >> 16) & 0xff] ^ g_crc_table[4][lo >> 24] ^
          g_crc_table[3][hi & 0xff] ^ g_crc_table[2][(hi >> 8) & 0xff] ^
          g_crc_table[1][(hi >> 16) & 0xff] ^ g_crc_table[0][hi >> 24];
    data += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ g_crc_table[0][(crc ^ *data++) & 0xff];
  return crc ^ 0xffffffffu;
}

uint32_t masked_crc(const uint8_t* data, size_t n) {
  uint32_t crc = crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

// ----------------------------------------------------------------- writer

struct Writer {
  FILE* f = nullptr;
};

// ----------------------------------------------------------------- reader

struct Reader {
  FILE* f = nullptr;
  bool verify = true;
  std::string current;
  std::string error;

  // Returns 1 record-read, 0 EOF, -1 error.
  int next() {
    uint8_t header[12];
    size_t got = fread(header, 1, 12, f);
    if (got == 0) return 0;
    if (got != 12) {
      error = "truncated record header";
      return -1;
    }
    uint64_t len;
    uint32_t len_crc;
    memcpy(&len, header, 8);
    memcpy(&len_crc, header + 8, 4);
    if (verify && masked_crc(header, 8) != len_crc) {
      error = "corrupted record length (crc mismatch)";
      return -1;
    }
    // With CRC verification off, a corrupt length field is caught only
    // here: cap at 1 GiB (far above any real tf.Example) and never let
    // resize() throw across the extern "C"/ctypes boundary.
    if (len > (1ull << 30)) {
      error = "implausible record length";
      return -1;
    }
    try {
      current.resize(len);
    } catch (const std::exception& e) {
      error = std::string("record allocation failed: ") + e.what();
      return -1;
    }
    if (len && fread(&current[0], 1, len, f) != len) {
      error = "truncated record payload";
      return -1;
    }
    uint32_t data_crc;
    if (fread(&data_crc, 1, 4, f) != 4) {
      error = "truncated record footer";
      return -1;
    }
    if (verify &&
        masked_crc(reinterpret_cast<const uint8_t*>(current.data()),
                   current.size()) != data_crc) {
      error = "corrupted record payload (crc mismatch)";
      return -1;
    }
    return 1;
  }
};

// ------------------------------------------------- interleave prefetcher

struct FileQueue {
  std::deque<std::string> q;
  std::mutex mu;
  std::condition_variable cv_push;
  std::condition_variable cv_pop;
  bool done = false;
  std::string error;
};

struct Interleave {
  std::vector<std::unique_ptr<FileQueue>> queues;  // one per SLOT
  std::vector<std::vector<std::string>> slot_files;
  std::vector<std::thread> workers;
  size_t capacity = 64;
  size_t cursor = 0;
  size_t open_files = 0;  // live SLOTS
  std::vector<bool> exhausted;
  std::string current;
  std::string error;
  bool stopping = false;
  std::mutex stop_mu;

  ~Interleave() {
    {
      std::lock_guard<std::mutex> l(stop_mu);
      stopping = true;
    }
    for (auto& fq : queues) {
      std::lock_guard<std::mutex> l(fq->mu);
      fq->done = true;
      fq->cv_push.notify_all();
      fq->cv_pop.notify_all();
    }
    for (auto& t : workers)
      if (t.joinable()) t.join();
  }

  bool stop_requested() {
    std::lock_guard<std::mutex> l(stop_mu);
    return stopping;
  }
};

// One worker per SLOT: reads its statically-assigned files (slot s owns
// files s, s+C, s+2C, ...) sequentially, so thread count and queue memory
// are bounded by the cycle length, not the file count.
void worker_read_slot(Interleave* it, FileQueue* fq,
                      const std::vector<std::string>* files, bool verify) {
  for (const std::string& path : *files) {
    Reader r;
    r.verify = verify;
    r.f = fopen(path.c_str(), "rb");
    if (!r.f) {
      std::lock_guard<std::mutex> l(fq->mu);
      fq->error = "cannot open " + path;
      fq->done = true;
      fq->cv_pop.notify_all();
      return;
    }
    for (;;) {
      int rc = r.next();
      if (rc != 1) {
        if (rc < 0) {
          std::lock_guard<std::mutex> l(fq->mu);
          fq->error = path + ": " + r.error;
          fq->done = true;
          fq->cv_pop.notify_all();
          fclose(r.f);
          return;
        }
        break;  // EOF: advance to this slot's next file
      }
      std::unique_lock<std::mutex> l(fq->mu);
      fq->cv_push.wait(l, [&] {
        return fq->q.size() < it->capacity || fq->done;
      });
      if (fq->done) {  // shutdown
        fclose(r.f);
        return;
      }
      fq->q.push_back(std::move(r.current));
      fq->cv_pop.notify_one();
      l.unlock();
      if (it->stop_requested()) {
        fclose(r.f);
        return;
      }
    }
    fclose(r.f);
  }
  std::lock_guard<std::mutex> l(fq->mu);
  fq->done = true;
  fq->cv_pop.notify_all();
}

}  // namespace

extern "C" {

// ----------------------------------------------------------- writer API

void* t2r_writer_open(const char* path, const char* mode) {
  FILE* f = fopen(path, (mode && mode[0] == 'a') ? "ab" : "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  return w;
}

int t2r_writer_write(void* handle, const void* data, uint64_t len) {
  auto* w = static_cast<Writer*>(handle);
  uint8_t header[12];
  memcpy(header, &len, 8);
  uint32_t len_crc = masked_crc(header, 8);
  memcpy(header + 8, &len_crc, 4);
  uint32_t data_crc =
      masked_crc(static_cast<const uint8_t*>(data), len);
  if (fwrite(header, 1, 12, w->f) != 12) return -1;
  if (len && fwrite(data, 1, len, w->f) != len) return -1;
  if (fwrite(&data_crc, 1, 4, w->f) != 4) return -1;
  return 0;
}

int t2r_writer_flush(void* handle) {
  return fflush(static_cast<Writer*>(handle)->f);
}

int t2r_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  int rc = fclose(w->f);
  delete w;
  return rc;
}

// ----------------------------------------------------------- reader API

void* t2r_reader_open(const char* path, int verify_crc) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* r = new Reader();
  r->f = f;
  r->verify = verify_crc != 0;
  return r;
}

// Returns payload length and sets *data (valid until the next call);
// -1 on EOF, -2 on error (see t2r_reader_error).
int64_t t2r_reader_next(void* handle, const uint8_t** data) {
  auto* r = static_cast<Reader*>(handle);
  int rc = r->next();
  if (rc == 0) return -1;
  if (rc < 0) return -2;
  *data = reinterpret_cast<const uint8_t*>(r->current.data());
  return static_cast<int64_t>(r->current.size());
}

const char* t2r_reader_error(void* handle) {
  return static_cast<Reader*>(handle)->error.c_str();
}

// Repositions the reader to an absolute byte offset — a RECORD BOUNDARY
// from a shard index sidecar (data/shard_index.py); seeking mid-record
// surfaces as a framing/CRC error on the next read, never silence.
// Returns 0 on success, -1 on seek failure.
int t2r_reader_seek(void* handle, uint64_t offset) {
  auto* r = static_cast<Reader*>(handle);
  if (fseeko(r->f, static_cast<off_t>(offset), SEEK_SET) != 0) {
    r->error = "seek failed";
    return -1;
  }
  return 0;
}

void t2r_reader_close(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  fclose(r->f);
  delete r;
}

// ------------------------------------------------------- interleave API

void* t2r_interleave_open(const char** paths, int n_paths,
                          int cycle_length, int queue_capacity,
                          int verify_crc) {
  if (n_paths <= 0) return nullptr;
  int slots = cycle_length > 0 ? cycle_length : 16;
  if (slots > n_paths) slots = n_paths;
  auto* it = new Interleave();
  it->capacity = queue_capacity > 0 ? queue_capacity : 64;
  it->exhausted.assign(slots, false);
  it->open_files = slots;
  it->slot_files.resize(slots);
  for (int i = 0; i < n_paths; i++)
    it->slot_files[i % slots].push_back(paths[i]);
  for (int s = 0; s < slots; s++)
    it->queues.emplace_back(new FileQueue());
  for (int s = 0; s < slots; s++)
    it->workers.emplace_back(worker_read_slot, it, it->queues[s].get(),
                             &it->slot_files[s], verify_crc != 0);
  return it;
}

// Round-robin pop across slots (block_length=1). Returns length, -1
// when every slot is exhausted, -2 on error.
int64_t t2r_interleave_next(void* handle, const uint8_t** data) {
  auto* it = static_cast<Interleave*>(handle);
  while (it->open_files > 0) {
    size_t i = it->cursor % it->queues.size();
    if (it->exhausted[i]) {
      it->cursor++;
      continue;
    }
    FileQueue* fq = it->queues[i].get();
    std::unique_lock<std::mutex> l(fq->mu);
    fq->cv_pop.wait(l, [&] { return !fq->q.empty() || fq->done; });
    if (!fq->q.empty()) {
      it->current = std::move(fq->q.front());
      fq->q.pop_front();
      fq->cv_push.notify_one();
      l.unlock();
      it->cursor++;
      *data = reinterpret_cast<const uint8_t*>(it->current.data());
      return static_cast<int64_t>(it->current.size());
    }
    // done && empty → file finished (or errored)
    if (!fq->error.empty()) {
      it->error = fq->error;
      return -2;
    }
    it->exhausted[i] = true;
    it->open_files--;
    it->cursor++;
  }
  return -1;
}

const char* t2r_interleave_error(void* handle) {
  return static_cast<Interleave*>(handle)->error.c_str();
}

void t2r_interleave_close(void* handle) {
  delete static_cast<Interleave*>(handle);
}

// ------------------------------------------------------------ utilities

uint32_t t2r_masked_crc32c(const void* data, uint64_t len) {
  return masked_crc(static_cast<const uint8_t*>(data), len);
}

}  // extern "C"

// ===================================================================
// tf.Example wire-format parser (no protobuf dependency).
//
// Schema subset used by the spec-driven codec (data/example_codec.py):
//   Example{1: Features{1: map<string, Feature{1:BytesList 2:FloatList
//   3:Int64List}>}}
// Fixed- and padded-varlen float/int64 features fill contiguous [B, N]
// buffers; bytes features (encoded images) are returned as
// (offset, length) spans into the caller's record so Python can slice
// without copying.

namespace {

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  bool skip(uint32_t wire) {
    switch (wire) {
      case 0: varint(); return ok;
      case 1: if (end - p < 8) return ok = false; p += 8; return true;
      case 2: {
        uint64_t n = varint();
        if (!ok || static_cast<uint64_t>(end - p) < n) return ok = false;
        p += n;
        return true;
      }
      case 5: if (end - p < 4) return ok = false; p += 4; return true;
      default: return ok = false;
    }
  }

  // Returns (field, wire) or field=0 at end.
  bool tag(uint32_t* field, uint32_t* wire) {
    if (p >= end) return false;
    uint64_t t = varint();
    if (!ok) return false;
    *field = static_cast<uint32_t>(t >> 3);
    *wire = static_cast<uint32_t>(t & 7);
    return true;
  }

  Cursor sub() {
    uint64_t n = varint();
    Cursor c{p, p, false};
    if (!ok || static_cast<uint64_t>(end - p) < n) return c;
    c.end = p + n;
    c.ok = true;
    p += n;
    return c;
  }
};

enum FieldKind { kFloat = 0, kInt64 = 1, kBytes = 2 };

struct FieldSpec {
  std::string key;
  int kind;
  int64_t flat_len;   // elements per example; for kBytes: max spans
  int required;
  int varlen;         // pad/clip to flat_len; fixed specs error on mismatch
};

struct Parser {
  std::vector<FieldSpec> fields;
  std::string error;
};

// Parses one Feature submessage into the output slot for record b.
bool parse_feature(Cursor fc, const FieldSpec& fs, int64_t b,
                   void* out, const uint8_t* rec_base, Parser* pr) {
  uint32_t field, wire;
  int64_t count = 0;
  while (fc.tag(&field, &wire)) {
    if (!fc.ok) break;
    if (field == 2 && fs.kind == kFloat && wire == 2) {  // FloatList
      Cursor lc = fc.sub();
      if (!fc.ok || !lc.ok) break;
      uint32_t f2, w2;
      float* dst = static_cast<float*>(out) + b * fs.flat_len;
      while (lc.tag(&f2, &w2)) {
        if (f2 == 1 && w2 == 2) {  // packed
          Cursor pc = lc.sub();
          if (!lc.ok || !pc.ok) { lc.ok = false; break; }
          int64_t n = (pc.end - pc.p) / 4;
          for (int64_t i = 0; i < n; i++) {
            if (count < fs.flat_len)
              memcpy(dst + count, pc.p + 4 * i, 4);
            count++;  // clip extras (varlen clip semantics)
          }
        } else if (f2 == 1 && w2 == 5) {  // unpacked float
          if (lc.end - lc.p < 4) { lc.ok = false; break; }
          if (count < fs.flat_len) memcpy(dst + count, lc.p, 4);
          count++;
          lc.p += 4;
        } else if (!lc.skip(w2)) {
          break;
        }
      }
      if (!lc.ok) { pr->error = fs.key + ": malformed FloatList"; return false; }
    } else if (field == 3 && fs.kind == kInt64 && wire == 2) {  // Int64List
      Cursor lc = fc.sub();
      if (!fc.ok || !lc.ok) break;
      uint32_t f2, w2;
      int64_t* dst = static_cast<int64_t*>(out) + b * fs.flat_len;
      while (lc.tag(&f2, &w2)) {
        if (f2 == 1 && w2 == 2) {  // packed varints
          Cursor pc = lc.sub();
          if (!lc.ok || !pc.ok) { lc.ok = false; break; }
          while (pc.p < pc.end && pc.ok) {
            uint64_t v = pc.varint();
            if (!pc.ok) break;
            if (count < fs.flat_len)
              dst[count] = static_cast<int64_t>(v);
            count++;
          }
          if (!pc.ok) { lc.ok = false; break; }
        } else if (f2 == 1 && w2 == 0) {
          uint64_t v = lc.varint();
          if (!lc.ok) break;
          if (count < fs.flat_len) dst[count] = static_cast<int64_t>(v);
          count++;
        } else if (!lc.skip(w2)) {
          break;
        }
      }
      if (!lc.ok) { pr->error = fs.key + ": malformed Int64List"; return false; }
    } else if (field == 1 && fs.kind == kBytes && wire == 2) {  // BytesList
      Cursor lc = fc.sub();
      if (!fc.ok || !lc.ok) break;
      uint32_t f2, w2;
      // spans buffer: int64 [B, flat_len, 2] of (offset, length)
      int64_t* dst = static_cast<int64_t*>(out) + b * fs.flat_len * 2;
      while (lc.tag(&f2, &w2)) {
        if (f2 == 1 && w2 == 2) {
          Cursor bc = lc.sub();
          if (!lc.ok || !bc.ok) { lc.ok = false; break; }
          if (count < fs.flat_len) {
            dst[count * 2] = bc.p - rec_base;
            dst[count * 2 + 1] = bc.end - bc.p;
          }
          count++;
        } else if (!lc.skip(w2)) {
          break;
        }
      }
      if (!lc.ok) { pr->error = fs.key + ": malformed BytesList"; return false; }
    } else if (!fc.skip(wire)) {
      break;
    }
  }
  if (!fc.ok) {
    pr->error = fs.key + ": malformed Feature";
    return false;
  }
  if (count == 0 && fs.required) {
    pr->error = fs.key + ": required feature empty/missing";
    return false;
  }
  if (!fs.varlen && count != 0 && count != fs.flat_len) {
    pr->error = fs.key + ": expected " + std::to_string(fs.flat_len) +
                " values, got " + std::to_string(count);
    return false;
  }
  return true;
}

}  // namespace

extern "C" {

// Output buffers are pre-filled by the caller with pad/default values;
// the parser only overwrites what the wire data provides.
void* t2r_parser_create(const char** keys, const int* kinds,
                        const int64_t* flat_lens, const int* required,
                        const int* varlen, int n_fields) {
  auto* p = new Parser();
  for (int i = 0; i < n_fields; i++) {
    p->fields.push_back(FieldSpec{keys[i], kinds[i], flat_lens[i],
                                  required[i], varlen[i]});
  }
  return p;
}

const char* t2r_parser_error(void* handle) {
  return static_cast<Parser*>(handle)->error.c_str();
}

// Fills per-field output buffers for a batch of serialized Examples.
// float fields: float32 [B, flat_len]; int64 fields: int64 [B, flat_len];
// bytes fields: int64 [B, flat_len, 2] (offset, len) into each record.
// Buffers must be pre-filled by the caller with pad/default values.
// Returns 0 on success, -1 on error (see t2r_parser_error).
int t2r_parser_parse_batch(void* handle, const uint8_t* const* recs,
                           const uint64_t* lens, int64_t batch,
                           void* const* outs) {
  auto* pr = static_cast<Parser*>(handle);
  pr->error.clear();
  size_t nf = pr->fields.size();
  std::vector<bool> seen(nf);
  for (int64_t b = 0; b < batch; b++) {
    std::fill(seen.begin(), seen.end(), false);
    Cursor rc{recs[b], recs[b] + lens[b]};
    uint32_t field, wire;
    while (rc.tag(&field, &wire)) {
      if (!rc.ok) break;
      if (field != 1 || wire != 2) {  // not Features
        if (!rc.skip(wire)) break;
        continue;
      }
      Cursor feats = rc.sub();
      if (!rc.ok || !feats.ok) { rc.ok = false; break; }
      uint32_t f1, w1;
      while (feats.tag(&f1, &w1)) {
        if (f1 != 1 || w1 != 2) {  // not a map entry
          if (!feats.skip(w1)) break;
          continue;
        }
        Cursor entry = feats.sub();
        if (!feats.ok || !entry.ok) { feats.ok = false; break; }
        // map entry: field 1 key, field 2 Feature
        std::string key;
        Cursor feature{nullptr, nullptr, false};
        uint32_t f2, w2;
        while (entry.tag(&f2, &w2)) {
          if (f2 == 1 && w2 == 2) {
            Cursor kc = entry.sub();
            if (!entry.ok || !kc.ok) { entry.ok = false; break; }
            key.assign(reinterpret_cast<const char*>(kc.p), kc.end - kc.p);
          } else if (f2 == 2 && w2 == 2) {
            feature = entry.sub();
            if (!entry.ok) break;
          } else if (!entry.skip(w2)) {
            break;
          }
        }
        if (!entry.ok) { feats.ok = false; break; }
        for (size_t i = 0; i < nf; i++) {
          if (pr->fields[i].key == key) {
            if (feature.ok) {
              if (!parse_feature(feature, pr->fields[i], b, outs[i],
                                 recs[b], pr))
                return -1;
              seen[i] = true;
            }
            break;
          }
        }
      }
      if (!feats.ok) { rc.ok = false; break; }
    }
    if (!rc.ok) {
      pr->error = "malformed Example at batch index " + std::to_string(b);
      return -1;
    }
    for (size_t i = 0; i < nf; i++) {
      if (!seen[i] && pr->fields[i].required) {
        pr->error = pr->fields[i].key + ": required feature missing";
        return -1;
      }
    }
  }
  return 0;
}

void t2r_parser_destroy(void* handle) {
  delete static_cast<Parser*>(handle);
}

}  // extern "C"
