// Native JPEG batch decoder for the record input pipeline.
//
// The reference's input pipeline decodes images inside tf.data's C++
// runtime (tf.image.decode_image under utils/tfdata.py's parse map);
// this is the TPU rebuild's equivalent: libjpeg decoding straight into
// the caller-provided contiguous [N, H, W, C] batch buffer, so batch
// assembly needs no per-image numpy intermediates and no np.stack copy.
// Python binds via ctypes (tensor2robot_tpu/native/__init__.py) and
// falls back to PIL per image for anything this decoder declines
// (non-JPEG bytes, unexpected geometry) — see the status codes below.
//
// Built as its own shared object so a host without libjpeg headers
// still gets the record-IO runtime; the Python layer degrades to PIL.

#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

// Per-image decode status written back to the caller.
enum Status : int32_t {
  kOk = 0,
  kEmpty = 1,      // empty bytes: buffer slot zero-filled (codec convention)
  kNotJpeg = 2,    // no JPEG magic: slot untouched, caller must fill
  kBadShape = 3,   // decoded geometry != (H, W): slot untouched
  kError = 4,      // libjpeg failure: slot untouched
};

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* mgr = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(mgr->jump, 1);
}

void output_message(j_common_ptr) {}  // silence stderr chatter

int32_t decode_one(const uint8_t* buf, uint64_t len, uint8_t* out,
                   int height, int width, int channels) {
  if (len == 0) {
    memset(out, 0, static_cast<size_t>(height) * width * channels);
    return kEmpty;
  }
  if (len < 3 || buf[0] != 0xFF || buf[1] != 0xD8) return kNotJpeg;

  jpeg_decompress_struct cinfo;
  ErrorMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = error_exit;
  err.pub.output_message = output_message;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return kError;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = (channels == 1) ? JCS_GRAYSCALE : JCS_RGB;
  // ISLOW: the default PIL also uses — decoded pixels are BITWISE
  // IDENTICAL to the PIL fallback path, so mixed native/fallback
  // batches are deterministic. (IFAST measured ~15% faster but ±1 LSB
  // off the fallback decode.)
  cinfo.dct_method = JDCT_ISLOW;
  jpeg_start_decompress(&cinfo);
  if (static_cast<int>(cinfo.output_height) != height ||
      static_cast<int>(cinfo.output_width) != width ||
      static_cast<int>(cinfo.output_components) != channels) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return kBadShape;
  }
  const size_t stride = static_cast<size_t>(width) * channels;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out + cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return kOk;
}

}  // namespace

extern "C" {

// Decodes n JPEG buffers into the contiguous out[n, height, width,
// channels] uint8 buffer. status[i] receives a Status per image; slots
// whose status is kNotJpeg/kBadShape/kError are left untouched for the
// caller's fallback decoder. num_threads <= 1 decodes inline (the right
// choice on single-core hosts); otherwise images are striped across
// worker threads (libjpeg contexts are per-call, so this is safe).
// Returns the number of non-Ok, non-Empty statuses.
int t2r_jpeg_decode_batch(const uint8_t** bufs, const uint64_t* lens,
                          int n, uint8_t* out, int height, int width,
                          int channels, int num_threads,
                          int32_t* status) {
  const size_t image_bytes =
      static_cast<size_t>(height) * width * channels;
  auto work = [&](int begin, int end) {
    for (int i = begin; i < end; i++) {
      status[i] = decode_one(bufs[i], lens[i], out + i * image_bytes,
                             height, width, channels);
    }
  };
  if (num_threads <= 1 || n <= 1) {
    work(0, n);
  } else {
    int workers = num_threads < n ? num_threads : n;
    std::vector<std::thread> threads;
    threads.reserve(workers);
    int chunk = (n + workers - 1) / workers;
    for (int w = 0; w < workers; w++) {
      int begin = w * chunk;
      int end = begin + chunk < n ? begin + chunk : n;
      if (begin >= end) break;
      threads.emplace_back(work, begin, end);
    }
    for (auto& t : threads) t.join();
  }
  int failures = 0;
  for (int i = 0; i < n; i++) {
    if (status[i] != kOk && status[i] != kEmpty) failures++;
  }
  return failures;
}

}  // extern "C"
