"""Native (C++) runtime components, built on demand and bound via ctypes.

The reference rides on tf.data's C++ runtime for its data path; this
package is the TPU rebuild's own native layer: ``record_io.cpp`` provides
TFRecord-wire-format IO (CRC32C framing) plus a threaded interleaved
prefetch reader, compiled once per source revision with the system
toolchain and cached.

``load_record_io()`` returns the loaded ``ctypes.CDLL`` or ``None`` when
no toolchain is available (callers fall back to the TF path). Set
``T2R_NATIVE_DISABLE=1`` to force the fallback.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), 'record_io.cpp')
_JPEG_SRC = os.path.join(os.path.dirname(__file__), 'jpeg_decode.cpp')
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None  # GUARDED_BY(_LOCK)
_TRIED = False  # GUARDED_BY(_LOCK)
_JPEG_LIB: Optional[ctypes.CDLL] = None  # GUARDED_BY(_LOCK)
_JPEG_TRIED = False  # GUARDED_BY(_LOCK)


def _build_dir() -> str:
  cache = os.environ.get('T2R_NATIVE_CACHE') or os.path.join(
      tempfile.gettempdir(), 't2r_native')
  os.makedirs(cache, exist_ok=True)
  return cache


def _compile_src(src: str, stem: str, what: str,
                 extra_flags=()) -> Optional[str]:
  with open(src, 'rb') as f:
    digest = hashlib.sha256(f.read()).hexdigest()[:16]
  out = os.path.join(_build_dir(), f'{stem}_{digest}.so')
  if os.path.exists(out):
    return out
  tmp = out + f'.tmp{os.getpid()}'
  cmd = ['g++', '-O3', '-std=c++17', '-shared', '-fPIC', '-pthread',
         src, '-o', tmp, *extra_flags]
  try:
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)
  except (OSError, subprocess.SubprocessError) as e:
    logging.warning('native %s build failed (%s); using fallback', what, e)
    return None
  os.replace(tmp, out)  # atomic: racing builders converge on one file
  return out


def _compile() -> Optional[str]:
  return _compile_src(_SRC, 'libt2r_io', 'record_io')


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
  u8p = ctypes.POINTER(ctypes.c_uint8)
  lib.t2r_writer_open.restype = ctypes.c_void_p
  lib.t2r_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
  lib.t2r_writer_write.restype = ctypes.c_int
  lib.t2r_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64]
  lib.t2r_writer_flush.restype = ctypes.c_int
  lib.t2r_writer_flush.argtypes = [ctypes.c_void_p]
  lib.t2r_writer_close.restype = ctypes.c_int
  lib.t2r_writer_close.argtypes = [ctypes.c_void_p]

  lib.t2r_reader_open.restype = ctypes.c_void_p
  lib.t2r_reader_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
  lib.t2r_reader_next.restype = ctypes.c_int64
  lib.t2r_reader_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(u8p)]
  lib.t2r_reader_error.restype = ctypes.c_char_p
  lib.t2r_reader_error.argtypes = [ctypes.c_void_p]
  lib.t2r_reader_seek.restype = ctypes.c_int
  lib.t2r_reader_seek.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
  lib.t2r_reader_close.restype = None
  lib.t2r_reader_close.argtypes = [ctypes.c_void_p]

  lib.t2r_interleave_open.restype = ctypes.c_void_p
  lib.t2r_interleave_open.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                                      ctypes.c_int, ctypes.c_int,
                                      ctypes.c_int, ctypes.c_int]
  lib.t2r_interleave_next.restype = ctypes.c_int64
  lib.t2r_interleave_next.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(u8p)]
  lib.t2r_interleave_error.restype = ctypes.c_char_p
  lib.t2r_interleave_error.argtypes = [ctypes.c_void_p]
  lib.t2r_interleave_close.restype = None
  lib.t2r_interleave_close.argtypes = [ctypes.c_void_p]

  lib.t2r_masked_crc32c.restype = ctypes.c_uint32
  lib.t2r_masked_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]

  lib.t2r_parser_create.restype = ctypes.c_void_p
  lib.t2r_parser_create.argtypes = [
      ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int),
      ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int),
      ctypes.POINTER(ctypes.c_int), ctypes.c_int]
  lib.t2r_parser_parse_batch.restype = ctypes.c_int
  lib.t2r_parser_parse_batch.argtypes = [
      ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
      ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64,
      ctypes.POINTER(ctypes.c_void_p)]
  lib.t2r_parser_error.restype = ctypes.c_char_p
  lib.t2r_parser_error.argtypes = [ctypes.c_void_p]
  lib.t2r_parser_destroy.restype = None
  lib.t2r_parser_destroy.argtypes = [ctypes.c_void_p]
  return lib


def load_record_io() -> Optional[ctypes.CDLL]:
  """Compiles (once) and loads the native record-IO library."""
  global _LIB, _TRIED
  if os.environ.get('T2R_NATIVE_DISABLE'):
    return None
  with _LOCK:
    if _TRIED:
      return _LIB
    _TRIED = True
    path = _compile()
    if path is not None:
      try:
        _LIB = _bind(ctypes.CDLL(path))
      except OSError as e:
        logging.warning('native record_io load failed (%s)', e)
        _LIB = None
    return _LIB


def _bind_jpeg(lib: ctypes.CDLL) -> ctypes.CDLL:
  lib.t2r_jpeg_decode_batch.restype = ctypes.c_int
  lib.t2r_jpeg_decode_batch.argtypes = [
      ctypes.POINTER(ctypes.c_char_p),  # bufs
      ctypes.POINTER(ctypes.c_uint64),  # lens
      ctypes.c_int,                     # n
      ctypes.POINTER(ctypes.c_uint8),   # out
      ctypes.c_int, ctypes.c_int, ctypes.c_int,  # h, w, c
      ctypes.c_int,                     # num_threads
      ctypes.POINTER(ctypes.c_int32),   # status
  ]
  return lib


def load_jpeg_decode() -> Optional[ctypes.CDLL]:
  """Compiles (once, needs libjpeg) and loads the JPEG batch decoder."""
  global _JPEG_LIB, _JPEG_TRIED
  if os.environ.get('T2R_NATIVE_DISABLE') or os.environ.get(
      'T2R_NATIVE_JPEG_DISABLE'):
    return None
  with _LOCK:
    if _JPEG_TRIED:
      return _JPEG_LIB
    _JPEG_TRIED = True
    path = _compile_src(_JPEG_SRC, 'libt2r_jpeg', 'jpeg_decode',
                        extra_flags=('-ljpeg',))
    if path is not None:
      try:
        _JPEG_LIB = _bind_jpeg(ctypes.CDLL(path))
      except OSError as e:
        logging.warning('native jpeg_decode load failed (%s)', e)
        _JPEG_LIB = None
    return _JPEG_LIB
