"""Classification model base (reference: models/classification_model.py:48-242).

The reference declares ``a_func(features) -> logits`` and wires sigmoid
cross-entropy plus eval metrics. Here the subclass supplies a Flax module
whose output dict contains ``'a_predicted'`` logits; loss and metrics are
pure jnp.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from tensor2robot_tpu.models.base import FlaxModel
from tensor2robot_tpu.specs import SpecStruct


def sigmoid_log_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
  """Mean sigmoid cross entropy (tf.losses.log_loss on sigmoid outputs)."""
  logits = logits.astype(jnp.float32)
  labels = labels.astype(jnp.float32)
  # Numerically stable BCE-with-logits.
  per_element = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(
      jnp.exp(-jnp.abs(logits)))
  return jnp.mean(per_element)


class ClassificationModel(FlaxModel):
  """Binary classifier over spec-declared features.

  Predictions contract (classification_model.py:154-201):
  ``a_predicted`` (logits). Eval metrics: loss/accuracy/precision/recall/mse.
  """

  loss_fn = staticmethod(sigmoid_log_loss)

  def model_train_fn(self, features, labels, inference_outputs, mode):
    logits = inference_outputs['a_predicted']
    target = self._classification_target(labels)
    loss = self.loss_fn(logits, target)
    return loss, {}

  def _classification_target(self, labels) -> jax.Array:
    """The label tensor holding {0,1} targets; override for custom specs."""
    if isinstance(labels, SpecStruct) or hasattr(labels, 'keys'):
      keys = list(labels.keys())
      if len(keys) != 1:
        raise ValueError(
            f'Override _classification_target for multi-label specs: {keys}')
      return labels[keys[0]]
    return labels

  def model_eval_fn(self, features, labels, inference_outputs):
    logits = inference_outputs['a_predicted'].astype(jnp.float32)
    target = self._classification_target(labels).astype(jnp.float32)
    prob = jax.nn.sigmoid(logits)
    predicted = (prob > 0.5).astype(jnp.float32)
    loss = self.loss_fn(logits, target)
    tp = jnp.sum(predicted * target)
    metrics = {
        'loss': loss,
        'accuracy': jnp.mean((predicted == target).astype(jnp.float32)),
        'precision': tp / jnp.maximum(jnp.sum(predicted), 1.0),
        'recall': tp / jnp.maximum(jnp.sum(target), 1.0),
        'mean_squared_error': jnp.mean(jnp.square(prob - target)),
    }
    return metrics

  def create_export_outputs_fn(self, features, inference_outputs):
    outputs = SpecStruct()
    outputs['a_predicted'] = jax.nn.sigmoid(
        inference_outputs['a_predicted'].astype(jnp.float32))
    return outputs
