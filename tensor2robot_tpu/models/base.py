"""Model protocol: the TPU-native equivalent of the reference's model layer.

Re-design of ``ModelInterface`` / ``AbstractT2RModel``
(``/root/reference/models/model_interface.py:53-151``,
``/root/reference/models/abstract_model.py:153-919``). The reference couples
the model to ``tf.estimator``: ``model_fn(features, labels, mode)`` builds a
graph and returns an ``EstimatorSpec``; TPU support is bolted on by wrapping
the model in ``TPUT2RModelWrapper``.

Here the model is a *functional protocol* and one generic trainer owns the
jitted step, so there is a single code path for CPU/GPU/TPU:

* ``get_feature_specification(mode)`` / ``get_label_specification(mode)``
  declare the device-side data contract (post-preprocessing).
* ``preprocessor`` pairs the model with its preprocessor, wrapped in the
  bfloat16 :class:`DtypePolicyPreprocessor` when ``device_type == 'tpu'``
  (capability of ``models/tpu_model_wrapper.py:58-314`` with no wrapper class
  for the model itself — dtype policy lives at the data boundary).
* ``init_variables(rng, features)`` / ``inference_network_fn(variables, ...)``
  replace graph building: pure functions over explicit Flax variables, safe
  to ``jax.jit`` / ``pjit`` / ``vmap`` (which is what makes MAML trivial).
* ``model_train_fn`` / ``model_eval_fn`` / ``create_export_outputs_fn``
  keep the reference's names and roles (loss, eval metrics, serving outputs).

The trainer composes these exactly like ``abstract_model.py:683-821``
composes ``model_fn``, but as jitted functions instead of graph modes.
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Dict, Mapping, Optional, Sequence, Tuple, Type

import jax

from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.preprocessors import (
    AbstractPreprocessor,
    DtypePolicyPreprocessor,
    NoOpPreprocessor,
)
from tensor2robot_tpu.specs import SpecStruct, algebra

# A model's variables: a (frozen) dict of Flax collections, always containing
# 'params' (trainable) and possibly others ('batch_stats', ...).
Variables = Mapping[str, Any]
Predictions = SpecStruct
Scalars = Dict[str, Any]

DEVICE_TYPE_CPU = 'cpu'
DEVICE_TYPE_GPU = 'gpu'
DEVICE_TYPE_TPU = 'tpu'


def split_variables(variables: Variables) -> Tuple[Any, Dict[str, Any]]:
  """Splits Flax variables into (trainable params, non-trainable state)."""
  variables = dict(variables)
  params = variables.pop('params', {})
  return params, variables


def merge_variables(params: Any, model_state: Mapping[str, Any]) -> Variables:
  merged = dict(model_state or {})
  merged['params'] = params
  return merged


class ModelInterface(abc.ABC):
  """Minimal surface the infrastructure (trainer/predictors) relies on.

  Mirrors ``models/model_interface.py:53-151``.
  """

  @property
  @abc.abstractmethod
  def preprocessor(self) -> AbstractPreprocessor:
    ...

  @abc.abstractmethod
  def get_feature_specification(self, mode: str) -> SpecStruct:
    """Device-side (post-preprocessing) feature specs."""

  @abc.abstractmethod
  def get_label_specification(self, mode: str) -> Optional[SpecStruct]:
    """Device-side (post-preprocessing) label specs."""

  def get_feature_specification_for_packing(self, mode: str) -> SpecStruct:
    """Specs used by policies to pack numpy inputs (pre-preprocessing)."""
    return self.preprocessor.get_in_feature_specification(mode)

  def get_label_specification_for_packing(
      self, mode: str) -> Optional[SpecStruct]:
    return self.preprocessor.get_in_label_specification(mode)


class AbstractT2RModel(ModelInterface):
  """Base model: spec declaration + pure network/loss/metric functions.

  Constructor flags mirror ``abstract_model.py:168-211``:

  * ``preprocessor_cls``: preprocessor type paired with this model; it is
    constructed with the model's spec getters (the spec handshake of
    ``input_generators/abstract_input_generator.py:80-103``).
  * ``create_optimizer_fn``: zero-arg factory returning an optax
    ``GradientTransformation`` (see :mod:`tensor2robot_tpu.models.optimizers`).
  * ``device_type``: 'cpu' | 'gpu' | 'tpu'. On 'tpu', the preprocessor is
    wrapped with the bfloat16 dtype policy.
  * ``use_avg_model_params``: keep an EMA of params in the train state and
    export/eval the averaged weights — capability of the reference's
    ``MovingAverageOptimizer`` + swapping saver (``models/optimizers.py:
    140-167`` in the reference) without any saver trickery.
  * ``init_from_checkpoint_fn``: ``fn(params, model_state) -> (params,
    model_state)`` warm-start hook, the equivalent of
    ``default_init_from_checkpoint_fn`` (``abstract_model.py:88-118``).
  * ``remat_policy``: activation-rematerialization policy applied around
    this model's conv towers (``'none' | 'conv_towers' | 'full'``, see
    :mod:`tensor2robot_tpu.layers.remat`). Trades activation HBM against
    recompute so larger (micro)batches fit past the memory cliff; the
    parameter tree and numerics are unchanged — only backward-pass
    scheduling differs. Models that build remat-capable towers
    (``layers.resnet.ResNet``, ``layers.vision_layers.
    ImagesToFeaturesModel``, the qtopt/grasp2vec networks) thread this
    through; models without towers accept and ignore it.
  * ``kernel_policy``: hand-written Pallas kernel routing for the conv
    towers (``'none' | 'pool' | 'pool_conv'``, see
    :mod:`tensor2robot_tpu.ops._pallas_dispatch`) — same shape as
    ``remat_policy``. ``'pool'`` routes max-pools through the
    argmax-emitting fused kernel (``ops/pool.py``); ``'pool_conv'``
    additionally routes the shallow first conv through the
    space-to-depth Pallas matmul (``ops/conv_s2d.py``). Call sites are
    size-gated and fall back to the stock XLA ops off-TPU or for
    unsupported shapes; parameter trees are identical either way, so
    checkpoints interchange. Off by default.
  * ``matmul_precision``: contraction precision for Dense/Conv
    (``'bf16' | 'fp8'``, see :mod:`tensor2robot_tpu.quantize.
    fp8_training`). ``'fp8'`` runs the matmul contractions through
    delayed-amax-scaled ``float8_e4m3fn`` quantize-dequantize (master
    weights stay f32 in the optimizer state; gradients leave the ops
    unscaled in full precision). ``TrainerConfig.matmul_precision``
    overrides this at trainer construction.
  """

  def __init__(self,
               preprocessor_cls: Optional[Type[AbstractPreprocessor]] = None,
               create_optimizer_fn: Optional[Callable[[], Any]] = None,
               device_type: str = DEVICE_TYPE_TPU,
               use_avg_model_params: bool = False,
               avg_model_params_decay: float = 0.9999,
               init_from_checkpoint_fn: Optional[Callable] = None,
               remat_policy: str = 'none',
               kernel_policy: str = 'none',
               matmul_precision: str = 'bf16'):
    from tensor2robot_tpu.layers import remat as remat_lib
    from tensor2robot_tpu.ops import _pallas_dispatch as dispatch_lib
    from tensor2robot_tpu.quantize import fp8_training as fp8_lib

    self._preprocessor_cls = preprocessor_cls
    self._create_optimizer_fn = create_optimizer_fn
    if device_type not in (DEVICE_TYPE_CPU, DEVICE_TYPE_GPU, DEVICE_TYPE_TPU):
      raise ValueError(f'Unknown device_type: {device_type}')
    self._device_type = device_type
    self.use_avg_model_params = use_avg_model_params
    self.avg_model_params_decay = avg_model_params_decay
    self.init_from_checkpoint_fn = init_from_checkpoint_fn
    self._remat_policy = remat_lib.validate_remat_policy(remat_policy)
    self._kernel_policy = dispatch_lib.validate_kernel_policy(kernel_policy)
    self._matmul_precision = fp8_lib.validate_matmul_precision(
        matmul_precision)

  # ------------------------------------------------------------------ device

  @property
  def device_type(self) -> str:
    return self._device_type

  @property
  def is_device_tpu(self) -> bool:
    return self._device_type == DEVICE_TYPE_TPU

  @property
  def remat_policy(self) -> str:
    """Activation-remat policy name ('none' | 'conv_towers' | 'full')."""
    return self._remat_policy

  @property
  def kernel_policy(self) -> str:
    """Pallas kernel routing ('none' | 'pool' | 'pool_conv')."""
    return self._kernel_policy

  @property
  def matmul_precision(self) -> str:
    """Dense/Conv contraction precision ('bf16' | 'fp8')."""
    return self._matmul_precision

  def set_matmul_precision(self, precision: str) -> None:
    """Trainer-level override (``TrainerConfig.matmul_precision``);
    validates + gates on :func:`quantize.quantization.fp8_supported`.
    Must run before :meth:`create_module`/``init_variables`` — modules
    bake the precision in at construction."""
    from tensor2robot_tpu.quantize import fp8_training as fp8_lib

    self._matmul_precision = fp8_lib.require_fp8_support(precision)

  @property
  def compute_dtype(self):
    """Activation dtype for the network (params stay float32).

    On TPU this is bfloat16 — the MXU's native input dtype — matching the
    dtype the :class:`DtypePolicyPreprocessor` delivers at the device
    boundary (capability of ``models/tpu_model_wrapper.py:105-118``: specs
    re-typed to bfloat16 so compute runs in bf16 on TPU hardware).
    """
    import jax.numpy as jnp

    return jnp.bfloat16 if self.is_device_tpu else jnp.float32

  # ------------------------------------------------------------ preprocessor

  @property
  def default_preprocessor_cls(self) -> Type[AbstractPreprocessor]:
    return NoOpPreprocessor

  @property
  def preprocessor(self) -> AbstractPreprocessor:
    preprocessor_cls = self._preprocessor_cls or self.default_preprocessor_cls
    preprocessor = preprocessor_cls(
        model_feature_specification_fn=self.get_feature_specification,
        model_label_specification_fn=self.get_label_specification)
    if self.is_device_tpu:
      preprocessor = DtypePolicyPreprocessor(preprocessor)
    return preprocessor

  def param_sharding_rules(self, mesh) -> Sequence:
    """Tensor-parallel parameter layouts for this model (optional).

    Returns ``(path_regex, per-dim axis spec)`` pairs consumed by
    ``parallel.mesh.state_shardings_for``: the first matching rule shards
    that parameter over the named mesh axes (e.g.
    ``(r'fcgrasp/kernel$', (None, 'model'))`` column-shards a Dense
    kernel, Megatron-style). Unmatched parameters fall back to the fsdp
    rule. Axes missing from ``mesh`` are ignored, so rules are
    layout-portable.
    """
    del mesh
    return ()

  # ------------------------------------------------------------- core fns

  @abc.abstractmethod
  def init_variables(self, rng: jax.Array, features: SpecStruct,
                     mode: str = ModeKeys.TRAIN) -> Variables:
    """Initializes model variables for spec-shaped ``features``."""

  @abc.abstractmethod
  def inference_network_fn(
      self,
      variables: Variables,
      features: SpecStruct,
      labels: Optional[SpecStruct],
      mode: str,
      rng: Optional[jax.Array] = None,
  ) -> Tuple[Predictions, Variables]:
    """Pure forward pass; returns (predictions, updated variables).

    Updated variables matter for stateful collections (batch norm); for
    stateless models return ``variables`` unchanged.
    """

  def model_train_fn(
      self,
      features: SpecStruct,
      labels: Optional[SpecStruct],
      inference_outputs: Predictions,
      mode: str,
  ) -> Tuple[jax.Array, Scalars]:
    """Returns (scalar loss, scalar summaries). Must be jit-traceable."""
    raise NotImplementedError(
        f'{type(self).__name__} does not implement model_train_fn.')

  def model_eval_fn(
      self,
      features: SpecStruct,
      labels: Optional[SpecStruct],
      inference_outputs: Predictions,
  ) -> Scalars:
    """Per-batch eval metrics; the trainer averages them over eval batches."""
    loss, scalars = self.model_train_fn(features, labels, inference_outputs,
                                        ModeKeys.EVAL)
    metrics = dict(scalars)
    metrics['loss'] = loss
    return metrics

  def create_export_outputs_fn(
      self,
      features: SpecStruct,
      inference_outputs: Predictions,
  ) -> Predictions:
    """Outputs exposed by exported serving models; default: all predictions."""
    del features
    return inference_outputs

  # ------------------------------------------------------------- optimizer

  def create_optimizer(self):
    """Optax optimizer; EMA of params is handled by the trainer state."""
    if self._create_optimizer_fn is not None:
      return self._create_optimizer_fn()
    from tensor2robot_tpu.models import optimizers

    return optimizers.default_create_optimizer_fn()

  # ----------------------------------------------------------- conveniences

  def validated_features(self, features, mode: str,
                         labels=None) -> Tuple[SpecStruct, Any]:
    """validate_and_pack against the device-side data contract.

    Mirrors ``abstract_model.py:683-691``, except validation uses the
    preprocessor *out* specs: on TPU those are the model specs with the
    bfloat16 dtype policy applied and optionals stripped — exactly what
    arrives on device (the reference gets this via ``TPUT2RModelWrapper``
    re-typing the model specs, ``tpu_model_wrapper.py:105-118``).
    """
    preprocessor = self.preprocessor
    features = algebra.validate_and_pack(
        preprocessor.get_out_feature_specification(mode), features,
        ignore_batch=True)
    label_spec = preprocessor.get_out_label_specification(mode)
    if labels is not None and label_spec is not None:
      labels = algebra.validate_and_pack(label_spec, labels, ignore_batch=True)
    return features, labels

  def pack_features(self, state, context, timestep) -> SpecStruct:
    """Packs a policy's (state, context, timestep) into model features.

    Overridden by models that drive policies (critic/regression models);
    mirrors the packing contract used by ``policies/policies.py``.
    """
    raise NotImplementedError(
        f'{type(self).__name__} does not implement pack_features.')


class FlaxModel(AbstractT2RModel):
  """Convenience base for single-``nn.Module`` models.

  Subclasses implement :meth:`create_module` and the loss; ``init_variables``
  and ``inference_network_fn`` are derived. The module's ``__call__`` must
  accept ``(features, mode)`` keyword ``train`` and return a dict-like of
  predictions.
  """

  _RNG_COLLECTIONS = ('dropout', 'sample')

  def create_module(self):
    raise NotImplementedError(
        f'{type(self).__name__} must implement create_module().')

  @property
  def module(self):
    # Linen modules are cheap immutable pytrees; construct on demand.
    return self.create_module()

  def init_variables(self, rng, features, mode=ModeKeys.TRAIN):
    features, _ = self.validated_features(features, mode)
    rngs = self._make_rngs(rng, include_params=True)
    return self.module.init(rngs, features, train=False)

  def inference_network_fn(self, variables, features, labels, mode,
                           rng=None):
    del labels
    features, _ = self.validated_features(features, mode)
    train = mode == ModeKeys.TRAIN
    mutable = [k for k in variables if k != 'params'] if train else False
    kwargs = {}
    if rng is not None:
      kwargs['rngs'] = self._make_rngs(rng, include_params=False)
    if mutable:
      outputs, mutated = self.module.apply(
          variables, features, train=train, mutable=mutable, **kwargs)
      new_variables = merge_variables(variables['params'], mutated)
    else:
      outputs = self.module.apply(variables, features, train=train, **kwargs)
      new_variables = variables
    if not isinstance(outputs, SpecStruct):
      outputs = algebra.flatten_spec_structure(outputs)
    return outputs, new_variables

  def _make_rngs(self, rng, include_params: bool):
    names = list(self._RNG_COLLECTIONS)
    if include_params:
      names = ['params'] + names
    keys = jax.random.split(rng, len(names))
    return dict(zip(names, keys))
