"""Optimizer and learning-rate factories (optax).

Capability-equivalent of the reference's gin-exposed factories
(``/root/reference/models/optimizers.py:29-167``): Adam / SGD / Momentum
with constant or exponentially-decaying learning rates, plus
moving-average ("Polyak") parameter averaging.

The reference implements averaging with ``MovingAverageOptimizer`` and a
*swapping saver* so checkpoints contain averaged weights
(``models/optimizers.py:140-167``). In JAX the trainer simply keeps an
``ema_params`` tree in the train state (see ``train/train_state.py``) and
evaluates/exports it — no saver machinery needed, so this module only
provides the decay schedule helpers and the gradient transformations.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import optax

from tensor2robot_tpu.ops import fused_update as fused_lib

Schedule = Callable[[int], float]
LearningRate = Union[float, Schedule]


# ------------------------------------------------------------ lr schedules


def create_constant_learning_rate_fn(learning_rate: float = 1e-4) -> Schedule:
  """Mirrors ``create_constant_learning_rate`` (optimizers.py:102-110)."""
  return optax.constant_schedule(learning_rate)


def create_exp_decaying_learning_rate_fn(
    initial_learning_rate: float = 1e-4,
    decay_steps: int = 10000,
    decay_rate: float = 0.9,
    staircase: bool = True) -> Schedule:
  """Mirrors ``create_exp_decaying_learning_rate`` (optimizers.py:113-137)."""
  return optax.exponential_decay(
      init_value=initial_learning_rate,
      transition_steps=decay_steps,
      decay_rate=decay_rate,
      staircase=staircase)


# --------------------------------------------------------------- optimizers


def create_adam_optimizer(
    learning_rate: LearningRate = 1e-4,
    beta1: float = 0.9,
    beta2: float = 0.999,
    epsilon: float = 1e-8) -> optax.GradientTransformation:
  """Mirrors ``create_adam_optimizer`` (optimizers.py:29-50).

  The returned transformation is TAGGED for the fused-update kernel
  (``ops/fused_update.py``, ``TrainerConfig.fused_update``): a
  duck-typed ``(init, update, fused_spec)`` NamedTuple optax treats
  exactly like a plain ``GradientTransformation``. Wrapping it (e.g.
  ``with_gradient_clipping``) drops the tag and keeps the stock path.
  """
  return fused_lib.tag(
      optax.adam(learning_rate, b1=beta1, b2=beta2, eps=epsilon),
      fused_lib.FusedSpec(kind='adam', learning_rate=learning_rate,
                          b1=beta1, b2=beta2, eps=epsilon))


def create_gradient_descent_optimizer(
    learning_rate: LearningRate = 1e-4) -> optax.GradientTransformation:
  """Mirrors ``create_gradient_descent_optimizer`` (optimizers.py:53-70).

  Tagged for the fused-update kernel, like :func:`create_adam_optimizer`.
  """
  return fused_lib.tag(
      optax.sgd(learning_rate),
      fused_lib.FusedSpec(kind='sgd', learning_rate=learning_rate))


def create_momentum_optimizer(
    learning_rate: LearningRate = 1e-4,
    momentum: float = 0.9,
    use_nesterov: bool = False) -> optax.GradientTransformation:
  """Mirrors ``create_momentum_optimizer`` (optimizers.py:73-99)."""
  return optax.sgd(learning_rate, momentum=momentum, nesterov=use_nesterov)


def create_rms_prop_optimizer(
    learning_rate: LearningRate = 1e-4,
    decay: float = 0.9,
    momentum: float = 0.0,
    epsilon: float = 1e-10) -> optax.GradientTransformation:
  """RMSProp, used by the QT-Opt optimizer builder."""
  return optax.rmsprop(
      learning_rate, decay=decay, momentum=momentum, eps=epsilon)


def with_gradient_clipping(
    optimizer: optax.GradientTransformation,
    clip_norm: Optional[float] = None,
    clip_value: Optional[float] = None) -> optax.GradientTransformation:
  """Global-norm / value clipping composed in front of an optimizer."""
  transforms = []
  if clip_norm is not None:
    transforms.append(optax.clip_by_global_norm(clip_norm))
  if clip_value is not None:
    transforms.append(optax.clip(clip_value))
  transforms.append(optax.with_extra_args_support(optimizer))
  return optax.chain(*transforms)


def with_gradient_accumulation(
    optimizer: optax.GradientTransformation,
    accumulate_steps: int) -> optax.GradientTransformation:
  """Optimizer-level accumulation ACROSS dispatches (``optax.MultiSteps``).

  Complements ``TrainerConfig.grad_accum_microbatches``, which slices one
  host batch into microbatches INSIDE the jitted step (the memory lever —
  activations never exist at the full effective batch). This wrapper
  instead averages gradients over ``accumulate_steps`` consecutive host
  batches and applies one real update per window — useful when the
  effective batch should exceed what the host pipeline can deliver as a
  single batch. The trainer's ``state.step`` still advances every
  dispatch, so logging/checkpoint cadence is unchanged; only every
  ``accumulate_steps``-th dispatch moves the params.
  """
  if accumulate_steps <= 1:
    return optimizer
  return optax.MultiSteps(optimizer, every_k_schedule=accumulate_steps)


def default_create_optimizer_fn() -> optax.GradientTransformation:
  """The reference default: Adam at 1e-4 (abstract_model.py:168-178)."""
  return create_adam_optimizer()
