"""Warm start: partial parameter restore from a checkpoint or export.

Capability-equivalent of the reference's
``default_init_from_checkpoint_fn`` (``models/abstract_model.py:88-118``,
``tf.train.init_from_checkpoint`` with optional partial restore) and the
ResNet pretrained-checkpoint restore (``layers/resnet.py:152-218``: load
ImageNet backbone weights, excluding FiLM and the classifier head).

The returned function plugs into ``AbstractT2RModel(init_from_checkpoint_fn=...)``
and runs inside ``create_train_state`` after random init: matching
parameter paths (by '/'-joined key and shape) are overwritten from the
source checkpoint, everything else keeps its fresh initialization.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, Mapping, Optional, Sequence, Tuple

import numpy as np
from flax import traverse_util


def _flatten(tree) -> Dict[str, Any]:
  if not isinstance(tree, Mapping):
    return {'': tree}
  return traverse_util.flatten_dict(dict(tree), sep='/')


def load_checkpoint_variables(checkpoint_path: str):
  """Loads a raw variable tree from any framework artifact.

  Accepts: an export version dir (``state/``), a trainer step dir
  (``ckpt_<n>/`` with ``default/``), or a bare Orbax pytree dir.
  """
  import orbax.checkpoint as ocp

  path = os.path.abspath(checkpoint_path)
  for sub in ('state', 'default'):
    if os.path.isdir(os.path.join(path, sub)):
      path = os.path.join(path, sub)
      break
  return ocp.PyTreeCheckpointer().restore(path)


def _split_source(tree) -> Tuple[Mapping, Mapping]:
  """(params, model_state) from a TrainState payload or variables dict."""
  if isinstance(tree, Mapping) and 'params' in tree:
    if 'opt_state' in tree or 'step' in tree:  # TrainState payload
      return tree['params'], dict(tree.get('model_state') or {})
    state = {k: v for k, v in tree.items() if k != 'params'}
    return tree['params'], state
  return tree, {}


def default_init_from_checkpoint_fn(
    checkpoint_path: str,
    include: Optional[Sequence[str]] = None,
    exclude: Sequence[str] = (),
    source_prefix: str = '',
    target_prefix: str = '',
    restore_model_state: bool = True) -> Callable:
  """Builds an ``init_from_checkpoint_fn(params, model_state)`` hook.

  Args:
    checkpoint_path: source artifact (see :func:`load_checkpoint_variables`).
    include: if given, only parameter paths containing one of these
      substrings are restored.
    exclude: parameter paths containing any of these substrings are kept
      at their fresh initialization (e.g. a classifier head).
    source_prefix: path prefix to strip from source keys (restore a
      submodule trained standalone into a larger model).
    target_prefix: path prefix to prepend when matching target keys.
    restore_model_state: also restore matching non-trainable collections
      (batch_stats etc.).

  Returns:
    ``fn(params, model_state) -> (params, model_state)`` restoring every
    matching (path, shape) pair; raises if nothing matched.
  """

  def _selected(path: str) -> bool:
    if include is not None and not any(s in path for s in include):
      return False
    return not any(s in path for s in exclude)

  def _restore_tree(target, source) -> Tuple[Any, int]:
    flat_target = dict(_flatten(target))
    flat_source = _flatten(source)
    matched = 0
    for path, value in flat_target.items():
      src_key = source_prefix + path[len(target_prefix):] if path.startswith(
          target_prefix) else None
      if src_key is None or not _selected(path):
        continue
      if src_key not in flat_source:
        continue
      src_value = flat_source[src_key]
      if tuple(np.shape(src_value)) != tuple(np.shape(value)):
        logging.warning(
            'warm start: shape mismatch at %s: %s vs %s — skipped', path,
            np.shape(src_value), np.shape(value))
        continue
      flat_target[path] = np.asarray(src_value).astype(
          np.asarray(value).dtype)
      matched += 1
    return traverse_util.unflatten_dict(flat_target, sep='/'), matched

  def init_fn(params, model_state):
    tree = load_checkpoint_variables(checkpoint_path)
    src_params, src_state = _split_source(tree)
    params, matched = _restore_tree(params, src_params)
    total_state_matched = 0
    if restore_model_state and model_state and src_state:
      model_state = dict(model_state)
      for collection, target in model_state.items():
        if collection in src_state:
          model_state[collection], n = _restore_tree(
              target, src_state[collection])
          total_state_matched += n
    if matched == 0:
      raise ValueError(
          f'Warm start from {checkpoint_path!r} matched no parameters '
          f'(include={include}, exclude={list(exclude)}).')
    logging.info('warm start: restored %d params + %d state vars from %s',
                 matched, total_state_matched, checkpoint_path)
    return params, model_state

  return init_fn


def create_resnet_init_from_checkpoint_fn(
    checkpoint_path: str,
    restore_film: bool = False,
    restore_head: bool = False,
    **kwargs) -> Callable:
  """Pretrained-ResNet partial restore (``layers/resnet.py:152-218``).

  Restores the backbone (convs + norms) from a checkpoint of a
  :class:`...layers.resnet.FilmResNet`/``ResNet`` model, keeping the FiLM
  generator and the classifier head (``final_dense``) freshly initialized
  unless explicitly requested.
  """
  exclude = list(kwargs.pop('exclude', ()))
  if not restore_film:
    exclude.append('film')
  if not restore_head:
    exclude.append('final_dense')
  return default_init_from_checkpoint_fn(
      checkpoint_path, exclude=tuple(exclude), **kwargs)
