"""Critic (Q-function) model base (reference: models/critic_model.py:48-243).

Declares separate *state* and *action* specs; the network ``q_func`` maps
(state, action) → ``q_predicted``; training regresses Monte-Carlo returns
with log loss (QT-Opt style, rewards in [0, 1]).

CEM contract (critic_model.py:111-141): at PREDICT time the policy
evaluates one state against ``action_batch_size`` candidate actions. The
reference tiles the state inside the graph to amortize session round
trips; here the jitted predictor makes per-candidate batching free, and
:meth:`pack_features` performs the numpy-side tiling so exported-model
clients keep the same contract (predictors expand dims the same way,
``predictors/exported_savedmodel_predictor.py:89-101``).
"""

from __future__ import annotations

import abc
from typing import Optional

import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.models.base import FlaxModel
from tensor2robot_tpu.specs import SpecStruct, algebra


def log_loss(predictions: jnp.ndarray, targets: jnp.ndarray,
             epsilon: float = 1e-7) -> jnp.ndarray:
  """tf.losses.log_loss semantics: binary CE on probabilities."""
  predictions = jnp.clip(predictions.astype(jnp.float32), epsilon,
                         1.0 - epsilon)
  targets = targets.astype(jnp.float32)
  return -jnp.mean(targets * jnp.log(predictions) +
                   (1.0 - targets) * jnp.log(1.0 - predictions))


def mean_squared_error(predictions: jnp.ndarray,
                       targets: jnp.ndarray) -> jnp.ndarray:
  return jnp.mean(
      jnp.square(predictions.astype(jnp.float32) -
                 targets.astype(jnp.float32)))


class CriticModel(FlaxModel):
  """Q(s, a) critic with split state/action specs.

  ``loss_function(predictions, targets)`` defaults to MSE on Monte-Carlo
  returns (critic_model.py:53-66); QT-Opt swaps in :func:`log_loss`.
  """

  def __init__(self,
               action_batch_size: Optional[int] = None,
               loss_function=mean_squared_error,
               **kwargs):
    super().__init__(**kwargs)
    self._action_batch_size = action_batch_size
    self._loss_function = loss_function

  @property
  def action_batch_size(self) -> Optional[int]:
    return self._action_batch_size

  # ------------------------------------------------------------------ specs

  @abc.abstractmethod
  def get_state_specification(self) -> SpecStruct:
    ...

  @abc.abstractmethod
  def get_action_specification(self) -> SpecStruct:
    ...

  def get_feature_specification(self, mode: str) -> SpecStruct:
    """state/... + action/... merged (critic_model.py:87-109)."""
    del mode
    spec = SpecStruct()
    for key, value in algebra.flatten_spec_structure(
        self.get_state_specification()).items():
      spec[f'state/{key}'] = value
    for key, value in algebra.flatten_spec_structure(
        self.get_action_specification()).items():
      spec[f'action/{key}'] = value
    return spec

  def get_label_specification(self, mode: str) -> SpecStruct:
    del mode
    from tensor2robot_tpu.specs import TensorSpec

    spec = SpecStruct()
    spec['reward'] = TensorSpec(shape=(1,), dtype=np.float32, name='reward')
    return spec

  # ------------------------------------------------------------- loss/eval

  def q_predicted(self, inference_outputs) -> jnp.ndarray:
    return inference_outputs['q_predicted']

  def model_train_fn(self, features, labels, inference_outputs, mode):
    q = self.q_predicted(inference_outputs)
    reward = labels['reward'].astype(jnp.float32).reshape(q.shape)
    loss = self._loss_function(q, reward)
    return loss, {'q_mean': jnp.mean(q.astype(jnp.float32))}

  def model_eval_fn(self, features, labels, inference_outputs):
    q = self.q_predicted(inference_outputs).astype(jnp.float32)
    reward = labels['reward'].astype(jnp.float32).reshape(q.shape)
    return {
        'loss': self._loss_function(q, reward),
        'q_mean': jnp.mean(q),
        'td_abs_error': jnp.mean(jnp.abs(q - reward)),
    }

  def create_export_outputs_fn(self, features, inference_outputs):
    outputs = SpecStruct()
    outputs['q_predicted'] = self.q_predicted(inference_outputs)
    return outputs

  # ----------------------------------------------------------------- policy

  def pack_features(self, state, context, timestep) -> SpecStruct:
    """Packs one env state + a batch of candidate actions for CEM.

    ``context`` carries the candidate actions (numpy [num_samples, adim]);
    the state is tiled across the candidate batch.
    """
    del timestep
    packed = SpecStruct()
    state_spec = algebra.flatten_spec_structure(self.get_state_specification())
    action_spec = algebra.flatten_spec_structure(
        self.get_action_specification())
    actions = context
    if hasattr(actions, 'keys'):
      action_items = {k: np.asarray(v) for k, v in actions.items()}
    else:
      keys = list(action_spec.keys())
      if len(keys) != 1:
        raise ValueError('Single-array actions need a single action spec.')
      action_items = {keys[0]: np.asarray(actions)}
    num_samples = next(iter(action_items.values())).shape[0]
    state_items = (
        {k: np.asarray(v) for k, v in state.items()}
        if hasattr(state, 'keys') else
        {list(state_spec.keys())[0]: np.asarray(state)})
    for key in state_spec:
      value = state_items[key]
      tiled = np.broadcast_to(value, (num_samples,) + value.shape)
      packed[f'state/{key}'] = tiled
    for key in action_spec:
      packed[f'action/{key}'] = action_items[key]
    return packed
