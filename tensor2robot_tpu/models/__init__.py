"""Models: the functional T2R model protocol and base model families."""

from tensor2robot_tpu.models.base import (
    DEVICE_TYPE_CPU,
    DEVICE_TYPE_GPU,
    DEVICE_TYPE_TPU,
    AbstractT2RModel,
    FlaxModel,
    ModelInterface,
    merge_variables,
    split_variables,
)
from tensor2robot_tpu.models.classification_model import (
    ClassificationModel,
    sigmoid_log_loss,
)
from tensor2robot_tpu.models.critic_model import CriticModel, log_loss
from tensor2robot_tpu.models.regression_model import RegressionModel
from tensor2robot_tpu.models import optimizers
from tensor2robot_tpu.models.warm_start import (
    create_resnet_init_from_checkpoint_fn,
    default_init_from_checkpoint_fn,
    load_checkpoint_variables,
)
