"""Regression model base (reference: models/regression_model.py:50-172).

Predictions contract: ``inference_output``; loss: mean squared error.
"""

from __future__ import annotations

import jax.numpy as jnp

from tensor2robot_tpu.models.base import FlaxModel
from tensor2robot_tpu.specs import SpecStruct


class RegressionModel(FlaxModel):
  """Regression over spec-declared features → 'inference_output'."""

  def model_train_fn(self, features, labels, inference_outputs, mode):
    prediction = inference_outputs['inference_output'].astype(jnp.float32)
    target = self._regression_target(labels).astype(jnp.float32)
    loss = jnp.mean(jnp.square(prediction - target))
    return loss, {}

  def _regression_target(self, labels):
    if hasattr(labels, 'keys'):
      keys = list(labels.keys())
      if len(keys) != 1:
        raise ValueError(
            f'Override _regression_target for multi-label specs: {keys}')
      return labels[keys[0]]
    return labels

  def model_eval_fn(self, features, labels, inference_outputs):
    prediction = inference_outputs['inference_output'].astype(jnp.float32)
    target = self._regression_target(labels).astype(jnp.float32)
    return {
        'loss': jnp.mean(jnp.square(prediction - target)),
        'mean_absolute_error': jnp.mean(jnp.abs(prediction - target)),
    }

  def create_export_outputs_fn(self, features, inference_outputs):
    outputs = SpecStruct()
    outputs['inference_output'] = inference_outputs['inference_output']
    return outputs
