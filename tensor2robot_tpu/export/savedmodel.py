"""TF-Serving-consumable SavedModel export.

Closes the framework's one documented interop waiver: in addition to the
StableHLO serving artifact, an export version can now carry a genuine TF
SavedModel that ``tf.saved_model.load`` / TF-Serving's ``SavedModelBundle``
consume directly, with both reference receiver flavors
(``/root/reference/export_generators/default_export_generator.py:47-138``):

* ``serving_default`` — flat raw-tensor inputs keyed by spec path, batch
  dimension polymorphic, preprocessing INSIDE the graph. This is jax2tf of
  the SAME hermetic serving fn that ``exporters.serialize_serving_fn``
  serializes as StableHLO, lowered for cpu AND tpu, so the SavedModel and
  the jax_export artifact are the same program by construction.
* ``tf_example`` — per-dataset-key ``input_example_<key>`` string batches
  parsed with the spec-driven TF parser (``data/example_codec.py`` —
  FixedLen/VarLen schema, JPEG/PNG decode, bf16 cast-back), then the same
  converted chain.

plus ``assets.extra/tf_serving_warmup_requests`` — a TFRecord of
``tensorflow_serving.apis.PredictionLog`` protos
(``/root/reference/export_generators/abstract_export_generator.py:114-147``).
The serving proto package is not a dependency of this image, so the three
wrapper messages are encoded directly on the protobuf wire (field numbers
from the public ``tensorflow_serving/apis/{prediction_log,predict,
model}.proto``); the ``TensorProto`` payloads come from
``tf.make_tensor_proto``, so the tensor encoding is TF's own.

The SavedModel files are written INTO the export version directory (next to
``state/`` and ``serving_fn.jax_export``), because TF-Serving resolves
``<model_base_path>/<int_version>/saved_model.pb`` — pointing a serving
fleet at the trainer's ``export_root`` then works as-is, exactly like the
reference's estimator exports.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.specs import algebra
from tensor2robot_tpu.specs import assets as assets_lib
from tensor2robot_tpu.specs import numpy_gen

WARMUP_FILENAME = 'tf_serving_warmup_requests'
SAVED_MODEL_PB = 'saved_model.pb'
TF_EXAMPLE_SIGNATURE = 'tf_example'


def _tf():
  import tensorflow as tf  # local import: host-only dependency
  return tf


# --------------------------------------------------------------------------
# Protobuf wire encoding for the TF-Serving wrapper messages.
# --------------------------------------------------------------------------


def _varint(value: int) -> bytes:
  """Unsigned LEB128 — the protobuf varint."""
  out = bytearray()
  while True:
    bits = value & 0x7F
    value >>= 7
    if value:
      out.append(bits | 0x80)
    else:
      out.append(bits)
      return bytes(out)


def _delimited(field_number: int, payload: bytes) -> bytes:
  """A length-delimited (wire type 2) field."""
  return _varint((field_number << 3) | 2) + _varint(len(payload)) + payload


def encode_model_spec(model_name: str, signature_name: str) -> bytes:
  """``tensorflow_serving.apis.ModelSpec``: name=1, signature_name=3."""
  return (_delimited(1, model_name.encode('utf-8')) +
          _delimited(3, signature_name.encode('utf-8')))


def encode_predict_request(
    model_name: str,
    inputs: Mapping[str, np.ndarray],
    signature_name: str = 'serving_default') -> bytes:
  """``tensorflow_serving.apis.PredictRequest``: model_spec=1, inputs=2.

  ``inputs`` is a ``map<string, TensorProto>``; each map entry is a nested
  message with key=1, value=2.
  """
  tf = _tf()
  body = _delimited(1, encode_model_spec(model_name, signature_name))
  for key in sorted(inputs):
    tensor_proto = tf.make_tensor_proto(inputs[key]).SerializeToString()
    entry = _delimited(1, key.encode('utf-8')) + _delimited(2, tensor_proto)
    body += _delimited(2, entry)
  return body


def encode_prediction_log(predict_request: bytes) -> bytes:
  """``PredictionLog(predict_log=PredictLog(request=...))``.

  ``PredictionLog.predict_log`` is field 6; ``PredictLog.request`` field 1.
  """
  return _delimited(6, _delimited(1, predict_request))


def write_tf_serving_warmup_requests(
    export_dir: str,
    model,
    model_name: Optional[str] = None,
    batch_sizes: Sequence[int] = (1,),
    signature_name: str = 'serving_default') -> str:
  """``assets.extra/tf_serving_warmup_requests`` for Servo.

  One zero-filled ``PredictionLog`` per batch size, keyed by the required
  PREDICT in-spec — the reference's ``create_warmup_requests_numpy``
  (``abstract_export_generator.py:114-147``) on the wire format above.
  """
  tf = _tf()
  in_spec = _serving_input_spec(model)
  assets_dir = os.path.join(export_dir, assets_lib.EXTRA_ASSETS_DIRECTORY)
  os.makedirs(assets_dir, exist_ok=True)
  path = os.path.join(assets_dir, WARMUP_FILENAME)
  name = model_name or type(model).__name__
  with tf.io.TFRecordWriter(path) as writer:
    for batch_size in batch_sizes:
      features = numpy_gen.make_constant_numpy(
          in_spec, constant_value=0, batch_size=batch_size)
      request = encode_predict_request(
          name, {k: np.asarray(v) for k, v in features.items()},
          signature_name)
      writer.write(encode_prediction_log(request))
  return path


# --------------------------------------------------------------------------
# SavedModel writer.
# --------------------------------------------------------------------------


def _serving_input_spec(model) -> 'algebra.SpecStruct':
  """The flat REQUIRED raw-feature spec the serving fn takes.

  Identical to the spec ``exporters.serialize_serving_fn`` traces over, so
  both artifacts share one calling convention.
  """
  return algebra.filter_required_flat_tensor_spec(
      model.preprocessor.get_in_feature_specification(ModeKeys.PREDICT))


def _tf_input_signature(in_spec) -> Dict[str, object]:
  tf = _tf()
  return {
      key: tf.TensorSpec([None] + [int(d) for d in spec.shape],
                         tf.dtypes.as_dtype(spec.dtype.name), name=key)
      for key, spec in in_spec.items()
  }


def build_serving_module(
    model,
    serving_variables,
    platforms: Optional[Sequence[str]] = None) -> Tuple[object, Dict]:
  """A ``tf.Module`` holding the variables + its serving signatures.

  Returns ``(module, signatures)`` ready for ``tf.saved_model.save``. The
  variables live as ``tf.Variable``s inside the module, so TF-Serving's
  standard variable restore applies; the compute is one ``XlaCallModule``
  produced by jax2tf native serialization of the hermetic serving fn.
  """
  import jax
  from jax.experimental import jax2tf

  from tensor2robot_tpu.export import exporters

  tf = _tf()
  in_spec = _serving_input_spec(model)
  for key, spec in in_spec.items():
    if spec.is_sequence or any(d is None for d in spec.shape):
      raise ValueError(
          f'SavedModel serving requires static per-example shapes; spec '
          f'{key!r} ({spec}) has a dynamic/sequence dimension. Serve this '
          f'model through the StableHLO artifact instead.')

  serving_fn = exporters.build_serving_fn(model)
  variables = exporters.to_plain_tree(serving_variables)
  poly_features = {
      key: '(b, ' + ', '.join('_' for _ in spec.shape) + ')'
      if spec.shape else '(b,)'
      for key, spec in in_spec.items()
  }
  if platforms is None:
    # jax.default_backend() says 'gpu' where jax2tf's platform set says
    # 'cuda'/'rocm'; canonicalize and keep only names jax2tf accepts.
    backend = {'gpu': 'cuda'}.get(jax.default_backend(),
                                  jax.default_backend())
    platforms = sorted(
        ({'cpu', backend} | {'tpu'}) & {'cpu', 'cuda', 'rocm', 'tpu'})
  converted = jax2tf.convert(
      serving_fn,
      polymorphic_shapes=[None, poly_features],
      with_gradient=False,
      native_serialization_platforms=tuple(platforms))

  class ServingModule(tf.Module):

    def __init__(self):
      super().__init__(name='t2r_serving')
      self.model_variables = tf.nest.map_structure(tf.Variable, variables)

    @tf.function(autograph=False)
    def serve(self, features):
      return converted(self.model_variables, features)

  module = ServingModule()
  signatures = {
      'serving_default':
          module.serve.get_concrete_function(_tf_input_signature(in_spec)),
  }

  example_signature = _build_tf_example_signature(model, module, in_spec)
  if example_signature is not None:
    signatures[TF_EXAMPLE_SIGNATURE] = example_signature
  return module, signatures


def _build_tf_example_signature(model, module, in_spec):
  """The serialized-``tf.Example`` receiver, parse inside the graph.

  Mirrors ``create_serving_input_receiver_tf_example_fn``
  (``default_export_generator.py:90-138``): one string input per
  ``dataset_key``, named ``input_example_<key or 'tensor'>``, run through
  the spec-driven TF parser (schema + image decode + bf16 cast), then the
  same converted serving chain. Returns None (with a log line) for spec
  features the batched parser cannot produce with static shapes.
  """
  tf = _tf()
  try:
    from tensor2robot_tpu.data import example_codec
  except Exception as e:  # TF host lib unavailable
    logging.info('tf_example signature skipped: %r', e)
    return None

  dataset_keys = sorted({spec.dataset_key or '' for spec in in_spec.values()})
  receiver_names = {
      dataset_key: 'input_example_' + (dataset_key.rstrip('/') or 'tensor')
      for dataset_key in dataset_keys
  }
  parse_fn = example_codec.make_parse_fn(in_spec)

  # tf.function args must be valid identifiers; map back to dataset keys.
  arg_names = sorted(receiver_names.values())

  @tf.function(autograph=False)
  def serve_examples(**kwargs):
    streams = {
        dataset_key: kwargs[name]
        for dataset_key, name in receiver_names.items()
    }
    parsed = parse_fn(streams)
    features = {key: parsed[key] for key in in_spec.keys()}
    return module.serve(features)

  specs = {
      name: tf.TensorSpec([None], tf.string, name=name) for name in arg_names
  }
  try:
    return serve_examples.get_concrete_function(**specs)
  except Exception as e:
    logging.warning(
        'tf_example signature could not be traced for %s (the raw-tensor '
        'serving_default signature is unaffected): %r',
        type(model).__name__, e)
    return None


def write_saved_model(
    model,
    serving_variables,
    export_dir: str,
    model_name: Optional[str] = None,
    warmup_batch_sizes: Sequence[int] = (1,),
    platforms: Optional[Sequence[str]] = None) -> str:
  """Writes a TF-Serving-loadable SavedModel into ``export_dir``.

  ``export_dir`` is the (numeric) export version directory; after this call
  it contains ``saved_model.pb`` + ``variables/`` +
  ``assets.extra/tf_serving_warmup_requests`` next to the framework's own
  artifacts, so both a jax robot host and a TF-Serving fleet can consume
  the same version.
  """
  tf = _tf()
  module, signatures = build_serving_module(
      model, serving_variables, platforms=platforms)
  tf.saved_model.save(module, export_dir, signatures=signatures)
  write_tf_serving_warmup_requests(
      export_dir, model, model_name=model_name,
      batch_sizes=warmup_batch_sizes)
  return export_dir
