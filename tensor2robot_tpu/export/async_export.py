"""Async export callbacks: export-on-checkpoint + TD3 lagged exports.

Capability-equivalent of ``hooks/async_export_hook_builder.py:91-137``
(export a serving artifact after every checkpoint save, off the critical
path) and ``hooks/td3.py:39-135`` / ``hooks/checkpoint_hooks.py:96-206``
(TD3's target network realized as a *lagged*, one-version-behind export
directory on the filesystem).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from tensor2robot_tpu.export import exporters as exporters_lib
from tensor2robot_tpu.export.exporters import ModelExporter
from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.train import resilience
from tensor2robot_tpu.train.trainer import TrainerCallback


class AsyncExportCallback(TrainerCallback):
  """Exports the serving model after each checkpoint save.

  The export runs on a worker thread so the train loop never blocks on
  serialization (the AsyncCheckpointSaverHook capability).

  Preemption-aware (the distributed-resilience contract): the callback
  persists ``last_exported_step`` into the export root after every
  version, so a restarted run SKIPS checkpoints its pre-preemption
  incarnation already exported (``export/skipped_already_exported``);
  when a graceful shutdown has been requested, the forced preemption
  checkpoint's export runs SYNCHRONOUSLY — the process is about to exit
  resumable (42), and a daemon worker thread would be killed mid-write,
  leaving a torn version for the commit marker to catch. In
  multi-process runs only the primary process exports.
  """

  def __init__(self,
               export_dir: Optional[str] = None,
               export_name: str = 'latest_exporter_numpy',
               keep: int = 5,
               asynchronous: bool = True,
               serialize_serving: bool = True):
    self._export_dir = export_dir
    self._export_name = export_name
    # serialize_serving=False skips the StableHLO artifact: versions are
    # cheap orbax state dumps and predictors use the model-class
    # fallback — the right trade for high-cadence collect-loop exports
    # where the actor fleet shares the training code anyway.
    self._exporter = ModelExporter(keep=keep,
                                   serialize_serving=serialize_serving)
    self._asynchronous = asynchronous
    self._pending: Optional[threading.Thread] = None

  def _resolve_export_dir(self, trainer) -> str:
    if self._export_dir:
      return self._export_dir
    return os.path.join(trainer.config.model_dir, 'export', self._export_name)

  def _shutdown_requested(self, trainer) -> bool:
    shutdown = (getattr(trainer, '_shutdown', None)
                or resilience.active_shutdown())
    return shutdown is not None and shutdown.requested

  def after_checkpoint(self, trainer, step: int) -> None:
    import jax

    if not getattr(trainer, 'is_primary_process', True):
      return  # one export version per job, not one per host
    export_dir = self._resolve_export_dir(trainer)
    last = exporters_lib.read_export_state(export_dir).get(
        'last_exported_step')
    if last is not None and int(step) <= int(last):
      metrics_lib.counter('export/skipped_already_exported').inc()
      logging.info(
          'Skipping export of checkpoint step %d: step %d was already '
          'exported before the restart.', step, last)
      return
    model = trainer.model
    # Snapshot to host NOW: the jitted train step donates the state buffers,
    # so device arrays captured by the worker thread would be deleted.
    state = jax.device_get(trainer.state)

    def work(state=state):
      self._exporter.export(model, state, export_dir)
      exporters_lib.write_export_state(export_dir,
                                       last_exported_step=int(step))

    if not self._asynchronous or self._shutdown_requested(trainer):
      # Shutdown path: this is the forced preemption checkpoint — finish
      # the export before the process exits 42 rather than racing a
      # daemon thread against interpreter teardown.
      work()
      return
    self.join()  # one in-flight export at a time; drop-behind is fine
    self._pending = threading.Thread(target=work, daemon=True)
    self._pending.start()

  def end(self, trainer) -> None:
    self.join()

  def join(self) -> None:
    if self._pending is not None and self._pending.is_alive():
      self._pending.join()
    self._pending = None


class TD3ExportCallback(TrainerCallback):
  """Maintains current + lagged export dirs (TD3 target network on disk).

  ``lagged_export_dir`` always holds the *previous* exported version —
  the contract of ``LaggedCheckpointListener``
  (``hooks/checkpoint_hooks.py:96-206``).
  """

  def __init__(self,
               export_dir: str,
               lagged_export_dir: str,
               keep: int = 5):
    self._export_dir = export_dir
    self._lagged_export_dir = lagged_export_dir
    self._exporter = ModelExporter(keep=keep)
    self._lagged_exporter = ModelExporter(keep=keep)
    self._previous_state = None

  def after_checkpoint(self, trainer, step: int) -> None:
    import jax

    state = jax.device_get(trainer.state)
    self._exporter.export(trainer.model, state, self._export_dir)
    # Lagged dir gets the previous version (or the current one on the first
    # save, mirroring the listener's bootstrap).
    lagged_state = self._previous_state or state
    self._lagged_exporter.export(
        trainer.model, lagged_state, self._lagged_export_dir)
    self._previous_state = state
