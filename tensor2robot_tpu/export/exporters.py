"""Model export: versioned serving artifacts + best/latest exporters.

Capability-equivalent of the reference's export stack
(``export_generators/``, ``utils/train_eval.py:206-361``,
``hooks/checkpoint_hooks.py``): the trainer writes timestamp-versioned
export directories that a robot-side predictor polls and hot-reloads.

An export directory ``<export_root>/<version>/`` contains:

* ``state/`` — Orbax checkpoint of the serving variables (EMA params when
  enabled — the reference's swapping-saver capability).
* ``assets.extra/t2r_assets.pbtxt`` (+ JSON twin) — feature/label specs and
  global_step (``hooks/async_export_hook_builder.py:66-88``).
* ``export_meta.json`` — model class path + ctor kwargs, so predictors can
  rebuild the serving fn without the training script (the role the
  SavedModel GraphDef plays in the reference).

Versions are numeric timestamps exactly like SavedModel export dirs, and
old versions are GC'd to N newest (``hooks/checkpoint_hooks.py:36-53``).
"""

from __future__ import annotations

import importlib
import json
import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.specs import assets as assets_lib
from tensor2robot_tpu.specs.spec_struct import SpecStruct

EXPORT_META_FILENAME = 'export_meta.json'
STATE_DIRNAME = 'state'


def _numeric_version_dirs(export_root: str) -> List[str]:
  """All numeric-named child dirs, oldest → newest (predictor contract)."""
  try:
    entries = os.listdir(export_root)
  except FileNotFoundError:
    return []
  versions = [e for e in entries if e.isdigit() and
              os.path.isdir(os.path.join(export_root, e))]
  return sorted(versions, key=int)


def valid_export_dirs(export_root: str) -> List[str]:
  """Versions whose contents are complete (assets + state + meta).

  The validation-before-load contract of
  ``exported_savedmodel_predictor.py:258-274``.
  """
  valid = []
  for version in _numeric_version_dirs(export_root):
    path = os.path.join(export_root, version)
    if not os.path.exists(os.path.join(
        path, assets_lib.EXTRA_ASSETS_DIRECTORY,
        assets_lib.T2R_ASSETS_FILENAME)):
      continue
    if not os.path.exists(os.path.join(path, EXPORT_META_FILENAME)):
      continue
    if not os.path.isdir(os.path.join(path, STATE_DIRNAME)):
      continue
    valid.append(path)
  return valid


def gc_export_versions(export_root: str, keep: int = 5) -> None:
  """Keeps the N newest versions (``_DirectoryVersionGC``, checkpoint_hooks)."""
  versions = _numeric_version_dirs(export_root)
  for version in versions[:-keep] if keep else versions:
    shutil.rmtree(os.path.join(export_root, version), ignore_errors=True)


class ModelExporter:
  """Writes one export version from a trainer state."""

  def __init__(self, keep: int = 5):
    self._keep = keep
    self._checkpointer = ocp.StandardCheckpointer()

  def export(self, model, state, export_root: str,
             version: Optional[int] = None) -> str:
    """Writes ``<export_root>/<version>`` and returns its path."""
    os.makedirs(export_root, exist_ok=True)
    if version is None:
      version = int(time.time() * 1e6)  # microseconds: unique + ordered
    final_dir = os.path.join(export_root, str(version))
    tmp_dir = os.path.join(export_root, f'.tmp_{version}')
    if os.path.exists(tmp_dir):
      shutil.rmtree(tmp_dir)
    os.makedirs(tmp_dir)

    # 1. Serving variables (EMA when enabled).
    serving_variables = jax.device_get(dict(state.eval_variables))
    self._checkpointer.save(
        os.path.abspath(os.path.join(tmp_dir, STATE_DIRNAME)),
        serving_variables)
    self._checkpointer.wait_until_finished()

    # 2. Specs + global step.
    feature_spec = model.get_feature_specification_for_packing(
        ModeKeys.PREDICT)
    label_spec = model.get_label_specification_for_packing(ModeKeys.PREDICT)
    assets_lib.write_assets_to_export_dir(
        tmp_dir, feature_spec, label_spec, global_step=int(state.step))

    # 3. Reconstruction metadata.
    meta = {
        'model_class': f'{type(model).__module__}.{type(model).__qualname__}',
        'global_step': int(state.step),
    }
    with open(os.path.join(tmp_dir, EXPORT_META_FILENAME), 'w') as f:
      json.dump(meta, f, indent=2)

    # Atomic publish: predictors never observe partial exports.
    os.replace(tmp_dir, final_dir)
    if self._keep:
      gc_export_versions(export_root, keep=self._keep)
    return final_dir


def load_model_from_export_dir(export_dir: str,
                               model_kwargs: Optional[Dict[str, Any]] = None):
  """Rebuilds the model object recorded in export_meta.json."""
  with open(os.path.join(export_dir, EXPORT_META_FILENAME)) as f:
    meta = json.load(f)
  module_name, _, class_name = meta['model_class'].rpartition('.')
  module = importlib.import_module(module_name)
  model_cls = getattr(module, class_name)
  return model_cls(**(model_kwargs or {}))


def load_state_from_export_dir(export_dir: str):
  """Loads the serving variables written by :class:`ModelExporter`."""
  checkpointer = ocp.StandardCheckpointer()
  return checkpointer.restore(
      os.path.abspath(os.path.join(export_dir, STATE_DIRNAME)))


# ------------------------------------------------------------ eval exporters


def create_valid_result_smaller(metric_key: str = 'loss'):
  """Best = smaller metric (train_eval.py:206-246)."""

  def compare(best: Optional[Dict], current: Dict) -> bool:
    if best is None or metric_key not in best:
      return True
    return current[metric_key] < best[metric_key]

  return compare


def create_valid_result_larger(metric_key: str):
  """Best = larger metric (train_eval.py:249-292)."""

  def compare(best: Optional[Dict], current: Dict) -> bool:
    if best is None or metric_key not in best:
      return True
    return current[metric_key] > best[metric_key]

  return compare


class LatestExporter:
  """Exports on every eval, keeping N newest (LatestExporter semantics)."""

  def __init__(self, name: str = 'latest_exporter_numpy', keep: int = 5):
    self.name = name
    self._exporter = ModelExporter(keep=keep)

  def export(self, trainer, metrics: Dict[str, float]) -> Optional[str]:
    del metrics
    export_root = os.path.join(trainer.config.model_dir, 'export', self.name)
    return self._exporter.export(trainer.model, trainer.state, export_root)


class BestExporter:
  """Exports only when the metric improves (BestExporter semantics)."""

  def __init__(self,
               name: str = 'best_exporter_numpy',
               compare_fn: Optional[Callable] = None,
               keep: int = 5):
    self.name = name
    self._compare_fn = compare_fn or create_valid_result_smaller('loss')
    self._exporter = ModelExporter(keep=keep)
    self._best_metrics: Optional[Dict[str, float]] = None

  def export(self, trainer, metrics: Dict[str, float]) -> Optional[str]:
    if not metrics:
      return None
    if not self._compare_fn(self._best_metrics, metrics):
      return None
    self._best_metrics = dict(metrics)
    export_root = os.path.join(trainer.config.model_dir, 'export', self.name)
    return self._exporter.export(trainer.model, trainer.state, export_root)


def create_default_exporters(best_metric_key: str = 'loss',
                             compare_larger: bool = False,
                             keep: int = 5):
  """Best + latest exporter pair (train_eval.py:295-361)."""

  def create_exporters_fn(model):
    del model
    compare = (create_valid_result_larger(best_metric_key) if compare_larger
               else create_valid_result_smaller(best_metric_key))
    return [
        BestExporter(compare_fn=compare, keep=keep),
        LatestExporter(keep=keep),
    ]

  return create_exporters_fn
