"""Model export: versioned serving artifacts + best/latest exporters.

Capability-equivalent of the reference's export stack
(``export_generators/``, ``utils/train_eval.py:206-361``,
``hooks/checkpoint_hooks.py``): the trainer writes timestamp-versioned
export directories that a robot-side predictor polls and hot-reloads.

An export directory ``<export_root>/<version>/`` contains:

* ``state/`` — Orbax checkpoint of the serving variables (EMA params when
  enabled — the reference's swapping-saver capability).
* ``assets.extra/t2r_assets.pbtxt`` (+ JSON twin) — feature/label specs and
  global_step (``hooks/async_export_hook_builder.py:66-88``).
* ``serving_fn.jax_export`` — the SELF-CONTAINED serving function
  (preprocessing + forward + export outputs) serialized with
  ``jax.export`` (StableHLO). This is the SavedModel-GraphDef equivalent:
  a robot host deserializes and calls it with only jax installed — no
  model class, no training script
  (``export_generators/default_export_generator.py:47-87``: preprocessing
  inside the serving graph).
* ``assets.extra/warmup_requests.npz`` + ``warmup_requests.tfexamples`` —
  spec-shaped warmup inputs, as numpy and as serialized tf.Example bytes
  (``abstract_export_generator.py:114-147``).
* ``export_meta.json`` — model class path + global step; the model-class
  fallback path for predictors when the StableHLO artifact is absent.

Versions are numeric timestamps exactly like SavedModel export dirs, and
old versions are GC'd to N newest (``hooks/checkpoint_hooks.py:36-53``).
"""

from __future__ import annotations

import importlib
import json
import logging
import os
import shutil
import struct
import time
from typing import Any, Callable, Dict, List, Mapping, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.observability import metrics as metrics_lib
from tensor2robot_tpu.specs import algebra
from tensor2robot_tpu.specs import assets as assets_lib
from tensor2robot_tpu.specs import numpy_gen
from tensor2robot_tpu.specs.spec_struct import SpecStruct

EXPORT_META_FILENAME = 'export_meta.json'
STATE_DIRNAME = 'state'
SERVING_FN_FILENAME = 'serving_fn.jax_export'
WARMUP_NPZ_FILENAME = 'warmup_requests.npz'
WARMUP_EXAMPLES_FILENAME = 'warmup_requests.tfexamples'
# Written LAST into every export version: a version dir without it is a
# torn/partial export (a copy or move that died mid-flight) and hot-
# reloading predictors must skip it. The local os.replace publish is
# already atomic — the marker is the cross-filesystem/rsync-era guard
# mirroring the checkpoint commit protocol (train/checkpoints.py).
EXPORT_COMMIT_FILENAME = 'export_commit.json'
# Persisted exporter position (export root, not version): survives a
# preemption so the restarted trainer/evaluator skips already-exported
# checkpoints instead of re-exporting them.
EXPORT_STATE_FILENAME = 'export_state.json'


def to_plain_tree(obj):
  """Mappings → plain dicts (stable pytree structure for jax.export)."""
  if isinstance(obj, Mapping):
    return {k: to_plain_tree(v) for k, v in obj.items()}
  return obj


def build_serving_fn(model):
  """The hermetic PREDICT chain: preprocess → network → export outputs.

  Takes/returns PLAIN dicts so the serialized calling convention doesn't
  depend on framework pytree types.
  """
  preprocessor = model.preprocessor

  def serving_fn(variables, features):
    features_p, _ = preprocessor.preprocess(
        SpecStruct(features), None, ModeKeys.PREDICT, None)
    outputs, _ = model.inference_network_fn(
        dict(variables), features_p, None, ModeKeys.PREDICT)
    return dict(model.create_export_outputs_fn(features_p, outputs))

  return serving_fn


def serialize_serving_fn(model, serving_variables,
                         batch_size: Optional[int] = None) -> bytes:
  """Serializes the serving fn with ``jax.export`` (StableHLO).

  ``batch_size=None`` exports a symbolic batch dimension (the reference's
  unknown-batch serving signature, ``README.md:180-184``); pass an int to
  pin it if a model's preprocessing can't trace symbolically.
  """
  from jax import export as jax_export

  serving_fn = build_serving_fn(model)
  in_spec = algebra.filter_required_flat_tensor_spec(
      model.preprocessor.get_in_feature_specification(ModeKeys.PREDICT))
  if batch_size is None:
    (batch,) = jax_export.symbolic_shape('b')
  else:
    batch = int(batch_size)
  feature_args = {
      key: jax.ShapeDtypeStruct((batch,) + tuple(spec.shape), spec.dtype)
      for key, spec in in_spec.items()
  }
  var_args = jax.tree_util.tree_map(
      lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
      to_plain_tree(serving_variables))
  # cpu + tpu: robots serve on CPU hosts, servers on TPU.
  platforms = sorted({'cpu', jax.default_backend()} | {'tpu'})
  try:
    exported = jax_export.export(
        jax.jit(serving_fn), platforms=platforms)(var_args, feature_args)
  except Exception as e:
    # Some lowering rules are platform-gated; fall back to the current one.
    logging.warning(
        'Multi-platform serving export (platforms=%s) failed; retrying for '
        'the current backend only — the artifact will NOT be portable '
        'across platforms. Original error: %r', platforms, e)
    exported = jax_export.export(jax.jit(serving_fn))(var_args, feature_args)
  return exported.serialize()


def serving_program_fingerprint(exported) -> str:
  """Canonical digest of an ``Exported``'s PROGRAM (not its bytes).

  ``Exported.serialize()`` embeds MLIR ``loc(...)`` debug locations —
  call-site file:line that drifts between otherwise identical exports —
  so hashing the raw artifact makes every export version look like a new
  program and defeats serving-executable cache reuse on weights-only
  hot swaps. Hashing the location-stripped module text is stable:
  equal fingerprints <=> same compute program, only weights differ.
  """
  import hashlib
  import re

  text = exported.mlir_module()
  text = re.sub(r'(?m)^#loc.*$', '', text)  # "#locN = loc(...)" defs
  text = re.sub(r'loc\([^)]*\)', '', text)  # trailing "loc(#locN)" refs
  return hashlib.sha256(text.encode()).hexdigest()


def write_warmup_requests(export_dir: str,
                          model,
                          batch_size: int = 1,
                          num_requests: int = 2) -> None:
  """Spec-shaped warmup inputs (abstract_export_generator.py:114-147).

  Written both as an ``.npz`` of numpy feature dicts (suffix ``/<i>``)
  and as length-prefixed serialized tf.Example records, so robot hosts
  can warm up either receiver path.
  """
  in_spec = algebra.filter_required_flat_tensor_spec(
      model.preprocessor.get_in_feature_specification(ModeKeys.PREDICT))
  assets_dir = os.path.join(export_dir, assets_lib.EXTRA_ASSETS_DIRECTORY)
  os.makedirs(assets_dir, exist_ok=True)
  arrays = {}
  example_records: List[bytes] = []
  for i in range(num_requests):
    features = numpy_gen.make_random_numpy(
        in_spec, batch_size=batch_size, seed=i)
    for key, value in features.items():
      arrays[f'{key}/{i}'] = value
    try:
      from tensor2robot_tpu.data import example_codec

      for b in range(batch_size):
        single = SpecStruct(
            {k: np.asarray(v)[b] for k, v in features.items()})
        example_records.append(
            example_codec.encode_example(in_spec, single))
    except Exception:
      pass  # TF host lib unavailable: npz warmup only
  np.savez(os.path.join(assets_dir, WARMUP_NPZ_FILENAME), **arrays)
  if example_records:
    with open(os.path.join(assets_dir, WARMUP_EXAMPLES_FILENAME), 'wb') as f:
      for record in example_records:
        f.write(struct.pack('<Q', len(record)))
        f.write(record)


def read_warmup_examples(export_dir: str) -> List[bytes]:
  """Reads the length-prefixed serialized warmup examples."""
  path = os.path.join(export_dir, assets_lib.EXTRA_ASSETS_DIRECTORY,
                      WARMUP_EXAMPLES_FILENAME)
  records = []
  with open(path, 'rb') as f:
    while True:
      header = f.read(8)
      if len(header) < 8:
        break
      (length,) = struct.unpack('<Q', header)
      records.append(f.read(length))
  return records


def _numeric_version_dirs(export_root: str) -> List[str]:
  """All numeric-named child dirs, oldest → newest (predictor contract)."""
  try:
    entries = os.listdir(export_root)
  except FileNotFoundError:
    return []
  versions = [e for e in entries if e.isdigit() and
              os.path.isdir(os.path.join(export_root, e))]
  return sorted(versions, key=int)


def valid_export_dirs(export_root: str) -> List[str]:
  """Versions whose contents are complete (assets + state + meta).

  The validation-before-load contract of
  ``exported_savedmodel_predictor.py:258-274``.
  """
  valid = []
  for version in _numeric_version_dirs(export_root):
    path = os.path.join(export_root, version)
    if not os.path.exists(os.path.join(
        path, assets_lib.EXTRA_ASSETS_DIRECTORY,
        assets_lib.T2R_ASSETS_FILENAME)):
      continue
    if not os.path.exists(os.path.join(path, EXPORT_META_FILENAME)):
      continue
    if not os.path.isdir(os.path.join(path, STATE_DIRNAME)):
      continue
    valid.append(path)
  return valid


# Torn export versions already counted/warned about, so a hot-reload
# poller (the serving plane polls every reload interval) logs and counts
# each torn dir ONCE instead of once per poll.
_reported_torn_exports: set = set()


def committed_export_dirs(export_root: str,
                          dirs: Optional[List[str]] = None) -> List[str]:
  """Filters export version dirs to COMMITTED ones (legacy-aware).

  Once any version carries :data:`EXPORT_COMMIT_FILENAME`, versions
  without it are torn/partial (a copy that died mid-flight) and are
  skipped with an ``export/uncommitted_skipped`` count; marker-less
  legacy roots (exports written before the marker existed) stay fully
  visible so old artifacts keep serving.
  """
  if dirs is None:
    dirs = valid_export_dirs(export_root)
  marked = [d for d in dirs
            if os.path.exists(os.path.join(d, EXPORT_COMMIT_FILENAME))]
  if not marked:
    return dirs
  torn = [d for d in dirs if d not in marked
          and d not in _reported_torn_exports]
  if torn:
    _reported_torn_exports.update(torn)
    metrics_lib.counter('export/uncommitted_skipped').inc(len(torn))
    logging.warning(
        'Ignoring %d export version(s) under %r without a commit marker '
        '(torn/partial export): %s', len(torn), export_root,
        [os.path.basename(d) for d in torn])
  return marked


def read_export_state(export_root: str) -> Dict[str, Any]:
  """The persisted exporter position, or {} (missing/corrupt file)."""
  try:
    with open(os.path.join(export_root, EXPORT_STATE_FILENAME)) as f:
      return dict(json.load(f))
  except (OSError, ValueError, TypeError):
    return {}


def write_export_state(export_root: str, **updates) -> None:
  """Atomically merges ``updates`` into the persisted exporter state."""
  os.makedirs(export_root, exist_ok=True)
  state = read_export_state(export_root)
  state.update(updates)
  path = os.path.join(export_root, EXPORT_STATE_FILENAME)
  tmp = f'{path}.tmp{os.getpid()}'
  with open(tmp, 'w') as f:
    json.dump(state, f, indent=2)
  os.replace(tmp, path)


def gc_export_versions(export_root: str, keep: int = 5) -> None:
  """Keeps the N newest versions (``_DirectoryVersionGC``, checkpoint_hooks)."""
  versions = _numeric_version_dirs(export_root)
  for version in versions[:-keep] if keep else versions:
    shutil.rmtree(os.path.join(export_root, version), ignore_errors=True)


class ModelExporter:
  """Writes one export version from a trainer state.

  ``serialize_serving`` controls whether the self-contained StableHLO
  serving fn + warmup requests are written (slower export; on by default).
  ``serving_batch_size=None`` exports a symbolic batch dim.
  """

  def __init__(self,
               keep: int = 5,
               serialize_serving: bool = True,
               serving_batch_size: Optional[int] = None,
               warmup_batch_size: int = 1,
               saved_model: bool = False):
    self._keep = keep
    self._serialize_serving = serialize_serving
    self._serving_batch_size = serving_batch_size
    self._warmup_batch_size = warmup_batch_size
    self._saved_model = saved_model
    self._checkpointer = ocp.StandardCheckpointer()

  def export(self, model, state, export_root: str,
             version: Optional[int] = None) -> str:
    """Writes ``<export_root>/<version>`` and returns its path."""
    os.makedirs(export_root, exist_ok=True)
    if version is None:
      version = int(time.time() * 1e6)  # microseconds: unique + ordered
    final_dir = os.path.join(export_root, str(version))
    tmp_dir = os.path.join(export_root, f'.tmp_{version}')
    if os.path.exists(tmp_dir):
      shutil.rmtree(tmp_dir)
    os.makedirs(tmp_dir)

    # 1. Serving variables (EMA when enabled).
    serving_variables = jax.device_get(dict(state.eval_variables))
    self._checkpointer.save(
        os.path.abspath(os.path.join(tmp_dir, STATE_DIRNAME)),
        serving_variables)
    self._checkpointer.wait_until_finished()

    # 2. Specs + global step.
    feature_spec = model.get_feature_specification_for_packing(
        ModeKeys.PREDICT)
    label_spec = model.get_label_specification_for_packing(ModeKeys.PREDICT)
    assets_lib.write_assets_to_export_dir(
        tmp_dir, feature_spec, label_spec, global_step=int(state.step))

    # 3. Self-contained serving fn + warmup requests.
    serving_fn_ok = False
    if self._serialize_serving:
      try:
        data = serialize_serving_fn(
            model, serving_variables, batch_size=self._serving_batch_size)
        with open(os.path.join(tmp_dir, SERVING_FN_FILENAME), 'wb') as f:
          f.write(data)
        serving_fn_ok = True
      except Exception as e:
        # The model-class-import fallback still works, but the export is
        # no longer the self-contained artifact the serving contract
        # advertises (README §Serving contract) — say so loudly.
        logging.warning(
            'Self-contained StableHLO serving export FAILED for %s; the '
            'export degrades to the model-class fallback (predictors must '
            'import %s.%s). Recorded as self_contained_serving_fn=false in '
            'export_meta.json. Error: %r',
            type(model).__name__, type(model).__module__,
            type(model).__qualname__, e)
      try:
        write_warmup_requests(
            tmp_dir, model, batch_size=self._warmup_batch_size)
      except Exception as e:
        # Warmup is best-effort; never abort the export for it.
        logging.warning('Warmup request generation failed: %r', e)

    # 3.5. TF-Serving-consumable SavedModel (saved_model.pb + variables/ +
    # Servo warmup) in the same version dir. Best-effort like warmup: the
    # StableHLO artifact remains the primary serving contract.
    saved_model_ok = False
    if self._saved_model:
      try:
        from tensor2robot_tpu.export import savedmodel as savedmodel_lib

        savedmodel_lib.write_saved_model(
            model, serving_variables, tmp_dir,
            warmup_batch_sizes=(self._warmup_batch_size,))
        saved_model_ok = True
      except Exception as e:
        logging.warning(
            'TF SavedModel export failed for %s; the version still carries '
            'the StableHLO serving artifact. Error: %r',
            type(model).__name__, e)
        # A failure AFTER tf.saved_model.save would otherwise publish a
        # loadable saved_model.pb (consumers key on file presence) that
        # the meta records as failed — remove the partial artifact.
        for name in ('saved_model.pb', 'fingerprint.pb', 'variables',
                     'assets'):
          partial = os.path.join(tmp_dir, name)
          if os.path.isdir(partial):
            shutil.rmtree(partial, ignore_errors=True)
          elif os.path.exists(partial):
            os.remove(partial)

    # 4. Reconstruction metadata.
    meta = {
        'model_class': f'{type(model).__module__}.{type(model).__qualname__}',
        'global_step': int(state.step),
        'self_contained_serving_fn': serving_fn_ok,
        'tf_saved_model': saved_model_ok,
    }
    with open(os.path.join(tmp_dir, EXPORT_META_FILENAME), 'w') as f:
      json.dump(meta, f, indent=2)

    # 5. Commit marker, written LAST: a version dir missing it is a torn
    # export and hot-reloading predictors skip it (the local rename
    # below is atomic; the marker survives non-atomic replication).
    with open(os.path.join(tmp_dir, EXPORT_COMMIT_FILENAME), 'w') as f:
      json.dump({'global_step': int(state.step), 'time': time.time()}, f)
      f.flush()
      os.fsync(f.fileno())

    # Atomic publish: predictors never observe partial exports.
    os.replace(tmp_dir, final_dir)
    if self._keep:
      gc_export_versions(export_root, keep=self._keep)
    return final_dir


def load_model_from_export_dir(export_dir: str,
                               model_kwargs: Optional[Dict[str, Any]] = None):
  """Rebuilds the model object recorded in export_meta.json."""
  with open(os.path.join(export_dir, EXPORT_META_FILENAME)) as f:
    meta = json.load(f)
  module_name, _, class_name = meta['model_class'].rpartition('.')
  module = importlib.import_module(module_name)
  model_cls = getattr(module, class_name)
  return model_cls(**(model_kwargs or {}))


def load_state_from_export_dir(export_dir: str):
  """Loads the serving variables written by :class:`ModelExporter`."""
  checkpointer = ocp.StandardCheckpointer()
  return checkpointer.restore(
      os.path.abspath(os.path.join(export_dir, STATE_DIRNAME)))


def load_serving_fn_from_export_dir(export_dir: str):
  """Deserializes the self-contained serving fn, or None if absent.

  Returns ``fn(variables, features) -> outputs`` over plain dicts; needs
  only jax on the host — the SavedModel-load equivalent
  (``predictors/exported_savedmodel_predictor.py:179``).
  """
  path = os.path.join(export_dir, SERVING_FN_FILENAME)
  if not os.path.exists(path):
    return None
  from jax import export as jax_export

  with open(path, 'rb') as f:
    exported = jax_export.deserialize(f.read())
  return exported.call


# ------------------------------------------------------------ eval exporters


def create_valid_result_smaller(metric_key: str = 'loss'):
  """Best = smaller metric (train_eval.py:206-246)."""

  def compare(best: Optional[Dict], current: Dict) -> bool:
    if best is None or metric_key not in best:
      return True
    return current[metric_key] < best[metric_key]

  return compare


def create_valid_result_larger(metric_key: str):
  """Best = larger metric (train_eval.py:249-292)."""

  def compare(best: Optional[Dict], current: Dict) -> bool:
    if best is None or metric_key not in best:
      return True
    return current[metric_key] > best[metric_key]

  return compare


def _should_skip_export(trainer, export_root: str) -> bool:
  """Preemption-aware gating for step-keyed exporters (LatestExporter,
  AsyncExportCallback's root — BestExporter dedups via its persisted
  best metrics instead).

  Skips (a) non-primary processes of a multi-process job — one export
  version per job, not one per host — and (b) checkpoints at or below
  the persisted ``last_exported_step``, so a restarted run never
  re-exports what it already published (counted as
  ``export/skipped_already_exported``).
  """
  if not getattr(trainer, 'is_primary_process', True):
    return True
  last = read_export_state(export_root).get('last_exported_step')
  step = int(trainer.state.step) if trainer.state is not None else 0
  if last is not None and step <= int(last):
    metrics_lib.counter('export/skipped_already_exported').inc()
    logging.info(
        'Skipping export of step %d under %r: step %d was already '
        'exported before the restart.', step, export_root, last)
    return True
  return False


class LatestExporter:
  """Exports on every eval, keeping N newest (LatestExporter semantics).

  Preemption-aware: persists ``last_exported_step`` into the export
  root after every version, and skips checkpoints a pre-restart
  incarnation already exported.
  """

  def __init__(self, name: str = 'latest_exporter_numpy', keep: int = 5,
               saved_model: bool = False):
    self.name = name
    self._exporter = ModelExporter(keep=keep, saved_model=saved_model)

  def export(self, trainer, metrics: Dict[str, float]) -> Optional[str]:
    del metrics
    export_root = os.path.join(trainer.config.model_dir, 'export', self.name)
    if _should_skip_export(trainer, export_root):
      return None
    path = self._exporter.export(trainer.model, trainer.state, export_root)
    write_export_state(export_root,
                       last_exported_step=int(trainer.state.step))
    return path


class BestExporter:
  """Exports only when the metric improves (BestExporter semantics).

  The best-so-far metrics are PERSISTED beside the versions, so a
  restarted run keeps raising the bar instead of re-exporting the first
  post-restart eval as a fresh "best".
  """

  def __init__(self,
               name: str = 'best_exporter_numpy',
               compare_fn: Optional[Callable] = None,
               keep: int = 5,
               saved_model: bool = False):
    self.name = name
    self._compare_fn = compare_fn or create_valid_result_smaller('loss')
    self._exporter = ModelExporter(keep=keep, saved_model=saved_model)
    self._best_metrics: Optional[Dict[str, float]] = None

  def export(self, trainer, metrics: Dict[str, float]) -> Optional[str]:
    if not metrics:
      return None
    if not getattr(trainer, 'is_primary_process', True):
      return None
    export_root = os.path.join(trainer.config.model_dir, 'export', self.name)
    if self._best_metrics is None:
      # Restart dedup: the pre-preemption best is the bar to beat — a
      # restarted run re-evaluating an already-exported checkpoint gets
      # the same metrics, which are not an improvement, so nothing is
      # re-exported. (No step gate here: a better metric at the same
      # step IS a legitimate new best within a run.)
      persisted = read_export_state(export_root).get('best_metrics')
      if isinstance(persisted, dict):
        self._best_metrics = {k: float(v) for k, v in persisted.items()}
    if not self._compare_fn(self._best_metrics, metrics):
      metrics_lib.counter('export/skipped_not_improved').inc()
      return None
    self._best_metrics = dict(metrics)
    path = self._exporter.export(trainer.model, trainer.state, export_root)
    write_export_state(export_root,
                       last_exported_step=int(trainer.state.step),
                       best_metrics=self._best_metrics)
    return path


def create_default_exporters(best_metric_key: str = 'loss',
                             compare_larger: bool = False,
                             keep: int = 5,
                             saved_model: bool = False):
  """Best + latest exporter pair (train_eval.py:295-361).

  ``saved_model=True`` additionally writes the TF-Serving-consumable
  SavedModel into every export version (export/savedmodel.py).
  """

  def create_exporters_fn(model):
    del model
    compare = (create_valid_result_larger(best_metric_key) if compare_larger
               else create_valid_result_smaller(best_metric_key))
    return [
        BestExporter(compare_fn=compare, keep=keep, saved_model=saved_model),
        LatestExporter(keep=keep, saved_model=saved_model),
    ]

  return create_exporters_fn
