"""Export: versioned serving artifacts, exporters, async export callbacks."""

from tensor2robot_tpu.export.async_export import (
    AsyncExportCallback,
    TD3ExportCallback,
)
from tensor2robot_tpu.export.exporters import (
    BestExporter,
    LatestExporter,
    ModelExporter,
    create_default_exporters,
    create_valid_result_larger,
    create_valid_result_smaller,
    gc_export_versions,
    load_model_from_export_dir,
    load_state_from_export_dir,
    valid_export_dirs,
)
