"""Parallelism: device mesh, sharding rules, multi-host init."""

from tensor2robot_tpu.parallel.mesh import (
    BATCH_AXES,
    DATA_AXIS,
    DEFAULT_AXES,
    FSDP_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    MeshSpec,
    batch_sharding,
    create_local_mesh,
    create_mesh,
    describe_topology,
    global_batch_size,
    initialize_multihost,
    mesh_spans_processes,
    replicated,
    shard_batch,
    single_device_mesh,
    state_shardings_for,
)
from tensor2robot_tpu.parallel.sequence_parallel import (
    make_ring_attention,
    make_ulysses_attention,
    reference_attention,
    ring_attention,
    ulysses_attention,
)
