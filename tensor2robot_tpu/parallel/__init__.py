"""Parallelism: device mesh, sharding rules, multi-host init."""

from tensor2robot_tpu.parallel.mesh import (
    BATCH_AXES,
    DATA_AXIS,
    DEFAULT_AXES,
    FSDP_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    MeshSpec,
    batch_sharding,
    create_mesh,
    global_batch_size,
    initialize_multihost,
    replicated,
    shard_batch,
    single_device_mesh,
    state_shardings_for,
)
from tensor2robot_tpu.parallel.sequence_parallel import (
    make_ring_attention,
    make_ulysses_attention,
    reference_attention,
    ring_attention,
    ulysses_attention,
)
