"""Device mesh + sharding rules: the framework's parallelism backbone.

The reference's only sharded-compute mode is TPU data parallelism via
``TPUEstimator`` + ``CrossShardOptimizer`` (``models/tpu_model_wrapper.py:
50-54,227``), with gRPC parameter servers for async CPU/GPU training. The
TPU-native replacement is a single SPMD program over a
``jax.sharding.Mesh``: batches sharded on the data axes, parameters
replicated (pure DP) or sharded (FSDP/TP), gradients all-reduced by XLA
collectives over ICI — no NCCL/MPI and no wrapper optimizers.

Axes (all optional; size-1 axes cost nothing under GSPMD):

* ``data`` — batch sharding (the reference's cross-shard DP).
* ``fsdp`` — batch *and* parameter sharding (ZeRO-3 style).
* ``model`` — tensor parallelism over hidden dims.
* ``seq`` — sequence/context parallelism (ring attention fan-out).

``jax.distributed.initialize`` handles multi-host process groups; each host
runs this same module and the mesh spans all devices.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = 'data'
FSDP_AXIS = 'fsdp'
MODEL_AXIS = 'model'
SEQ_AXIS = 'seq'

DEFAULT_AXES = (DATA_AXIS, FSDP_AXIS, MODEL_AXIS, SEQ_AXIS)

# The axes a batch's leading dim is sharded over.
BATCH_AXES = (DATA_AXIS, FSDP_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
  """Declarative mesh layout: axis name → size (-1 = all remaining devices)."""

  data: int = -1
  fsdp: int = 1
  model: int = 1
  seq: int = 1

  def axis_sizes(self, num_devices: int) -> Dict[str, int]:
    sizes = {
        DATA_AXIS: self.data,
        FSDP_AXIS: self.fsdp,
        MODEL_AXIS: self.model,
        SEQ_AXIS: self.seq,
    }
    fixed = 1
    wildcard = None
    for name, size in sizes.items():
      if size == -1:
        if wildcard is not None:
          raise ValueError('Only one mesh axis may be -1.')
        wildcard = name
      else:
        fixed *= size
    if wildcard is not None:
      if num_devices % fixed:
        raise ValueError(
            f'{num_devices} devices not divisible by fixed axes {sizes}')
      sizes[wildcard] = num_devices // fixed
    total = int(np.prod(list(sizes.values())))
    if total != num_devices:
      raise ValueError(
          f'Mesh axes {sizes} use {total} devices, have {num_devices}.')
    return sizes

  def create(self, devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    sizes = self.axis_sizes(len(devices))
    names = tuple(sizes.keys())
    shape = tuple(sizes.values())
    # ICI topology note: jax.devices() order keeps physically-adjacent chips
    # adjacent, so the innermost (fastest-varying) axes land on neighbor
    # links. Put `model`/`seq` innermost: their collectives are per-step
    # latency-bound, while `data` all-reduces overlap with compute.
    mesh_devices = np.asarray(devices).reshape(shape)
    return Mesh(mesh_devices, names)


def create_mesh(devices: Optional[Sequence] = None,
                data: int = -1,
                fsdp: int = 1,
                model: int = 1,
                seq: int = 1) -> Mesh:
  return MeshSpec(data=data, fsdp=fsdp, model=model, seq=seq).create(devices)


def create_local_mesh(data: int = -1,
                      fsdp: int = 1,
                      model: int = 1,
                      seq: int = 1) -> Mesh:
  """A mesh over THIS process's devices only (per-host SPMD mode).

  In a multi-process job each host then runs its own replica group:
  batches are host-global, no cross-host collectives are compiled into
  the step, and cross-host agreement (preemption, checkpoint commits,
  liveness) is owned by the control plane
  (``train/distributed_resilience.py``) rather than the data plane. This
  is the layout the 2-process resilience drills run, and the fallback
  for backends whose XLA build cannot execute multi-process programs.
  """
  return MeshSpec(data=data, fsdp=fsdp, model=model,
                  seq=seq).create(jax.local_devices())


def single_device_mesh() -> Mesh:
  return Mesh(np.asarray(jax.devices()[:1]).reshape((1, 1, 1, 1)),
              DEFAULT_AXES)


def mesh_spans_processes(mesh: Mesh) -> bool:
  """Whether ``mesh`` contains devices from more than one process.

  The data-plane test multi-host code paths must branch on — NOT
  ``jax.process_count()``: a per-host mesh in a multi-process job feeds
  host-global batches exactly like a single-process run, while a global
  mesh needs per-process shard assembly.
  """
  return len({d.process_index for d in mesh.devices.flat}) > 1


# ----------------------------------------------- elastic checkpoint views

SAVE_AXIS = 'save'


def participant_devices(participants: Optional[Sequence[int]] = None):
  """All devices belonging to ``participants`` (process indices).

  ``None`` means every process in the job. Order follows
  ``jax.devices()`` (identical on every host), so the save mesh built
  from it is consistent job-wide without communication.
  """
  devices = jax.devices()
  if participants is None:
    return list(devices)
  wanted = set(int(p) for p in participants)
  return [d for d in devices if d.process_index in wanted]


def global_save_mesh(participants: Optional[Sequence[int]] = None) -> Mesh:
  """A 1-D mesh over the participants' devices, used ONLY for payload io.

  Checkpoint writes never run an XLA program over this mesh — it exists
  so each leaf can be expressed as one global ``jax.Array`` whose shards
  are distributed across hosts, letting Orbax's multiprocess writers
  stripe the payload (every host writes its own shards). That makes it
  safe on backends whose XLA build cannot execute cross-process programs
  (array construction and serialization are pure metadata + local
  device_puts).
  """
  devices = participant_devices(participants)
  return Mesh(np.asarray(devices).reshape((len(devices),)), (SAVE_AXIS,))


def save_sharding_for(mesh: Mesh, leaf) -> NamedSharding:
  """IO sharding for one state leaf on the 1-D save mesh.

  The largest dim divisible by the device count is striped over
  ``save``; leaves with no divisible dim (scalars, rng keys, small
  biases) stay replicated — Orbax then writes exactly one copy (the
  replica-0 shard), so small leaves cost one writer, big leaves cost
  every writer 1/N of the bytes.
  """
  n = mesh.devices.size
  shape = tuple(getattr(leaf, 'shape', ()) or ())
  if n <= 1 or not shape:
    return NamedSharding(mesh, P())
  candidates = [(dim, i) for i, dim in enumerate(shape) if dim % n == 0]
  if not candidates:
    return NamedSharding(mesh, P())
  _, idx = max(candidates)
  spec = [None] * len(shape)
  spec[idx] = SAVE_AXIS
  return NamedSharding(mesh, P(*spec))


def build_global_save_view(tree: Any, mesh: Mesh) -> Any:
  """Re-expresses a host-local state tree as global arrays on ``mesh``.

  Used by the sharded checkpoint path when training runs per-host
  replica groups (``create_local_mesh``): every host holds the full
  (replicated, lockstep) state, and this view assigns each host the
  slices it is responsible for WRITING. Each process materializes only
  its addressable shards (``jax.make_array_from_callback`` device_puts
  local slices; no collectives), so a 2-host job writes each striped
  leaf half-and-half. States already sharded over a process-spanning
  mesh (true FSDP on a pod) skip this view and save their arrays
  directly — re-slicing them would force an all-gather.

  Leaves must be HOST data (numpy, post ``device_get``); non-array
  leaves (python ints) pass through for Orbax's aggregate writer.
  """

  def to_global(x):
    arr = np.asarray(x)
    sharding = save_sharding_for(mesh, arr)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx, a=arr: a[idx])

  def view(x):
    if isinstance(x, (int, float)) or x is None:
      return x
    return to_global(x)

  return jax.tree_util.tree_map(view, tree)


def describe_topology(mesh: Optional[Mesh] = None, **extra) -> Dict[str, Any]:
  """The run topology a checkpoint is only valid within.

  Recorded in every checkpoint commit marker
  (``train/checkpoints.py``) and validated on restore: resuming a 2-host
  run on 1 host (or onto a different mesh shape / microbatch config)
  silently misinterprets the saved state, so the mismatch must fail
  loudly instead. ``extra`` carries trainer-level knobs
  (``grad_accum_microbatches``, ``steps_per_dispatch``).
  """
  out: Dict[str, Any] = {
      'process_count': jax.process_count(),
  }
  if mesh is not None:
    out['mesh_shape'] = {name: int(mesh.shape[name])
                         for name in mesh.axis_names}
    out['device_count'] = int(mesh.devices.size)
    out['mesh_spans_processes'] = mesh_spans_processes(mesh)
  out.update({k: v for k, v in extra.items() if v is not None})
  return out


# ---------------------------------------------------------------- shardings


def batch_sharding(mesh: Mesh) -> NamedSharding:
  """Leading dim sharded over (data, fsdp); rest replicated."""
  axes = tuple(a for a in BATCH_AXES if a in mesh.axis_names)
  return NamedSharding(mesh, P(axes if axes else None))

def stacked_batch_sharding(mesh: Mesh) -> NamedSharding:
  """For ``[K, batch, ...]`` step-groups: dim 1 is the batch dim.

  ``Trainer(steps_per_dispatch=K)`` stacks K batches per dispatch; the
  scan axis (dim 0) stays unsharded, the per-step batch dim shards over
  the usual batch axes.
  """
  axes = tuple(a for a in BATCH_AXES if a in mesh.axis_names)
  return NamedSharding(mesh, P(None, axes if axes else None))


def replicated(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P())


def microbatch_split(tree: Any, num_microbatches: int) -> Any:
  """Reshapes batch leaves ``[B, ...]`` → ``[M, B/M, ...]`` for grad accum.

  The microbatch axis (dim 0 after the split) stays UNSHARDED — it is the
  ``lax.scan`` axis of the gradient-accumulation step — while the
  per-microbatch batch dim keeps the normal batch-axis sharding (GSPMD
  propagates it through the reshape; each microbatch still spans the
  data×fsdp axes). This mirrors ``stacked_batch_sharding``'s convention
  for ``steps_per_dispatch`` groups, so ``K`` (scan over host batches)
  and ``M`` (scan over microbatch slices) nest as one program:
  ``[K, B, ...]`` → per-step ``[B, ...]`` → ``[M, B/M, ...]``.

  Runs inside jit (pure reshape, no data movement on the host). ``B``
  must divide by ``num_microbatches``; the error names the offending
  leaf. For sharded batches, ``B / M`` should remain divisible by the
  product of the batch mesh axes or GSPMD inserts a reshard.
  """
  if num_microbatches <= 1:
    return tree

  def split(path, x):
    shape = tuple(x.shape)
    if not shape or shape[0] % num_microbatches:
      raise ValueError(
          f'grad_accum_microbatches={num_microbatches} must divide the '
          f'batch dim; got shape {shape} at '
          f'{jax.tree_util.keystr(path)}.')
    return x.reshape(
        (num_microbatches, shape[0] // num_microbatches) + shape[1:])

  return jax.tree_util.tree_map_with_path(split, tree)


def batch_shardings_for(mesh: Mesh, tree: Any) -> Any:
  """A matching tree of batch shardings for an arbitrary batch pytree."""
  sharding = batch_sharding(mesh)
  return jax.tree_util.tree_map(lambda _: sharding, tree)


def global_batch_size(per_device_batch: int, mesh: Mesh) -> int:
  n = 1
  for axis in BATCH_AXES:
    if axis in mesh.axis_names:
      n *= mesh.shape[axis]
  return per_device_batch * n


def shard_batch(batch: Any, mesh: Mesh, formats: Any = None,
                stacked: bool = False) -> Any:
  """Places a batch onto the mesh, sharded on the batch axes.

  Single-process: ``batch`` is the global batch; a plain sharded
  ``device_put``. Multi-host (``jax.process_count() > 1``): each process
  passes its PROCESS-LOCAL shard (fed by per-host file sharding in the
  input pipeline) and the global array is assembled with
  ``jax.make_array_from_process_local_data`` — the reference gets this
  per-host feeding from TPUEstimator's per-host ``input_fn``
  (``utils/tfdata.py:43-66``); feeding a host-global batch on every host
  would silently duplicate data across hosts.

  ``formats``: optional pytree of ``jax.experimental.layout.Format``
  matching ``batch`` — place each leaf in the COMPILED EXECUTABLE's
  preferred layout (see ``Trainer`` auto input layouts) so XLA never
  re-lays the batch out inside the step. Single-process only; the
  multi-host assembly path ignores it.

  ``stacked``: the batch is a ``[K, batch, ...]`` step-group
  (``steps_per_dispatch``); shard dim 1 instead of dim 0.
  """
  sharding = stacked_batch_sharding(mesh) if stacked else batch_sharding(mesh)
  # Branch on the MESH spanning processes, not on process_count: a
  # per-host mesh in a multi-process job (the distributed-resilience
  # drills, per-host replica groups) feeds host-global batches exactly
  # like a single-process run.
  if mesh_spans_processes(mesh):
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)), batch)
  if formats is not None:
    return jax.tree_util.tree_map(
        lambda x, f: jax.device_put(x, f), batch, formats)
  return jax.tree_util.tree_map(
      lambda x: jax.device_put(x, sharding), batch)


# ------------------------------------------------- parameter sharding rules


def fsdp_param_sharding(mesh: Mesh, param) -> NamedSharding:
  """Shards the largest divisible dim over `fsdp`; replicates otherwise.

  The simple ZeRO-3 rule: parameters are split along their biggest axis so
  each device stores 1/fsdp of every weight; XLA inserts the all-gathers.
  """
  fsdp_size = mesh.shape.get(FSDP_AXIS, 1)
  shape = getattr(param, 'shape', ())
  if fsdp_size <= 1 or not shape:
    return replicated(mesh)
  # Largest dim divisible by the fsdp axis size.
  candidates = [(dim, i) for i, dim in enumerate(shape)
                if dim % fsdp_size == 0]
  if not candidates:
    return replicated(mesh)
  _, idx = max(candidates)
  spec = [None] * len(shape)
  spec[idx] = FSDP_AXIS
  return NamedSharding(mesh, P(*spec))


REPLICATED = 'replicated'


def rule_param_sharding(mesh: Mesh, path: str, param,
                        rules) -> Optional[NamedSharding]:
  """First matching (regex, spec) rule → NamedSharding, else None.

  ``rules``: sequence of ``(pattern, spec)`` where ``pattern`` is matched
  (``re.search``) against the parameter's slash-joined tree path and
  ``spec`` is a tuple of axis names / None per dimension — e.g.
  ``(r'fcgrasp/kernel', (None, 'model'))`` column-shards a Dense kernel
  over the tensor-parallel axis (Megatron-style). Axes absent from the
  mesh or not dividing the dim are dropped (replicated on that dim), so
  one rule set serves every mesh layout. A rule naming the same mesh axis
  on two dims is rejected up front (JAX's own error at jit time is
  opaque). ``spec`` may also be the sentinel string ``'replicated'`` to
  pin the param fully replicated — distinct from an all-None tuple, which
  (like a fully degenerated rule) falls through to the default fsdp rule.
  """
  import re

  shape = getattr(param, 'shape', ())
  for pattern, spec in rules:
    if re.search(pattern, path) is None:
      continue
    if isinstance(spec, str):
      if spec != REPLICATED:
        raise ValueError(
            f'Unknown sharding-rule sentinel {spec!r} for pattern '
            f'{pattern!r}; the only string spec is {REPLICATED!r}.')
      return replicated(mesh)
    if len(spec) != len(shape):
      continue
    named = [a for a in spec if a is not None]
    if len(named) != len(set(named)):
      raise ValueError(
          f'Sharding rule {pattern!r} names mesh axis more than once in '
          f'spec {spec!r} (param {path!r}); each mesh axis may shard at '
          'most one dimension.')
    fixed = []
    for dim, axis in zip(shape, spec):
      if (axis is None or axis not in mesh.axis_names or
          mesh.shape.get(axis, 1) <= 1 or dim % mesh.shape[axis]):
        fixed.append(None)
      else:
        fixed.append(axis)
    if not any(fixed):
      # Every requested axis degenerated (absent / size 1 / indivisible):
      # fall through to the default rule instead of pinning the param
      # replicated — otherwise declaring TP rules would silently disable
      # fsdp sharding on non-TP meshes.
      return None
    return NamedSharding(mesh, P(*fixed))
  return None


def state_shardings_for(mesh: Mesh, state: Any, rules=()) -> Any:
  """Sharding tree for a TrainState.

  Per-leaf: a matching model rule (tensor-parallel layouts, see
  :func:`rule_param_sharding`) wins; otherwise the ZeRO-3 fsdp rule;
  otherwise replicated. Models declare rules via
  ``AbstractT2RModel.param_sharding_rules``.
  """
  fsdp_size = mesh.shape.get(FSDP_AXIS, 1)
  rep = replicated(mesh)

  def leaf_sharding(path, leaf):
    if rules:
      name = '/'.join(str(getattr(k, 'key', getattr(k, 'name', k)))
                      for k in path)
      ruled = rule_param_sharding(mesh, name, leaf, rules)
      if ruled is not None:
        return ruled
    if fsdp_size > 1:
      return fsdp_param_sharding(mesh, leaf)
    return rep

  return jax.tree_util.tree_map_with_path(leaf_sharding, state)


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
  """Multi-host process-group init (the reference's TF_CONFIG equivalent)."""
  if jax.process_count() > 1:
    return  # already initialized
  if coordinator_address is None:
    return  # single-host run
  jax.distributed.initialize(
      coordinator_address=coordinator_address,
      num_processes=num_processes,
      process_id=process_id)
