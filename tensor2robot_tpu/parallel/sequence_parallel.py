"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no long-context machinery (SURVEY §5: episodes ≤ ~100
steps), but this framework treats sequence parallelism as first-class so
the same trainer scales to long-horizon/context workloads:

* :func:`ring_attention` — blockwise attention over the ``seq`` mesh axis:
  each device holds a query block; key/value blocks rotate around the ring
  with ``jax.lax.ppermute`` while a numerically-stable online softmax
  (flash-attention style m/l/o accumulators) folds in one block per hop.
  Communication rides ICI neighbor links; memory per device is O(T/n).
* :func:`ulysses_attention` — all-to-all alternative: resharding
  [seq-sharded, all heads] → [full seq, head-sharded] with
  ``jax.lax.all_to_all``, full local attention per head group, and the
  inverse all-to-all. Cheaper at moderate T when heads ≥ mesh axis size.

Both are pure functions designed for use INSIDE ``shard_map`` over a mesh
``seq`` axis; :func:`make_ring_attention` / :func:`make_ulysses_attention`
build the sharded callable for a given mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tensor2robot_tpu.parallel.mesh import SEQ_AXIS


def _block_attention(q, k, v, mask, m_prev, l_prev, o_prev):
  """One online-softmax accumulation step (flash-attention recurrence).

  q: [B, Tq, H, D]; k/v: [B, Tk, H, D]; mask: [Tq, Tk] or None.
  Accumulators: m [B, H, Tq], l [B, H, Tq], o [B, Tq, H, D].
  """
  scale = 1.0 / np.sqrt(q.shape[-1])
  # [B, H, Tq, Tk]
  logits = jnp.einsum('bqhd,bkhd->bhqk', q, k) * scale
  if mask is not None:
    logits = jnp.where(mask[None, None], logits, -jnp.inf)
  m_block = jnp.max(logits, axis=-1)  # [B, H, Tq]
  m_new = jnp.maximum(m_prev, m_block)
  # Guard fully-masked rows: exp(-inf - -inf) → exp(0); zero them via l.
  safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
  p = jnp.exp(logits - safe_m[..., None])
  p = jnp.where(jnp.isfinite(logits), p, 0.0)
  correction = jnp.where(
      jnp.isfinite(m_prev), jnp.exp(m_prev - safe_m), 0.0)  # [B, H, Tq]
  l_new = l_prev * correction + jnp.sum(p, axis=-1)
  o_scaled = o_prev * correction.transpose(0, 2, 1)[..., None]
  o_new = o_scaled + jnp.einsum('bhqk,bkhd->bqhd', p, v)
  return m_new, l_new, o_new


def ring_attention(q: jax.Array,
                   k: jax.Array,
                   v: jax.Array,
                   axis_name: str = SEQ_AXIS,
                   causal: bool = False,
                   kv_chunk: Optional[int] = None) -> jax.Array:
  """Blockwise ring attention; call INSIDE shard_map over ``axis_name``.

  Args:
    q, k, v: process-local blocks [B, T_local, H, D]; the global sequence
      is the concatenation over the mesh axis.
    axis_name: the mesh axis the sequence is sharded over.
    causal: apply a causal mask over GLOBAL positions.
    kv_chunk: process each hop's K/V in chunks of this many positions so
      the per-hop logits tensor is [B, H, T_local, kv_chunk] instead of
      [B, H, T_local, T_local] — the memory knob for long per-device
      shards. Must divide ``T_local``; default = one chunk per hop.

  Returns:
    [B, T_local, H, D] attention output for the local query block.
  """
  axis_size = jax.lax.psum(1, axis_name)
  my_index = jax.lax.axis_index(axis_name)
  batch, t_local, heads, dim = q.shape
  chunk = t_local if kv_chunk is None else kv_chunk
  if chunk <= 0 or t_local % chunk:
    raise ValueError(
        f'kv_chunk ({chunk}) must divide the local sequence ({t_local}).')
  n_chunks = t_local // chunk

  m0 = jnp.full((batch, heads, t_local), -jnp.inf, jnp.float32)
  l0 = jnp.zeros((batch, heads, t_local), jnp.float32)
  o0 = jnp.zeros((batch, t_local, heads, dim), jnp.float32)
  q32 = q.astype(jnp.float32)

  def hop(i, carry):
    m, l, o, k_blk, v_blk = carry
    # This hop's kv block originated on device (my_index - i) % axis_size.
    src = (my_index - i) % axis_size

    def chunk_step(c, inner):
      m, l, o = inner
      k_c = jax.lax.dynamic_slice_in_dim(k_blk, c * chunk, chunk, axis=1)
      v_c = jax.lax.dynamic_slice_in_dim(v_blk, c * chunk, chunk, axis=1)
      if causal:
        q_pos = my_index * t_local + jnp.arange(t_local)  # [Tq]
        k_pos = src * t_local + c * chunk + jnp.arange(chunk)  # [chunk]
        mask = q_pos[:, None] >= k_pos[None, :]
      else:
        mask = None
      return _block_attention(
          q32, k_c.astype(jnp.float32), v_c.astype(jnp.float32), mask,
          m, l, o)

    if n_chunks == 1:  # unchunked hot path: no nested scan under grad
      m, l, o = chunk_step(0, (m, l, o))
    else:
      m, l, o = jax.lax.fori_loop(0, n_chunks, chunk_step, (m, l, o))
    # Rotate kv around the ring: device d sends to d+1 (next hop's block
    # on this device then originates one device further back).
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
    v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return m, l, o, k_blk, v_blk

  m, l, o, _, _ = jax.lax.fori_loop(0, axis_size, hop, (m0, l0, o0, k, v))
  l = jnp.maximum(l, 1e-20)
  out = o / l.transpose(0, 2, 1)[..., None]
  return out.astype(q.dtype)


def ulysses_attention(q: jax.Array,
                      k: jax.Array,
                      v: jax.Array,
                      axis_name: str = SEQ_AXIS,
                      causal: bool = False) -> jax.Array:
  """All-to-all (Ulysses) sequence parallelism; call INSIDE shard_map.

  Reshards [B, T/n, H, D] → [B, T, H/n, D] with one all-to-all, runs full
  local attention over the complete sequence for its head group, and
  reshards back. Requires ``H % axis_size == 0``.
  """
  axis_size = jax.lax.psum(1, axis_name)
  heads = q.shape[2]
  if heads % axis_size:
    raise ValueError(
        f'ulysses_attention needs heads ({heads}) divisible by the '
        f'sequence axis size ({axis_size}).')

  def to_headsharded(x):  # [B, T/n, H, D] -> [B, T, H/n, D]
    return jax.lax.all_to_all(
        x, axis_name, split_axis=2, concat_axis=1, tiled=True)

  def to_seqsharded(x):  # [B, T, H/n, D] -> [B, T/n, H, D]
    return jax.lax.all_to_all(
        x, axis_name, split_axis=1, concat_axis=2, tiled=True)

  ql, kl, vl = to_headsharded(q), to_headsharded(k), to_headsharded(v)
  t, d = ql.shape[1], ql.shape[3]
  from tensor2robot_tpu.ops.flash_attention import (flash_attention,
                                                    is_supported)

  if is_supported(t, d, itemsize=ql.dtype.itemsize):
    # The full-sequence local attention is exactly the flash kernel's
    # job: O(T·D) HBM memory instead of the [B, H, T, T] logits tensor.
    out = flash_attention(ql, kl, vl, causal)
  else:
    mask = (jnp.tril(jnp.ones((t, t), bool)) if causal else None)
    m0 = jnp.full(ql.shape[:1] + (ql.shape[2], t), -jnp.inf, jnp.float32)
    l0 = jnp.zeros_like(m0)
    o0 = jnp.zeros(ql.shape, jnp.float32)
    m, l, o = _block_attention(
        ql.astype(jnp.float32), kl.astype(jnp.float32),
        vl.astype(jnp.float32), mask, m0, l0, o0)
    out = (o / jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]).astype(
        q.dtype)
  return to_seqsharded(out)


def _shard_map(fn, mesh: Mesh, in_specs, out_specs):
  """shard_map across jax API generations: ``jax.shard_map(...,
  check_vma=)`` (new) vs ``jax.experimental.shard_map.shard_map(...,
  check_rep=)`` (0.4.x). Replication checking stays off either way —
  the attention bodies use unchecked collectives."""
  if hasattr(jax, 'shard_map'):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
  from jax.experimental.shard_map import shard_map as legacy_shard_map

  return legacy_shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


def _sharded_apply(fn, mesh: Mesh, axis_name: str, causal: bool):
  spec = P(None, axis_name, None, None)

  def apply(q, k, v):
    return fn(q, k, v, axis_name=axis_name, causal=causal)

  return _shard_map(apply, mesh, (spec, spec, spec), spec)


def make_ring_attention(mesh: Mesh,
                        axis_name: str = SEQ_AXIS,
                        causal: bool = False,
                        kv_chunk: Optional[int] = None):
  """Jittable [B, T, H, D] → [B, T, H, D] ring attention over ``mesh``."""
  fn = functools.partial(ring_attention, kv_chunk=kv_chunk)
  return _sharded_apply(fn, mesh, axis_name, causal)


def make_ulysses_attention(mesh: Mesh,
                           axis_name: str = SEQ_AXIS,
                           causal: bool = False):
  """Jittable [B, T, H, D] → [B, T, H, D] Ulysses attention over ``mesh``."""
  return _sharded_apply(ulysses_attention, mesh, axis_name, causal)


def reference_attention(q, k, v, causal: bool = False):
  """Plain full attention (the numerics oracle for tests)."""
  scale = 1.0 / np.sqrt(q.shape[-1])
  logits = jnp.einsum('bqhd,bkhd->bhqk', q.astype(jnp.float32),
                      k.astype(jnp.float32)) * scale
  if causal:
    t = q.shape[1]
    mask = jnp.tril(jnp.ones((t, t), bool))
    logits = jnp.where(mask[None, None], logits, -jnp.inf)
  probs = jax.nn.softmax(logits, axis=-1)
  return jnp.einsum('bhqk,bkhd->bqhd', probs,
                    v.astype(jnp.float32)).astype(q.dtype)
