"""Grasp2Vec: self-supervised grasping representation workload."""

from tensor2robot_tpu.research.grasp2vec.grasp2vec_model import (
    Grasp2VecModel,
    Grasp2VecPreprocessor,
)
from tensor2robot_tpu.research.grasp2vec.networks import Embedding
from tensor2robot_tpu.research.grasp2vec import losses, visualization
