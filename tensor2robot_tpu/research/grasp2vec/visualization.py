"""Grasp2Vec heatmap localization utilities.

Capability-equivalent of
``/root/reference/research/grasp2vec/visualization.py`` — in particular
its ``_GetSoftMaxResponse`` (here :func:`get_softmax_response`):
correlate a goal embedding against a spatial
feature map and return the soft-argmax response (the instance-localization
mechanism evaluated in the paper).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from tensor2robot_tpu.layers.spatial_softmax import spatial_softmax


def get_softmax_response(goal_embedding: jnp.ndarray,
                         scene_spatial: jnp.ndarray
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
  """Correlation heatmap + its max response (visualization.py:246-273).

  Args:
    goal_embedding: [B, C] goal vectors.
    scene_spatial: [B, H, W, C] scene feature maps.

  Returns:
    (heatmap [B, H, W, 1] softmaxed over pixels, response [B] max logit).
  """
  heatmap_logits = jnp.einsum('bhwc,bc->bhw', scene_spatial, goal_embedding)
  batch, h, w = heatmap_logits.shape
  flat = heatmap_logits.reshape(batch, h * w)
  softmax = jax.nn.softmax(flat, axis=-1).reshape(batch, h, w, 1)
  response = jnp.max(flat, axis=-1)
  return softmax, response


def heatmap_keypoints(goal_embedding: jnp.ndarray,
                      scene_spatial: jnp.ndarray) -> jnp.ndarray:
  """Expected (x, y) of the correlation heatmap via spatial softmax."""
  heatmap = jnp.einsum('bhwc,bc->bhw', scene_spatial, goal_embedding)
  points, _ = spatial_softmax(heatmap[..., None])
  return points
