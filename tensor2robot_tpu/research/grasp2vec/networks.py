"""Grasp2Vec embedding network.

Capability-equivalent of
``/root/reference/research/grasp2vec/networks.py:27-45`` +
``resnet.py:338-563`` (their private ResNet-50 copy): a ResNet-50 trunk
producing *spatial* feature maps, ReLU'd, mean-pooled into the embedding
vector. Reuses the framework ResNet instead of a private copy.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp

from tensor2robot_tpu.layers.resnet import ResNet


class Embedding(nn.Module):
  """Scene/goal embedding: (mean-pooled vector, spatial map)."""

  resnet_size: int = 50

  @nn.compact
  def __call__(self, image: jnp.ndarray,
               train: bool = False) -> Tuple[jnp.ndarray, jnp.ndarray]:
    _, endpoints = ResNet(
        resnet_size=self.resnet_size, num_classes=None, name='resnet')(
            image, train=train)
    spatial = nn.relu(endpoints['pre_final_pool'])
    summed = jnp.mean(spatial, axis=(1, 2))
    return summed, spatial
