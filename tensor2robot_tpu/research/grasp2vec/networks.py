"""Grasp2Vec embedding network.

Capability-equivalent of
``/root/reference/research/grasp2vec/networks.py:27-45`` +
``resnet.py:338-563`` (their private ResNet-50 copy): a ResNet-50 trunk
producing *spatial* feature maps, ReLU'd, mean-pooled into the embedding
vector. Reuses the framework ResNet instead of a private copy.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from tensor2robot_tpu.layers.resnet import ResNet


class Embedding(nn.Module):
  """Scene/goal embedding: (mean-pooled vector, spatial map).

  ``dtype`` is the tower compute dtype (bfloat16 on TPU, the reference's
  wholesale TPU cast ``models/tpu_model_wrapper.py:105-118``); the pooled
  embedding *vector* is always reduced and returned in float32 — it feeds
  the numerically sensitive embedding-arithmetic losses.
  """

  resnet_size: int = 50
  dtype: Optional[Any] = None
  remat_policy: str = 'none'
  kernel_policy: str = 'none'

  @nn.compact
  def __call__(self, image: jnp.ndarray,
               train: bool = False) -> Tuple[jnp.ndarray, jnp.ndarray]:
    _, endpoints = ResNet(
        resnet_size=self.resnet_size, num_classes=None, dtype=self.dtype,
        remat_policy=self.remat_policy, kernel_policy=self.kernel_policy,
        name='resnet')(image, train=train)
    spatial = nn.relu(endpoints['pre_final_pool'])
    summed = jnp.mean(spatial.astype(jnp.float32), axis=(1, 2))
    return summed, spatial
