"""Grasp2Vec embedding losses, pure jnp.

Capability-equivalent of ``/root/reference/research/grasp2vec/losses.py``:
N-pairs (both directions), semi-hard triplet, L2/cosine arithmetic
consistency (``pregrasp - postgrasp ≈ goal``), and keypoint quadrant
accuracy. tf-slim's metric-learning losses are re-derived in jnp.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def _npairs_loss(labels: jnp.ndarray, embeddings_anchor: jnp.ndarray,
                 embeddings_positive: jnp.ndarray) -> jnp.ndarray:
  """tf.contrib npairs_loss: softmax CE over anchor·positiveᵀ similarities."""
  logits = embeddings_anchor @ embeddings_positive.T
  log_probs = jax.nn.log_softmax(logits, axis=1)
  one_hot = jax.nn.one_hot(labels, logits.shape[1], dtype=log_probs.dtype)
  return -jnp.mean(jnp.sum(one_hot * log_probs, axis=1))


def npairs_loss(pregrasp_embedding: jnp.ndarray,
                goal_embedding: jnp.ndarray,
                postgrasp_embedding: jnp.ndarray,
                non_negativity_constraint: bool = False) -> jnp.ndarray:
  """Bidirectional N-pairs on (pre-post, goal) (losses.py:165-190)."""
  pair_a = pregrasp_embedding - postgrasp_embedding
  if non_negativity_constraint:
    pair_a = jax.nn.relu(pair_a)
  pair_b = goal_embedding
  labels = jnp.arange(pair_a.shape[0])
  return (_npairs_loss(labels, pair_a, pair_b) +
          _npairs_loss(labels, pair_b, pair_a))


def l2_arithmetic_loss(pregrasp_embedding, goal_embedding,
                       postgrasp_embedding, mask) -> jnp.ndarray:
  """Masked mean ||pre - goal - post||² (losses.py:34-57)."""
  raw = pregrasp_embedding - goal_embedding - postgrasp_embedding
  distances = jnp.sum(jnp.square(raw), axis=1)
  mask = mask.astype(jnp.float32).reshape(-1)
  total = jnp.sum(mask)
  return jnp.where(total > 0, jnp.sum(distances * mask) /
                   jnp.maximum(total, 1.0), 0.0)


def cosine_arithmetic_loss(pregrasp_embedding, goal_embedding,
                           postgrasp_embedding, mask) -> jnp.ndarray:
  """Masked mean cosine distance of (pre-post) vs goal (losses.py:85-113)."""

  def normalize(x):
    return x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)

  pair_a = normalize(pregrasp_embedding - postgrasp_embedding)
  pair_b = normalize(goal_embedding)
  distances = 1.0 - jnp.sum(pair_a * pair_b, axis=1)
  mask = mask.astype(jnp.float32).reshape(-1)
  total = jnp.sum(mask)
  return jnp.where(total > 0, jnp.sum(distances * mask) /
                   jnp.maximum(total, 1.0), 0.0)


def triplet_semihard_loss(labels: jnp.ndarray, embeddings: jnp.ndarray,
                          margin: float = 1.0) -> jnp.ndarray:
  """Semi-hard mining triplet loss (tf-slim triplet_semihard_loss)."""
  # Pairwise squared distances.
  dots = embeddings @ embeddings.T
  sq = jnp.diag(dots)
  pdist = jnp.maximum(sq[:, None] - 2 * dots + sq[None, :], 0.0)
  adjacency = labels[:, None] == labels[None, :]
  adjacency_not = ~adjacency
  batch = embeddings.shape[0]

  # For each anchor-positive pair (i, j), find the semi-hard negative:
  # the closest negative farther than d(i, j); fallback to the largest.
  inf = jnp.asarray(1e9, pdist.dtype)
  neg_mask = adjacency_not[:, None, :]  # [i, j, k]: k negative of i
  d_ij = pdist[:, :, None]
  d_ik = pdist[:, None, :].repeat(batch, axis=1)
  semihard = neg_mask & (d_ik > d_ij)
  semihard_min = jnp.min(jnp.where(semihard, d_ik, inf), axis=2)
  hardest_max = jnp.max(jnp.where(neg_mask, d_ik, -inf), axis=2)
  neg_dist = jnp.where(semihard_min < inf, semihard_min, hardest_max)

  loss_mat = jnp.maximum(pdist + margin - neg_dist, 0.0)
  pos_mask = adjacency & ~jnp.eye(batch, dtype=bool)
  num_pos = jnp.maximum(jnp.sum(pos_mask), 1.0)
  return jnp.sum(jnp.where(pos_mask, loss_mat, 0.0)) / num_pos


def triplet_loss(pregrasp_embedding, goal_embedding,
                 postgrasp_embedding) -> Tuple[jnp.ndarray, jnp.ndarray,
                                               jnp.ndarray]:
  """Semi-hard triplet on normalized pairs (losses.py:59-83)."""

  def normalize(x):
    return x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)

  pair_a = normalize(pregrasp_embedding - postgrasp_embedding)
  pair_b = normalize(goal_embedding)
  labels = jnp.arange(pair_a.shape[0])
  labels = jnp.concatenate([labels, labels])
  pairs = jnp.concatenate([pair_a, pair_b], axis=0)
  loss = triplet_semihard_loss(labels, pairs, margin=3.0)
  return loss, pairs, labels


def keypoint_accuracy(keypoints: jnp.ndarray,
                      labels: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
  """Quadrant accuracy of spatial-softmax keypoints (losses.py:117-146)."""
  keypoints = keypoints.reshape((-1, 2))
  quadrant_centers = jnp.asarray(
      [[0.5, -0.5], [-0.5, -0.5], [0.5, 0.5], [-0.5, 0.5]], jnp.float32)
  logits = keypoints @ quadrant_centers.T
  predictions = jnp.argmax(logits, axis=1)
  labels = labels.reshape(-1).astype(jnp.int32)
  correct = jnp.mean((predictions == labels).astype(jnp.float32))
  labels_onehot = jax.nn.one_hot(labels, 4, dtype=jnp.float32)
  per_elem = (jnp.maximum(logits, 0) - logits * labels_onehot +
              jnp.log1p(jnp.exp(-jnp.abs(logits))))
  return correct, jnp.mean(per_elem)


# Reference-name aliases.
NPairsLoss = npairs_loss
TripletLoss = triplet_loss
L2ArithmeticLoss = l2_arithmetic_loss
CosineArithmeticLoss = cosine_arithmetic_loss
KeypointAccuracy = keypoint_accuracy
